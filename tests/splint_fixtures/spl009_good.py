"""SPL009 good: traced functions return what they compute; host-side
state is updated outside the trace, on committed arrays."""

import jax

HISTORY = []


@jax.jit
def scale(x):
    y = x * 2  # locals are fine: they die with the trace
    return y


def record(x):
    out = scale(x)
    HISTORY.append(out)  # outside the trace: a real device array
    return out
