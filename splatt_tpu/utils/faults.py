"""Fault injection harness — the test hook points of the resilience layer.

Resilience code that only runs when the infrastructure misbehaves is
dead code until the day it matters; this module makes the misbehavior
reproducible.  Production call sites (probe compiles, engine dispatch,
checkpoint writes, sweep outputs) call :func:`maybe_fail` /
:func:`consume` / :func:`poison` with a site name; tests (or an
operator, via env var) arm faults against those sites and the real
error-handling paths execute.

Arming a fault
    - context manager (tests)::

        with faults.inject("probe_compile", "http500", times=2):
            ...   # the first two probe compiles raise an HTTP 500

    - env var (whole-process, e.g. under the CLI)::

        SPLATT_FAULTS="probe_compile:http500:2,engine.fused_t:runtime"

      Comma-separated ``site[:kind][:modifier]...`` specs; ``times``
      defaults to 1, ``*`` means every eligible call.

Chaos schedules (docs/guarded-als.md)
    Beyond the one-shot ``times`` counter, a spec may carry seeded,
    declarative *schedule* modifiers deciding WHEN the armed fault is
    eligible to fire:

    - ``site:kind:iter=k``          — fire on exactly the k-th call to
      the site (1-based; each check at the site counts one call)
    - ``site:kind:p=0.1:seed=N``    — fire each call with probability
      p, drawn from a per-spec ``random.Random(seed)`` so the firing
      pattern is deterministic and replayable
    - ``site:kind:after=t``         — fire on any call once t seconds
      have elapsed since arming
    - ``site:slow:delay=s``         — the ``slow`` kind's sleep length

    Modifiers compose; ``times`` still bounds the TOTAL number of
    firings once a call is eligible.  A spec whose kind is omitted
    (``engine.fused_t:iter=3``) defaults to ``runtime``.
    :func:`parse_schedule` / :func:`format_schedule` round-trip the
    grammar; ``splatt chaos`` (splatt_tpu/chaos.py) drives a CPD under
    a schedule and asserts the soak invariant.

Sites used by the production code:
    - ``probe_compile``          — the capability-probe remote compile
    - ``engine.<name>``          — an MTTKRP dispatch engine at call
      time (e.g. ``engine.fused_t``, ``engine.xla_scan``)
    - ``checkpoint_write``       — raise during the checkpoint save
    - ``checkpoint_torn``        — consumed (not raised): the writer
      truncates the bytes it just wrote, simulating a torn write
    - ``tuner.measure``          — one autotuner candidate measurement
      (tune.py)
    - ``cpd.sweep``              — poison (not raise): corrupt one ALS
      sweep's outputs with non-finite values, exercising the
      numerical-health sentinel (cpd.py / parallel/common.py)
    - ``serve.submit`` / ``serve.journal_write`` / ``serve.job_run``
      — the serve daemon's submission, durable-journal and supervised-
      job hooks (serve.py, docs/serve.md)

Per-job scoping (docs/serve.md)
    :func:`scoped` arms a schedule in a contextvars overlay shadowing
    the global registry for the sites it names — the serve daemon
    wraps each supervised job in one, so a job spec's declared faults
    fire inside that job's thread only.

Fault kinds map to canned exceptions whose messages exercise specific
:func:`splatt_tpu.resilience.classify_failure` branches:

    ========== ==================================== ===============
    kind       message signature                    classifies as
    ========== ==================================== ===============
    http500    ``... HTTP code 500``                transient
    internal   ``INTERNAL: ...``                    transient
    unavailable ``UNAVAILABLE: ...``                transient
    timeout    ``TimeoutError``                     transient
    oom        ``RESOURCE_EXHAUSTED: ...``          resource
    mosaic     ``Mosaic ...``                       deterministic
    runtime    generic runtime failure              unknown
    ========== ==================================== ===============

Two kinds do not raise at all:

    - ``nan`` / ``inf`` — claimed only by :func:`poison`, which
      multiplies the value it guards by NaN/Inf (a silent numerical
      blowup, the sentinel's quarry);
    - ``slow``          — claimed by :func:`maybe_fail`, which SLEEPS
      ``delay`` seconds instead of raising, so the deadline watchdog
      (:func:`splatt_tpu.resilience.deadline`) fires for real.

The registry is process-local and the checks are O(1) dict lookups on
cold paths only (probes, dispatch resolution, checkpoint IO, one check
per sweep) — never inside a kernel.
"""

from __future__ import annotations

import contextlib
import contextvars
import dataclasses
import random
import threading
import time
from typing import Dict, Optional, Tuple, Union

_FAULTS_ENV = "SPLATT_FAULTS"

#: times value meaning "every eligible call"
ALWAYS = -1

#: kinds whose firing RAISES a canned exception from maybe_fail
RAISING_KINDS = ("http500", "internal", "unavailable", "timeout",
                 "oom", "mosaic", "runtime")
#: kinds claimed only by poison(): corrupt a value instead of raising
POISON_KINDS = ("nan", "inf")
#: kinds claimed by maybe_fail() that sleep instead of raising — the
#: way to make a real call blow a real deadline
DELAY_KINDS = ("slow",)

#: default sleep of the ``slow`` kind (overridable per spec: delay=s)
SLOW_DELAY_S = 1.0

#: The declared fault sites of the production code, site → doc.  A
#: trailing ``.*`` marks a dynamic family (the production call passes
#: an f-string with that prefix).  This registry is load-bearing, not
#: documentation-only: `splint` rule SPL006 checks that every site
#: string the production code passes to :func:`maybe_fail` /
#: :func:`consume` / :func:`poison` is declared here, that every
#: declared site is still called somewhere, and that every declared
#: site is exercised by at least one test — so a renamed hook cannot
#: silently orphan the resilience path it was built to exercise.
#: (Tests may arm ad-hoc sites to test the harness itself; those need
#: no declaration.)
SITES = {
    "probe_compile": "the capability-probe remote compile "
                     "(ops/pallas_kernels.py)",
    "engine.*": "an MTTKRP dispatch engine at call time, e.g. "
                "engine.fused_t / engine.xla_scan (ops/mttkrp.py); "
                "poison-armed specs corrupt the engine's OUTPUT "
                "instead of raising",
    "checkpoint_write": "raise during the checkpoint save (cpd.py)",
    "checkpoint_torn": "consumed (not raised): the writer truncates "
                       "the bytes it just wrote, simulating a torn "
                       "write (cpd.py)",
    "format.encode": "the compact-format v2 encode of one blocked "
                     "layout (blocked.py build_layout/reencode_layout); "
                     "a raised fault must degrade the build classified "
                     "to the v1 i32 encoding (format_fallback event), "
                     "never fail it",
    "format.dense": "the dense tile-layout build of one mode "
                    "(blocked.py build_layout/from_coo/reencode_layout, "
                    "docs/dense.md); a raised fault must degrade the "
                    "build classified to the sparse blocked encoding "
                    "(format_fallback event with site=dense), never "
                    "fail it",
    "format.decode": "native stream consumption of a compact layout "
                     "at MTTKRP dispatch (ops/mttkrp.py "
                     "mttkrp_blocked, docs/format.md); a raised fault "
                     "must degrade the dispatch classified to the "
                     "materialized global-i32 v1 path "
                     "(blocked.decode_to_v1, format_fallback event "
                     "with site=decode) — slower bytes, never a "
                     "failed run",
    "layout.pack": "the balanced fiber packing of one blocked layout "
                   "(blocked.py build_layout, docs/layout-balance.md); "
                   "a raised fault must degrade the build classified "
                   "to the fixed slicing (packing_fallback event), "
                   "never fail it",
    "reorder.apply": "the reorder permutation compute + apply "
                     "(reorder.py apply_reorder); a raised fault must "
                     "degrade the run classified to identity order "
                     "(reorder_fallback event), never fail it",
    "comm.ring_exchange": "the ring row-exchange of a distributed "
                          "sweep (parallel/ring_kernels.py: the async "
                          "remote-copy kernels and their ppermute "
                          "fallback); a raised fault surfaces at the "
                          "sweep's first invocation and must degrade "
                          "CLASSIFIED down the comm chain — "
                          "async_ring -> ring -> all2all "
                          "(comm_fallback events, docs/ring.md) — "
                          "never kill the run",
    "tuner.measure": "one autotuner candidate measurement — warm + "
                     "timed MTTKRP runs of a forced engine (tune.py); "
                     "a crashing measurement must degrade dispatch to "
                     "the heuristic chain, never fail the run",
    "cpd.sweep": "poisoned (not raised): corrupt one ALS sweep's "
                 "factor output with non-finite values, exercising "
                 "the numerical-health sentinel and its rollback "
                 "(cpd.py, parallel/common.py)",
    "serve.submit": "one job submission into the serve daemon's "
                    "queue (serve.py); a raised fault must reject "
                    "that submission, classified — never kill the "
                    "daemon",
    "serve.journal_write": "one durable journal append (serve.py); a "
                           "failure while journaling a submission "
                           "rejects the job (durability cannot be "
                           "promised), terminal-record failures "
                           "degrade to warn-and-continue",
    "serve.job_run": "the start of one supervised job (serve.py); a "
                     "raising kind marks the job failed/degraded, "
                     "'slow' holds the job open (blowing a per-job "
                     "deadline, or pinning it for kill-and-restart "
                     "soaks)",
    "fleet.lease_acquire": "one atomic job-lease acquisition (fleet.py "
                           "FleetMember.acquire — the flock + atomic-"
                           "rename claim of docs/fleet.md); a raised "
                           "fault drops the claim classified (the job "
                           "stays claimable and the fleet scan "
                           "re-surfaces it), never kills the worker",
    "fleet.heartbeat": "one membership heartbeat + held-lease renewal "
                       "sweep (fleet.py FleetMember.beat); a raised "
                       "fault degrades classified — a missed beat "
                       "makes the replica look dead sooner, so peers "
                       "adopt its jobs after the lease window, which "
                       "is the documented failure mode",
    "fleet.adopt": "one dead-peer job takeover (fleet.py "
                   "FleetMember.adopt: expired-lease steal with a gen "
                   "bump); a raised fault leaves the job for the next "
                   "scan pass, classified — adoption is retried, "
                   "never lost",
    "trace.export": "the Chrome trace-event JSON export "
                    "(trace.write_chrome_trace); a raised fault must "
                    "degrade classified to a trace_written ok=False "
                    "event — losing the trace must never lose the run "
                    "it observed (docs/observability.md)",
    "trace.flight": "one flight-recorder ring flush (trace.py "
                    "_flight_flush: the bounded per-replica black box "
                    "of docs/observability.md); a raised fault must "
                    "disarm the recorder and degrade classified to a "
                    "flight_degraded event — the trace.export "
                    "discipline: losing the black box must never lose "
                    "the run it records",
    "serve.batch": "one coalesced batch dispatch (serve.py "
                   "_run_batch, docs/batched.md); a raised fault must "
                   "degrade the batch CLASSIFIED to per-tensor "
                   "dispatch of its members (batch_degraded event) — "
                   "every member still reaches its own terminal "
                   "record, never a lost job",
    "cpd.update": "the warm incremental-update pre-pass of a served "
                  "model (cpd.py refresh_touched_rows, consumed by "
                  "serve.py _run_update; docs/batched.md); a raised "
                  "fault must degrade the update CLASSIFIED to the "
                  "full-refit repair path (refit_scheduled event) — "
                  "an update can cost extra sweeps, never the model",
    "cpd.batch.sweep": "poisoned (not raised): corrupt SLOT 0 of a "
                       "batched ALS sweep's last factor output with "
                       "non-finite values (cpd.py "
                       "_cpd_als_batched_traced) — the per-slot "
                       "isolation drill: slot 0 must roll back ALONE "
                       "while every batch neighbor stays bit-clean "
                       "(docs/batched.md)",
    "predict.read": "the direct generation-fenced model read of one "
                    "predict (predict.py load_model_generation, "
                    "docs/predict.md); a raised fault must REFUSE "
                    "that predict classified (predict_degraded "
                    "event) — a refusal, never garbage",
    "predict.cache": "one hot-factor cache lookup on the predict "
                     "lane (predict.py HotFactorCache.get); a raised "
                     "fault must degrade that predict classified to "
                     "the direct generation-fenced read "
                     "(predict_degraded event with a served answer) "
                     "— slower bytes, never a wrong generation",
    "model.generation": "the generation-stamp advance of one model "
                        "commit (predict.py advance_generation, "
                        "called from serve.py's update/fit commits); "
                        "a raised fault must ABORT that commit "
                        "classified — the stamp never advances, so "
                        "readers keep serving the previous "
                        "generation (docs/predict.md)",
    "ingest.read": "one chunk read from the raw record stream "
                   "(ingest.py IngestState.read_chunks, docs/"
                   "ingest.md); a raised fault must ABORT the run "
                   "classified with every committed chunk intact — "
                   "a re-run resumes from the journal watermark and "
                   "re-reads from the recorded byte offset, losing "
                   "and duplicating nothing",
    "ingest.vocab": "the vocab-delta publish of one chunk commit "
                    "(ingest.py IngestState.publish_vocab); a raised "
                    "fault must ABORT that chunk BEFORE its journal "
                    "append — the watermark never moves, so the "
                    "vocab can never land ahead of or behind the "
                    "data (docs/ingest.md fence order)",
    "ingest.commit": "the journal-append watermark fence of one "
                     "chunk commit (ingest.py "
                     "IngestState.append_journal); a raised fault "
                     "leaves published segment/vocab debris but NO "
                     "journal record — the chunk re-commits "
                     "bit-identically on resume, the exactly-once "
                     "invariant's load-bearing window",
}


def _canned(kind: str, site: str) -> Exception:
    if kind == "http500":
        return RuntimeError(
            f"XLA:TPU compile failed: HTTP code 500 from remote compile "
            f"service (injected fault at {site})")
    if kind == "internal":
        return RuntimeError(
            f"INTERNAL: injected transient service failure at {site}")
    if kind == "unavailable":
        return RuntimeError(
            f"UNAVAILABLE: injected relay failure at {site}")
    if kind == "timeout":
        return TimeoutError(f"injected deadline expiry at {site}")
    if kind == "oom":
        return RuntimeError(
            f"RESOURCE_EXHAUSTED: injected out-of-memory at {site} "
            f"(attempting to allocate 128.00G)")
    if kind == "mosaic":
        return RuntimeError(
            f"Mosaic failed to compile the injected kernel at {site}")
    if kind == "runtime":
        return RuntimeError(f"injected engine runtime failure at {site}")
    raise ValueError(f"unknown fault kind {kind!r}")


def _validate_kind(kind: str) -> None:
    """Arm-time validation of every kind, raising or not."""
    if kind in POISON_KINDS or kind in DELAY_KINDS:
        return
    _canned(kind, "validate")  # raises ValueError on unknown kinds


@dataclasses.dataclass
class FaultSpec:
    """One armed fault: what to do and the schedule deciding when.

    `times` bounds total firings (ALWAYS = unbounded); `iter_at`, `p`
    (+ `seed`), and `after` decide per-call ELIGIBILITY — see the
    module docstring's chaos-schedule grammar.
    """

    kind: str
    times: int = 1          # remaining trigger count; ALWAYS = unbounded
    exc: Optional[Exception] = None   # overrides the canned exception
    fired: int = 0          # how often it actually triggered
    iter_at: Optional[int] = None     # fire on the N-th call only
    p: Optional[float] = None         # per-call Bernoulli probability
    seed: Optional[int] = None        # seeds the Bernoulli draw
    after: Optional[float] = None     # eligible after N seconds armed
    delay: Optional[float] = None     # 'slow' kind: sleep length
    calls: int = 0          # calls observed at the site since arming
    armed_ts: float = dataclasses.field(default_factory=time.monotonic)
    _rng: Optional[random.Random] = dataclasses.field(
        default=None, repr=False, compare=False)

    def rng(self) -> random.Random:
        if self._rng is None:
            self._rng = random.Random(self.seed)
        return self._rng


_LOCK = threading.Lock()
_ACTIVE: Dict[str, FaultSpec] = {}
_env_loaded = False

#: per-context fault overlay (docs/serve.md): a serve job's declared
#: schedule shadows the global registry for the sites it names, so one
#: tenant's chaos drill fires inside that job only — sites the overlay
#: does not name fall through to the global/env-armed registry.
_SCOPED: contextvars.ContextVar = contextvars.ContextVar(
    "splatt_faults_scope", default=None)


def _lookup_locked(site: str) -> Optional[FaultSpec]:
    """The spec governing `site` in this context: the scoped overlay's
    when it names the site, else the global registry's."""
    overlay = _SCOPED.get()
    if overlay is not None and site in overlay:
        return overlay[site]
    return _ACTIVE.get(site)


@contextlib.contextmanager
def scoped(schedule: Union[str, Dict[str, FaultSpec], None]):
    """Arm a per-context fault schedule (same grammar as SPLATT_FAULTS
    / :func:`parse_schedule`) overlaying the global registry for the
    duration of the block.  The serve daemon wraps each supervised job
    in one of these so a job spec's declared faults fire inside that
    job's thread only — per-tenant chaos without cross-tenant blast
    radius.  Yields the {site: FaultSpec} dict; callers read each
    spec's ``fired`` counter afterwards for evidence matching."""
    if schedule is None:
        specs: Dict[str, FaultSpec] = {}
    elif isinstance(schedule, str):
        specs = parse_schedule(schedule)
    else:
        specs = dict(schedule)
    token = _SCOPED.set(specs)
    try:
        yield specs
    finally:
        _SCOPED.reset(token)


def parse_spec(item: str) -> Tuple[str, FaultSpec]:
    """Parse one ``site[:kind][:modifier]...`` spec → (site, FaultSpec).

    Raises ValueError/TypeError on malformation — callers decide
    whether that is fatal (:func:`parse_schedule` from code) or
    warn-and-ignore (the env loader).
    """
    parts = [p.strip() for p in item.split(":")]
    if len(parts) < 2 or not parts[0]:
        raise ValueError("want site:kind[:modifier]... or "
                         "site:modifier=value")
    site = parts[0]
    rest = parts[1:]
    kind = "runtime"
    if rest and "=" not in rest[0] and rest[0] != "*" \
            and not rest[0].isdigit():
        kind = rest[0]
        rest = rest[1:]
    _validate_kind(kind)
    spec = FaultSpec(kind=kind)
    for mod in rest:
        if mod == "*":
            spec.times = ALWAYS
        elif mod.isdigit():
            spec.times = int(mod)
        elif "=" in mod:
            key, _, val = mod.partition("=")
            key = key.strip()
            val = val.strip()
            if key == "iter":
                spec.iter_at = int(val)
                if spec.iter_at < 1:
                    raise ValueError("iter= is 1-based")
            elif key == "p":
                spec.p = float(val)
                if not 0.0 <= spec.p <= 1.0:
                    raise ValueError("p= must lie in [0, 1]")
            elif key == "seed":
                spec.seed = int(val)
            elif key == "after":
                spec.after = float(val)
            elif key == "delay":
                spec.delay = float(val)
            elif key == "times":
                spec.times = ALWAYS if val == "*" else int(val)
            else:
                raise ValueError(f"unknown schedule modifier {key!r}")
        else:
            raise ValueError(f"unparseable modifier {mod!r}")
    return site, spec


def format_spec(site: str, spec: FaultSpec) -> str:
    """Inverse of :func:`parse_spec` (round-trip: parse(format(s)) == s
    for every schedule field)."""
    parts = [site, spec.kind]
    if spec.iter_at is not None:
        parts.append(f"iter={spec.iter_at}")
    if spec.p is not None:
        parts.append(f"p={spec.p:g}")
    if spec.seed is not None:
        parts.append(f"seed={spec.seed}")
    if spec.after is not None:
        parts.append(f"after={spec.after:g}")
    if spec.delay is not None:
        parts.append(f"delay={spec.delay:g}")
    if spec.times == ALWAYS:
        parts.append("*")
    elif spec.times != 1:
        parts.append(str(spec.times))
    return ":".join(parts)


def parse_schedule(text: str) -> Dict[str, FaultSpec]:
    """Parse a comma-separated chaos schedule → {site: FaultSpec}.
    Strict: a malformed entry raises (the env loader has its own
    warn-and-ignore wrapper — a typo in an interactive chaos run should
    fail loudly, a typo in a production env var should not kill the
    run)."""
    out: Dict[str, FaultSpec] = {}
    for item in text.split(","):
        item = item.strip()
        if not item:
            continue
        site, spec = parse_spec(item)
        out[site] = spec
    return out


def format_schedule(schedule: Dict[str, FaultSpec]) -> str:
    """Inverse of :func:`parse_schedule`."""
    return ",".join(format_spec(site, spec)
                    for site, spec in schedule.items())


def arm(site: str, spec: FaultSpec) -> None:
    """Arm `spec` at `site` until :func:`reset` (chaos harness; tests
    preferring scoped arming use :func:`inject`)."""
    with _LOCK:
        _load_env_locked()
        _ACTIVE[site] = spec


def _load_env_locked() -> None:
    global _env_loaded
    if _env_loaded:
        return
    _env_loaded = True
    from splatt_tpu.utils.env import read_env

    raw = read_env(_FAULTS_ENV)
    for item in raw.split(","):
        item = item.strip()
        if not item:
            continue
        # every malformation is warn-and-ignore: a typo in a fault spec
        # must not kill the production run at some random hook site
        try:
            site, spec = parse_spec(item)
        except (ValueError, TypeError) as e:
            import sys

            print(f"splatt-tpu: bad {_FAULTS_ENV} entry {item!r} "
                  f"({e}); ignored", file=sys.stderr)
            continue
        _ACTIVE[site] = spec


def _eligible_locked(spec: FaultSpec) -> bool:
    """Whether THIS call (already counted) satisfies the schedule."""
    if spec.iter_at is not None and spec.calls != spec.iter_at:
        return False
    if spec.after is not None \
            and time.monotonic() - spec.armed_ts < spec.after:
        return False
    if spec.p is not None and not spec.rng().random() < spec.p:
        return False
    return True


def _take(site: str, kinds: Optional[tuple] = None) -> Optional[FaultSpec]:
    """Claim one firing of the fault armed at `site`, if any.  `kinds`
    restricts which fault kinds this hook may claim, so a poison-armed
    spec is never consumed (and wasted) by a raise-shaped hook at the
    same site."""
    with _LOCK:
        _load_env_locked()
        spec = _lookup_locked(site)
        if spec is None:
            return None
        if kinds is not None and spec.kind not in kinds:
            return None
        spec.calls += 1
        if spec.times == 0:
            return None
        if not _eligible_locked(spec):
            return None
        if spec.times != ALWAYS:
            spec.times -= 1
        spec.fired += 1
        return spec


def maybe_fail(site: str) -> None:
    """Production hook: raise the armed fault for `site`, if any —
    or SLEEP, for the ``slow`` kind, so a wrapping deadline watchdog
    fires for real.  A no-op (one dict lookup) when nothing is armed."""
    spec = _take(site, kinds=RAISING_KINDS + DELAY_KINDS)
    if spec is None:
        return
    if spec.kind in DELAY_KINDS:
        time.sleep(spec.delay if spec.delay is not None else SLOW_DELAY_S)
        return
    raise spec.exc if spec.exc is not None else _canned(spec.kind, site)


def poison(site: str, value):
    """Production hook for non-finite injection: when a ``nan``/``inf``
    fault is armed (and scheduled) at `site`, return `value` multiplied
    by NaN/Inf — the silent numerical blowup the health sentinel
    exists to catch; otherwise return `value` unchanged.  Works on any
    array-like with scalar broadcasting (jax arrays included; under a
    jit trace the corruption is baked into the traced program, flushed
    by the sweep rebuild a rollback performs)."""
    spec = _take(site, kinds=POISON_KINDS)
    if spec is None:
        return value
    return value * float("nan" if spec.kind == "nan" else "inf")


def consume(site: str) -> bool:
    """Production hook for non-raising faults (e.g. torn writes): True
    when a fault was armed at `site` (and claims one firing)."""
    return _take(site) is not None


def active(site: str) -> bool:
    """Whether a fault is currently armed at `site` (no claim) — the
    scoped overlay included."""
    with _LOCK:
        _load_env_locked()
        spec = _lookup_locked(site)
        return spec is not None and spec.times != 0


def fired(site: Optional[str] = None):
    """How often armed faults actually triggered: a count for one
    `site`, or {site: count} for every armed site (the chaos harness
    matches run-report events against what actually fired)."""
    with _LOCK:
        _load_env_locked()
        overlay = _SCOPED.get() or {}
        if site is not None:
            spec = _lookup_locked(site)
            return spec.fired if spec is not None else 0
        merged = dict(_ACTIVE)
        merged.update(overlay)  # overlay shadows, as in _lookup_locked
        return {s: spec.fired for s, spec in merged.items()}


@contextlib.contextmanager
def inject(site: str, kind: str = "runtime", times: int = 1,
           exc: Optional[Exception] = None,
           iter_at: Optional[int] = None, p: Optional[float] = None,
           seed: Optional[int] = None, after: Optional[float] = None,
           delay: Optional[float] = None):
    """Arm a fault at `site` for the duration of the block (tests).
    `times` bounds how many calls trigger (ALWAYS = every call); `exc`
    substitutes a custom exception for the canned one; `iter_at` / `p`
    (+ `seed`) / `after` / `delay` are the chaos-schedule fields (see
    the module docstring)."""
    if exc is None:
        _validate_kind(kind)  # validate early
    spec = FaultSpec(kind=kind, times=times, exc=exc, iter_at=iter_at,
                     p=p, seed=seed, after=after, delay=delay)
    with _LOCK:
        _load_env_locked()
        prev = _ACTIVE.get(site)
        _ACTIVE[site] = spec
    try:
        yield spec
    finally:
        with _LOCK:
            if prev is None:
                _ACTIVE.pop(site, None)
            else:
                _ACTIVE[site] = prev


def reset() -> None:
    """Disarm everything and forget the env parse (tests)."""
    global _env_loaded
    with _LOCK:
        _ACTIVE.clear()
        _env_loaded = False
