#!/bin/bash
# Chained claim-probe -> full TPU session loop.
#
# Round-3 verdict: three rounds of BENCH artifacts were burned on
# "accelerator unavailable" because the probe loop and the work session
# were never connected.  This loop probes for a claim window and, the
# moment one opens, immediately runs the full ordered work session
# (bench.py first — the flagship number — then kernel bisects/tuning).
#
# Discipline:
#   * single client at a time (the axon relay serializes claims; a killed
#     client can wedge the lease) — claims are never interrupted mid-flight;
#   * a hard wall-clock deadline so the loop NEVER overlaps the driver's
#     own round-end bench run;
#   * tools/STOP_PROBE stops the loop between attempts.
#
# Run: nohup bash tools/tpu_chained_loop.sh > tools/tpu_chained_loop.out 2>&1 &
cd "$(dirname "$0")/.."
rm -f tools/STOP_PROBE
# a stale artifact from a previous session must not satisfy the
# success check below
rm -f BENCH_TPU_CAND.json
DEADLINE=$(( $(date +%s) + ${TPU_LOOP_BUDGET_S:-34200} ))  # default 9.5 h
SESSION_DONE=0
for i in $(seq 1 200); do
  [ -e tools/STOP_PROBE ] && { echo "loop: stopped by sentinel"; exit 0; }
  now=$(date +%s)
  if [ "$now" -ge "$DEADLINE" ]; then
    echo "loop: wall-clock deadline reached after $i attempts"; exit 0
  fi
  echo "=== probe attempt $i $(date -u +%H:%M:%S) ==="
  # Cap a single claim below the time to the deadline so we never hold a
  # claim attempt into the driver's round-end window.
  remain=$(( DEADLINE - now ))
  TPU_PROBE_TIMEOUT=$(( remain < 2700 ? remain : 2700 )) python tools/tpu_probe.py
  rc=$?
  if [ $rc -eq 0 ]; then
    echo "=== claim OK on attempt $i; launching work session ==="
    bash tools/tpu_session.sh
    src=$?
    echo "=== session rc=$src ==="
    # Success means stage B produced a TPU-platform bench artifact.
    if grep -q '"tpu"' BENCH_TPU_CAND.json 2>/dev/null; then
      echo "loop: TPU bench captured; done"
      SESSION_DONE=1
      exit 0
    fi
    echo "loop: session ran but no TPU bench artifact; continuing to probe"
  fi
  [ -e tools/STOP_PROBE ] && { echo "loop: stopped by sentinel"; exit 0; }
  sleep 240
done
echo "loop: exhausted attempts (session_done=$SESSION_DONE)"
exit 1
