"""SPL005 bad: dtype literals outside config.py."""

import jax.numpy as jnp
import numpy as np


def make(x):
    a = jnp.zeros((4, 4), jnp.float32)
    b = np.zeros(4, dtype=np.float64)
    c = x.astype(jnp.bfloat16)
    return a, b, c
