"""CLI verb tests (≙ the cmd_* paths; driven in-process)."""

import os

import numpy as np
import pytest

from splatt_tpu.cli import main
from splatt_tpu.coo import SparseTensor
from splatt_tpu.io import load, read_matrix
from tests import gen


@pytest.fixture
def tns(tensors_dir):
    return str(tensors_dir / "med.tns")


def test_cpd_writes_factors(tns, tmp_path, capsys, monkeypatch):
    monkeypatch.chdir(tmp_path)
    rc = main(["cpd", tns, "-r", "4", "-i", "5", "--seed", "3", "--f64"])
    assert rc == 0
    out = capsys.readouterr().out
    assert "Final fit:" in out
    assert "DIMS=" in out
    tt = gen.fixture_tensor("med")
    for m in range(3):
        U = read_matrix(f"mode{m + 1}.mat")
        assert U.shape == (tt.dims[m], 4)
    lam = np.loadtxt("lambda.mat")
    assert lam.shape == (4,)


def test_cpd_nowrite_and_verbose(tns, tmp_path, capsys, monkeypatch):
    monkeypatch.chdir(tmp_path)
    rc = main(["cpd", tns, "-r", "3", "-i", "3", "--seed", "1",
               "--nowrite", "-v"])
    assert rc == 0
    out = capsys.readouterr().out
    assert "its =" in out          # per-iteration report
    assert "Timing information" in out
    assert not os.path.exists("mode1.mat")


def test_check_clean_and_dirty(tmp_path, capsys, tensors_dir):
    rc = main(["check", str(tensors_dir / "small.tns")])
    assert rc == 0
    assert "duplicates: 0" in capsys.readouterr().out
    # dirty tensor: duplicates + empty slice
    dirty = tmp_path / "dirty.tns"
    dirty.write_text("1 1 1 1.0\n1 1 1 2.0\n3 2 2 1.0\n")
    fixed = str(tmp_path / "fixed.tns")
    rc = main(["check", str(dirty), "--fix", fixed])
    assert rc == 1
    out = load(fixed)
    assert out.nnz == 2
    assert out.dims == (2, 2, 2)   # empty slice 2 of mode 0 removed


def test_stats(tns, capsys):
    rc = main(["stats", tns])
    assert rc == 0
    out = capsys.readouterr().out
    assert "DENSITY=" in out
    assert "mode 0:" in out


def test_convert_roundtrip(tns, tmp_path, capsys):
    out_bin = str(tmp_path / "t.bin")
    assert main(["convert", tns, "bin", out_bin]) == 0
    a, b = load(tns), load(out_bin)
    np.testing.assert_array_equal(a.inds, b.inds)
    for target in ("graph", "fibhgraph", "nnzhgraph", "fibmat"):
        out = str(tmp_path / f"t.{target}")
        assert main(["convert", tns, target, out]) == 0
        assert os.path.getsize(out) > 0


def test_reorder_preserves_content(tns, tmp_path, capsys):
    out_path = str(tmp_path / "r.tns")
    assert main(["reorder", tns, "random", out_path, "--seed", "5"]) == 0
    a, b = load(tns), load(out_path)
    assert a.nnz == b.nnz
    np.testing.assert_allclose(np.sort(a.vals), np.sort(b.vals))
    for m in range(a.nmodes):
        np.testing.assert_array_equal(
            np.sort(np.unique(a.inds[m])), np.sort(np.unique(b.inds[m])))


def test_bench_runs(tns, capsys):
    rc = main(["bench", tns, "-r", "4", "--reps", "1", "--block", "256",
               "-a", "stream", "-a", "blocked"])
    assert rc == 0
    out = capsys.readouterr().out
    assert "stream" in out and "blocked" in out and "total:" in out
    # roofline lines: model GB/s per path per mode (VERDICT r3 #7)
    assert "Effective bandwidth" in out and "GB/s" in out


def test_roofline_model_units():
    """The bytes model orders algorithms sensibly: the fused pallas plan
    streams factor TABLES once instead of one row fetch per nonzero, so
    its modeled traffic must be below the stream path's; ttbox does one
    pass per rank column, so its traffic must be far above."""
    from splatt_tpu.bench_algs import mttkrp_bytes
    from splatt_tpu.blocked import build_layout
    from tests import gen

    tt = gen.fixture_tensor("med")
    lay = build_layout(tt, 0, block=128, val_dtype=np.float32)
    b_stream = mttkrp_bytes("stream", tt, 16, 0, 4)
    b_fused = mttkrp_bytes("blocked_pallas", tt, 16, 0, 4, lay)
    b_ttbox = mttkrp_bytes("ttbox", tt, 16, 0, 4)
    assert 0 < b_fused < b_stream < b_ttbox
    # output term present: a bigger rank moves more bytes everywhere
    assert mttkrp_bytes("stream", tt, 32, 0, 4) > b_stream


def test_bench_device_scaling_sweep():
    """SPLATT_BENCH_DEVICES runs the worker-count scaling sweep
    (≙ thread scaling, src/bench.c:84-117) and prints one JSON line
    with sec/iter + parallel efficiency per device count."""
    import json
    import os
    import subprocess
    import sys

    env = dict(os.environ)
    env.update(SPLATT_BENCH_DEVICES="1,2", SPLATT_BENCH_NNZ="60000",
               SPLATT_BENCH_RANK="6", SPLATT_BENCH_ITERS="1")
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    p = subprocess.run([sys.executable, os.path.join(repo, "bench.py")],
                       env=env, capture_output=True, text=True,
                       timeout=900, cwd=repo)
    line = [l for l in p.stdout.splitlines() if l.startswith("{")]
    assert line, p.stderr[-500:]
    rec = json.loads(line[-1])
    assert "scaling" in rec and len(rec["scaling"]) == 2
    assert rec["scaling"][0]["n_devices"] == 1
    assert rec["scaling"][0]["efficiency"] == 1.0
    assert rec["scaling"][1]["sec_per_iter"] is not None


def test_cpd_distributed_flags(tns, capsys):
    """--decomp runs the distributed path; --comm selects the ring."""
    rc = main(["cpd", tns, "-r", "3", "-i", "3", "--seed", "2", "--nowrite",
               "--decomp", "fine", "--comm", "point2point"])
    assert rc == 0
    out = capsys.readouterr().out
    assert "DISTRIBUTED decomp=fine" in out
    assert "Final fit:" in out


def test_cpd_distributed_grid(tns, capsys):
    rc = main(["cpd", tns, "-r", "3", "-i", "3", "--seed", "2", "--nowrite",
               "--decomp", "medium", "--grid", "2x2x2"])
    assert rc == 0
    assert "grid=2x2x2" in capsys.readouterr().out


def test_cpd_bad_flag_combinations(tns, capsys):
    # -p with a non-fine decomposition is rejected
    assert main(["cpd", tns, "-r", "2", "--decomp", "medium",
                 "-p", "whatever.part"]) == 1
    assert "FINE-decomposition" in capsys.readouterr().err
    # ring comm outside fine is rejected
    assert main(["cpd", tns, "-r", "2", "--decomp", "medium",
                 "--comm", "point2point"]) == 1
    assert "fine" in capsys.readouterr().err
    # wrong-arity / non-positive grid is rejected cleanly
    assert main(["cpd", tns, "-r", "2", "--decomp", "medium",
                 "--grid", "2x2"]) == 1
    assert "one positive factor per mode" in capsys.readouterr().err
    assert main(["cpd", tns, "-r", "2", "--decomp", "medium",
                 "--grid", "0x2x2"]) == 1
    assert "positive" in capsys.readouterr().err


def test_check_out_of_range(tmp_path, capsys):
    """A binary declaring indices beyond its dims is flagged."""
    from splatt_tpu.io import save

    tt = gen.fixture_tensor("small")
    bad = SparseTensor(tt.inds.copy(), tt.vals.copy(),
                       (tt.dims[0] - 1, *tt.dims[1:]))  # dims too small
    path = str(tmp_path / "bad.bin")
    save(bad, path)
    rc = main(["check", path])
    assert rc == 1
    out = capsys.readouterr().out
    assert "out-of-range" in out


def test_stats_partition_flag(tns, tmp_path, capsys):
    tt = gen.fixture_tensor("med")
    part = tmp_path / "p.part"
    rng = np.random.default_rng(1)
    part.write_text("\n".join(str(int(x))
                              for x in rng.integers(0, 4, size=tt.nnz)))
    rc = main(["stats", tns, "-p", str(part)])
    assert rc == 0
    out = capsys.readouterr().out
    assert "Partition quality" in out and "TOTAL-CUT=" in out


def test_bench_check(tns, capsys):
    rc = main(["bench", tns, "-r", "4", "--reps", "1", "--block", "128",
               "--check", "--f64"])
    assert rc == 0
    out = capsys.readouterr().out
    assert "cross-check max" in out


def test_version_flag(capsys):
    import splatt_tpu

    with pytest.raises(SystemExit) as exc:
        main(["--version"])
    assert exc.value.code == 0
    assert splatt_tpu.__version__ in capsys.readouterr().out


def test_cpd_stem(tns, tmp_path, capsys):
    # trailing slash => directory semantics
    outdir = str(tmp_path / "factors") + os.sep
    rc = main(["cpd", tns, "-r", "2", "-i", "2", "--seed", "1",
               "-s", outdir])
    assert rc == 0
    assert os.path.exists(os.path.join(outdir, "mode1.mat"))
    assert os.path.exists(os.path.join(outdir, "lambda.mat"))
    # bare stem => reference-style <stem>.mode1.mat (cmd_cpd.c:219
    # inserts the '.' itself)
    prefix = str(tmp_path / "run1")
    rc = main(["cpd", tns, "-r", "2", "-i", "2", "--seed", "1",
               "-s", prefix])
    assert rc == 0
    assert os.path.exists(str(tmp_path / "run1.mode1.mat"))
    assert os.path.exists(str(tmp_path / "run1.lambda.mat"))

def test_top_watch_zero_interval_runs_once(tmp_path, capsys, monkeypatch):
    """SPLATT_STATUS_WATCH_S=0 makes the watch-by-default `splatt top`
    (and `status --watch`) run ONCE and exit — what tests and scripts
    set instead of killing a sleep loop (docs/batched.md CI satellite)."""
    monkeypatch.setenv("SPLATT_STATUS_WATCH_S", "0")
    root = str(tmp_path / "spool")
    os.makedirs(root, exist_ok=True)
    rc = main(["top", root])
    assert rc == 0
    assert "splatt fleet" in capsys.readouterr().out
    # an explicit --interval 0 behaves the same without the env var
    monkeypatch.delenv("SPLATT_STATUS_WATCH_S")
    rc = main(["status", root, "--watch", "--interval", "0"])
    assert rc == 0
    assert "splatt fleet" in capsys.readouterr().out
