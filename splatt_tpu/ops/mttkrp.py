"""MTTKRP — the hot kernel (≙ src/mttkrp.c, 1931 LoC in the reference).

``mttkrp(X, factors, mode)`` computes, for every output row i of `mode`::

    M[i, :] = Σ_{nnz n : ind_mode[n] = i}  val[n] · ∏_{k≠mode} U_k[ind_k[n], :]

Four execution paths replace the reference's root/internal/leaf ×
locked/nolock × tiled traversal matrix (src/mttkrp.c:104-1341):

- ``stream``        — COO gather + segment_sum.  Trivially correct; the
  differential-test gold oracle (≙ mttkrp_stream, src/mttkrp.c:1697-1757).
- ``sorted_onehot`` — blocked layout sorted by the output mode: per-block
  partial products reduced by a small one-hot matmul on the MXU, then a
  block-level scatter combine.  ≙ the root-mode CSF traversal — scatter
  contention is gone by construction, like CSF's accumulate-up-the-tree.
- ``privatized``    — short output modes: full-width one-hot per block and
  a pure tree-sum over blocks, no scatter at all.  ≙ per-thread output
  replicas + parallel reduction (p_reduce_privatized, src/mttkrp.c:56-87).
- ``scatter``       — generic path for modes the layout is not sorted for
  (≙ internal/leaf traversals with the mutex pool): XLA scatter-add via
  segment_sum, flagged sorted when the layout mode matches.

Path choice (≙ mttkrp_csf dispatch src/mttkrp.c:1287-1341 +
p_is_privatized :221-236) is static at trace time.
"""

from __future__ import annotations

from functools import partial
from typing import List, NamedTuple, Optional, Sequence, Union

import jax
import jax.numpy as jnp
import numpy as np

from splatt_tpu.blocked import BlockedSparse, ModeLayout
from splatt_tpu.config import Options
from splatt_tpu.coo import SparseTensor
from splatt_tpu.utils.env import read_env, read_env_int

PATHS = ("stream", "sorted_onehot", "privatized", "scatter",
         "sorted_scatter", "dense")

#: engines that consume a compact layout's encoded streams NATIVELY —
#: decode runs in registers (fused_v2 in the Pallas kernel, xla_scan
#: per chunk inside the scan step, xla fused into the scatter/segment
#: sum), so the decoded i32 temp never lands in HBM and achieved bytes
#: track the encoded streams (docs/format.md).  Everything else
#: decodes at operand prep; bench's decode_overhead model and the
#: format_decode run-report event both read this set.
STREAM_NATIVE_ENGINES = ("fused_v2", "xla_scan", "xla")


def _gather_prod(inds: jax.Array, vals: jax.Array,
                 factors: Sequence[jax.Array], mode: int) -> jax.Array:
    """(nnz, R) partial products: val · ⊛_{k≠mode} U_k[ind_k].

    Gathers lower to XLA dynamic-gather; the Hadamard chain fuses.
    Out-of-range (sentinel) indices clamp — their values are zero.
    """
    dtype = factors[0].dtype
    prod = vals.astype(dtype)[:, None]
    for k, U in enumerate(factors):
        if k != mode:
            prod = prod * jnp.take(U, inds[k], axis=0, mode="clip",
                                   indices_are_sorted=False)
    return prod


def _gather_prod_layout(layout: ModeLayout, factors: Sequence[jax.Array],
                        mode: int) -> jax.Array:
    """:func:`_gather_prod` over a layout's ENCODED streams: v2 local
    indices decode per mode (``local + base``, fused into the gather's
    index computation) and bf16-stored values decode at the gather
    (``astype`` to the factor dtype) — the layout never rematerializes
    a global-i32/f32 copy of itself."""
    dtype = factors[0].dtype
    prod = layout.vals.astype(dtype)[:, None]
    for k, U in enumerate(factors):
        if k != mode:
            prod = prod * jnp.take(U, layout.mode_ids(k), axis=0,
                                   mode="clip", indices_are_sorted=False)
    return prod


def _acc_dtype(dtype):
    """Accumulate bf16/f16 operands in f32 (the MXU-native mixed
    pattern: low-precision reads, full-precision accumulation).
    Delegates to :func:`splatt_tpu.config.acc_dtype` — the config
    module owns dtype policy; this name survives as the engines'
    local spelling (and the probe cache hashes config.py so policy
    edits invalidate cached verdicts)."""
    from splatt_tpu.config import acc_dtype

    return acc_dtype(dtype)


acc_dtype = _acc_dtype  # public name for the sharded sweeps


def mxu_precision(dtype):
    """MXU pass policy for dots with `dtype` operands.

    The TPU MXU multiplies in bf16: a DEFAULT-precision f32 dot rounds
    each operand to one bf16 pass (measured max_err ~7e-2 on the one-hot
    contraction on a v5e — outside even the reference's float tolerance,
    tests/mttkrp_test.c:25-30).  HIGHEST decomposes each f32 operand
    into bf16 pieces for f32-faithful products; bf16 operands are native
    single-pass and keep DEFAULT.
    """
    if dtype == jnp.float32:  # splint: ignore[SPL005] mxu_precision IS dtype-policy code, colocated with the kernels it guards
        return jax.lax.Precision.HIGHEST
    return jax.lax.Precision.DEFAULT


def onehot_precision(dtype, onehot_side: str = "lhs"):
    """Per-operand MXU precision for one-hot contractions.

    A one-hot operand holds only 0.0/1.0 — exactly representable in one
    bf16 pass — so only the *values* operand needs the HIGHEST bf16
    decomposition for f32-faithful products.  Per-operand precision
    keeps exactness while dropping the pass count versus HIGHEST on
    both sides.  `onehot_side` names which dot operand is the one-hot.
    """
    if dtype != jnp.float32:  # splint: ignore[SPL005] onehot_precision IS dtype-policy code, colocated with the kernels it guards
        p = jax.lax.Precision.DEFAULT
        return (p, p)
    if onehot_side == "lhs":
        return (jax.lax.Precision.DEFAULT, jax.lax.Precision.HIGHEST)
    return (jax.lax.Precision.HIGHEST, jax.lax.Precision.DEFAULT)


# -- stream (oracle) -------------------------------------------------------

@partial(jax.jit, static_argnames=("mode", "dim"))
def mttkrp_stream(inds: jax.Array, vals: jax.Array,
                  factors: List[jax.Array], mode: int, dim: int) -> jax.Array:
    """COO streaming MTTKRP — the gold oracle (≙ src/mttkrp.c:1697-1757)."""
    prod = _gather_prod(inds, vals, factors, mode)
    acc = _acc_dtype(prod.dtype)
    return jax.ops.segment_sum(prod.astype(acc), inds[mode],
                               num_segments=dim)


def mttkrp_batched_stream(inds: jax.Array, vals: jax.Array,
                          factors: Sequence[jax.Array], mode: int,
                          dim: int) -> jax.Array:
    """Vmapped stream MTTKRP over a stacked same-regime batch
    (docs/batched.md): `inds` is ``(K, nmodes, nnz_pad)`` global i32,
    `vals` ``(K, nnz_pad)``, `factors` per-mode ``(K, dim_m, R)`` —
    each slot computes exactly :func:`mttkrp_stream`'s gather/
    segment-sum dataflow over its own lane (pads are additive
    identities), with the engines' f32 accumulation under bf16
    storage.  Pure jnp and un-jitted here: the batched sweep
    (cpd._make_batched_sweep) owns the one jit wrapping K tenants."""
    def one(inds_s, vals_s, factors_s):
        prod = _gather_prod(inds_s, vals_s, factors_s, mode)
        acc = _acc_dtype(prod.dtype)
        return jax.ops.segment_sum(prod.astype(acc), inds_s[mode],
                                   num_segments=dim)

    return jax.vmap(one)(inds, vals, list(factors))


@partial(jax.jit, static_argnames=("mode", "dim"))
def mttkrp_ttbox(inds: jax.Array, vals: jax.Array,
                 factors: List[jax.Array], mode: int, dim: int) -> jax.Array:
    """Column-major rank loop (≙ mttkrp_ttbox, src/mttkrp.c:1655-1695).

    Historical Tensor-Toolbox formulation: one pass over the nonzeros
    per rank column.  Kept as a bench baseline — rank sequentialism is
    exactly what the MXU-batched paths avoid.  (The GigaTensor CSR
    variant, src/mttkrp.c:1604-1649, is deliberately not reproduced:
    it materializes the Khatri-Rao column space, the one thing a
    TPU formulation must never do.)
    """

    def col(r):
        p = vals.astype(factors[0].dtype)
        for k, U in enumerate(factors):
            if k != mode:
                p = p * jnp.take(U[:, r], inds[k], mode="clip")
        # upcast-before-reduce like mttkrp_stream: bf16 columns must
        # not accumulate at 8 mantissa bits (SPL024)
        return jax.ops.segment_sum(p.astype(_acc_dtype(p.dtype)),
                                   inds[mode], num_segments=dim)

    rank = factors[0].shape[1]
    cols = jax.lax.map(col, jnp.arange(rank))
    return cols.T


# -- dense path (docs/dense.md) --------------------------------------------

def dense_operands(layout, factors: Sequence[jax.Array], mode: int):
    """The two Khatri-Rao operands of the dense-mode matmul: ``w``, the
    chained Khatri-Rao product of the OUTER non-target factors
    ((n_outer, R), all-ones when the mode has only one other), and
    ``u``, the INNER factor zero-padded to the tile span's 128-lane
    boundary ((inner_pad, R)) — so the pad columns of the value tiles
    meet exact-zero KR entries and contribute nothing, with no mask
    read on the hot path.

    Column c of the unfolding is ``outer_lin * inner_pad + inner_idx``
    (build_dense_layout's scatter), which is exactly the row order of
    ``(w[:, None, :] * u[None, :, :]).reshape(span, R)`` — the KR tile
    is a regular grid, so no gather is ever needed to build it.  ONE
    definition shared by the Pallas kernel and the XLA reference: bit
    parity between the engines starts with identical operands."""
    geo = layout.geometry
    dtype = factors[0].dtype
    R = int(factors[0].shape[1])
    w = jnp.ones((1, R), dtype=dtype)
    for k in geo.others[:-1]:
        w = (w[:, None, :] * factors[k][None, :, :]).reshape(-1, R)
    u = factors[geo.inner]
    pad = geo.inner_pad - int(u.shape[0])
    if pad:
        u = jnp.pad(u, ((0, pad), (0, 0)))
    return w, u


def dense_mttkrp(layout, factors: Sequence[jax.Array],
                 mode: int) -> jax.Array:
    """Dense-mode MTTKRP, XLA reference engine (``dense_xla``): the
    mode's unfolding tiles contracted against the Khatri-Rao'd factors
    in one batched dot_general — no index streams, no gathers, no
    scatter.  The always-works terminal of the dense engine chain
    (plain dot_general: no kernel or VMEM preconditions); the Pallas
    ``fused_dense`` engine computes the identical reduction per row
    tile (same operands, same precision, same accumulator dtype)."""
    if mode != layout.mode:
        raise ValueError("dense_mttkrp requires the layout's own mode")
    dtype = factors[0].dtype
    R = int(factors[0].shape[1])
    w, u = dense_operands(layout, factors, mode)
    kr = (w[:, None, :] * u[None, :, :]).reshape(-1, R)   # (span, R)
    out = jax.lax.dot_general(
        layout.tiles.astype(dtype), kr,
        dimension_numbers=(((2,), (0,)), ((), ())),
        preferred_element_type=_acc_dtype(dtype),
        precision=mxu_precision(dtype))                   # (ntiles, tile, R)
    return out.reshape(-1, R)[:layout.dim]


# -- blocked paths ---------------------------------------------------------

#: elements of one-hot materialized per scan step of the XLA engine —
#: the fallback's main tuning knob (more = fewer, bigger fused steps).
#: Env-overridable so the hardware tuning sweep (tools/tpu_tune.py) can
#: measure it; the default matches the round-2/3 measured configs.
_SCAN_TARGET = read_env_int("SPLATT_SCAN_TARGET_ELEMS")


def _block_chunks(nblocks: int, elems_per_block: int,
                  target_elems: Optional[int] = None) -> int:
    """Blocks per scan step, sized to bound one-hot materialization."""
    if target_elems is None:
        target_elems = _SCAN_TARGET
    c = max(1, target_elems // max(elems_per_block, 1))
    return min(c, nblocks)


def _scan_fused(layout: ModeLayout, factors: Sequence[jax.Array], mode: int,
                width: int, accumulate: bool,
                target_elems: Optional[int] = None) -> jax.Array:
    """Fused gather + Hadamard + one-hot reduce as a scan over block
    chunks (the XLA engine of the fused MTTKRP).

    The (nnz, R) partial-product tensor never exists in HBM: each scan
    step gathers the factor rows for one chunk of blocks, forms the
    Hadamard products, and reduces them with the one-hot contraction —
    all inside one fusion.  ≙ the reference's hot loop reading factor
    rows once per fiber inside the traversal (src/mttkrp.c:427-463)
    rather than staging an intermediate.
    """
    nb, B = layout.nblocks, layout.block
    R = int(factors[0].shape[1])
    dtype = factors[0].dtype
    nmodes = layout.nmodes
    C = _block_chunks(nb, width * B, target_elems)
    nsteps = -(-nb // C)
    nb_pad = nsteps * C

    # per-mode encoded streams through the stream-consumer interface
    # (blocked.ModeStreams): v1 = global i32 rows, the compact
    # encodings = narrow local/segment/delta/RLE streams + per-block
    # bases.  Decoding happens inside the scan step via the SHARED
    # decode helpers (blocked.decode_gather_ids/decode_segment_ids —
    # the same functions the fused_v2 kernel body runs), one chunk at
    # a time, so the global-i32 form never exists whole in HBM for
    # encoded layouts.
    from splatt_tpu.blocked import decode_global_ids, decode_segment_ids

    streams, bases, encs = layout.mode_streams()
    streams = list(streams)
    vals = layout.vals
    row_start = layout.row_start
    if nb_pad != nb:
        # pad with whole sentinel blocks: mode index = dim (falls in the
        # dropped tail rows; for v2 the BASE carries the sentinel and
        # the stored locals stay 0 — an RLE pad block's count vector is
        # [B, 0, ...], every entry in segment 0), other indices 0,
        # values 0
        pad = (nb_pad - nb) * B
        for k, s in enumerate(streams):
            if encs[k] == "rle":
                s = jnp.pad(s, ((0, nb_pad - nb), (0, 0)))
                streams[k] = s.at[nb:, 0].set(B)
            else:
                streams[k] = jnp.pad(
                    s, (0, pad),
                    constant_values=(layout.dim
                                     if bases is None and k == mode
                                     else 0))
        vals = jnp.pad(vals, (0, pad))
        row_start = jnp.pad(row_start, (0, nb_pad - nb),
                            constant_values=layout.dim)
        if bases is not None:
            bases = [jnp.pad(b, (0, nb_pad - nb),
                             constant_values=(layout.dim if k == mode
                                              else 0))
                     for k, b in enumerate(bases)]

    inds_s = tuple(s.reshape(nsteps, C, -1) for s in streams)
    vals_s = vals.reshape(nsteps, C, B)
    rs_s = row_start.reshape(nsteps, C)
    base_s = (None if bases is None
              else tuple(b.reshape(nsteps, C) for b in bases))

    iota = jnp.arange(width, dtype=jnp.int32)
    acc = _acc_dtype(dtype)

    def step(carry, xs):
        # per-mode (C,B) encoded chunks ((C,S) counts under RLE),
        # (C,B) vals, (C,) run starts, per-mode (C,) bases (None for
        # v1) — decoded here, in registers, via the shared helpers
        inds_c, vals_c, rs_c, base_c = xs
        prod = vals_c.astype(dtype)[..., None]
        for k in range(nmodes):
            if k != mode:
                # decode_global_ids handles every stream kind — incl.
                # gathering the layout's SORTED mode (its segment/RLE
                # stream expands here) when dispatching another mode
                g = decode_global_ids(
                    inds_c[k],
                    None if base_c is None else base_c[k][:, None],
                    encs[k], B)
                rows = jnp.take(factors[k], g.reshape(-1), axis=0,
                                mode="clip").reshape(C, B, R)
                prod = prod * rows
        if accumulate:
            if base_c is None:
                local = inds_c[mode]
            else:
                local = decode_global_ids(inds_c[mode],
                                          base_c[mode][:, None],
                                          encs[mode], B)
        elif base_c is None:
            local = inds_c[mode] - rs_c[:, None]
        else:
            local = decode_segment_ids(inds_c[mode], encs[mode], B)
        onehot = (local[:, None, :] == iota[None, :, None]).astype(dtype)
        part = jnp.einsum("cwb,cbr->cwr", onehot, prod,
                          preferred_element_type=acc,
                          precision=onehot_precision(dtype, "lhs"))
        if accumulate:
            return carry + jnp.sum(part, axis=0), None
        return carry, part

    if accumulate:
        init = jnp.zeros((width, R), dtype=acc)
        out, _ = jax.lax.scan(step, init, (inds_s, vals_s, rs_s, base_s))
        return out
    _, parts = jax.lax.scan(step, None, (inds_s, vals_s, rs_s, base_s))
    return parts.reshape(nb_pad, width, R)[:nb]


def _tuned_plan_for(layout: ModeLayout, factors: Sequence[jax.Array],
                    mode: int, path: str,
                    autotune: Optional[bool] = None,
                    shape_key: Optional[str] = None):
    """The applicable cached autotuner plan for this dispatch, or None.

    Applicability is strict — the plan was measured for exactly this
    (path, nnz_block) configuration, so a dispatch whose layout block
    or chosen path disagrees keeps the heuristic chain, and an engine
    the resilience registry demoted mid-run is never resurrected by a
    stale plan.  The tuner can make dispatch faster, never wronger.
    """
    from splatt_tpu import resilience, tune

    if not tune.autotune_enabled(autotune):
        return None
    nnz = getattr(layout, "nnz", None)
    if nnz is None:
        return None  # partial layout (gate-probing tests): no plan key
    plan = tune.cached_plan([int(f.shape[0]) for f in factors],
                            nnz, mode, int(factors[0].shape[1]),
                            factors[0].dtype,
                            skew=getattr(layout, "skew", ""),
                            mode_density=getattr(layout,
                                                 "density_bucket", ""))
    if (plan is None or plan.path != path
            or plan.nnz_block != layout.block
            or plan.idx_width != getattr(layout, "idx_width", "i32")
            or plan.val_storage != getattr(layout, "val_storage", "auto")
            or plan.packing != getattr(layout, "packing", "fixed")
            or plan.reorder != getattr(layout, "reorder", "identity")):
        # the format AND the layout-balance axes (packing, reorder —
        # docs/layout-balance.md) are part of the measured
        # configuration: a plan for the v2 encoding never steers a v1
        # layout's dispatch, a balanced-packing plan never steers a
        # fixed layout (and vice versa) — the tuner can make dispatch
        # faster, never wronger
        return None
    # per-shape (OOM) demotions only match with the shape_key, so it
    # must be computed when the caller (engine_plan, the cpd_als plan
    # report) did not thread one through — otherwise reporting would
    # promote an engine dispatch refuses to run
    if shape_key is None:
        shape_key = _engine_shape_key(layout, factors, mode)
    if resilience.is_demoted(plan.engine, shape_key):
        return None
    return plan


#: (engine, shape_key) pairs whose first (compile-bearing) dispatch
#: already ran under the deadline watchdog — warm calls skip the timer
_DEADLINE_ARMED: set = set()


def mttkrp_blocked(layout: ModeLayout, factors: List[jax.Array], mode: int,
                   path: str = "sorted_onehot",
                   impl: str = "xla",
                   scan_target: Optional[int] = None,
                   fallback: Optional[bool] = None,
                   autotune: Optional[bool] = None) -> jax.Array:
    """Blocked MTTKRP over one :class:`ModeLayout`.

    `path` picks the algorithm (static dispatch); `impl` picks the
    one-hot reduction engine:

    - "xla": fused scan — gather, Hadamard and the one-hot contraction
      all live inside one scan step, so the (nnz, R) partial-product
      tensor never hits HBM;
    - "pallas" (TPU): the fused Mosaic kernel when every input factor
      fits VMEM next to the working set (gather + Hadamard + reduce in
      VMEM; HBM traffic ≈ inds + vals + touched factor rows + output),
      else the unfused kernel on a precomputed prod;
    - "pallas_interpret": kernel semantics on CPU, for tests.

    `scan_target` tunes how much one-hot the XLA engine's scan step
    materializes (default: the autotuned plan's value when one applies,
    else SPLATT_SCAN_TARGET_ELEMS).  Resolved here — outside the jit —
    so it is part of the cache key and changing it always takes effect.

    Autotuning (`autotune`, default from Options.autotune /
    SPLATT_AUTOTUNE): when the plan cache (splatt_tpu/tune.py) holds a
    measured winner for this exact (shape regime, rank, dtype, path,
    nnz_block), that engine heads the chain; everything below — lazy
    probes, demotion, runtime fallback — applies to it unchanged.

    Runtime graceful degradation (`fallback`, default from
    SPLATT_ENGINE_FALLBACK / resilience.fallback_enabled): the ordered
    engine chain from :func:`engine_chain` is walked engine by engine;
    a failure of the selected engine demotes it in the resilience
    registry (process-wide, or per-shape for RESOURCE failures) and the
    next engine runs — one backend's failure degrades, not kills, the
    run.  The terminal "xla" engine (the stream/scatter formulation)
    has no kernel/VMEM preconditions, so the chain cannot run dry.
    """
    from splatt_tpu import resilience
    from splatt_tpu.utils import faults

    if fallback is None:
        fallback = resilience.fallback_enabled()
    # dense tile layouts have no streams to decode — they skip the
    # format-decode machinery entirely and dispatch on their own
    # engine chain (fused_dense -> dense_xla, docs/dense.md).  The
    # layout's encoding is authoritative over the `path` default, so a
    # caller handing us a dense layout without asking choose_path first
    # still lands on the dense matmul, never a sparse body that would
    # choke on the missing index streams.
    if getattr(layout, "encoding", "v1") == "dense":
        path = "dense"
    if getattr(layout, "encoding", "v1") not in ("v1", "dense"):
        from splatt_tpu.blocked import decode_to_v1
        from splatt_tpu.config import resolve_decode

        if resolve_decode() == "prep":
            # the A/B lever (docs/format.md): materialize the decoded
            # global-i32 form BEFORE any engine runs, so every path —
            # Pallas and XLA alike — executes the pre-format-v2
            # operand-prep dataflow the decode_overhead model prices
            layout = decode_to_v1(layout)
        else:
            # the format.decode fault site (docs/format.md): native
            # stream consumption failing at dispatch must degrade the
            # RUN, not kill it — classify, report format_fallback
            # evidence, and fall back to the materialized global-i32
            # v1 path every engine can always consume (bit-identical
            # by construction: decode_to_v1 runs the same
            # stream-consumer decode)
            try:
                faults.maybe_fail("format.decode")
            except Exception as e:
                cls = resilience.classify_failure(e)
                resilience.run_report().add(
                    "format_fallback", mode=int(mode), site="decode",
                    idx_width=getattr(layout, "idx_width", "?"),
                    failure_class=cls.value,
                    error=resilience.failure_message(e)[:200])
                layout = decode_to_v1(layout)
    # regime/shape_key are computed ONCE per dispatch and threaded
    # through the chain build — this runs once per mode per sweep
    # iteration, and the three consumers must agree on the regime
    regime = _chain_regime(layout, factors, mode)
    shape_key = _engine_shape_key(layout, factors, mode, regime=regime)
    chain = engine_chain(layout, factors, mode, path, impl,
                         shape_key=shape_key)
    # the autotuner's plan is the new head of dispatch: a measured
    # winner for this exact (path, block) is tried first, and everything
    # below — probes, demotion, fallback on failure — applies to it
    # unchanged, so a stale plan degrades to the heuristic chain
    tuned = _tuned_plan_for(layout, factors, mode, path,
                            autotune=autotune, shape_key=shape_key)
    if tuned is not None and tuned.engine in chain:
        if scan_target is None and tuned.engine == "xla_scan":
            scan_target = tuned.scan_target
        chain = [tuned.engine] + [e for e in chain if e != tuned.engine]
    if scan_target is None:
        scan_target = _SCAN_TARGET
    interpret = impl == "pallas_interpret"
    last = len(chain) - 1
    for i, engine in enumerate(chain):
        if i < last and not _engine_probed_ok(
                engine, regime, layout.block, interpret,
                idx_width=getattr(layout, "idx_width", "auto")):
            continue

        def attempt(engine=engine):
            faults.maybe_fail(f"engine.{engine}")
            # deadline watchdog (docs/guarded-als.md): bounds this
            # engine's FIRST call per shape — the one that compiles
            # (off by default; a blown deadline classifies TIMEOUT and
            # demotes per-shape below, exactly like OOM).  Warm
            # dispatches are microsecond async launches: skipping the
            # watchdog there saves a Timer thread per MTTKRP call.
            first = (engine, shape_key) not in _DEADLINE_ARMED
            if first:
                _DEADLINE_ARMED.add((engine, shape_key))
                if getattr(layout, "encoding", "v1") == "dense":
                    # first (compile-bearing) dispatch over a dense
                    # tile layout: record the hybrid dispatcher's
                    # verdict as evidence (docs/dense.md) — once per
                    # (engine, shape), like the deadline arming
                    resilience.run_report().add(
                        "dense_dispatch", engine=engine, mode=int(mode),
                        tile=int(layout.block), span=int(layout.span),
                        density_bucket=getattr(layout,
                                               "density_bucket", ""))
                elif getattr(layout, "encoding", "v1") != "v1":
                    # first (compile-bearing) dispatch over an encoded
                    # layout: record WHERE its decode runs — natively
                    # in-kernel/per-chunk, or at operand prep — next
                    # to the consumed encoding (docs/format.md); once
                    # per (engine, shape), like the deadline arming
                    resilience.run_report().add(
                        "format_decode", engine=engine, mode=int(mode),
                        enc=layout.format_desc(),
                        strategy=("kernel"
                                  if engine in STREAM_NATIVE_ENGINES
                                  else "prep"))
                with resilience.deadline(f"engine.{engine}"):
                    out = _mttkrp_blocked_jit(layout, factors, mode,
                                              path, impl, scan_target,
                                              engine)
            else:
                out = _mttkrp_blocked_jit(layout, factors, mode, path,
                                          impl, scan_target, engine)
            # chaos hook: a poison-armed engine fault corrupts this
            # engine's OUTPUT with non-finite values (under a fused
            # whole-sweep trace the poison is baked into the traced
            # program — flushed by the sweep rebuild a health rollback
            # performs)
            return faults.poison(f"engine.{engine}", out)

        try:
            resilience.note_engine_attempt(engine, shape_key)
            # TRANSIENT failures (a remote-compile relay hiccuping on
            # this engine's first jit) are retried in place with capped
            # backoff per the taxonomy contract — without this, one
            # transient HTTP 500 at compile time would demote the
            # flagship engine for the whole run, the PR 1 bug class at
            # run scope.  Deterministic/resource/unknown failures
            # propagate immediately to the demotion below.  The span
            # records host-side dispatch cost with the CHOSEN engine;
            # under a jitted sweep it fires at trace time, once per
            # compilation (docs/observability.md).
            from splatt_tpu import trace

            with trace.span("mttkrp.dispatch", mode=int(mode), path=path,
                            engine=engine, block=int(layout.block),
                            enc=getattr(layout, "format_desc",
                                        lambda: "i32/glob/?")()):
                return resilience.retry_transient(attempt,
                                                  label=f"engine.{engine}")
        except Exception as e:
            if not fallback or i == last:
                raise
            resilience.demote_engine(engine, e, shape_key=shape_key)
    raise AssertionError("engine chain exhausted")  # pragma: no cover


@partial(jax.jit, static_argnames=("mode", "path", "impl", "scan_target",
                                   "engine"))
def _mttkrp_blocked_jit(layout: ModeLayout, factors: List[jax.Array],
                        mode: int, path: str, impl: str,
                        scan_target: int, engine: str) -> jax.Array:
    from splatt_tpu.ops.pallas_kernels import (fused_mttkrp, fused_mttkrp_t,
                                               fused_mttkrp_tg,
                                               onehot_reduce_full,
                                               onehot_reduce_sorted,
                                               vmem_chunk)

    dim = int(factors[mode].shape[0])
    R = factors[mode].shape[1]
    interpret = impl == "pallas_interpret"

    if path == "dense":
        # the dense tile layout's batched matmul (docs/dense.md): the
        # MXU kernel when probed/VMEM-fit, else the dot_general
        # reference — bit-identical engines, so demotion costs speed,
        # never numerics
        if engine == "fused_dense":
            from splatt_tpu.ops.pallas_kernels import fused_dense

            return fused_dense(layout, factors, mode,
                               interpret=interpret)
        return dense_mttkrp(layout, factors, mode)

    if path in ("scatter", "sorted_scatter") or engine == "xla":
        if path == "sorted_scatter" and mode != layout.mode:
            # indices_are_sorted=True on unsorted indices is a
            # correctness-affecting XLA hint, not just a pessimization.
            raise ValueError("sorted_scatter requires the layout's own mode")
        # XLA fuses the gather+Hadamard producers into the scatter-add,
        # so this path has no (nnz, R) HBM intermediate either.  As the
        # `engine == "xla"` terminal-fallback of the blocked paths it is
        # the stream formulation over the layout's arrays: correct for
        # any mode, no kernel or VMEM preconditions.  v2 layouts decode
        # per mode inside the same fusion (mode_ids/_gather_prod_layout).
        sorted_seg = (path == "sorted_scatter"
                      or (path not in ("scatter",) and mode == layout.mode))
        prod = _gather_prod_layout(layout, factors, mode)
        nseg = dim + 1 if mode == layout.mode else dim
        out = jax.ops.segment_sum(prod.astype(_acc_dtype(prod.dtype)),
                                  layout.mode_ids(mode),
                                  num_segments=nseg,
                                  indices_are_sorted=sorted_seg)
        return out[:dim]

    nb, B = layout.nblocks, layout.block
    itemsize = jnp.dtype(factors[0].dtype).itemsize

    # the resolved engine is a static arg: mttkrp_blocked walks the
    # engine_chain outside the jit, so a runtime demotion retraces with
    # the next engine instead of recompiling the same failing one
    plan = engine

    if path == "privatized":
        width = -(-(dim + 1) // 8) * 8  # +1: room for the sentinel row
        if plan == "fused_v2":
            from splatt_tpu.ops.pallas_kernels import fused_mttkrp_v2

            return fused_mttkrp_v2(layout, factors, mode, width,
                                   accumulate=True,
                                   interpret=interpret)[:dim]
        if plan == "fused_t":
            return fused_mttkrp_t(layout, factors, mode, width,
                                  accumulate=True,
                                  interpret=interpret)[:dim]
        if plan == "fused_tg":
            return fused_mttkrp_tg(layout, factors, mode, width,
                                   accumulate=True,
                                   interpret=interpret)[:dim]
        if plan == "fused":
            return fused_mttkrp(layout, factors, mode, width,
                                accumulate=True,
                                interpret=interpret)[:dim]
        if plan == "unfused_pallas":
            prod = _gather_prod_layout(layout, factors,
                                       mode).reshape(nb, B, R)
            local = layout.mode_ids(mode).reshape(nb, B)
            return onehot_reduce_full(local, prod, width,
                                      interpret=interpret,
                                      chunk=vmem_chunk(width, B, int(R),
                                                       itemsize))[:dim]
        return _scan_fused(layout, factors, mode, width,
                           accumulate=True, target_elems=scan_target)[:dim]

    if path == "sorted_onehot":
        if mode != layout.mode:
            raise ValueError("sorted_onehot requires the layout's own mode")
        S = layout.seg_width
        if plan == "fused_v2":
            from splatt_tpu.ops.pallas_kernels import fused_mttkrp_v2

            parts = fused_mttkrp_v2(layout, factors, mode, S,
                                    accumulate=False, interpret=interpret)
        elif plan == "fused_t":
            parts = fused_mttkrp_t(layout, factors, mode, S,
                                   accumulate=False, interpret=interpret)
        elif plan == "fused_tg":
            parts = fused_mttkrp_tg(layout, factors, mode, S,
                                    accumulate=False, interpret=interpret)
        elif plan == "fused":
            parts = fused_mttkrp(layout, factors, mode, S,
                                 accumulate=False, interpret=interpret)
        elif plan == "unfused_pallas":
            prod = _gather_prod_layout(layout, factors,
                                       mode).reshape(nb, B, R)
            local = layout.blocked_locals()
            parts = onehot_reduce_sorted(local, prod, S,
                                         interpret=interpret,
                                         chunk=vmem_chunk(S, B, int(R),
                                                          itemsize))
        else:
            parts = _scan_fused(layout, factors, mode, S,
                                accumulate=False,
                                target_elems=scan_target)    # (nb, S, R)
        idx = (layout.row_start[:, None] + jnp.arange(S, dtype=jnp.int32)).reshape(-1)
        out = jnp.zeros((dim + S + 1, R), dtype=parts.dtype)
        out = out.at[idx].add(parts.reshape(-1, R))
        return out[:dim]

    raise ValueError(f"unknown path {path!r}")


mttkrp_blocked.clear_cache = _mttkrp_blocked_jit.clear_cache


def _chain_regime(layout: ModeLayout, factors: Sequence[jax.Array],
                  mode: int) -> str:
    """Probe regime of this call — per lane-chunk regime: a Mosaic
    crash in the many-chunk (small-dims) regime must not veto the
    flagship single-chunk production shapes, and vice versa.  Only the
    GATHERED (non-target) factors are lane-chunked, so the target
    mode's dim does not enter the classification."""
    from splatt_tpu.ops.pallas_kernels import probe_regime

    return probe_regime([int(f.shape[0])
                         for k, f in enumerate(factors) if k != mode],
                        layout.block)


def _engine_shape_key(layout: ModeLayout, factors: Sequence[jax.Array],
                      mode: int, regime: Optional[str] = None) -> str:
    """Demotion scope for RESOURCE failures — the same (regime, block)
    granularity the capability probes use, so an OOM at one shape never
    demotes the engine for shapes that fit.  The single owner of the
    key format: demotions recorded at dispatch and the chain pruning in
    engine_plan must agree on it.  `regime` skips recomputation when
    the caller already classified the call.

    The v2 compact encoding is part of the scope (a ``:v2`` suffix;
    v1 keys stay byte-identical to the pre-format-v2 era): an OOM under
    a v2 plan demotes the engine for v2 dispatches only — the v1 path
    keeps its standing, and vice versa."""
    if regime is None:
        regime = _chain_regime(layout, factors, mode)
    key = f"{regime}:b{layout.block}"
    # getattr: gate-probing tests pass partial layout stand-ins
    enc = getattr(layout, "encoding", "v1")
    if enc == "dense":
        # the dense tile scope (docs/dense.md): a dense-engine OOM
        # demotes the engine for dense dispatches only — the sparse
        # path's standing is untouched, and vice versa
        key += ":dn"
    elif enc != "v1":
        key += f":{enc}"
    # layout-balance axes scope their own demotions exactly like :v2
    # (docs/layout-balance.md): an OOM under a balanced/reordered
    # layout never demotes the engine for the default layouts, and
    # vice versa — default-layout keys stay byte-identical to the
    # pre-balance era
    if getattr(layout, "packing", "fixed") != "fixed":
        key += ":bal"
    if getattr(layout, "reorder", "identity") != "identity":
        key += ":ro"
    return key


def _engine_probed_ok(engine: str, regime: str, block: int,
                      interpret: bool, idx_width: str = "auto") -> bool:
    """Capability gate of one chain candidate, probed LAZILY: each
    probe costs a remote compile attempt on the tunneled TPU service
    (~35 s, 240 s wedged) — an engine never reached because an earlier
    one won must not be probed at all, which is why engine_chain defers
    this check to selection/fallback time instead of resolving the
    whole chain eagerly.  `idx_width` scopes the fused_v2 probe to the
    layout's encoding family (the stream kinds are static kernel
    params — an "auto" verdict never vouches for delta/RLE)."""
    from splatt_tpu.ops.pallas_kernels import (fused_gather_supported,
                                               fused_t_supported,
                                               fused_tg_supported)

    from splatt_tpu.ops.pallas_kernels import fused_v2_supported

    if interpret or engine in ("unfused_pallas", "xla_scan", "xla",
                               "dense_xla"):
        return True
    if engine == "fused_dense":
        from splatt_tpu.ops.pallas_kernels import fused_dense_supported

        return fused_dense_supported(regime, block)
    if engine == "fused_v2":
        return fused_v2_supported(regime, block, idx_width)
    if engine == "fused_t":
        return fused_t_supported(regime, block)
    if engine == "fused_tg":
        return fused_tg_supported(regime, block)
    if engine == "fused":
        return fused_gather_supported(regime, block)
    return True


def engine_chain(layout: ModeLayout, factors: List[jax.Array], mode: int,
                 path: str = "sorted_onehot", impl: str = "xla",
                 *, shape_key: Optional[str] = None) -> List[str]:
    """The ORDERED engine fallback chain for this call: every engine
    whose cheap gates (VMEM plan, HBM budget, runtime demotions) pass,
    best first — the decode-in-kernel fused_v2 engine (compact layouts
    only, docs/format.md) → fused Pallas (fused_t → fused_tg →
    experimental fused) → unfused Pallas → xla_scan → the terminal
    "xla" stream/scatter formulation, which has no preconditions and
    cannot fail to apply.
    Capability probes are NOT consulted here (they cost a remote
    compile each); :func:`_engine_probed_ok` runs them lazily when an
    engine is actually selected.  :func:`mttkrp_blocked` walks this
    chain at dispatch and again on runtime failure, so one engine's
    failure degrades the run to the next engine instead of killing it.
    """
    from splatt_tpu import resilience
    from splatt_tpu.ops.pallas_kernels import (fused_t_vmem_ok,
                                               fused_tg_vmem_ok,
                                               fused_vmem_ok, vmem_chunk)

    if path in ("scatter", "sorted_scatter", "stream"):
        return ["xla"]
    if (path == "dense"
            or getattr(layout, "encoding", "v1") == "dense"):
        # the dense tile layout's own chain (docs/dense.md): the MXU
        # kernel when the tile working set fits VMEM, then the
        # dot_general reference — which has no kernel or VMEM
        # preconditions, so the dense chain cannot run dry either
        from splatt_tpu.ops.pallas_kernels import dense_vmem_ok

        if shape_key is None:
            shape_key = _engine_shape_key(layout, factors, mode)
        chain = []
        if (impl in ("pallas", "pallas_interpret")
                and not resilience.is_demoted("fused_dense", shape_key)
                and dense_vmem_ok(layout, factors, mode)):
            chain.append("fused_dense")
        chain.append("dense_xla")
        return chain
    dim = int(factors[mode].shape[0])
    R = int(factors[0].shape[1])
    B = layout.block
    itemsize = jnp.dtype(factors[0].dtype).itemsize
    pallas = impl in ("pallas", "pallas_interpret")
    if path == "privatized":
        width = -(-(dim + 1) // 8) * 8
    else:
        width = layout.seg_width
    if shape_key is None:
        shape_key = _engine_shape_key(layout, factors, mode)

    def live(name):
        return not resilience.is_demoted(name, shape_key)

    chain = []
    # the decode-in-kernel engine heads the chain for compact layouts
    # (docs/format.md): it consumes the raw encoded streams natively —
    # achieved HBM bytes ≈ encoded bytes — where the prep-decoding
    # kernels below first rematerialize global i32.  SPLATT_DECODE=
    # "prep" is the A/B lever forcing the old dataflow.
    from splatt_tpu.config import resolve_decode
    from splatt_tpu.ops.pallas_kernels import fused_v2_vmem_ok

    if (pallas and getattr(layout, "encoding", "v1") != "v1"
            and resolve_decode() == "kernel" and live("fused_v2")
            and fused_v2_vmem_ok(factors, mode, width, B)):
        chain.append("fused_v2")
    if pallas and live("fused_t") and fused_t_vmem_ok(factors, mode,
                                                      width, B):
        chain.append("fused_t")
    if pallas and live("fused_tg") and fused_tg_vmem_ok(factors, mode,
                                                        width, B):
        chain.append("fused_tg")
    # The row-major fused kernel's arbitrary u[idx] gather is known-
    # unlowerable on current jax/Mosaic (VERDICT r4 weak #5): it is out
    # of the production dispatch order — no probe slot, no session time
    # — unless explicitly re-enabled for a future jax version.  Its
    # math stays covered by the interpret-mode tests.
    if pallas and read_env("SPLATT_EXPERIMENTAL_FUSED") == "1" \
            and live("fused") and fused_vmem_ok(factors, mode, width, B):
        chain.append("fused")
    if (pallas and live("unfused_pallas")
            and vmem_chunk(width, B, R, itemsize) >= 1
            and _unfused_hbm_ok(layout, R, itemsize)):
        chain.append("unfused_pallas")
    if live("xla_scan"):
        chain.append("xla_scan")
    # terminal engine: the stream/scatter formulation — always appended,
    # never demotable out of the chain, so dispatch cannot run dry
    chain.append("xla")
    return chain


def engine_plan(layout: ModeLayout, factors: List[jax.Array], mode: int,
                path: str = "sorted_onehot", impl: str = "xla",
                autotune: Optional[bool] = None) -> str:
    """Which engine :func:`mttkrp_blocked` will actually run for this
    call — the applicable autotuned plan's engine when one is cached,
    else the first :func:`engine_chain` entry whose (lazily probed)
    capability gate passes.  Dispatch falls back silently (VMEM gates,
    Mosaic capability, runtime demotions), so benches and tests use
    this to label results truthfully.
    """
    chain = engine_chain(layout, factors, mode, path, impl)
    regime = _chain_regime(layout, factors, mode)
    interpret = impl == "pallas_interpret"
    tuned = _tuned_plan_for(layout, factors, mode, path, autotune=autotune)
    if tuned is not None and tuned.engine in chain:
        chain = [tuned.engine] + [e for e in chain if e != tuned.engine]
    for engine in chain[:-1]:
        if _engine_probed_ok(engine, regime, layout.block, interpret,
                             idx_width=getattr(layout, "idx_width",
                                               "auto")):
            return engine
    return chain[-1]


class Plan(NamedTuple):
    """One MTTKRP dispatch decision: the resolved engine family
    (`impl`), the algorithm (`path`), and the reduction engine that
    will actually execute (`engine`).  :func:`plan_mttkrp` is the single
    source of this truth — :func:`mttkrp` executes the plan it returns
    and :func:`describe_plan`/benches/tests print the same object, so
    the reported plan cannot desynchronize from what runs."""

    impl: str    # "native" | "pallas" | "pallas_interpret" | "xla"
    path: str    # one of PATHS
    engine: str  # "native" | "fused_t" | "fused_tg" | "fused" |
                 # "unfused_pallas" | "xla_scan" | "xla"


def _native_runnable(layout: ModeLayout, factors: Sequence[jax.Array],
                     path: Optional[str]) -> bool:
    """Exactly the conditions under which the native C++ engine runs —
    each mirrors a bailout inside :func:`native.mttkrp` or the trace
    check in dispatch, so `plan.engine == "native"` iff it executes."""
    if path is not None:
        return False  # explicit path = the caller wants that jit engine
    if any(isinstance(U, jax.core.Tracer) for U in factors):
        return False  # inside a jit trace (e.g. the fused sweep)
    if layout.encoding != "v1":
        return False  # the C++ ABI reads contiguous global i32 indices
    if getattr(layout, "block_nnz", None) is not None:
        # balanced packing pads mid-stream: the native engine reads the
        # first `nnz` positions as the real prefix, which no longer
        # holds (docs/layout-balance.md) — the XLA paths decode pads as
        # additive identities instead
        return False
    vdt = layout.vals.dtype
    if vdt not in (jnp.float32, jnp.float64):  # splint: ignore[SPL005] native-engine f32/f64 ABI gate
        return False
    if any(f.dtype != vdt for f in factors):
        return False  # mixed dtypes: the XLA paths own promotion
    if layout.nmodes > 8:
        return False
    return native_available()


def _resolve_dispatch(X: "BlockedSparse", factors: Sequence[jax.Array],
                      mode: int, path: Optional[str],
                      impl: Optional[str]) -> tuple:
    """Resolve (impl, path) — the part of the dispatch decision
    :func:`mttkrp` needs to execute.  The engine-within-impl choice is
    made by engine_plan inside mttkrp_blocked; plan_mttkrp surfaces it
    for reporting without making the hot path compute it twice."""
    if impl is None:
        impl = choose_impl(X.opts)
    if impl == "native":
        if _native_runnable(X.layout_for(mode), factors, path):
            return "native", path or _choose_path_bs(X, mode)
        impl = "xla"
    if path is None:
        path = _choose_path_bs(X, mode)
    return impl, path


def plan_mttkrp(X: "BlockedSparse", factors: Sequence[jax.Array], mode: int,
                path: Optional[str] = None,
                impl: Optional[str] = None) -> Plan:
    """Compute the dispatch decision :func:`mttkrp` will execute for
    this call (≙ mttkrp_csf dispatch, src/mttkrp.c:1287-1341 — but
    reified as a value so benches/CLI/tests can consume the same
    decision instead of hand-mirroring the conditions)."""
    impl, path = _resolve_dispatch(X, factors, mode, path, impl)
    if impl == "native":
        return Plan("native", path, "native")
    return Plan(impl, path,
                engine_plan(X.layout_for(mode), factors, mode, path, impl,
                            autotune=X.opts.autotune))


def describe_plan(X: "BlockedSparse", factors: List[jax.Array]) -> str:
    """One-line human-readable dispatch plan for a CPD run over `X` —
    which impl (native/pallas/xla) and, per mode, which path/engine
    mttkrp() will actually execute.  Dispatch falls back silently (VMEM
    gates, Mosaic capability probes), so the CLI prints this at
    Verbosity.LOW to make the chosen engine observable
    (≙ the reference's CSF/tile report lines, src/stats.c:226-296).
    Built from the same :func:`plan_mttkrp` objects dispatch executes.
    """
    impl = choose_impl(X.opts)
    parts = []
    for m in range(X.nmodes):
        plan = plan_mttkrp(X, factors, m)
        parts.append(f"mode{m}={plan.path}/{plan.engine}")
    note = ""
    from splatt_tpu.ops.pallas_kernels import PROBE_STATES

    unproven = {k: v for k, v in PROBE_STATES.items()
                if v in ("timeout", "infra")}
    if unproven:
        labels = [f"{k} {'timed out' if v == 'timeout' else 'service error'}"
                  for k, v in sorted(unproven.items())]
        note = f" [probe {'; '.join(labels)}: unproven, not rejected]"
    from splatt_tpu import resilience

    demoted = resilience.demotions()
    if demoted:
        labels = [d.engine + (f"@{d.shape_key}" if d.shape_key else "")
                  for d in demoted]
        note += f" [demoted this run: {', '.join(sorted(set(labels)))}]"
    return f"engine plan: impl={impl} " + " ".join(parts) + note


def _unfused_hbm_ok(layout: ModeLayout, R: int, itemsize: int,
                    budget_bytes: int = 6 << 30) -> bool:
    """Whether the unfused Pallas plan's (nnz_pad, R) HBM partial-product
    intermediate fits comfortably (XLA pads R to 128 lanes for the
    gather output, so cost the padded width).  The xla_scan engine never
    materializes it and has no such limit."""
    lanes = -(-R // 128) * 128
    return layout.nnz_pad * lanes * itemsize <= budget_bytes


def _onehot_pays(opts: Options) -> bool:
    """Whether the one-hot contraction paths are worth choosing.

    The redundant MACs are only free where a matrix unit executes them
    (measured: sorted_scatter ≈ 2x faster than the one-hot on CPU at
    2M nnz).  ``use_pallas=True`` forces them on any backend (mirrors
    choose_impl's force semantics — tests rely on it).
    """
    return opts.use_pallas is True or jax.default_backend() == "tpu"


def choose_path(layout: ModeLayout, mode: int, opts: Options) -> str:
    """Static path selection (≙ mttkrp_csf dispatch + p_is_privatized)."""
    if getattr(layout, "encoding", "v1") == "dense":
        return "dense"
    if mode == layout.mode:
        if layout.seg_width <= opts.onehot_cap and _onehot_pays(opts):
            return "sorted_onehot"
        return "sorted_scatter"
    return "scatter"


def _choose_path_bs(bs: BlockedSparse, mode: int) -> str:
    layout = bs.layout_for(mode)
    if getattr(layout, "encoding", "v1") == "dense":
        # the hybrid per-mode dispatcher (docs/dense.md): a mode whose
        # compiled layout is dense tiles runs the dense matmul path;
        # every other mode keeps its sparse-blocked path
        return "dense"
    dim = bs.dims[mode]
    if mode != layout.mode:
        if (_onehot_pays(bs.opts)
                and dim + 16 <= bs.opts.priv_cap
                and dim <= bs.opts.priv_threshold * max(bs.nnz, 1)):
            return "privatized"
        return "scatter"
    return choose_path(layout, mode, bs.opts)


def native_available() -> bool:
    """Whether the native C++ MTTKRP engine can run here."""
    from splatt_tpu import native

    return native.available()


def choose_impl(opts: Options) -> str:
    """Pick the MTTKRP engine: Pallas on TPU (or when forced), the
    native C++ host kernel on CPU when the library is available,
    scanned-XLA otherwise; forcing Pallas off-TPU uses interpret mode.
    ``use_pallas=False`` forces pure-XLA (the differential tests' way to
    pin the jit engines)."""
    backend = jax.default_backend()
    if opts.use_pallas is None:
        if backend == "tpu":
            return "pallas"
        return "native" if native_available() else "xla"
    if not opts.use_pallas:
        return "xla"
    return "pallas" if backend == "tpu" else "pallas_interpret"


def mttkrp(X: Union[SparseTensor, BlockedSparse], factors: List[jax.Array],
           mode: int, path: Optional[str] = None,
           impl: Optional[str] = None) -> jax.Array:
    """Public MTTKRP (≙ splatt_mttkrp, include/splatt/api_kernels.h:98-119).

    Accepts a host COO tensor (oracle path) or a compiled BlockedSparse.
    `path` forces a specific execution path and `impl` a reduction
    engine (tests sweep both).
    """
    if isinstance(X, SparseTensor):
        if path is not None and path != "stream":
            raise ValueError(
                f"path={path!r} requires a BlockedSparse input; a COO "
                f"SparseTensor only supports the stream path")
        inds = jnp.asarray(X.inds)
        vals = jnp.asarray(X.vals)
        return mttkrp_stream(inds, vals, factors, mode, X.dims[mode])
    rimpl, rpath = _resolve_dispatch(X, factors, mode, path, impl)
    layout = X.layout_for(mode)
    if rimpl == "native":
        out = _run_native(layout, factors, mode)
        if out is not None:
            return out
        # the shared library failed at call time (not a planned
        # condition — e.g. deleted mid-session); degrade to XLA
        rimpl = "xla"
    return mttkrp_blocked(layout, factors, mode, path=rpath, impl=rimpl,
                          fallback=X.opts.engine_fallback,
                          autotune=X.opts.autotune)


def _run_native(layout: ModeLayout, factors: List[jax.Array],
                mode: int) -> Optional[jax.Array]:
    """Execute the native C++ host engine for a planned "native" call.
    Runnability was decided by :func:`_native_runnable`; native.mttkrp
    still re-validates defensively and returns None on surprise."""
    from splatt_tpu import native

    dims = [int(f.shape[0]) for f in factors]
    out = native.mttkrp(
        np.asarray(layout.inds), np.asarray(layout.vals),
        [np.asarray(U) for U in factors], mode, dims,
        sorted_by_mode=(mode == layout.mode), nnz=layout.nnz)
    if out is None:
        return None
    return jnp.asarray(out)
