"""Async ICI ring exchange — Pallas remote-copy ring sweeps (docs/ring.md).

The POINT2POINT path (parallel/ring.py) already gives the fine
decomposition O(dim/ndev) peak factor memory, but every ``ppermute``
hop is a barrier: the device finishes its masked pass over the local
nonzeros, THEN waits for the whole hop, THEN starts the next pass —
per-iteration wall-clock pays comm + compute in series.  The
reference's medium-grained MPI decomposition wins precisely by
streaming the row exchange while ranks compute (Isend/Irecv in
p_reduce_rows_point2point / p_update_rows_point2point,
src/mpi/mpi_cpd.c:323-546), and r04's bytes model showed the MTTKRP
kernel is bandwidth-bound — hiding the exchange is worth a full hop
time per step.

This module is the TPU-native version of that overlap: one Pallas
kernel per ring phase holds the entire ``ndev``-step loop, with the
factor row-block double-buffered in HBM and
``pltpu.make_async_remote_copy`` DMAs streaming block *s+1* from the
left neighbor while the compute for block *s* runs — the ICI DMA
engines move bytes concurrently with the VPU/MXU work, so a hop only
costs wall-clock when it is longer than the compute it hides under.

Double-buffer protocol (per device, per kernel; docs/ring.md has the
full lifecycle diagram):

- ``buf`` is a ``(2, block, R)`` HBM landing zone; step *s* computes on
  slot ``s % 2`` while the RDMA for step *s+1* lands in slot
  ``(s+1) % 2``.
- A **credit** (regular) semaphore implements backpressure: a device
  may start its step-*s* send only after consuming a credit granted by
  its RIGHT neighbor, and a device grants its LEFT neighbor a credit
  only once it has finished computing on (= freed) a slot AND retired
  its own send that sourced that slot.  Credits granted == sends, so
  the semaphore drains to zero — a leaked count would wedge the next
  collective.
- ``send_sem``/``recv_sem`` are the DMA-completion semaphores:
  ``recv`` is waited before computing on a freshly received slot,
  ``send`` before a slot is handed back as a landing zone (and before
  the kernel retires).  Each DMA's send and recv side is waited
  exactly once.
- Step 0 opens with a neighbor barrier (``get_barrier_semaphore``) so
  no RDMA can land on a device that has not yet entered the kernel.

Fallback ladder: the kernels only run on a real TPU backend
(:func:`async_ring_supported`); everywhere else — CPU tests,
interpret mode, jax builds without the RDMA primitives —
:func:`async_ring_gather_rows` / :func:`async_blockwise_reduce_rows`
delegate to the ``ppermute`` implementations in
:mod:`splatt_tpu.parallel.ring`, so the ASYNC_RING comm strategy keeps
*today's semantics bit-for-bit* off-TPU and tier-1 exercises the exact
dataflow.  A runtime failure of the async engine is degraded
classified by the driver (sharded.py): the comm engine is demoted
under its own shape key and the sweep rebuilds on the sync ring, then
all2all (``comm_fallback`` run-report events) — never an unhandled
exception.  The ``comm.ring_exchange`` fault site arms that ladder for
chaos drills.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from splatt_tpu.utils import faults

#: nnz rows processed per in-kernel chunk of the gather/reduce compute
#: loops — sublane-aligned, small enough that the chunk working set
#: (indices + rows + one-hot tiles) stays a sliver of VMEM next to the
#: resident factor block.
_NNZ_CHUNK = 1024


def _pltpu():
    from jax.experimental.pallas import tpu as pltpu

    return pltpu


@functools.cache
def async_ring_supported() -> bool:
    """Whether the Pallas remote-copy ring kernels can run here: a real
    TPU backend (interpret mode has no ICI) and a jax with the RDMA
    primitives.  Everywhere else the ASYNC_RING strategy silently uses
    the ppermute dataflow — same math, bit-for-bit — so selection never
    needs to fail off-TPU."""
    try:
        if jax.default_backend() != "tpu":
            return False
        pltpu = _pltpu()
        return (hasattr(pltpu, "make_async_remote_copy")
                and hasattr(pltpu, "get_barrier_semaphore"))
    # splint: ignore[SPL002] backend discovery off-accelerator: any
    # failure to even ask means "not a TPU", which selects the fallback
    except Exception:
        return False


# -- kernel building blocks -------------------------------------------------


def _neighbor_barrier(pltpu, left, right):
    """Step-0 rendezvous: both neighbors must be inside the kernel
    (buffers + semaphores live) before any RDMA or credit signal may
    target them.  Signal both sides, wait for both — balanced, so the
    global barrier semaphore drains."""
    barrier = pltpu.get_barrier_semaphore()
    for nbr in (left, right):
        pltpu.semaphore_signal(barrier, inc=1, device_id=(nbr,),
                               device_id_type=pltpu.DeviceIdType.MESH)
    pltpu.semaphore_wait(barrier, 2)


def _grant_credit(pltpu, credit_sem, left):
    pltpu.semaphore_signal(credit_sem, inc=1, device_id=(left,),
                           device_id_type=pltpu.DeviceIdType.MESH)


def _hop(pltpu, buf_ref, src_slot, dst_slot, send_sem, recv_sem, right):
    """The step's remote copy descriptor: my ``src_slot`` streams into
    the right neighbor's ``dst_slot``.  Reconstructed with identical
    refs wherever its send/recv side is waited (the descriptor is just
    the address/semaphore tuple)."""
    return pltpu.make_async_remote_copy(
        src_ref=buf_ref.at[src_slot], dst_ref=buf_ref.at[dst_slot],
        send_sem=send_sem, recv_sem=recv_sem, device_id=(right,),
        device_id_type=pltpu.DeviceIdType.MESH)


def _stage(pltpu, src_ref, dst_ref, sem):
    """Blocking local (HBM<->VMEM) copy — the staging moves around the
    resident block; the REMOTE copies are the ones left in flight."""
    cp = pltpu.make_async_copy(src_ref, dst_ref, sem)
    cp.start()
    cp.wait()


# -- the TPU kernels --------------------------------------------------------
#
# Both kernels share the skeleton: grid=(ndev,) ring steps executed
# sequentially on the core, a (2, block, R) HBM comm buffer (a pallas
# output the caller discards), VMEM staging for the resident block,
# and an inner fori_loop over nnz chunks for the compute.  The gather
# kernel accumulates picked rows INTO the (nnz_pad, R) output across
# steps (read-modify-write through VMEM; the grid is sequential so the
# revisits cannot race); the reduce kernel accumulates the travelling
# (block, R) partial in VMEM and writes it once at the final step.


def _ring_gather_kernel(idx_div_ref, idx_loc_ref, u0_ref, rows_ref,
                        buf_ref, u_vmem, rows_vmem, div_vmem, loc_vmem,
                        local_sems, send_sem, recv_sem, credit_sem, *,
                        ndev: int, axis: str, block: int, nnz_pad: int):
    """One device's whole gather ring (≙ mpi_update_rows streamed).

    idx_div/idx_loc: (nnz_pad,) int32 — owner shard and within-block
    row of each local nonzero's request (step-independent; only the
    ownership mask changes per step; pad entries carry owner -1 and
    match no shard).  u0: (block, R) my factor block.  rows (out):
    (nnz_pad, R) picked rows.  buf (out, discarded): (2, block, R)
    the double-buffered landing zone.
    """
    pltpu = _pltpu()
    s = pl.program_id(0)
    my = jax.lax.axis_index(axis)
    right = jax.lax.rem(my + 1, ndev)
    left = jax.lax.rem(my + ndev - 1, ndev)
    cur = jax.lax.rem(s, 2)
    nchunks = nnz_pad // _NNZ_CHUNK

    @pl.when(s == 0)
    def _open():
        _neighbor_barrier(pltpu, left, right)
        # my own block seeds slot 0 (local HBM->HBM copy); slot 1 is a
        # free landing zone — grant the left neighbor its first credit
        _stage(pltpu, u0_ref, buf_ref.at[0], local_sems.at[0])
        _grant_credit(pltpu, credit_sem, left)

    @pl.when(s > 0)
    def _recv_wait():
        # the step-(s-1) hop delivered this slot
        _hop(pltpu, buf_ref, 1 - cur, cur, send_sem, recv_sem,
             right).wait_recv()

    @pl.when(s < ndev - 1)
    def _send():
        # backpressure: consume the credit the right neighbor granted
        # when it freed the destination slot, then stream my current
        # block forward — this DMA is what overlaps the compute below
        pltpu.semaphore_wait(credit_sem, 1)
        _hop(pltpu, buf_ref, cur, 1 - cur, send_sem, recv_sem,
             right).start()

    # stage the resident block for compute (HBM -> VMEM)
    _stage(pltpu, buf_ref.at[cur], u_vmem, local_sems.at[1])

    shard = jax.lax.rem(my - s + ndev, ndev)

    def chunk_body(c, _):
        lo = c * _NNZ_CHUNK
        # index streams live in HBM (ANY refs load only via DMA)
        _stage(pltpu, idx_div_ref.at[pl.ds(lo, _NNZ_CHUNK)], div_vmem,
               local_sems.at[2])
        _stage(pltpu, idx_loc_ref.at[pl.ds(lo, _NNZ_CHUNK)], loc_vmem,
               local_sems.at[2])
        div = div_vmem[...]
        loc = loc_vmem[...]
        mask = div == shard
        # one-hot row pick against the VMEM-resident block: the same
        # MXU-friendly contraction the single-chip engines use
        # ((C, block) @ (block, R)).  Each nonzero matches exactly one
        # shard, so the cross-step accumulation only ever adds zeros —
        # bit-identical to a single gather.
        safe = jnp.where(mask, loc, 0)
        iota = jax.lax.broadcasted_iota(jnp.int32, (_NNZ_CHUNK, block), 1)
        onehot = ((safe[:, None] == iota)
                  & mask[:, None]).astype(u_vmem.dtype)
        picked = jax.lax.dot_general(
            onehot, u_vmem[...],
            dimension_numbers=(((1,), (0,)), ((), ())),
            preferred_element_type=rows_vmem.dtype)

        @pl.when(s > 0)
        def _load():
            _stage(pltpu, rows_ref.at[pl.ds(lo, _NNZ_CHUNK)], rows_vmem,
                   local_sems.at[2])

        @pl.when(s == 0)
        def _zero():
            rows_vmem[...] = jnp.zeros_like(rows_vmem)

        rows_vmem[...] += picked
        _stage(pltpu, rows_vmem, rows_ref.at[pl.ds(lo, _NNZ_CHUNK)],
               local_sems.at[2])
        return 0

    jax.lax.fori_loop(0, nchunks, chunk_body, 0)

    # slot bookkeeping: my step-s send sourced buf[cur]; once it has
    # retired AND the compute above consumed the slot, hand it back to
    # the left neighbor as a landing zone.  Grants happen for the slots
    # a future send will actually target (steps 0..ndev-3); the final
    # step only drains the last in-flight send.
    @pl.when((s <= ndev - 3) & (s < ndev - 1))
    def _free():
        _hop(pltpu, buf_ref, cur, 1 - cur, send_sem, recv_sem,
             right).wait_send()
        _grant_credit(pltpu, credit_sem, left)

    @pl.when((s == ndev - 2) & (s < ndev - 1))
    def _retire_penultimate():
        # the second-to-last send is the LAST send; its slot is never
        # re-landed, so retire the DMA without granting a credit
        _hop(pltpu, buf_ref, cur, 1 - cur, send_sem, recv_sem,
             right).wait_send()


def _ring_reduce_kernel(idx_div_ref, idx_loc_ref, prod_ref, out_ref,
                        buf_ref, sbuf_ref, acc_vmem, blk_vmem, prod_vmem,
                        div_vmem, loc_vmem,
                        local_sems, send_sem, recv_sem, credit_sem, *,
                        ndev: int, axis: str, block: int, nnz_pad: int):
    """One device's whole reduce ring (≙ mpi_reduce_rows streamed).

    The partial destined for device d starts at device d+1 and travels
    RIGHT, each holder adding its local segment-sum for that block;
    after ndev-1 hops device d adds its own contribution and owns the
    fully reduced block.  Addition order around the ring differs from
    the fallback's psum (same math, different rounding order —
    docs/ring.md; the CPU fallback keeps psum semantics so tier-1
    parity stays bit-exact).

    Unlike the gather kernel — whose send source (slot ``cur``) and
    landing zone (slot ``1-cur``) are disjoint halves of ONE buffer —
    the reduce's outgoing partial is a fresh value each step, so it
    gets its own staging buffer ``sbuf``: every device stages into
    ``sbuf[cur]`` at step s while the left neighbor's RDMA lands in
    ``buf[(s+1) % 2]``; in-flight reads and incoming writes can never
    touch the same slot.  Both ``buf`` slots start free, so the left
    neighbor is granted min(2, ndev-1) credits up front and one more
    per folded (= freed) slot — grants == sends, every semaphore
    drains.

    prod: (nnz_pad, R) the Hadamard gather-product (zero-padded).
    out: (block, R) my reduced row-block, accumulator dtype.
    buf/sbuf (outs, discarded): (2, block, R) recv landing zone /
    send staging.
    """
    pltpu = _pltpu()
    s = pl.program_id(0)
    my = jax.lax.axis_index(axis)
    right = jax.lax.rem(my + 1, ndev)
    left = jax.lax.rem(my + ndev - 1, ndev)
    cur = jax.lax.rem(s, 2)
    nchunks = nnz_pad // _NNZ_CHUNK

    def hop_in():
        # the left neighbor's step-(s-1) send: its sbuf[1-cur] into my
        # buf[cur] (the descriptor is symmetric under SPMD, so the same
        # refs reconstruct both wait sides)
        return pltpu.make_async_remote_copy(
            src_ref=sbuf_ref.at[1 - cur], dst_ref=buf_ref.at[cur],
            send_sem=send_sem, recv_sem=recv_sem, device_id=(right,),
            device_id_type=pltpu.DeviceIdType.MESH)

    @pl.when(s == 0)
    def _open():
        _neighbor_barrier(pltpu, left, right)
        # both landing slots start free: grant their credits up front
        # (min(2, ndev-1): never more credits than sends)
        _grant_credit(pltpu, credit_sem, left)

        @pl.when(ndev > 2)
        def _second():
            _grant_credit(pltpu, credit_sem, left)

    @pl.when(s > 0)
    def _recv_wait():
        hop_in().wait_recv()

    # local partial for the block this step handles: j = (my - 1 - s)
    # mod ndev — the chunk that ends at its owner after the remaining
    # hops (standard ring reduce-scatter schedule)
    j = jax.lax.rem(my - 1 - s + 2 * ndev, ndev)

    def chunk_body(c, _):
        lo = c * _NNZ_CHUNK
        _stage(pltpu, idx_div_ref.at[pl.ds(lo, _NNZ_CHUNK)], div_vmem,
               local_sems.at[2])
        _stage(pltpu, idx_loc_ref.at[pl.ds(lo, _NNZ_CHUNK)], loc_vmem,
               local_sems.at[2])
        div = div_vmem[...]
        loc = loc_vmem[...]
        _stage(pltpu, prod_ref.at[pl.ds(lo, _NNZ_CHUNK)], prod_vmem,
               local_sems.at[1])
        mask = div == j
        safe = jnp.where(mask, loc, 0)
        iota = jax.lax.broadcasted_iota(jnp.int32, (block, _NNZ_CHUNK), 0)
        onehot = ((safe[None, :] == iota)
                  & mask[None, :]).astype(acc_vmem.dtype)
        part = jax.lax.dot_general(
            onehot, prod_vmem[...].astype(acc_vmem.dtype),
            dimension_numbers=(((1,), (0,)), ((), ())),
            preferred_element_type=acc_vmem.dtype)

        @pl.when(c == 0)
        def _init():
            acc_vmem[...] = part

        @pl.when(c != 0)
        def _acc():
            acc_vmem[...] += part

        return 0

    jax.lax.fori_loop(0, nchunks, chunk_body, 0)

    @pl.when(s > 0)
    def _fold():
        # fold in the travelling partial that just arrived
        _stage(pltpu, buf_ref.at[cur], blk_vmem, local_sems.at[2])
        acc_vmem[...] += blk_vmem[...]

    @pl.when(s < ndev - 1)
    def _send():
        # stage acc into MY send slot and stream it into the right
        # neighbor's landing slot; the DMA overlaps the NEXT step's
        # local partial computation.  sbuf[cur]'s previous send (step
        # s-2) was retired at step s-1, so re-staging is safe.
        pltpu.semaphore_wait(credit_sem, 1)
        _stage(pltpu, acc_vmem, sbuf_ref.at[cur], local_sems.at[0])
        pltpu.make_async_remote_copy(
            src_ref=sbuf_ref.at[cur], dst_ref=buf_ref.at[1 - cur],
            send_sem=send_sem, recv_sem=recv_sem, device_id=(right,),
            device_id_type=pltpu.DeviceIdType.MESH).start()

    @pl.when((s > 0) & (s <= ndev - 2))
    def _retire():
        # retire my step-(s-1) send (sbuf[1-cur] is re-staged at s+1)
        hop_in().wait_send()

    @pl.when((s >= 1) & (s <= ndev - 3))
    def _grant():
        # the fold consumed buf[cur]: hand it back to the left
        # neighbor as a landing zone (its send s+1 targets this slot).
        # Together with _open's up-front credits, grants == sends.
        _grant_credit(pltpu, credit_sem, left)

    @pl.when(s == ndev - 1)
    def _close():
        # my block is fully reduced: publish it and retire the FINAL
        # send (step ndev-2, sourced from sbuf[1-cur]) so the kernel
        # ends with every semaphore drained
        _stage(pltpu, acc_vmem, out_ref, local_sems.at[0])

        @pl.when(ndev > 1)
        def _():
            hop_in().wait_send()


def _pad_streams(idx: jax.Array, block: int):
    """(idx // block, idx % block) padded to whole _NNZ_CHUNKs with an
    owner id of -1 (matches no shard: padding rows contribute zero).
    The stream widens through the blocked format's stream-consumer
    boundary (blocked.widen_ids — the same interface the single-chip
    engines decode through), so a narrow encoded shard stream flows
    into the ring kernels unchanged."""
    from splatt_tpu.blocked import widen_ids
    from splatt_tpu.utils.env import ceil_to

    n = int(idx.shape[0])
    n_pad = max(_NNZ_CHUNK, ceil_to(n, _NNZ_CHUNK))
    padded = jnp.pad(widen_ids(idx), (0, n_pad - n))
    div = jnp.where(jnp.arange(n_pad) < n, padded // block, -1)
    return div.astype(jnp.int32), jnp.mod(padded, block), n_pad


def _ring_compiler_params(collective_id: int):
    from splatt_tpu.ops.pallas_kernels import _compiler_params

    params = _compiler_params()
    try:
        return type(params)(vmem_limit_bytes=params.vmem_limit_bytes,
                            collective_id=collective_id,
                            has_side_effects=True)
    except TypeError:
        # older jax CompilerParams without these fields: the barrier
        # semaphore falls back to its default id
        return params


def _gather_pallas(U_l: jax.Array, idx: jax.Array, axis: str,
                   ndev: int) -> jax.Array:
    """TPU path of :func:`async_ring_gather_rows`."""
    pltpu = _pltpu()
    block, R = int(U_l.shape[0]), int(U_l.shape[1])
    div, loc, nnz_pad = _pad_streams(idx, block)
    kernel = functools.partial(_ring_gather_kernel, ndev=ndev, axis=axis,
                               block=block, nnz_pad=nnz_pad)
    rows, _ = pl.pallas_call(
        kernel,
        grid=(ndev,),
        in_specs=[pl.BlockSpec(memory_space=pltpu.ANY)] * 3,
        out_specs=(pl.BlockSpec(memory_space=pltpu.ANY),
                   pl.BlockSpec(memory_space=pltpu.ANY)),
        out_shape=(jax.ShapeDtypeStruct((nnz_pad, R), U_l.dtype),
                   jax.ShapeDtypeStruct((2, block, R), U_l.dtype)),
        scratch_shapes=(
            pltpu.VMEM((block, R), U_l.dtype),
            pltpu.VMEM((_NNZ_CHUNK, R), U_l.dtype),
            pltpu.VMEM((_NNZ_CHUNK,), jnp.int32),
            pltpu.VMEM((_NNZ_CHUNK,), jnp.int32),
            pltpu.SemaphoreType.DMA((3,)),
            pltpu.SemaphoreType.DMA(()),
            pltpu.SemaphoreType.DMA(()),
            pltpu.SemaphoreType.REGULAR,
        ),
        compiler_params=_ring_compiler_params(7),
    )(div, loc, U_l)
    return rows[:int(idx.shape[0])]


def _reduce_pallas(prod: jax.Array, idx: jax.Array, axis: str, ndev: int,
                   block: int) -> jax.Array:
    """TPU path of :func:`async_blockwise_reduce_rows`."""
    pltpu = _pltpu()
    from splatt_tpu.ops.mttkrp import acc_dtype

    R = int(prod.shape[1])
    out_dtype = acc_dtype(prod.dtype)
    div, loc, nnz_pad = _pad_streams(idx, block)
    n = int(prod.shape[0])
    prod_pad = jnp.pad(prod, ((0, nnz_pad - n), (0, 0)))
    kernel = functools.partial(_ring_reduce_kernel, ndev=ndev, axis=axis,
                               block=block, nnz_pad=nnz_pad)
    out, _, _ = pl.pallas_call(
        kernel,
        grid=(ndev,),
        in_specs=[pl.BlockSpec(memory_space=pltpu.ANY)] * 3,
        out_specs=(pl.BlockSpec(memory_space=pltpu.ANY),
                   pl.BlockSpec(memory_space=pltpu.ANY),
                   pl.BlockSpec(memory_space=pltpu.ANY)),
        out_shape=(jax.ShapeDtypeStruct((block, R), out_dtype),
                   jax.ShapeDtypeStruct((2, block, R), out_dtype),
                   jax.ShapeDtypeStruct((2, block, R), out_dtype)),
        scratch_shapes=(
            pltpu.VMEM((block, R), out_dtype),
            pltpu.VMEM((block, R), out_dtype),
            pltpu.VMEM((_NNZ_CHUNK, R), prod.dtype),
            pltpu.VMEM((_NNZ_CHUNK,), jnp.int32),
            pltpu.VMEM((_NNZ_CHUNK,), jnp.int32),
            pltpu.SemaphoreType.DMA((3,)),
            pltpu.SemaphoreType.DMA(()),
            pltpu.SemaphoreType.DMA(()),
            pltpu.SemaphoreType.REGULAR,
        ),
        compiler_params=_ring_compiler_params(8),
    )(div, loc, prod_pad)
    return out


# -- public entry points (what make_sharded_sweep calls) --------------------


def async_ring_gather_rows(U_l: jax.Array, idx: jax.Array, axis: str,
                           ndev: int) -> jax.Array:
    """Rows of a row-sharded factor at global ids `idx` via the async
    remote-copy ring; ≡ :func:`splatt_tpu.parallel.ring.ring_gather_rows`
    mathematically (each id matches exactly one shard, so the
    cross-step accumulation only adds zeros — exact).

    The ``comm.ring_exchange`` fault site arms here (trace time — the
    sweep's first invocation), so chaos drills exercise the driver's
    comm-fallback ladder exactly where a real Mosaic/RDMA failure would
    surface.
    """
    faults.maybe_fail("comm.ring_exchange")
    if ndev >= 2 and async_ring_supported():
        return _gather_pallas(U_l, idx, axis, ndev)
    # interpret/CPU fallback: today's ppermute semantics, bit-for-bit
    # (docs/ring.md fallback ladder) — tier-1 exercises this dataflow
    from splatt_tpu.parallel.ring import ring_gather_rows

    return ring_gather_rows(U_l, idx, axis, ndev)


def async_blockwise_reduce_rows(prod: jax.Array, idx: jax.Array, axis: str,
                                ndev: int, block: int) -> jax.Array:
    """Row-sharded MTTKRP output via the async reduce ring.  On TPU the
    partial travels the ring accumulating in hop order (different
    rounding ORDER than psum, same math — docs/ring.md); off-TPU it
    delegates to the psum formulation so CPU parity stays bit-exact
    with the POINT2POINT path."""
    faults.maybe_fail("comm.ring_exchange")
    if ndev >= 2 and async_ring_supported():
        return _reduce_pallas(prod, idx, axis, ndev, block)
    from splatt_tpu.parallel.ring import blockwise_reduce_rows

    return blockwise_reduce_rows(prod, idx, axis, ndev, block)
