"""Coarse-grained decomposition: owner-computes over per-mode copies.

≙ the reference's COARSE decomposition (types_config.h:179-190,
src/cmds/mpi_cmd_cpd.c:223-258): each rank owns a contiguous block of
*every* mode's slices and keeps one filtered tensor copy per mode
(hence the ALLMODE CSF requirement).  Updating mode m needs **no
output reduction at all** — a rank holds every nonzero that touches its
rows of mode m — at the price of replicating the nonzeros nmodes times
and gathering the input factors.

TPU mapping over a 1-D mesh axis ``d``:
  - per mode m, nonzeros are sorted by mode m and bucketed by the
    equal row fences of axis d (pad cells to the max bucket);
  - factor m is row-sharded over d;
  - update m: ``all_gather`` the other factors (≙ mpi_update_rows),
    local gather-prod + segment-sum into the owned block, local solve,
    λ/Gram ``psum`` — and no reduce_rows anywhere.
"""

from __future__ import annotations

from functools import partial
from typing import List, Optional

import jax
import jax.numpy as jnp
from splatt_tpu.utils.env import shard_map
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from splatt_tpu.config import Options, default_opts, resolve_dtype
from splatt_tpu.coo import SparseTensor
from splatt_tpu.cpd import init_factors
from splatt_tpu.kruskal import KruskalTensor
from splatt_tpu.ops.mttkrp import acc_dtype
from splatt_tpu.parallel.common import (blocked_local_mttkrp, bucket_engine,
                                        bucket_scatter, fit_tail,
                                        mode_update_tail,
                                        run_distributed_als)
from splatt_tpu.parallel.mesh import make_mesh, single_axis_of
from splatt_tpu.utils.env import ceil_to


def _bucket_by_mode(tt: SparseTensor, mode: int, ndev: int, val_dtype,
                    streamed: Optional[bool] = None,
                    out_dir: Optional[str] = None,
                    chunk: int = 1 << 22):
    """Bucket nonzeros by the equal row fences of `mode`.

    Returns (inds (nmodes, ndev, C) int32 with mode-m indices local to
    the fence, vals (ndev, C), block_rows, counts).

    `streamed` (auto: when tt holds memmapped indices) runs the
    bucketing in chunked passes — host RSS O(chunk + bucket metadata)
    — with optionally disk-backed outputs under `out_dir`, so a
    beyond-RAM tensor builds its per-mode copies end-to-end.
    """
    from splatt_tpu.parallel.common import (is_memmapped,
                                            streamed_bucket_scatter)

    dim_pad = ceil_to(max(tt.dims[mode], ndev), ndev)
    block = dim_pad // ndev
    if streamed is None:
        streamed = is_memmapped(tt.inds)
    if streamed:
        def postprocess(placed):
            placed[mode] %= block
            return placed

        binds, bvals, _, counts = streamed_bucket_scatter(
            tt.inds, tt.vals, lambda ic, s: ic[mode] // block, ndev,
            val_dtype, chunk=chunk, out_dir=out_dir,
            postprocess=postprocess)
        return binds, bvals, block, counts
    owner = tt.inds[mode] // block
    binds, bvals, _, counts = bucket_scatter(tt.inds, tt.vals, owner, ndev,
                                             val_dtype)
    binds[mode] %= block  # localize to the fence (pad slots stay 0)
    return binds, bvals, block, counts


def coarse_cpd_als(tt: SparseTensor, rank: int, mesh: Optional[Mesh] = None,
                   opts: Optional[Options] = None,
                   init: Optional[List[jax.Array]] = None,
                   axis: str = "d",
                   local_engine: Optional[str] = None,
                   out_dir: Optional[str] = None,
                   row_distribute: Optional[str] = None,
                   checkpoint_path: Optional[str] = None,
                   checkpoint_every: int = 10,
                   resume: bool = True) -> KruskalTensor:
    """Distributed CPD-ALS, coarse-grained owner-computes.

    `row_distribute="balanced"` (docs/layout-balance.md): nnz-weighted
    row relabeling per mode (chains-on-chains style — the
    capacity-constrained LPT pack of balanced_relabel) before the
    equal fences are cut, so a hot slice no longer fattens one rank's
    bucket — every per-mode cell is padded to the FULLEST bucket, so
    bucket imbalance is wasted compute on every device.  Original row
    order is restored on gather (run_distributed_als row_select).

    `local_engine`: "blocked" (the default) sorts each per-mode bucket
    and runs the single-chip blocked MTTKRP engine inside the sweep
    (≙ mttkrp_csf over each rank's per-mode tensor copy); "stream"
    keeps the naive formulation (the differential oracle).  Memmapped
    (out-of-core) tensors keep the blocked engine: the buckets build
    via streamed chunked passes and the sorted layouts via the chunked
    counting sort (streamed_blocked_buckets) — both disk-backed under
    `out_dir` when given, so host RSS stays bounded at any scale
    (≙ every rank running the optimized mttkrp_csf regardless of
    tensor size, src/mpi/mpi_cpd.c:714).
    """
    import os

    opts = (opts or default_opts()).validate()
    mesh, axis = single_axis_of(mesh, axis)
    mesh = mesh or make_mesh(axis_names=(axis,))
    ndev = mesh.shape[axis]
    nmodes = tt.nmodes
    xnormsq = tt.normsq()
    dtype = resolve_dtype(opts, tt.vals.dtype)
    if local_engine is None:
        from splatt_tpu.parallel.common import auto_local_engine

        local_engine = auto_local_engine(tt, out_dir)
    if local_engine not in ("blocked", "stream"):
        raise ValueError(f"unknown local_engine {local_engine!r}")
    blocked = local_engine == "blocked"

    orig_dims = tt.dims
    relabels = None
    if row_distribute == "balanced":
        # nnz-weighted per-mode relabeling (docs/layout-balance.md):
        # rows LPT-packed into the equal fences by slice weight, so
        # every rank's bucket — and with it the pad-to-fullest cell —
        # balances.  All modes relabel at once: mode k's indices feed
        # the gathered factor-k lookups inside every other mode's
        # update, so the labeling must be globally consistent.
        from splatt_tpu.parallel.common import (balanced_relabel,
                                                relabel_tensor)

        relabels = []
        for m in range(nmodes):
            dim_pad = ceil_to(max(tt.dims[m], ndev), ndev)
            relabels.append(
                balanced_relabel(tt.mode_histogram(m), ndev,
                                 dim_pad // ndev)
                if ndev > 1 else None)
        tt = relabel_tensor(
            tt, relabels, tuple(ceil_to(max(d, ndev), ndev)
                                for d in tt.dims))
    elif row_distribute is not None:
        raise ValueError(f"unknown row_distribute {row_distribute!r} "
                         f"(coarse supports 'balanced')")

    # one sorted+bucketed copy per mode (≙ per-mode tensors + ALLMODE);
    # per-mode out_dir subdirs: the memmap file names inside are fixed
    per_mode = [_bucket_by_mode(
        tt, m, ndev, dtype,
        out_dir=(os.path.join(out_dir, f"mode{m}")
                 if out_dir is not None else None))
        for m in range(nmodes)]
    blocks = tuple(b for (_, _, b, _) in per_mode)
    dims_pad = tuple(b * ndev for b in blocks)
    # achieved bucket balance per mode (pad-to-fullest makes max/mean
    # exactly the wasted-compute factor): recorded for --json /
    # MULTICHIP (docs/layout-balance.md)
    from splatt_tpu.parallel.common import record_shard_imbalance

    for m, (_, _, _, counts) in enumerate(per_mode):
        record_shard_imbalance("coarse_bucket", counts,
                               policy=row_distribute or "equal", mode=m)
    nnz_sharding = NamedSharding(mesh, P(None, axis, None))
    val_sharding = NamedSharding(mesh, P(axis, None))
    if blocked:
        from splatt_tpu.parallel.common import build_bucket_layout

        cells = []
        inds_dev = []
        vals_dev = []
        rs_dev = []
        for m, (bi, bv, blk_rows, counts) in enumerate(per_mode):
            i, v, rs, blkk, S = build_bucket_layout(
                bi, bv, counts, m, blk_rows, opts.nnz_block,
                out_dir=(os.path.join(out_dir, f"mode{m}", "blocked")
                         if out_dir is not None else None))
            path, impl = bucket_engine(S, opts)
            cells.append(dict(block=blkk, seg_width=S, path=path,
                              impl=impl))
            inds_dev.append(jax.device_put(i, nnz_sharding))
            vals_dev.append(jax.device_put(v, val_sharding))
            rs_dev.append(jax.device_put(rs, val_sharding))
    else:
        cells = None
        inds_dev = [jax.device_put(i, nnz_sharding)
                    for (i, _, _, _) in per_mode]
        vals_dev = [jax.device_put(v, val_sharding)
                    for (_, v, _, _) in per_mode]
        rs_dev = []

    # init in the ORIGINAL row space (rank-count/distribution
    # invariance); relabels only affect placement
    factors_host = (init if init is not None
                    else init_factors(orig_dims, rank, opts.seed(),
                                      dtype=dtype))
    factors = []
    for m, U in enumerate(factors_host):
        U_pad = jnp.zeros((dims_pad[m], U.shape[1]), dtype=dtype)
        U = jnp.asarray(U, dtype=dtype)[:orig_dims[m]]
        if relabels is not None and relabels[m] is not None:
            U_pad = U_pad.at[jnp.asarray(relabels[m])].set(U)
        else:
            U_pad = U_pad.at[:orig_dims[m]].set(U)
        factors.append(jax.device_put(
            U_pad, NamedSharding(mesh, P(axis, None))))
    factors = tuple(factors)
    from splatt_tpu.ops.linalg import gram

    grams = tuple(jax.device_put(gram(U), NamedSharding(mesh, P()))
                  for U in factors)

    factor_specs = tuple([P(axis, None)] * nmodes)
    gram_specs = tuple([P()] * nmodes)
    inds_specs = tuple([P(None, axis, None)] * nmodes)
    vals_specs = tuple([P(axis, None)] * nmodes)
    rs_specs = (tuple([P(axis, None)] * nmodes) if blocked else ())
    reg = opts.regularization

    @partial(shard_map, mesh=mesh,
             in_specs=(inds_specs, vals_specs, rs_specs, factor_specs,
                       gram_specs, P()),
             out_specs=(factor_specs, gram_specs, P(), P(), P()),
             check_vma=False)
    def sweep(inds_l, vals_l, rs_l, factors_l, grams_l, first_flag):
        factors_l = list(factors_l)
        grams_l = list(grams_l)
        lam = None
        M_l = None
        for m in range(nmodes):
            ic = inds_l[m].reshape(nmodes, -1)
            vc = vals_l[m].reshape(-1)
            if blocked:
                # ≙ mpi_update_rows, then the rank-local optimized
                # MTTKRP over this mode's sorted copy — owner-computes:
                # NO output reduction
                fac_full = [
                    jax.lax.all_gather(factors_l[k], axis, axis=0,
                                       tiled=True) if k != m
                    else factors_l[m]  # local fence IS the row space
                    for k in range(nmodes)]
                M_l = blocked_local_mttkrp(
                    ic, vc, rs_l[m].reshape(-1), fac_full, m,
                    dim=blocks[m], block=cells[m]["block"],
                    seg_width=cells[m]["seg_width"],
                    path=cells[m]["path"], impl=cells[m]["impl"])
            else:
                prod = vc[:, None].astype(factors_l[0].dtype)
                for k in range(nmodes):
                    if k != m:
                        # ≙ mpi_update_rows: fetch the other factors
                        U = jax.lax.all_gather(factors_l[k], axis, axis=0,
                                               tiled=True)
                        prod = prod * jnp.take(U, ic[k], axis=0,
                                               mode="clip")
                # owner-computes: all nonzeros for my rows are local,
                # so the MTTKRP block needs NO reduction
                M_l = jax.ops.segment_sum(
                    prod.astype(acc_dtype(prod.dtype)), ic[m],
                    num_segments=blocks[m])
            U_l, gram, lam = mode_update_tail(M_l, grams_l, m, reg,
                                              first_flag, axis,
                                              store_dtype=dtype)
            factors_l[m] = U_l
            grams_l[m] = gram
        znormsq, inner = fit_tail(lam, grams_l, M_l, factors_l[nmodes - 1],
                                  axis)
        return tuple(factors_l), tuple(grams_l), lam, znormsq, inner

    sweep = jax.jit(sweep)

    def step(factors, grams, flag):
        return sweep(tuple(inds_dev), tuple(vals_dev), tuple(rs_dev),
                     factors, grams, flag)

    return run_distributed_als(step, factors, grams, rank, opts, xnormsq,
                               orig_dims, dtype, row_select=relabels,
                               checkpoint_path=checkpoint_path,
                               checkpoint_every=checkpoint_every,
                               resume=resume)
