"""SPL026 bad: a kernel whose static block-buffer sum blows the
declared VMEM envelope (a streamed 4096x8192 f32 block is 256 MiB
double-buffered), issued with no dispatch gate registered."""

import jax
from jax.experimental import pallas as pl


def _copy_kernel(x_ref, o_ref):
    o_ref[...] = x_ref[...]


def oversized_entry(x):
    return pl.pallas_call(
        _copy_kernel,
        grid=(4,),
        in_specs=[pl.BlockSpec((4096, 8192), lambda i: (i, 0))],
        out_specs=pl.BlockSpec((4096, 8192), lambda i: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((16384, 8192), x.dtype),
    )(x)
