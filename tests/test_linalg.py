"""Dense linalg tests (≙ tests/matrix_test.c)."""

import jax.numpy as jnp
import numpy as np
import pytest

from splatt_tpu.ops.linalg import (form_normal_lhs, gram, normalize_columns,
                                   solve_normals)


def _rand(shape, seed=0):
    return jnp.asarray(np.random.default_rng(seed).random(shape))


def test_gram():
    U = _rand((20, 6))
    np.testing.assert_allclose(np.asarray(gram(U)),
                               np.asarray(U).T @ np.asarray(U), atol=1e-12)


def test_form_normal_lhs():
    grams = [gram(_rand((10, 4), seed=s)) for s in range(3)]
    lhs = form_normal_lhs(grams, mode=1, regularization=0.5)
    want = np.asarray(grams[0]) * np.asarray(grams[2]) + 0.5 * np.eye(4)
    np.testing.assert_allclose(np.asarray(lhs), want, atol=1e-12)


def test_solve_normals_spd():
    rng = np.random.default_rng(3)
    A = rng.random((5, 5))
    lhs = jnp.asarray(A @ A.T + 5 * np.eye(5))  # SPD
    rhs = _rand((12, 5), seed=4)
    X = solve_normals(lhs, rhs)
    # X · lhs = rhs
    np.testing.assert_allclose(np.asarray(X @ lhs), np.asarray(rhs), atol=1e-8)


def test_solve_normals_singular_fallback():
    """Rank-deficient lhs exercises the pseudoinverse path
    (≙ the gelss fallback, src/matrix.c:554-603)."""
    v = np.array([1.0, 2.0, 3.0])
    lhs = jnp.asarray(np.outer(v, v))  # rank 1, not SPD
    rhs = _rand((4, 3), seed=5)
    X = solve_normals(lhs, rhs)
    assert np.all(np.isfinite(np.asarray(X)))
    # least-squares optimality: residual orthogonal to range(lhs)
    resid = np.asarray(X @ lhs) - np.asarray(rhs)
    np.testing.assert_allclose(resid @ np.asarray(lhs).T, 0.0, atol=1e-8)


def test_normalize_2norm():
    U = _rand((30, 5), seed=6)
    out, lam = normalize_columns(U, "2")
    np.testing.assert_allclose(np.asarray(jnp.linalg.norm(out, axis=0)),
                               1.0, atol=1e-12)
    np.testing.assert_allclose(np.asarray(out * lam), np.asarray(U), atol=1e-12)


def test_normalize_maxnorm_floor():
    """Max-norm is the *signed* max clamped at 1 (≙ p_mat_maxnorm,
    src/matrix.c:164-194 — SS_MAX over raw vals, no fabs)."""
    U = jnp.asarray(np.array([[0.5, 3.0], [0.25, -6.0]]))
    out, lam = normalize_columns(U, "max")
    np.testing.assert_allclose(np.asarray(lam), [1.0, 3.0])
    np.testing.assert_allclose(np.asarray(out),
                               [[0.5, 1.0], [0.25, -2.0]])
    # all-negative column: signed max < 1 -> λ clamps to 1, no scaling
    V = jnp.asarray(np.array([[-2.0], [-3.0]]))
    outv, lamv = normalize_columns(V, "max")
    np.testing.assert_allclose(np.asarray(lamv), [1.0])
    np.testing.assert_allclose(np.asarray(outv), np.asarray(V))


def test_normalize_zero_column_safe():
    U = jnp.asarray(np.array([[0.0, 1.0], [0.0, 1.0]]))
    out, lam = normalize_columns(U, "2")
    assert np.all(np.isfinite(np.asarray(out)))
    assert float(lam[0]) == 0.0
