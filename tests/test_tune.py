"""splatt-tune: the empirical autotuner (splatt_tpu/tune.py).

Contract under test (docs/autotune.md): plan-cache lifecycle
(write / hit / TTL-expire / source-hash-invalidate / corrupt-file
degrades to re-tune), candidate pruning (demoted engines are never
candidates, deterministic failures persist as negative entries,
transient failures retry in place), dispatch integration (a cached
plan heads the engine chain; an inapplicable or missing plan keeps
the heuristics), the donated-sweep fast path (cpd_als fit identical
with donation on and off), and the fault drill — a crashing
measurement degrades dispatch to the heuristic chain, never fails
the run.
"""

import dataclasses
import json

import jax.numpy as jnp
import numpy as np
import pytest

import splatt_tpu.ops.pallas_kernels as pk
import splatt_tpu.tune as tune
from splatt_tpu import resilience
from splatt_tpu.blocked import BlockedSparse, build_layout
from splatt_tpu.config import BlockAlloc, Options, Verbosity
from splatt_tpu.coo import SparseTensor
from splatt_tpu.cpd import cpd_als, init_factors
from splatt_tpu.ops.mttkrp import engine_plan, mttkrp_blocked, mttkrp_stream
from splatt_tpu.utils import faults
from tests import gen

RANK = 4


@pytest.fixture(autouse=True)
def _isolated(tmp_path, monkeypatch):
    """Every test gets its own plan cache, a clean demotion registry,
    a clean run report, and instant transient backoff."""
    monkeypatch.setenv(tune._CACHE_ENV, str(tmp_path / "tune_cache.json"))
    monkeypatch.setattr(resilience.time, "sleep", lambda s: None)
    tune.reset_memo()
    resilience.reset_demotions()
    resilience.run_report().clear()
    yield
    tune.reset_memo()
    resilience.reset_demotions()
    resilience.run_report().clear()
    faults.reset()


def _tensor():
    return gen.fixture_tensor("med")


def _opts(**kw):
    kw.setdefault("random_seed", 42)
    kw.setdefault("verbosity", Verbosity.NONE)
    kw.setdefault("val_dtype", np.float64)
    kw.setdefault("use_pallas", False)
    return Options(**kw)


def _cache_file():
    import pathlib

    return pathlib.Path(str(tune.cache_path()))


# -- plan-cache lifecycle ---------------------------------------------------

def test_tune_writes_plan_and_warm_cache_skips_measurement():
    """The acceptance contract: a second run with a warm plan cache
    runs ZERO measurements."""
    tt = _tensor()
    res = tune.tune(tt, RANK, opts=_opts(), reps=1)
    assert res.plans and set(res.plans) == set(range(tt.nmodes))
    assert res.measured > 0 and res.cache_hits == 0
    assert _cache_file().exists()
    res2 = tune.tune(tt, RANK, opts=_opts(), reps=1)
    assert res2.measured == 0, "warm cache must skip all measurement"
    assert res2.cache_hits == tt.nmodes
    assert res2.plans == res.plans


def test_plan_survives_process_restart():
    """A fresh process (simulated: memo reset) reloads the plan from
    disk — that is what makes `splatt tune` pre-tuning pay off."""
    tt = _tensor()
    res = tune.tune(tt, RANK, opts=_opts(), reps=1)
    tune.reset_memo()
    plan = tune.cached_plan(tt.dims, tt.nnz, 0, RANK, jnp.float64,
                            skew=tune.skew_of(tt, 0))
    assert plan == res.plans[0]


def test_ttl_expiry_retunes(monkeypatch):
    """Even a proven plan expires after the (probe-cache) TTL: the
    winning configuration drifts with the infrastructure."""
    tt = _tensor()
    tune.tune(tt, RANK, opts=_opts(), reps=1)
    data = json.loads(_cache_file().read_text())
    for env in data["envs"].values():
        for entry in env.values():
            entry["ts"] = 1.0  # the distant past
    _cache_file().write_text(json.dumps(data))
    tune.reset_memo()
    assert tune.cached_plan(tt.dims, tt.nnz, 0, RANK, jnp.float64,
                            skew=tune.skew_of(tt, 0)) is None
    res = tune.tune(tt, RANK, opts=_opts(), reps=1)
    assert res.measured > 0, "expired plans must be re-earned"


def test_kernel_source_hash_invalidates_plans(monkeypatch):
    """The plan cache shares the probe cache's environment key: editing
    a kernel source must invalidate every cached plan."""
    tt = _tensor()
    tune.tune(tt, RANK, opts=_opts(), reps=1)
    tune.reset_memo()
    monkeypatch.setattr(pk, "_kernel_src_hash", lambda: "edited123456")
    assert tune.cached_plan(tt.dims, tt.nnz, 0, RANK, jnp.float64,
                            skew=tune.skew_of(tt, 0)) is None


def test_corrupt_cache_degrades_to_retune():
    """A corrupt plan-cache file is an unusable cache, not a failed
    dispatch: reported through the taxonomy, then re-tuned."""
    tt = _tensor()
    _cache_file().parent.mkdir(parents=True, exist_ok=True)
    _cache_file().write_text("{ not json")
    assert tune.cached_plan(tt.dims, tt.nnz, 0, RANK, jnp.float64,
                            skew=tune.skew_of(tt, 0)) is None
    assert resilience.run_report().events("tune_cache_io_error")
    res = tune.tune(tt, RANK, opts=_opts(), reps=1)
    assert res.plans and res.measured > 0
    # the re-tune replaced the corrupt file with a valid one
    tune.reset_memo()
    assert tune.cached_plan(tt.dims, tt.nnz, 0, RANK, jnp.float64,
                            skew=tune.skew_of(tt, 0)) is not None


def test_foreign_cache_version_is_retuned():
    """A cache written by a different tuner generation is re-tuned,
    never reinterpreted."""
    tt = _tensor()
    tune.tune(tt, RANK, opts=_opts(), reps=1)
    data = json.loads(_cache_file().read_text())
    data["version"] = tune.PLAN_CACHE_VERSION + 1
    _cache_file().write_text(json.dumps(data))
    tune.reset_memo()
    assert tune.cached_plan(tt.dims, tt.nnz, 0, RANK, jnp.float64,
                            skew=tune.skew_of(tt, 0)) is None


def test_plan_key_is_shape_regime_scoped():
    """Two tensors in the same power-of-two shape regime share plans;
    a different rank or dtype never does."""
    tt = _tensor()
    key = tune.plan_key(tt.dims, tt.nnz, 0, RANK, jnp.float64)
    # same power-of-two buckets (dims scaled < 2x, same nnz bucket)
    assert key == tune.plan_key(tt.dims, tt.nnz - 1, 0, RANK, jnp.float64)
    assert key != tune.plan_key([d * 4 for d in tt.dims], tt.nnz, 0,
                                RANK, jnp.float64)
    assert key != tune.plan_key(tt.dims, tt.nnz, 0, RANK + 1, jnp.float64)
    assert key != tune.plan_key(tt.dims, tt.nnz, 0, RANK, jnp.float32)
    assert key != tune.plan_key(tt.dims, tt.nnz, 1, RANK, jnp.float64)


# -- candidate handling -----------------------------------------------------

def test_demoted_engine_is_never_a_candidate(monkeypatch):
    tt = _tensor()
    resilience.demote_engine("xla_scan", RuntimeError("Mosaic crash"))
    measured = []

    def spy(layout, factors, mode, path, impl, engine, st, **kw):
        measured.append(engine)
        return 0.001

    monkeypatch.setattr(tune, "_measure_candidate", spy)
    # use_pallas forces the one-hot path so xla_scan WOULD be a
    # candidate if it were live
    res = tune.tune(tt, RANK, opts=_opts(use_pallas=True), reps=1)
    assert measured and "xla_scan" not in measured
    assert all(p.engine != "xla_scan" for p in res.plans.values())


def test_deterministic_failure_becomes_negative_entry(monkeypatch):
    """A Mosaic-class measurement failure persists as a negative entry:
    a later tune skips the candidate instead of re-paying the compile."""
    tt = _tensor()
    attempts = []

    def failing(layout, factors, mode, path, impl, engine, st, **kw):
        attempts.append(engine)
        if engine == "xla_scan":
            raise RuntimeError("Mosaic failed to compile the kernel")
        return 0.001

    monkeypatch.setattr(tune, "_measure_candidate", failing)
    res = tune.tune(tt, RANK, opts=_opts(use_pallas=True), reps=1)
    assert all(p.engine == "xla" for p in res.plans.values())
    assert resilience.run_report().events("tuner_negative")
    assert "neg:" in _cache_file().read_text()
    # a forced re-tune skips the negative candidates entirely
    first_scan_attempts = attempts.count("xla_scan")
    assert first_scan_attempts > 0
    res2 = tune.tune(tt, RANK, opts=_opts(use_pallas=True), reps=1,
                     force=True)
    assert attempts.count("xla_scan") == first_scan_attempts
    assert res2.skipped > 0


def test_transient_failure_is_retried_in_place(monkeypatch):
    """An HTTP-500-class timing failure retries with backoff inside
    the tuner (resilience.retry_transient) and is never persisted."""
    tt = _tensor()
    calls = {"n": 0}

    def flaky(layout, factors, mode, path, impl, engine, st, **kw):
        calls["n"] += 1
        if calls["n"] < 3:
            raise RuntimeError("XLA compile: HTTP code 500 from relay")
        return 0.001

    monkeypatch.setattr(tune, "_measure_candidate", flaky)
    res = tune.tune(tt, RANK, opts=_opts(), modes=[0], reps=1)
    assert 0 in res.plans
    assert calls["n"] >= 3
    assert "neg:" not in _cache_file().read_text()


def test_all_candidates_failing_degrades_to_heuristics(monkeypatch):
    tt = _tensor()
    monkeypatch.setattr(
        tune, "_measure_candidate",
        lambda *a, **k: (_ for _ in ()).throw(RuntimeError("boom")))
    res = tune.tune(tt, RANK, opts=_opts(), reps=1)
    assert not res.plans
    assert resilience.run_report().events("tuner_degraded")
    # and dispatch still works — the heuristic chain is intact
    bs = BlockedSparse.compile(tt, _opts(nnz_block=256), rank=RANK)
    out = cpd_als(bs, RANK, opts=_opts(max_iterations=3, nnz_block=256))
    assert np.isfinite(float(out.fit))


def test_fault_drill_env_armed_tuner_crash(monkeypatch):
    """The SPLATT_FAULTS=tuner.measure:* drill: every measurement
    crashes, tuning yields no plan, and the run degrades to the
    heuristic chain instead of failing."""
    monkeypatch.setenv("SPLATT_FAULTS", "tuner.measure:runtime:*")
    faults.reset()  # re-read the env spec
    tt = _tensor()
    res = tune.tune(tt, RANK, opts=_opts(), reps=1)
    assert not res.plans
    assert resilience.run_report().events("tuner_degraded")
    out = cpd_als(BlockedSparse.compile(tt, _opts(nnz_block=256),
                                        rank=RANK),
                  RANK, opts=_opts(max_iterations=3, nnz_block=256))
    assert np.isfinite(float(out.fit))


# -- dispatch integration ---------------------------------------------------

def _store_plan(tt, mode, rank, dtype, **plan):
    plan.setdefault("sec", 0.001)
    tune._entry_store(tune.plan_key(tt.dims, tt.nnz, mode, rank, dtype,
                                    skew=tune.skew_of(tt, mode)),
                      {"plan": plan})


def test_cached_plan_heads_the_engine_chain():
    """A cached winner is dispatched FIRST — engine_plan reports it and
    mttkrp_blocked attempts it — while the heuristic head differs."""
    tt = _tensor()
    lay = build_layout(tt, 0, block=1024, val_dtype=np.float64)
    facs = init_factors(tt.dims, RANK, 0, dtype=jnp.float64)
    assert engine_plan(lay, facs, 0, "sorted_onehot", "xla",
                       autotune=False) == "xla_scan"
    _store_plan(tt, 0, RANK, jnp.float64, path="sorted_onehot",
                engine="xla", nnz_block=lay.block, scan_target=1 << 21)
    assert engine_plan(lay, facs, 0, "sorted_onehot", "xla",
                       autotune=True) == "xla"
    out = mttkrp_blocked(lay, facs, 0, path="sorted_onehot", impl="xla",
                         autotune=True)
    assert resilience.last_engine_attempt()[0] == "xla"
    # and the tuned engine computes the same numbers as the oracle
    ref = mttkrp_stream(jnp.asarray(tt.inds), jnp.asarray(tt.vals),
                        facs, 0, tt.dims[0])
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-10, atol=1e-10)


def test_inapplicable_plan_keeps_heuristics():
    """A plan whose block or path disagrees with this dispatch is NOT
    applied — the tuner can never make dispatch wronger."""
    tt = _tensor()
    lay = build_layout(tt, 0, block=1024, val_dtype=np.float64)
    facs = init_factors(tt.dims, RANK, 0, dtype=jnp.float64)
    _store_plan(tt, 0, RANK, jnp.float64, path="sorted_onehot",
                engine="xla", nnz_block=lay.block + 128,  # block mismatch
                scan_target=1 << 21)
    assert engine_plan(lay, facs, 0, "sorted_onehot", "xla",
                       autotune=True) == "xla_scan"


def test_demoted_plan_engine_keeps_heuristics():
    tt = _tensor()
    lay = build_layout(tt, 0, block=1024, val_dtype=np.float64)
    facs = init_factors(tt.dims, RANK, 0, dtype=jnp.float64)
    _store_plan(tt, 0, RANK, jnp.float64, path="sorted_onehot",
                engine="xla", nnz_block=lay.block, scan_target=1 << 21)
    resilience.demote_engine("xla", RuntimeError("Mosaic crash"))
    # the demoted engine is still the chain's terminal fallback, but a
    # stale plan must not PROMOTE it over the live heuristic head
    assert engine_plan(lay, facs, 0, "sorted_onehot", "xla",
                       autotune=True) == "xla_scan"


def test_shape_scoped_demotion_blocks_plan_in_reporting():
    """A per-shape (OOM) demotion must also stop the plan in
    engine_plan's reporting path, which has no caller shape_key —
    otherwise benches would label results with an engine dispatch
    refuses to run."""
    import importlib

    mk = importlib.import_module("splatt_tpu.ops.mttkrp")

    tt = _tensor()
    lay = build_layout(tt, 0, block=1024, val_dtype=np.float64)
    facs = init_factors(tt.dims, RANK, 0, dtype=jnp.float64)
    _store_plan(tt, 0, RANK, jnp.float64, path="sorted_onehot",
                engine="xla", nnz_block=lay.block, scan_target=1 << 21)
    shape_key = mk._engine_shape_key(lay, facs, 0)
    resilience.demote_engine(
        "xla", RuntimeError("RESOURCE_EXHAUSTED: out of memory"),
        shape_key=shape_key)
    assert engine_plan(lay, facs, 0, "sorted_onehot", "xla",
                       autotune=True) == "xla_scan"


def test_autotune_off_ignores_plans():
    tt = _tensor()
    lay = build_layout(tt, 0, block=1024, val_dtype=np.float64)
    facs = init_factors(tt.dims, RANK, 0, dtype=jnp.float64)
    _store_plan(tt, 0, RANK, jnp.float64, path="sorted_onehot",
                engine="xla", nnz_block=lay.block, scan_target=1 << 21)
    assert engine_plan(lay, facs, 0, "sorted_onehot", "xla",
                       autotune=False) == "xla_scan"


def test_autotune_env_kill_switch(monkeypatch):
    monkeypatch.setenv("SPLATT_AUTOTUNE", "0")
    assert tune.autotune_enabled(None) is False
    assert tune.autotune_enabled(True) is True  # explicit opt wins
    monkeypatch.setenv("SPLATT_AUTOTUNE", "1")
    assert tune.autotune_enabled(None) is True
    assert tune.autotune_enabled(False) is False


def test_compile_builds_layouts_at_tuned_blocks():
    """BlockedSparse.compile consults the plan cache so the layout is
    built ONCE at the winning block instead of rebuilt later."""
    tt = _tensor()
    # sorted_scatter is the heuristic path for these layouts on CPU, so
    # the stored plans stay applicable (and reportable) at dispatch
    for m in range(tt.nmodes):
        _store_plan(tt, m, RANK, jnp.float64, path="sorted_scatter",
                    engine="xla", nnz_block=512, scan_target=1 << 23)
    opts = _opts(block_alloc=BlockAlloc.ALLMODE)  # default block 4096
    bs = BlockedSparse.compile(tt, opts, rank=RANK)
    assert all(lay.block == 512 for lay in bs.layouts)
    # without a rank (or with autotune off) compile is plain from_coo
    bs_plain = BlockedSparse.compile(tt, opts)
    assert all(lay.block != 512 for lay in bs_plain.layouts)
    out = cpd_als(bs, RANK, opts=_opts(max_iterations=3))
    assert np.isfinite(float(out.fit))
    # cpd_als surfaced the consulted plan in the run report
    assert resilience.run_report().events("tuned_plan")


def test_tuned_cpd_matches_untuned_fit():
    """End to end: a tuned run computes the same factorization as the
    static-default run (the plan changes speed, never math)."""
    tt = _tensor()
    tune.tune(tt, RANK, opts=_opts(), reps=1)
    init = init_factors(tt.dims, RANK, 7, dtype=jnp.float64)
    a = cpd_als(BlockedSparse.from_coo(tt, _opts(autotune=False)), RANK,
                opts=_opts(autotune=False, max_iterations=5), init=init)
    b = cpd_als(BlockedSparse.compile(tt, _opts(autotune=True), rank=RANK),
                RANK, opts=_opts(autotune=True, max_iterations=5),
                init=init)
    assert float(a.fit) == pytest.approx(float(b.fit), abs=1e-9)


# -- donated sweeps ---------------------------------------------------------

def test_cpd_fit_identical_with_donation_on_and_off():
    """The donated whole-sweep fast path is a pure buffer-aliasing
    optimization: bit-identical results, donation on or off."""
    tt = _tensor()
    init = init_factors(tt.dims, 3, 11, dtype=jnp.float64)
    outs = {}
    for donate in (False, True):
        opts = _opts(max_iterations=6, nnz_block=256,
                     block_alloc=BlockAlloc.ALLMODE, donate_sweep=donate)
        outs[donate] = cpd_als(BlockedSparse.from_coo(tt, opts), 3,
                               opts=opts, init=init)
    assert float(outs[False].fit) == float(outs[True].fit)
    for ua, ub in zip(outs[False].factors, outs[True].factors):
        np.testing.assert_array_equal(np.asarray(ua), np.asarray(ub))
    # the caller's init arrays survive the donated run
    assert not any(u.is_deleted() for u in init)


def test_donated_sweep_consumes_inputs():
    """The donated fused sweep really donates: its inputs are consumed
    (so the no-copy aliasing is actually in effect, not silently off)."""
    from splatt_tpu.cpd import _make_sweep
    from splatt_tpu.ops.linalg import gram

    tt = _tensor()
    bs = BlockedSparse.from_coo(tt, _opts(nnz_block=256, autotune=False,
                                          block_alloc=BlockAlloc.ALLMODE))
    factors = init_factors(tt.dims, 3, 3, dtype=jnp.float64)
    grams = [gram(U) for U in factors]
    sweep = _make_sweep(bs, tt.nmodes, 0.0, donate=True)
    f2, g2, *_ = sweep(factors, grams, True)
    # mode 0's INPUT factor/gram are dead values in the sweep dataflow
    # (the update replaces them before any read), so jit prunes rather
    # than donates them; every live input is consumed
    assert all(u.is_deleted() for u in factors[1:])
    assert all(g.is_deleted() for g in grams[1:])
    assert all(not u.is_deleted() for u in f2)


def test_rescue_rematerializes_donated_state(monkeypatch):
    """An ASYNC engine failure surfacing after the sweep already
    consumed its donated inputs: the rescue re-materializes the
    pre-sweep state from the host snapshot and the run completes on
    the surviving engines (instead of dying on deleted buffers)."""
    import splatt_tpu.cpd as cpd_mod

    tt = _tensor()
    opts = _opts(max_iterations=4, nnz_block=256, donate_sweep=True,
                 block_alloc=BlockAlloc.ALLMODE, engine_fallback=True)
    bs = BlockedSparse.from_coo(tt, opts)
    real_make = cpd_mod._make_sweep
    state = {"fail": True}

    def patched(X, nmodes, reg, donate=False):
        real = real_make(X, nmodes, reg, donate=donate)

        def wrapper(factors, grams, first):
            out = real(factors, grams, first)  # consumes donated inputs
            if state["fail"]:
                state["fail"] = False
                resilience.note_engine_attempt("xla_scan", None)
                raise RuntimeError("INTERNAL: async runtime failure")
            return out

        return wrapper

    monkeypatch.setattr(cpd_mod, "_make_sweep", patched)
    out = cpd_mod.cpd_als(bs, 3, opts=opts)
    assert np.isfinite(float(out.fit))
    assert resilience.is_demoted("xla_scan")


# -- block-clamp observability (ISSUE 3 satellite) --------------------------

def test_block_clamp_is_reported(capsys):
    tt = _tensor()  # ~3k nnz: a 65536 block must clamp
    lay = build_layout(tt, 0, block=65536, val_dtype=np.float64,
                       verbose=True)
    assert lay.block < 65536
    assert "clamped" in capsys.readouterr().out
    events = resilience.run_report().events("block_clamp")
    assert events and events[-1]["requested"] == 65536
    assert events[-1]["effective"] == lay.block
    # the effective block is surfaced by the repr (not the dataclass
    # default dumping device arrays)
    assert f"block={lay.block}" in repr(lay)
    assert "inds" not in repr(lay)


def test_no_clamp_no_event():
    tt = _tensor()
    resilience.run_report().clear()
    build_layout(tt, 0, block=256, val_dtype=np.float64)
    assert not resilience.run_report().events("block_clamp")


# -- tuner measurement plumbing --------------------------------------------

def test_measure_candidate_times_forced_engine():
    """The real measurement body: times the forced engine and returns
    a positive median — and the faults hook is live in it."""
    tt = _tensor()
    lay = build_layout(tt, 0, block=512, val_dtype=np.float64)
    facs = init_factors(tt.dims, RANK, 0, dtype=jnp.float64)
    sec = tune._measure_candidate(lay, facs, 0, "sorted_onehot", "xla",
                                  "xla_scan", 1 << 21, warm=1, reps=2)
    assert sec > 0
    with faults.inject("tuner.measure", "runtime", times=1):
        with pytest.raises(RuntimeError):
            tune._measure_candidate(lay, facs, 0, "sorted_onehot", "xla",
                                    "xla_scan", 1 << 21)


def test_tuned_plan_never_slower_than_static_default():
    """The never-worse acceptance property, by construction: the static
    default configuration is itself a candidate, so the winner's
    measured time is <= the default's measured time."""
    tt = _tensor()
    recorded = {}

    real = tune._measure_candidate

    def recording(layout, factors, mode, path, impl, engine, st, **kw):
        sec = real(layout, factors, mode, path, impl, engine, st, **kw)
        recorded.setdefault(mode, {})[(engine, layout.block, st)] = sec
        return sec

    import splatt_tpu.tune as tmod
    orig = tmod._measure_candidate
    tmod._measure_candidate = recording
    try:
        res = tune.tune(tt, RANK, opts=_opts(), reps=1)
    finally:
        tmod._measure_candidate = orig
    for m, plan in res.plans.items():
        assert plan.sec <= min(recorded[m].values()) + 1e-12


# -- concurrent shared-cache access (docs/serve.md) --------------------------
#
# The serve daemon runs N tenants' jobs as threads in ONE process, all
# sharing the warm plan cache.  The locked protocol must hold under
# that contention: no torn JSON, no lost winners, and a broken cache
# degrades classified — never into a failed dispatch.

def test_concurrent_plan_stores_lose_no_winners():
    """N threads storing distinct winners simultaneously: the final
    cache file holds every one (the locked read-modify-write cannot
    drop a concurrent writer's entry) and parses as one JSON object."""
    import threading

    n = 16
    errs = []

    def store(i):
        try:
            tune._entry_store(f"conc:key{i}",
                              {"plan": dict(path="sorted_onehot",
                                            engine="xla", nnz_block=512,
                                            scan_target=1 << 21,
                                            sec=0.001 * (i + 1))})
        except Exception as e:  # pragma: no cover - the assert reports
            errs.append(e)

    threads = [threading.Thread(target=store, args=(i,))
               for i in range(n)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert not errs
    data = json.loads(_cache_file().read_text())  # not torn
    env = data["envs"][pk._cache_env_key()]
    assert {f"conc:key{i}" for i in range(n)} <= set(env)
    # and every winner is readable back through the memo-less path
    tune.reset_memo()
    for i in range(n):
        assert tune._entry_get(f"conc:key{i}")["plan"]["sec"] == \
            pytest.approx(0.001 * (i + 1))


def test_concurrent_loads_and_stores_interleaved():
    """Readers hammering the cache while writers mutate it: every read
    returns either None (not yet written) or a complete entry — never
    a torn/partial one — and no exception escapes."""
    import threading

    stop = threading.Event()
    errs = []

    def writer(i):
        try:
            for k in range(8):
                tune._entry_store(
                    f"mix:w{i}k{k}",
                    {"plan": dict(path="sorted_onehot", engine="xla",
                                  nnz_block=512, scan_target=1 << 21,
                                  sec=0.5)})
        except Exception as e:  # pragma: no cover
            errs.append(e)

    def reader():
        try:
            while not stop.is_set():
                tune.reset_memo()  # force real file reads
                for i in range(4):
                    ent = tune._entry_get(f"mix:w{i}k0")
                    assert ent is None or ent["plan"]["sec"] == 0.5
        except Exception as e:  # pragma: no cover
            errs.append(e)

    writers = [threading.Thread(target=writer, args=(i,))
               for i in range(4)]
    readers = [threading.Thread(target=reader) for _ in range(4)]
    for t in readers + writers:
        t.start()
    for t in writers:
        t.join()
    stop.set()
    for t in readers:
        t.join()
    assert not errs
    tune.reset_memo()
    for i in range(4):
        for k in range(8):
            assert tune._entry_get(f"mix:w{i}k{k}") is not None


def test_concurrent_reads_of_corrupt_cache_degrade_classified():
    """A corrupt cache under concurrent readers: every read degrades
    to None (re-tune) and the failure is CLASSIFIED into the run
    report (tune_cache_io_error) — never an exception, never a torn
    verdict."""
    import threading

    _cache_file().write_text("{ definitely not json")
    errs = []

    def reader():
        try:
            tune.reset_memo()
            for _ in range(5):
                assert tune._load_file() is None
        except Exception as e:  # pragma: no cover
            errs.append(e)

    threads = [threading.Thread(target=reader) for _ in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert not errs
    evs = resilience.run_report().events("tune_cache_io_error")
    assert evs and all(e["failure_class"] == "unknown" for e in evs)


def test_entry_get_never_clobbers_concurrent_write_through(monkeypatch):
    """The memo's check-then-act window: a reader that missed the memo
    and read a stale (empty) cache file must ADOPT a write-through
    that landed mid-read, not negative-cache over it — otherwise a
    persisted plan reads as missing for the rest of the process."""
    key = "race:key"
    plan = {"plan": dict(path="sorted_onehot", engine="xla",
                         nnz_block=512, scan_target=1 << 21, sec=0.5)}
    real_load = tune._load_file

    def stale_read_with_concurrent_store():
        # a sibling job's store lands while this reader holds its
        # stale view of the file
        tune._entry_store(key, plan)
        return None  # the reader's read: nothing on disk

    monkeypatch.setattr(tune, "_load_file",
                        stale_read_with_concurrent_store)
    got = tune._entry_get(key)
    assert got is not None and got["plan"]["sec"] == 0.5
    # and the memo was not poisoned with a negative entry
    monkeypatch.setattr(tune, "_load_file", real_load)
    assert tune._entry_get(key) is not None
