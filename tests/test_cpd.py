"""CPD-ALS end-to-end tests (≙ the cpd CLI path + fit semantics).

The reference has no direct cpd unit test; correctness is anchored by the
MTTKRP oracle plus the fit formula.  Here we verify stronger properties:
exact recovery of a synthetic low-rank tensor, fit monotonic-ish
improvement, determinism under a fixed seed, and stream-vs-blocked
agreement on the final fit.
"""

import jax.numpy as jnp
import numpy as np
import pytest

from splatt_tpu.blocked import BlockedSparse
from splatt_tpu.config import BlockAlloc, Options, Verbosity
from splatt_tpu.coo import SparseTensor
from splatt_tpu.cpd import cpd_als, init_factors
from tests import gen


def lowrank_tensor(dims, rank, seed=11, keep=1.0):
    """Sparse sample of an exactly rank-`rank` tensor.

    Note: with keep < 1 the *sparse* tensor (missing entries = zeros) is
    no longer low-rank — only keep=1.0 admits exact recovery.
    """
    rng = np.random.default_rng(seed)
    factors = [rng.random((d, rank)) + 0.1 for d in dims]
    dense = np.einsum("ir,jr,kr->ijk", *factors)
    mask = rng.random(dims) < keep
    idx = np.argwhere(mask)
    vals = dense[mask]
    return SparseTensor(idx.T, vals, dims)


def _opts(**kw):
    kw.setdefault("random_seed", 42)
    kw.setdefault("verbosity", Verbosity.NONE)
    kw.setdefault("val_dtype", np.float64)
    return Options(**kw)


def test_exact_recovery_stream():
    tt = lowrank_tensor((15, 12, 10), rank=3)
    out = cpd_als(tt, rank=5, opts=_opts(max_iterations=100, tolerance=1e-10))
    assert float(out.fit) > 0.999


def test_exact_recovery_blocked():
    tt = lowrank_tensor((15, 12, 10), rank=3, seed=12)
    bs = BlockedSparse.from_coo(tt, _opts(nnz_block=128))
    out = cpd_als(bs, rank=5, opts=_opts(max_iterations=100, tolerance=1e-10))
    assert float(out.fit) > 0.999


def test_reconstruction_matches_fit():
    tt = lowrank_tensor((8, 7, 6), rank=2, seed=13, keep=1.0)
    out = cpd_als(tt, rank=4, opts=_opts(max_iterations=100, tolerance=1e-12))
    dense = tt.to_dense()
    recon = out.to_dense()
    rel = np.linalg.norm(dense - recon) / np.linalg.norm(dense)
    assert rel == pytest.approx(1.0 - float(out.fit), abs=1e-6)
    assert rel < 1e-3


def test_deterministic_with_seed():
    tt = gen.fixture_tensor("med")
    a = cpd_als(tt, rank=4, opts=_opts(max_iterations=5))
    b = cpd_als(tt, rank=4, opts=_opts(max_iterations=5))
    np.testing.assert_allclose(float(a.fit), float(b.fit), atol=0)
    for fa, fb in zip(a.factors, b.factors):
        np.testing.assert_array_equal(np.asarray(fa), np.asarray(fb))


def test_stream_blocked_fit_agreement():
    """Blocked CPD must track the stream CPD bit-for-bit-ish: same init,
    same math, different MTTKRP path."""
    tt = gen.fixture_tensor("med")
    opts = _opts(max_iterations=10, block_alloc=BlockAlloc.ALLMODE,
                 nnz_block=256)
    init = init_factors(tt.dims, 8, opts.seed(), dtype=jnp.float64)
    a = cpd_als(tt, rank=8, opts=opts, init=init)
    bs = BlockedSparse.from_coo(tt, opts)
    b = cpd_als(bs, rank=8, opts=opts, init=init)
    assert float(a.fit) == pytest.approx(float(b.fit), abs=1e-7)


def test_fit_in_range_and_lambda_positive():
    out = cpd_als(gen.fixture_tensor("med4"), rank=4,
                  opts=_opts(max_iterations=8))
    assert 0.0 <= float(out.fit) <= 1.0
    assert np.all(np.asarray(out.lam) >= 0)
    # post-processing leaves unit-norm columns (cpd_post_process)
    for U in out.factors:
        norms = np.linalg.norm(np.asarray(U), axis=0)
        np.testing.assert_allclose(norms[norms > 1e-12], 1.0, atol=1e-8)


def test_convergence_tolerance_stops_early():
    tt = lowrank_tensor((10, 9, 8), rank=2, seed=14, keep=0.5)
    loose = cpd_als(tt, rank=3, opts=_opts(max_iterations=50, tolerance=1e-2))
    assert 0.0 < float(loose.fit) <= 1.0


def test_regularization_runs():
    tt = gen.fixture_tensor("small")
    out = cpd_als(tt, rank=3, opts=_opts(max_iterations=5, regularization=1e-3))
    assert np.isfinite(float(out.fit))


def test_4mode_and_5mode():
    for name in ("med4", "med5"):
        tt = gen.fixture_tensor(name)
        bs = BlockedSparse.from_coo(tt, _opts(nnz_block=256))
        out = cpd_als(bs, rank=4, opts=_opts(max_iterations=5))
        assert np.isfinite(float(out.fit))
        assert out.nmodes == tt.nmodes


def test_checkpoint_resume_past_max_iterations(tmp_path):
    """Resuming a finished run must return the checkpointed model, not
    a zero-fit shell."""
    tt = gen.fixture_tensor("med")
    ck = str(tmp_path / "ck.npz")
    opts = _opts(max_iterations=6)
    a = cpd_als(tt, rank=3, opts=opts, checkpoint_path=ck,
                checkpoint_every=2)
    b = cpd_als(tt, rank=3, opts=opts, checkpoint_path=ck,
                checkpoint_every=2)  # start_it == max_iterations
    assert float(b.fit) == pytest.approx(float(a.fit), abs=1e-8)
    np.testing.assert_allclose(b.to_dense(), a.to_dense(), atol=1e-8)


def test_checkpoint_mismatch_rejected(tmp_path):
    tt = gen.fixture_tensor("med")
    ck = str(tmp_path / "ck.npz")
    cpd_als(tt, rank=3, opts=_opts(max_iterations=4),
            checkpoint_path=ck, checkpoint_every=2)
    with pytest.raises(ValueError, match="checkpoint"):
        cpd_als(tt, rank=8, opts=_opts(max_iterations=4),
                checkpoint_path=ck, checkpoint_every=2)
    # resume=False overwrites instead
    out = cpd_als(tt, rank=8, opts=_opts(max_iterations=4),
                  checkpoint_path=ck, checkpoint_every=2, resume=False)
    assert out.rank == 8


def test_fit_check_every_same_result():
    """k>1 batches host syncs between convergence checks; with
    convergence disabled (tol=0) the math is identical to k=1."""
    import numpy as np

    from splatt_tpu import BlockedSparse, cpd_als, default_opts
    from tests.gen import fixture_tensor

    tt = fixture_tensor("small")
    res = {}
    for k in (1, 4):
        opts = default_opts()
        opts.random_seed = 5
        opts.max_iterations = 8
        opts.tolerance = 0.0
        opts.fit_check_every = k
        res[k] = cpd_als(BlockedSparse.from_coo(tt, opts), rank=3, opts=opts)
    assert abs(float(res[1].fit) - float(res[4].fit)) < 1e-6
    for a, b in zip(res[1].factors, res[4].factors):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-5, atol=1e-6)


def test_fit_check_every_validation():
    import pytest

    from splatt_tpu import default_opts

    opts = default_opts()
    opts.fit_check_every = 0
    with pytest.raises(ValueError, match="fit_check_every"):
        opts.validate()


def test_phased_sweep_matches_fused():
    """The per-phase jitted sweep (TPU default: a whole-sweep program
    wedges the tunneled remote compiler) is bit-identical to the fused
    sweep — same phase order, same accumulations."""
    from splatt_tpu.cpd import _make_phased_sweep, _make_sweep
    from splatt_tpu.ops.linalg import gram

    rng = np.random.default_rng(5)
    dims = (14, 11, 9)
    ind = np.stack([rng.integers(0, d, size=300) for d in dims])
    tt = SparseTensor(ind, rng.random(300), dims)
    # pin the XLA engine: the un-jitted phased sweep would otherwise
    # dispatch to the native C++ engine, whose summation order differs
    bs = BlockedSparse.from_coo(tt, _opts(nnz_block=128,
                                          block_alloc=BlockAlloc.ALLMODE,
                                          use_pallas=False))
    outs = []
    for builder in (_make_sweep, _make_phased_sweep):
        factors = init_factors(tt.dims, 6, 3, dtype=jnp.float64)
        grams = [gram(U) for U in factors]
        sweep = builder(bs, tt.nmodes, 0.0)
        f, g, lam, zz, inner = sweep(factors, grams, True)
        for _ in range(3):
            f, g, lam, zz, inner = sweep(f, g, False)
        outs.append((f, lam, float(zz), float(inner)))
    (f_a, lam_a, zz_a, in_a), (f_b, lam_b, zz_b, in_b) = outs
    assert zz_a == zz_b and in_a == in_b
    np.testing.assert_array_equal(np.asarray(lam_a), np.asarray(lam_b))
    for ua, ub in zip(f_a, f_b):
        np.testing.assert_array_equal(np.asarray(ua), np.asarray(ub))


def test_phased_sweep_donation_bit_identical():
    """Regression for the SPL008-driven restructure of the phased
    sweep (the last mode's update + fit moved OUTSIDE the donating
    loop so the donated M is never live at the fit read): mid-phase M
    donation stays a pure buffer-aliasing optimization — bit-identical
    to the non-donating phased sweep, callers' factors untouched."""
    from splatt_tpu.cpd import _make_phased_sweep
    from splatt_tpu.ops.linalg import gram

    rng = np.random.default_rng(7)
    dims = (14, 11, 9)
    ind = np.stack([rng.integers(0, d, size=300) for d in dims])
    tt = SparseTensor(ind, rng.random(300), dims)
    bs = BlockedSparse.from_coo(tt, _opts(nnz_block=128,
                                          block_alloc=BlockAlloc.ALLMODE,
                                          use_pallas=False))
    outs = []
    for donate in (False, True):
        factors = init_factors(tt.dims, 6, 3, dtype=jnp.float64)
        grams = [gram(U) for U in factors]
        sweep = _make_phased_sweep(bs, tt.nmodes, 0.0, donate=donate)
        f, g, lam, zz, inner = sweep(factors, grams, True)
        for _ in range(2):
            f, g, lam, zz, inner = sweep(f, g, False)
        # the fit phase read M AFTER the last (non-donating) update —
        # with donation on, a mid-phase M re-read would have raised
        outs.append((f, lam, float(zz), float(inner)))
        assert not any(u.is_deleted() for u in factors)
    (f_a, lam_a, zz_a, in_a), (f_b, lam_b, zz_b, in_b) = outs
    assert zz_a == zz_b and in_a == in_b
    np.testing.assert_array_equal(np.asarray(lam_a), np.asarray(lam_b))
    for ua, ub in zip(f_a, f_b):
        np.testing.assert_array_equal(np.asarray(ua), np.asarray(ub))


def test_stop_hook_checkpoints_and_returns_early(tmp_path):
    """The cooperative `stop` hook (the serve daemon's drain,
    docs/serve.md): polled at fit-check iterations; returning True
    checkpoints the just-committed state and returns early, and a
    later resume continues the same optimization to the un-stopped
    result."""
    from splatt_tpu.cpd import load_checkpoint

    tt = lowrank_tensor((15, 12, 10), rank=3)
    ck = str(tmp_path / "stop.npz")
    opts = _opts(max_iterations=20, tolerance=0.0)
    calls = []

    def stop():
        calls.append(1)
        return len(calls) >= 3

    partial = cpd_als(tt, rank=3, opts=opts, checkpoint_path=ck,
                      checkpoint_every=100, stop=stop)
    _, _, it, fit = load_checkpoint(ck)
    assert it == 3 and len(calls) == 3      # stopped at the 3rd check
    assert fit == pytest.approx(float(partial.fit))
    # resume without the hook: finishes the remaining iterations and
    # matches an uninterrupted run of the same config
    resumed = cpd_als(tt, rank=3, opts=opts, checkpoint_path=ck,
                      checkpoint_every=100)
    straight = cpd_als(tt, rank=3, opts=opts)
    assert float(resumed.fit) == pytest.approx(float(straight.fit),
                                               abs=1e-6)


def test_stop_hook_never_true_changes_nothing(tmp_path):
    tt = lowrank_tensor((15, 12, 10), rank=3)
    opts = _opts(max_iterations=10, tolerance=0.0)
    a = cpd_als(tt, rank=3, opts=opts)
    b = cpd_als(tt, rank=3, opts=opts, stop=lambda: False)
    assert float(a.fit) == pytest.approx(float(b.fit), abs=0.0)


def test_health_guard_disabled_skips_snapshot_refresh(monkeypatch):
    """Satellite: with SPLATT_HEALTH_RETRIES=0 the sentinel's host-
    snapshot refresh is skipped entirely (guards must be free when
    disabled) — only the single initial rescue snapshot is taken for
    the donated fused sweep, and none at all for non-donating sweeps.
    """
    import splatt_tpu.cpd as cpd_mod

    monkeypatch.setenv("SPLATT_HEALTH_RETRIES", "0")
    tt = lowrank_tensor((15, 12, 10), rank=3)
    opts = _opts(max_iterations=6, tolerance=0.0)
    bs = BlockedSparse.from_coo(tt, opts)

    copies = []
    real = np.asarray

    def counting_asarray(a, *k, **kw):
        copies.append(1)
        return real(a, *k, **kw)

    monkeypatch.setattr(cpd_mod.np, "asarray", counting_asarray)
    out = cpd_als(bs, rank=3, opts=opts)
    disabled_copies = len(copies)
    assert np.isfinite(float(out.fit))

    # with the sentinel ON, the snapshot refreshes at every check
    # iteration — strictly more host copies than the disabled run
    monkeypatch.setenv("SPLATT_HEALTH_RETRIES", "3")
    copies.clear()
    cpd_als(bs, rank=3, opts=opts)
    assert len(copies) > disabled_copies
