"""Bounded-memory decomposition proof (VERDICT item 5).

Generates a >=100M-nnz binary tensor on disk, then decomposes it with
the streamed grid build (memmap in, memmap out) while sampling RSS.
Done-criterion: peak RSS stays O(chunk + cell metadata) — a small
fraction of the 2.3GB tensor — proving the 1.7B-nnz Amazon config's
convert -> memmap -> decompose -> cpd pipeline is host-RAM-bounded.

Usage: python tools/rss_decomp_proof.py [nnz] (default 100_000_000)
Writes tools/rss_proof.json.
"""
import json
import os
import resource
import sys

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def rss_mb():
    return resource.getrusage(resource.RUSAGE_SELF).ru_maxrss / 1024.0


def main():
    nnz = int(sys.argv[1]) if len(sys.argv) > 1 else 100_000_000
    dims = (120_000, 90_000, 280_000)
    work = "/tmp/rss_proof"
    os.makedirs(work, exist_ok=True)
    path = os.path.join(work, "big.bin")

    # write the binary by chunks (header format of splatt_tpu.io:
    # SPTT magic, <IIII version/nmodes/idx_width/val_width, u64 dims,
    # u64 nnz, then mode-major int32 index block + f64 values)
    import struct

    chunk = 4_000_000
    expect = 24 + 3 * 8 + 8 + nnz * (3 * 4 + 8)
    if os.path.exists(path) and os.path.getsize(path) == expect:
        print("reusing", path)
        return _measure(path, nnz)
    with open(path, "wb") as f:
        f.write(b"SPTT")
        f.write(struct.pack("<IIII", 1, 3, 4, 8))
        f.write(np.asarray(dims, dtype=np.uint64).tobytes())
        f.write(struct.pack("<Q", nnz))
        rng = np.random.default_rng(0)
        for m, d in enumerate(dims):
            for s in range(0, nnz, chunk):
                n = min(chunk, nnz - s)
                raw = (rng.zipf(1.25, n) * 2654435761 + rng.integers(0, d, n)) % d
                f.write(raw.astype(np.int32).tobytes())
        for s in range(0, nnz, chunk):
            n = min(chunk, nnz - s)
            f.write(rng.random(n).astype(np.float64).tobytes())
    return _measure(path, nnz)


def _measure(path, nnz):
    work = os.path.dirname(path)
    size_gb = os.path.getsize(path) / 2**30

    # fresh subprocess so generation RSS does not pollute the measurement
    # each decomposition in its own fresh subprocess so RSS peaks are
    # attributed per-build (and generation RSS never pollutes them)
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    codes = dict(
        grid=f'''
import json, os, resource, sys
os.environ["JAX_PLATFORMS"] = "cpu"
sys.path.insert(0, {repo!r})
import jax
jax.config.update("jax_platforms", "cpu")
import numpy as np
from splatt_tpu.io import load_memmap
from splatt_tpu.parallel.grid import GridDecomp

def rss_mb():
    return resource.getrusage(resource.RUSAGE_SELF).ru_maxrss / 1024.0

tt = load_memmap({path!r})
r0 = rss_mb()
d = GridDecomp.build(tt, grid=(2, 2, 2), val_dtype=np.float32,
                     streamed=True, out_dir={work!r} + "/bk",
                     chunk=1 << 21)
print(json.dumps(dict(rss_after_load_mb=round(r0, 1),
                      rss_peak_mb=round(rss_mb(), 1),
                      fill=round(d.fill, 3), cell_nnz=d.cell_nnz,
                      nnz=d.nnz)))
''',
        fine=f'''
import json, os, resource, sys
os.environ["JAX_PLATFORMS"] = "cpu"
sys.path.insert(0, {repo!r})
import jax
jax.config.update("jax_platforms", "cpu")
import numpy as np
from splatt_tpu.io import load_memmap
from splatt_tpu.parallel.sharded import shard_nnz_host

def rss_mb():
    return resource.getrusage(resource.RUSAGE_SELF).ru_maxrss / 1024.0

tt = load_memmap({path!r})
r0 = rss_mb()
inds, vals = shard_nnz_host(tt, 8, np.float32, streamed=True,
                            out_dir={work!r} + "/fine", chunk=1 << 21)
print(json.dumps(dict(rss_after_load_mb=round(r0, 1),
                      rss_peak_mb=round(rss_mb(), 1),
                      nnz_pad=int(inds.shape[1]))))
''',
        coarse=f'''
import json, os, resource, sys
os.environ["JAX_PLATFORMS"] = "cpu"
sys.path.insert(0, {repo!r})
import jax
jax.config.update("jax_platforms", "cpu")
import numpy as np
from splatt_tpu.io import load_memmap
from splatt_tpu.parallel.coarse import _bucket_by_mode

def rss_mb():
    return resource.getrusage(resource.RUSAGE_SELF).ru_maxrss / 1024.0

tt = load_memmap({path!r})
r0 = rss_mb()
# mode 0 is representative; the driver builds one per mode, each its
# own streamed pass with the same bounded footprint
binds, bvals, block, counts = _bucket_by_mode(
    tt, 0, 8, np.float32, streamed=True,
    out_dir={work!r} + "/coarse0", chunk=1 << 21)
print(json.dumps(dict(rss_after_load_mb=round(r0, 1),
                      rss_peak_mb=round(rss_mb(), 1),
                      bucket_nnz=int(binds.shape[2]))))
''',
        # the round-5 additions: the OPTIMIZED blocked engine's sorted
        # layouts built from the memmapped decomposition by the chunked
        # counting sort (streamed_blocked_buckets) — host RSS must stay
        # bounded here too, or out-of-core loses the fast engine
        # (VERDICT r4 missing #3)
        grid_blocked=f'''
import json, os, resource, sys
os.environ["JAX_PLATFORMS"] = "cpu"
sys.path.insert(0, {repo!r})
import jax
jax.config.update("jax_platforms", "cpu")
import numpy as np
from splatt_tpu.io import load_memmap
from splatt_tpu.parallel.grid import GridDecomp
from splatt_tpu.parallel.common import is_memmapped
from splatt_tpu.config import BlockAlloc, default_opts

def rss_mb():
    return resource.getrusage(resource.RUSAGE_SELF).ru_maxrss / 1024.0

tt = load_memmap({path!r})
r0 = rss_mb()
d = GridDecomp.build(tt, grid=(2, 2, 2), val_dtype=np.float32,
                     streamed=True, out_dir={work!r} + "/bk",
                     chunk=1 << 21)
opts = default_opts()
opts.block_alloc = BlockAlloc.ONEMODE
cells = d.build_cell_layouts(opts, chunk=1 << 21)
lay = cells.layouts[0]
print(json.dumps(dict(rss_after_load_mb=round(r0, 1),
                      rss_peak_mb=round(rss_mb(), 1),
                      memmapped=bool(is_memmapped(lay["inds"])),
                      seg_width=lay["seg_width"], block=lay["block"])))
''',
        coarse_blocked=f'''
import json, os, resource, sys
os.environ["JAX_PLATFORMS"] = "cpu"
sys.path.insert(0, {repo!r})
import jax
jax.config.update("jax_platforms", "cpu")
import numpy as np
from splatt_tpu.io import load_memmap
from splatt_tpu.parallel.coarse import _bucket_by_mode
from splatt_tpu.parallel.common import streamed_blocked_buckets

def rss_mb():
    return resource.getrusage(resource.RUSAGE_SELF).ru_maxrss / 1024.0

tt = load_memmap({path!r})
r0 = rss_mb()
binds, bvals, block, counts = _bucket_by_mode(
    tt, 0, 8, np.float32, streamed=True,
    out_dir={work!r} + "/coarse0", chunk=1 << 21)
i, v, rs, blk, S = streamed_blocked_buckets(
    binds, bvals, counts, 0, block, 4096,
    out_dir={work!r} + "/coarse0/blocked", chunk=1 << 21)
print(json.dumps(dict(rss_after_load_mb=round(r0, 1),
                      rss_peak_mb=round(rss_mb(), 1),
                      memmapped=isinstance(i, np.memmap),
                      seg_width=S, block=blk)))
''')
    import subprocess
    rec = dict(tensor_gb=round(size_gb, 2), nnz_requested=nnz)
    for name, code in codes.items():
        out = subprocess.run([sys.executable, "-c", code],
                             capture_output=True, text=True, check=True)
        sub = json.loads(out.stdout.strip().splitlines()[-1])
        sub["bounded"] = sub["rss_peak_mb"] < 1024.0 * size_gb / 2
        rec[name] = sub
        print(name, json.dumps(sub), flush=True)
    rec["bounded"] = all(rec[n]["bounded"] for n in codes)
    with open("tools/rss_proof.json", "w") as f:
        json.dump(rec, f, indent=1)
    print(json.dumps(rec))


if __name__ == "__main__":
    main()
