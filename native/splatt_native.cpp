// Native host runtime for splatt-tpu: fast coordinate-tensor parsing and
// sorting.  (≙ the reference's C host code: the text parser hot path in
// src/io.c:62-108 and the hybrid sort in src/sort.c — re-designed, not
// translated: single buffered pass, branch-light digit parsing, and
// std::sort-based slice sorting.)
//
// Exposed as a plain C ABI for ctypes:
//   tns_open(path)             -> handle (counts rows/cols, loads buffer)
//   tns_rows(h) / tns_cols(h)  -> dimensions of the parse
//   tns_fill(h, inds, vals)    -> parse into caller-allocated buffers
//                                 (inds: int64 [ncols-1][nrows] row-major
//                                  per mode; vals: double [nrows])
//   tns_close(h)
//
// Build: g++ -O3 -march=native -shared -fPIC -o _native.so splatt_native.cpp

#include <algorithm>
#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <vector>

namespace {

struct TnsFile {
  std::vector<char> buf;
  int64_t nrows = 0;
  int ncols = 0;
};

inline const char *skip_ws(const char *p, const char *end) {
  while (p < end && (*p == ' ' || *p == '\t' || *p == '\r')) ++p;
  return p;
}

inline const char *skip_line(const char *p, const char *end) {
  while (p < end && *p != '\n') ++p;
  return p < end ? p + 1 : end;
}

// Counts whitespace-separated fields on one line.
inline int count_fields(const char *p, const char *end) {
  int n = 0;
  while (p < end && *p != '\n') {
    p = skip_ws(p, end);
    if (p >= end || *p == '\n') break;
    ++n;
    while (p < end && *p != ' ' && *p != '\t' && *p != '\r' && *p != '\n')
      ++p;
  }
  return n;
}

inline bool is_comment_or_blank(const char *p, const char *end) {
  p = skip_ws(p, end);
  return p >= end || *p == '\n' || *p == '#';
}

}  // namespace

extern "C" {

void *tns_open(const char *path) {
  FILE *f = fopen(path, "rb");
  if (!f) return nullptr;
  fseek(f, 0, SEEK_END);
  long size = ftell(f);
  fseek(f, 0, SEEK_SET);
  auto *t = new TnsFile();
  // +1: NUL terminator so strtod on the last line of a file with no
  // trailing newline cannot scan past the allocation.
  t->buf.resize(static_cast<size_t>(size) + 1);
  if (size > 0 && fread(t->buf.data(), 1, size, f) != (size_t)size) {
    fclose(f);
    delete t;
    return nullptr;
  }
  t->buf[size] = '\0';
  fclose(f);

  const char *p = t->buf.data();
  const char *end = p + size;
  while (p < end) {
    if (!is_comment_or_blank(p, end)) {
      if (t->ncols == 0) {
        t->ncols = count_fields(p, end);
      }
      ++t->nrows;
    }
    p = skip_line(p, end);
  }
  if (t->ncols < 2) {  // need at least one index + a value
    delete t;
    return nullptr;
  }
  return t;
}

int64_t tns_rows(void *h) { return static_cast<TnsFile *>(h)->nrows; }
int tns_cols(void *h) { return static_cast<TnsFile *>(h)->ncols; }

// Parses into inds (per-mode contiguous, mode-major: inds[m*nrows + r])
// and vals.  Returns 0 on success, nonzero on malformed input (ragged
// rows or non-numeric fields).
int tns_fill(void *h, int64_t *inds, double *vals) {
  auto *t = static_cast<TnsFile *>(h);
  const char *p = t->buf.data();
  const char *end = p + t->buf.size() - 1;  // excludes the NUL terminator
  const int nmodes = t->ncols - 1;
  const int64_t nrows = t->nrows;
  int64_t r = 0;
  while (p < end) {
    if (is_comment_or_blank(p, end)) {
      p = skip_line(p, end);
      continue;
    }
    for (int m = 0; m < nmodes; ++m) {
      p = skip_ws(p, end);
      bool neg = (p < end && *p == '-');
      if (neg) ++p;
      if (p >= end || *p < '0' || *p > '9') return 1;
      int64_t v = 0;
      while (p < end && *p >= '0' && *p <= '9') v = v * 10 + (*p++ - '0');
      // an index field must end at whitespace — otherwise a short row
      // like "1 2 0.7" would silently donate its value's integer part
      // to the index column (rc=1 ≙ the reference aborting on a bad
      // parse, src/io.c:85-97)
      if (p < end && *p != ' ' && *p != '\t' && *p != '\r' && *p != '\n')
        return 1;
      inds[m * nrows + r] = neg ? -v : v;
    }
    p = skip_ws(p, end);
    char *next = nullptr;
    vals[r] = strtod(p, &next);
    if (next == p) return 2;
    p = next;
    p = skip_ws(p, end);
    if (p < end && *p != '\n') return 3;  // ragged row (extra fields)
    p = skip_line(p, end);
    ++r;
  }
  return r == nrows ? 0 : 4;
}

void tns_close(void *h) { delete static_cast<TnsFile *>(h); }

// ---------------------------------------------------------------------
// Streaming text -> binary conversion with bounded memory (two passes
// over the file, ~8MB buffer), for tensors larger than RAM.  (≙ the
// reference's streamed chunk ingest, mpi_simple_distribute
// src/mpi/mpi_io.c:587-648 — here the "ranks" are the per-mode regions
// of the binary file, written via buffered seeks.)
//
// Binary layout must match splatt_tpu/io.py: magic "SPTT", u32
// {version=1, nmodes, idx_width, val_width=8}, u64 dims[nmodes], u64
// nnz, then per-mode index arrays, then doubles.

namespace {

constexpr size_t kChunk = 8u << 20;

struct LineScanner {
  FILE *f;
  std::vector<char> buf;
  size_t len = 0, pos = 0;
  bool eof = false;
  bool error = false;  // line longer than the buffer / read failure

  explicit LineScanner(FILE *file) : f(file), buf(kChunk + 1) {}

  // Returns pointer to the next NUL-terminated line (without '\n'),
  // or nullptr at end of file or on error (check `error`).  The
  // pointer is valid until next call.
  char *next_line() {
    for (;;) {
      // find '\n' in [pos, len)
      char *nl = static_cast<char *>(
          memchr(buf.data() + pos, '\n', len - pos));
      if (nl) {
        *nl = '\0';
        char *line = buf.data() + pos;
        pos = static_cast<size_t>(nl - buf.data()) + 1;
        return line;
      }
      if (eof) {
        if (pos < len) {  // final line without '\n'
          buf[len] = '\0';
          char *line = buf.data() + pos;
          pos = len;
          return line;
        }
        return nullptr;
      }
      // shift the partial tail to the front and refill
      size_t tail = len - pos;
      if (tail >= kChunk) {
        // a single line fills the whole buffer: refusing beats
        // silently truncating the rest of the file
        error = true;
        return nullptr;
      }
      memmove(buf.data(), buf.data() + pos, tail);
      pos = 0;
      len = tail;
      size_t got = fread(buf.data() + len, 1, kChunk - len, f);
      len += got;
      if (got == 0) {
        if (ferror(f)) error = true;
        eof = true;
      }
    }
  }
};

inline bool parse_row(char *line, int ncols, int64_t *idx, double *val) {
  char *p = line;
  for (int c = 0; c < ncols - 1; ++c) {
    while (*p == ' ' || *p == '\t' || *p == '\r') ++p;
    bool neg = (*p == '-');
    if (neg) ++p;
    if (*p < '0' || *p > '9') return false;
    int64_t v = 0;
    while (*p >= '0' && *p <= '9') v = v * 10 + (*p++ - '0');
    // index fields terminate at whitespace (same short-row guard as
    // tns_fill): "1 2 0.7" must not parse 0 as an index and .7 as val
    if (*p != ' ' && *p != '\t' && *p != '\r' && *p != '\n' && *p != '\0')
      return false;
    idx[c] = neg ? -v : v;
  }
  char *next = nullptr;
  *val = strtod(p, &next);
  if (next == p) return false;
  while (*next == ' ' || *next == '\t' || *next == '\r') ++next;
  return *next == '\0';
}

inline bool line_blank_or_comment(const char *p) {
  while (*p == ' ' || *p == '\t' || *p == '\r') ++p;
  return *p == '\0' || *p == '#';
}

struct RegionWriter {
  FILE *f;
  long base;
  int width;  // 4 or 8
  std::vector<char> buf;
  size_t used = 0;
  int64_t written = 0;
  bool ok = true;  // sticky: any short write (ENOSPC, I/O error) trips it

  RegionWriter(FILE *file, long base_off, int w)
      : f(file), base(base_off), width(w), buf(1u << 20) {}

  void push(int64_t v) {
    if (used + 8 > buf.size()) flush();
    if (width == 4) {
      int32_t x = static_cast<int32_t>(v);
      memcpy(buf.data() + used, &x, 4);
      used += 4;
    } else {
      memcpy(buf.data() + used, &v, 8);
      used += 8;
    }
  }

  void push_d(double v) {
    if (used + 8 > buf.size()) flush();
    memcpy(buf.data() + used, &v, 8);
    used += 8;
  }

  void flush() {
    if (used) {
      if (fseek(f, base + written, SEEK_SET) != 0 ||
          fwrite(buf.data(), 1, used, f) != used)
        ok = false;
      written += static_cast<int64_t>(used);
      used = 0;
    }
  }
};

}  // namespace

int tns_stream_to_bin(const char *src, const char *dst) {
  // pass 1: rows, cols, per-column min/max
  FILE *f = fopen(src, "rb");
  if (!f) return 1;
  int ncols = 0;
  int64_t nrows = 0;
  int64_t idx[64];
  double val;
  int64_t mins[64], maxs[64];
  {
    LineScanner sc(f);
    char *line;
    while ((line = sc.next_line()) != nullptr) {
      if (line_blank_or_comment(line)) continue;
      if (ncols == 0) {
        ncols = count_fields(line, line + strlen(line));
        if (ncols < 2 || ncols > 65) { fclose(f); return 2; }
        for (int c = 0; c < ncols - 1; ++c) {
          mins[c] = INT64_MAX;
          maxs[c] = INT64_MIN;
        }
      }
      if (!parse_row(line, ncols, idx, &val)) { fclose(f); return 3; }
      for (int c = 0; c < ncols - 1; ++c) {
        if (idx[c] < 0) { fclose(f); return 3; }  // negative coordinate
        if (idx[c] < mins[c]) mins[c] = idx[c];
        if (idx[c] > maxs[c]) maxs[c] = idx[c];
      }
      ++nrows;
    }
    if (sc.error) { fclose(f); return 3; }
  }
  fclose(f);
  if (ncols == 0 || nrows == 0) return 4;
  const int nmodes = ncols - 1;
  // 0/1-index autodetect: any zero anywhere -> 0-indexed (io.py rule)
  int64_t global_min = INT64_MAX;
  for (int c = 0; c < nmodes; ++c)
    if (mins[c] < global_min) global_min = mins[c];
  const int64_t shift = global_min > 0 ? 1 : 0;
  int64_t max_idx = 0;
  for (int c = 0; c < nmodes; ++c)
    if (maxs[c] - shift > max_idx) max_idx = maxs[c] - shift;
  const int idx_width = max_idx < (int64_t(1) << 31) ? 4 : 8;

  // header + region offsets
  FILE *out = fopen(dst, "wb");
  if (!out) return 5;
  fwrite("SPTT", 1, 4, out);
  uint32_t hdr[4] = {1u, static_cast<uint32_t>(nmodes),
                     static_cast<uint32_t>(idx_width), 8u};
  fwrite(hdr, 4, 4, out);
  for (int c = 0; c < nmodes; ++c) {
    uint64_t d = static_cast<uint64_t>(maxs[c] - shift + 1);
    fwrite(&d, 8, 1, out);
  }
  uint64_t nnz_u = static_cast<uint64_t>(nrows);
  fwrite(&nnz_u, 8, 1, out);
  long data_base = ftell(out);

  std::vector<RegionWriter> writers;
  writers.reserve(nmodes + 1);
  for (int c = 0; c < nmodes; ++c)
    writers.emplace_back(out, data_base + (long)c * idx_width * nrows,
                         idx_width);
  writers.emplace_back(out, data_base + (long)nmodes * idx_width * nrows, 8);

  // pass 2: parse + scatter into regions
  f = fopen(src, "rb");
  if (!f) { fclose(out); return 1; }
  {
    LineScanner sc(f);
    char *line;
    int64_t r = 0;
    while ((line = sc.next_line()) != nullptr) {
      if (line_blank_or_comment(line)) continue;
      if (!parse_row(line, ncols, idx, &val)) { fclose(f); fclose(out); return 3; }
      for (int c = 0; c < nmodes; ++c) writers[c].push(idx[c] - shift);
      writers[nmodes].push_d(val);
      ++r;
    }
    if (sc.error || r != nrows) { fclose(f); fclose(out); return 6; }
  }
  fclose(f);
  bool ok = true;
  for (auto &w : writers) {
    w.flush();
    ok = ok && w.ok;
  }
  if (fclose(out) != 0) ok = false;
  return ok ? 0 : 7;
}

// ---------------------------------------------------------------------
// Blocked-layout sort: lexicographic (key_mode, then remaining modes in
// a given order) permutation of nnz.  (≙ tt_sort's role in CSF builds,
// src/sort.c:912-961.)  Counting-bucket on the leading mode + std::sort
// within each slice on packed secondary keys.
// Returns 0 on success; perm must hold nnz int64.

int sort_perm(const int64_t *inds, int64_t nnz, int nmodes,
              const int64_t *dims, const int *mode_order, int64_t *perm) {
  if (nmodes < 1 || nnz < 0) return 1;
  // every index must lie in [0, dims[m]) — the bucket array and the
  // packed keys below assume it (the numpy fallback tolerates anything,
  // so out-of-range data degrades to the fallback, never to UB)
  for (int m = 0; m < nmodes; ++m) {
    const int64_t *col = inds + static_cast<int64_t>(m) * nnz;
    const int64_t d = dims[m];
    for (int64_t i = 0; i < nnz; ++i)
      if (col[i] < 0 || col[i] >= d) return 2;
  }
  const int lead = mode_order[0];
  const int64_t lead_dim = dims[lead];
  // bucket offsets by leading-mode index
  std::vector<int64_t> counts(static_cast<size_t>(lead_dim) + 1, 0);
  const int64_t *lead_ind = inds + static_cast<int64_t>(lead) * nnz;
  for (int64_t i = 0; i < nnz; ++i) ++counts[lead_ind[i] + 1];
  for (int64_t s = 0; s < lead_dim; ++s) counts[s + 1] += counts[s];
  std::vector<int64_t> cursor(counts.begin(), counts.end() - 1);
  for (int64_t i = 0; i < nnz; ++i) perm[cursor[lead_ind[i]]++] = i;

  // secondary key: pack remaining modes into an unsigned 128-bit key
  // when the combined span provably fits, else compare lexicographically
  bool packable = true;
  unsigned __int128 span = 1;
  const unsigned __int128 kLimit = static_cast<unsigned __int128>(1) << 126;
  for (int k = 1; k < nmodes; ++k) {
    const unsigned __int128 f =
        static_cast<unsigned __int128>(dims[mode_order[k]]) + 1;
    if (f == 0 || span > kLimit / f) {  // true overflow check, no wrap
      packable = false;
      break;
    }
    span *= f;
  }
  if (packable) {
    std::vector<unsigned __int128> keys(nnz);
    for (int64_t i = 0; i < nnz; ++i) {
      unsigned __int128 key = 0;
      for (int k = 1; k < nmodes; ++k) {
        const int m = mode_order[k];
        key = key * (static_cast<unsigned __int128>(dims[m]) + 1) +
              static_cast<unsigned __int128>(inds[static_cast<int64_t>(m) * nnz + i]);
      }
      keys[i] = key;
    }
    for (int64_t s = 0; s < lead_dim; ++s) {
      int64_t lo = counts[s], hi = counts[s + 1];
      if (hi - lo > 1)
        std::sort(perm + lo, perm + hi, [&](int64_t a, int64_t b) {
          // tie-break on original position => stable like np.lexsort
          return keys[a] != keys[b] ? keys[a] < keys[b] : a < b;
        });
    }
    return 0;
  }
  for (int64_t s = 0; s < lead_dim; ++s) {
    int64_t lo = counts[s], hi = counts[s + 1];
    if (hi - lo > 1)
      std::sort(perm + lo, perm + hi, [&](int64_t a, int64_t b) {
        for (int k = 1; k < nmodes; ++k) {
          const int m = mode_order[k];
          const int64_t ia = inds[static_cast<int64_t>(m) * nnz + a];
          const int64_t ib = inds[static_cast<int64_t>(m) * nnz + b];
          if (ia != ib) return ia < ib;
        }
        return a < b;
      });
  }
  return 0;
}

}  // extern "C"

// ---------------------------------------------------------------------------
// Native MTTKRP — the host fallback engine (≙ the reference's
// register-blocked fiber loops, src/mttkrp.c:427-463, re-designed for
// the blocked layout: a flat pass over mode-sorted nonzeros with a
// rank-length register accumulator flushed on output-row change; no
// tree, no locks — one core, contiguous rank-length rows, f32 or f64).
//
//   inds:    (nmodes, nnz_pad) int32 row-major (the layout's indices)
//   vals:    (nnz_pad,) T
//   factors: nmodes pointers, factors[k] = (dims[k], rank) T row-major
//            (factors[mode] is never read)
//   out:     (dims[mode], rank) T, caller-zeroed
//   sorted:  nonzeros are sorted by `mode` (enables run accumulation);
//            0 => direct scatter accumulation (generic modes)

namespace {

template <typename T>
void mttkrp_impl(const int32_t *inds, const T *vals, int64_t nnz,
                 int64_t nnz_pad, int nmodes, int mode,
                 const T *const *factors, const int64_t *dims, int rank,
                 T *out, int sorted) {
  const int32_t *orow = inds + static_cast<int64_t>(mode) * nnz_pad;
  const int64_t odim = dims[mode];
  std::vector<T> accbuf(rank, T(0));
  std::vector<T> prodbuf(rank);
  T *acc = accbuf.data();
  T *prod = prodbuf.data();
  int64_t cur = -1;

  // gather the non-output mode index streams once
  const int32_t *oinds[8];
  const T *ofac[8];
  int nother = 0;
  for (int k = 0; k < nmodes; ++k) {
    if (k == mode) continue;
    oinds[nother] = inds + static_cast<int64_t>(k) * nnz_pad;
    ofac[nother] = factors[k];
    ++nother;
  }

  // NOTE on further tuning (measured, round 5 — tools/cpu_profile.json):
  // this loop is at its single-core floor for the flagship config
  // (20M nnz, rank 50, f32, NELL-2 dims): ~22-24 ns/nonzero sorted,
  // matching an isolated microbench of the same loop (~19-20 ns on
  // uniform-random indices).  Software-prefetching the factor rows
  // PF_DIST ahead wins 16-22% on uniform-random gathers but is a wash
  // to slightly negative on the real power-law tensors (hot rows are
  // already cache-resident, so the extra prefetch instructions buy
  // nothing), and compile-time rank specialization measured within
  // noise of this runtime-rank loop — both were tried and reverted.
  for (int64_t n = 0; n < nnz; ++n) {
    const int64_t row = orow[n];
    const T v = vals[n];
    if (nother == 2) {
      const T *a = ofac[0] + static_cast<int64_t>(oinds[0][n]) * rank;
      const T *b = ofac[1] + static_cast<int64_t>(oinds[1][n]) * rank;
      if (sorted) {
        if (row != cur) {
          if (cur >= 0 && cur < odim) {
            T *o = out + cur * rank;
            for (int r = 0; r < rank; ++r) o[r] += acc[r];
          }
          for (int r = 0; r < rank; ++r) acc[r] = T(0);
          cur = row;
        }
        for (int r = 0; r < rank; ++r) acc[r] += v * a[r] * b[r];
      } else if (row >= 0 && row < odim) {
        T *o = out + row * rank;
        for (int r = 0; r < rank; ++r) o[r] += v * a[r] * b[r];
      }
    } else {
      for (int r = 0; r < rank; ++r) prod[r] = v;
      for (int j = 0; j < nother; ++j) {
        const T *u = ofac[j] + static_cast<int64_t>(oinds[j][n]) * rank;
        for (int r = 0; r < rank; ++r) prod[r] *= u[r];
      }
      if (sorted) {
        if (row != cur) {
          if (cur >= 0 && cur < odim) {
            T *o = out + cur * rank;
            for (int r = 0; r < rank; ++r) o[r] += acc[r];
          }
          for (int r = 0; r < rank; ++r) acc[r] = T(0);
          cur = row;
        }
        for (int r = 0; r < rank; ++r) acc[r] += prod[r];
      } else if (row >= 0 && row < odim) {
        T *o = out + row * rank;
        for (int r = 0; r < rank; ++r) o[r] += prod[r];
      }
    }
  }
  if (sorted && cur >= 0 && cur < odim) {
    T *o = out + cur * rank;
    for (int r = 0; r < rank; ++r) o[r] += acc[r];
  }
}

}  // namespace

extern "C" {

void mttkrp_f32(const int32_t *inds, const float *vals, int64_t nnz,
                int64_t nnz_pad, int nmodes, int mode,
                const float *const *factors, const int64_t *dims, int rank,
                float *out, int sorted) {
  mttkrp_impl<float>(inds, vals, nnz, nnz_pad, nmodes, mode, factors, dims,
                     rank, out, sorted);
}

void mttkrp_f64(const int32_t *inds, const double *vals, int64_t nnz,
                int64_t nnz_pad, int nmodes, int mode,
                const double *const *factors, const int64_t *dims, int rank,
                double *out, int sorted) {
  mttkrp_impl<double>(inds, vals, nnz, nnz_pad, nmodes, mode, factors, dims,
                      rank, out, sorted);
}

}  // extern "C"
