"""MTTKRP algorithm comparison harness (≙ src/bench.c + cmd_bench.c).

The reference's `splatt bench` times MTTKRP algorithms {splatt, csf,
giga, ttbox, coord} per mode with thread scaling (src/bench.c:50-436).
The TPU equivalents are the execution paths of
:mod:`splatt_tpu.ops.mttkrp`: {stream, sorted_onehot(+pallas),
privatized, scatter}; thread scaling has no analog (XLA owns the chip),
so the sweep axis is the path × engine matrix instead.
"""

from __future__ import annotations

import time
from typing import Dict, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from splatt_tpu.blocked import BlockedSparse
from splatt_tpu.config import BlockAlloc, Options, resolve_dtype
from splatt_tpu.coo import SparseTensor
from splatt_tpu.cpd import init_factors
from splatt_tpu.ops.mttkrp import (choose_impl, mttkrp_blocked,
                                   mttkrp_stream, mttkrp_ttbox)

ALGS = ("stream", "blocked", "blocked_pallas", "scatter", "ttbox",
        "native")


def _alg_plan(alg: str, layout, mode: int, dim: int, opts: Options):
    """Map a bench algorithm name to (path, impl) for mttkrp_blocked,
    or None when the config cannot run (privatized width over cap).
    Raises on unknown names — shared by timing and cross-checking."""
    if alg == "scatter":
        return (("sorted_scatter" if layout.mode == mode else "scatter"),
                "xla")
    if alg in ("blocked", "blocked_pallas"):
        path = "sorted_onehot" if layout.mode == mode else "privatized"
        if path == "privatized" and dim + 16 > opts.priv_cap:
            return None
        impl = ("xla" if alg == "blocked" else choose_impl(
            Options(use_pallas=True, val_dtype=opts.val_dtype)))
        return path, impl
    raise ValueError(f"unknown algorithm {alg!r}")


def _time_call(fn, warmup: int = 1, reps: int = 3) -> float:
    for _ in range(warmup):
        jax.block_until_ready(fn())
    t0 = time.perf_counter()
    for _ in range(reps):
        out = fn()
    jax.block_until_ready(out)
    return (time.perf_counter() - t0) / reps


def bench_mttkrp(tt: SparseTensor, rank: int = 16,
                 algs: Sequence[str] = ALGS,
                 opts: Optional[Options] = None,
                 reps: int = 3) -> Dict[str, List[float]]:
    """Per-mode wall clock for each algorithm; returns alg -> [sec/mode].

    ≙ the per-mode timing loop of src/bench.c:84-117.
    """
    opts = opts or Options(block_alloc=BlockAlloc.ALLMODE)
    dtype = resolve_dtype(opts, tt.vals.dtype)
    factors = init_factors(tt.dims, rank, opts.seed() or 1, dtype=dtype)
    inds = jnp.asarray(tt.inds)
    vals = jnp.asarray(tt.vals, dtype=dtype)
    results: Dict[str, List[float]] = {}

    needs_blocked = any(a not in ("stream", "ttbox") for a in algs)
    bs = BlockedSparse.from_coo(tt, opts) if needs_blocked else None

    for alg in algs:
        times: List[float] = []
        for mode in range(tt.nmodes):
            if alg == "stream":
                fn = lambda: mttkrp_stream(inds, vals, factors, mode,
                                           tt.dims[mode])
            elif alg == "ttbox":
                fn = lambda: mttkrp_ttbox(inds, vals, factors, mode,
                                          tt.dims[mode])
            elif alg == "native":
                from splatt_tpu.ops.mttkrp import _run_native, plan_mttkrp

                layout = bs.layout_for(mode)
                if plan_mttkrp(bs, factors, mode,
                               impl="native").engine != "native":
                    times.append(float("nan"))
                    continue
                fn = lambda: _run_native(layout, factors, mode)
            else:
                layout = bs.layout_for(mode)
                plan = _alg_plan(alg, layout, mode, tt.dims[mode], opts)
                if plan is None:
                    times.append(float("nan"))
                    continue
                path, impl = plan
                fn = lambda: mttkrp_blocked(layout, factors, mode,
                                            path=path, impl=impl)
            times.append(_time_call(fn, reps=reps))
        results[alg] = times
    return results


def crosscheck_mttkrp(tt: SparseTensor, rank: int = 16,
                      algs: Sequence[str] = ALGS,
                      opts: Optional[Options] = None) -> float:
    """Verify every algorithm computes the same MTTKRP: max deviation
    from the stream result over all modes, *relative* to the result's
    magnitude (summation-order noise scales with value magnitudes and
    nnz).  ≙ the role of the reference's `bench --write` dumps:
    cross-validating algorithm outputs rather than timing them."""
    import sys


    opts = opts or Options(block_alloc=BlockAlloc.ALLMODE)
    dtype = resolve_dtype(opts, tt.vals.dtype)
    factors = init_factors(tt.dims, rank, opts.seed() or 1, dtype=dtype)
    inds = jnp.asarray(tt.inds)
    vals = jnp.asarray(tt.vals, dtype=dtype)
    bs = BlockedSparse.from_coo(tt, opts)
    worst = 0.0
    skipped = 0
    for mode in range(tt.nmodes):
        ref = np.asarray(mttkrp_stream(inds, vals, factors, mode,
                                       tt.dims[mode]))
        for alg in algs:
            if alg == "stream":
                continue
            if alg == "ttbox":
                out = mttkrp_ttbox(inds, vals, factors, mode,
                                   tt.dims[mode])
            elif alg == "native":
                from splatt_tpu.ops.mttkrp import _run_native, plan_mttkrp

                layout = bs.layout_for(mode)
                out = (_run_native(layout, factors, mode)
                       if plan_mttkrp(bs, factors, mode,
                                      impl="native").engine == "native"
                       else None)
                if out is None:
                    skipped += 1
                    continue
            else:
                layout = bs.layout_for(mode)
                plan = _alg_plan(alg, layout, mode, tt.dims[mode], opts)
                if plan is None:
                    skipped += 1
                    continue
                path, impl = plan
                out = mttkrp_blocked(layout, factors, mode, path=path,
                                     impl=impl)
            scale = max(float(np.max(np.abs(ref))), 1.0)
            dev = float(np.max(np.abs(np.asarray(out) - ref))) / scale
            worst = max(worst, dev)
    if skipped:
        print(f"crosscheck: {skipped} (alg, mode) configs skipped "
              f"(privatized width over priv_cap)", file=sys.stderr)
    return worst


def format_bench(results: Dict[str, List[float]]) -> str:
    lines = []
    for alg, times in results.items():
        cols = "  ".join(f"mode{m}: {'  nan  ' if np.isnan(t) else f'{t:0.5f}'}"
                         for m, t in enumerate(times))
        total = np.nansum(times)
        lines.append(f"  {alg:<16s} {cols}  total: {total:0.5f}s")
    return "\n".join(lines)
