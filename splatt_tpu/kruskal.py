"""Kruskal tensor — the CPD output (≙ splatt_kruskal, include/splatt/structs.h:25-44)."""

from __future__ import annotations

import dataclasses
from typing import List, Tuple

import jax
import jax.numpy as jnp
import numpy as np


@jax.tree_util.register_dataclass
@dataclasses.dataclass
class KruskalTensor:
    """Rank-R factorization: ``X ≈ Σ_r λ_r · U1[:,r] ∘ ... ∘ Um[:,r]``.

    Attributes:
      factors: list of (dim_m, rank) factor matrices.
      lam: (rank,) column norms λ.
      fit: scalar quality-of-fit in [0, 1] (1 = exact).
    """

    factors: List[jax.Array]
    lam: jax.Array
    fit: jax.Array

    @property
    def rank(self) -> int:
        return int(self.factors[0].shape[1])

    @property
    def nmodes(self) -> int:
        return len(self.factors)

    @property
    def dims(self) -> Tuple[int, ...]:
        return tuple(int(f.shape[0]) for f in self.factors)

    def to_dense(self) -> np.ndarray:
        """Reconstruct the dense tensor — tests/small problems only."""
        rank = self.rank
        out = None
        for r in range(rank):
            term = np.asarray(self.lam)[r]
            vec = None
            for f in self.factors:
                col = np.asarray(f)[:, r]
                vec = col if vec is None else np.multiply.outer(vec, col)
            out = term * vec if out is None else out + term * vec
        return out

    def normsq(self) -> jax.Array:
        """⟨Z,Z⟩ = λᵀ (⊛_m UᵐᵀUᵐ) λ (≙ p_kruskal_norm, src/cpd.c:116-152)."""
        rank = self.factors[0].shape[1]
        had = jnp.outer(self.lam, self.lam)
        for f in self.factors:
            had = had * (f.T @ f)
        return jnp.sum(had)
