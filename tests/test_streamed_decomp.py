"""Streamed (bounded-memory) decomposition ≙ the reference's
root-streamed chunk distribution (src/mpi/mpi_io.c:587-648): chunked
passes must reproduce the in-RAM bucketing bit-for-bit."""

import os

import numpy as np
import pytest

from splatt_tpu.coo import SparseTensor
from splatt_tpu.parallel.common import bucket_scatter, streamed_bucket_scatter
from splatt_tpu.parallel.grid import GridDecomp


def _tensor(seed=0, nnz=5000, dims=(64, 40, 96), skew=False):
    rng = np.random.default_rng(seed)
    if skew:
        inds = np.stack([np.minimum(rng.zipf(1.3, nnz) - 1, d - 1)
                         for d in dims]).astype(np.int64)
    else:
        inds = np.stack([rng.integers(0, d, nnz)
                         for d in dims]).astype(np.int64)
    return SparseTensor(inds=inds, vals=rng.random(nnz), dims=dims)


def test_streamed_bucket_scatter_matches_dense():
    tt = _tensor()
    owner = (tt.inds[0] * 7 + tt.inds[1]) % 6
    b0, v0, c0, n0 = bucket_scatter(tt.inds, tt.vals, owner, 6, np.float32)
    b1, v1, c1, n1 = streamed_bucket_scatter(
        tt.inds, tt.vals, lambda ic, s: (ic[0] * 7 + ic[1]) % 6, 6,
        np.float32, chunk=701)
    assert c0 == c1
    np.testing.assert_array_equal(n0, n1)
    np.testing.assert_array_equal(b0, b1)
    np.testing.assert_array_equal(v0, v1)


def test_streamed_bucket_scatter_memmap_out(tmp_path):
    tt = _tensor(1)
    owner = tt.inds[2] % 4
    b0, v0, c0, n0 = bucket_scatter(tt.inds, tt.vals, owner, 4, np.float64)
    b1, v1, c1, n1 = streamed_bucket_scatter(
        tt.inds, tt.vals, lambda ic, s: ic[2] % 4, 4, np.float64,
        chunk=997, out_dir=str(tmp_path / "bk"))
    assert isinstance(b1, np.memmap) and isinstance(v1, np.memmap)
    assert c0 == c1
    np.testing.assert_array_equal(b0, np.asarray(b1))
    np.testing.assert_array_equal(v0, np.asarray(v1))


@pytest.mark.parametrize("balance", [False, True])
def test_streamed_grid_build_matches(balance):
    tt = _tensor(2, skew=balance)
    d0 = GridDecomp.build(tt, grid=(2, 2, 2), val_dtype=np.float32,
                          balance=balance, streamed=False)
    d1 = GridDecomp.build(tt, grid=(2, 2, 2), val_dtype=np.float32,
                          balance=balance, streamed=True, chunk=613)
    assert d0.cell_nnz == d1.cell_nnz
    assert d0.fill == d1.fill
    np.testing.assert_array_equal(d0.cell_counts, d1.cell_counts)
    np.testing.assert_array_equal(d0.inds_local, d1.inds_local)
    np.testing.assert_array_equal(d0.vals, d1.vals)
    if balance:
        assert d1.relabels is not None
        for r0, r1 in zip(d0.relabels, d1.relabels):
            np.testing.assert_array_equal(r0, r1)


def test_streamed_auto_on_memmap(tmp_path):
    from splatt_tpu.io import load_memmap, save

    tt = _tensor(3, nnz=2000)
    path = str(tmp_path / "t.bin")
    save(tt, path, binary=True)
    mm = load_memmap(path)
    from splatt_tpu.parallel.common import is_memmapped

    assert is_memmapped(mm.inds)
    d0 = GridDecomp.build(tt, grid=(2, 1, 2), val_dtype=np.float32)
    d1 = GridDecomp.build(mm, grid=(2, 1, 2), val_dtype=np.float32,
                          out_dir=str(tmp_path / "bk"))
    np.testing.assert_array_equal(d0.inds_local, np.asarray(d1.inds_local))
    np.testing.assert_array_equal(d0.vals, np.asarray(d1.vals))


def test_streamed_grid_cpd_end_to_end(tmp_path):
    """convert → memmap → streamed decompose → cpd (the 1.7B-nnz
    pipeline shape, at test scale)."""
    import jax.numpy as jnp

    from splatt_tpu import default_opts
    from splatt_tpu.io import load_memmap, save
    from splatt_tpu.parallel.grid import grid_cpd_als

    tt = _tensor(4, nnz=1500, dims=(24, 18, 30))
    path = str(tmp_path / "t.bin")
    save(tt, path, binary=True)
    mm = load_memmap(path)

    opts = default_opts()
    opts.random_seed = 11
    opts.max_iterations = 3
    res_mem = grid_cpd_als(mm, rank=3, grid=(2, 2, 2), opts=opts)
    res_ram = grid_cpd_als(tt, rank=3, grid=(2, 2, 2), opts=opts)
    assert abs(float(res_mem.fit) - float(res_ram.fit)) < 1e-6
    for a, b in zip(res_mem.factors, res_ram.factors):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-5, atol=1e-6)


def test_memmapped_blocked_engine_all_drivers(tmp_path):
    """A memmapped tensor keeps the OPTIMIZED blocked engine in all
    three distributed drivers (VERDICT r4 missing #3 — the reference
    runs mttkrp_csf per rank regardless of scale, mpi_cpd.c:714), with
    disk-backed layouts under out_dir, and matches the in-RAM stream
    oracle exactly."""
    from splatt_tpu import default_opts
    from splatt_tpu.io import load_memmap, save
    from splatt_tpu.parallel.coarse import coarse_cpd_als
    from splatt_tpu.parallel.grid import grid_cpd_als
    from splatt_tpu.parallel.sharded import sharded_cpd_als

    tt = _tensor(9, nnz=2000, dims=(24, 18, 30))
    path = str(tmp_path / "t.bin")
    save(tt, path, binary=True)
    mm = load_memmap(path)

    def opts():
        o = default_opts()
        o.random_seed = 5
        o.max_iterations = 3
        o.val_dtype = np.float64
        return o

    cases = [
        ("grid", lambda t, e, d: grid_cpd_als(
            t, 3, grid=(2, 2, 2), opts=opts(), local_engine=e, out_dir=d)),
        ("fine", lambda t, e, d: sharded_cpd_als(
            t, 3, opts=opts(), local_engine=e, out_dir=d)),
        ("coarse", lambda t, e, d: coarse_cpd_als(
            t, 3, opts=opts(), local_engine=e, out_dir=d)),
    ]
    for label, run in cases:
        oracle = run(tt, "stream", None)
        d = str(tmp_path / f"{label}_bk")
        got = run(mm, None, d)              # auto must pick blocked
        assert float(got.fit) == pytest.approx(float(oracle.fit),
                                               abs=1e-9), label
        for a, b in zip(oracle.factors, got.factors):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       atol=1e-8, err_msg=label)
        # the sorted layouts really are disk-backed memmaps
        import glob
        assert glob.glob(os.path.join(d, "**", "linds.npy"),
                         recursive=True), label


def test_memmapped_without_scratch_stays_lean(tmp_path):
    """Auto engine selection: a memmapped tensor WITHOUT out_dir keeps
    the stream engine (the sorted copies would be a second O(nnz)
    in-RAM allocation on a beyond-RAM input); with out_dir it upgrades
    to blocked (disk-backed)."""
    from unittest import mock

    from splatt_tpu import default_opts
    from splatt_tpu.io import load_memmap, save
    from splatt_tpu.parallel import common
    from splatt_tpu.parallel.coarse import coarse_cpd_als
    from splatt_tpu.parallel.grid import grid_cpd_als
    from splatt_tpu.parallel.sharded import sharded_cpd_als

    tt = _tensor(2, nnz=800, dims=(16, 12, 20))
    path = str(tmp_path / "t.bin")
    save(tt, path, binary=True)

    def opts():
        o = default_opts()
        o.random_seed = 1
        o.max_iterations = 2
        return o

    for label, run in (
            ("grid", lambda t, d: grid_cpd_als(t, 2, opts=opts(),
                                               out_dir=d)),
            ("fine", lambda t, d: sharded_cpd_als(t, 2, opts=opts(),
                                                  out_dir=d)),
            ("coarse", lambda t, d: coarse_cpd_als(t, 2, opts=opts(),
                                                   out_dir=d))):
        mm = load_memmap(path)
        with mock.patch.object(common, "blocked_buckets",
                               side_effect=AssertionError(
                                   "in-RAM sort on memmapped-no-scratch")
                               ) as blk, \
             mock.patch.object(common, "streamed_blocked_buckets",
                               side_effect=AssertionError(
                                   "streamed sort without scratch")):
            run(mm, None)       # lean: neither sort path may run
        d = str(tmp_path / f"{label}_s")
        res = run(mm, d)        # disk-backed: blocked engine
        assert np.isfinite(float(res.fit)), label


def test_auto_local_engine_policy(tmp_path):
    """The shared policy table: blocked for in-RAM tensors regardless
    of scratch dir; memmapped tensors need the scratch dir to upgrade."""
    from splatt_tpu.io import load_memmap, save
    from splatt_tpu.parallel.common import auto_local_engine

    tt = _tensor(1, nnz=300, dims=(8, 6, 10))
    path = str(tmp_path / "t.bin")
    save(tt, path, binary=True)
    mm = load_memmap(path)
    assert auto_local_engine(tt, None) == "blocked"
    assert auto_local_engine(tt, "/scratch") == "blocked"
    assert auto_local_engine(mm, None) == "stream"
    assert auto_local_engine(mm, "/scratch") == "blocked"


def test_build_bucket_layout_dispatch(tmp_path):
    """ONE dispatch point: memmapped buckets take the streamed counting
    sort (disk-backed outputs), in-RAM buckets the argsort build — same
    results either way."""
    from splatt_tpu.parallel.common import (build_bucket_layout,
                                            bucket_scatter,
                                            streamed_bucket_scatter)

    tt = _tensor(5, nnz=600, dims=(12, 10, 14))
    owner = tt.inds[0] % 3
    b0, v0, _, n0 = bucket_scatter(tt.inds, tt.vals, owner, 3, np.float64)
    b1, v1, _, n1 = streamed_bucket_scatter(
        tt.inds, tt.vals, lambda ic, s: ic[0] % 3, 3, np.float64,
        chunk=101, out_dir=str(tmp_path / "bk"))
    ram = build_bucket_layout(b0, v0, n0, 1, tt.dims[1], 128)
    disk = build_bucket_layout(b1, v1, n1, 1, tt.dims[1], 128,
                               out_dir=str(tmp_path / "lay"), chunk=97)
    assert not isinstance(ram[0], np.memmap)
    assert isinstance(disk[0], np.memmap)
    np.testing.assert_array_equal(ram[0], np.asarray(disk[0]))
    np.testing.assert_array_equal(ram[1], np.asarray(disk[1]))
    np.testing.assert_array_equal(ram[2], disk[2])
    assert ram[3:] == disk[3:]
