"""End-to-end integration journeys — the full user paths in one test
each, crossing every subsystem seam (IO → format → compute → output)."""

import numpy as np
import pytest

import splatt_tpu
from splatt_tpu import native
from splatt_tpu.config import BlockAlloc, Options, Verbosity
from splatt_tpu.io import load_memmap, save
from splatt_tpu.kruskal import KruskalTensor
from splatt_tpu.parallel import distributed_cpd_als
from tests import gen


def _opts(**kw):
    kw.setdefault("random_seed", 42)
    kw.setdefault("verbosity", Verbosity.NONE)
    kw.setdefault("val_dtype", np.float64)
    return Options(**kw)


def test_journey_text_to_factors(tmp_path):
    """text file → load → check → blocked → cpd → save → reload →
    reconstruct."""
    tt0 = gen.fixture_tensor("med")
    path = str(tmp_path / "t.tns")
    save(tt0, path)

    tt = splatt_tpu.load(path)
    assert tt.count_duplicates() == 0
    bs = splatt_tpu.BlockedSparse.from_coo(tt, _opts(nnz_block=256))
    out = splatt_tpu.cpd_als(bs, rank=5, opts=_opts(max_iterations=8))
    out.save(str(tmp_path / "factors"))
    back = KruskalTensor.load(str(tmp_path / "factors"), nmodes=tt.nmodes)
    # the reloaded model reconstructs identically to the computed one
    np.testing.assert_allclose(back.to_dense(), out.to_dense(), atol=1e-10)
    # and approximates the data no worse than a fit-consistent bound
    rel = (np.linalg.norm(back.to_dense() - tt.to_dense())
           / np.linalg.norm(tt.to_dense()))
    assert rel == pytest.approx(1.0 - float(out.fit), abs=1e-6)


@pytest.mark.skipif(not native.available(),
                    reason="native runtime not built")
def test_journey_streamed_binary_to_distributed(tmp_path):
    """beyond-RAM route: text → streamed binary → memmap load →
    distributed grid CPD → factors match the in-memory route."""
    tt0 = gen.fixture_tensor("med4")
    text = str(tmp_path / "t.tns")
    save(tt0, text)
    binary = str(tmp_path / "t.bin")
    assert native.stream_to_bin(text, binary)

    mm = load_memmap(binary)
    assert isinstance(mm.inds.base, np.memmap)

    from splatt_tpu.cpd import init_factors

    opts = _opts(max_iterations=5)
    init = init_factors(mm.dims, 4, opts.seed(), dtype=np.float64)
    via_mm = distributed_cpd_als(mm, rank=4, opts=opts, init=init)
    via_ram = splatt_tpu.cpd_als(tt0, rank=4, opts=opts, init=init)
    assert float(via_mm.fit) == pytest.approx(float(via_ram.fit), abs=1e-8)
    for a, b in zip(via_mm.factors, via_ram.factors):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-6)
