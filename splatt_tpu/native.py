"""ctypes bindings to the native host runtime (native/splatt_native.cpp).

The reference implements its host-side hot paths (text parsing
src/io.c:62-108, sorting src/sort.c) in C; this module provides the
same for splatt-tpu: a buffered single-pass `.tns` parser and a
bucket+std::sort permutation used by the blocked-layout compiler.

The shared library is built on first use (g++ is assumed present, as on
the target image); every entry point degrades gracefully — callers fall
back to the numpy implementations when the library is unavailable.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
from pathlib import Path
from typing import Optional, Sequence, Tuple

import numpy as np

_SO_PATH = Path(__file__).resolve().parent / "_native.so"
_SRC_PATH = Path(__file__).resolve().parent.parent / "native" / "splatt_native.cpp"

_lib: Optional[ctypes.CDLL] = None
_load_failed = False


def _build() -> bool:
    base = ["g++", "-O3", "-shared", "-fPIC", "-std=c++17",
            "-o", str(_SO_PATH), str(_SRC_PATH)]
    # -march=native vectorizes the MTTKRP rank loops; retry without it
    # for toolchains that reject the flag
    for flags in (base[:2] + ["-march=native"] + base[2:], base):
        try:
            subprocess.run(flags, check=True, capture_output=True,
                           timeout=300)
            return True
        except (OSError, subprocess.SubprocessError):
            continue
    return False


def _load() -> Optional[ctypes.CDLL]:
    global _lib, _load_failed
    if _lib is not None:
        return _lib
    if _load_failed:
        return None
    if not _SO_PATH.exists() or (
            _SRC_PATH.exists()
            and _SRC_PATH.stat().st_mtime > _SO_PATH.stat().st_mtime):
        if not _SRC_PATH.exists() or not _build():
            _load_failed = True
            return None
    try:
        lib = ctypes.CDLL(str(_SO_PATH))
    except OSError:
        _load_failed = True
        return None
    lib.tns_open.restype = ctypes.c_void_p
    lib.tns_open.argtypes = [ctypes.c_char_p]
    lib.tns_rows.restype = ctypes.c_int64
    lib.tns_rows.argtypes = [ctypes.c_void_p]
    lib.tns_cols.restype = ctypes.c_int
    lib.tns_cols.argtypes = [ctypes.c_void_p]
    lib.tns_fill.restype = ctypes.c_int
    lib.tns_fill.argtypes = [ctypes.c_void_p, ctypes.c_void_p,
                             ctypes.c_void_p]
    lib.tns_close.argtypes = [ctypes.c_void_p]
    lib.sort_perm.restype = ctypes.c_int
    lib.sort_perm.argtypes = [ctypes.c_void_p, ctypes.c_int64, ctypes.c_int,
                              ctypes.c_void_p, ctypes.c_void_p,
                              ctypes.c_void_p]
    lib.tns_stream_to_bin.restype = ctypes.c_int
    lib.tns_stream_to_bin.argtypes = [ctypes.c_char_p, ctypes.c_char_p]
    for name in ("mttkrp_f32", "mttkrp_f64"):
        fn = getattr(lib, name)
        fn.restype = None
        fn.argtypes = [ctypes.c_void_p, ctypes.c_void_p, ctypes.c_int64,
                       ctypes.c_int64, ctypes.c_int, ctypes.c_int,
                       ctypes.POINTER(ctypes.c_void_p), ctypes.c_void_p,
                       ctypes.c_int, ctypes.c_void_p, ctypes.c_int]
    _lib = lib
    return lib


def available() -> bool:
    return _load() is not None


def parse_tns(path: str) -> Optional[Tuple[np.ndarray, np.ndarray]]:
    """Parse a coordinate text file; None → caller should fall back."""
    lib = _load()
    if lib is None:
        return None
    h = lib.tns_open(os.fsencode(path))
    if not h:
        return None
    try:
        nrows = lib.tns_rows(h)
        ncols = lib.tns_cols(h)
        nmodes = ncols - 1
        inds = np.empty((nmodes, nrows), dtype=np.int64)
        vals = np.empty(nrows, dtype=np.float64)  # splint: ignore[SPL005] C++ ABI: the shared library exports an f64 ingest buffer
        rc = lib.tns_fill(h, inds.ctypes.data_as(ctypes.c_void_p),
                          vals.ctypes.data_as(ctypes.c_void_p))
        if rc != 0:
            raise ValueError(f"{path}: malformed tensor file "
                             f"(native parser rc={rc})")
        return inds, vals
    finally:
        lib.tns_close(h)


def stream_to_bin(src: str, dst: str) -> bool:
    """Two-pass streaming text→binary conversion with ~8MB memory
    (for tensors larger than RAM).  False → caller should fall back to
    the in-memory path; raises on malformed input.
    """
    lib = _load()
    if lib is None:
        return False
    rc = lib.tns_stream_to_bin(os.fsencode(src), os.fsencode(dst))
    if rc != 0:
        # never leave a partial binary with a valid header behind
        try:
            os.unlink(dst)
        except OSError:
            pass
        if rc in (1, 5):
            raise OSError(f"cannot open {src if rc == 1 else dst}")
        if rc in (6, 7):
            raise OSError(
                f"{dst}: write failed during conversion (disk full or "
                f"I/O error, rc={rc})")
        raise ValueError(f"{src}: malformed tensor file "
                         f"(stream converter rc={rc})")
    return True


def sort_perm(inds: np.ndarray, dims: Sequence[int],
              mode_order: Sequence[int]) -> Optional[np.ndarray]:
    """Lexicographic nnz permutation by mode_order; None → fall back."""
    lib = _load()
    if lib is None:
        return None
    inds = np.ascontiguousarray(inds, dtype=np.int64)
    nmodes, nnz = inds.shape
    order = list(mode_order)
    # the C comparator walks mode_order[1..nmodes); a partial order has
    # different semantics (remaining modes unordered) — numpy handles it
    if len(order) != nmodes or sorted(order) != list(range(nmodes)):
        return None
    dims_arr = np.asarray(dims, dtype=np.int64)
    order_arr = np.asarray(order, dtype=np.int32)
    perm = np.empty(nnz, dtype=np.int64)
    rc = lib.sort_perm(inds.ctypes.data_as(ctypes.c_void_p),
                       ctypes.c_int64(nnz), ctypes.c_int(nmodes),
                       dims_arr.ctypes.data_as(ctypes.c_void_p),
                       order_arr.ctypes.data_as(ctypes.c_void_p),
                       perm.ctypes.data_as(ctypes.c_void_p))
    if rc != 0:
        return None
    return perm


def mttkrp(inds: np.ndarray, vals: np.ndarray, factors, mode: int,
           dims: Sequence[int], sorted_by_mode: bool,
           nnz: int) -> Optional[np.ndarray]:
    """Native single-core MTTKRP over a blocked layout's arrays
    (≙ the reference's register-blocked fiber loops, src/mttkrp.c:427-463
    — re-designed as a flat pass with run accumulation).

    inds: (nmodes, nnz_pad) int32; vals: (nnz_pad,) f32/f64; factors:
    per-mode (dims[k], rank) arrays matching vals' dtype.  `nnz` is the
    true nonzero count and is REQUIRED: padding entries trail the sort
    and carry a sentinel index equal to `dim` on the sort-mode row —
    out of range for the factor gather — so a loop bound that includes
    them is undefined behavior (the round-2 nondeterminism bug).  Pass
    nnz == inds.shape[1] only for genuinely unpadded arrays.
    None → caller should fall back to the XLA engines.
    """
    lib = _load()
    if lib is None:
        return None
    vals = np.ascontiguousarray(vals)
    dtype = vals.dtype
    if dtype == np.float32:  # splint: ignore[SPL005] C++ ABI gate: the library exports exactly f32/f64 kernels
        fn = lib.mttkrp_f32
    elif dtype == np.float64:  # splint: ignore[SPL005] C++ ABI gate: the library exports exactly f32/f64 kernels
        fn = lib.mttkrp_f64
    else:
        return None
    if any(np.asarray(f).dtype != dtype for f in factors):
        return None  # mixed dtypes: let the XLA paths apply promotion
    inds = np.ascontiguousarray(inds, dtype=np.int32)
    nmodes, nnz_pad = inds.shape
    if nmodes > 8:
        return None
    facs = [np.ascontiguousarray(f, dtype=dtype) for f in factors]
    rank = facs[0].shape[1]
    fac_ptrs = (ctypes.c_void_p * nmodes)(
        *[f.ctypes.data_as(ctypes.c_void_p).value for f in facs])
    dims_arr = np.asarray(dims, dtype=np.int64)
    out = np.zeros((dims[mode], rank), dtype=dtype)
    fn(inds.ctypes.data_as(ctypes.c_void_p),
       vals.ctypes.data_as(ctypes.c_void_p),
       ctypes.c_int64(min(nnz, nnz_pad)), ctypes.c_int64(nnz_pad),
       ctypes.c_int(nmodes), ctypes.c_int(mode),
       fac_ptrs, dims_arr.ctypes.data_as(ctypes.c_void_p),
       ctypes.c_int(rank), out.ctypes.data_as(ctypes.c_void_p),
       ctypes.c_int(1 if sorted_by_mode else 0))
    return out
