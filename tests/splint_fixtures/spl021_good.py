"""SPL021 good: persist first, then advance, straight-line — the
stamp covers exactly the content just written, on every path."""


def advance_generation(ckpt_dir, model, factors, lam):
    return 1  # stand-in for splatt_tpu.predict.advance_generation


def _save_checkpoint(path, factors, lam, it, fit):
    pass  # stand-in for splatt_tpu.cpd._save_checkpoint


def _save_model_tensor(path, tt, applied):
    pass  # stand-in for splatt_tpu.serve._save_model_tensor


def commit_update(path, ckpt_dir, model, tt, factors, lam, applied):
    # the commit protocol in order: checkpoint, model tensor, THEN the
    # generation advance — no early return between persist and stamp
    _save_checkpoint(path, factors, lam, 0, 0.0)
    _save_model_tensor(path + ".model", tt, applied)
    return advance_generation(ckpt_dir, model, factors, lam)
