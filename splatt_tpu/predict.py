"""Generation-fenced prediction plane (docs/predict.md).

The model store (docs/batched.md: ``ckpt/<model>.npz`` + the merged
``.model.npz``) is write-mostly until something READS it — and the
read path is where the robustness contract actually bites: under
concurrent ``update`` commits, replica SIGKILLs and corrupt
checkpoints, a prediction must never be computed from a stale, torn
or half-merged model.  This module is that contract:

Model generations
    Every model-store commit atomically advances a per-model
    generation stamp — a monotonic ordinal plus the factor-content
    sha — published via :func:`durable.publish_json` beside the
    factors (``<model>.gen.json``, previous generation kept as
    ``.bak``).  The advance is serialized across processes by a flock
    sidecar and is IDEMPOTENT: re-committing bit-identical factors
    returns the current ordinal without advancing, so a replayed
    commit cannot invalidate readers for nothing.

Fenced reads
    :func:`load_model_generation` only returns factors whose content
    sha verifies against a stamp (cpd.load_checkpoint_resilient_gen
    walks the (checkpoint, stamp) pairs newest-first); a torn pair
    degrades classified (``model_torn``) to the ``.bak`` generation,
    and when nothing survives the fence the caller REFUSES — a
    refusal, never garbage.

Hot-factor cache
    :class:`HotFactorCache` keys entries by ``(model, generation)``:
    an update commit invalidates by generation ADVANCE, never by
    deletion, so an in-flight predict pinned at admission finishes on
    its generation bit-exactly.  LRU-bounded per replica
    (SPLATT_PREDICT_CACHE_MAX); a poisoned lookup (the
    ``predict.cache`` fault site) degrades to the direct fenced read.

The math itself is the easy part (GenTen's reconstruction use-case):
an entry estimate is ``x̂(i₁..i_N) = Σ_r λ_r Π_m U_m[i_m, r]`` and a
top-k slice scan fixes all modes but one, reducing to one tall
``(I_mode × R) @ (R,)`` matmul — MXU-shaped on device, and small
enough host-side that numpy keeps replies bit-exact and deterministic
(the property the pinned-generation race test asserts).
"""

from __future__ import annotations

import collections
import json
import os
import threading
import time
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

try:
    import fcntl as _fcntl
except ImportError:  # non-POSIX: advances degrade to in-process safety
    _fcntl = None

from splatt_tpu import trace
from splatt_tpu.utils import faults
from splatt_tpu.utils.durable import publish_json


# -- generation stamps -------------------------------------------------------

def stamp_path(ckpt_dir: str, model: str) -> str:
    """The generation stamp published beside the model's factors:
    ``<ckpt_dir>/<model>.gen.json`` (previous generation at ``.bak``)."""
    return os.path.join(str(ckpt_dir), f"{model}.gen.json")


def read_stamp(path: str) -> Optional[dict]:
    """Parse one generation stamp -> ``{"model","gen","sha","ts"}``,
    or None.  A MISSING stamp is silently None (the model predates the
    fence or was never committed); an unreadable/garbage one is a torn
    artifact and degrades classified with a ``model_torn`` event —
    the caller falls back a generation or refuses, never guesses."""
    from splatt_tpu import resilience

    try:
        with open(path, "r") as f:
            obj = json.load(f)
        if not isinstance(obj, dict) or "gen" not in obj \
                or not obj.get("sha"):
            raise ValueError(f"stamp {path} missing gen/sha fields")
        obj["gen"] = int(obj["gen"])
        return obj
    except FileNotFoundError:
        return None
    except Exception as e:
        resilience.run_report().add(
            "model_torn", path=path, piece="generation-stamp",
            failure_class=resilience.classify_failure(e).value,
            error=str(e)[:200])
        return None


def advance_generation(ckpt_dir: str, model: str, factors,
                       lam) -> int:
    """Atomically advance `model`'s generation stamp to cover the
    factor content just committed, returning the new (or, idempotent,
    current) ordinal.

    Serialized across replicas by a flock sidecar so two concurrent
    commits cannot both mint ordinal N+1; the previous stamp is kept
    as ``.bak`` (the rollback generation readers degrade to).  A
    bit-identical re-commit — same content sha — returns the current
    ordinal WITHOUT advancing: a replayed/adopted commit must not
    invalidate every reader's cache for nothing.  The
    ``model.generation`` fault site fires before any write: a failed
    advance raises, the calling commit aborts classified, and the old
    generation keeps serving (the stamp never moved).
    """
    from splatt_tpu import resilience
    from splatt_tpu.cpd import factor_content_sha

    spath = stamp_path(ckpt_dir, model)
    lockf = open(spath + ".lock", "a+")
    try:
        if _fcntl is not None:
            _fcntl.flock(lockf.fileno(), _fcntl.LOCK_EX)
        faults.maybe_fail("model.generation")
        sha = factor_content_sha(factors, lam)
        cur = read_stamp(spath)
        if cur is not None and cur.get("sha") == sha:
            return int(cur["gen"])
        gen = int(cur["gen"]) + 1 if cur is not None else 1
        if cur is not None:
            # keep the outgoing generation as the rollback stamp
            publish_json(spath + ".bak", cur)
        publish_json(spath, {"model": str(model), "gen": gen,
                             "sha": sha, "ts": time.time()})
        resilience.run_report().add(
            "model_generation_advanced", model=str(model), gen=gen,
            sha=sha[:12])
        return gen
    finally:
        if _fcntl is not None:
            _fcntl.flock(lockf.fileno(), _fcntl.LOCK_UN)
        lockf.close()


def current_generation(ckpt_dir: str, model: str) -> int:
    """The model's committed generation ordinal right now (0 = no
    intact stamp) — what a predict pins at admission.  Reads the
    stamp only; the factors are verified against it at serve time."""
    cur = read_stamp(stamp_path(ckpt_dir, model))
    return int(cur["gen"]) if cur is not None else 0


def load_model_generation(ckpt_dir: str, model: str,
                          expect_reorder: Optional[str] = None
                          ) -> Optional[dict]:
    """The direct fenced read: load the newest generation of `model`
    whose factor content verifies against a stamp.

    Returns ``{"factors": [np arrays], "lam": np array, "gen": int,
    "sha": str}`` or None (REFUSE — no intact generation).  The
    ``predict.read`` fault site covers the whole read; torn pairs
    degrade through cpd.load_checkpoint_resilient_gen's ``model_torn``
    classification down to the ``.bak`` generation.  A checkpoint
    with NO stamp at all is not servable: "never stale or torn" wins
    over "best effort", and the first commit through
    :func:`advance_generation` makes it servable."""
    from splatt_tpu import resilience
    from splatt_tpu.cpd import load_checkpoint_resilient_gen

    faults.maybe_fail("predict.read")
    ckpt = os.path.join(str(ckpt_dir), f"{model}.npz")
    spath = stamp_path(ckpt_dir, model)
    stamp = read_stamp(spath)
    bak = read_stamp(spath + ".bak")
    if stamp is None and bak is None:
        if os.path.exists(ckpt):
            resilience.run_report().add(
                "model_torn", path=ckpt, piece="no-generation-stamp",
                failure_class="permanent",
                error="checkpoint exists but no generation stamp "
                      "verifies it; refusing to serve unfenced factors")
        return None
    out = load_checkpoint_resilient_gen(ckpt, stamp, bak,
                                        expect_reorder=expect_reorder)
    if out is None:
        return None
    factors, lam, _it, _fit, gen, sha = out
    return {"factors": [np.asarray(U) for U in factors],
            "lam": np.asarray(lam), "gen": int(gen), "sha": sha}


# -- hot-factor cache --------------------------------------------------------

class HotFactorCache:
    """In-replica hot factors keyed by ``(model, generation)``.

    The invalidation protocol is the whole design: an update commit
    advances the generation, so new predicts key a NEW entry and the
    old one ages out by LRU — it is never deleted under a reader, so
    an in-flight predict pinned to the old generation still finishes
    on it bit-exactly.  ``max_entries <= 0`` disables storage (every
    lookup is a recorded miss and predicts take the direct read)."""

    def __init__(self, max_entries: int = 8):
        self.max_entries = int(max_entries)
        self._lock = threading.Lock()
        self._entries: "collections.OrderedDict[Tuple[str, int], dict]" \
            = collections.OrderedDict()

    def get(self, model: str, gen: int) -> Optional[dict]:
        """One consult (the ``predict.cache`` fault site; a raised
        fault is the poisoned-cache drill — callers degrade to the
        direct fenced read).  Records hit/miss into
        splatt_predict_cache_total."""
        faults.maybe_fail("predict.cache")
        with self._lock:
            entry = self._entries.get((str(model), int(gen)))
            if entry is not None:
                self._entries.move_to_end((str(model), int(gen)))
        trace.metric_inc("splatt_predict_cache_total",
                         outcome="hit" if entry is not None else "miss")
        return entry

    def put(self, model: str, gen: int, entry: dict) -> None:
        if self.max_entries <= 0:
            return
        with self._lock:
            self._entries[(str(model), int(gen))] = entry
            self._entries.move_to_end((str(model), int(gen)))
            while len(self._entries) > self.max_entries:
                self._entries.popitem(last=False)

    def __len__(self) -> int:
        with self._lock:
            return len(self._entries)


# -- the math ----------------------------------------------------------------

def reconstruct_entries(factors: Sequence, lam,
                        coords) -> np.ndarray:
    """Batched entry reconstruction: for each coordinate row
    ``(i₁..i_N)`` return ``x̂ = Σ_r λ_r Π_m U_m[i_m, r]``.

    `coords` is ``(B, nmodes)`` integer indices.  Host-side numpy —
    a (B × R) gather-product per mode then one ``@ λ`` contraction —
    keeps replies deterministic and bit-exact across replays (the
    generation-fence tests depend on it); the same shape maps to an
    MXU matmul on device when B grows past host comfort."""
    fs = [np.asarray(U) for U in factors]
    lam = np.asarray(lam)
    coords = np.asarray(coords)
    if coords.ndim == 1:
        coords = coords[None, :]
    coords = coords.astype(np.int64)
    if coords.shape[1] != len(fs):
        raise ValueError(
            f"coords have {coords.shape[1]} modes, model has {len(fs)}")
    for m, U in enumerate(fs):
        col = coords[:, m]
        if col.size and (col.min() < 0 or col.max() >= U.shape[0]):
            raise ValueError(
                f"coordinate out of range for mode {m} "
                f"(dim {U.shape[0]})")
    rows = np.ones((coords.shape[0], fs[0].shape[1]),
                   dtype=np.result_type(*[U.dtype for U in fs]))
    for m, U in enumerate(fs):
        rows = rows * U[coords[:, m], :]
    return rows @ lam


def top_k_slice(factors: Sequence, lam, fixed: Dict[int, int],
                mode: int, k: int) -> Tuple[np.ndarray, np.ndarray]:
    """Top-k scan of one slice: fix every mode but `mode` at the
    indices in `fixed`, score all I_mode candidates, return the k
    best ``(indices, scores)`` in descending score order.

    The rank-R reduction ``w_r = λ_r Π_fixed U_m[i_m, r]`` collapses
    the fixed modes to one weight vector, and the scan is the tall
    matmul ``U_mode @ w`` — the MXU-friendly shape the paper's
    lineage (GenTen) calls out for completion workloads."""
    fs = [np.asarray(U) for U in factors]
    mode = int(mode)
    if not 0 <= mode < len(fs):
        raise ValueError(f"mode {mode} out of range for {len(fs)} modes")
    want = set(range(len(fs))) - {mode}
    got = {int(m) for m in fixed}
    if got != want:
        raise ValueError(
            f"fixed must pin exactly the non-target modes "
            f"{sorted(want)}, got {sorted(got)}")
    w = np.asarray(lam).astype(np.result_type(*[U.dtype for U in fs]),
                               copy=True)
    for m in sorted(want):
        idx = int(fixed[m])
        if not 0 <= idx < fs[m].shape[0]:
            raise ValueError(
                f"coordinate out of range for mode {m} "
                f"(dim {fs[m].shape[0]})")
        w = w * fs[m][idx, :]
    scores = fs[mode] @ w
    k = max(1, min(int(k), scores.shape[0]))
    part = np.argpartition(-scores, k - 1)[:k]
    order = part[np.argsort(-scores[part], kind="stable")]
    return order.astype(np.int64), scores[order]
