"""SPL014 good: every shared-structure write holds the owning lock —
directly, or inside a ``_locked``-suffix helper whose callers hold it
(the caller-owns-the-lock convention, docs/static-analysis.md)."""

import threading

_TABLE = {}
_TABLE_LOCK = threading.Lock()


class Server:
    def __init__(self):
        self._lock = threading.Lock()
        self._jobs = {}  # construction: the object is not yet shared

    def accept(self, jid, spec):
        with self._lock:
            self._jobs[jid] = {"spec": spec, "state": "accepted"}

    def accept_many(self, specs):
        with self._lock:
            for jid, spec in specs.items():
                self._apply_locked(jid, spec)

    def _apply_locked(self, jid, spec):
        # the _locked suffix documents (and SPL014 trusts) that every
        # caller already holds self._lock
        self._jobs[jid] = {"spec": spec, "state": "accepted"}


def remember(key, value):
    with _TABLE_LOCK:
        _TABLE[key] = value
