"""SPL023 bad: a durable-root write with no fsync barrier — the
writer reports success, the process dies, the post-crash reader sees
nothing (or a torn prefix)."""

import os


def append_journal_raw(root, line):
    # hand-rolled journal append: write + flush reaches the page
    # cache, not the platter — a crash can lose the record a replay
    # depends on
    journal_path = os.path.join(root, "journal.jsonl")
    with open(journal_path, "a") as f:
        f.write(line + "\n")
        f.flush()
