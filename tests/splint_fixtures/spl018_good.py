"""SPL018 good: the sanctioned token + try/finally reset idiom
(resilience.scope / faults.scoped / trace.enabling all have this
shape) — the scoped state is restored on every exit path."""

import contextlib
import contextvars

_SCOPE = contextvars.ContextVar("scope", default=None)


def run_job(job_id, body):
    token = _SCOPE.set(job_id)
    try:
        return body()
    finally:
        _SCOPE.reset(token)


@contextlib.contextmanager
def scope(job_id):
    token = _SCOPE.set(job_id)
    try:
        yield job_id
    finally:
        _SCOPE.reset(token)
