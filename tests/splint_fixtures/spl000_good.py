"""SPL000 good: justified pragmas, inline and full-line."""

import jax.numpy as jnp

A = jnp.zeros(4, jnp.float32)  # splint: ignore[SPL005] fixture constant

# splint: ignore[SPL005] full-line pragma with a reason covers the
# next code line, multi-line justification comments included
B = jnp.ones(4, jnp.float64)
