"""Multi-device tests on the 8-virtual-CPU-device mesh.

≙ the reference's `mpirun -np 4 / -np 7` single-machine tests
(scripts/mpi_test.sh) — including the deliberately-awkward device count
(row/nnz counts not divisible by the mesh) via padding.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from splatt_tpu.config import Options, Verbosity
from splatt_tpu.coo import SparseTensor
from splatt_tpu.cpd import cpd_als, init_factors
from splatt_tpu.parallel.mesh import auto_grid, make_mesh
from splatt_tpu.parallel.sharded import (shard_factors, shard_nnz,
                                         sharded_cpd_als, sharded_mttkrp)
from tests import gen
from tests.test_mttkrp import np_mttkrp


def _opts(**kw):
    kw.setdefault("random_seed", 42)
    kw.setdefault("verbosity", Verbosity.NONE)
    kw.setdefault("val_dtype", np.float64)
    return Options(**kw)


def test_eight_devices_available():
    assert len(jax.devices()) == 8


def test_auto_grid():
    """≙ p_get_best_mpi_dim (src/mpi/mpi_io.c:537-574)."""
    assert sorted(auto_grid(8, (100, 100, 100))) == [1, 2, 4] \
        or sorted(auto_grid(8, (100, 100, 100))) == [2, 2, 2]
    g = auto_grid(12, (1000, 10, 10))
    assert np.prod(g) == 12
    assert g[0] >= 4  # longest mode gets the most devices
    assert auto_grid(7, (5, 5)) in ((7, 1), (1, 7))
    assert np.prod(auto_grid(1, (3, 3, 3))) == 1


@pytest.mark.parametrize("ndev", [4, 8])
def test_sharded_mttkrp_matches_oracle(ndev):
    tt = gen.fixture_tensor("med")
    mesh = make_mesh(n_devices=ndev)
    rank = 8
    rng = np.random.default_rng(3)
    factors_host = [jnp.asarray(rng.random((d, rank))) for d in tt.dims]
    inds, vals = shard_nnz(tt, mesh, val_dtype=np.float64)
    factors = shard_factors(factors_host, tt.dims, mesh)
    for mode in range(tt.nmodes):
        got = np.asarray(sharded_mttkrp(inds, vals, factors, mode, mesh))
        want = np_mttkrp(tt, factors_host, mode)
        np.testing.assert_allclose(got[:tt.dims[mode]], want, atol=1e-10)
        # padded rows receive nothing
        np.testing.assert_allclose(got[tt.dims[mode]:], 0.0, atol=0)


def test_sharded_cpd_matches_single_device():
    """Same seed → same fit on 1 device and 8 devices (rank-count
    invariance, ≙ mpi_mat_rand seed stability)."""
    tt = gen.fixture_tensor("med")
    opts = _opts(max_iterations=8)
    init = init_factors(tt.dims, 6, opts.seed(), dtype=jnp.float64)
    single = cpd_als(tt, rank=6, opts=opts, init=init)
    mesh = make_mesh(n_devices=8)
    multi = sharded_cpd_als(tt, rank=6, mesh=mesh, opts=opts, init=init)
    assert float(multi.fit) == pytest.approx(float(single.fit), abs=1e-8)
    for a, b in zip(single.factors, multi.factors):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-6)


def test_sharded_cpd_device_count_invariance():
    """Fit identical across device counts (4 vs 8)."""
    tt = gen.fixture_tensor("med4")
    opts = _opts(max_iterations=6)
    init = init_factors(tt.dims, 4, opts.seed(), dtype=jnp.float64)
    fits = []
    for ndev in (4, 8):
        mesh = make_mesh(n_devices=ndev)
        out = sharded_cpd_als(tt, rank=4, mesh=mesh, opts=opts, init=init)
        fits.append(float(out.fit))
    assert fits[0] == pytest.approx(fits[1], abs=1e-9)


def test_sharded_awkward_sizes():
    """Dims and nnz not divisible by the device count (≙ -np 7 tests)."""
    rng = np.random.default_rng(9)
    dims = (13, 11, 7)
    tt = SparseTensor(
        np.stack([rng.integers(0, d, size=101) for d in dims]),
        rng.random(101), dims).deduplicate()
    mesh = make_mesh(n_devices=8)
    out = sharded_cpd_als(tt, rank=3, mesh=mesh, opts=_opts(max_iterations=4))
    assert np.isfinite(float(out.fit))
    for U, d in zip(out.factors, dims):
        assert U.shape == (d, 3)
