"""SPL012 bad: emitting a run-report event kind the
RUN_REPORT_EVENTS registry never declared."""

from splatt_tpu import resilience


def degrade_quietly(err):
    resilience.run_report().add(
        "spl012_fixture_undeclared_event", error=str(err))
