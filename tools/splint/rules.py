"""The splint rule catalog — each rule encodes a project invariant.

Every rule here is grounded in a real hazard this codebase has already
paid for (see docs/static-analysis.md for the war stories): these are
code-shape properties — what the code *would* do when infrastructure
misbehaves — which is exactly what behavioral tests cannot catch.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set, Tuple

from tools.splint.core import FileCtx, Finding, Project

#: handler-body names accepted as "routing the failure through the
#: taxonomy" — the resilience module's public verbs.  Projects add
#: their own wrappers via [tool.splint] resilience-routers.
RESILIENCE_ROUTERS = {
    "classify_failure", "demote_engine", "retry_transient",
    "run_report", "failure_message",
}

_DTYPE_LITERALS = {"float32", "float64", "bfloat16", "float16"}
_DTYPE_MODULES = {"numpy", "jax.numpy"}
_SYNC_JAX = {"jax.block_until_ready", "jax.device_get"}
_NP_HOST = {"numpy.asarray", "numpy.array"}
_FAULT_FNS = {"maybe_fail", "consume", "active", "inject"}
_ENV_READ_FNS = {"read_env", "read_env_int", "read_env_float"}


class Rule:
    id = "SPL?"
    title = ""
    hint = ""

    def check(self, ctx: FileCtx, project: Project) -> List[Finding]:
        return []

    def finalize(self, project: Project) -> List[Finding]:
        return []

    def finding(self, ctx_or_path, line: int, message: str) -> Finding:
        path = (ctx_or_path.relpath if isinstance(ctx_or_path, FileCtx)
                else ctx_or_path)
        return Finding(self.id, path, line, message, hint=self.hint)


# -- SPL001 -----------------------------------------------------------------

class RawEnvironAccess(Rule):
    """Raw ``os.environ`` access outside the sanctioned env module.

    Every env read outside ``utils/env.py`` bypasses the ENV_VARS
    registry (so the variable escapes documentation and SPL007), and —
    because env.py feeds the probe cache's ``_kernel_src_hash`` — can
    change dispatch-relevant behavior without invalidating cached
    capability verdicts."""

    id = "SPL001"
    title = "raw os.environ access outside utils/env.py"
    hint = ("read through splatt_tpu.utils.env.read_env/read_env_int/"
            "read_env_float and declare the variable in ENV_VARS")

    def check(self, ctx: FileCtx, project: Project) -> List[Finding]:
        if ctx.relpath == project.config.env_module:
            return []
        out = []
        for node in ast.walk(ctx.tree):
            dotted = None
            if isinstance(node, ast.Attribute):
                dotted = ctx.resolve(node)
            elif isinstance(node, ast.Name):
                dotted = ctx.aliases.get(node.id)
            if dotted in ("os.environ", "os.getenv", "os.putenv"):
                out.append(self.finding(
                    ctx, node.lineno,
                    f"raw {dotted} access bypasses the ENV_VARS "
                    f"registry in {project.config.env_module}"))
        return _dedupe(out)


# -- SPL002 -----------------------------------------------------------------

class BroadExceptSwallows(Rule):
    """``except Exception`` that neither re-raises nor routes the error
    through the failure taxonomy.  The PR 1 bug class: one broad except
    swallowed a transient HTTP 500 and persisted it as a permanent
    engine demotion."""

    id = "SPL002"
    title = "except Exception swallows the failure class"
    hint = ("classify via resilience.classify_failure (or demote_engine/"
            "retry_transient/run_report), re-raise, or add a justified "
            "'# splint: ignore[SPL002] <reason>'")

    def check(self, ctx: FileCtx, project: Project) -> List[Finding]:
        routers = RESILIENCE_ROUTERS | set(
            project.config.resilience_routers)
        out = []
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.ExceptHandler):
                continue
            if not self._is_broad(node.type):
                continue
            names: Set[str] = set()
            reraises = False
            for sub in node.body:
                for n in ast.walk(sub):
                    if isinstance(n, ast.Raise):
                        reraises = True
                    elif isinstance(n, ast.Name):
                        names.add(n.id)
                    elif isinstance(n, ast.Attribute):
                        names.add(n.attr)
            if reraises or names & routers:
                continue
            out.append(self.finding(
                ctx, node.lineno,
                "broad except swallows the error without classifying "
                "it — a transient infra failure and a real bug become "
                "indistinguishable here"))
        return out

    @staticmethod
    def _is_broad(type_node) -> bool:
        if type_node is None:
            return True  # bare except
        nodes = (type_node.elts if isinstance(type_node, ast.Tuple)
                 else [type_node])
        return any(isinstance(n, ast.Name)
                   and n.id in ("Exception", "BaseException")
                   for n in nodes)


# -- jit helpers (SPL003 / SPL004) ------------------------------------------

def _jit_static_names(ctx: FileCtx,
                      fn: ast.FunctionDef) -> Optional[Set[str]]:
    """The static argnames of a jit-decorated function, or None when
    the function is not jitted.  Handles ``@jax.jit``,
    ``@jax.jit(...)`` and ``@partial(jax.jit, ...)``."""
    for dec in fn.decorator_list:
        call = dec if isinstance(dec, ast.Call) else None
        target = call.func if call else dec
        dotted = ctx.resolve(target) or ""
        kwargs = {k.arg: k.value for k in call.keywords} if call else {}
        if dotted.split(".")[-1] == "partial" and call and call.args:
            inner = ctx.resolve(call.args[0]) or ""
            if inner in ("jax.jit", "jit"):
                return _static_names_from(kwargs, fn)
            continue
        if dotted in ("jax.jit", "jit"):
            return _static_names_from(kwargs, fn)
    return None


def _static_names_from(kwargs: Dict[str, ast.AST],
                       fn: ast.FunctionDef) -> Set[str]:
    static: Set[str] = set()
    names = kwargs.get("static_argnames")
    if names is not None:
        for n in ([names] if isinstance(names, ast.Constant)
                  else getattr(names, "elts", [])):
            if isinstance(n, ast.Constant) and isinstance(n.value, str):
                static.add(n.value)
    nums = kwargs.get("static_argnums")
    if nums is not None:
        all_args = [a.arg for a in
                    fn.args.posonlyargs + fn.args.args]
        for n in ([nums] if isinstance(nums, ast.Constant)
                  else getattr(nums, "elts", [])):
            if isinstance(n, ast.Constant) and isinstance(n.value, int) \
                    and 0 <= n.value < len(all_args):
                static.add(all_args[n.value])
    return static


def _fn_params(fn: ast.FunctionDef) -> List[str]:
    a = fn.args
    return [x.arg for x in a.posonlyargs + a.args + a.kwonlyargs]


# -- SPL003 -----------------------------------------------------------------

class HostSyncInJit(Rule):
    """Host-device synchronization inside a jitted function (where it
    either fails at trace time or silently forces a device round-trip
    per call) or a configured hot-path function."""

    id = "SPL003"
    title = "host sync inside a jitted function / hot path"
    hint = ("keep block_until_ready/np.asarray/.item()/device_get out "
            "of traced code; batch host fetches at the sweep boundary "
            "(cpd.py's fit_check_every pattern)")

    def check(self, ctx: FileCtx, project: Project) -> List[Finding]:
        hot = set(project.config.hot_functions)
        out = []
        for fn in ast.walk(ctx.tree):
            if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            jitted = _jit_static_names(ctx, fn) is not None
            if not jitted and f"{ctx.relpath}::{fn.name}" not in hot:
                continue
            where = ("jitted function" if jitted
                     else "configured hot path")
            for node in ast.walk(fn):
                if not isinstance(node, ast.Call):
                    continue
                dotted = ctx.resolve(node.func) or ""
                label = None
                if dotted in _SYNC_JAX or \
                        dotted.split(".")[-1] == "block_until_ready":
                    label = dotted.split(".")[-1]
                elif dotted in _NP_HOST:
                    label = dotted
                elif (isinstance(node.func, ast.Attribute)
                        and node.func.attr == "item"
                        and not node.args and not node.keywords):
                    label = ".item()"
                if label:
                    out.append(self.finding(
                        ctx, node.lineno,
                        f"host sync {label} inside {where} "
                        f"'{fn.name}'"))
        return out


# -- SPL004 -----------------------------------------------------------------

class RecompilationHazard(Rule):
    """A jitted function branching in Python on a non-static argument:
    jax either fails at trace time (tracer in bool context) or — when
    the value is concrete, e.g. a shape-dependent int — specializes
    the compilation to it, recompiling per distinct value."""

    id = "SPL004"
    title = "Python branch on a non-static jit argument"
    hint = ("mark the argument static_argnames (accepting per-value "
            "retraces deliberately) or branch on-device with "
            "jnp.where/lax.cond/lax.while_loop")

    def check(self, ctx: FileCtx, project: Project) -> List[Finding]:
        out = []
        for fn in ast.walk(ctx.tree):
            if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            static = _jit_static_names(ctx, fn)
            if static is None:
                continue
            nonstatic = set(_fn_params(fn)) - static - {"self"}
            for node in ast.walk(fn):
                if not isinstance(node, (ast.If, ast.While)):
                    continue
                for name in self._branching_names(node.test, nonstatic):
                    kind = "while" if isinstance(node, ast.While) else "if"
                    out.append(self.finding(
                        ctx, node.lineno,
                        f"Python {kind} on non-static jit argument "
                        f"'{name}' of '{fn.name}' — recompiles per "
                        f"value (or fails on a traced value)"))
        return out

    @staticmethod
    def _branching_names(test: ast.AST, nonstatic: Set[str]) -> List[str]:
        parents = {child: parent for parent in ast.walk(test)
                   for child in ast.iter_child_nodes(parent)}
        hits = []
        for node in ast.walk(test):
            if not (isinstance(node, ast.Name) and node.id in nonstatic):
                continue
            parent = parents.get(node)
            # attribute access (x.mode) is usually static metadata, and
            # call arguments (len(x), isinstance(x, ...)) resolve to
            # static values at trace time — only a direct value use of
            # the argument is a per-value specialization
            if isinstance(parent, ast.Attribute) and parent.value is node:
                continue
            if isinstance(parent, ast.Call) and node is not parent.func:
                continue
            if isinstance(parent, ast.Compare) and \
                    all(isinstance(op, (ast.Is, ast.IsNot))
                        for op in parent.ops):
                continue  # `x is None`: pytree structure, static
            hits.append(node.id)
        return hits


# -- SPL005 -----------------------------------------------------------------

class DtypeLiteral(Rule):
    """A dtype literal outside the config module: per-site dtype
    choices drift from the central Options.val_dtype / resolve_dtype
    policy (the bf16 and f64 paths both exist because dtype is a
    *policy*, not a per-callsite constant)."""

    id = "SPL005"
    title = "dtype literal outside config.py"
    hint = ("resolve dtypes through splatt_tpu.config.resolve_dtype / "
            "Options.val_dtype (or derive from an input's .dtype)")

    def check(self, ctx: FileCtx, project: Project) -> List[Finding]:
        if ctx.relpath == project.config.config_module:
            return []
        out = []
        for node in ast.walk(ctx.tree):
            if (isinstance(node, ast.Attribute)
                    and node.attr in _DTYPE_LITERALS
                    and (ctx.resolve(node.value) or "") in _DTYPE_MODULES):
                out.append(self.finding(
                    ctx, node.lineno,
                    f"dtype literal .{node.attr} outside "
                    f"{project.config.config_module}"))
        return out


# -- SPL006 -----------------------------------------------------------------

def _call_sites(ctx: FileCtx) -> List[Tuple[Optional[str], int]]:
    """(site, lineno) for every fault-hook call in `ctx`; site is the
    literal string, 'prefix.*' for an f-string with a literal prefix,
    or None when not statically resolvable."""
    out = []
    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.Call):
            continue
        dotted = ctx.resolve(node.func) or ""
        if dotted.split(".")[-1] not in _FAULT_FNS or \
                "faults" not in dotted:
            continue
        arg = node.args[0] if node.args else None
        site: Optional[str] = None
        if isinstance(arg, ast.Constant) and isinstance(arg.value, str):
            site = arg.value
        elif isinstance(arg, ast.Name):
            site = ctx.str_consts.get(arg.id)
        elif isinstance(arg, ast.JoinedStr) and arg.values:
            first = arg.values[0]
            if isinstance(first, ast.Constant) and \
                    isinstance(first.value, str) and first.value:
                site = first.value + "*"
        out.append((site, node.lineno))
    return out


def _declared_sites(ctx: FileCtx) -> Dict[str, int]:
    for node in ast.walk(ctx.tree):
        if (isinstance(node, ast.Assign) and len(node.targets) == 1
                and isinstance(node.targets[0], ast.Name)
                and node.targets[0].id == "SITES"
                and isinstance(node.value, ast.Dict)):
            return {k.value: k.lineno for k in node.value.keys
                    if isinstance(k, ast.Constant)
                    and isinstance(k.value, str)}
    return {}


def _site_matches(declared: str, used: str) -> bool:
    if declared.endswith(".*"):
        return used == declared or used.startswith(declared[:-1])
    return used == declared


class FaultSiteDrift(Rule):
    """Fault-site drift: every site string the production code passes
    to the fault hooks must be declared in the faults module's SITES
    registry and exercised by at least one test — and every declared
    site must still exist in production.  A renamed hook otherwise
    silently orphans the resilience path it was built to exercise."""

    id = "SPL006"
    title = "fault-site drift against utils/faults.py:SITES"
    hint = ("declare the site (with a doc) in faults.SITES and "
            "exercise it from a test via faults.inject")

    def finalize(self, project: Project) -> List[Finding]:
        cfg = project.config
        faults_ctx = project.ctx_for(cfg.faults_module)
        if faults_ctx is None:
            return []
        declared = _declared_sites(faults_ctx)
        out = []
        prod_sites: List[Tuple[str, FileCtx, int]] = []
        for ctx in project.files:
            if ctx.relpath == cfg.faults_module:
                continue
            for site, line in _call_sites(ctx):
                if site is None:
                    out.append(self.finding(
                        ctx, line,
                        "fault site is not statically resolvable — "
                        "splint cannot check it against SITES"))
                else:
                    prod_sites.append((site, ctx, line))
        test_sites = {site for tctx in project.test_ctxs()
                      for site, _ in _call_sites(tctx) if site}
        for site, ctx, line in prod_sites:
            if not any(_site_matches(d, site) for d in declared):
                out.append(self.finding(
                    ctx, line,
                    f"fault site '{site}' is not declared in "
                    f"{cfg.faults_module}:SITES"))
        used = {s for s, _, _ in prod_sites}
        for d, line in declared.items():
            if not any(_site_matches(d, u) for u in used):
                out.append(self.finding(
                    faults_ctx, line,
                    f"declared fault site '{d}' has no production "
                    f"call — dead declaration or renamed hook"))
            elif not any(_site_matches(d, t) for t in test_sites):
                out.append(self.finding(
                    faults_ctx, line,
                    f"declared fault site '{d}' is not exercised by "
                    f"any test under {cfg.tests_path}/"))
        return out


# -- SPL007 -----------------------------------------------------------------

def _declared_env_vars(ctx: FileCtx) -> Dict[str, int]:
    for node in ast.walk(ctx.tree):
        if (isinstance(node, ast.Assign) and len(node.targets) == 1
                and isinstance(node.targets[0], ast.Name)
                and node.targets[0].id == "ENV_VARS"
                and isinstance(node.value, ast.Dict)):
            return {k.value: k.lineno for k in node.value.keys
                    if isinstance(k, ast.Constant)
                    and isinstance(k.value, str)}
    return {}


class UndocumentedEnvVar(Rule):
    """Every SPLATT_* environment variable the code reads must be
    declared (with a doc string) in the env module's ENV_VARS registry
    — the single source the docs render from."""

    id = "SPL007"
    title = "undocumented SPLATT_* environment variable"
    hint = ("declare the variable in splatt_tpu/utils/env.py:ENV_VARS "
            "(name -> default -> doc); docs render from that registry")

    def finalize(self, project: Project) -> List[Finding]:
        env_ctx = project.ctx_for(project.config.env_module)
        declared = _declared_env_vars(env_ctx) if env_ctx else {}
        out = []
        for ctx in project.files:
            for name, line in self._env_reads(ctx):
                if name.startswith("SPLATT_") and name not in declared:
                    out.append(self.finding(
                        ctx, line,
                        f"env var {name} is read but not declared in "
                        f"{project.config.env_module}:ENV_VARS"))
        return out

    @staticmethod
    def _env_reads(ctx: FileCtx) -> List[Tuple[str, int]]:
        out = []

        def literal(arg) -> Optional[str]:
            if isinstance(arg, ast.Constant) and isinstance(arg.value, str):
                return arg.value
            if isinstance(arg, ast.Name):
                return ctx.str_consts.get(arg.id)
            return None

        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.Call):
                dotted = ctx.resolve(node.func) or ""
                if (dotted in ("os.environ.get", "os.getenv")
                        or dotted.split(".")[-1] in _ENV_READ_FNS):
                    name = literal(node.args[0]) if node.args else None
                    if name:
                        out.append((name, node.lineno))
            elif isinstance(node, ast.Subscript) and \
                    (ctx.resolve(node.value) or "") == "os.environ":
                name = literal(node.slice)
                if name:
                    out.append((name, node.lineno))
        return out


def _dedupe(findings: List[Finding]) -> List[Finding]:
    seen = set()
    out = []
    for f in findings:
        k = (f.rule, f.path, f.line, f.message)
        if k not in seen:
            seen.add(k)
            out.append(f)
    return out


RULES: List[Rule] = [
    RawEnvironAccess(),
    BroadExceptSwallows(),
    HostSyncInJit(),
    RecompilationHazard(),
    DtypeLiteral(),
    FaultSiteDrift(),
    UndocumentedEnvVar(),
]
