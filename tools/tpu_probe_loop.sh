#!/bin/bash
# Sequential TPU claim attempts (single-lease discipline: one client at a
# time, clean exits, never a mid-claim kill).  Stops when a probe
# succeeds or when tools/STOP_PROBE exists (checked only between
# attempts so a running claim is never interrupted).
cd "$(dirname "$0")/.."
rm -f tools/STOP_PROBE
for i in $(seq 1 40); do
  [ -e tools/STOP_PROBE ] && { echo "probe loop: stopped by sentinel"; exit 0; }
  echo "=== probe attempt $i $(date -u +%H:%M:%S) ==="
  python tools/tpu_probe.py
  rc=$?
  if [ $rc -eq 0 ]; then
    echo "probe loop: SUCCESS on attempt $i"
    exit 0
  fi
  [ -e tools/STOP_PROBE ] && { echo "probe loop: stopped by sentinel"; exit 0; }
  sleep 420
done
echo "probe loop: exhausted attempts"
exit 1
