"""Graph / hypergraph models of a sparse tensor (≙ src/graph.c).

Host-side analysis structures (numpy CSR), used by the reorder and
convert verbs:

- :func:`tensor_to_graph`      ≙ graph_convert (src/graph.c:637-678):
  the m-partite weighted graph — one vertex per (mode, index), an edge
  between every pair of coordinates co-occurring in a nonzero, weighted
  by co-occurrence count.
- :func:`hypergraph_nnz`       ≙ hgraph_nnz_alloc (src/graph.c:452):
  nonzeros as vertices, index-slices as hyperedges.
- :func:`hypergraph_fibers`    ≙ hgraph_fib_alloc (src/graph.c:506):
  mode-rooted fibers as vertices.

External partitioner hooks (METIS/PaToH/Ashado, src/graph.h:180-228)
have no equivalent binary in this environment; partition files can be
supplied to the reorderer instead (≙ the FINE decomposition's partfile).
"""

from __future__ import annotations

import dataclasses
from typing import Optional, Tuple

import numpy as np

from splatt_tpu.coo import SparseTensor


@dataclasses.dataclass
class Graph:
    """CSR adjacency with per-vertex/edge weights."""

    indptr: np.ndarray
    adj: np.ndarray
    vwts: Optional[np.ndarray]
    ewts: Optional[np.ndarray]
    nvtxs: int

    @property
    def nedges(self) -> int:
        return int(self.adj.shape[0])


@dataclasses.dataclass
class Hypergraph:
    """Vertices + CSR hyperedge membership (eptr/eind)."""

    nvtxs: int
    eptr: np.ndarray
    eind: np.ndarray
    vwts: Optional[np.ndarray]

    @property
    def nhedges(self) -> int:
        return int(self.eptr.shape[0] - 1)


def _mode_offsets(dims: Tuple[int, ...]) -> np.ndarray:
    return np.concatenate([[0], np.cumsum(dims)]).astype(np.int64)


def _merge_unique(uniq, cnts, u, c):
    """Merge two (sorted-unique keys, counts) pairs, summing counts."""
    merged = np.concatenate([uniq, u])
    mc = np.concatenate([cnts, c])
    u2, inv = np.unique(merged, return_inverse=True)
    c2 = np.zeros(u2.shape[0], dtype=np.int64)
    np.add.at(c2, inv, mc)
    return u2, c2


class _UniqueAccumulator:
    """Streaming (sorted-unique keys, counts) accumulation via
    binary-counter run merging (mergesort-run / LSM style): pushing n
    runs costs O(E log n) merge work with only O(log n) runs
    outstanding — neither n re-sorts of the running set nor all runs
    held at once."""

    def __init__(self):
        self._levels = []

    def push(self, u, c):
        run = (u, c)
        for i in range(len(self._levels)):
            if self._levels[i] is None:
                self._levels[i] = run
                return
            run = _merge_unique(*self._levels[i], *run)
            self._levels[i] = None
        self._levels.append(run)

    def result(self):
        out = None
        for lvl in self._levels:
            if lvl is None:
                continue
            out = lvl if out is None else _merge_unique(*out, *lvl)
        if out is None:
            return np.empty(0, dtype=np.int64), np.zeros(0, dtype=np.int64)
        return out


def tensor_to_graph(tt: SparseTensor, chunk: int = 1 << 23) -> Graph:
    """m-partite graph: vertex v = offset[m] + index, edges between all
    co-occurring coordinate pairs, weight = #co-occurrences.

    Edge keys are accumulated pair-by-pair in nnz chunks (unique per
    chunk, merged into the running unique set) — peak temporaries are
    O(chunk + edges), not the m·(m−1)·nnz concatenation that made
    NELL-2-scale graphs cost ~3.7GB of int64.
    """
    offs = _mode_offsets(tt.dims)
    nvtxs = int(offs[-1])
    nnz = tt.nnz
    acc = _UniqueAccumulator()
    for a in range(tt.nmodes):
        for b in range(tt.nmodes):
            if a == b:
                continue
            for s in range(0, max(nnz, 1), chunk):
                e = min(nnz, s + chunk)
                key = ((np.asarray(tt.inds[a][s:e], dtype=np.int64)
                        + offs[a]) * nvtxs
                       + np.asarray(tt.inds[b][s:e], dtype=np.int64)
                       + offs[b])
                u, c = np.unique(key, return_counts=True)
                acc.push(u, c.astype(np.int64))
    uniq, counts = acc.result()
    # keys are sorted, so (src, dst) is already lexicographic
    src_u = (uniq // nvtxs).astype(np.int64)
    dst_u = (uniq % nvtxs).astype(np.int64)
    indptr = np.zeros(nvtxs + 1, dtype=np.int64)
    np.add.at(indptr, src_u + 1, 1)
    np.cumsum(indptr, out=indptr)
    vwts = np.concatenate([tt.mode_histogram(m) for m in range(tt.nmodes)])
    return Graph(indptr=indptr, adj=dst_u, vwts=vwts,
                 ewts=counts, nvtxs=nvtxs)


def hypergraph_nnz(tt: SparseTensor) -> Hypergraph:
    """Nonzeros as vertices; hyperedge per (mode, index) containing the
    nonzeros in that slice."""
    offs = _mode_offsets(tt.dims)
    nhedges = int(offs[-1])
    counts = np.zeros(nhedges, dtype=np.int64)
    for m in range(tt.nmodes):
        counts[offs[m]:offs[m + 1]] = tt.mode_histogram(m)
    eptr = np.concatenate([[0], np.cumsum(counts)])
    eind = np.empty(int(eptr[-1]), dtype=np.int64)
    for m in range(tt.nmodes):
        order = np.argsort(tt.inds[m], kind="stable")
        seg = eptr[offs[m]] + np.arange(tt.nnz)
        eind[seg] = order
    return Hypergraph(nvtxs=tt.nnz, eptr=eptr.astype(np.int64), eind=eind,
                      vwts=None)


def hypergraph_fibers(tt: SparseTensor, mode: int) -> Hypergraph:
    """Mode-`mode`-rooted fibers as vertices (a fiber = all nnz sharing
    every coordinate except `mode`); hyperedges per (mode, index) list
    the fibers touching that slice."""
    others = [m for m in range(tt.nmodes) if m != mode]
    # fiber id = rank of the distinct coordinate tuple over `others`
    keys = np.stack([tt.inds[m] for m in others])
    order = np.lexsort(keys[::-1])
    sorted_keys = keys[:, order]
    new_fiber = np.ones(tt.nnz, dtype=bool)
    if tt.nnz > 1:
        new_fiber[1:] = np.any(sorted_keys[:, 1:] != sorted_keys[:, :-1], axis=0)
    fiber_of_sorted = np.cumsum(new_fiber) - 1
    fiber_id = np.empty(tt.nnz, dtype=np.int64)
    fiber_id[order] = fiber_of_sorted
    nfibers = int(fiber_of_sorted[-1]) + 1 if tt.nnz else 0

    offs = _mode_offsets(tt.dims)
    # hyperedges: for every (m, idx) slice, the set of fibers present
    pairs = []
    for m in range(tt.nmodes):
        key = (tt.inds[m] + offs[m]) * max(nfibers, 1) + fiber_id
        pairs.append(np.unique(key))
    allpairs = np.concatenate(pairs) if pairs else np.empty(0, np.int64)
    hedge = allpairs // max(nfibers, 1)
    vtx = allpairs % max(nfibers, 1)
    eptr = np.zeros(int(offs[-1]) + 1, dtype=np.int64)
    np.add.at(eptr, hedge + 1, 1)
    np.cumsum(eptr, out=eptr)
    return Hypergraph(nvtxs=nfibers, eptr=eptr, eind=vtx, vwts=None)


def hypergraph_uncut(h: Hypergraph, parts: np.ndarray) -> np.ndarray:
    """Hyperedges NOT cut by `parts`: every pin in one part
    (≙ hgraph_uncut, src/graph.c:576-624; empty hyperedges are
    trivially uncut).  `parts` maps vertex → part id."""
    parts = np.asarray(parts)
    pin_parts = parts[h.eind]
    # vectorized: an edge is cut iff any *within-edge* adjacent pin pair
    # disagrees (adjacency inequality detects any disagreement without
    # requiring sorted pins); edge-start positions are masked out so
    # pairs never straddle edges
    diff = np.zeros(len(pin_parts), dtype=bool)
    if len(pin_parts) > 1:
        diff[1:] = pin_parts[1:] != pin_parts[:-1]
    starts = h.eptr[:-1]
    diff[starts[starts < len(diff)]] = False
    pos = np.nonzero(diff)[0]
    cut = np.unique(np.searchsorted(h.eptr, pos, side="right") - 1)
    return np.setdiff1d(np.arange(h.nhedges), cut, assume_unique=True)


def write_graph(g: Graph, path: str) -> None:
    """METIS-like text format (≙ graph writers in src/io.c)."""
    has_ew = g.ewts is not None
    has_vw = g.vwts is not None
    fmt = f"{int(has_vw)}{int(has_ew)}"
    with open(path, "w") as f:
        f.write(f"{g.nvtxs} {g.nedges // 2} {fmt}\n")
        for v in range(g.nvtxs):
            parts = []
            if has_vw:
                parts.append(str(int(g.vwts[v])))
            for k in range(g.indptr[v], g.indptr[v + 1]):
                parts.append(str(int(g.adj[k]) + 1))
                if has_ew:
                    parts.append(str(int(g.ewts[k])))
            f.write(" ".join(parts) + "\n")


def write_hypergraph(h: Hypergraph, path: str) -> None:
    """PaToH/hMETIS-like text format."""
    with open(path, "w") as f:
        f.write(f"{h.nhedges} {h.nvtxs}\n")
        for e in range(h.nhedges):
            mem = h.eind[h.eptr[e]:h.eptr[e + 1]]
            f.write(" ".join(str(int(v) + 1) for v in mem) + "\n")
