"""Native extension tests: parser and sort must agree with the numpy
reference implementations exactly (they are drop-in fast paths)."""

import numpy as np
import pytest

from splatt_tpu import native
from splatt_tpu.io import load, save
from tests import gen

pytestmark = pytest.mark.skipif(not native.available(),
                                reason="native extension not built")


def test_parse_matches_python(tmp_path, any_tensor):
    tt = any_tensor
    path = str(tmp_path / "t.tns")
    save(tt, path)
    inds, vals = native.parse_tns(path)
    np.testing.assert_array_equal(inds - 1, tt.inds)  # file is 1-indexed
    np.testing.assert_allclose(vals, tt.vals)


def test_parse_comments_blank_lines(tmp_path):
    p = tmp_path / "c.tns"
    p.write_text("# hdr\n\n  # indented comment\n1 2 1 1.5\n2 1 2 -2.5e-1\n")
    inds, vals = native.parse_tns(str(p))
    np.testing.assert_array_equal(inds, [[1, 2], [2, 1], [1, 2]])
    np.testing.assert_allclose(vals, [1.5, -0.25])


def test_parse_no_trailing_newline(tmp_path):
    p = tmp_path / "t.tns"
    p.write_bytes(b"1 1 1 2.0\n2 2 2 3.0")
    inds, vals = native.parse_tns(str(p))
    assert inds.shape == (3, 2)
    np.testing.assert_allclose(vals, [2.0, 3.0])


def test_parse_ragged_raises(tmp_path):
    p = tmp_path / "r.tns"
    p.write_text("1 2 3\n1 1 1 5.0\n")
    with pytest.raises(ValueError):
        native.parse_tns(str(p))


def test_parse_short_row_raises(tmp_path):
    # a short row whose value has a decimal point must not silently
    # donate the value's integer part to an index column
    p = tmp_path / "s.tns"
    p.write_text("1 2 3 0.5\n1 2 0.7\n")
    with pytest.raises(ValueError):
        native.parse_tns(str(p))


def test_parse_nonnumeric_raises(tmp_path):
    p = tmp_path / "x.tns"
    p.write_text("1 a 1 5.0\n")
    with pytest.raises(ValueError):
        native.parse_tns(str(p))


def test_load_uses_native_and_matches(tmp_path, any_tensor):
    """End-to-end: load() (native fast path) == in-memory fixture."""
    tt = any_tensor
    path = str(tmp_path / "t.tns")
    save(tt, path)
    out = load(path)
    np.testing.assert_array_equal(out.inds, tt.inds)
    np.testing.assert_allclose(out.vals, tt.vals)


@pytest.mark.parametrize("lead", [0, 1])
def test_sort_perm_matches_lexsort(any_tensor, lead):
    tt = any_tensor
    order = [lead] + [m for m in range(tt.nmodes) if m != lead]
    got = native.sort_perm(tt.inds, tt.dims, order)
    keys = tuple(tt.inds[m] for m in reversed(order))
    want = np.lexsort(keys)
    np.testing.assert_array_equal(got, want)


def test_sort_perm_with_duplicates():
    inds = np.array([[1, 1, 0, 1], [2, 2, 0, 2], [0, 0, 1, 0]])
    dims = (2, 3, 2)
    got = native.sort_perm(inds, dims, [0, 1, 2])
    want = np.lexsort((inds[2], inds[1], inds[0]))
    np.testing.assert_array_equal(got, want)  # stability incl. exact dups


def test_partial_mode_order_falls_back(any_tensor):
    """A partial mode order has different semantics than the C sort
    (remaining modes unordered) — native must decline, numpy handles it."""
    tt = any_tensor
    assert native.sort_perm(tt.inds, tt.dims, [1]) is None
    perm = tt.sort_order([1])  # goes through the numpy fallback
    rows = tt.inds[1][perm]
    assert np.all(np.diff(rows) >= 0)


def test_out_of_range_indices_decline():
    """Indices outside dims must not crash the native sort (the numpy
    fallback tolerates them)."""
    inds = np.array([[0, 5], [1, 0], [0, 1]])  # 5 >= dims[0]=2
    assert native.sort_perm(inds, (2, 2, 2), [0, 1, 2]) is None


def test_stream_to_bin_matches_inmemory(tmp_path, any_tensor):
    """Streamed conversion must produce a file the normal loader reads
    back identically (incl. 1-index shift + narrowing)."""
    tt = any_tensor
    src = str(tmp_path / "t.tns")
    save(tt, src, one_indexed=True)
    dst = str(tmp_path / "t.bin")
    assert native.stream_to_bin(src, dst)
    out = load(dst)
    assert out.dims == tt.dims
    np.testing.assert_array_equal(out.inds, tt.inds)
    np.testing.assert_allclose(out.vals, tt.vals)


def test_stream_to_bin_comments_zero_indexed(tmp_path):
    src = tmp_path / "z.tns"
    src.write_text("# hdr\n0 1 0 1.5\n\n2 0 1 -2.0")  # 0-indexed, no final \n
    dst = str(tmp_path / "z.bin")
    assert native.stream_to_bin(str(src), dst)
    out = load(dst)
    assert out.dims == (3, 2, 2)
    np.testing.assert_array_equal(out.inds, [[0, 2], [1, 0], [0, 1]])


def test_stream_to_bin_malformed(tmp_path):
    src = tmp_path / "bad.tns"
    src.write_text("1 2 x 1.0\n")
    with pytest.raises(ValueError):
        native.stream_to_bin(str(src), str(tmp_path / "bad.bin"))


def test_stream_to_bin_negative_index_rejected(tmp_path):
    src = tmp_path / "neg.tns"
    src.write_text("1 -3 1 2.5\n")
    with pytest.raises(ValueError):
        native.stream_to_bin(str(src), str(tmp_path / "neg.bin"))


def test_native_mttkrp_differential():
    """Native C++ engine vs the stream oracle, every mode, f32+f64,
    sorted (layout mode) and unsorted (generic) paths, 2/3/4-mode
    (≙ the reference's differential MTTKRP matrix, tests/mttkrp_test.c)."""
    import jax.numpy as jnp

    from splatt_tpu import native
    from splatt_tpu.blocked import BlockedSparse
    from splatt_tpu.config import BlockAlloc, Options, Verbosity
    from splatt_tpu.coo import SparseTensor
    from splatt_tpu.cpd import init_factors
    from splatt_tpu.ops.mttkrp import mttkrp, mttkrp_stream

    if not native.available():
        pytest.skip("native library unavailable")
    rng = np.random.default_rng(0)
    for dims, nnz, dt in (((40, 30, 50), 4000, np.float32),
                          ((20, 16, 12, 10), 2500, np.float64)):
        inds = np.stack([rng.integers(0, d, nnz)
                         for d in dims]).astype(np.int64)
        tt = SparseTensor(inds=inds, vals=rng.random(nnz), dims=dims)
        opts = Options(random_seed=1, verbosity=Verbosity.NONE,
                       val_dtype=dt, nnz_block=256,
                       block_alloc=BlockAlloc.TWOMODE)
        bs = BlockedSparse.from_coo(tt, opts)
        fac = init_factors(dims, 9, 1, dtype=jnp.dtype(dt))
        for m in range(len(dims)):
            gold = np.asarray(mttkrp_stream(
                jnp.asarray(tt.inds), jnp.asarray(tt.vals), fac, m,
                dims[m]))
            out = np.asarray(mttkrp(bs, fac, m, impl="native"))
            err = (np.abs(out - gold).max()
                   / max(np.abs(gold).max(), 1e-30))
            tol = 9e-3 if dt == np.float32 else 1e-10
            assert err < tol, (dims, m, err)


def test_native_mttkrp_inside_trace_falls_back():
    """Inside a jit trace the native engine cannot run; dispatch must
    fall back to the XLA engine and still be correct."""
    import jax
    import jax.numpy as jnp

    from splatt_tpu.blocked import BlockedSparse
    from splatt_tpu.config import Options, Verbosity
    from splatt_tpu.coo import SparseTensor
    from splatt_tpu.cpd import init_factors
    from splatt_tpu.ops.mttkrp import mttkrp, mttkrp_stream

    rng = np.random.default_rng(2)
    dims = (15, 12, 9)
    inds = np.stack([rng.integers(0, d, 400) for d in dims]).astype(np.int64)
    tt = SparseTensor(inds=inds, vals=rng.random(400), dims=dims)
    bs = BlockedSparse.from_coo(tt, Options(
        random_seed=1, verbosity=Verbosity.NONE, val_dtype=np.float64,
        nnz_block=128))
    fac = init_factors(dims, 5, 1, dtype=jnp.float64)

    @jax.jit
    def traced(fs):
        return mttkrp(bs, fs, 0, impl="native")

    gold = np.asarray(mttkrp_stream(jnp.asarray(tt.inds),
                                    jnp.asarray(tt.vals), fac, 0, dims[0]))
    np.testing.assert_allclose(np.asarray(traced(fac)), gold, atol=1e-10)


def test_native_mttkrp_never_reads_padding():
    """Regression for the round-2 nondeterministic NaN failures: the
    kernel must never touch padded entries.  Padding carries a sentinel
    index equal to `dim` on the sort-mode row — an out-of-bounds factor
    gather (UB; 0*garbage injected NaN depending on heap state).  Here
    the padding is poisoned with huge values and in-bounds indices so a
    loop bound that includes it fails deterministically."""
    import jax.numpy as jnp

    from splatt_tpu.ops.mttkrp import mttkrp_stream

    rng = np.random.default_rng(5)
    dims = (11, 7, 9)
    nnz, rank = 100, 6
    inds = np.stack([rng.integers(0, d, nnz) for d in dims]).astype(np.int64)
    vals = rng.random(nnz)
    fac = [jnp.asarray(rng.random((d, rank))) for d in dims]

    nnz_pad = 256  # pretend block padding
    pinds = np.zeros((3, nnz_pad), dtype=np.int32)
    pinds[:, :nnz] = inds
    pinds[:, nnz:] = 1          # in-bounds poison rows
    pvals = np.full(nnz_pad, 1e30)
    pvals[:nnz] = vals

    for mode in range(3):
        gold = np.asarray(mttkrp_stream(
            jnp.asarray(inds), jnp.asarray(vals), fac, mode, dims[mode]))
        for sorted_by_mode in (False, True):
            if sorted_by_mode:
                order = np.argsort(pinds[mode, :nnz], kind="stable")
                sinds, svals = pinds.copy(), pvals.copy()
                sinds[:, :nnz] = pinds[:, :nnz][:, order]
                svals[:nnz] = pvals[:nnz][order]
            else:
                sinds, svals = pinds, pvals
            out = native.mttkrp(sinds, svals,
                                [np.asarray(f) for f in fac], mode, dims,
                                sorted_by_mode=sorted_by_mode, nnz=nnz)
            assert out is not None
            np.testing.assert_allclose(
                out, gold, atol=1e-10,
                err_msg=f"mode={mode} sorted={sorted_by_mode}")


def test_native_mttkrp_dtype_mismatch_falls_back():
    """f64 factors with an f32 layout must return None (the XLA paths
    own the promotion semantics), not silently compute in f32."""
    rng = np.random.default_rng(6)
    dims = (5, 4, 3)
    inds = np.stack([rng.integers(0, d, 50) for d in dims]).astype(np.int32)
    vals = rng.random(50).astype(np.float32)
    fac64 = [rng.random((d, 4)) for d in dims]
    assert native.mttkrp(inds, vals, fac64, 0, dims,
                         sorted_by_mode=False, nnz=50) is None
