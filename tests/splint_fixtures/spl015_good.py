"""SPL015 good: one global lock order, everywhere — queue-lock before
cache-lock at every nesting site, so the acquisition graph is acyclic
and no interleaving can deadlock."""

import threading

_QUEUE_LOCK = threading.Lock()
_CACHE_LOCK = threading.Lock()


def drain_into_cache(queue, cache):
    with _QUEUE_LOCK:
        with _CACHE_LOCK:
            while queue:
                cache[queue.pop()] = True


def evict_into_queue(queue, cache):
    # same order as drain_into_cache; the eviction set is decided
    # under both locks, exactly like the drain
    with _QUEUE_LOCK:
        with _CACHE_LOCK:
            for key in list(cache):
                queue.append(cache.pop(key))
