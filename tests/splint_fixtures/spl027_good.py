"""SPL027 good: schema, plan record, key builder and strict-match
comparator agree in both directions."""

PLAN_CACHE_VERSION = 2

PLAN_SCHEMA = {
    "version": 2,
    "key": ("dims", "nnz"),
    "fields": ("path", "nnz_block", "sec"),
    "match": ("path", "nnz_block"),
    "exempt": ("sec",),
}
# v2: nnz_block joined the measured configuration


class TunedPlan:
    path: str
    nnz_block: int
    sec: float


def plan_key(dims, nnz):
    return f"{dims}|{nnz}"


def cached_plan(key):
    return None


def _tuned_plan_for(layout, path):
    plan = cached_plan(plan_key(layout.dims, layout.nnz))
    if plan is None or plan.path != path \
            or plan.nnz_block != layout.block:
        return None
    return plan
