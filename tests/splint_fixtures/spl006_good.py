"""SPL006 good: only declared fault sites (utils/faults.py:SITES)."""

from splatt_tpu.utils import faults


def risky_write():
    faults.maybe_fail("checkpoint_write")


def risky_dispatch(engine):
    faults.maybe_fail(f"engine.{engine}")


def risky_measurement():
    # the autotuner's candidate-timing hook (tune.py)
    faults.maybe_fail("tuner.measure")


def risky_serve():
    # the serve daemon's submission / durable-journal / supervised-job
    # hooks (serve.py, docs/serve.md)
    faults.maybe_fail("serve.submit")
    faults.maybe_fail("serve.journal_write")
    faults.maybe_fail("serve.job_run")


def risky_ring_exchange():
    # the distributed ring row-exchange hook (parallel/ring_kernels.py
    # and the ppermute fallback in parallel/ring.py, docs/ring.md)
    faults.maybe_fail("comm.ring_exchange")


def risky_trace_export():
    # the Chrome trace-event exporter hook (trace.py,
    # docs/observability.md) — a raised fault degrades classified to a
    # trace_written ok=False event, never fails the traced run
    faults.maybe_fail("trace.export")


def risky_layout_balance():
    # the load-balanced layout hooks (docs/layout-balance.md): the
    # balanced fiber pack (blocked.py) and the reorder permutation
    # compute+apply (reorder.py) — both degrade classified, never fail
    faults.maybe_fail("layout.pack")
    faults.maybe_fail("reorder.apply")
