"""SPL011 good: cache IO routed through helpers that take the path as
a parameter — the sanctioned chokepoint shape."""

import json
import pathlib


def cache_path():
    return pathlib.Path("/tmp/spl011_fixture_cache.json")


def _json_cache_load(path, on_error=None):
    try:
        with open(path) as f:  # helper body: the path is a parameter
            return json.load(f)
    except FileNotFoundError:
        return None


def read_via_helper():
    return _json_cache_load(cache_path())
