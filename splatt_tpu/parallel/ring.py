"""Memory-lean point-to-point (ring) variant of the fine decomposition.

≙ SPLATT_OPTION_COMM = POINT2POINT (types_config.h:197-201): the
reference's Isend/Irecv row-exchange variant
(p_reduce_rows_point2point / p_update_rows_point2point,
src/mpi/mpi_cpd.c:323-546).  On TPU the point-to-point primitive is
``ppermute`` over the ICI ring, and the payoff is the same one ring
attention gets for long sequences: **no device ever materializes a
full factor matrix or a full MTTKRP output** — peak memory per device
is one row *block*, O(dim/ndev · R), instead of O(dim · R).

Two building blocks, both inside `shard_map`:

- :func:`ring_gather_rows` (≙ mpi_update_rows): factor blocks travel
  the ring; at each of the ndev steps a device multiplies in the rows
  of the block it currently holds for the nonzeros that reference it.
- :func:`blockwise_reduce_rows` (≙ mpi_reduce_rows): the MTTKRP output
  is reduced one row-block at a time (psum of a (block, R) buffer per
  step), so the full (dim_pad, R) partial never exists.

The compute cost is ndev masked passes over the local nonzeros —
the classic ring trade: O(ndev·nnz_local) work for O(dim/ndev) memory.
Use it when dims·rank outgrows HBM (e.g. the 1.7B-nnz Amazon config);
the ALL2ALL variant (sharded.py) is faster when factors fit.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def ring_gather_rows(U_l: jax.Array, idx: jax.Array, axis: str,
                     ndev: int) -> jax.Array:
    """Rows of a row-sharded factor at global ids `idx`, via a ppermute
    ring instead of an all_gather.

    U_l: (block, R) local shard (device d initially holds block d).
    After s forward ppermutes device d holds block (d - s) mod ndev.
    """
    from splatt_tpu.utils import faults

    # the ring row-exchange fault site covers the sync ring too: a
    # drill armed past the async engine must land here next and degrade
    # the sweep to all2all (docs/ring.md fallback ladder)
    faults.maybe_fail("comm.ring_exchange")
    # widen through the blocked format's stream-consumer boundary
    # (blocked.widen_ids): the sync ring consumes index streams via
    # the same interface as the async kernels and single-chip engines
    from splatt_tpu.blocked import widen_ids

    idx = widen_ids(idx)
    block = U_l.shape[0]
    my_id = jax.lax.axis_index(axis)
    perm = [(i, (i + 1) % ndev) for i in range(ndev)]

    def body(step, carry):
        rows, U_cur = carry
        shard_id = jnp.mod(my_id - step, ndev)
        mask = (idx // block) == shard_id
        local = jnp.where(mask, jnp.mod(idx, block), 0)
        picked = jnp.take(U_cur, local, axis=0, mode="clip")
        rows = rows + jnp.where(mask[:, None], picked, 0)
        U_next = jax.lax.ppermute(U_cur, axis, perm)
        return rows, U_next

    rows0 = jnp.zeros((idx.shape[0], U_l.shape[1]), dtype=U_l.dtype)
    rows, _ = jax.lax.fori_loop(0, ndev, body, (rows0, U_l))
    return rows


def blockwise_reduce_rows(prod: jax.Array, idx: jax.Array, axis: str,
                          ndev: int, block: int) -> jax.Array:
    """Row-sharded MTTKRP output without the full (dim_pad, R) partial:
    for each row block j, every device reduces its local contribution
    and the block-psum is kept only by the owner."""
    from splatt_tpu.blocked import widen_ids
    from splatt_tpu.ops.mttkrp import acc_dtype

    idx = widen_ids(idx)
    my_id = jax.lax.axis_index(axis)
    out_dtype = acc_dtype(prod.dtype)

    def body(j, acc):
        mask = (idx // block) == j
        p = jax.ops.segment_sum(
            (prod * mask[:, None]).astype(out_dtype),
            jnp.where(mask, jnp.mod(idx, block), 0),
            num_segments=block)
        tot = jax.lax.psum(p, axis)
        return jnp.where(j == my_id, tot, acc)

    acc0 = jnp.zeros((block, prod.shape[1]), dtype=out_dtype)
    return jax.lax.fori_loop(0, ndev, body, acc0)


# The ring ALS sweep itself is built by make_sharded_sweep(variant="ring")
# — one sweep body, two sets of comm primitives.
