"""splint configuration: the ``[tool.splint]`` table of pyproject.toml.

Python 3.10 has no ``tomllib`` and splint must not grow dependencies,
so a minimal single-table parser lives here: it understands exactly the
value shapes the splint table uses (strings, string arrays — including
multiline arrays) and nothing more.  The same :class:`Config` object is
what tests construct directly to point the analyzer at fixture
mini-projects, so the analyzer runs identically from pytest, the CLI,
and any future CI job.
"""

from __future__ import annotations

import ast
import dataclasses
import re
from pathlib import Path
from typing import List, Optional


@dataclasses.dataclass
class Config:
    """Where splint looks and which project modules anchor its rules."""

    #: project root every relative path below resolves against
    root: Path
    #: files/directories to analyze (relative to root)
    paths: List[str] = dataclasses.field(
        default_factory=lambda: ["splatt_tpu"])
    #: the checked-in baseline of grandfathered findings
    baseline: str = "tools/splint/baseline.json"
    #: the single sanctioned env-access module (SPL001 exemption,
    #: SPL007's ENV_VARS registry)
    env_module: str = "splatt_tpu/utils/env.py"
    #: the fault-injection harness declaring SITES (SPL006)
    faults_module: str = "splatt_tpu/utils/faults.py"
    #: the dtype-policy module (SPL005 exemption)
    config_module: str = "splatt_tpu/config.py"
    #: test tree scanned for exercised fault sites (SPL006)
    tests_path: str = "tests"
    #: non-jitted hot-path functions ("relpath::name") that get the
    #: SPL003 host-sync scan as if they were jitted
    hot_functions: List[str] = dataclasses.field(default_factory=list)
    #: extra handler-body names SPL002 accepts as routing the failure
    #: (project helpers that wrap resilience.classify_failure)
    resilience_routers: List[str] = dataclasses.field(default_factory=list)
    #: the resilience module declaring RUN_REPORT_EVENTS (SPL012)
    resilience_module: str = "splatt_tpu/resilience.py"
    #: the trace module declaring the SPANS name registry (SPL013)
    #: and the METRICS registry (SPL029)
    trace_module: str = "splatt_tpu/trace.py"
    #: the markdown file whose metrics table SPL029 checks against
    #: trace.METRICS in both directions ("" disables the docs legs)
    metrics_doc: str = "docs/observability.md"
    #: functions returning shared-cache file paths; values derived
    #: from them must only reach IO through the locked helpers (SPL011)
    cache_path_functions: List[str] = dataclasses.field(
        default_factory=list)
    #: the sanctioned cache-IO helper functions whose bodies SPL011
    #: exempts (they ARE the locked chokepoints)
    cache_io_helpers: List[str] = dataclasses.field(default_factory=list)
    #: shared-structure → guarding-lock map for SPL014, entries of the
    #: form "relpath::self.attr=self.lock" (instance state, the class
    #: resolved at each mutation site) or "relpath::NAME=LOCK_NAME"
    #: (module globals)
    shared_state: List[str] = dataclasses.field(default_factory=list)
    #: the sanctioned durable-write helper functions whose bodies
    #: SPL016 exempts (they ARE the fsync/tmp-write→replace/append
    #: chokepoints — splatt_tpu/utils/durable.py)
    durable_write_helpers: List[str] = dataclasses.field(
        default_factory=list)
    #: control-plane functions ("relpath::name") where SPL017 flags a
    #: blocking call (fsync/flock/sleep/join/wait/subprocess, directly
    #: or transitively) made while an in-process lock is held
    hot_lock_paths: List[str] = dataclasses.field(default_factory=list)
    #: path fragments naming the durable roots (journal, ckpt, stamp,
    #: lease, result, metrics ...) — a write-mode open whose path
    #: expression carries one of these is a durable write (SPL023)
    durable_roots: List[str] = dataclasses.field(default_factory=list)
    #: the atomic-publish subset of the durable helpers whose bodies
    #: SPL019 audits for the full tmp-write → fsync → os.replace →
    #: parent-dir-fsync protocol, in order
    atomic_publish_helpers: List[str] = dataclasses.field(
        default_factory=list)
    #: every function ("relpath::name") allowed to append to the job
    #: journal — SPL020 flags appends anywhere else
    journal_append_functions: List[str] = dataclasses.field(
        default_factory=list)
    #: the subset of journal-append functions whose terminal appends
    #: must be dominated by a live-lease fence (SPL020)
    lease_fenced_functions: List[str] = dataclasses.field(
        default_factory=list)
    #: call names that constitute a live-lease fence (SPL020)
    lease_fence_calls: List[str] = dataclasses.field(default_factory=list)
    #: call names that advance the generation stamp (SPL021 leg A)
    stamp_advance_calls: List[str] = dataclasses.field(
        default_factory=list)
    #: call names that persist factor content a stamp covers (SPL021
    #: leg A: one must dominate every stamp advance)
    factor_persist_calls: List[str] = dataclasses.field(
        default_factory=list)
    #: commit-scope persists after which the stamp advance is
    #: mandatory on every normal-flow path (SPL021 leg B)
    commit_persist_calls: List[str] = dataclasses.field(
        default_factory=list)
    #: the serve module declaring TERMINAL and KNOWN_KINDS (SPL020,
    #: SPL022)
    serve_module: str = "splatt_tpu/serve.py"
    #: files/dirs whose reductions the SPL024 dtype-flow interpreter
    #: audits for accumulation-dtype discipline
    numerics_modules: List[str] = dataclasses.field(default_factory=list)
    #: the sanctioned accumulation-dtype helper names — a reduce
    #: routed through one carries the discipline (SPL024); each must
    #: exist in config-module (registry leg)
    acc_dtype_helpers: List[str] = dataclasses.field(default_factory=list)
    #: hot stream functions ("relpath::name") audited by SPL028 for
    #: narrow×wide elementwise promotion before the accumulate point
    hot_stream_functions: List[str] = dataclasses.field(
        default_factory=list)
    #: declared entry dtypes for the hot stream functions
    #: ("relpath::fn::param=bf16") — the storage contract the dispatch
    #: layer feeds them (SPL024/SPL028 lattice seeds)
    hot_stream_param_dtypes: List[str] = dataclasses.field(
        default_factory=list)
    #: files/dirs whose BlockSpecs SPL025/SPL026 audit
    pallas_modules: List[str] = dataclasses.field(default_factory=list)
    #: dtype-blind padding helpers (ceil_to/_pad_blocks) — values they
    #: produce need a unit that certifies the block position (SPL025)
    align_helpers: List[str] = dataclasses.field(default_factory=list)
    #: dtype-AWARE padding helpers (_rank_pad/tile_packing) whose
    #: results certify any sublane position (SPL025)
    tile_pack_helpers: List[str] = dataclasses.field(default_factory=list)
    #: declared dispatch envelope for SPL026's static accounting:
    #: "dim-text=int" caps a block dim by its unparsed source text,
    #: "*name=int" caps a starred spec list's multiplicity
    vmem_dim_caps: List[str] = dataclasses.field(default_factory=list)
    #: default per-kernel VMEM budget in MiB (SPL026)
    vmem_budget_mib: str = "16"
    #: per-kernel overrides, "fn=MiB" (SPL026)
    vmem_kernel_budgets: List[str] = dataclasses.field(
        default_factory=list)
    #: kernel-wrapper → dispatch-gate registry, "fn=gate" (SPL026):
    #: every pallas_call wrapper needs one, the gate must exist in the
    #: wrapper's module and be consulted somewhere
    vmem_gate_map: List[str] = dataclasses.field(default_factory=list)
    #: functions performing the plan cache's strict-match comparison;
    #: SPL027 checks each compares the schema's match set exactly
    plan_match_functions: List[str] = dataclasses.field(
        default_factory=list)
    #: rules whose finding budget is ZERO — never baselined, never
    #: grandfathered; the pytest gate enforces each at 0 findings
    zero_rules: List[str] = dataclasses.field(default_factory=list)
    #: path fragments to skip entirely
    exclude: List[str] = dataclasses.field(default_factory=list)

    def resolve(self, rel: str) -> Path:
        return (self.root / rel).resolve()


_KEY_RE = re.compile(r"^\s*([A-Za-z0-9_-]+)\s*=\s*(.*)$")


def _parse_table(text: str, table: str) -> dict:
    """Parse one ``[table]`` of a TOML file into a dict.

    Handles the subset splint uses: ``key = "string"`` and
    ``key = ["a", "b", ...]`` (arrays may span lines).  TOML string and
    array literals in this subset are also valid Python literals, so
    ``ast.literal_eval`` does the value parsing.
    """
    lines = text.splitlines()
    out: dict = {}
    in_table = False
    i = 0
    while i < len(lines):
        line = lines[i].strip()
        i += 1
        if line.startswith("["):
            in_table = line == f"[{table}]"
            continue
        if not in_table or not line or line.startswith("#"):
            continue
        m = _KEY_RE.match(line)
        if not m:
            raise ValueError(f"splint: cannot parse pyproject line: {line!r}")
        key, val = m.group(1), m.group(2)
        # accumulate a multiline array until brackets balance
        while val.count("[") > val.count("]"):
            if i >= len(lines):
                raise ValueError(
                    f"splint: unterminated array for {key!r} in [{table}]")
            val += " " + lines[i].strip()
            i += 1
        try:
            out[key.replace("-", "_")] = ast.literal_eval(val)
        except (SyntaxError, ValueError) as e:
            raise ValueError(
                f"splint: unsupported value for {key!r} in [{table}] "
                f"(splint's mini-parser takes strings and string arrays, "
                f"no end-of-line comments): {val!r} ({e})") from e
    return out


def load_config(root: Optional[Path] = None) -> Config:
    """Build a :class:`Config` from ``<root>/pyproject.toml``'s
    ``[tool.splint]`` table (missing file/table → defaults)."""
    root = Path(root) if root is not None else Path.cwd()
    cfg = Config(root=root)
    pp = root / "pyproject.toml"
    if not pp.exists():
        return cfg
    table = _parse_table(pp.read_text(), "tool.splint")
    for key, val in table.items():
        if not hasattr(cfg, key):
            raise ValueError(f"splint: unknown [tool.splint] key {key!r}")
        setattr(cfg, key, val)
    return cfg
