"""MTTKRP algorithm comparison harness (≙ src/bench.c + cmd_bench.c).

The reference's `splatt bench` times MTTKRP algorithms {splatt, csf,
giga, ttbox, coord} per mode with thread scaling (src/bench.c:50-436).
The TPU equivalents are the execution paths of
:mod:`splatt_tpu.ops.mttkrp`: {stream, sorted_onehot(+pallas),
privatized, scatter}; thread scaling has no analog (XLA owns the chip),
so the sweep axis is the path × engine matrix instead.
"""

from __future__ import annotations

import time
from typing import Dict, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from splatt_tpu.blocked import BlockedSparse
from splatt_tpu.config import BlockAlloc, Options, resolve_dtype
from splatt_tpu.coo import SparseTensor
from splatt_tpu.cpd import init_factors
from splatt_tpu.ops.mttkrp import (choose_impl, mttkrp_blocked,
                                   mttkrp_stream, mttkrp_ttbox)

ALGS = ("stream", "blocked", "blocked_pallas", "scatter", "ttbox",
        "native")


def _alg_plan(alg: str, layout, mode: int, dim: int, opts: Options):
    """Map a bench algorithm name to (path, impl) for mttkrp_blocked,
    or None when the config cannot run (privatized width over cap).
    Raises on unknown names — shared by timing and cross-checking."""
    if alg == "scatter":
        return (("sorted_scatter" if layout.mode == mode else "scatter"),
                "xla")
    if alg in ("blocked", "blocked_pallas"):
        path = "sorted_onehot" if layout.mode == mode else "privatized"
        if path == "privatized" and dim + 16 > opts.priv_cap:
            return None
        impl = ("xla" if alg == "blocked" else choose_impl(
            Options(use_pallas=True, val_dtype=opts.val_dtype)))
        return path, impl
    raise ValueError(f"unknown algorithm {alg!r}")


def _time_call(fn, warmup: int = 1, reps: int = 3) -> float:
    for _ in range(warmup):
        jax.block_until_ready(fn())
    t0 = time.perf_counter()
    for _ in range(reps):
        out = fn()
    jax.block_until_ready(out)
    return (time.perf_counter() - t0) / reps


def bench_mttkrp(tt: SparseTensor, rank: int = 16,
                 algs: Sequence[str] = ALGS,
                 opts: Optional[Options] = None,
                 reps: int = 3, return_layouts: bool = False):
    """Per-mode wall clock for each algorithm; returns alg -> [sec/mode]
    (with `return_layouts`, also the per-mode ModeLayouts for the
    roofline model).

    ≙ the per-mode timing loop of src/bench.c:84-117.
    """
    opts = opts or Options(block_alloc=BlockAlloc.ALLMODE)
    dtype = resolve_dtype(opts, tt.vals.dtype)
    factors = init_factors(tt.dims, rank, opts.seed() or 1, dtype=dtype)
    inds = jnp.asarray(tt.inds)
    vals = jnp.asarray(tt.vals, dtype=dtype)
    results: Dict[str, List[float]] = {}

    needs_blocked = any(a not in ("stream", "ttbox") for a in algs)
    bs = BlockedSparse.from_coo(tt, opts) if needs_blocked else None

    for alg in algs:
        times: List[float] = []
        for mode in range(tt.nmodes):
            if alg == "stream":
                fn = lambda: mttkrp_stream(inds, vals, factors, mode,
                                           tt.dims[mode])
            elif alg == "ttbox":
                fn = lambda: mttkrp_ttbox(inds, vals, factors, mode,
                                          tt.dims[mode])
            elif alg == "native":
                from splatt_tpu.ops.mttkrp import _run_native, plan_mttkrp

                layout = bs.layout_for(mode)
                if plan_mttkrp(bs, factors, mode,
                               impl="native").engine != "native":
                    times.append(float("nan"))
                    continue
                fn = lambda: _run_native(layout, factors, mode)
            else:
                layout = bs.layout_for(mode)
                plan = _alg_plan(alg, layout, mode, tt.dims[mode], opts)
                if plan is None:
                    times.append(float("nan"))
                    continue
                path, impl = plan
                fn = lambda: mttkrp_blocked(layout, factors, mode,
                                            path=path, impl=impl)
            times.append(_time_call(fn, reps=reps))
        results[alg] = times
    if return_layouts:
        layouts = ([bs.layout_for(m) for m in range(tt.nmodes)]
                   if bs is not None else None)
        return results, layouts
    return results


def crosscheck_mttkrp(tt: SparseTensor, rank: int = 16,
                      algs: Sequence[str] = ALGS,
                      opts: Optional[Options] = None) -> float:
    """Verify every algorithm computes the same MTTKRP: max deviation
    from the stream result over all modes, *relative* to the result's
    magnitude (summation-order noise scales with value magnitudes and
    nnz).  ≙ the role of the reference's `bench --write` dumps:
    cross-validating algorithm outputs rather than timing them."""
    import sys


    opts = opts or Options(block_alloc=BlockAlloc.ALLMODE)
    dtype = resolve_dtype(opts, tt.vals.dtype)
    factors = init_factors(tt.dims, rank, opts.seed() or 1, dtype=dtype)
    inds = jnp.asarray(tt.inds)
    vals = jnp.asarray(tt.vals, dtype=dtype)
    bs = BlockedSparse.from_coo(tt, opts)
    worst = 0.0
    skipped = 0
    for mode in range(tt.nmodes):
        ref = np.asarray(mttkrp_stream(inds, vals, factors, mode,
                                       tt.dims[mode]))
        for alg in algs:
            if alg == "stream":
                continue
            if alg == "ttbox":
                out = mttkrp_ttbox(inds, vals, factors, mode,
                                   tt.dims[mode])
            elif alg == "native":
                from splatt_tpu.ops.mttkrp import _run_native, plan_mttkrp

                layout = bs.layout_for(mode)
                out = (_run_native(layout, factors, mode)
                       if plan_mttkrp(bs, factors, mode,
                                      impl="native").engine == "native"
                       else None)
                if out is None:
                    skipped += 1
                    continue
            else:
                layout = bs.layout_for(mode)
                plan = _alg_plan(alg, layout, mode, tt.dims[mode], opts)
                if plan is None:
                    skipped += 1
                    continue
                path, impl = plan
                out = mttkrp_blocked(layout, factors, mode, path=path,
                                     impl=impl)
            scale = max(float(np.max(np.abs(ref))), 1.0)
            dev = float(np.max(np.abs(np.asarray(out) - ref))) / scale
            worst = max(worst, dev)
    if skipped:
        print(f"crosscheck: {skipped} (alg, mode) configs skipped "
              f"(privatized width over priv_cap)", file=sys.stderr)
    return worst


def format_bench(results: Dict[str, List[float]]) -> str:
    lines = []
    for alg, times in results.items():
        cols = "  ".join(f"mode{m}: {'  nan  ' if np.isnan(t) else f'{t:0.5f}'}"
                         for m, t in enumerate(times))
        total = np.nansum(times)
        lines.append(f"  {alg:<16s} {cols}  total: {total:0.5f}s")
    return "\n".join(lines)


# -- roofline model ---------------------------------------------------------

#: HBM peak bandwidth by device-kind prefix (GB/s).  Sources: public
#: TPU spec sheets (v4 1228, v5e 819, v5p 2765, v6e "Trillium" 1640).
HBM_PEAK_GBS = (("TPU v6", 1640.0), ("TPU v5p", 2765.0),
                ("TPU v5", 819.0), ("TPU v4", 1228.0), ("TPU v3", 900.0),
                ("TPU v2", 700.0))


def hbm_peak_gbs() -> Optional[float]:
    """Peak HBM bandwidth of device 0, or None off-TPU."""
    try:
        kind = jax.devices()[0].device_kind
    # splint: ignore[SPL002] device discovery off-accelerator: absence
    # of a backend is the signal (no roofline), not a failure to route
    except Exception:
        return None
    for prefix, gbs in HBM_PEAK_GBS:
        if kind.startswith(prefix):
            return gbs
    return None


def mttkrp_bytes(alg: str, tt: SparseTensor, rank: int, mode: int,
                 itemsize: int, layout=None) -> float:
    """First-order HBM bytes moved by one MTTKRP (the roofline model
    the blocked format was designed against; ≙ the hand arithmetic of
    the reference's perf analysis).  Counts logical traffic: index +
    value streams, one factor-row fetch per nonzero per input mode
    (gathers on sparse coordinates miss), and the output — plus each
    algorithm's own intermediates:

    - stream/scatter: gather+Hadamard fuse into the segment/scatter
      sum, no intermediate;
    - blocked (one-hot, xla_scan engine): block partials (nb, S, R)
      written then scatter-combined (read+write);
    - blocked_pallas fused engines: the factor TABLES stream once
      (VMEM-resident) instead of once per nonzero — the design's
      whole premise — plus the same partials;
    - ttbox: one full index+value pass per rank column.
    """
    nnz = tt.nnz
    nmodes = tt.nmodes
    acc = 4  # f32 accumulator width
    out = tt.dims[mode] * rank * acc
    idx_val = nnz * (nmodes * 4 + itemsize)
    rows = (nmodes - 1) * nnz * rank * itemsize
    if alg == "stream":
        return idx_val + rows + out
    if alg == "ttbox":
        return rank * (idx_val + (nmodes - 1) * nnz * itemsize) + out
    if alg == "scatter":
        return idx_val + rows + out
    if alg in ("blocked", "blocked_pallas"):
        nb = layout.nblocks if layout is not None else 1
        S = layout.seg_width if layout is not None else 8
        partials = 2 * nb * S * rank * acc
        if alg == "blocked_pallas":
            tables = sum(d * rank * itemsize
                         for k, d in enumerate(tt.dims) if k != mode)
            return idx_val + tables + partials + out
        return idx_val + rows + partials + out
    if alg == "native":
        return idx_val + rows + out
    raise ValueError(f"unknown algorithm {alg!r}")


def mttkrp_bytes_encoded(alg: str, X: BlockedSparse, rank: int, mode: int,
                         factor_itemsize: int) -> float:
    """ACHIEVED HBM bytes of one MTTKRP over a compiled
    :class:`BlockedSparse` — the same traffic structure as
    :func:`mttkrp_bytes`, but the index/value streams are costed at the
    layout's STORED widths (``ModeLayout.storage_bytes``: narrow v2
    local indices + per-block bases, bf16 values) and the factor terms
    at the factors' actual itemsize.  This is what bench reports per
    path (docs/format.md): the fixed i32/f32 model would claim the
    compact format moves bytes it no longer does.
    """
    lay = X.layout_for(mode)
    nmodes, nnz = lay.nmodes, lay.nnz
    acc = 4  # f32 accumulator width
    out = X.dims[mode] * rank * acc
    streams = lay.storage_bytes()     # encoded idx + bases + vals + starts
    if getattr(lay, "encoding", "v1") == "dense":
        # dense tile layout (docs/dense.md): value tiles + pad mask
        # stream once (storage_bytes — ZERO index bytes, the point of
        # the format), the non-mode factor tables stream once into the
        # Khatri-Rao operand, and the KR matrix (span x R) is
        # materialized (write + read)
        tables = sum(d * rank * factor_itemsize
                     for k, d in enumerate(X.dims) if k != mode)
        kr = 2 * lay.span * rank * factor_itemsize
        return streams + tables + kr + out
    rows = (nmodes - 1) * nnz * rank * factor_itemsize
    if alg in ("blocked", "blocked_pallas"):
        partials = 2 * lay.nblocks * lay.seg_width * rank * acc
        if alg == "blocked_pallas":
            tables = sum(d * rank * factor_itemsize
                         for k, d in enumerate(X.dims) if k != mode)
            return streams + tables + partials + out
        return streams + rows + partials + out
    # stream/scatter formulation over the layout's encoded arrays
    return streams + rows + out


def mttkrp_decode_bytes(X: BlockedSparse, rank: int, mode: int,
                        engine: str) -> float:
    """Extra HBM bytes the named engine's operand prep spends DECODING
    an encoded layout before its kernel runs (docs/format.md) — the
    traffic the in-kernel decode line exists to delete.  Zero for v1
    layouts and for the stream-native engines
    (:data:`splatt_tpu.ops.mttkrp.STREAM_NATIVE_ENGINES`: fused_v2
    decodes in registers, xla_scan per scan chunk, the xla scatter
    inside its fusion).  The prep-decoding Pallas engines rematerialize
    every mode's global-i32 stream (write + read), and the transposed-
    table kernels additionally stream the sublane-replicated request
    tiles ``_prep_t_operands`` materializes — the reason "achieved
    bytes ≈ 2x encoded" before the fused_v2 engine.  bench reports the
    per-path ratio as ``decode_overhead`` next to
    ``model_gb_per_path``."""
    from splatt_tpu.ops.mttkrp import STREAM_NATIVE_ENGINES
    from splatt_tpu.utils.env import ceil_to

    lay = X.layout_for(mode)
    if (getattr(lay, "encoding", "v1") in ("v1", "dense")
            or engine in STREAM_NATIVE_ENGINES or engine == "native"):
        return 0.0
    decoded = 2.0 * lay.nmodes * lay.nnz_pad * 4   # i32 write + read
    if engine in ("fused_t", "fused_tg"):
        b_pad = ceil_to(lay.block, 128)
        for k, d in enumerate(X.dims):
            if k != mode:
                d_pad = ceil_to(int(d), 128)
                ck = -(-b_pad // d_pad)
                decoded += 2.0 * lay.nblocks * ck * 8 * d_pad * 4
    return decoded


#: MXU peak compute by device-kind prefix (bf16 GFLOP/s per chip).
#: Sources: public TPU spec sheets (v4 275 TFLOPS, v5e 197, v5p 459,
#: v6e "Trillium" 918).  The same prefix-match contract as
#: :data:`HBM_PEAK_GBS`.
MXU_PEAK_GFLOPS = (("TPU v6", 918000.0), ("TPU v5p", 459000.0),
                   ("TPU v5", 197000.0), ("TPU v4", 275000.0),
                   ("TPU v3", 123000.0), ("TPU v2", 45000.0))

#: nominal CPU peaks for the roofline VERDICT off-TPU (docs/dense.md):
#: the bound classification (memory- vs compute-bound) only needs the
#: ridge's order of magnitude, and CI runs the densemode bench on CPU —
#: a missing peak would silence the verdict legs exactly where they
#: gate.
NOMINAL_CPU_GBS = 50.0
NOMINAL_CPU_GFLOPS = 100.0


def mxu_peak_gflops() -> Optional[float]:
    """Peak MXU compute of device 0 (bf16 GFLOP/s), or None off-TPU."""
    try:
        kind = jax.devices()[0].device_kind
    # splint: ignore[SPL002] device discovery off-accelerator: absence
    # of a backend is the signal (no roofline), not a failure to route
    except Exception:
        return None
    for prefix, gflops in MXU_PEAK_GFLOPS:
        if kind.startswith(prefix):
            return gflops
    return None


def mttkrp_flops(alg: str, X: BlockedSparse, rank: int,
                 mode: int) -> float:
    """First-order flop count of one MTTKRP over a compiled
    :class:`BlockedSparse` — the compute half of the roofline model
    (docs/dense.md) beside the bytes-only :func:`mttkrp_bytes_encoded`.

    - dense tile layout: the batched matmul's MACs over the PADDED
      cell space (2 * cells * R — pad rows are real MXU work, which is
      exactly why the verdict thresholds padded density) plus the
      Khatri-Rao build (span * R multiplies);
    - sparse paths: one Hadamard chain + accumulate per nonzero per
      rank column (2 * nnz * R * (nmodes-1)), plus the one-hot
      expansion's dense MACs (2 * nblocks * S * block * R) for the
      one-hot algorithms — work amplification the bytes model cannot
      see.
    """
    lay = X.layout_for(mode)
    if getattr(lay, "encoding", "v1") == "dense":
        geo = lay.geometry
        return 2.0 * geo.cells * rank + geo.span * rank
    flops = 2.0 * lay.nnz * rank * (lay.nmodes - 1)
    if alg in ("blocked", "blocked_pallas"):
        flops += 2.0 * lay.nblocks * lay.seg_width * lay.block * rank
    return flops


def roofline_verdict(bytes_moved: float, flops: float) -> dict:
    """Classify one path against the device roofline: arithmetic
    intensity (flops/byte), the device ridge point (peak flops / peak
    bandwidth), and which side of it the path sits on.  Off-TPU the
    NOMINAL CPU peaks stand in — the bound verdict is an order-of-
    magnitude classification, not a measurement (docs/dense.md)."""
    peak_bw = hbm_peak_gbs() or NOMINAL_CPU_GBS
    peak_fl = mxu_peak_gflops() or NOMINAL_CPU_GFLOPS
    intensity = flops / max(bytes_moved, 1.0)
    ridge = peak_fl / peak_bw
    return dict(intensity=round(intensity, 3), ridge=round(ridge, 3),
                bound=("compute" if intensity >= ridge else "memory"))


def roofline_report(tt: SparseTensor, results: Dict[str, List[float]],
                    rank: int, itemsize: int,
                    layouts=None) -> List[str]:
    """Per-alg/mode effective bandwidth lines: model GB/s and, on TPU,
    % of the HBM peak (≙ src/bench.c printing per-algorithm times —
    extended with the bytes model so a reader sees headroom, not just
    seconds)."""
    peak = hbm_peak_gbs()
    lines = []
    for alg, times in results.items():
        cells = []
        for m, t in enumerate(times):
            if np.isnan(t) or t <= 0:
                cells.append(f"mode{m}:    --  ")
                continue
            lay = layouts[m] if layouts is not None else None
            gbs = mttkrp_bytes(alg, tt, rank, m, itemsize, lay) / t / 1e9
            pct = f" ({100 * gbs / peak:3.0f}%)" if peak else ""
            cells.append(f"mode{m}: {gbs:6.1f}{pct}")
        label = f"  {alg:<16s}"
        lines.append(label + "  ".join(cells)
                     + ("  GB/s of HBM peak" if peak else "  GB/s (model)"))
    return lines
