"""Incremental model updates — the `update` job kind (docs/batched.md).

The contracts under test:

- ACCEPTANCE: a seeded delta applied via an `update` job reaches fit
  within 1e-3 of a from-scratch refit of the merged tensor while
  running <= 25% of its sweeps, warm-started from the checkpointed
  model (delta-touched rows re-solved first);
- the journal/checkpoint store acts as a MODEL STORE: the update
  advances ckpt/<base>.npz and persists the merged COO beside it,
  updates chain (each loads the previous merge), re-runs are
  idempotent (the `applied` stamp), and the lineage is auditable
  through `splatt status --json` / fleetobs.fleet_status;
- repair paths: a missing model, the periodic
  SPLATT_UPDATE_REFIT_EVERY boundary, and a classified warm-path
  failure (the ``cpd.update`` fault site) all degrade to a full refit
  (``refit_scheduled``) — never a failed job;
- admission: update specs without base/delta (or with an unknown
  kind, or a dim-growing delta) are rejected/failed loudly.
"""

import os

import numpy as np
import pytest

from splatt_tpu import fleetobs, resilience, serve
from splatt_tpu.chaos import synthetic_tensor
from splatt_tpu.config import Options, Verbosity
from splatt_tpu.cpd import cpd_als, refresh_touched_rows, touched_rows
from splatt_tpu.utils import faults

DIMS = (20, 16, 12)
BASE = {"dims": list(DIMS), "nnz": 900, "seed": 3}
DELTA = {"dims": list(DIMS), "nnz": 60, "seed": 42}
ITERS = 20          # the base/refit budget
UP_SWEEPS = 5       # <= 25% of ITERS — the acceptance bound


@pytest.fixture(autouse=True)
def _clean_state():
    def clean():
        faults.reset()
        resilience.reset_demotions()
        resilience.run_report().clear()

    clean()
    yield
    clean()


def _base_spec(**kw):
    spec = {"id": "base", "rank": 3, "iters": ITERS, "seed": 7,
            "checkpoint_every": 2, "synthetic": dict(BASE)}
    spec.update(kw)
    return spec


def _up_spec(jid="up1", **kw):
    spec = {"id": jid, "kind": "update", "base": "base",
            "delta": dict(DELTA), "iters": UP_SWEEPS}
    spec.update(kw)
    return spec


def _merged_tensor(extra_deltas=()):
    tt = synthetic_tensor(tuple(BASE["dims"]), BASE["nnz"], BASE["seed"])
    for d in (DELTA, *extra_deltas):
        dt = synthetic_tensor(tuple(d["dims"]), d["nnz"], d["seed"])
        tt = serve._merge_delta(tt, dt)
    return tt


def _run(srv, *specs):
    for spec in specs:
        r = srv.submit(spec)
        assert r["state"] == serve.ACCEPTED, r
    srv.run_once()
    return [serve.read_result(srv.root, s["id"]) for s in specs]


# -- the update acceptance ---------------------------------------------------

def test_update_converges_within_epsilon_of_refit(tmp_path):
    srv = serve.Server(str(tmp_path), workers=1)
    (base_res,) = _run(srv, _base_spec())
    assert base_res["status"] == "converged"
    (up_res,) = _run(srv, _up_spec())
    assert up_res["status"] == "converged"
    info = up_res["update"]
    assert info["base"] == "base" and info["sweeps"] == UP_SWEEPS
    assert UP_SWEEPS <= ITERS // 4
    kinds = {e["kind"] for e in up_res["events"]}
    assert "update_applied" in kinds and "refit_scheduled" not in kinds
    # from-scratch refit of the SAME merged tensor, full budget
    refit = cpd_als(_merged_tensor(), rank=3,
                    opts=Options(random_seed=7, max_iterations=ITERS,
                                 tolerance=1e-5, autotune=None,
                                 verbosity=Verbosity.NONE))
    assert abs(up_res["fit"] - float(refit.fit)) < 1e-3


def test_update_advances_model_store_and_chains(tmp_path):
    srv = serve.Server(str(tmp_path), workers=1)
    _run(srv, _base_spec())
    ckpt = os.path.join(srv.ckpt_dir, "base.npz")
    model0 = open(ckpt, "rb").read()
    (up1,) = _run(srv, _up_spec("up1"))
    assert up1["update"]["update_n"] == 1
    # the model checkpoint advanced and the merged COO is persisted
    assert open(ckpt, "rb").read() != model0
    tpath = os.path.join(srv.ckpt_dir, "base.model.npz")
    tt, applied = serve._load_model_tensor(tpath)
    assert applied == ["up1"]
    assert tt.nnz == _merged_tensor().nnz
    # a second update CHAINS: it merges into the persisted tensor
    d2 = {"dims": list(DIMS), "nnz": 40, "seed": 43}
    (up2,) = _run(srv, _up_spec("up2", delta=d2))
    assert up2["status"] == "converged"
    assert up2["update"]["update_n"] == 2
    tt2, applied2 = serve._load_model_tensor(tpath)
    assert applied2 == ["up1", "up2"]
    assert tt2.nnz == _merged_tensor((d2,)).nnz


def test_update_rerun_is_idempotent(tmp_path):
    srv = serve.Server(str(tmp_path), workers=1)
    _run(srv, _base_spec())
    (up1,) = _run(srv, _up_spec("up1"))
    assert up1["status"] == "converged"
    tpath = os.path.join(srv.ckpt_dir, "base.model.npz")
    nnz_once = serve._load_model_tensor(tpath)[0].nnz
    # a crashed update's re-run (persist landed, terminal record did
    # not): the applied stamp stops the delta merging twice
    out, info = srv._run_update("up1", _up_spec("up1"), lambda: False)
    assert serve._load_model_tensor(tpath)[0].nnz == nnz_once
    assert float(out.fit) == pytest.approx(up1["fit"], abs=1e-3)


def test_update_lineage_auditable_via_status(tmp_path):
    srv = serve.Server(str(tmp_path), workers=1)
    _run(srv, _base_spec())
    _run(srv, _up_spec("up1"))
    # journal lineage: one accepted/started/done chain for the update
    recs, _ = serve.Journal(os.path.join(
        srv.root, "journal.jsonl")).replay()
    kinds = [r["rec"] for r in recs if r.get("job") == "up1"]
    assert kinds == [serve.ACCEPTED, serve.STARTED, serve.DONE]
    # client-side status audit (what `splatt status --json` prints)
    st = fleetobs.fleet_status(str(tmp_path))
    assert st["jobs"]["up1"] == serve.DONE
    rec = next(r for r in st["recent"] if r["job"] == "up1")
    assert rec["kind"] == "update" and rec["base"] == "base"
    assert any("update_of=base" in line
               for line in fleetobs.format_status(st))
    # read_status rides the result along
    out = serve.read_status(str(tmp_path), "up1")
    assert out["state"] == serve.DONE
    assert out["result"]["update"]["base"] == "base"


# -- repair paths ------------------------------------------------------------

def test_update_without_model_refits(tmp_path):
    srv = serve.Server(str(tmp_path), workers=1)
    _run(srv, _base_spec(iters=1, checkpoint_every=10))
    # store retention loss: checkpoint + generation stamp gone (every
    # completed fit commits one now, so absence must be manufactured)
    for name in os.listdir(srv.ckpt_dir):
        if name.startswith("base."):
            os.remove(os.path.join(srv.ckpt_dir, name))
    (up,) = _run(srv, _up_spec())
    assert up["status"] == "converged"
    assert up["update"]["refit"] == "no_model"
    kinds = {e["kind"] for e in up["events"]}
    assert "refit_scheduled" in kinds and "update_applied" not in kinds


def test_periodic_refit_cadence(tmp_path, monkeypatch):
    monkeypatch.setenv("SPLATT_UPDATE_REFIT_EVERY", "2")
    srv = serve.Server(str(tmp_path), workers=1)
    _run(srv, _base_spec())
    (up1,) = _run(srv, _up_spec("up1"))
    assert "refit" not in up1["update"]          # update #1: warm
    (up2,) = _run(srv, _up_spec("up2"))
    assert up2["update"]["refit"] == "periodic"  # update #2: boundary
    assert up2["status"] == "converged"


def test_update_fault_degrades_to_refit(tmp_path):
    """The cpd.update fault site: a raised fault in the warm pre-pass
    repairs via a classified full refit, never a failed job."""
    srv = serve.Server(str(tmp_path), workers=1)
    _run(srv, _base_spec())
    (up,) = _run(srv, _up_spec("up1", faults="cpd.update:runtime"))
    assert up["status"] == "converged"
    assert up["update"]["refit"].startswith("failed:")
    kinds = {e["kind"] for e in up["events"]}
    assert "refit_scheduled" in kinds
    # the refit still advanced the model store
    assert os.path.exists(os.path.join(srv.ckpt_dir, "base.model.npz"))


def test_update_admission_validation(tmp_path):
    srv = serve.Server(str(tmp_path), workers=1)
    r = srv.submit({"id": "u1", "kind": "update",
                    "delta": dict(DELTA)})
    assert r["state"] == serve.REJECTED and "base" in r["reason"]
    r = srv.submit({"id": "u2", "kind": "update", "base": "base"})
    assert r["state"] == serve.REJECTED and "delta" in r["reason"]
    r = srv.submit({"id": "u3", "kind": "nope",
                    "synthetic": dict(BASE)})
    assert r["state"] == serve.REJECTED and "kind" in r["reason"]


def test_update_unknown_base_and_growing_delta_fail(tmp_path):
    from splatt_tpu.io import save

    srv = serve.Server(str(tmp_path), workers=1)
    (up,) = _run(srv, _up_spec("u1", base="ghost"))
    assert up["status"] == "failed"
    assert "unknown" in up["error"]
    _run(srv, _base_spec())
    # a delta whose indices name rows past the model's dims (on-disk
    # tensor: the synthetic generator compacts empty slices, so a
    # genuinely growing delta needs explicit coordinates)
    from splatt_tpu.coo import SparseTensor

    big = SparseTensor(np.array([[39], [2], [3]]), np.array([1.0]),
                       (40, 16, 12))
    path = str(tmp_path / "grow.tns")
    save(big, path)
    spec = _up_spec("u2")
    del spec["delta"]
    spec["delta_tensor"] = path
    (up2,) = _run(srv, spec)
    # the growing delta fails the warm path AND the refit path, loudly
    assert up2["status"] == "failed"
    assert "grows mode" in up2["error"]


# -- the warm pre-pass (cpd.refresh_touched_rows) ----------------------------

def test_touched_rows_and_refresh():
    tt = synthetic_tensor(DIMS, 400, seed=0)
    delta = synthetic_tensor(DIMS, 30, seed=1)
    touched = touched_rows(delta, tt.nmodes)
    for m in range(tt.nmodes):
        assert np.array_equal(touched[m],
                              np.unique(np.asarray(delta.inds[m])))
    opts = Options(random_seed=0, max_iterations=6, autotune=False,
                   verbosity=Verbosity.NONE)
    out = cpd_als(tt, rank=3, opts=opts)
    merged = serve._merge_delta(tt, delta)
    from splatt_tpu.blocked import BlockedSparse

    bs = BlockedSparse.from_coo(merged, opts)
    warm = refresh_touched_rows(bs, out.factors, touched)
    # untouched rows keep their converged values EXACTLY
    for m in range(tt.nmodes):
        untouched = np.setdiff1d(np.arange(DIMS[m]), touched[m])
        np.testing.assert_array_equal(
            np.asarray(warm[m])[untouched],
            np.asarray(out.factors[m])[untouched])
        # touched rows were re-solved (generically different)
        if touched[m].size:
            assert not np.array_equal(
                np.asarray(warm[m])[touched[m]],
                np.asarray(out.factors[m])[touched[m]])


def test_refresh_rows_fault_site_raises():
    """The cpd.update site fires inside the warm pre-pass itself —
    what the serve repair path classifies into a refit."""
    tt = synthetic_tensor(DIMS, 200, seed=0)
    delta = synthetic_tensor(DIMS, 10, seed=1)
    opts = Options(random_seed=0, max_iterations=2, autotune=False,
                   verbosity=Verbosity.NONE)
    out = cpd_als(tt, rank=3, opts=opts)
    with faults.inject("cpd.update", "runtime"):
        with pytest.raises(RuntimeError, match="injected"):
            refresh_touched_rows(tt, out.factors,
                                 touched_rows(delta, tt.nmodes))


def test_merge_delta_additive_and_validated():
    tt = synthetic_tensor(DIMS, 100, seed=0)
    delta = synthetic_tensor(DIMS, 10, seed=1)
    merged = serve._merge_delta(tt, delta)
    assert merged.nnz == tt.nnz + delta.nnz
    assert merged.dims == tt.dims
    assert merged.normsq() == pytest.approx(
        float(np.dot(np.concatenate([tt.vals, delta.vals]),
                     np.concatenate([tt.vals, delta.vals]))))
    from splatt_tpu.coo import SparseTensor

    grow = SparseTensor(np.array([[39], [2], [3]]), np.array([1.0]),
                        (40, 16, 12))
    with pytest.raises(ValueError, match="grows mode"):
        serve._merge_delta(tt, grow)
    fourmode = SparseTensor(np.array([[1], [2], [3], [0]]),
                            np.array([1.0]), (20, 16, 12, 4))
    with pytest.raises(ValueError, match="modes"):
        serve._merge_delta(tt, fourmode)


def test_corrupt_model_tensor_degrades(tmp_path):
    path = str(tmp_path / "m.model.npz")
    with open(path, "wb") as f:
        f.write(b"not an npz")
    tt, applied = serve._load_model_tensor(path)
    assert tt is None and applied == []
    evs = resilience.run_report().events("model_torn")
    assert evs and evs[0]["piece"] == "model-tensor"


def test_model_tensor_missing_applied_or_bad_checksum(tmp_path):
    """A model tensor without its idempotency stamp, or whose content
    checksum no longer matches, is TORN — classified degrade to the
    refit path, never silently trusted."""
    tt = synthetic_tensor(DIMS, 50, seed=0)
    path = str(tmp_path / "m.model.npz")
    serve._save_model_tensor(path, tt, ["u1"])
    got, applied = serve._load_model_tensor(path)
    assert got is not None and applied == ["u1"]

    # strip the applied stamp
    with np.load(path) as z:
        slim = {k: z[k] for k in ("inds", "vals", "dims")}
    np.savez(path, **slim)
    got, applied = serve._load_model_tensor(path)
    assert got is None and applied == []
    evs = resilience.run_report().events("model_torn")
    assert evs and "applied" in evs[-1]["error"]

    # flip a value under an otherwise-valid file: checksum catches it
    serve._save_model_tensor(path, tt, ["u1"])
    with np.load(path) as z:
        bad = {k: np.asarray(z[k]) for k in z.files}
    bad["vals"] = bad["vals"] + 1.0
    np.savez(path, **bad)
    got, applied = serve._load_model_tensor(path)
    assert got is None and applied == []
    assert any("checksum" in e["error"]
               for e in resilience.run_report().events("model_torn"))
