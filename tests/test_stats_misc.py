"""Stats text, ttbox algorithm, Kruskal save/load."""

import jax.numpy as jnp
import numpy as np
import pytest

from splatt_tpu.config import Options, Verbosity
from splatt_tpu.cpd import cpd_als
from splatt_tpu.kruskal import KruskalTensor
from splatt_tpu.ops.mttkrp import mttkrp_ttbox
from splatt_tpu.parallel.grid import GridDecomp
from splatt_tpu.stats import cpd_stats_text, grid_stats_text, tensor_stats
from tests import gen
from tests.test_mttkrp import TOL, make_factors, np_mttkrp


def test_ttbox_matches_oracle(any_tensor):
    tt = any_tensor
    factors = make_factors(tt.dims)
    for mode in range(tt.nmodes):
        got = mttkrp_ttbox(jnp.asarray(tt.inds), jnp.asarray(tt.vals),
                           factors, mode, tt.dims[mode])
        np.testing.assert_allclose(np.asarray(got),
                                   np_mttkrp(tt, factors, mode), atol=TOL)


def test_grid_stats_text():
    tt = gen.fixture_tensor("med")
    d = GridDecomp.build(tt, grid=(2, 2, 2), val_dtype=np.float64)
    txt = grid_stats_text(d)
    assert "GRID=2x2x2" in txt
    assert "CELLS=8" in txt
    assert "FILL=" in txt
    assert "CELL-NNZ min=" in txt


def test_tensor_and_cpd_stats_text():
    tt = gen.fixture_tensor("small")
    assert "DIMS=" in tensor_stats(tt)
    from splatt_tpu.blocked import BlockedSparse

    opts = Options(random_seed=1, val_dtype=np.float64)
    bs = BlockedSparse.from_coo(tt, opts)
    txt = cpd_stats_text(bs, 4, opts)
    assert "NFACTORS=4" in txt and "BLOCKED-ALLOC=" in txt


def test_kruskal_save_load_roundtrip(tmp_path):
    tt = gen.fixture_tensor("small")
    out = cpd_als(tt, rank=3,
                  opts=Options(random_seed=3, max_iterations=4,
                               verbosity=Verbosity.NONE,
                               val_dtype=np.float64))
    out.save(str(tmp_path))
    back = KruskalTensor.load(str(tmp_path), nmodes=tt.nmodes)
    for a, b in zip(out.factors, back.factors):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-15)
    np.testing.assert_allclose(np.asarray(out.lam), np.asarray(back.lam),
                               atol=1e-15)
    # reconstruction from the round-tripped tensor matches
    np.testing.assert_allclose(back.to_dense(), out.to_dense(), atol=1e-10)


def test_partition_quality_text():
    from splatt_tpu.stats import partition_quality_text

    tt = gen.fixture_tensor("med")
    rng = np.random.default_rng(0)
    parts = rng.integers(0, 4, size=tt.nnz)
    txt = partition_quality_text(tt, parts)
    assert "PARTS=4" in txt
    assert "TOTAL-CUT=" in txt
    # a single-part partition has zero cut
    txt1 = partition_quality_text(tt, np.zeros(tt.nnz, dtype=np.int64))
    assert "TOTAL-CUT=0" in txt1
