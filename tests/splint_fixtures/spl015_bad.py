"""SPL015 bad: two sites nest the same locks in opposite orders — two
threads walking the two paths deadlock (A waits for B's lock, B for
A's).  The acquisition-graph cycle is the static witness."""

import threading

_QUEUE_LOCK = threading.Lock()
_CACHE_LOCK = threading.Lock()


def drain_into_cache(queue, cache):
    with _QUEUE_LOCK:
        with _CACHE_LOCK:  # queue-lock -> cache-lock
            while queue:
                cache[queue.pop()] = True


def evict_into_queue(queue, cache):
    with _CACHE_LOCK:
        with _QUEUE_LOCK:  # cache-lock -> queue-lock: the cycle
            for key in list(cache):
                queue.append(cache.pop(key))
