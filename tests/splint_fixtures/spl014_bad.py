"""SPL014 bad: writes to declared shared structures without their
owning lock (the [tool.splint] shared-state map names the owners)."""

import threading

_TABLE = {}
_TABLE_LOCK = threading.Lock()


class Server:
    def __init__(self):
        self._lock = threading.Lock()
        self._jobs = {}

    def accept(self, jid, spec):
        # decided nothing, locked nothing: a worker thread iterating
        # _jobs concurrently sees a dict resized under its feet
        self._jobs[jid] = {"spec": spec, "state": "accepted"}

    def forget(self, jid):
        del self._jobs[jid]


def remember(key, value):
    _TABLE[key] = value  # module-global shared map, same hazard


def forget_all():
    _TABLE.clear()
