"""splatt-tune: empirical autotuner for MTTKRP engine plans.

The blocked format's speed comes from picking the right execution plan
per tensor — BENCH_r05 measured a 33x spread between dispatch paths on
the same tensor — yet the port used to hardcode the plan: one
``nnz_block`` (4096 + clamp), one ``scan_target``, an engine chain
ordered by static heuristics.  GenTen's performance-portable MTTKRP and
the load-balanced GPU MTTKRP line of work (PAPERS.md) both show the
winning kernel configuration depends on the nnz distribution, the rank
and the device: it must be *measured*, not guessed.  This module is
that measurement layer.

For a given (shape regime, rank, dtype) — the device kind lives in the
cache environment key — :func:`tune` times candidate plans per mode:

    engine (from :func:`splatt_tpu.ops.mttkrp.engine_chain`)
      x nnz_block in NNZ_BLOCKS
      x scan_target ladder (xla_scan engine only)

with short warm+timed runs, and persists each mode's winner in a
versioned on-disk **plan cache** next to the capability-probe cache.
The cache shares the probe cache's environment key (jax version, device
kind, ``_kernel_src_hash`` — editing a kernel source invalidates every
cached plan) and TTL (``SPLATT_PROBE_CACHE_TTL_S``), and applies the
same resilience verdict handling: engines demoted by the resilience
registry are never candidates, transient timing failures are retried in
place via :func:`resilience.retry_transient`, and deterministic or
resource failures are recorded as **negative entries** so a later tune
does not re-pay the failing compile.

Dispatch integration: :func:`splatt_tpu.ops.mttkrp.mttkrp_blocked`
consults :func:`cached_plan` first (the new head of dispatch) and falls
back to the heuristic chain when no applicable plan exists or autotune
is off (``Options.autotune`` / ``SPLATT_AUTOTUNE``);
:meth:`BlockedSparse.compile` consults :func:`tuned_blocks_for` so the
layouts are built at the tuned ``nnz_block`` directly.  ``splatt tune``
(cli.py) pre-tunes a tensor offline; bench.py reports a ``"tuned"``
timing next to ``"blocked"``/``"stream"``.

Plans are tuned against the mode's OWN sorted layout (the allmode-style
fast path).  A dispatch whose path or block disagrees with the stored
plan simply does not match it and keeps today's heuristics — the tuner
can make dispatch faster, never wronger.
"""

from __future__ import annotations

import dataclasses
import threading
import time
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

#: bump when the plan schema or the measurement methodology changes —
#: a cache written by an older tuner is re-tuned, not reinterpreted.
#: v2: plans carry the layout FORMAT (idx_width/val_storage,
#: docs/format.md) and were measured per encoding.
#: v3: plans carry the layout-BALANCE axes (fiber packing / reorder
#: recipe, docs/layout-balance.md) and the plan key gains a slice-skew
#: regime component so uniform-tuned plans never steer power-law
#: tensors.
#: v4: the delta/RLE catalog entries join the format candidates and
#: winners were measured in the in-kernel-decode era (the fused_v2
#: engine heads compact chains, docs/format.md) — plans tuned when
#: every engine paid operand-prep decode are re-earned, not
#: reinterpreted.
#: v5: the plan key gains a mode-density regime component and the
#: dense tile-layout candidates join the matrix (docs/dense.md) —
#: plans tuned when every mode was sparse-only are re-earned on
#: dense-eligible regimes, not reinterpreted.
PLAN_CACHE_VERSION = 5

#: candidate nnz blocks (build_layout clamps small tensors; duplicate
#: effective blocks are measured once)
NNZ_BLOCKS = (1024, 2048, 4096, 8192, 16384)

#: scan_target ladder for the xla_scan engine (elements of one-hot
#: materialized per scan step); the middle rung is the static default
SCAN_TARGETS = (1 << 21, 1 << 23, 1 << 25)

#: candidate index widths when the policy is not pinned: the v1 global
#: encoding, the compact v2 local/segment encoding, the u8 segment-id
#: narrowing, and the delta/RLE catalog entries (docs/format.md) —
#: when a regime's block spans exceed uint8 (or RLE would invert
#: compression, or a delta stream cannot narrow below "auto") the
#: candidate's encode degrades and collapses into an already-measured
#: one via the seen-dedup
IDX_CANDIDATES = ("i32", "auto", "u8", "delta", "rle")

#: candidate fiber-packing policies when the knob is not pinned
#: (docs/layout-balance.md): the fixed slicing and the nnz-balanced
#: fiber packing with long-fiber splitting.  A balanced pack that
#: degrades to fixed at build time collapses into the fixed candidate
#: via the seen-dedup (measured once).
PACKING_CANDIDATES = ("fixed", "balanced")

#: candidate reorder recipes when the knob is not pinned: identity plus
#: the relabeling strategies of splatt_tpu.reorder.  "random" is
#: deliberately not a default candidate (it exists to DESTROY locality
#: — a useful control, available pinned via Options.reorder /
#: SPLATT_REORDER).  Each recipe's permutation is computed once per
#: tune call and every candidate axis is measured over the relabeled
#: tensor; the verdict is whole-tensor at compile time
#: (BlockedSparse.compile resolves a unanimous winner).
REORDER_CANDIDATES = ("identity", "graph", "hgraph", "fibsched")

_AUTOTUNE_ENV = "SPLATT_AUTOTUNE"
_CACHE_ENV = "SPLATT_TUNE_CACHE"


@dataclasses.dataclass(frozen=True)
class TunedPlan:
    """One persisted dispatch decision: the measured-fastest
    (path, engine, nnz_block, scan_target, layout format) for a
    plan-cache key, plus the winning median seconds per MTTKRP call as
    evidence.  ``idx_width``/``val_storage`` name the encoding the
    winner was measured under (docs/format.md) — dispatch only applies
    a plan to a layout built at exactly that format."""

    path: str
    engine: str
    nnz_block: int
    scan_target: int
    sec: float
    idx_width: str = "i32"
    val_storage: str = "auto"
    #: layout-balance axes (docs/layout-balance.md): the fiber-packing
    #: policy and reorder recipe the winner was measured under —
    #: dispatch only applies a plan to a layout built at exactly them
    packing: str = "fixed"
    reorder: str = "identity"


#: The v5 plan-cache schema contract, in ONE declared place (splint
#: SPL027 audits the code against it in both directions):
#: ``key`` — the regime components :func:`plan_key` must fold in;
#: ``fields`` — every :class:`TunedPlan` field; ``match`` — the subset
#: dispatch must STRICT-compare against the built layout before
#: applying a plan (ops/mttkrp._tuned_plan_for); ``exempt`` — fields
#: that are evidence or applied outputs, never match predicates.
#: Growing TunedPlan/plan_key without updating this dict (and bumping
#: PLAN_CACHE_VERSION — the v2..v5 history above) is the silent
#: mis-dispatch drift class: a plan measured under one layout axis
#: steering a layout built under another.  cached_plan consults
#: ``fields`` so a foreign/partial cache entry is rejected as a
#: schema mismatch instead of half-read.
PLAN_SCHEMA = {
    "version": 5,
    "key": ("dims", "nnz", "mode", "rank", "dtype", "skew", "batch",
            "mode_density"),
    "fields": ("path", "engine", "nnz_block", "scan_target", "sec",
               "idx_width", "val_storage", "packing", "reorder"),
    "match": ("path", "nnz_block", "idx_width", "val_storage",
              "packing", "reorder"),
    "exempt": ("engine", "scan_target", "sec"),
}


@dataclasses.dataclass
class TuneResult:
    """What one :func:`tune` invocation did: the per-mode winning plans,
    how many candidate measurements actually ran (0 on a fully warm
    cache — the cache-hit contract bench and tests assert on), how many
    modes were satisfied straight from the cache, and how many
    candidates were skipped via negative entries or demotions."""

    plans: Dict[int, TunedPlan]
    measured: int = 0
    cache_hits: int = 0
    skipped: int = 0


# -- enablement -------------------------------------------------------------

def autotune_enabled(override: Optional[bool] = None) -> bool:
    """Whether dispatch consults the plan cache: an explicit
    ``Options.autotune`` wins; otherwise the SPLATT_AUTOTUNE env
    default (on unless 0/off/false/no)."""
    if override is not None:
        return bool(override)
    from splatt_tpu.utils.env import read_env

    return str(read_env(_AUTOTUNE_ENV)).lower() not in (
        "0", "off", "false", "no")


# -- plan-cache keys --------------------------------------------------------

def shape_regime(dims: Sequence[int], nnz: int) -> str:
    """Power-of-two shape regime: per-mode dim buckets + an nnz bucket.
    Tensors within 2x of each other per mode share plans — the same
    granularity at which the winning configuration actually moves."""
    db = "-".join(str(int(d).bit_length()) for d in dims)
    return f"m{len(dims)}:d{db}:z{int(max(nnz, 1)).bit_length()}"


def skew_regime(bucket: str) -> str:
    """The regime component of a slice-skew bucket
    (blocked.nnz_skew_bucket): near-uniform buckets (max/mean < 8)
    collapse to "" so uniform-tensor plan keys stay byte-identical to
    the pre-balance cache era; heavier skew keys its own regime — the
    winning layout on a zipf tensor (balanced packing, small
    seg_width) is a different animal from the uniform winner
    (docs/layout-balance.md)."""
    return "" if bucket in ("", "k0", "k1", "k2", "k3") else bucket


def skew_of(tt, mode: int) -> str:
    """The slice-skew bucket of one mode of a COO tensor — what
    build_layout stamps into ModeLayout.skew (permutation-invariant:
    relabeling shuffles the histogram, not its multiset)."""
    from splatt_tpu.blocked import nnz_skew_bucket

    return nnz_skew_bucket(tt.mode_histogram(mode))


def plan_key(dims: Sequence[int], nnz: int, mode: int, rank: int,
             dtype, skew: str = "", batch: int = 1,
             mode_density: str = "") -> str:
    """The cache key of one tuned dispatch site.  Device kind and
    kernel-source hash live in the environment key (shared with the
    probe cache), so this only carries the workload shape — plus the
    mode's slice-skew regime (:func:`skew_regime`; "" for
    near-uniform, keeping legacy keys byte-identical), the mode's
    density regime (blocked.mode_density_bucket, docs/dense.md; "" for
    genuinely sparse modes, keeping legacy keys byte-identical — a
    plan tuned on a near-dense mode never steers a sparse one) and,
    for the batched fleet engine (docs/batched.md), a power-of-two
    batch-size bucket: a plan measured under one vmapped batch never
    steers single-tensor dispatch (or the reverse) — ``batch=1``
    (every pre-batch caller) keeps legacy keys byte-identical."""
    import jax.numpy as jnp

    sk = skew_regime(skew)
    md = str(mode_density or "")
    bt = f":bk{int(batch).bit_length()}" if int(batch) > 1 else ""
    return (f"{shape_regime(dims, nnz)}:mode{mode}:r{int(rank)}"
            f":{jnp.dtype(dtype).name}" + (f":{sk}" if sk else "")
            + (f":{md}" if md else "") + bt)


def _negative_key(key: str, engine: str, block: int, scan_target: int,
                  fmt: str = "i32-auto") -> str:
    return f"neg:{key}:{engine}:b{block}:s{scan_target}:{fmt}"


# -- on-disk plan cache -----------------------------------------------------
#
# Shares machinery with the capability-probe cache
# (ops/pallas_kernels.py): the same environment key — jax version |
# device kind | _kernel_src_hash, so editing any kernel source
# invalidates every cached plan — the same TTL
# (SPLATT_PROBE_CACHE_TTL_S), and the same locked atomic
# read-modify-write so concurrent tuners do not drop each other's
# plans.  Cache IO is best-effort by the same contract: a broken cache
# degrades to re-tuning (and ultimately to the heuristic chain), never
# to a failed dispatch.

#: process-wide plan-cache path override (beats the env var): the
#: chaos harness points measurements at a throwaway file so a soak run
#: cannot dirty the real cache with plans measured under injected
#: faults.  None = env/default resolution.
_cache_path_override: Optional[str] = None


def set_cache_path(path: Optional[str]) -> None:
    """Override the plan-cache file for this process (None restores
    the env/default resolution).  Clears the in-process memo so stale
    entries from the previous file cannot leak across."""
    global _cache_path_override
    _cache_path_override = str(path) if path is not None else None
    reset_memo()


def cache_path():
    """The plan-cache file: the process override, else
    $SPLATT_TUNE_CACHE, else tune_cache.json next to the probe cache."""
    import pathlib

    from splatt_tpu.ops.pallas_kernels import _cache_path
    from splatt_tpu.utils.env import read_env

    if _cache_path_override:
        return pathlib.Path(_cache_path_override)
    p = read_env(_CACHE_ENV)
    if p:
        return pathlib.Path(p)
    return _cache_path().with_name("tune_cache.json")


def _cache_io_error(op: str, exc) -> None:
    """Route a plan-cache IO failure through the failure taxonomy into
    the run report (same contract as the probe cache's helper)."""
    from splatt_tpu import resilience

    resilience.run_report().add(
        "tune_cache_io_error", op=op,
        failure_class=resilience.classify_failure(exc).value,
        error=resilience.failure_message(exc)[:200])


#: in-process memo of resolved cache entries, keyed
#: (cache file, env key, entry key) -> entry dict | False (negative).
#: Dispatch consults the plan once per (mode, sweep) — the memo keeps
#: that a dict lookup instead of a JSON parse per MTTKRP.  Guarded by
#: a lock: concurrent serve jobs share this memo (warm plans are the
#: point of multi-tenancy — docs/serve.md), and a reset racing a
#: write-through must not resurrect an entry from a replaced cache
#: file.
#: under SPLATT_LOCKCHECK the memo is an owner-assertion proxy
#: (utils/lockcheck.py — the SPL014 dynamic cross-check); otherwise
#: both pass through as a plain dict and Lock
from splatt_tpu.utils import lockcheck as _lockcheck

_MEM_LOCK = _lockcheck.guard_lock(threading.Lock())
_MEM: dict = _lockcheck.guard({}, _MEM_LOCK, "tune._MEM")

#: lookup-miss sentinel (None is a legitimate memoized value)
_MISS = object()


def reset_memo() -> None:
    """Forget memoized cache entries (tests; a re-tune in-process)."""
    with _MEM_LOCK:
        _MEM.clear()


def _load_file() -> Optional[dict]:
    from splatt_tpu.ops.pallas_kernels import _json_cache_load

    # the shared read helper owns the degradation contract: missing
    # file -> None, unreadable/corrupt -> reported through the taxonomy
    # (as tune_cache_io_error here) and degraded to a re-tune — a
    # broken cache must never break dispatch
    data = _json_cache_load(cache_path(), on_error=_cache_io_error)
    if not isinstance(data, dict) \
            or data.get("version") != PLAN_CACHE_VERSION:
        # a different schema generation: re-tune rather than reinterpret
        return None
    return data


def _entry_get(key: str) -> Optional[dict]:
    """Resolve one cache entry (plan or negative) with TTL expiry,
    memoized per (file, environment)."""
    from splatt_tpu.ops.pallas_kernels import (_cache_env_key,
                                               probe_cache_ttl)

    memo_key = (str(cache_path()), _cache_env_key(), key)
    with _MEM_LOCK:
        hit = _MEM.get(memo_key, _MISS)
    if hit is not _MISS:
        return hit if hit is not False else None
    entry = None
    data = _load_file()
    if data is not None:
        try:
            entry = data.get("envs", {}).get(_cache_env_key(), {}).get(key)
            if entry is not None:
                ttl = probe_cache_ttl()
                if ttl > 0 and time.time() - float(entry.get("ts", 0)) > ttl:
                    entry = None  # expired: re-earn the plan
        except (AttributeError, TypeError, ValueError) as e:
            # malformed entry (hand-edited file, schema drift): an
            # unusable plan, not a dispatch failure — report and re-tune
            _cache_io_error("load", e)
            entry = None
    with _MEM_LOCK:
        # never clobber a concurrent write-through: a sibling job's
        # _entry_store may have landed between our file read and here,
        # and overwriting its fresh entry with our (older-read) miss
        # would negative-cache a persisted plan for the process life
        cur = _MEM.get(memo_key, _MISS)
        if cur is _MISS:
            _MEM[memo_key] = entry if entry is not None else False
        else:
            entry = cur if cur is not False else None
    return entry


def _entry_store(key: str, value: dict) -> None:
    """Persist one entry (locked atomic read-modify-write shared with
    the probe cache); write-through to the in-process memo."""
    from splatt_tpu.ops.pallas_kernels import (_cache_env_key,
                                               _json_cache_update)

    entry = dict(value, ts=time.time())
    env_key = _cache_env_key()

    def mutate(data):
        if data.get("version") != PLAN_CACHE_VERSION:
            # new or foreign-generation file: (re)start this schema
            data.clear()
            data["version"] = PLAN_CACHE_VERSION
        data.setdefault("envs", {}).setdefault(env_key, {})[key] = entry
        return data

    _json_cache_update(cache_path(), mutate, on_error=_cache_io_error)
    with _MEM_LOCK:
        _MEM[(str(cache_path()), env_key, key)] = entry


def cached_plan(dims: Sequence[int], nnz: int, mode: int, rank: int,
                dtype, skew: str = "",
                mode_density: str = "") -> Optional[TunedPlan]:
    """The persisted winning plan for this dispatch site, or None
    (never tuned, expired, negative-only, or unreadable cache)."""
    entry = _entry_get(plan_key(dims, nnz, mode, rank, dtype, skew=skew,
                                mode_density=mode_density))
    if not entry or "plan" not in entry:
        return None
    p = entry["plan"]
    unknown = set(p) - set(PLAN_SCHEMA["fields"])
    if unknown:
        # field drift without a version bump (a foreign-schema writer):
        # reject the entry classified instead of half-reading it
        _cache_io_error("load", ValueError(
            f"plan entry carries undeclared fields {sorted(unknown)}"))
        return None
    try:
        return TunedPlan(path=str(p["path"]), engine=str(p["engine"]),
                         nnz_block=int(p["nnz_block"]),
                         scan_target=int(p["scan_target"]),
                         sec=float(p.get("sec", 0.0)),
                         idx_width=str(p.get("idx_width", "i32")),
                         val_storage=str(p.get("val_storage", "auto")),
                         packing=str(p.get("packing", "fixed")),
                         reorder=str(p.get("reorder", "identity")))
    except (KeyError, TypeError, ValueError) as e:
        _cache_io_error("load", e)
        return None


def tuned_build_for(tt, rank: int, dtype) -> Dict[int, TunedPlan]:
    """Per-mode cached plans — what :meth:`BlockedSparse.compile`
    builds layouts with (winning ``nnz_block`` AND encoding:
    idx_width/val_storage, docs/format.md, AND the layout-balance axes:
    packing/reorder, docs/layout-balance.md), so the layout is built
    once at the tuned configuration instead of rebuilt when the plan
    disagrees with the default.  Takes the COO tensor (not just
    dims/nnz): the plan key's skew component needs the mode
    histograms."""
    from splatt_tpu.blocked import mode_density_bucket

    out = {}
    for m in range(tt.nmodes):
        plan = cached_plan(tt.dims, tt.nnz, m, rank, dtype,
                           skew=skew_of(tt, m),
                           mode_density=mode_density_bucket(
                               tt.dims, m, tt.nnz))
        if plan is not None:
            out[m] = plan
    return out


def tuned_blocks_for(tt, rank: int, dtype) -> Dict[int, int]:
    """Per-mode tuned nnz_block for every mode with a cached plan
    (the block-only view of :func:`tuned_build_for`)."""
    return {m: p.nnz_block
            for m, p in tuned_build_for(tt, rank, dtype).items()}


def batched_block_for(dims: Sequence[int], nnz: int, mode: int,
                      rank: Optional[int], dtype, k: int,
                      autotune: Optional[bool] = None) -> Optional[int]:
    """The tuned ``nnz_block`` for a BATCHED dispatch of `k` same-regime
    tensors (docs/batched.md), or None (untuned — the caller falls back
    to the options default).

    Consults the batch-axis plan key first (a verdict measured under
    vmapped batching), then the single-tensor key for the same site
    (a reasonable prior: the batch axis multiplies work per block but
    does not change the block's internal shape).  The batched engine
    consumes only the block size today; the full candidate walk stays
    single-tensor (``splatt tune``)."""
    if rank is None or not autotune_enabled(autotune):
        return None
    entry = _entry_get(plan_key(dims, nnz, mode, rank, dtype, batch=k))
    if entry and "plan" in entry:
        try:
            return int(entry["plan"]["nnz_block"])
        except (KeyError, TypeError, ValueError) as e:
            _cache_io_error("load", e)
    plan = cached_plan(dims, nnz, mode, rank, dtype)
    return plan.nnz_block if plan is not None else None


# -- measurement ------------------------------------------------------------

def _measure_candidate(layout, factors, mode: int, path: str, impl: str,
                       engine: str, scan_target: int,
                       warm: int = 1, reps: int = 2) -> float:
    """Median seconds of one forced-engine MTTKRP over `layout` after
    `warm` warm-up calls (compile excluded).  Module-level so tests can
    substitute the timing body without touching the candidate walk."""
    from splatt_tpu import resilience
    from splatt_tpu.ops.mttkrp import _mttkrp_blocked_jit
    from splatt_tpu.utils import faults
    from splatt_tpu.utils.env import host_fence

    def call():
        return _mttkrp_blocked_jit(layout, factors, mode, path, impl,
                                   scan_target, engine)

    # deadline watchdog (docs/guarded-als.md): one pathological
    # candidate's compile must not wedge the whole tune; a blown
    # deadline classifies TIMEOUT — skipped this session, never
    # persisted as a negative entry (slow today may be fine tomorrow)
    from splatt_tpu import trace

    with trace.span("tune.measure", mode=int(mode), path=path,
                    engine=engine, block=int(layout.block),
                    scan_target=int(scan_target)):
        with resilience.deadline("tuner.measure"):
            faults.maybe_fail("tuner.measure")
            for _ in range(max(warm, 1)):
                host_fence(call())
            times = []
            for _ in range(max(reps, 1)):
                t0 = time.perf_counter()
                host_fence(call())
                times.append(time.perf_counter() - t0)
    times.sort()
    return times[len(times) // 2]


def _tune_impl(opts) -> str:
    """The jit engine family candidates are measured under.  The native
    host engine sits before the blocked jit dispatch (plans do not
    govern it), and interpret mode's timings are meaningless — both
    coerce to the XLA family."""
    from splatt_tpu.ops.mttkrp import choose_impl

    impl = choose_impl(opts)
    if impl in ("native", "pallas_interpret"):
        return "xla"
    return impl


def _format_candidates(opts, dtype) -> List[Tuple[str, str]]:
    """(idx_width, val_storage) format candidates (docs/format.md).

    A pinned knob (an explicit ``Options.idx_width``/``val_storage``
    or an explicitly-set SPLATT_IDX_WIDTH/SPLATT_VAL_STORAGE) is
    measured alone; unpinned knobs span the candidate matrix — both
    index encodings, and bf16 value storage next to the compute dtype
    when computing in f32 (the only dtype a bf16 narrowing is a
    *format* choice for rather than a numerics change the caller
    already made).  The cheapest measured format wins per regime; the
    bit-parity (u16/seg) and fit-parity (bf16) test suites are what
    keep "cheapest" and "correct" the same set."""
    import jax.numpy as jnp

    from splatt_tpu.utils.env import env_is_set, read_env

    if opts.idx_width is not None:
        idx = (opts.idx_width,)
    elif env_is_set("SPLATT_IDX_WIDTH"):
        idx = (str(read_env("SPLATT_IDX_WIDTH")),)
    else:
        idx = IDX_CANDIDATES
    if opts.val_storage is not None:
        val = (opts.val_storage,)
    elif env_is_set("SPLATT_VAL_STORAGE"):
        val = (str(read_env("SPLATT_VAL_STORAGE")),)
    elif jnp.dtype(dtype) == jnp.dtype("float32"):
        val = ("auto", "bf16")
    else:
        val = ("auto",)
    return [(i, v) for i in idx for v in val]


def _packing_candidates(opts) -> Tuple[str, ...]:
    """Fiber-packing candidates: a pinned knob (explicit
    ``Options.fiber_packing`` or an explicitly-set
    SPLATT_FIBER_PACKING) is measured alone; unpinned spans both
    policies (docs/layout-balance.md).  Resolution goes through
    config.packing_pinned so a typo'd policy fails with its clear
    message up front, not deep inside a mid-tune build."""
    from splatt_tpu.config import packing_pinned

    pinned = packing_pinned(opts)
    return (pinned,) if pinned is not None else PACKING_CANDIDATES


def _reorder_candidates(opts) -> Tuple[str, ...]:
    """Reorder-recipe candidates: a pinned knob (``Options.reorder`` /
    a set SPLATT_REORDER) is measured alone; unpinned spans
    :data:`REORDER_CANDIDATES`."""
    from splatt_tpu.config import resolve_reorder

    pinned = resolve_reorder(opts)
    return (pinned,) if pinned is not None else REORDER_CANDIDATES


def _candidates(layout, factors, mode: int, path: str, impl: str,
                scan_targets: Sequence[int],
                default_scan: int) -> List[Tuple[str, int]]:
    """(engine, scan_target) candidates for one layout: every live
    engine_chain entry (demoted engines are pruned there — they are
    never candidates), with the scan ladder applied only to the
    xla_scan engine (the only consumer of scan_target)."""
    from splatt_tpu.ops.mttkrp import engine_chain

    out = []
    for engine in engine_chain(layout, factors, mode, path, impl):
        if engine == "xla_scan":
            out.extend((engine, int(st)) for st in scan_targets)
        else:
            out.append((engine, int(default_scan)))
    return out


def tune(tt, rank: int, opts=None, modes: Optional[Sequence[int]] = None,
         blocks: Optional[Sequence[int]] = None,
         scan_targets: Optional[Sequence[int]] = None,
         formats: Optional[Sequence[Tuple[str, str]]] = None,
         packings: Optional[Sequence[str]] = None,
         reorders: Optional[Sequence[str]] = None,
         warm: int = 1, reps: int = 2, force: bool = False) -> TuneResult:
    """Tune the MTTKRP plan for each mode of `tt` at `rank` and persist
    the winners in the plan cache.

    The candidate matrix is reorder x packing x engine x nnz_block x
    scan_target x FORMAT (docs/format.md, docs/layout-balance.md): each
    (idx_width, val_storage) pair from :func:`_format_candidates` (or
    an explicit `formats`) is measured against the same sorted build —
    the v2/bf16 re-encodings are derived without re-sorting — so the
    cheapest *correct* encoding wins empirically per regime.
    bf16-storage candidates are measured with bf16 factors (the
    configuration that actually dispatches), and a winner whose storage
    narrows the compute dtype is stored under BOTH the requested
    dtype's key (for compile-time layout building) and the storage
    dtype's key (for dispatch-time steering, where the factors already
    carry the narrow dtype).

    Layout-balance axes: each reorder recipe's permutation is computed
    ONCE per tune call (a failed recipe degrades classified via
    apply_reorder and is skipped); each (block, packing) pair is one
    sorted build, with a balanced pack that degraded to fixed
    collapsing into the fixed candidate via the seen-dedup.  Plans
    record the recipe, not the permutation — BlockedSparse.compile
    recomputes it deterministically (reorder.REORDER_SEED) and
    resolves a whole-tensor verdict.

    Already-cached (unexpired) plans short-circuit their mode entirely
    — a warm cache runs ZERO measurements (``result.measured == 0``),
    which is what makes pre-tuning with ``splatt tune`` pay off.  Pass
    ``force=True`` to re-measure anyway.

    Failure handling follows the resilience taxonomy: transient timing
    failures retry in place with backoff, deterministic/resource
    failures persist as negative entries (skipped by later tunes),
    unknown failures skip the candidate for this session only, and a
    measurement that blows the deadline watchdog (TIMEOUT,
    docs/guarded-als.md) is skipped this session but never persisted —
    a wedged relay today must not blacklist a healthy candidate
    forever.  A mode where every candidate fails gets NO plan —
    dispatch keeps the heuristic chain, recorded as a
    ``tuner_degraded`` run-report event.
    """
    import jax.numpy as jnp

    from splatt_tpu import resilience, trace
    from splatt_tpu.blocked import build_layout, reencode_layout
    from splatt_tpu.config import (LayoutFormat, Verbosity, default_opts,
                                   resolve_dtype, resolve_storage_dtype)
    from splatt_tpu.cpd import init_factors
    from splatt_tpu.ops.mttkrp import _SCAN_TARGET, choose_path
    from splatt_tpu.utils.env import read_env_int

    opts = (opts or default_opts()).validate()
    dtype = resolve_dtype(opts, tt.vals.dtype)
    impl = _tune_impl(opts)
    default_scan = read_env_int("SPLATT_SCAN_TARGET_ELEMS") or _SCAN_TARGET
    blocks = tuple(blocks) if blocks else NNZ_BLOCKS
    scan_targets = tuple(scan_targets) if scan_targets else SCAN_TARGETS
    formats = (list(formats) if formats
               else _format_candidates(opts, dtype))
    packings = tuple(packings) if packings else _packing_candidates(opts)
    reorders = tuple(reorders) if reorders else _reorder_candidates(opts)
    modes = range(tt.nmodes) if modes is None else modes
    loud = opts.verbosity >= Verbosity.LOW
    # one relabeled tensor per recipe, computed once (a recipe whose
    # permutation fails degrades classified inside apply_reorder — its
    # candidates are skipped, identity keeps the floor).  Shapes, nnz
    # and per-mode skew are permutation-invariant, so every recipe
    # shares the same plan key and factor operands.
    from splatt_tpu.reorder import apply_reorder

    tensors = None

    def reorder_tensors():
        # lazy, built on the FIRST cache miss only: a fully-warm tune()
        # must stay free (result.measured == 0 AND no O(nnz) permutation
        # builds or relabeled index copies) — serve's Nth same-regime
        # job and the bench tuned path rely on that contract.
        nonlocal tensors
        if tensors is None:
            tensors = {}
            for how in reorders:
                if how == "identity":
                    tensors[how] = tt
                else:
                    tt_r, rperm = apply_reorder(tt, how)
                    if rperm is not None:
                        tensors[how] = tt_r
                    elif loud:
                        print(f"  tune: reorder recipe {how!r} failed "
                              f"(classified); skipping its candidates")
        return tensors
    # plan-independent factor operands: the timing only needs shapes
    # and a realistic dtype, not the caller's actual factors.  Narrow-
    # storage candidates measure with matching narrow factors (memoized
    # casts — the real dispatch they stand for runs that way).
    factors = init_factors(tt.dims, rank, seed=0, dtype=dtype)
    facs_by_dtype = {jnp.dtype(dtype): factors}

    def factors_for(storage):
        sd = jnp.dtype(storage)
        if sd not in facs_by_dtype:
            facs_by_dtype[sd] = [f.astype(sd) for f in factors]
        return facs_by_dtype[sd]

    from splatt_tpu.blocked import mode_density_bucket

    result = TuneResult(plans={})
    for m in modes:
        skew = skew_of(tt, m)
        md = mode_density_bucket(tt.dims, m, tt.nnz)
        key = plan_key(tt.dims, tt.nnz, m, rank, dtype, skew=skew,
                       mode_density=md)
        if not force:
            plan = cached_plan(tt.dims, tt.nnz, m, rank, dtype, skew=skew,
                               mode_density=md)
            # always-on metrics (docs/observability.md): the serve
            # fleet's warm-cache payoff as a Prometheus series
            trace.metric_inc("splatt_tune_cache_total",
                             outcome="hit" if plan is not None
                             else "miss")
            if plan is not None:
                result.cache_hits += 1
                result.plans[m] = plan
                if loud:
                    print(f"  tune mode {m}: plan cache hit "
                          f"({plan.engine} b{plan.nnz_block} "
                          f"s{plan.scan_target} "
                          f"{plan.idx_width}/{plan.val_storage} "
                          f"{plan.packing}/{plan.reorder}) — "
                          f"skipping measurement")
                continue
        best: Optional[TunedPlan] = None
        seen = set()
        for how, tt_how in reorder_tensors().items():
            for req_block in blocks:
                for pack in packings:
                    base_layout = build_layout(
                        tt_how, m, block=int(req_block),
                        val_dtype=np.dtype(dtype),
                        mode_order=opts.mode_order,
                        mode_order_custom=opts.mode_order_custom,
                        packing=pack, reorder_label=how,
                        record_stats=False, dense=False)
                    path = choose_path(base_layout, m, opts)
                    for iw, vs in formats:
                        storage = resolve_storage_dtype(vs, dtype)
                        if (iw, vs) == ("i32", "auto"):
                            layout = base_layout
                        else:
                            # derive the candidate encoding from the one
                            # sorted build (a failed v2 encode degrades
                            # classified to v1 inside reencode_layout)
                            layout = reencode_layout(
                                base_layout, LayoutFormat(idx=iw, val=vs),
                                val_dtype=(None if jnp.dtype(storage) ==
                                           jnp.dtype(dtype) else storage))
                        cand_key = (layout.block, layout.idx_width,
                                    layout.val_storage, layout.packing,
                                    how)
                        if cand_key in seen:
                            continue  # clamp/fallback collapsed this one
                        seen.add(cand_key)
                        fac = factors_for(storage)
                        fmt_tag = (f"{layout.idx_width}-"
                                   f"{layout.val_storage}-"
                                   f"{layout.packing}-{how}")
                        for engine, st in _candidates(layout, fac, m,
                                                      path, impl,
                                                      scan_targets,
                                                      default_scan):
                            neg = _entry_get(_negative_key(
                                key, engine, layout.block, st, fmt_tag))
                            if neg is not None:
                                result.skipped += 1
                                continue

                            def attempt(layout=layout, fac=fac,
                                        path=path, engine=engine, st=st):
                                return _measure_candidate(
                                    layout, fac, m, path, impl, engine,
                                    st, warm=warm, reps=reps)

                            try:
                                sec = resilience.retry_transient(
                                    attempt, label=f"tuner.{engine}")
                            except Exception as e:
                                cls = resilience.classify_failure(e)
                                if cls in (
                                        resilience.FailureClass
                                        .DETERMINISTIC,
                                        resilience.FailureClass.RESOURCE):
                                    # proven: never re-pay this
                                    # candidate's compile
                                    _entry_store(
                                        _negative_key(key, engine,
                                                      layout.block, st,
                                                      fmt_tag),
                                        {"state": cls.value,
                                         "error":
                                         resilience
                                         .failure_message(e)[:200]})
                                resilience.run_report().add(
                                    "tuner_negative", key=key,
                                    engine=engine, block=layout.block,
                                    scan_target=st, fmt=fmt_tag,
                                    failure_class=cls.value,
                                    error=resilience
                                    .failure_message(e)[:200])
                                result.skipped += 1
                                continue
                            result.measured += 1
                            if loud:
                                print(f"  tune mode {m}: {path}/{engine} "
                                      f"b{layout.block} s{st} {fmt_tag}: "
                                      f"{sec:.4f}s")
                            if best is None or sec < best.sec:
                                best = TunedPlan(
                                    path=path, engine=engine,
                                    nnz_block=layout.block,
                                    scan_target=st, sec=sec,
                                    idx_width=layout.idx_width,
                                    val_storage=layout.val_storage,
                                    packing=layout.packing,
                                    reorder=how)
        # dense tile-layout candidates (docs/dense.md): measured
        # beside the sparse matrix whenever the policy allows and the
        # mode's geometry passes the density verdict.  One dense build
        # per value storage — the tile layout has no index-width or
        # packing axis, and a relabeling permutes cells without
        # changing density, so dense is measured under identity
        # reorder only.  A failed build (the format.dense fault site)
        # degrades classified to "no dense candidates", never a
        # failed tune.
        from splatt_tpu.blocked import build_dense_layout, \
            dense_mode_verdict
        from splatt_tpu.config import (resolve_dense,
                                       resolve_dense_threshold)
        from splatt_tpu.utils import faults

        pol = resolve_dense(opts)
        if pol != "off" and dense_mode_verdict(
                tt.dims, m, tt.nnz,
                threshold=resolve_dense_threshold(opts),
                force=(pol == "on")):
            for vs in dict.fromkeys(v for _, v in formats):
                storage = resolve_storage_dtype(vs, dtype)
                try:
                    faults.maybe_fail("format.dense")
                    dlay = build_dense_layout(
                        tt, m, val_dtype=np.dtype(storage))
                except Exception as e:
                    cls = resilience.classify_failure(e)
                    resilience.run_report().add(
                        "format_fallback", mode=m, site="dense",
                        idx_width="dense", failure_class=cls.value,
                        error=resilience.failure_message(e)[:200])
                    continue
                fac = factors_for(storage)
                fmt_tag = f"dense-{dlay.val_storage}-fixed-identity"
                for engine, st in _candidates(dlay, fac, m, "dense",
                                              impl, scan_targets,
                                              default_scan):
                    neg = _entry_get(_negative_key(
                        key, engine, dlay.block, st, fmt_tag))
                    if neg is not None:
                        result.skipped += 1
                        continue

                    def attempt_dense(dlay=dlay, fac=fac, engine=engine,
                                      st=st):
                        return _measure_candidate(
                            dlay, fac, m, "dense", impl, engine, st,
                            warm=warm, reps=reps)

                    try:
                        sec = resilience.retry_transient(
                            attempt_dense, label=f"tuner.{engine}")
                    except Exception as e:
                        cls = resilience.classify_failure(e)
                        if cls in (resilience.FailureClass.DETERMINISTIC,
                                   resilience.FailureClass.RESOURCE):
                            _entry_store(
                                _negative_key(key, engine, dlay.block,
                                              st, fmt_tag),
                                {"state": cls.value,
                                 "error":
                                 resilience.failure_message(e)[:200]})
                        resilience.run_report().add(
                            "tuner_negative", key=key, engine=engine,
                            block=dlay.block, scan_target=st,
                            fmt=fmt_tag, failure_class=cls.value,
                            error=resilience.failure_message(e)[:200])
                        result.skipped += 1
                        continue
                    result.measured += 1
                    if loud:
                        print(f"  tune mode {m}: dense/{engine} "
                              f"t{dlay.tile} {fmt_tag}: {sec:.4f}s")
                    if best is None or sec < best.sec:
                        best = TunedPlan(
                            path="dense", engine=engine,
                            nnz_block=dlay.tile, scan_target=st,
                            sec=sec, idx_width="dense",
                            val_storage=dlay.val_storage,
                            packing="fixed", reorder="identity")
        if best is None:
            # every candidate failed or was skipped: no plan — dispatch
            # keeps the heuristic chain (observable, not silent)
            resilience.run_report().add("tuner_degraded", mode=m, key=key)
            if loud:
                print(f"  tune mode {m}: no candidate measurable; "
                      f"dispatch keeps the heuristic chain")
            continue
        _entry_store(key, {"plan": dataclasses.asdict(best)})
        if best.path == "dense" and skew_regime(skew):
            # a dense layout has no nnz stream, so dispatch keys its
            # lookup with an empty skew bucket — alias the winner
            # there so a skewed-regime dense plan still steers
            # (the storage-dtype alias idiom below)
            _entry_store(plan_key(tt.dims, tt.nnz, m, rank, dtype,
                                  mode_density=md),
                         {"plan": dataclasses.asdict(best)})
        storage = resolve_storage_dtype(best.val_storage, dtype)
        if jnp.dtype(storage) != jnp.dtype(dtype):
            # a storage-narrowing winner also steers dispatch, where
            # the factors already carry the narrow dtype — alias the
            # plan under that key so the steering is not lost
            _entry_store(plan_key(tt.dims, tt.nnz, m, rank, storage,
                                  skew=skew, mode_density=md),
                         {"plan": dataclasses.asdict(best)})
        result.plans[m] = best
        if loud:
            print(f"  tune mode {m}: winner {best.path}/{best.engine} "
                  f"b{best.nnz_block} s{best.scan_target} "
                  f"{best.idx_width}/{best.val_storage} "
                  f"{best.packing}/{best.reorder} "
                  f"({best.sec:.4f}s)")
    return result
