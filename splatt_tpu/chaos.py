"""Chaos-schedule soak harness — `splatt chaos` (docs/guarded-als.md).

Resilience machinery that is only exercised by unit tests decays the
moment two guards interact in a way no unit test composed.  This module
runs a REAL (small, seeded, synthetic) CPD under a declarative fault
schedule — NaN poisoning, blown deadlines, transient relay failures,
engine crashes, all at once — and asserts the single invariant the
guarded execution layer promises:

    **converged-or-gracefully-degraded, with zero unhandled exceptions
    and a complete run report.**

Concretely, a chaos run passes iff:

1. no exception escapes the drivers (the guards caught everything);
2. every armed fault that actually FIRED left a matching run-report
   event (``health_*`` for poison, ``deadline_blown``/demotion for
   slow, ``transient_retry``/demotion for raising kinds) — degradation
   is observable, never silent;
3. every emitted event kind is declared in
   :data:`splatt_tpu.resilience.RUN_REPORT_EVENTS` (the report is
   complete/documented);
4. the final factors are finite, or the run explicitly reported a
   ``health_degraded`` verdict.

``splatt chaos --smoke`` is the tier-1 entry: a seconds-scale seeded
run on a tiny tensor, exercised on every PR so the soak invariant
cannot rot.  The full-size invocation (bigger tensor, more iterations,
probabilistic schedules) is the soak tool operators run against new
jax/device combinations before trusting them.
"""

from __future__ import annotations

import dataclasses
import os
from typing import Dict, List, Optional

import numpy as np

#: default schedule: one of each guard's quarry — a NaN poisoning at a
#: fixed iteration (sentinel + rollback), a slow tuner measurement
#: under the deadline watchdog (TIMEOUT), a transient relay failure at
#: an engine's first compile (retry-with-backoff; ``engine.xla`` is
#: the terminal engine, live on every backend), and a ring-exchange
#: failure in the distributed comm drill (the async-ring sweep must
#: degrade classified down the comm chain — docs/ring.md).
#: Deterministic: every trigger is count- or iteration-keyed; add a
#: probabilistic leg via --schedule 'site:kind:p=0.1:seed=N'.
DEFAULT_SCHEDULE = ("cpd.sweep:nan:iter=2,"
                    "tuner.measure:slow:delay=1.5,"
                    "engine.xla:internal:1,"
                    "comm.ring_exchange:runtime:1")

#: expected run-report evidence per fired fault kind: at least one of
#: these event kinds must appear when a fault of that kind fired
_EVIDENCE = {
    "nan": ("health_nonfinite", "health_rollback", "health_degraded"),
    "inf": ("health_nonfinite", "health_rollback", "health_degraded"),
    "slow": ("deadline_blown",),
    "http500": ("transient_retry", "engine_demotion",
                "tuner_negative", "probe_downgrade", "comm_fallback"),
    "internal": ("transient_retry", "engine_demotion",
                 "tuner_negative", "probe_downgrade", "comm_fallback"),
    "unavailable": ("transient_retry", "engine_demotion",
                    "tuner_negative", "probe_downgrade", "comm_fallback"),
    "timeout": ("transient_retry", "engine_demotion",
                "tuner_negative", "probe_downgrade", "comm_fallback"),
    "oom": ("engine_demotion", "tuner_negative", "probe_downgrade",
            "comm_fallback"),
    "mosaic": ("engine_demotion", "tuner_negative", "probe_downgrade",
               "comm_fallback"),
    "runtime": ("engine_demotion", "tuner_negative",
                "checkpoint_recovery", "probe_downgrade",
                "comm_fallback"),
}


@dataclasses.dataclass
class ChaosResult:
    """One chaos run's verdict and its evidence."""

    verdict: str                  # "converged" | "degraded" | "violated"
    fit: Optional[float]
    finite: bool
    fired: Dict[str, int]         # site -> how often its fault fired
    events: List[dict]            # the full run report
    violations: List[str]         # invariant breaches (empty = pass)
    error: Optional[str] = None   # the escaped exception, if any
    schedule: str = ""            # the RESOLVED schedule that ran

    @property
    def ok(self) -> bool:
        return not self.violations

    def to_json(self) -> dict:
        return dict(verdict=self.verdict, fit=self.fit,
                    finite=self.finite, fired=self.fired,
                    violations=self.violations, error=self.error,
                    schedule=self.schedule,
                    events=[{k: v for k, v in e.items() if k != "ts"}
                            for e in self.events])


def synthetic_tensor(dims, nnz: int, seed: int):
    """Seeded power-law synthetic tensor (every slice nonempty so the
    CPD shapes are exact) — shared by the chaos soak and the serve
    daemon's ``{"synthetic": ...}`` job workloads (serve.py)."""
    from splatt_tpu.coo import SparseTensor

    rng = np.random.default_rng(seed)
    inds = np.empty((len(dims), nnz), dtype=np.int64)
    for m, d in enumerate(dims):
        raw = rng.zipf(1.4, size=nnz).astype(np.int64)
        inds[m] = (raw + rng.integers(0, d, size=nnz)) % d
    vals = rng.random(nnz) + 0.1
    return SparseTensor(inds, vals, dims).deduplicate() \
                                         .remove_empty_slices()


def run_chaos(schedule: Optional[str] = None, seed: int = 0,
              dims=(40, 32, 24), nnz: int = 3000, rank: int = 4,
              iters: int = 8, deadline_s: float = 0.5,
              tune_first: bool = True, smoke: bool = False,
              verbose: bool = False,
              trace_path: Optional[str] = None) -> ChaosResult:
    """Run one seeded CPD soak under a chaos schedule and check the
    guarded-execution invariant.  Owns process-global resilience state
    (faults, demotions, the run report, the deadline override): a chaos
    run is a diagnostic, not a library call — it resets that state on
    entry and disarms on exit.

    With `trace_path` the soak additionally exercises the exporter end
    to end (docs/observability.md): span recording is enabled for the
    run, the recorder is exported to a Chrome trace-event file at
    `trace_path`, and the invariant gains two legs — the export must
    succeed (a ``trace_written`` ok event), and every fired fault's
    run-report evidence must ALSO appear as point events on the trace
    (the event-on-span wiring cannot silently rot).
    """
    from splatt_tpu import resilience, trace, tune
    from splatt_tpu.blocked import BlockedSparse
    from splatt_tpu.config import Options, Verbosity
    from splatt_tpu.cpd import cpd_als
    from splatt_tpu.utils import faults
    from splatt_tpu.utils.env import read_env

    if schedule is None:
        schedule = str(read_env("SPLATT_CHAOS_SCHEDULE") or "") \
            or DEFAULT_SCHEDULE
    if smoke:
        dims, nnz, rank, iters = (20, 16, 12), 1200, 3, 6
    specs = faults.parse_schedule(schedule)

    faults.reset()
    resilience.reset_demotions()
    resilience.run_report().clear()
    if trace_path:
        # the exporter leg: a fresh recorder, spans ON for the whole
        # soak (the guards' own spans included), exported in `finally`
        trace.reset()
        trace.set_enabled(True)
    # 0 = explicit disable (beats an exported SPLATT_DEADLINE_S); the
    # probe's own always-on default survives either way
    resilience.set_deadline(deadline_s if deadline_s > 0 else 0.0)
    for site, spec in specs.items():
        faults.arm(site, spec)

    tt = synthetic_tensor(dims, nnz, seed)
    opts = Options(random_seed=seed, max_iterations=iters,
                   verbosity=Verbosity.LOW if verbose
                   else Verbosity.NONE,
                   use_pallas=False,   # CPU-safe: xla_scan/xla engines
                   autotune=False)     # plans measured live, not cached
    error = None
    fit = None
    finite = False
    out = None
    try:
        import tempfile

        with tempfile.TemporaryDirectory(prefix="splatt-chaos-") as td:
            # a throwaway plan cache: plans measured under injected
            # faults must never leak into the real cache
            tune.set_cache_path(f"{td}/tune_cache.json")
            if tune_first and "tuner.measure" in specs:
                # exercise the tuner leg of the schedule: measurements
                # run under the deadline watchdog and must degrade,
                # not crash
                tune.tune(tt, rank=rank, opts=opts, blocks=(512,),
                          scan_targets=(1 << 21,), reps=1)
            bs = BlockedSparse.from_coo(tt, opts)
            out = cpd_als(bs, rank=rank, opts=opts)
            if "comm.ring_exchange" in specs:
                # distributed comm drill (docs/ring.md): a small FINE
                # async-ring CPD under the armed ring-exchange fault —
                # the failure must degrade classified down the comm
                # chain (async_ring -> ring -> all2all, comm_fallback
                # evidence) and still converge, never escape
                from splatt_tpu.config import CommPattern
                from splatt_tpu.parallel.sharded import sharded_cpd_als

                dopts = Options(random_seed=seed, max_iterations=3,
                                verbosity=opts.verbosity,
                                use_pallas=False, autotune=False,
                                comm_pattern=CommPattern.ASYNC_RING)
                dout = sharded_cpd_als(tt, rank=rank, opts=dopts,
                                       measure_overlap=False)
                if not all(np.isfinite(np.asarray(U)).all()
                           for U in dout.factors):
                    raise RuntimeError(
                        "comm drill produced non-finite factors")
        fit = float(out.fit)
        finite = bool(all(np.isfinite(np.asarray(U)).all()
                          for U in out.factors)
                      and np.isfinite(np.asarray(out.lam)).all())
    except Exception as e:  # the invariant IS "nothing escapes"
        error = (f"{resilience.classify_failure(e).value}: "
                 f"{resilience.failure_message(e)[:300]}")
    finally:
        fired = faults.fired()
        faults.reset()
        resilience.set_deadline(None)
        tune.set_cache_path(None)
        trace_ev = None
        trace_points: List[dict] = []
        if trace_path:
            trace_points = trace.points()
            trace_ev = trace.write_chrome_trace(trace_path)
            trace.set_enabled(None)

    report = resilience.run_report()
    events = report.events()
    degraded = bool(report.events("health_degraded"))

    violations: List[str] = []
    if error is not None:
        violations.append(f"unhandled exception escaped the guarded "
                          f"drivers: {error}")
    if trace_path:
        # the exporter legs of the invariant (docs/observability.md)
        if not (trace_ev and trace_ev.get("ok")):
            violations.append(
                f"trace export to {trace_path} failed: "
                f"{(trace_ev or {}).get('failure_class')}: "
                f"{(trace_ev or {}).get('error')}")
        else:
            try:
                exported = trace.load_trace(trace_path)
                if not any(e.get("ph") == "X" for e in exported):
                    violations.append(
                        f"exported trace {trace_path} holds no spans — "
                        f"the soak ran with recording on")
            except (OSError, ValueError) as e:
                violations.append(
                    f"exported trace {trace_path} is not loadable "
                    f"Chrome trace-event JSON: {e}")
        point_kinds = {p["name"] for p in trace_points}
        for site, spec in specs.items():
            if fired.get(site, 0) == 0:
                continue
            want = _EVIDENCE.get(spec.kind, ())
            if want and not point_kinds & set(want):
                violations.append(
                    f"fault {site}:{spec.kind} fired but none of its "
                    f"evidence events {list(want)} reached the trace "
                    f"as point events — the event-on-span wiring is "
                    f"broken")
    for site, spec in specs.items():
        if fired.get(site, 0) == 0:
            continue
        want = _EVIDENCE.get(spec.kind, ())
        if want and not any(report.events(kind) for kind in want):
            violations.append(
                f"fault {site}:{spec.kind} fired "
                f"{fired[site]}x but left none of the expected "
                f"run-report events {list(want)} — silent degradation")
    undeclared = sorted({e["kind"] for e in events}
                        - set(resilience.RUN_REPORT_EVENTS))
    if undeclared:
        violations.append(f"run report contains undeclared event "
                          f"kinds {undeclared} (SPL012 contract)")
    if error is None and not finite and not degraded:
        violations.append("final factors are non-finite and the run "
                          "did not report a health_degraded verdict")

    verdict = ("violated" if violations
               else "degraded" if degraded else "converged")
    return ChaosResult(verdict=verdict, fit=fit, finite=finite,
                       fired=dict(fired), events=events,
                       violations=violations, error=error,
                       schedule=schedule)


# -- bench regression gate (docs/format.md, ROADMAP open item 1) ------------
#
# `splatt chaos --smoke --bench-gate` folds the PR 6 bench regression
# gate into the chaos smoke tier: a smoke-sized `python bench.py
# --gate` run in a subprocess, so a format/engine change that regresses
# >10% against the newest same-metric prior BENCH_*.json fails the PR
# loudly next to the resilience invariant — not silently in a later
# full-scale bench.

def run_bench_gate(smoke: bool = True,
                   timeout_s: Optional[float] = None) -> dict:
    """Run ``python bench.py --gate`` as a subprocess (smoke-sized env
    defaults unless the caller already pinned SPLATT_BENCH_* knobs) and
    return ``{ok, returncode, record, stderr_tail}``.  The record is
    the parsed headline JSON line — including the per-path achieved
    bytes (``model_gb_per_path``) and format summaries the gate
    compares.  The default timeout scales with the tier: the smoke
    bench is seconds, the full-scale default bench (20M nnz + the
    stream oracle) legitimately runs tens of minutes."""
    import json
    import os
    import subprocess
    import sys

    if timeout_s is None:
        timeout_s = 900.0 if smoke else 3 * 3600.0

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    bench = os.path.join(repo, "bench.py")
    # splint: ignore[SPL001] forwarding the whole environment to the
    # bench subprocess, not reading config — no single ENV_VARS name
    env = dict(os.environ)
    if smoke:
        # seconds-scale: small tensor, the two format rows the gate is
        # really about; "tuned"/"stream" stay out of the smoke tier
        env.setdefault("SPLATT_BENCH_NNZ", "60000")
        env.setdefault("SPLATT_BENCH_RANK", "8")
        env.setdefault("SPLATT_BENCH_ITERS", "2")
        env.setdefault("SPLATT_BENCH_PATHS", "blocked,compact")
    try:
        p = subprocess.run([sys.executable, bench, "--gate"], env=env,
                           capture_output=True, text=True,
                           timeout=timeout_s)
    except subprocess.SubprocessError as e:
        return dict(ok=False, returncode=-1, record=None,
                    stderr_tail=str(e)[-400:])
    record = None
    for line in reversed(p.stdout.splitlines()):
        if line.startswith("{"):
            try:
                record = json.loads(line)
                break
            except json.JSONDecodeError:
                continue
    return dict(ok=(p.returncode == 0 and record is not None),
                returncode=p.returncode, record=record,
                stderr_tail=p.stderr[-400:])


# -- serve soak (docs/serve.md) ---------------------------------------------
#
# The single-run soak above cannot exercise the serve daemon's two
# load-bearing promises: (1) kill-and-restart mid-queue loses no
# accepted job, and (2) one tenant's injected NaN never demotes (or
# otherwise poisons) a neighbor's engines.  This soak proves both with
# a REAL daemon subprocess: file jobs, start `splatt serve --once`,
# SIGKILL it mid-job (a per-job `serve.job_run:slow` fault pins the
# first job open so the kill window is deterministic), restart, and
# assert every accepted job reached a terminal state with the
# isolation evidence in its result record.

@dataclasses.dataclass
class ServeChaosResult:
    """One serve kill-and-restart soak's verdict and evidence."""

    verdict: str                  # "survived" | "violated"
    jobs: Dict[str, str]          # job id -> terminal status
    killed_mid_queue: bool        # the SIGKILL landed before drain
    resumed: List[str]            # jobs the restart re-enqueued
    violations: List[str]         # invariant breaches (empty = pass)
    error: Optional[str] = None
    #: which durable-op crash windows the SIGKILL actually landed in
    #: (ids from the crash-point checker's vocabulary,
    #: tools/splint/crashpoint.py) — the dynamic half of the
    #: static-vs-dynamic coverage comparison in docs/static-analysis.md
    crash_windows: List[str] = dataclasses.field(default_factory=list)

    @property
    def ok(self) -> bool:
        return not self.violations

    def to_json(self) -> dict:
        return dataclasses.asdict(self)


def _crash_windows_exercised(root: str) -> List[str]:
    """Classify the spool's post-kill state into the durable-op crash
    windows the kill evidently landed in.

    The ids come from the crash-point checker's window vocabulary
    (``tools/splint/crashpoint.py``), which enumerates EVERY window
    exhaustively; a soak's SIGKILL samples a handful per run.  Emitting
    the sampled set (the ``crash_windows_exercised`` run-report event)
    makes that gap measurable instead of anecdotal — the comparison
    lives in docs/static-analysis.md.  Classification is conservative:
    only states that are unambiguous evidence of a window are counted.
    """
    from splatt_tpu import serve

    windows = set()
    jpath = os.path.join(root, "journal.jsonl")
    try:
        with open(jpath, "rb") as f:
            data = f.read()
    except OSError:
        data = b""
    if data:
        windows.add("journal.append")
        if not data.endswith(b"\n"):
            windows.add("journal.append.torn")
    # publish-window debris: a crash between the tmp write and the
    # atomic rename leaves the pid-stamped tmp beside the destination
    for dirpath, _dirs, names in os.walk(root):
        for name in names:
            if ".tmp" not in name:
                continue
            if "gen.json.bak" in name:
                windows.add("stamp.bak.publish")
            elif "gen.json" in name:
                windows.add("stamp.publish")
            elif ".npz" in name:
                windows.add("ckpt.publish")
            elif os.path.basename(dirpath) == "results":
                windows.add("result.publish")
            elif os.path.basename(dirpath) == "leases":
                windows.add("lease.publish")
    try:
        recs, torn = serve.Journal(jpath).replay()
    # splint: ignore[SPL002] post-mortem classification is best-effort
    # evidence gathering — an unreadable journal yields no windows,
    # and the soak's own invariant audit reports the breakage
    except Exception:
        recs, torn = [], 0
    if torn:
        windows.add("journal.append.torn")
    by_job: Dict[str, List[str]] = {}
    for r in recs:
        if r.get("job"):
            by_job.setdefault(r["job"], []).append(r.get("rec"))
    for jid, kinds in by_job.items():
        terminal = any(k in serve.TERMINAL for k in kinds)
        res = serve.read_result(root, jid)
        if res is not None and not terminal:
            # the terminal-commit protocol is result publish THEN the
            # terminal append: a result with no terminal record means
            # the crash landed before the final journal append
            windows.add("journal.append[done]")
        elif terminal and res is None:
            windows.add("result.publish")
    return sorted(windows)


def run_serve_chaos(seed: int = 0, smoke: bool = True,
                    verbose: bool = False) -> ServeChaosResult:
    """Kill-and-restart soak of the serve daemon (docs/serve.md).

    Files three jobs — one NaN-poisoned (sentinel + rollback), pinned
    open by a slow fault so the SIGKILL deterministically lands
    mid-job, and two clean neighbors — starts the daemon, SIGKILLs it,
    restarts with ``--once`` and checks:

    1. every accepted job reached a terminal state (no accepted job is
       lost to the crash);
    2. the journal replays cleanly and shows a resume lineage;
    3. the NaN job's result carries the health evidence
       (``health_rollback``/``health_degraded``) and demoted NOTHING;
    4. the clean jobs' results carry no health events and no demotions
       — the poisoned tenant stayed contained.
    """
    import json
    import os
    import subprocess
    import sys
    import tempfile
    import time

    from splatt_tpu import resilience, serve

    dims, nnz, rank, iters = (20, 16, 12), 1200, 3, 6
    if not smoke:
        dims, nnz, rank, iters = (40, 32, 24), 3000, 4, 10
    syn = {"dims": list(dims), "nnz": nnz, "seed": seed}
    violations: List[str] = []
    jobs: Dict[str, str] = {}
    resumed: List[str] = []
    crash_windows: List[str] = []
    killed_mid_queue = False
    error = None
    tmp = tempfile.mkdtemp(prefix="splatt-serve-chaos-")
    # splint: ignore[SPL001] forwarding the whole environment to the
    # daemon subprocess, not reading config — no single ENV_VARS name
    env = dict(os.environ)
    # a throwaway plan cache: plans the soak's jobs measure must never
    # leak into the real shared cache
    env["SPLATT_TUNE_CACHE"] = os.path.join(tmp, "tune_cache.json")
    # persistent executable cache shared across the kill (utils/
    # env.py): the restarted daemon re-adopts its jobs WITHOUT paying
    # the original's XLA compiles — single-device programs only, the
    # CPU-safe scope (see run_fleet_chaos)
    env["SPLATT_COMPILE_CACHE"] = os.path.join(tmp, "xla_cache")
    try:
        # the NaN job's id sorts FIRST ("0" < "c" in the spool's
        # sorted-filename ingest order), so with one worker it is the
        # job the slow fault pins open — the kill window below is
        # keyed to ITS started record, not to whichever job happened
        # to start first
        nan_id = "chaos-0-nan"
        nan_job = {"id": nan_id, "rank": rank, "iters": iters,
                   "synthetic": syn, "health_retries": 2,
                   "faults": "serve.job_run:slow:delay=4,"
                             "cpd.sweep:nan:iter=2"}
        clean = [{"id": f"chaos-clean{i}", "rank": rank, "iters": iters,
                  "synthetic": dict(syn, seed=seed + 1 + i)}
                 for i in range(2)]
        for spec in [nan_job] + clean:
            serve.file_request(tmp, spec)
        cmd = [sys.executable, "-m", "splatt_tpu.cli", "serve", tmp,
               "--once", "--workers", "1"]
        jpath = os.path.join(tmp, "journal.jsonl")
        proc = subprocess.Popen(cmd, env=env, stdout=subprocess.PIPE,
                                stderr=subprocess.PIPE)
        deadline = time.time() + 180
        started = False
        while time.time() < deadline and proc.poll() is None:
            started = any(
                r.get("rec") == "started" and r.get("job") == nan_id
                for r in serve.Journal(jpath).replay()[0])
            if started:
                break
            time.sleep(0.1)
        if started and proc.poll() is None:
            time.sleep(0.5)  # well inside the 4 s slow-fault window
            proc.kill()      # SIGKILL: no drain, no cleanup
            killed_mid_queue = True
        else:
            violations.append(
                "daemon finished (or died) before the kill — the soak "
                "did not exercise a mid-queue restart")
        proc.wait(timeout=60)

        # post-mortem, BEFORE the restart heals anything: which crash
        # windows did this kill actually land in?
        crash_windows = _crash_windows_exercised(tmp)
        resilience.run_report().add(
            "crash_windows_exercised", soak="serve",
            windows=",".join(crash_windows))

        restart = subprocess.run(cmd + ["--json"], env=env,
                                 capture_output=True, text=True,
                                 timeout=600)
        if restart.returncode != 0:
            violations.append(
                f"restarted daemon exited nonzero "
                f"({restart.returncode}): {restart.stderr[-300:]}")

        recs, torn = serve.Journal(jpath).replay()
        accepted = {r["job"] for r in recs if r.get("rec") == "accepted"}
        resumed = sorted({r["job"] for r in recs
                          if r.get("rec") == "resumed"})
        if killed_mid_queue and not resumed:
            violations.append("kill landed mid-queue but the restart "
                              "resumed nothing — journal replay broken")
        for jid in sorted(accepted):
            res = serve.read_result(tmp, jid)
            states = [r.get("rec") for r in recs if r.get("job") == jid]
            if not any(s in serve.TERMINAL for s in states):
                violations.append(f"accepted job {jid} never reached a "
                                  f"terminal state — a job was LOST")
                jobs[jid] = "lost"
                continue
            if res is None:
                violations.append(f"job {jid} is terminal but published "
                                  f"no result record")
                jobs[jid] = "no-result"
                continue
            jobs[jid] = res["status"]
            kinds = {e["kind"] for e in res.get("events", [])}
            if jid == nan_id:
                if res["status"] == "converged" \
                        and not kinds & {"health_rollback",
                                         "health_degraded"}:
                    violations.append(
                        "the NaN job converged with no health evidence "
                        "— the injected fault was silently lost")
                if res.get("demotions"):
                    violations.append(
                        "the NaN job demoted engines — NUMERICAL "
                        "failures must roll back, never demote")
            else:
                if kinds & {"health_nonfinite", "health_rollback",
                            "health_degraded"}:
                    violations.append(
                        f"clean job {jid} carries health events — the "
                        f"NaN tenant leaked into a neighbor")
                if res.get("demotions"):
                    violations.append(
                        f"clean job {jid} carries engine demotions "
                        f"{res['demotions']} — cross-job poisoning")
                if res["status"] != "converged":
                    violations.append(
                        f"clean job {jid} finished {res['status']!r} "
                        f"instead of converging")
    except Exception as e:  # the harness itself must not crash the CLI
        error = (f"{resilience.classify_failure(e).value}: "
                 f"{resilience.failure_message(e)[:300]}")
        violations.append(f"serve-chaos harness error: {error}")
    verdict = "violated" if violations else "survived"
    return ServeChaosResult(verdict=verdict, jobs=jobs,
                            killed_mid_queue=killed_mid_queue,
                            resumed=resumed, violations=violations,
                            error=error, crash_windows=crash_windows)


# -- fleet soak (docs/fleet.md) ---------------------------------------------
#
# The single-daemon soak above proves kill-and-RESTART; the fleet's
# promise is kill-and-FAILOVER: with N replicas over one spool, a
# SIGKILLed replica's accepted jobs must be adopted by live peers
# (lease takeover after expiry), finish exactly once, and — when the
# adopted job shares a shape regime with work a peer already ran —
# hit the warm shared caches (the Nth-request-is-free property
# surviving the failover).  This soak drives a REAL fleet of daemon
# subprocesses under multi-tenant load and audits all of it from the
# shared journal, the per-replica Prometheus snapshots and the
# per-replica span traces.

@dataclasses.dataclass
class FleetChaosResult:
    """One fleet kill-and-failover soak's verdict and evidence."""

    verdict: str                  # "survived" | "violated"
    jobs: Dict[str, str]          # job id -> terminal status
    replicas: List[str]           # replica ids (incl. the restart)
    victim: Optional[str]         # the SIGKILLed replica
    adopted: List[str]            # jobs that changed hands
    affinity: Dict[str, dict]     # adopted tune jobs' warm-cache stats
    violations: List[str]         # invariant breaches (empty = pass)
    error: Optional[str] = None
    #: fleet-aggregate evidence the kill is visible end-to-end
    #: (docs/observability.md): merged adoption/lease/slo-burn
    #: counters, the liveness census, and the victim's flight-ring
    #: event count
    observability: Dict[str, float] = dataclasses.field(
        default_factory=dict)
    #: which durable-op crash windows the victim's SIGKILL landed in
    #: (crash-point checker vocabulary, tools/splint/crashpoint.py)
    crash_windows: List[str] = dataclasses.field(default_factory=list)

    @property
    def ok(self) -> bool:
        return not self.violations

    def to_json(self) -> dict:
        return dataclasses.asdict(self)


def _fleet_lineage_violations(recs: List[dict]) -> List[str]:
    """Audit the shared journal's per-job ownership lineage: a job may
    only start on a second replica after an ``adopted`` takeover (or a
    clean ``interrupted`` handback), and reaches at most one terminal
    record — the journal-level face of 'no job runs on two replicas
    at once'."""
    from splatt_tpu import serve

    out: List[str] = []
    by_job: Dict[str, List[dict]] = {}
    for r in recs:
        if r.get("job") and r.get("rec"):
            by_job.setdefault(r["job"], []).append(r)
    for jid, rl in sorted(by_job.items()):
        owner = None
        terminals = 0
        for r in rl:
            k, rid = r["rec"], r.get("replica")
            if k == serve.STARTED:
                if owner is not None and rid != owner:
                    out.append(
                        f"job {jid} started on {rid} while owned by "
                        f"{owner} with no adoption/interruption "
                        f"between — double execution")
                owner = rid
            elif k == serve.ADOPTED:
                owner = rid
            elif k == serve.INTERRUPTED:
                owner = None
            elif k in (serve.DONE, serve.FAILED):
                terminals += 1
        if terminals > 1:
            out.append(f"job {jid} reached {terminals} terminal "
                       f"records — committed more than once")
    return out


def _predict_staleness_violations(recs: List[dict]) -> List[str]:
    """Audit the generation fence from the journal ALONE
    (docs/predict.md): every served prediction's generation must be >=
    the newest generation COMMITTED before that predict was accepted.
    Commit ``done`` records carry ``model``/``model_gen``; predict
    ``accepted`` records carry ``gen_pinned`` (the marker) and their
    spec; predict ``done`` records carry the served ``gen``.  The
    journal is totally ordered (one flocked file), so walking it in
    order reconstructs what any reader could have known."""
    from splatt_tpu import serve

    out: List[str] = []
    committed: Dict[str, int] = {}
    floor: Dict[str, int] = {}
    for r in recs:
        k, jid = r.get("rec"), r.get("job")
        if k == serve.ACCEPTED and "gen_pinned" in r:
            model = str((r.get("spec") or {}).get("model") or "")
            floor[jid] = committed.get(model, 0)
        elif k == serve.DONE:
            if r.get("model_gen") is not None:
                m = str(r.get("model"))
                committed[m] = max(committed.get(m, 0),
                                   int(r["model_gen"]))
            if r.get("status") == "served" and jid in floor:
                gen = int(r.get("gen") or 0)
                if gen < floor[jid]:
                    out.append(
                        f"predict {jid} served generation {gen} but "
                        f"generation {floor[jid]} was committed before "
                        f"it was accepted — a STALE read")
    return out


def run_fleet_chaos(seed: int = 0, smoke: bool = True,
                    replicas: Optional[int] = None,
                    verbose: bool = False) -> FleetChaosResult:
    """Kill-and-failover soak of a serve fleet (docs/fleet.md).

    Starts N ``splatt serve --fleet`` replica daemons over one shared
    spool (short leases, shared warm caches, per-replica metrics and
    traces), warms one shape regime, then files multi-tenant load
    including a same-regime job pinned open by a slow fault.  SIGKILLs
    the replica that claimed the pinned job mid-run, restarts a
    replacement, and checks:

    1. every accepted job reaches a terminal state (zero jobs lost to
       the kill);
    2. the pinned job changed hands: an ``adopted`` journal record
       from the victim, its terminal record on a survivor, and the
       single-owner lineage audit clean for EVERY job (no job ever
       ran on two replicas at once);
    3. the adopted same-regime job hit the warm shared caches
       (``tune.cache_hits > 0`` with zero measurements) — affinity
       evidence surviving failover;
    4. per-tenant isolation: the NaN tenant rolled back/degraded with
       zero demotions, every clean tenant finished converged with no
       health events and no demotions;
    5. the fleet's observability accounts for the failover: the
       adopter's Prometheus snapshot counts the adoption and its span
       trace carries the ``job_adopted`` point event;
    6. the kill is visible END-TO-END in the fleet observability
       plane (docs/observability.md): the merged fleet aggregate
       shows the lease expiry + adoption + an ``slo_burn`` spike (the
       replicas run with tight ``SPLATT_SLO_*`` knobs, so the
       adoption's queue-wait outage burns the error budget) that
       RECOVERS once the fleet is quiet; the victim's flight-recorder
       ring replays its timeline up to the kill — the pinned job's
       ``job_started`` liveness mark included; and ``splatt status``
       agrees with the journal about every job's state;
    7. the generation-fenced predict plane (docs/predict.md) under
       the same kill: a predict filed in the mid-kill burst is never
       lost, predicts interleaved with the update commit violate no
       staleness (every served generation >= the newest generation
       committed before acceptance, audited from the journal alone),
       >= 1 predict is served once the base model commits, and a
       predict against a shredded model (checkpoint + .bak + both
       generation stamps) REFUSES instead of serving garbage.
    """
    import json
    import os
    import signal as _signal
    import subprocess
    import sys
    import tempfile
    import time

    from splatt_tpu import resilience, serve, trace

    nrep = int(replicas) if replicas else (2 if smoke else 3)
    dims, nnz, rank, iters = (20, 16, 12), 1200, 3, 6
    if not smoke:
        dims, nnz, rank, iters = (40, 32, 24), 3000, 4, 10
    syn = {"dims": list(dims), "nnz": nnz, "seed": seed}
    violations: List[str] = []
    jobs: Dict[str, str] = {}
    adopted: List[str] = []
    affinity: Dict[str, dict] = {}
    crash_windows: List[str] = []
    rids = [f"r{i}" for i in range(nrep)]
    victim = None
    error = None
    procs: Dict[str, object] = {}
    logs = []
    tmp = tempfile.mkdtemp(prefix="splatt-fleet-chaos-")
    jpath = os.path.join(tmp, "journal.jsonl")
    # splint: ignore[SPL001] forwarding the whole environment to the
    # daemon subprocesses, not reading config — no single ENV_VARS name
    base_env = dict(os.environ)
    # shared WARM caches (the point of the fleet) but throwaway ones
    # (soak plans must not leak into the real caches); short leases so
    # failover fits a smoke budget.  The observability plane runs at
    # soak scale too: metrics/aggregation/SLO ticks sub-second, TIGHT
    # SLO knobs (any queue wait past 1s — e.g. the adoption outage —
    # burns the whole error budget at once, so the kill must show as
    # an slo_burn spike), and a flush-every-record flight ring so the
    # victim's black box is current up to the SIGKILL.
    base_env.update(
        SPLATT_TUNE_CACHE=os.path.join(tmp, "tune_cache.json"),
        SPLATT_PROBE_CACHE=os.path.join(tmp, "probe_cache.json"),
        SPLATT_FLEET_LEASE_S="2.0", SPLATT_FLEET_HEARTBEAT_S="0.5",
        SPLATT_SERVE_POLL_S="0.25",
        SPLATT_METRICS_INTERVAL_S="0.7",
        SPLATT_SLO_QUEUE_WAIT_P95_S="1.0",
        SPLATT_SLO_WINDOW_S="3.0", SPLATT_SLO_LONG_WINDOWS="4",
        SPLATT_SLO_BURN="1.5", SPLATT_FLIGHT_FLUSH="1",
        # batched + update tenant mix (docs/batched.md): two queued
        # same-regime jobs coalesce into one vmapped batch, and the
        # update tenant exercises the model store under failover
        SPLATT_SERVE_BATCH_MIN="2", SPLATT_UPDATE_SWEEPS="2",
        # shared persistent executable cache (utils/env.py,
        # ROADMAP item 4): replica 0's first compile of the common
        # job regime warms every peer, respawn and failover adoptee —
        # the cold-replica-skips-compile path, exercised under kills.
        # Safe here because replica jobs are single-device programs;
        # the suite's own process must NOT set this (sharded CPU
        # executables corrupt the heap when deserialized — see
        # tests/conftest.py)
        SPLATT_COMPILE_CACHE=os.path.join(tmp, "xla_cache"))
    # SPLATT_METRICS_PATH stays UNSET: fleet mode defaults each
    # replica's snapshot into <root>/fleet/metrics/<rid>.prom, which
    # is where the aggregator (and this soak's post-mortem) finds
    # them — retired/killed replicas' files included

    def spawn(rid: str):
        env = dict(base_env)
        log = open(os.path.join(tmp, f"{rid}.log"), "w")
        logs.append(log)
        cmd = [sys.executable, "-m", "splatt_tpu.cli", "serve", tmp,
               "--fleet", "--replica", rid, "--workers", "1",
               "--trace", os.path.join(tmp, f"trace-{rid}.json")]
        if verbose:
            cmd.append("-v")
        procs[rid] = subprocess.Popen(cmd, env=env, stdout=log,
                                      stderr=subprocess.STDOUT)

    def states() -> Dict[str, tuple]:
        recs, _ = serve.Journal(jpath).replay()
        out: Dict[str, tuple] = {}
        for r in recs:
            if r.get("job") and r.get("rec"):
                out[r["job"]] = (r["rec"], r.get("replica"))
        return out

    def wait_for(pred, deadline_s: float, what: str) -> bool:
        end = time.time() + deadline_s
        while time.time() < end:
            if pred():
                return True
            if all(p.poll() is not None for p in procs.values()):
                violations.append(
                    f"every replica exited while waiting for {what}")
                return False
            time.sleep(0.15)
        violations.append(f"timed out waiting for {what}")
        return False

    try:
        for rid in rids:
            spawn(rid)
        # phase 1 — warm one shape regime fleet-wide: the shared plan
        # cache is what makes the later adoption's Nth request free
        warm = {"id": "fleet-0-warm", "tenant": "acme", "rank": rank,
                "iters": iters, "tune": True, "synthetic": syn}
        serve.file_request(tmp, warm)
        if not wait_for(lambda: states().get("fleet-0-warm",
                                             (None,))[0]
                        in serve.TERMINAL, 300, "the warm job"):
            raise RuntimeError("fleet soak setup failed")
        # phase 2 — multi-tenant load, including the pinned
        # same-regime job the kill will orphan mid-run
        pin = {"id": "fleet-1-pin", "tenant": "acme", "rank": rank,
               "iters": iters, "tune": True,
               "synthetic": dict(syn, seed=seed + 1),
               "faults": "serve.job_run:slow:delay=5"}
        nan = {"id": "fleet-2-nan", "tenant": "beta", "rank": rank,
               "iters": iters, "health_retries": 2,
               "synthetic": dict(syn, seed=seed + 2),
               "faults": "cpd.sweep:nan:iter=2"}
        clean = {"id": "fleet-3-clean", "tenant": "coyote",
                 "rank": rank, "iters": iters,
                 "synthetic": dict(syn, seed=seed + 3)}
        # the update tenant's base model: a plain cpd job whose
        # checkpoint becomes the model store the later update advances
        # (iters offset by one: a distinct coalescing key, so the base
        # never rides a batch — batched runs do not checkpoint, and
        # the update wants the warm model)
        base_job = {"id": "fleet-4-base", "tenant": "epsilon",
                    "rank": rank, "iters": iters + 1,
                    "checkpoint_every": 2,
                    "synthetic": dict(syn, seed=seed + 4)}
        for spec in (pin, nan, clean, base_job):
            serve.file_request(tmp, spec)
        if not wait_for(
                lambda: states().get("fleet-1-pin",
                                     (None,))[0] == serve.STARTED,
                120, "the pinned job to start"):
            raise RuntimeError("fleet soak setup failed")
        victim = states()["fleet-1-pin"][1]
        if victim not in procs:
            raise RuntimeError(f"journal names unknown replica "
                               f"{victim!r} for the pinned job")
        time.sleep(0.5)  # well inside the 5 s slow-fault window
        procs[victim].kill()  # SIGKILL: no drain, no lease release
        procs[victim].wait(timeout=60)
        # post-mortem before the survivors heal the spool: which crash
        # windows did this kill actually land in?
        crash_windows = _crash_windows_exercised(tmp)
        resilience.run_report().add(
            "crash_windows_exercised", soak="fleet",
            windows=",".join(crash_windows))
        # batched tenant mix (docs/batched.md): filed in one burst
        # while the victim is dead, so one survivor ingests the set
        # together and its >= SPLATT_SERVE_BATCH_MIN same-key queue
        # coalesces into one vmapped batch (ingestion races across
        # replicas can still split the set — the post-mortem records
        # achieved coverage, the lineage audit holds either way)
        bsyn = {"dims": [16, 12, 10], "nnz": 800}
        batch_jobs = [f"fleet-b{i}" for i in range(3)]
        for i, bid in enumerate(batch_jobs):
            serve.file_request(tmp, {
                "id": bid, "tenant": "delta", "rank": 3, "iters": 4,
                "synthetic": dict(bsyn, seed=seed + 10 + i),
                "seed": seed + 10 + i})
        # ...plus a predict riding the SAME mid-kill burst: accepted
        # while the victim is dead, it must reach a terminal answer
        # (served if the base model commits first, REFUSED if it runs
        # before the commit — either is honest; losing it is not)
        serve.file_request(tmp, {
            "id": "fleet-p0", "kind": "predict", "tenant": "epsilon",
            "model": "fleet-4-base", "coords": [[0, 0, 0], [1, 1, 1]]})
        # kill-and-RESTART: a replacement joins under a fresh id (a
        # new incarnation — the dead id's leases must EXPIRE, not be
        # silently re-owned)
        restart = f"{victim}b"
        rids.append(restart)
        spawn(restart)
        # the update tenant needs its base model DONE first: the
        # journal/checkpoint store must hold the model to advance
        all_jobs = ["fleet-0-warm", "fleet-1-pin", "fleet-2-nan",
                    "fleet-3-clean", "fleet-4-base", "fleet-p0",
                    *batch_jobs]
        if wait_for(lambda: states().get("fleet-4-base",
                                         (None,))[0]
                    in serve.TERMINAL, 300, "the update base job"):
            # the predict stream around the update commit: p1 is
            # pinned at the base generation, the update advances it,
            # p2 files after the update — the journal staleness audit
            # must hold across the whole interleaving
            serve.file_request(tmp, {
                "id": "fleet-p1", "kind": "predict",
                "tenant": "epsilon", "model": "fleet-4-base",
                "coords": [[0, 0, 0], [1, 2, 3]]})
            serve.file_request(tmp, {
                "id": "fleet-5-up", "kind": "update",
                "base": "fleet-4-base", "tenant": "epsilon",
                "delta": {"dims": list(dims), "nnz": max(nnz // 20, 8),
                          "seed": seed + 99}})
            serve.file_request(tmp, {
                "id": "fleet-p2", "kind": "predict",
                "tenant": "epsilon", "model": "fleet-4-base",
                "top_k": {"fixed": {"1": 0, "2": 0}, "mode": 0,
                          "k": 3}})
            # only a FILED update is waited on: a base-job timeout is
            # its own (already recorded) violation, not a reason to
            # burn the final wait polling a job that never existed
            all_jobs += ["fleet-p1", "fleet-5-up", "fleet-p2"]
        wait_for(lambda: all(states().get(j, (None,))[0]
                             in serve.TERMINAL for j in all_jobs),
                 300 if smoke else 900, "all jobs to finish")
        # phase 5 — corrupt-model refusal drill (docs/predict.md):
        # shred the base model's checkpoint AND its .bak, drop both
        # generation stamps, then predict against it — the fenced read
        # finds no intact (checkpoint, stamp) pair and must REFUSE
        # classified, never serve garbage
        ckdir = os.path.join(tmp, "ckpt")
        for name in ("fleet-4-base.npz", "fleet-4-base.npz.bak"):
            fp = os.path.join(ckdir, name)
            if os.path.exists(fp):
                with open(fp, "wb") as f:
                    f.write(b"shredded by the chaos drill")
        for name in ("fleet-4-base.gen.json",
                     "fleet-4-base.gen.json.bak"):
            try:
                os.remove(os.path.join(ckdir, name))
            except FileNotFoundError:
                pass
        serve.file_request(tmp, {
            "id": "fleet-p3", "kind": "predict", "tenant": "epsilon",
            "model": "fleet-4-base", "coords": [[0, 0, 0]]})
        all_jobs.append("fleet-p3")
        wait_for(lambda: states().get("fleet-p3", (None,))[0]
                 in serve.TERMINAL, 180, "the corrupt-model predict")
    except Exception as e:  # the harness itself must not crash the CLI
        error = (f"{resilience.classify_failure(e).value}: "
                 f"{resilience.failure_message(e)[:300]}")
        violations.append(f"fleet-chaos harness error: {error}")
    finally:
        for rid, p in procs.items():
            if p.poll() is None:
                p.send_signal(_signal.SIGTERM)
        for p in procs.values():
            try:
                p.wait(timeout=120)
            except subprocess.TimeoutExpired:
                p.kill()
        for log in logs:
            log.close()

    recs, _torn = serve.Journal(jpath).replay()
    accepted = sorted({r["job"] for r in recs
                       if r.get("rec") == serve.ACCEPTED})
    adopted = sorted({r["job"] for r in recs
                      if r.get("rec") == serve.ADOPTED})
    # 1. zero accepted jobs lost
    for jid in accepted:
        last = states().get(jid, (None, None))
        res = serve.read_result(tmp, jid)
        if last[0] not in serve.TERMINAL:
            violations.append(f"accepted job {jid} never reached a "
                              f"terminal state — a job was LOST")
            jobs[jid] = "lost"
            continue
        if res is None:
            violations.append(f"job {jid} is terminal but published "
                              f"no result record")
            jobs[jid] = "no-result"
            continue
        jobs[jid] = res["status"]
    # 2. the failover actually happened, and lineage is single-owner
    if victim is not None:
        pin_last = states().get("fleet-1-pin", (None, None))
        if pin_last[1] == victim:
            violations.append(
                f"the pinned job's terminal record is on the killed "
                f"replica {victim} — the kill exercised no failover")
        if not any(r.get("rec") == serve.ADOPTED
                   and r.get("job") == "fleet-1-pin"
                   and r.get("from_replica") == victim for r in recs):
            violations.append(
                "no adopted record shows the pinned job taken over "
                "from the killed replica — adoption lineage missing")
    violations.extend(_fleet_lineage_violations(recs))
    violations.extend(_predict_staleness_violations(recs))
    # 3./4. per-job evidence: warm-cache affinity + tenant isolation
    for jid, status in sorted(jobs.items()):
        res = serve.read_result(tmp, jid)
        if res is None:
            continue
        kinds = {e["kind"] for e in res.get("events", [])}
        if jid.startswith("fleet-p"):
            # predicts answer "served" or an honest classified
            # "refused" — anything else (or a served answer with no
            # generation stamp) breaks the fence contract
            if status not in ("served", "refused"):
                violations.append(
                    f"predict {jid} finished {status!r} — a predict "
                    f"either serves or refuses, never fails open")
            elif status == "served" and not res.get("gen"):
                violations.append(
                    f"predict {jid} served with no generation stamp "
                    f"— the answer is unauditable")
            continue
        if jid == "fleet-2-nan":
            if status == "converged" \
                    and not kinds & {"health_rollback",
                                     "health_degraded"}:
                violations.append(
                    "the NaN job converged with no health evidence — "
                    "the injected fault was silently lost")
            if res.get("demotions"):
                violations.append(
                    "the NaN job demoted engines — NUMERICAL failures "
                    "must roll back, never demote")
        else:
            if kinds & {"health_nonfinite", "health_rollback",
                        "health_degraded"}:
                violations.append(
                    f"clean job {jid} carries health events — the NaN "
                    f"tenant leaked into a neighbor")
            if res.get("demotions"):
                violations.append(
                    f"clean job {jid} carries engine demotions — "
                    f"cross-tenant poisoning")
            if status != "converged":
                violations.append(
                    f"clean job {jid} finished {status!r} instead of "
                    f"converging")
        if jid == "fleet-1-pin":
            tune_info = res.get("tune") or {}
            affinity[jid] = dict(
                cache_hits=tune_info.get("cache_hits"),
                measured=tune_info.get("measured"),
                adopted_from=res.get("adopted_from"),
                replica=res.get("replica"))
            if not tune_info or not tune_info.get("cache_hits"):
                violations.append(
                    "the adopted same-regime job reports no warm "
                    "plan-cache hits — the Nth-request-is-free "
                    "property did not survive the failover")
            elif tune_info.get("measured"):
                violations.append(
                    f"the adopted job re-measured "
                    f"{tune_info['measured']} plans despite the warm "
                    f"shared cache")
    # 5. the adopter's metrics + trace account for the failover
    pin_replica = states().get("fleet-1-pin", (None, None))[1]
    if pin_replica and pin_replica != victim:
        mpath = os.path.join(tmp, "fleet", "metrics",
                             f"{pin_replica}.prom")
        try:
            with open(mpath) as f:
                mtext = f.read()
            if "splatt_fleet_adoptions_total" not in mtext:
                violations.append(
                    f"the adopter {pin_replica}'s Prometheus snapshot "
                    f"carries no splatt_fleet_adoptions_total sample "
                    f"— the failover is unaccounted")
        except OSError as e:
            violations.append(f"no metrics snapshot from the adopter "
                              f"{pin_replica}: {e}")
        tpath = os.path.join(tmp, f"trace-{pin_replica}.json")
        try:
            summ = trace.summarize(trace.load_trace(tpath))
            fl = summ.get("fleet") or {}
            if not fl.get("adoptions"):
                violations.append(
                    f"the adopter {pin_replica}'s span trace carries "
                    f"no job_adopted point event — the failover left "
                    f"no trace evidence")
        except (OSError, ValueError) as e:
            violations.append(f"no loadable span trace from the "
                              f"adopter {pin_replica}: {e}")
    # 6. the fleet observability plane shows the kill end-to-end
    # (docs/observability.md): merged aggregate + SLO burn/recovery +
    # the victim's flight-recorder black box + status↔journal agreement
    from splatt_tpu import fleetobs

    agg = fleetobs.aggregate(tmp)
    observability: Dict[str, float] = {
        "adoptions": agg.counter("splatt_fleet_adoptions_total"),
        "lease_expired": agg.counter(
            "splatt_fleet_lease_expired_total"),
        "slo_burns": agg.counter("splatt_slo_burn_total"),
        "replicas_dead": float(agg.samples.get(
            ("splatt_fleet_replicas", (("state", "dead"),)), 0.0)),
    }
    if victim is not None:
        if observability["adoptions"] < 1:
            violations.append(
                "the merged fleet aggregate counts no "
                "splatt_fleet_adoptions_total — the failover is "
                "invisible fleet-wide")
        if observability["lease_expired"] < 1:
            violations.append(
                "the merged fleet aggregate counts no "
                "splatt_fleet_lease_expired_total — the lease expiry "
                "is invisible fleet-wide")
        if observability["replicas_dead"] < 1:
            violations.append(
                "the liveness census counts no dead replica — the "
                "SIGKILLed victim's expired heartbeat went uncounted")
        if observability["slo_burns"] < 1:
            violations.append(
                "no slo_burn was counted anywhere in the fleet — the "
                "adoption outage burned no error budget, so a real "
                "incident would page nobody")
        else:
            # ...and the burn RECOVERS: a fresh two-point evaluation
            # over the now-quiet fleet (identical samples = zero new
            # errors in the window) must not be burning
            ev = fleetobs.SloEvaluator(window_s=3.0, long_windows=4,
                                       burn=1.5)
            t0 = time.time()
            ev.evaluate(agg.samples, now=t0)
            res2 = ev.evaluate(agg.samples, now=t0 + 60.0)
            still = [n for n, s in res2["slos"].items()
                     if s["burning"]]
            if still:
                violations.append(
                    f"SLOs {still} still burning over a quiet window "
                    f"— the burn evaluator cannot recover")
        # the victim's black box: its flight ring must replay the
        # timeline up to the kill, the pinned job's liveness mark
        # included (SPLATT_FLIGHT_FLUSH=1 makes every record durable
        # before the 0.5s kill window)
        fpath = os.path.join(tmp, "fleet", "flight",
                             f"{victim}.jsonl")
        try:
            fevs = trace.load_flight(fpath)
            observability["flight_events"] = float(len(fevs))
            if not any((e.get("args") or {}).get("job")
                       == "fleet-1-pin" and e.get("name")
                       == "job_started" for e in fevs):
                violations.append(
                    "the victim's flight ring carries no job_started "
                    "mark for the pinned job — the black box does "
                    "not show what the victim was running when killed")
        except (OSError, ValueError) as e:
            violations.append(
                f"the victim {victim}'s flight ring is unreadable — "
                f"the SIGKILL erased the black box: {e}")
    # 7. batched + update tenant mix (docs/batched.md): the lineage
    # audit above already proves no batch member double-ran or double-
    # committed; here the batch/update evidence itself is checked.
    # (Spool-claim races can split the batched set across replicas, so
    # achieved coalescing coverage is recorded — and required of the
    # full-size soak, where the burst lands on the lone survivor.)
    batched_jobs = 0
    for jid in accepted:
        res = serve.read_result(tmp, jid)
        if res and res.get("batched"):
            batched_jobs += 1
            if res["batched"].get("k", 0) < 2:
                violations.append(
                    f"job {jid} claims a coalesced batch of "
                    f"k={res['batched'].get('k')} — a batch is >= 2")
    observability["batched_jobs"] = float(batched_jobs)
    if not smoke and batched_jobs < 2:
        violations.append(
            "no coalesced batch formed in the full soak — the batched "
            "tenant mix exercised nothing")
    if "fleet-5-up" in accepted:
        up = serve.read_result(tmp, "fleet-5-up")
        if up is not None:
            kinds = {e["kind"] for e in up.get("events", [])}
            if not kinds & {"update_applied", "refit_scheduled"}:
                violations.append(
                    "the update job left no update_applied/"
                    "refit_scheduled evidence — the model-store "
                    "lineage is unauditable")
            if not os.path.exists(os.path.join(
                    tmp, "ckpt", "fleet-4-base.npz")):
                violations.append(
                    "the update base model checkpoint is missing from "
                    "the store after the update committed")
    # 8. the generation-fenced predict plane (docs/predict.md): the
    # staleness audit above already walked the journal; here the
    # predict stream's coverage and refusal honesty are checked
    served = refused = 0
    for jid in accepted:
        if not jid.startswith("fleet-p"):
            continue
        res = serve.read_result(tmp, jid)
        if res and res.get("status") == "served":
            served += 1
        elif res and res.get("status") == "refused":
            refused += 1
    observability["predicts_served"] = float(served)
    observability["predicts_refused"] = float(refused)
    observability["predict_latency_obs"] = float(sum(
        int(v.get("count", 0)) for (n, _lk), v in agg.samples.items()
        if n == "splatt_predict_latency_seconds"
        and isinstance(v, dict)))
    if "fleet-p1" in accepted and served < 1:
        violations.append(
            "no predict was served across the kill despite a "
            "committed base model — the prediction plane never "
            "answered")
    if "fleet-p3" in accepted:
        p3 = serve.read_result(tmp, "fleet-p3")
        if p3 is None or p3.get("status") != "refused":
            violations.append(
                f"the corrupt-model predict finished "
                f"{(p3 or {}).get('status')!r} instead of refusing — "
                f"a torn model must REFUSE, never serve garbage")
    st = fleetobs.fleet_status(tmp)
    jstates = states()
    for jid in accepted:
        if st["jobs"].get(jid) != jstates.get(jid, (None,))[0]:
            violations.append(
                f"splatt status disagrees with the journal about "
                f"{jid}: {st['jobs'].get(jid)!r} vs "
                f"{jstates.get(jid, (None,))[0]!r}")
    verdict = "violated" if violations else "survived"
    return FleetChaosResult(verdict=verdict, jobs=jobs, replicas=rids,
                            victim=victim, adopted=adopted,
                            affinity=affinity, violations=violations,
                            error=error, observability=observability,
                            crash_windows=crash_windows)


def format_fleet_report(res: FleetChaosResult) -> List[str]:
    """Human-readable fleet-soak verdict lines for the CLI."""
    lines = [f"fleet chaos: replicas {', '.join(res.replicas)}; "
             f"SIGKILLed {res.victim or '(nobody)'}; adopted: "
             f"{', '.join(res.adopted) or '(none)'}"]
    for jid, status in sorted(res.jobs.items()):
        lines.append(f"  job {jid}: {status}")
    for jid, ev in sorted(res.affinity.items()):
        lines.append(f"  affinity {jid}: cache_hits={ev['cache_hits']} "
                     f"measured={ev['measured']} "
                     f"adopted_from={ev['adopted_from']} "
                     f"ran_on={ev['replica']}")
    if res.observability:
        ob = res.observability
        lines.append(
            f"  observability: adoptions={ob.get('adoptions', 0):g} "
            f"lease_expired={ob.get('lease_expired', 0):g} "
            f"slo_burns={ob.get('slo_burns', 0):g} "
            f"dead_replicas={ob.get('replicas_dead', 0):g} "
            f"victim_flight_events={ob.get('flight_events', 0):g}")
        lines.append(
            f"  predict plane: served={ob.get('predicts_served', 0):g} "
            f"refused={ob.get('predicts_refused', 0):g} "
            f"latency_obs={ob.get('predict_latency_obs', 0):g}")
    for v in res.violations:
        lines.append(f"INVARIANT VIOLATED: {v}")
    lines.append(f"fleet chaos verdict: {res.verdict.upper()}")
    return lines


def format_serve_report(res: ServeChaosResult) -> List[str]:
    """Human-readable serve-soak verdict lines for the CLI."""
    lines = [f"serve chaos: SIGKILL mid-queue "
             f"{'landed' if res.killed_mid_queue else 'MISSED'}; "
             f"resumed after restart: "
             f"{', '.join(res.resumed) or '(none)'}"]
    for jid, status in sorted(res.jobs.items()):
        lines.append(f"  job {jid}: {status}")
    for v in res.violations:
        lines.append(f"INVARIANT VIOLATED: {v}")
    lines.append(f"serve chaos verdict: {res.verdict.upper()}")
    return lines


# -- ingest soak (docs/ingest.md) -------------------------------------------
#
# The unit tests prove each ingest pillar in isolation; the soak proves
# the one that only a REAL kill can: SIGKILL a `splatt ingest`
# subprocess mid-stream, restart it, and audit the chunk journal ALONE
# for the exactly-once invariant — zero records lost, zero duplicated,
# every quarantined record accounted, the final tensor byte-exact with
# what an uninterrupted run would have built.

@dataclasses.dataclass
class IngestChaosResult:
    """One ingest kill-and-resume soak's verdict and evidence."""

    verdict: str                  # "survived" | "violated"
    killed_mid_stream: bool       # the SIGKILL landed before finalize
    watermark_at_kill: int        # journal watermark at the post-mortem
    chunks: int                   # chunks committed end-to-end
    nnz: int                      # nonzeros in the finalized tensor
    quarantined: int              # records quarantined end-to-end
    resumed: bool                 # the restart reported a journal resume
    violations: List[str]         # invariant breaches (empty = pass)
    error: Optional[str] = None
    #: which durable-op crash windows the SIGKILL actually landed in
    #: (crash-point checker vocabulary, tools/splint/crashpoint.py —
    #: the ingest_chunk_commit protocol's windows)
    crash_windows: List[str] = dataclasses.field(default_factory=list)

    @property
    def ok(self) -> bool:
        return not self.violations

    def to_json(self) -> dict:
        return dataclasses.asdict(self)


def _ingest_crash_windows(dest: str) -> List[str]:
    """Classify the ingest directory's post-kill state into the
    durable-op crash windows the kill evidently landed in (same
    vocabulary as the crash-point checker's ``ingest_chunk_commit``
    protocol).  Conservative: only unambiguous debris counts."""
    windows = set()
    jpath = os.path.join(dest, "journal.jsonl")
    try:
        with open(jpath, "rb") as f:
            data = f.read()
    except OSError:
        data = b""
    if data:
        if not data.endswith(b"\n"):
            windows.add("journal.append.torn")
        import json as _json

        for ln in data.split(b"\n"):
            if not ln.strip():
                continue
            try:
                kind = _json.loads(ln).get("rec")
            except ValueError:
                continue
            if kind:
                windows.add(f"journal.append[{kind}]")
    for dirpath, _dirs, names in os.walk(dest):
        base = os.path.basename(dirpath)
        for name in names:
            if ".tmp" not in name and ".build" not in name:
                continue
            if base == "seg":
                windows.add("ingest.seg.publish")
            elif base == "vocab":
                windows.add("ingest.vocab.publish")
            elif "tensor.bin" in name:
                windows.add("ingest.bin.publish")
    return sorted(windows)


def run_ingest_chaos(seed: int = 0, smoke: bool = True,
                     verbose: bool = False) -> IngestChaosResult:
    """Kill-and-resume soak of the streaming ingest plane
    (docs/ingest.md).

    Generates a seeded record stream — string keys in mode 0 (the
    vocab store is in the blast radius) and a deterministic sprinkle
    of malformed records (the quarantine sidecar too) — then:

    1. runs a REAL ``splatt ingest`` subprocess with a slow fault
       armed at ``ingest.commit`` so each chunk commit dawdles and the
       kill window is deterministic;
    2. SIGKILLs it once the journal shows >= 2 committed chunks
       (mid-stream, no drain, no cleanup);
    3. audits the surviving journal ALONE (``ingest.audit_journal``):
       every journaled chunk's segment/vocab intact under its recorded
       sha, no watermark gaps, sidecar accounting covered;
    4. restarts the same command unfaulted and checks it RESUMES from
       the watermark and converges;
    5. checks end-to-end exactly-once accounting against the
       generator's ground truth: records seen == lines written, nnz ==
       good records, quarantined == malformed records, and the
       finalized ``tensor.bin`` loads with exactly that nnz.
    """
    import json
    import subprocess
    import sys
    import tempfile
    import time

    from splatt_tpu import ingest, resilience

    chunk_records = 120 if smoke else 1000
    nchunks_target = 12 if smoke else 40
    violations: List[str] = []
    crash_windows: List[str] = []
    killed = False
    watermark_at_kill = -1
    resumed = False
    chunks = nnz = quarantined = 0
    error = None
    tmp = tempfile.mkdtemp(prefix="splatt-ingest-chaos-")
    src = os.path.join(tmp, "stream.tns")
    dest = os.path.join(tmp, "ingest")

    # seeded ground truth: every 23rd line malformed (bad arity), the
    # rest "u<k> <i> <j> <val>" — string keys force the vocab path
    rng = np.random.default_rng(seed)
    total = chunk_records * nchunks_target
    good = bad = 0
    with open(src, "w") as f:
        f.write("# ingest soak stream\n")
        for n in range(total):
            if n and n % 23 == 0:
                f.write("malformed\n")
                bad += 1
            else:
                f.write(f"u{rng.integers(0, 500)} "
                        f"{rng.integers(0, 64)} {rng.integers(0, 48)} "
                        f"{rng.random() + 0.1:.6f}\n")
                good += 1

    cmd = [sys.executable, "-m", "splatt_tpu.cli", "ingest", src, dest,
           "--format", "tns", "--chunk", str(chunk_records), "--json"]
    # splint: ignore[SPL001] forwarding the whole environment to the
    # ingest subprocess, not reading config — no single ENV_VARS name
    env = dict(os.environ)
    env["SPLATT_FAULTS"] = "ingest.commit:slow:delay=0.25:*"
    try:
        proc = subprocess.Popen(cmd, env=env, stdout=subprocess.PIPE,
                                stderr=subprocess.STDOUT)
        deadline = time.time() + 180
        while time.time() < deadline and proc.poll() is None:
            recs, _torn = ingest.replay_journal(dest)
            if sum(1 for r in recs
                   if r.get("rec") == ingest.REC_CHUNK) >= 2:
                break
            time.sleep(0.05)
        if proc.poll() is None:
            proc.kill()      # SIGKILL: no drain, no cleanup
            killed = True
        else:
            violations.append(
                "ingest finished (or died) before the kill — the soak "
                "did not exercise a mid-stream resume")
        proc.wait(timeout=60)

        # post-mortem, BEFORE the restart heals anything
        crash_windows = _ingest_crash_windows(dest)
        resilience.run_report().add(
            "crash_windows_exercised", soak="ingest",
            windows=",".join(crash_windows))
        aud = ingest.audit_journal(dest)
        watermark_at_kill = aud["watermark"]
        if not aud["ok"]:
            violations.append(
                f"journal audit after the SIGKILL found "
                f"{len(aud['violations'])} exactly-once violation(s): "
                f"{'; '.join(aud['violations'][:3])}")

        # the resume leg: same command, faults disarmed
        env.pop("SPLATT_FAULTS", None)
        restart = subprocess.run(cmd, env=env, capture_output=True,
                                 text=True, timeout=600)
        if restart.returncode != 0:
            violations.append(
                f"restarted ingest exited nonzero "
                f"({restart.returncode}): {restart.stdout[-300:]}")
        summary = None
        for line in reversed(restart.stdout.splitlines()):
            if line.startswith("{"):
                try:
                    summary = json.loads(line)
                    break
                except json.JSONDecodeError:
                    continue
        if summary is None:
            violations.append("restarted ingest printed no JSON "
                              "summary — accounting unauditable")
        else:
            resumed = bool(summary.get("resumed"))
            chunks = int(summary.get("chunks") or 0)
            nnz = int(summary.get("nnz") or 0)
            quarantined = int(summary.get("quarantined") or 0)
            if summary.get("status") != "converged":
                violations.append(
                    f"restarted ingest finished "
                    f"{summary.get('status')!r} instead of converging")
            if killed and watermark_at_kill >= 0 and not resumed:
                violations.append(
                    "the kill landed mid-stream but the restart did "
                    "not resume from the journal watermark")
            for name, got, want in (
                    ("records", summary.get("records"), good + bad),
                    ("nnz", nnz, good),
                    ("quarantined", quarantined, bad)):
                if got != want:
                    violations.append(
                        f"end-to-end {name} accounted {got}, ground "
                        f"truth is {want} — records were LOST or "
                        f"DUPLICATED across the kill")

        aud2 = ingest.audit_journal(dest)
        if not aud2["ok"]:
            violations.append(
                f"final journal audit found violations: "
                f"{'; '.join(aud2['violations'][:3])}")
        elif not aud2["finalized"]:
            violations.append("the journal carries no finalize record "
                              "after a converged run")
        from splatt_tpu import io as _io

        binp = os.path.join(dest, "tensor.bin")
        try:
            tt = _io.load_memmap(binp)
            if tt.nnz != good:
                violations.append(
                    f"finalized tensor holds {tt.nnz} nnz, ground "
                    f"truth is {good}")
        except (OSError, ValueError) as e:
            violations.append(f"finalized tensor.bin unloadable: {e}")
    except Exception as e:  # the harness itself must not crash the CLI
        error = (f"{resilience.classify_failure(e).value}: "
                 f"{resilience.failure_message(e)[:300]}")
        violations.append(f"ingest-chaos harness error: {error}")
    verdict = "violated" if violations else "survived"
    return IngestChaosResult(verdict=verdict, killed_mid_stream=killed,
                             watermark_at_kill=watermark_at_kill,
                             chunks=chunks, nnz=nnz,
                             quarantined=quarantined, resumed=resumed,
                             violations=violations, error=error,
                             crash_windows=crash_windows)


def format_ingest_report(res: IngestChaosResult) -> List[str]:
    """Human-readable ingest-soak verdict lines for the CLI."""
    lines = [f"ingest chaos: SIGKILL mid-stream "
             f"{'landed' if res.killed_mid_stream else 'MISSED'} at "
             f"watermark {res.watermark_at_kill}; resume "
             f"{'replayed the journal' if res.resumed else 'MISSING'}",
             f"  end-to-end: {res.chunks} chunk(s), {res.nnz} nnz, "
             f"{res.quarantined} quarantined",
             f"  crash windows exercised: "
             f"{', '.join(res.crash_windows) or '(none)'}"]
    for v in res.violations:
        lines.append(f"INVARIANT VIOLATED: {v}")
    lines.append(f"ingest chaos verdict: {res.verdict.upper()}")
    return lines


def format_report(res: ChaosResult) -> List[str]:
    """Human-readable chaos verdict lines for the CLI."""
    lines = [f"chaos schedule: {res.schedule}",
             f"faults fired: " + (", ".join(
                 f"{s}x{n}" for s, n in sorted(res.fired.items()) if n)
                 or "(none)")]
    from splatt_tpu import resilience

    lines += ["run report:"] + (resilience.run_report().summary()
                                or ["  (no resilience events)"])
    if res.fit is not None:
        lines.append(f"final fit: {res.fit:0.5f} "
                     f"({'finite' if res.finite else 'NON-FINITE'})")
    for v in res.violations:
        lines.append(f"INVARIANT VIOLATED: {v}")
    lines.append(f"chaos verdict: {res.verdict.upper()}")
    return lines
