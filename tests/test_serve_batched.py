"""Serve auto-coalescing — SPLATT_SERVE_BATCH_MIN (docs/batched.md).

The contracts under test:

- >= batch_min queued batchable jobs sharing one regime key dispatch
  as ONE vmapped batch, each member keeping its OWN journal lineage
  (started/terminal records), result file, and metrics;
- eligibility: jobs with per-job machinery a batch cannot honor
  slot-wise (fault schedules, pre-tune, deadlines, per-job health
  budgets) run singly, as do mixed-key jobs and sub-threshold queues;
- partial-batch failure (the ``serve.batch`` fault site) degrades
  CLASSIFIED to per-tensor dispatch — every member still reaches
  exactly ONE terminal record;
- the journal ROUND-TRIP: a crashed daemon's accepted-but-never-run
  jobs re-coalesce on restart (no checkpoints -> still batchable);
- a NaN batch member degrades alone, neighbors converge.
"""

import json
import os

import pytest

from splatt_tpu import resilience, serve
from splatt_tpu.utils import faults

SYN = {"dims": [20, 16, 12], "nnz": 600}


@pytest.fixture(autouse=True)
def _clean_state():
    def clean():
        faults.reset()
        resilience.reset_demotions()
        resilience.run_report().clear()

    clean()
    yield
    clean()


def _spec(jid, seed, **kw):
    spec = {"id": jid, "rank": 3, "iters": 6, "tol": 0.0,
            "seed": seed, "synthetic": dict(SYN, seed=seed)}
    spec.update(kw)
    return spec


def _journal(root):
    recs, _ = serve.Journal(os.path.join(root, "journal.jsonl")).replay()
    return recs


def _kinds(root, jid):
    return [r["rec"] for r in _journal(root) if r.get("job") == jid]


def test_coalesced_dispatch_preserves_per_job_lineage(tmp_path):
    srv = serve.Server(str(tmp_path), workers=1, batch_min=2)
    ids = [f"b{i}" for i in range(3)]
    for i, jid in enumerate(ids):
        assert srv.submit(_spec(jid, seed=i))["state"] == serve.ACCEPTED
    srv.run_once()
    leaders = set()
    for jid in ids:
        # per-job journal lineage: one started (batch-stamped), one
        # terminal — exactly like a single job
        assert _kinds(str(tmp_path), jid) == [
            serve.ACCEPTED, serve.STARTED, serve.DONE]
        started = next(r for r in _journal(str(tmp_path))
                       if r.get("job") == jid
                       and r["rec"] == serve.STARTED)
        leaders.add(started.get("batch"))
        res = serve.read_result(str(tmp_path), jid)
        assert res["status"] == "converged"
        assert res["batched"]["k"] == 3
        assert res["batched"]["compiles"] == 1
        assert res["fit"] == pytest.approx(res["fit"])
        # per-tenant metric isolation: the member's own registry cut
        # carries its batch-job counter
        assert any("splatt_serve_batch_jobs_total" in k
                   for k in res["metrics"])
    assert leaders == {"b0"}  # one batch, one leader
    ev = resilience.run_report().events("batch_dispatched")
    assert ev and ev[-1]["k"] == 3 and set(ev[-1]["jobs"]) == set(ids)


def test_batch_min_not_met_runs_singly(tmp_path):
    srv = serve.Server(str(tmp_path), workers=1, batch_min=5)
    for i in range(3):
        srv.submit(_spec(f"s{i}", seed=i))
    srv.run_once()
    for i in range(3):
        res = serve.read_result(str(tmp_path), f"s{i}")
        assert res["status"] == "converged"
        assert "batched" not in res
    assert not resilience.run_report().events("batch_dispatched")


def test_ineligible_jobs_stay_single(tmp_path):
    """A job carrying per-job machinery (here: a fault schedule and a
    per-job health budget) never rides a batch — its eligible
    neighbors still coalesce."""
    srv = serve.Server(str(tmp_path), workers=1, batch_min=2)
    srv.submit(_spec("e0", seed=0))
    srv.submit(_spec("e1", seed=1))
    srv.submit(_spec("odd1", seed=2, faults="cpd.sweep:nan:iter=99"))
    srv.submit(_spec("odd2", seed=3, health_retries=2))
    srv.run_once()
    for jid in ("e0", "e1"):
        assert serve.read_result(str(tmp_path), jid)["batched"]["k"] == 2
    for jid in ("odd1", "odd2"):
        res = serve.read_result(str(tmp_path), jid)
        assert res["status"] == "converged" and "batched" not in res


def test_mixed_key_jobs_do_not_coalesce(tmp_path):
    """Same regime but a different iters budget = a different
    coalescing key: one vmapped program cannot honor both."""
    srv = serve.Server(str(tmp_path), workers=1, batch_min=2)
    srv.submit(_spec("k0", seed=0))
    srv.submit(_spec("k1", seed=1, iters=9))
    srv.run_once()
    for jid in ("k0", "k1"):
        res = serve.read_result(str(tmp_path), jid)
        assert res["status"] == "converged" and "batched" not in res


def test_batch_fault_degrades_to_per_tensor(tmp_path):
    """The serve.batch chaos drill: the batch path dying degrades
    CLASSIFIED to per-tensor dispatch — every member still reaches
    exactly one terminal record and a result."""
    srv = serve.Server(str(tmp_path), workers=1, batch_min=2)
    ids = [f"d{i}" for i in range(3)]
    for i, jid in enumerate(ids):
        srv.submit(_spec(jid, seed=i))
    with faults.inject("serve.batch", "runtime"):
        srv.run_once()
    ev = resilience.run_report().events("batch_degraded")
    assert ev and ev[-1]["failure_class"] == "unknown"
    for jid in ids:
        res = serve.read_result(str(tmp_path), jid)
        assert res["status"] == "converged"
        assert "batched" not in res
        # exactly ONE started + ONE terminal — the degrade re-ran the
        # members without double-journaling their start
        kinds = _kinds(str(tmp_path), jid)
        assert kinds.count(serve.STARTED) == 1
        assert kinds.count(serve.DONE) == 1


def test_journal_roundtrip_recoalesces_after_restart(tmp_path):
    """Kill-and-restart round-trip: accepted-but-never-run jobs replay
    on the next start and — having no checkpoints — coalesce again."""
    a = serve.Server(str(tmp_path), workers=1, batch_min=2)
    ids = [f"r{i}" for i in range(3)]
    for i, jid in enumerate(ids):
        assert a.submit(_spec(jid, seed=i))["state"] == serve.ACCEPTED
    del a  # crash: nothing ran, the journal holds three ACCEPTED
    b = serve.Server(str(tmp_path), workers=1, batch_min=2)
    assert {j for j, _ in
            [(jid, None) for jid in ids]} <= set(b.summary()["jobs"])
    b.run_once()
    for jid in ids:
        res = serve.read_result(str(tmp_path), jid)
        assert res["status"] == "converged"
        assert res["batched"]["k"] == 3
        assert res["resumed"] is True
        kinds = _kinds(str(tmp_path), jid)
        assert kinds[0] == serve.ACCEPTED
        assert kinds[-1] == serve.DONE
        assert kinds.count(serve.DONE) + kinds.count(serve.FAILED) == 1


def test_checkpointed_resume_stays_single(tmp_path):
    """A resumed job that left a checkpoint takes the single-job
    resume path (batched runs do not checkpoint)."""
    a = serve.Server(str(tmp_path), workers=1, batch_min=2)
    for i in range(2):
        a.submit(_spec(f"c{i}", seed=i))
    # plant a checkpoint for c0, as an interrupted run would have
    from splatt_tpu.cpd import _save_checkpoint, init_factors
    import jax.numpy as jnp

    dims = [d for d in SYN["dims"]]
    fac = init_factors(tuple(dims), 3, 0)
    _save_checkpoint(os.path.join(a.ckpt_dir, "c0.npz"), fac,
                     jnp.ones((3,)), 2, 0.1)
    del a
    b = serve.Server(str(tmp_path), workers=1, batch_min=2)
    b.run_once()
    r0 = serve.read_result(str(tmp_path), "c0")
    r1 = serve.read_result(str(tmp_path), "c1")
    assert r0["status"] == "converged" and "batched" not in r0
    assert r1["status"] == "converged" and "batched" not in r1


def test_nan_member_degrades_alone_in_batch(tmp_path, monkeypatch):
    """Per-slot health isolation THROUGH serve: slot 0 of the batch is
    poisoned persistently; its job degrades, neighbors converge."""
    monkeypatch.setenv("SPLATT_HEALTH_RETRIES", "1")
    srv = serve.Server(str(tmp_path), workers=1, batch_min=2)
    ids = [f"n{i}" for i in range(3)]
    for i, jid in enumerate(ids):
        srv.submit(_spec(jid, seed=i))
    with faults.inject("cpd.batch.sweep", "nan", times=faults.ALWAYS):
        srv.run_once()
    # slot 0 == the first member of the dispatch order (n0)
    r0 = serve.read_result(str(tmp_path), "n0")
    assert r0["status"] == "degraded"
    assert r0["batched"]["rollbacks"] >= 1
    assert any(e["kind"] == "health_degraded" and e.get("slot") == 0
               for e in r0["events"])
    for jid in ("n1", "n2"):
        res = serve.read_result(str(tmp_path), jid)
        assert res["status"] == "converged"
        assert not any(e["kind"].startswith("health_")
                       for e in res["events"])


def test_cli_batch_min_flag(tmp_path):
    """`splatt serve --batch-min` reaches the Server."""
    from splatt_tpu import cli

    root = str(tmp_path / "spool")
    spec = _spec("cli0", seed=0)
    os.makedirs(root, exist_ok=True)
    serve.file_request(root, spec)
    serve.file_request(root, _spec("cli1", seed=1))
    rc = cli.main(["serve", root, "--once", "--workers", "1",
                   "--batch-min", "2"])
    assert rc == 0
    for jid in ("cli0", "cli1"):
        res = serve.read_result(root, jid)
        assert res["status"] == "converged"
        assert res["batched"]["k"] == 2
