"""IO round-trip tests (≙ tests/io_test.c)."""

import numpy as np
import pytest

from splatt_tpu.coo import SparseTensor
from splatt_tpu.io import (load, read_matrix, read_permutation, save,
                           write_matrix, write_permutation, write_vector)
from tests import gen


def test_text_roundtrip(tmp_path, any_tensor):
    tt = any_tensor
    path = str(tmp_path / "t.tns")
    save(tt, path)
    out = load(path)
    assert out.dims == tt.dims
    np.testing.assert_array_equal(out.inds, tt.inds)
    np.testing.assert_allclose(out.vals, tt.vals)


def test_zero_vs_one_indexed(tmp_path):
    """≙ small4_zeroidx.tns autodetect (src/io.c:273-348)."""
    tt = gen.fixture_tensor("small4")
    p1 = str(tmp_path / "one.tns")
    p0 = str(tmp_path / "zero.tns")
    save(tt, p1, one_indexed=True)
    save(tt, p0, one_indexed=False)
    a, b = load(p1), load(p0)
    np.testing.assert_array_equal(a.inds, b.inds)
    assert a.dims == b.dims


def test_binary_roundtrip(tmp_path, any_tensor):
    tt = any_tensor
    path = str(tmp_path / "t.bin")
    save(tt, path)
    out = load(path)
    assert out.dims == tt.dims
    np.testing.assert_array_equal(out.inds, tt.inds)
    np.testing.assert_allclose(out.vals, tt.vals)


def test_binary_wide_indices(tmp_path):
    """Indices above 2^31 force 8-byte storage."""
    ind = np.array([[0, 2**31 + 5], [1, 0], [0, 1]], dtype=np.int64)
    tt = SparseTensor(ind, np.array([1.0, 2.0]), (2**31 + 6, 2, 2))
    path = str(tmp_path / "wide.bin")
    save(tt, path)
    out = load(path)
    np.testing.assert_array_equal(out.inds, tt.inds)


def test_comments_and_blank_lines(tmp_path):
    path = tmp_path / "c.tns"
    path.write_text("# header comment\n\n1 2 1 1.5\n# mid comment\n2 1 2 2.5\n")
    tt = load(str(path))
    assert tt.nnz == 2
    assert tt.dims == (2, 2, 2)
    np.testing.assert_allclose(tt.vals, [1.5, 2.5])


def test_fixture_files_load(tensors_dir):
    for name in ("small", "med", "small4", "med4", "med5"):
        tt = load(str(tensors_dir / f"{name}.tns"))
        ref = gen.fixture_tensor(name)
        assert tt.dims == ref.dims
        assert tt.nnz == ref.nnz


def test_matrix_vector_perm_roundtrip(tmp_path):
    mat = np.arange(12, dtype=float).reshape(4, 3) / 7.0
    write_matrix(mat, str(tmp_path / "m.mat"))
    np.testing.assert_allclose(read_matrix(str(tmp_path / "m.mat")), mat)
    write_vector(mat[:, 0], str(tmp_path / "v.vec"))
    perm = np.array([3, 1, 0, 2])
    write_permutation(perm, str(tmp_path / "p.perm"))
    np.testing.assert_array_equal(read_permutation(str(tmp_path / "p.perm")), perm)


def test_load_memmap_roundtrip(tmp_path, any_tensor):
    from splatt_tpu.io import load_memmap

    tt = any_tensor
    path = str(tmp_path / "t.bin")
    save(tt, path)
    out = load_memmap(path)
    # no copy on load: arrays are views over the mapped file
    assert isinstance(out.inds.base, np.memmap)
    assert isinstance(out.vals.base, np.memmap)
    assert out.dims == tt.dims
    np.testing.assert_array_equal(np.asarray(out.inds), tt.inds)
    np.testing.assert_allclose(np.asarray(out.vals), tt.vals)
    # memmapped tensors work through the normal pipeline
    assert out.normsq() == pytest.approx(tt.normsq())
    assert out.sorted_by(range(out.nmodes)).nnz == tt.nnz


# -- torn / truncated binary refusal (docs/ingest.md satellite) --------------
#
# A half-written .bin — the debris of a writer killed mid-stream —
# must be REFUSED at the header with a classified "truncated or torn"
# error, never surfaced later as a short memmap or garbage frombuffer.

def _torn_bin(tmp_path, name, mutate):
    tt = gen.fixture_tensor("small")
    path = str(tmp_path / "good.bin")
    save(tt, path)
    with open(path, "rb") as f:
        raw = bytearray(f.read())
    out = str(tmp_path / name)
    with open(out, "wb") as f:
        f.write(bytes(mutate(raw)))
    return out


@pytest.mark.parametrize("name,mutate,marker", [
    # payload cut short: header promises more bytes than the file holds
    ("payload.bin", lambda raw: raw[:-7], "truncated or torn"),
    # header itself cut mid-field
    ("header.bin", lambda raw: raw[:11], "truncated or torn"),
    # dims block cut: nmodes promises dims the file lacks
    ("dims.bin", lambda raw: raw[:22], "truncated or torn"),
    # torn width field (a garbage idx_width no writer produces)
    ("width.bin",
     lambda raw: raw[:12] + (7).to_bytes(4, "little") + raw[16:],
     "bad index/value widths"),
    # torn nmodes field claiming an implausible mode count
    ("modes.bin",
     lambda raw: raw[:8] + (10**6).to_bytes(4, "little") + raw[12:],
     "implausible mode count"),
])
def test_torn_binary_refused(tmp_path, name, mutate, marker):
    from splatt_tpu.io import _load_binary, load_memmap
    from splatt_tpu.resilience import FailureClass, classify_failure

    path = _torn_bin(tmp_path, name, mutate)
    for loader in (load_memmap, _load_binary, load):
        with pytest.raises(ValueError, match="torn|width|mode count") \
                as ei:
            loader(path)
        assert marker in str(ei.value)
        # the refusal is content-deterministic: never retried
        assert classify_failure(ei.value) is FailureClass.DETERMINISTIC


def test_parse_text_names_line_and_offset(tmp_path):
    from splatt_tpu.io import _parse_text
    from splatt_tpu.resilience import FailureClass, classify_failure

    ragged = tmp_path / "ragged.tns"
    ragged.write_text("# hdr\n1 2 1.0\n3 4\n5 6 2.0\n")
    with pytest.raises(ValueError) as ei:
        _parse_text(str(ragged))
    assert "ragged row at line 3" in str(ei.value)
    assert "offset 14" in str(ei.value)  # after "# hdr\n1 2 1.0\n"
    assert classify_failure(ei.value) is FailureClass.DETERMINISTIC

    bad = tmp_path / "bad.tns"
    bad.write_text("1 2 1.0\n1 zap 2.0\n")
    with pytest.raises(ValueError) as ei:
        _parse_text(str(bad))
    assert "bad token 'zap' at line 2" in str(ei.value)
    assert "offset 8" in str(ei.value)
    assert classify_failure(ei.value) is FailureClass.DETERMINISTIC
