"""SPL017 bad: blocking IO inside the control-plane lock's critical
section on a configured hot path — every concurrent submitter and
status poller stalls behind this thread's fsync and sleep (the PR 11
submit bug shape)."""

import os
import threading
import time


class Server:
    def __init__(self, journal_path):
        self._lock = threading.Lock()
        self._jobs = {}
        self._journal_path = journal_path

    def submit_hot(self, jid, spec):
        with self._lock:
            self._jobs[jid] = spec
            with open(self._journal_path, "ab") as f:
                f.write(b"accepted\n")
                f.flush()
                os.fsync(f.fileno())  # the whole daemon waits on disk
            time.sleep(0.01)          # and then some more
        return jid
