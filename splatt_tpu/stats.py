"""Tensor and factorization statistics (≙ src/stats.c).

- :func:`tensor_stats`  ≙ stats_tt basic dims/nnz/density/storage
  (src/stats.c:26-42)
- :func:`cpd_stats_text` ≙ cpd_stats factoring header (rank, iters, tol,
  allocation, storage — src/stats.c:226-296)
"""

from __future__ import annotations

from typing import Optional

import numpy as np

from splatt_tpu.blocked import BlockedSparse
from splatt_tpu.config import Options
from splatt_tpu.coo import SparseTensor


def _human_bytes(n: float) -> str:
    for unit in ("B", "KB", "MB", "GB", "TB"):
        if n < 1024 or unit == "TB":
            return f"{n:.2f}{unit}"
        n /= 1024
    return f"{n:.2f}TB"


def coo_storage_bytes(tt: SparseTensor) -> int:
    return tt.inds.size * tt.inds.dtype.itemsize + tt.vals.nbytes


def tensor_stats(tt: SparseTensor, name: str = "tensor") -> str:
    dims = "x".join(str(d) for d in tt.dims)
    lines = [
        f"Tensor information ---------------------------------",
        f"FILE={name}",
        f"DIMS={dims} NNZ={tt.nnz}",
        f"DENSITY={tt.density():e}",
        f"COORD-STORAGE={_human_bytes(coo_storage_bytes(tt))}",
    ]
    return "\n".join(lines)


def cpd_stats_text(bs_or_tt, rank: int, opts: Options) -> str:
    lines = [
        "Factoring ------------------------------------------",
        f"NFACTORS={rank} MAXITS={opts.max_iterations} TOL={opts.tolerance:0.1e} "
        f"REG={opts.regularization:0.1e} SEED={opts.seed()} THREADS=XLA",
    ]
    if isinstance(bs_or_tt, BlockedSparse):
        bs = bs_or_tt
        nlay = len(bs.layouts)
        lines.append(
            f"BLOCKED-ALLOC={bs.opts.block_alloc.value} NNZ-BLOCK={bs.opts.nnz_block} "
            f"LAYOUTS={nlay}")
        lines.append(f"BLOCKED-STORAGE={_human_bytes(bs.storage_bytes())}")
        for i, lay in enumerate(bs.layouts):
            lines.append(
                f"  layout[{i}]: mode={lay.mode} nblocks={lay.nblocks} "
                f"seg_width={lay.seg_width} pad={lay.nnz_pad - lay.nnz}")
    return "\n".join(lines)
