from splatt_tpu.utils.timers import timers, Timer, TimerRegistry

__all__ = ["timers", "Timer", "TimerRegistry"]
