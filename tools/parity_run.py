"""End-to-end CLI parity run (VERDICT round-1 item 8).

Same .tns file through the reference `splatt cpd` binary and
`splatt-tpu cpd` with fixed seeds; asserts final-fit agreement within a
small tolerance (different RNGs → different inits → nearby optima, so
the bar is fit-level, not factor-level).  ≙ src/cpd.c:357-367 output.

Usage: python tools/parity_run.py [ref_binary] (default
/tmp/splatt-build/bin/splatt; rebuild with
  cmake -S /root/reference -B /tmp/splatt-build -DCMAKE_BUILD_TYPE=Release \
    -DBLAS_LIBRARIES=/tmp/lapack-shim/libblas.so \
    -DLAPACK_LIBRARIES=/tmp/lapack-shim/liblapack.so && \
  cmake --build /tmp/splatt-build -j4
with .so.3 symlinked into /tmp/lapack-shim).
Writes tools/parity_run.json.
"""
import json
import os
import re
import subprocess
import sys
import tempfile

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def gen_tensor(path, seed=3, nnz=60_000, dims=(120, 90, 150)):
    rng = np.random.default_rng(seed)
    # unique coordinates so both sides see the identical effective tensor
    flat = rng.choice(int(np.prod(dims)), size=nnz, replace=False)
    inds = np.stack(np.unravel_index(flat, dims))
    # low-rank-ish structure so the fit is meaningfully > 0
    f = [rng.random((d, 4)) for d in dims]
    vals = (f[0][inds[0]] * f[1][inds[1]] * f[2][inds[2]]).sum(1)
    vals += 0.01 * rng.random(nnz)
    with open(path, "w") as fh:
        for n in range(nnz):
            fh.write(f"{inds[0][n]+1} {inds[1][n]+1} {inds[2][n]+1} "
                     f"{vals[n]:.10f}\n")


def main():
    ref_bin = sys.argv[1] if len(sys.argv) > 1 else "/tmp/splatt-build/bin/splatt"
    if not os.path.exists(ref_bin):
        print(json.dumps({"skipped": f"reference binary not found: {ref_bin}"}))
        return
    rank, iters, tol = 8, 50, 1e-6
    with tempfile.TemporaryDirectory() as td:
        tns = os.path.join(td, "parity.tns")
        gen_tensor(tns)
        ref = subprocess.run(
            [ref_bin, "cpd", tns, "-r", str(rank), "-i", str(iters),
             "--tol", str(tol), "--seed", "42", "--nowrite", "-t", "1"],
            capture_output=True, text=True, check=True)
        m = re.search(r"Final fit:\s*([0-9.eE+-]+)", ref.stdout)
        ref_fit = float(m.group(1))

        ours = subprocess.run(
            [sys.executable, "-m", "splatt_tpu.cli", "cpd", tns,
             "-r", str(rank), "-i", str(iters), "-t", str(tol),
             "--seed", "42", "--f64", "--nowrite"],
            capture_output=True, text=True, check=True,
            env={**os.environ, "JAX_PLATFORMS":
                 os.environ.get("PARITY_PLATFORM", "cpu")},
            cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
        m2 = re.search(r"Final fit:\s*([0-9.eE+-]+)", ours.stdout)
        our_fit = float(m2.group(1))

    rec = dict(ref_fit=ref_fit, our_fit=our_fit,
               abs_diff=round(abs(ref_fit - our_fit), 6),
               rank=rank, iters=iters, tol=tol,
               agree=abs(ref_fit - our_fit) < 5e-3)
    with open("tools/parity_run.json", "w") as f:
        json.dump(rec, f, indent=1)
    print(json.dumps(rec))


if __name__ == "__main__":
    main()
