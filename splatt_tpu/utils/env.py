"""Environment/platform helpers shared by entry points."""

from __future__ import annotations

import os


def ceil_to(x: int, mult: int) -> int:
    """Round x up to a multiple of mult."""
    return ((x + mult - 1) // mult) * mult


def check_int32_dims(dims) -> None:
    """Device indices are int32 (≙ the reference's compile-time
    splatt_idx_t choice, include/splatt/types_config.h:38-43), and the
    blocked layouts use `dim` itself as the padding sentinel — so every
    dim must fit strictly below INT32_MAX.  Called by each path that
    casts host int64 coordinates down (layout build, nnz sharding,
    bucket scatter) so overflow fails loudly instead of wrapping.
    """
    limit = 2**31 - 1
    if max(dims, default=0) >= limit:
        raise ValueError(
            f"dims {tuple(dims)} exceed the int32 device index width "
            f"(max dim must be < {limit}); relabel/split the mode first")


def shard_map(f, **kwargs):
    """Version-portable `jax.shard_map` (resilience to jax API drift).

    Newer jax exposes ``jax.shard_map`` with a ``check_vma`` kwarg;
    older releases only have ``jax.experimental.shard_map.shard_map``
    with the same contract under ``check_rep``.  One hard
    ``from jax import shard_map`` at import time used to take down the
    whole :mod:`splatt_tpu.parallel` package — and with it every
    blocked-layout build — on an older jax; resolving lazily here keeps
    the distributed stack importable everywhere and fails only if a
    sweep actually runs on a jax with neither API.
    """
    import jax

    sm = getattr(jax, "shard_map", None)
    if sm is None:
        from jax.experimental.shard_map import shard_map as sm

        if "check_vma" in kwargs:
            kwargs["check_rep"] = kwargs.pop("check_vma")
    return sm(f, **kwargs)


def host_fence(x):
    """Force true device completion of `x` and everything it depends on.

    block_until_ready alone is not enough on tunneled/relayed devices
    (e.g. the axon TPU relay), which can ack readiness before execution
    finishes — a one-element host fetch is a true data-dependency fence.
    Every leaf is fetched: under the phased sweep the leaves are produced
    by separate device programs, so fencing only the first would leave
    the later phases un-covered.  Returns `x` for chaining.
    """
    import jax

    jax.block_until_ready(x)
    for leaf in jax.tree_util.tree_leaves(x):
        if not hasattr(leaf, "ravel") or getattr(leaf, "size", 0) == 0:
            continue
        jax.device_get(leaf.ravel()[0])
    return x


def apply_env_platform() -> None:
    """Mirror JAX_PLATFORMS into jax.config.

    Some images install a site plugin (e.g. a TPU relay) that selects
    platforms programmatically at interpreter startup, which overrides
    the JAX_PLATFORMS env var.  Calling this before any backend
    initializes makes the env var authoritative again.
    """
    platforms = os.environ.get("JAX_PLATFORMS")
    if platforms:
        import jax

        try:
            jax.config.update("jax_platforms", platforms)
        except Exception:
            pass
