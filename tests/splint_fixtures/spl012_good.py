"""SPL012 good: emission sites name events declared in
resilience.py:RUN_REPORT_EVENTS."""

from splatt_tpu import resilience


def degrade_loudly(err):
    resilience.run_report().add(
        "engine_demotion", engine="example",
        failure_class="unknown", error=str(err))


def degrade_comm(err):
    # the comm-engine fallback ladder's evidence (docs/ring.md)
    resilience.run_report().add(
        "comm_fallback", strategy="async_ring", fallback_to="ring",
        failure_class="unknown", error=str(err))


def observe_exports(path, nspans):
    # the observability layer's own evidence (docs/observability.md):
    # a trace export and a metrics snapshot, both declared kinds
    resilience.run_report().add("trace_written", path=path, ok=True,
                                spans=nspans, events=0)
    resilience.run_report().add("metrics_snapshot", path=path, ok=True,
                                samples=0)
