"""Comm-minimizing factor-row distribution
(≙ p_greedy_mat_distribution, src/mpi/mpi_mat_distribute.c:436-548)."""

import numpy as np
import pytest

from splatt_tpu.coo import SparseTensor
from splatt_tpu.parallel.distribute import (comm_minimizing_relabels,
                                            greedy_row_distribution,
                                            local_touch_fraction,
                                            owner_to_relabel, touch_matrix)


def test_touch_matrix_counts():
    rows = np.array([0, 0, 1, 2, 2, 2])
    shards = np.array([0, 1, 1, 0, 0, 1])
    T = touch_matrix(rows, shards, 3, 2)
    np.testing.assert_array_equal(T, [[1, 1], [0, 1], [2, 1]])


def test_greedy_prefers_heaviest_toucher():
    # row 0 touched 5x by shard 1, 1x by shard 0 → shard 1 claims it
    T = np.array([[1, 5], [3, 0], [0, 2], [2, 2]])
    owner = greedy_row_distribution(T, cap=2)
    assert owner[0] == 1 and owner[1] == 0 and owner[2] == 1
    assert np.bincount(owner, minlength=2).max() <= 2


def test_greedy_respects_capacity():
    # every row prefers shard 0; only cap fit, rest spill to shard 1
    T = np.tile(np.array([[10, 1]]), (6, 1))
    owner = greedy_row_distribution(T, cap=3)
    assert np.bincount(owner, minlength=2).tolist() == [3, 3]
    with pytest.raises(ValueError):
        greedy_row_distribution(T, cap=2)


def test_owner_to_relabel_contiguous_and_bijective():
    owner = np.array([1, 0, 1, 0, 0])
    rl = owner_to_relabel(owner, 2, cap=3)
    # shard 0's rows (1,3,4) get labels 0,1,2; shard 1's (0,2) get 3,4
    np.testing.assert_array_equal(rl, [3, 0, 4, 1, 2])
    assert len(set(rl.tolist())) == 5


def _clustered_tensor(seed=0, nnz=4000, dims=(64, 48, 80), ndev=4):
    """Nonzeros whose rows correlate with their shard — scrambled, so
    equal fences are maximally non-local but a greedy distribution can
    recover locality."""
    rng = np.random.default_rng(seed)
    shard = np.arange(nnz) * ndev // nnz  # equal contiguous chunks
    scramble = [rng.permutation(d) for d in dims]
    inds = np.empty((3, nnz), dtype=np.int64)
    for m, d in enumerate(dims):
        within = rng.integers(0, d // ndev, nnz)
        inds[m] = scramble[m][(shard * (d // ndev) + within) % d]
    return SparseTensor(inds=inds, vals=rng.random(nnz), dims=dims)


def test_relabels_improve_locality():
    tt = _clustered_tensor()
    rls, stats = comm_minimizing_relabels(np.asarray(tt.inds), tt.dims, 4)
    for m, st in enumerate(stats):
        assert st["local_after"] > st["local_before"] + 0.3, st
        assert st["local_after"] > 0.95, st
        # a permutation into [0, nshards*cap)
        rl = rls[m]
        assert len(set(rl.tolist())) == tt.dims[m]
        assert rl.min() >= 0 and rl.max() < 4 * st["cap"]


def test_sharded_cpd_greedy_matches_plain():
    import jax.numpy as jnp

    from splatt_tpu import default_opts
    from splatt_tpu.parallel.sharded import sharded_cpd_als

    tt = _clustered_tensor(1, nnz=1200, dims=(32, 24, 40))
    opts = default_opts()
    opts.random_seed = 9
    opts.max_iterations = 4
    plain = sharded_cpd_als(tt, rank=3, opts=opts)
    greedy = sharded_cpd_als(tt, rank=3, opts=opts, row_distribute="greedy")
    assert abs(float(plain.fit) - float(greedy.fit)) < 1e-5
    for a, b in zip(plain.factors, greedy.factors):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-4, atol=2e-5)


def test_sharded_cpd_greedy_unknown_kind():
    from splatt_tpu import default_opts
    from splatt_tpu.parallel.sharded import sharded_cpd_als

    tt = _clustered_tensor(2, nnz=400, dims=(16, 12, 20))
    with pytest.raises(ValueError, match="row_distribute"):
        sharded_cpd_als(tt, rank=2, opts=default_opts(),
                        row_distribute="nope")
