"""Durable streaming ingest — the exactly-once chunk pipeline
(docs/ingest.md).

The contracts under test:

- EXACTLY-ONCE: a resumed ingest continues from the journal watermark
  with zero lost and zero duplicated records, a chunk whose commit was
  torn before the journal fence re-commits bit-identically, and the
  journal-alone audit (``audit_journal``) detects every violation
  class it claims to;
- QUARANTINE: malformed records land in the sidecar with classified
  ``record_quarantined`` events (bad_arity / bad_token / bad_index /
  nonfinite_value), and past the count or rate budget the run DEGRADES
  classified with the committed watermark intact;
- VOCAB ATOMICITY: string keys map through per-chunk vocab deltas
  that commit atomically with their chunk record — a fault between the
  vocab publish and the journal append never leaves the vocab ahead of
  the watermark;
- FAULT DRILLS: the ``ingest.read`` / ``ingest.vocab`` /
  ``ingest.commit`` sites abort classified and a clean re-run lands
  the exact ground-truth totals;
- SERVE LINEAGE: the ``ingest`` job kind drives the pipeline against
  a live model store, emitting chained ``update`` jobs per watermark
  interval with the commit→update lag observable;
- SOAK: a REAL `splatt ingest` subprocess SIGKILLed mid-stream
  resumes exactly-once, audited from the journal alone
  (``splatt chaos --ingest --smoke``).
"""

import json
import os

import numpy as np
import pytest

from splatt_tpu import ingest, resilience, serve
from splatt_tpu.utils import faults

pytestmark = pytest.mark.filterwarnings("ignore::DeprecationWarning")


@pytest.fixture(autouse=True)
def _clean_state():
    def clean():
        faults.reset()
        resilience.reset_demotions()
        resilience.run_report().clear()

    clean()
    yield
    clean()


# a stream with one vocab mode (string users), two numeric modes, and
# a known sprinkle of malformed records
def _write_stream(path, lines=60, bad_every=0, seed=0):
    rng = np.random.default_rng(seed)
    good = bad = 0
    with open(path, "w") as f:
        f.write("# test stream\n")
        for n in range(lines):
            if bad_every and n and n % bad_every == 0:
                f.write("malformed\n")
                bad += 1
            else:
                f.write(f"u{rng.integers(0, 12)} "
                        f"{rng.integers(0, 8)} {rng.integers(0, 6)} "
                        f"{rng.random() + 0.1:.6f}\n")
                good += 1
    return good, bad


def _events(kind):
    return resilience.run_report().events(kind)


# -- fresh round-trip --------------------------------------------------------

def test_fresh_ingest_roundtrip(tmp_path):
    from splatt_tpu.io import load_memmap

    src = str(tmp_path / "s.tns")
    dest = str(tmp_path / "ing")
    good, bad = _write_stream(src, lines=50, bad_every=9)
    summary = ingest.ingest_stream(src, dest, fmt="tns",
                                   chunk_records=16)
    assert summary["status"] == "converged" and not summary["resumed"]
    assert summary["records"] == good + bad
    assert summary["nnz"] == good and summary["quarantined"] == bad
    # the finalized tensor is the memmap binary layout, exactly good nnz
    tt = load_memmap(summary["tensor"])
    assert tt.nnz == good and len(tt.dims) == 3
    # mode 0 was vocab-mapped: its dim is the vocabulary cardinality
    aud = ingest.audit_journal(dest)
    assert aud["ok"], aud["violations"]
    assert aud["finalized"] and aud["nnz"] == good
    # the observable evidence trail
    assert len(_events("watermark_advanced")) == summary["chunks"]
    assert len(_events("record_quarantined")) == bad
    assert _events("vocab_stats")


def test_jsonl_and_csv_formats(tmp_path):
    rows = [[0, 1, 1.5], [1, 0, 2.5], [2, 2, 0.5]]
    jl = tmp_path / "s.jsonl"
    jl.write_text("".join(json.dumps(r) + "\n" for r in rows))
    s1 = ingest.ingest_stream(str(jl), str(tmp_path / "a"))
    cs = tmp_path / "s.csv"
    cs.write_text("".join(",".join(str(x) for x in r) + "\n"
                          for r in rows))
    s2 = ingest.ingest_stream(str(cs), str(tmp_path / "b"))
    for s in (s1, s2):
        assert s["status"] == "converged" and s["nnz"] == 3
        assert s["dims"] == [3, 3]


# -- exactly-once resume -----------------------------------------------------

def test_watermark_resume_exactly_once(tmp_path):
    src = str(tmp_path / "s.tns")
    dest = str(tmp_path / "ing")
    good, bad = _write_stream(src, lines=48, bad_every=7)
    # first leg: commit exactly two chunks, then "die" (no finalize)
    st = ingest.IngestState(src, dest, fmt="tns", chunk_records=12)
    for rc in st.read_chunks():
        st.commit_chunk(rc)
        if st.watermark == 1:
            break
    first = ingest.audit_journal(dest)
    assert first["ok"] and first["watermark"] == 1
    # second leg: the public driver resumes from the watermark
    summary = ingest.ingest_stream(src, dest, fmt="tns",
                                   chunk_records=12)
    assert summary["resumed"] and summary["status"] == "converged"
    assert summary["records"] == good + bad
    assert summary["nnz"] == good and summary["quarantined"] == bad
    assert _events("ingest_resumed")
    aud = ingest.audit_journal(dest)
    assert aud["ok"], aud["violations"]
    # no ordinal journaled twice: replay counts one chunk record each
    recs, torn = ingest.replay_journal(dest)
    ordinals = [r["n"] for r in recs if r["rec"] == ingest.REC_CHUNK]
    assert torn == 0 and sorted(ordinals) == sorted(set(ordinals))


def test_replay_treats_newline_less_tail_as_torn(tmp_path):
    """A journal tail with no newline is torn debris even when its
    bytes decode as valid JSON (writer killed between write and
    newline): the watermark must never rest on an unfinished append."""
    src = str(tmp_path / "s.tns")
    dest = str(tmp_path / "ing")
    _write_stream(src, lines=20)
    ingest.ingest_stream(src, dest, fmt="tns", chunk_records=10)
    fake = {"rec": "chunk", "n": 2, "lo": 999, "hi": 1200,
            "records": 10, "nnz": 10, "quarantined": 0,
            "sha": "x", "seg_sha": "y", "vocab_sha": None}
    with open(ingest._journal_path(dest), "ab") as f:
        f.write(json.dumps(fake).encode())  # deliberately no newline
    recs, torn = ingest.replay_journal(dest)
    assert torn == 1
    assert all(r.get("n") != 2 for r in recs
               if r.get("rec") == ingest.REC_CHUNK)
    evs = _events("journal_torn")
    assert evs and evs[-1]["failure_class"] == "deterministic"
    # the audit does not count the torn tail either
    aud = ingest.audit_journal(dest)
    assert aud["ok"] and aud["watermark"] == 1


def test_rerun_after_convergence_is_idempotent(tmp_path):
    src = str(tmp_path / "s.tns")
    dest = str(tmp_path / "ing")
    good, _ = _write_stream(src, lines=30)
    s1 = ingest.ingest_stream(src, dest, chunk_records=10)
    with open(s1["tensor"], "rb") as f:
        bin1 = f.read()
    s2 = ingest.ingest_stream(src, dest, chunk_records=10)
    assert s2["resumed"] and s2["status"] == "converged"
    assert s2["nnz"] == s1["nnz"] == good
    with open(s2["tensor"], "rb") as f:
        assert f.read() == bin1  # finalize verified, not rebuilt


def test_torn_commit_replays_chunk_bit_identically(tmp_path):
    """A crash AFTER the segment/vocab publish but BEFORE the journal
    fence: the chunk is not committed (watermark unmoved), and the
    resume re-commits it over the orphan debris bit-identically."""
    src = str(tmp_path / "s.tns")
    dest = str(tmp_path / "ing")
    good, bad = _write_stream(src, lines=36, bad_every=11)
    st = ingest.IngestState(src, dest, fmt="tns", chunk_records=12)
    rc0 = next(st.read_chunks())
    pc = st.parse_chunk(rc0)
    st.publish_vocab(pc)
    st.publish_segment(pc)          # ...crash here: nothing journaled
    with open(ingest._segment_path(dest, 0), "rb") as f:
        orphan = f.read()
    aud = ingest.audit_journal(dest)
    assert aud["ok"] and aud["watermark"] == -1  # debris, no commit
    summary = ingest.ingest_stream(src, dest, fmt="tns",
                                   chunk_records=12)
    assert summary["status"] == "converged"
    assert summary["nnz"] == good and summary["quarantined"] == bad
    with open(ingest._segment_path(dest, 0), "rb") as f:
        assert f.read() == orphan  # the re-commit overwrote it 1:1
    assert ingest.audit_journal(dest)["ok"]


def test_resume_refuses_misaligned_chunking(tmp_path):
    src = str(tmp_path / "s.tns")
    dest = str(tmp_path / "ing")
    _write_stream(src, lines=30)
    st = ingest.IngestState(src, dest, fmt="tns", chunk_records=10)
    st.commit_chunk(next(st.read_chunks()))
    with pytest.raises(ingest.IngestError, match="chunk_records"):
        ingest.IngestState(src, dest, fmt="tns", chunk_records=7)


# -- audit teeth -------------------------------------------------------------

def test_audit_detects_missing_segment_and_torn_content(tmp_path):
    src = str(tmp_path / "s.tns")
    dest = str(tmp_path / "ing")
    _write_stream(src, lines=40)
    ingest.ingest_stream(src, dest, chunk_records=10)
    seg1 = ingest._segment_path(dest, 1)
    with open(seg1, "rb") as f:
        raw = f.read()
    os.remove(seg1)
    aud = ingest.audit_journal(dest)
    assert not aud["ok"]
    assert any("segment file is missing" in v for v in aud["violations"])
    with open(seg1, "wb") as f:
        f.write(raw[:-3] + b"xyz")
    aud = ingest.audit_journal(dest)
    assert any("does not match its" in v for v in aud["violations"])


def test_audit_detects_watermark_gap(tmp_path):
    src = str(tmp_path / "s.tns")
    dest = str(tmp_path / "ing")
    _write_stream(src, lines=40)
    ingest.ingest_stream(src, dest, chunk_records=10)
    # surgically remove chunk 1's record: chunks 2, 3 sit above a gap
    jp = ingest._journal_path(dest)
    with open(jp, "rb") as f:
        lines = [ln for ln in f.read().split(b"\n") if ln.strip()]
    kept = [ln for ln in lines
            if not (b'"rec": "chunk"' in ln and b'"n": 1' in ln)]
    with open(jp, "wb") as f:
        f.write(b"\n".join(kept) + b"\n")
    aud = ingest.audit_journal(dest)
    assert not aud["ok"] and aud["watermark"] == 0
    assert any("above a gap" in v for v in aud["violations"])


# -- quarantine --------------------------------------------------------------

def test_quarantine_classes_and_sidecar(tmp_path):
    # chunk 0 (2 records) pins the mode policy — vocab, numeric,
    # numeric — so the later chunks' malformed rows classify against
    # it instead of flipping a mode to vocab
    src = tmp_path / "s.tns"
    src.write_text(
        "u1 2 3 1.0\n"       # policy row: vocab, numeric, numeric
        "u2 1 2\n"           # bad_arity
        "u3 x 1 2.0\n"       # bad_token (non-integer numeric mode)
        "u4 1 99 3.0\n"      # bad_index (dims pins mode 2 to 6)
        "u5 2 3 nan\n"       # nonfinite_value
        "u6 3 4 4.0\n")
    dest = str(tmp_path / "ing")
    summary = ingest.ingest_stream(str(src), dest, fmt="tns",
                                   chunk_records=2, dims=(64, 8, 6))
    assert summary["nnz"] == 2 and summary["quarantined"] == 4
    with open(ingest._quarantine_path(dest), "rb") as f:
        side = [json.loads(ln) for ln in f.read().splitlines()
                if ln.strip()]
    assert [q["class"] for q in side] == [
        "bad_arity", "bad_token", "bad_index", "nonfinite_value"]
    assert all(q["class"] in ingest.QUARANTINE_CLASSES for q in side)
    # sidecar records carry the source line for operator triage
    assert [q["line"] for q in side] == [2, 3, 4, 5]
    evs = _events("record_quarantined")
    assert sorted(e["quarantine_class"] for e in evs) == sorted(
        q["class"] for q in side)


def test_quarantine_count_budget_degrades_classified(tmp_path):
    src = str(tmp_path / "s.tns")
    dest = str(tmp_path / "ing")
    good, bad = _write_stream(src, lines=60, bad_every=4)
    assert bad > 3
    summary = ingest.ingest_stream(src, dest, fmt="tns",
                                   chunk_records=12, quarantine_max=3)
    assert summary["status"] == "degraded"
    assert "quarantine budget exhausted" in summary["error"]
    evs = _events("ingest_degraded")
    assert evs and evs[0]["failure_class"] == "deterministic"
    # committed chunks survive the degrade: a re-run with a real
    # budget resumes them and lands the exact ground truth
    resilience.run_report().clear()
    s2 = ingest.ingest_stream(src, dest, fmt="tns", chunk_records=12,
                              quarantine_max=0)
    assert s2["resumed"] and s2["status"] == "converged"
    assert s2["nnz"] == good and s2["quarantined"] == bad


def test_degraded_summary_accounts_pending_quarantine(tmp_path):
    """The records that TRIP the budget live in the failing chunk's
    pending count (its commit never advanced): the degraded summary
    and the ingest_degraded event must both account them."""
    src = tmp_path / "s.tns"
    src.write_text("1 1 1.0\n"
                   "bad\n" "bad\n" "bad\n")
    summary = ingest.ingest_stream(str(src), str(tmp_path / "ing"),
                                   fmt="tns", chunk_records=50,
                                   quarantine_max=2)
    assert summary["status"] == "degraded"
    assert summary["quarantined"] == 3
    evs = _events("ingest_degraded")
    assert evs and evs[0]["quarantined"] == 3


def test_degraded_run_does_not_leak_reader_thread(tmp_path):
    """A committer that exits early (budget trip) with a long stream
    still queued must stop the reader: a single queue drain is not
    enough — a refilled bounded queue would park the thread (and the
    open source fd) in put() forever."""
    import threading
    import time as _time

    src = str(tmp_path / "s.tns")
    with open(src, "w") as f:
        f.write("0 0 1.0\n1 1 1.0\n")          # chunk 0: policy, clean
        f.write("bad\nbad\n")                  # chunk 1 trips max=1
        for n in range(2000):                  # long remaining stream
            f.write(f"{n % 7} {n % 5} 1.0\n")
    summary = ingest.ingest_stream(src, str(tmp_path / "ing"),
                                   fmt="tns", chunk_records=2,
                                   quarantine_max=1, inflight=1)
    assert summary["status"] == "degraded"
    deadline = _time.monotonic() + 5.0
    while _time.monotonic() < deadline and any(
            t.name == "splatt-ingest-reader" and t.is_alive()
            for t in threading.enumerate()):
        _time.sleep(0.05)
    assert not any(t.name == "splatt-ingest-reader" and t.is_alive()
                   for t in threading.enumerate())


def test_quarantine_rate_budget(tmp_path):
    src = str(tmp_path / "s.tns")
    dest = str(tmp_path / "ing")
    # > half the stream malformed, well past _RATE_MIN_RECORDS
    _write_stream(src, lines=500, bad_every=2)
    summary = ingest.ingest_stream(src, dest, fmt="tns",
                                   chunk_records=300, quarantine_max=0,
                                   quarantine_rate=0.25)
    assert summary["status"] == "degraded"
    assert "quarantine rate" in summary["error"]


# -- vocab atomicity ---------------------------------------------------------

def test_vocab_commits_atomically_with_watermark(tmp_path):
    """A fault at ``ingest.vocab`` aborts the chunk BEFORE the journal
    fence: the watermark never moves, so the vocab can never run ahead
    of the data — and the clean re-run lands identical mappings."""
    src = str(tmp_path / "s.tns")
    dest = str(tmp_path / "ing")
    good, _ = _write_stream(src, lines=40)
    with faults.inject("ingest.vocab", "runtime", times=1):
        with pytest.raises(RuntimeError):
            ingest.ingest_stream(src, dest, fmt="tns",
                                 chunk_records=10)
    aud = ingest.audit_journal(dest)
    assert aud["ok"] and aud["watermark"] == -1  # nothing journaled
    summary = ingest.ingest_stream(src, dest, fmt="tns",
                                   chunk_records=10)
    assert summary["status"] == "converged" and summary["nnz"] == good
    # every committed chunk's journaled vocab sha matches its delta
    # file, and the union of deltas IS the final mode-0 cardinality
    recs, _ = ingest.replay_journal(dest)
    keys = set()
    for r in recs:
        if r["rec"] == ingest.REC_CHUNK and r.get("vocab_sha"):
            with open(ingest._vocab_path(dest, r["n"]), "rb") as f:
                delta = json.loads(f.read())
            keys.update(delta["modes"]["0"])
    assert len(keys) == summary["dims"][0]


def test_declared_dims_bound_vocab_modes(tmp_path):
    """--dims is a hard bound on vocab-mapped modes too: a new key
    that would grow the vocabulary past the declared dim quarantines
    as bad_index (an update delta must never index factor rows the
    base model does not have), and the finalized dims are the
    DECLARED dims."""
    src = tmp_path / "s.tns"
    src.write_text("a 0 1.0\n"
                   "b 1 2.0\n"
                   "c 2 3.0\n"    # third key: past declared dim 2
                   "a 3 4.0\n")   # known key: still fine
    dest = str(tmp_path / "ing")
    summary = ingest.ingest_stream(str(src), dest, fmt="tns",
                                   chunk_records=10, dims=(2, 5))
    assert summary["status"] == "converged"
    assert summary["nnz"] == 3 and summary["quarantined"] == 1
    assert summary["dims"] == [2, 5]
    with open(ingest._quarantine_path(dest), "rb") as f:
        side = [json.loads(ln) for ln in f.read().splitlines()
                if ln.strip()]
    assert [q["class"] for q in side] == ["bad_index"]
    # the quarantined key was never minted
    with open(ingest._vocab_path(dest, 0), "rb") as f:
        assert json.loads(f.read())["modes"]["0"] == ["a", "b"]


def test_dims_arity_mismatch_refuses(tmp_path):
    src = tmp_path / "s.tns"
    src.write_text("1 2 3 1.0\n")
    with pytest.raises(ingest.IngestError, match="deterministic"):
        ingest.ingest_stream(str(src), str(tmp_path / "ing"),
                             fmt="tns", dims=(4, 4))


def test_quarantined_record_never_grows_vocab(tmp_path):
    src = tmp_path / "s.tns"
    src.write_text("alpha 1 1.0\n"
                   "ghost 2 nan\n"     # quarantined: must not mint 'ghost'
                   "beta 3 2.0\n")
    dest = str(tmp_path / "ing")
    summary = ingest.ingest_stream(str(src), dest, fmt="tns")
    assert summary["quarantined"] == 1
    assert summary["dims"][0] == 2  # alpha, beta — no ghost entry
    with open(ingest._vocab_path(dest, 0), "rb") as f:
        delta = json.loads(f.read())
    assert delta["modes"]["0"] == ["alpha", "beta"]


# -- fault-site drills -------------------------------------------------------

def _drill_abort_then_resume(tmp_path, tag, injected):
    """Abort under `injected` (an armed faults.inject), then resume
    and land the exact ground truth — zero lost, zero duplicated."""
    src = str(tmp_path / "s.tns")
    dest = str(tmp_path / f"ing-{tag}")
    good, bad = _write_stream(src, lines=48, bad_every=7)
    with injected:
        with pytest.raises(RuntimeError):
            ingest.ingest_stream(src, dest, fmt="tns",
                                 chunk_records=12)
    # whatever was committed before the abort is intact and audited
    assert ingest.audit_journal(dest)["ok"]
    summary = ingest.ingest_stream(src, dest, fmt="tns",
                                   chunk_records=12)
    assert summary["status"] == "converged"
    assert summary["nnz"] == good and summary["quarantined"] == bad
    assert ingest.audit_journal(dest)["ok"]


def test_read_fault_aborts_then_resumes_exactly_once(tmp_path):
    _drill_abort_then_resume(
        tmp_path, "read", faults.inject("ingest.read", "runtime", times=1))


def test_commit_fault_aborts_then_resumes_exactly_once(tmp_path):
    _drill_abort_then_resume(
        tmp_path, "commit",
        faults.inject("ingest.commit", "runtime", times=1))


def test_commit_fault_mid_stream_leaves_watermark_resumable(tmp_path):
    src = str(tmp_path / "s.tns")
    dest = str(tmp_path / "ing")
    good, _ = _write_stream(src, lines=50)
    # the 3rd journal append dies (begin + chunk0 land, chunk1 doesn't)
    with faults.inject("ingest.commit", "runtime", iter_at=3):
        with pytest.raises(RuntimeError):
            ingest.ingest_stream(src, dest, fmt="tns",
                                 chunk_records=10)
    aud = ingest.audit_journal(dest)
    assert aud["ok"] and aud["watermark"] == 1
    summary = ingest.ingest_stream(src, dest, fmt="tns",
                                   chunk_records=10)
    assert summary["resumed"] and summary["nnz"] == good


# -- serve lineage: ingest -> chained updates --------------------------------

def test_serve_ingest_job_chains_updates(tmp_path):
    srv = serve.Server(str(tmp_path), workers=1)
    # the base model the updates advance
    base = {"id": "base", "rank": 3, "iters": 8, "seed": 7,
            "checkpoint_every": 2,
            "synthetic": {"dims": [24, 16, 12], "nnz": 900, "seed": 3}}
    r = srv.submit(base)
    assert r["state"] == serve.ACCEPTED
    srv.run_once()
    assert serve.read_result(srv.root, "base")["status"] == "converged"

    src = str(tmp_path / "s.tns")
    with open(src, "w") as f:
        rng = np.random.default_rng(5)
        for _ in range(40):
            f.write(f"{rng.integers(0, 24)} {rng.integers(0, 16)} "
                    f"{rng.integers(0, 12)} {rng.random() + 0.1:.5f}\n")
    spec = {"id": "ing", "kind": "ingest", "source": src,
            "base": "base", "dims": [24, 16, 12], "chunk_records": 10,
            "update_every": 2}
    r = srv.submit(spec)
    assert r["state"] == serve.ACCEPTED
    srv.run_once()
    res = serve.read_result(srv.root, "ing")
    assert res["status"] == "converged"
    info = res["ingest"]
    assert info["chunks"] == 4 and info["nnz"] == 40
    # one update per 2-chunk watermark interval, all converged, each
    # carrying the commit->update lag the histogram observes
    assert len(res["updates"]) == 2
    for uid in res["updates"]:
        ur = serve.read_result(srv.root, uid)
        assert ur["status"] == "converged"
        assert ur["update"]["base"] == "base"
        assert ur["update"].get("ingest_lag_s") is not None
    # lineage is journaled: ingest accepted before its updates
    recs, _ = serve.Journal(
        os.path.join(srv.root, "journal.jsonl")).replay()
    order = [r["job"] for r in recs if r.get("rec") == serve.ACCEPTED]
    assert order.index("ing") < order.index(res["updates"][0])


def test_serve_ingest_rerun_resumes_update_chain_exactly_once(tmp_path):
    """A killed/lease-stopped ingest job re-runs whole; the durable
    updates journal must make the update chain exactly-once across
    the re-run: the recovered leg never re-spans chunks the first leg
    already fed to an update (no wider delta over applied chunks, no
    dedup-dropped interval), and a published delta file is never
    overwritten."""
    srv = serve.Server(str(tmp_path), workers=1)
    base = {"id": "base", "rank": 3, "iters": 8, "seed": 7,
            "checkpoint_every": 2,
            "synthetic": {"dims": [24, 16, 12], "nnz": 900, "seed": 3}}
    assert srv.submit(base)["state"] == serve.ACCEPTED
    srv.run_once()
    assert serve.read_result(srv.root, "base")["status"] == "converged"

    src = str(tmp_path / "s.tns")
    with open(src, "w") as f:
        rng = np.random.default_rng(5)
        for _ in range(40):
            f.write(f"{rng.integers(0, 24)} {rng.integers(0, 16)} "
                    f"{rng.integers(0, 12)} {rng.random() + 0.1:.5f}\n")
    spec = {"id": "ing", "kind": "ingest", "source": src,
            "base": "base", "dims": [24, 16, 12], "chunk_records": 10,
            "update_every": 2}

    # leg 1: the job is stopped (lease loss) after two chunks — one
    # update emitted, covering chunks [0, 1]
    calls = {"n": 0}

    def stop_after_two():
        calls["n"] += 1
        return calls["n"] > 2

    r1 = srv._run_ingest("ing", spec, stop_after_two)
    assert r1["ingest"]["stopped"] and r1["ingest"]["watermark"] == 1
    assert r1["updates"] == ["ing-up-0-1"]
    dpath = os.path.join(srv.root, "ingest", "ing", "deltas",
                         "up-00000000-00000001.bin")
    with open(dpath, "rb") as f:
        delta1 = f.read()

    # leg 2: the re-run resumes both planes from durable state
    r2 = srv._run_ingest("ing", spec, lambda: False)
    assert r2["ingest"]["resumed"]
    assert r2["ingest"]["watermark"] == 3
    # the recovered interval re-submits (deduped) and the NEW interval
    # covers exactly the new chunks — not a wider span from chunk 0
    assert r2["updates"] == ["ing-up-0-1", "ing-up-2-3"]
    with open(dpath, "rb") as f:
        assert f.read() == delta1  # published delta never overwritten

    # the durable intents partition the chunk sequence: disjoint,
    # contiguous, zero-overlap — the journal-alone proof
    recs, torn = serve.Journal(os.path.join(
        srv.root, "ingest", "ing", "deltas", "updates.jsonl")).replay()
    spans = [(r["lo"], r["hi"]) for r in recs
             if r.get("rec") == "update_intent"]
    assert torn == 0 and spans == [(0, 1), (2, 3)]

    # both updates run to convergence against the base model
    srv.run_once()
    for uid in r2["updates"]:
        ur = serve.read_result(srv.root, uid)
        assert ur["status"] == "converged"
        assert ur["update"]["base"] == "base"


def test_serve_ingest_spec_validation(tmp_path):
    srv = serve.Server(str(tmp_path), workers=1)
    r = srv.submit({"id": "x", "kind": "ingest"})
    assert r["state"] == serve.REJECTED
    r = srv.submit({"id": "y", "kind": "ingest", "source": "s.tns",
                    "base": "base"})  # base without dims
    assert r["state"] == serve.REJECTED


# -- the SIGKILL soak (tier-1 smoke) -----------------------------------------

def test_ingest_chaos_smoke_sigkill_resume():
    from splatt_tpu import chaos

    res = chaos.run_ingest_chaos(seed=0, smoke=True)
    assert res.killed_mid_stream, res.violations
    assert res.ok, res.violations
    assert res.verdict == "survived" and res.resumed
    # the post-mortem names real crash-checker windows
    assert any(w.startswith("journal.append") for w in res.crash_windows)
    lines = chaos.format_ingest_report(res)
    assert any("SURVIVED" in ln for ln in lines)
