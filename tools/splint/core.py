"""splint core: file model, ignore pragmas, baseline, and the run loop.

The analyzer is deliberately pure — stdlib ``ast`` + ``tokenize``, no
imports of the analyzed package (importing ``splatt_tpu`` would pull
jax into every lint run and couple the checker to a working runtime).
Everything a rule needs — module alias maps, dotted-name resolution,
declared registries — is derived statically from source.
"""

from __future__ import annotations

import ast
import dataclasses
import io
import json
import re
import tokenize
from pathlib import Path
from typing import Dict, FrozenSet, List, Optional, Set, Tuple

from tools.splint.config import Config


@dataclasses.dataclass
class Finding:
    """One rule violation at a source location."""

    rule: str
    path: str        # repo-relative, posix separators
    line: int
    message: str
    hint: str = ""

    @property
    def key(self) -> str:
        """Baseline grouping key.  Deliberately line-free: baselines
        keyed on line numbers churn on every unrelated edit; keying on
        (rule, file) with a count makes the baseline a burn-down
        ledger instead of a merge-conflict generator."""
        return f"{self.rule}:{self.path}"

    def as_dict(self, baselined: bool) -> dict:
        return dict(rule=self.rule, path=self.path, line=self.line,
                    message=self.message, hint=self.hint,
                    baselined=baselined)


@dataclasses.dataclass
class Report:
    """Outcome of one analyzer run over a project."""

    findings: List[Finding]            # all unsuppressed findings
    new: List[Finding]                 # findings over baseline budget
    suppressed: int                    # inline-pragma suppressions
    stale: List[str]                   # baseline keys with 0 findings
    shrunk: Dict[str, Tuple[int, int]]  # key -> (found, allowed), found<allowed

    @property
    def ok(self) -> bool:
        return not self.new


# -- ignore pragmas ---------------------------------------------------------

_PRAGMA_RE = re.compile(
    r"#\s*splint:\s*ignore\[\s*([A-Z0-9,\s]+?)\s*\]\s*(.*)$")
_PRAGMA_HINT_RE = re.compile(r"#\s*splint\s*:")


class Ignores:
    """Per-file map of ``# splint: ignore[RULES] reason`` pragmas.

    An inline pragma applies to its own line; a full-line comment
    pragma applies to the next non-blank, non-comment line (so a
    multi-line justification comment still covers the code below it).
    """

    def __init__(self, source: str):
        #: target line -> (set of rule ids, reason, pragma line)
        self.targets: Dict[int, Tuple[set, str, int]] = {}
        #: pragma parse problems -> SPL000 findings
        self.errors: List[Tuple[int, str]] = []
        lines = source.splitlines()
        try:
            tokens = list(tokenize.generate_tokens(
                io.StringIO(source).readline))
        except (tokenize.TokenError, IndentationError, SyntaxError):
            return  # the file-level parse error is reported elsewhere
        for tok in tokens:
            if tok.type != tokenize.COMMENT:
                continue
            m = _PRAGMA_RE.search(tok.string)
            if not m:
                if _PRAGMA_HINT_RE.search(tok.string) and \
                        "ignore" in tok.string:
                    self.errors.append(
                        (tok.start[0],
                         "malformed splint pragma (want "
                         "'# splint: ignore[RULE] reason')"))
                continue
            rules = {r.strip() for r in m.group(1).split(",") if r.strip()}
            reason = m.group(2).strip()
            row, col = tok.start
            full_line = lines[row - 1][:col].strip() == ""
            target = row
            if full_line:
                # skip over blank/comment lines (incl. the pragma's own
                # continuation comments) to the code line below
                t = row
                while t < len(lines):
                    nxt = lines[t].strip()
                    if nxt and not nxt.startswith("#"):
                        target = t + 1
                        break
                    t += 1
            prev = self.targets.get(target)
            if prev:
                rules = rules | prev[0]
                reason = reason or prev[1]
            self.targets[target] = (rules, reason, row)

    def suppresses(self, finding: Finding) -> Optional[Tuple[str, int]]:
        """(reason, pragma_line) when `finding` is pragma-suppressed."""
        entry = self.targets.get(finding.line)
        if entry and finding.rule in entry[0]:
            return entry[1], entry[2]
        return None


# -- file / project model ---------------------------------------------------

def walk_nodes(tree) -> list:
    """``list(ast.walk(tree))``, memoized on the tree object.  Every
    structural rule re-walks each module tree, and the generator
    machinery (deque extends + iter_child_nodes) dominates the
    perf-gated full-tree run — one materialized walk per file serves
    them all.  Use ONLY on whole-file trees (FileCtx.tree): subtree
    walks are cheap and memoizing them would pin every node twice."""
    cached = getattr(tree, "_splint_walk", None)
    if cached is None:
        cached = tree._splint_walk = list(ast.walk(tree))
    return cached


class FileCtx:
    """One analyzed source file: path, AST, alias map, pragmas."""

    def __init__(self, path: Path, relpath: str, source: str,
                 tree: ast.AST):
        self.path = path
        self.relpath = relpath
        self.source = source
        self.tree = tree
        self.ignores = Ignores(source)
        self._aliases: Optional[Dict[str, str]] = None
        self._consts: Optional[Dict[str, str]] = None

    @property
    def aliases(self) -> Dict[str, str]:
        """name -> dotted module/object it is bound to, from imports
        (``import numpy as np`` -> {'np': 'numpy'}; ``from jax import
        numpy as jnp`` -> {'jnp': 'jax.numpy'})."""
        if self._aliases is None:
            amap: Dict[str, str] = {}
            for node in walk_nodes(self.tree):
                if isinstance(node, ast.Import):
                    for a in node.names:
                        amap[a.asname or a.name.split(".")[0]] = (
                            a.name if a.asname else a.name.split(".")[0])
                elif isinstance(node, ast.ImportFrom) and node.module:
                    for a in node.names:
                        amap[a.asname or a.name] = \
                            f"{node.module}.{a.name}"
            self._aliases = amap
        return self._aliases

    @property
    def str_consts(self) -> Dict[str, str]:
        """Simple module/function-level ``NAME = "literal"`` bindings —
        lets rules resolve ``read_env(_CACHE_ENV)`` to its value."""
        if self._consts is None:
            consts: Dict[str, str] = {}
            for node in walk_nodes(self.tree):
                if (isinstance(node, ast.Assign)
                        and len(node.targets) == 1
                        and isinstance(node.targets[0], ast.Name)
                        and isinstance(node.value, ast.Constant)
                        and isinstance(node.value.value, str)):
                    consts[node.targets[0].id] = node.value.value
            self._consts = consts
        return self._consts

    def resolve(self, node: ast.AST) -> Optional[str]:
        """Dotted name of an expression through the alias map:
        ``np.asarray`` -> 'numpy.asarray', ``os.environ.get`` ->
        'os.environ.get'.  None for non-name expressions."""
        parts: List[str] = []
        while isinstance(node, ast.Attribute):
            parts.append(node.attr)
            node = node.value
        if not isinstance(node, ast.Name):
            return None
        base = self.aliases.get(node.id, node.id)
        return ".".join([base] + list(reversed(parts)))


class Project:
    """Cross-file state shared by the rules during one run."""

    def __init__(self, config: Config):
        self.config = config
        self.files: List[FileCtx] = []
        self.parse_errors: List[Finding] = []
        self._extra: Dict[str, Optional[FileCtx]] = {}

    def ctx_for(self, rel: str) -> Optional[FileCtx]:
        """FileCtx for a project module that may live outside the
        analyzed paths (env/faults modules, test files)."""
        for ctx in self.files:
            if ctx.relpath == rel:
                return ctx
        if rel not in self._extra:
            path = self.config.resolve(rel)
            # a registry module a mini-project simply doesn't have is
            # "nothing declared", not a parse error
            self._extra[rel] = (_load_file(path, rel, self.parse_errors)
                                if path.is_file() else None)
        return self._extra[rel]

    def test_ctxs(self) -> List[FileCtx]:
        tests_root = self.config.resolve(self.config.tests_path)
        out = []
        if tests_root.is_dir():
            for p in sorted(tests_root.rglob("*.py")):
                rel = _relpath(p, self.config.root)
                # splint's own rule fixtures arm deliberately-bogus
                # sites; they must not count as "exercised by a test"
                if "splint_fixtures" in rel:
                    continue
                ctx = self.ctx_for(rel)
                if ctx is not None:
                    out.append(ctx)
        return out


def _relpath(p: Path, root: Path) -> str:
    try:
        return p.resolve().relative_to(root.resolve()).as_posix()
    except ValueError:
        return p.as_posix()


def _load_file(path: Path, rel: str,
               errors: List[Finding]) -> Optional[FileCtx]:
    try:
        source = path.read_text()
        tree = ast.parse(source, filename=str(path))
    except (OSError, SyntaxError, ValueError) as e:
        errors.append(Finding(
            "SPL000", rel, getattr(e, "lineno", None) or 1,
            f"cannot analyze file: {type(e).__name__}: {e}"))
        return None
    return FileCtx(path, rel, source, tree)


def collect_files(config: Config) -> List[Path]:
    out: List[Path] = []
    for entry in config.paths:
        p = config.resolve(entry)
        if p.is_file():
            out.append(p)
        elif p.is_dir():
            out.extend(sorted(p.rglob("*.py")))
    return [p for p in out
            if not any(x in _relpath(p, config.root)
                       for x in config.exclude)]


# -- baseline ---------------------------------------------------------------

def load_baseline(path: Path) -> Dict[str, dict]:
    """Baseline entries: ``{"RULE:path": {"count": N, "reason": ...}}``."""
    if not path.exists():
        return {}
    data = json.loads(path.read_text())
    entries = data.get("entries", {})
    for key, entry in entries.items():
        if "count" not in entry:
            raise ValueError(f"splint baseline entry {key!r} has no count")
    return entries


def update_baseline(path: Path, report: Report) -> Dict[str, dict]:
    """Rewrite the baseline from the current findings, preserving the
    reasons of surviving entries.  Newly grandfathered groups get an
    UNJUSTIFIED placeholder — tests refuse a baseline containing one,
    so every grandfathered entry carries a human-written reason."""
    old = load_baseline(path) if path.exists() else {}
    groups: Dict[str, int] = {}
    for f in report.findings:
        groups[f.key] = groups.get(f.key, 0) + 1
    entries = {}
    for key in sorted(groups):
        reason = old.get(key, {}).get(
            "reason", "UNJUSTIFIED: justify this grandfathered group "
                      "or fix the findings")
        entries[key] = {"count": groups[key], "reason": reason}
    path.parent.mkdir(parents=True, exist_ok=True)
    path.write_text(json.dumps(
        {"comment": "splint grandfathered findings — burn this down; "
                    "regenerate with python -m tools.splint "
                    "--update-baseline (reasons are preserved)",
         "version": 1, "entries": entries}, indent=1, sort_keys=True)
        + "\n")
    return entries


# -- dataflow engine --------------------------------------------------------
#
# Flow-sensitive machinery for the SPL008-SPL011 rule family: a
# statement-level per-function CFG (with exception edges, because the
# hazard SPL008 exists for — a donated buffer observed from an except
# handler — only exists ON the exception edge), reaching-definition /
# def-use chains over it, and a lightweight interprocedural
# "jit-boundary map" recording which callables are jit-wrapped and
# with which donate/static argnums.  Known imprecision is documented
# in docs/static-analysis.md: nested function bodies are opaque nodes
# (their free-variable reads are attributed to their call sites),
# aliases (`a = factors`) are not tracked, and containers hide their
# elements.  Rules built on this must therefore choose sides: SPL008
# is tuned to zero false positives on the sanctioned idioms (the
# is_deleted re-materialization guard) at the cost of missing
# laundered reads.


class CFGNode:
    """One control-flow node: a simple statement, a compound-statement
    header (``if``/``while`` test, ``for`` iter, ``with`` items, an
    ``except`` entry), or the synthetic entry/exit."""

    __slots__ = ("idx", "kind", "stmt", "succs", "exc_succs",
                 "defs", "uses", "line")

    def __init__(self, idx: int, kind: str, stmt):
        self.idx = idx
        self.kind = kind          # entry|exit|stmt|test|for|with|except
        self.stmt = stmt          # owning ast node (None for entry/exit)
        self.succs: List[int] = []      # normal-flow successor idxs
        self.exc_succs: List[int] = []  # may-raise edges into handlers
        self.defs: List[str] = []       # names this node (re)binds
        self.uses: List[Tuple[str, int]] = []  # (name, line) reads
        self.line = getattr(stmt, "lineno", 0)


def _expr_loads(node, bound: FrozenSet[str] = frozenset()
                ) -> List[Tuple[str, int]]:
    """(name, line) for every Name *read* in an expression, excluding
    names bound locally by nested lambdas / comprehension targets (so
    ``[f(u) for u in xs]`` reads ``xs``, not ``u``) and skipping nested
    function/class bodies entirely (opaque)."""
    out: List[Tuple[str, int]] = []

    def walk(n, bound):
        if isinstance(n, ast.Name):
            if isinstance(n.ctx, ast.Load) and n.id not in bound:
                out.append((n.id, n.lineno))
            return
        if isinstance(n, ast.Lambda):
            a = n.args
            params = {x.arg for x in a.posonlyargs + a.args + a.kwonlyargs}
            if a.vararg:
                params.add(a.vararg.arg)
            if a.kwarg:
                params.add(a.kwarg.arg)
            for d in list(a.defaults) + [d for d in a.kw_defaults if d]:
                walk(d, bound)
            walk(n.body, bound | params)
            return
        if isinstance(n, (ast.ListComp, ast.SetComp, ast.DictComp,
                          ast.GeneratorExp)):
            targets: Set[str] = set()
            for gen in n.generators:
                walk(gen.iter, bound | targets)
                targets |= {t.id for t in ast.walk(gen.target)
                            if isinstance(t, ast.Name)}
                for cond in gen.ifs:
                    walk(cond, bound | targets)
            if isinstance(n, ast.DictComp):
                walk(n.key, bound | targets)
                walk(n.value, bound | targets)
            else:
                walk(n.elt, bound | targets)
            return
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef,
                          ast.ClassDef)):
            return  # opaque: free-var reads attributed at call sites
        for c in ast.iter_child_nodes(n):
            walk(c, bound)

    walk(node, frozenset(bound))
    return out


def _target_defs(target) -> List[str]:
    """Plain names (re)bound by an assignment target, through tuple/
    list unpacking and starred elements.  Subscript/attribute stores
    bind nothing — their bases are *reads* (they need the object
    alive), which :func:`_expr_loads` already collects."""
    return [n.id for n in ast.walk(target)
            if isinstance(n, ast.Name) and isinstance(n.ctx, ast.Store)]


def _fill_defs_uses(node: CFGNode) -> None:
    """Populate `defs`/`uses` of one CFG node from its ast statement."""
    s = node.stmt
    if s is None:
        return
    if node.kind == "test":               # If/While header: the test
        node.uses = _expr_loads(s.test)
    elif node.kind == "for":              # For header: iter + target
        node.uses = _expr_loads(s.iter)
        node.defs = _target_defs(s.target)
    elif node.kind == "with":             # With header: items
        for item in s.items:
            node.uses += _expr_loads(item.context_expr)
            if item.optional_vars is not None:
                node.defs += _target_defs(item.optional_vars)
    elif node.kind == "except":           # handler entry: type + name
        if s.type is not None:
            node.uses = _expr_loads(s.type)
        if s.name:
            node.defs = [s.name]
    elif isinstance(s, (ast.Assign, ast.AnnAssign)):
        targets = s.targets if isinstance(s, ast.Assign) else [s.target]
        if getattr(s, "value", None) is not None:
            node.uses += _expr_loads(s.value)
        for t in targets:
            node.uses += _expr_loads(t)   # subscript/attr bases+indices
            node.defs += _target_defs(t)
    elif isinstance(s, ast.AugAssign):
        node.uses = _expr_loads(s.value) + _expr_loads(s.target)
        if isinstance(s.target, ast.Name):
            node.uses.append((s.target.id, s.target.lineno))
            node.defs = [s.target.id]
    elif isinstance(s, ast.Delete):
        for t in s.targets:
            if isinstance(t, ast.Name):
                node.defs.append(t.id)    # the binding is gone: a kill
            else:
                node.uses += _expr_loads(t)
    elif isinstance(s, (ast.FunctionDef, ast.AsyncFunctionDef)):
        node.defs = [s.name]
        for dec in s.decorator_list:
            node.uses += _expr_loads(dec)
        a = s.args
        for d in list(a.defaults) + [d for d in a.kw_defaults if d]:
            node.uses += _expr_loads(d)
    elif isinstance(s, ast.ClassDef):
        node.defs = [s.name]
        for e in s.bases + [k.value for k in s.keywords] \
                + s.decorator_list:
            node.uses += _expr_loads(e)
    elif isinstance(s, (ast.Import, ast.ImportFrom)):
        node.defs = [a.asname or a.name.split(".")[0] for a in s.names]
    elif isinstance(s, (ast.Global, ast.Nonlocal, ast.Pass, ast.Break,
                        ast.Continue)):
        pass
    else:  # Expr, Return, Raise, Assert, ...
        node.uses = _expr_loads(s)


class FunctionCFG:
    """Statement-level control-flow graph of one function body.

    Nested function/class bodies are opaque single nodes.  Exception
    edges (`exc_succs`) run from every node inside a ``try`` body to
    that try's handler entries — a raise can interrupt a statement
    mid-effect, which is exactly when a donated buffer is observed
    from the handler (SPL008's home turf).  ``break``/``continue``/
    ``return``/``raise`` cut normal fallthrough."""

    def __init__(self, fn):
        self.fn = fn
        self.nodes: List[CFGNode] = []
        self.entry = self._new("entry", None)
        self.exit = self._new("exit", None)
        self.entry.defs = [a.arg for a in
                           fn.args.posonlyargs + fn.args.args
                           + fn.args.kwonlyargs]
        if fn.args.vararg:
            self.entry.defs.append(fn.args.vararg.arg)
        if fn.args.kwarg:
            self.entry.defs.append(fn.args.kwarg.arg)
        self._loops: List[Tuple[int, List[int]]] = []  # header, breaks
        self._handlers: List[List[int]] = []
        for t in self._block(fn.body, [self.entry.idx]):
            self._edge(t, self.exit.idx)

    # - construction -

    def _new(self, kind: str, stmt) -> CFGNode:
        node = CFGNode(len(self.nodes), kind, stmt)
        _fill_defs_uses(node)
        self.nodes.append(node)
        return node

    def _edge(self, a: int, b: int) -> None:
        if b not in self.nodes[a].succs:
            self.nodes[a].succs.append(b)

    def _node(self, kind: str, stmt, preds: List[int]) -> CFGNode:
        node = self._new(kind, stmt)
        for p in preds:
            self._edge(p, node.idx)
        for handlers in self._handlers:
            for h in handlers:
                if h not in node.exc_succs:
                    node.exc_succs.append(h)
        return node

    def _block(self, stmts, preds: List[int]) -> List[int]:
        for stmt in stmts:
            preds = self._stmt(stmt, preds)
            if not preds:
                break  # code after return/raise/break is unreachable
        return preds

    def _stmt(self, stmt, preds: List[int]) -> List[int]:
        if isinstance(stmt, ast.If):
            test = self._node("test", stmt, preds)
            out = self._block(stmt.body, [test.idx])
            out += (self._block(stmt.orelse, [test.idx])
                    if stmt.orelse else [test.idx])
            return out
        if isinstance(stmt, ast.While):
            test = self._node("test", stmt, preds)
            breaks: List[int] = []
            self._loops.append((test.idx, breaks))
            body_out = self._block(stmt.body, [test.idx])
            self._loops.pop()
            for t in body_out:
                self._edge(t, test.idx)
            out = (self._block(stmt.orelse, [test.idx])
                   if stmt.orelse else [test.idx])
            return out + breaks
        if isinstance(stmt, (ast.For, ast.AsyncFor)):
            head = self._node("for", stmt, preds)
            breaks = []
            self._loops.append((head.idx, breaks))
            body_out = self._block(stmt.body, [head.idx])
            self._loops.pop()
            for t in body_out:
                self._edge(t, head.idx)
            out = (self._block(stmt.orelse, [head.idx])
                   if stmt.orelse else [head.idx])
            return out + breaks
        if isinstance(stmt, ast.Try):
            entries = [self._new("except", h) for h in stmt.handlers]
            self._handlers.append([e.idx for e in entries])
            out = self._block(stmt.body, preds)
            self._handlers.pop()
            if stmt.orelse:
                out = self._block(stmt.orelse, out)
            for e, h in zip(entries, stmt.handlers):
                out += self._block(h.body, [e.idx])
            if stmt.finalbody:
                out = self._block(stmt.finalbody, out)
            return out
        if isinstance(stmt, (ast.With, ast.AsyncWith)):
            head = self._node("with", stmt, preds)
            return self._block(stmt.body, [head.idx])
        node = self._node("stmt", stmt, preds)
        if isinstance(stmt, (ast.Return, ast.Raise)):
            self._edge(node.idx, self.exit.idx)
            return []
        if isinstance(stmt, ast.Break):
            if self._loops:
                self._loops[-1][1].append(node.idx)
            return []
        if isinstance(stmt, ast.Continue):
            if self._loops:
                self._edge(node.idx, self._loops[-1][0])
            return []
        return [node.idx]

    # - predecessor views (exception edges carry a weaker state) -

    def preds(self) -> Dict[int, List[Tuple[int, bool]]]:
        """node idx -> [(pred idx, via_exception_edge)]."""
        out: Dict[int, List[Tuple[int, bool]]] = {
            n.idx: [] for n in self.nodes}
        for n in self.nodes:
            for s in n.succs:
                out[s].append((n.idx, False))
            for s in n.exc_succs:
                out[s].append((n.idx, True))
        return out


def reaching_defs(cfg: FunctionCFG
                  ) -> Tuple[List[Dict[str, Set[int]]],
                             List[Dict[str, Set[int]]]]:
    """Classic may-reach definitions over the CFG: per node, IN/OUT
    maps of name -> defining node idxs.  Function parameters are defs
    at the entry node.  Exception edges propagate IN ∪ GEN without the
    kill — the raising statement may or may not have completed its
    (re)binding."""
    nodes = cfg.nodes
    preds = cfg.preds()
    ins: List[Dict[str, Set[int]]] = [{} for _ in nodes]
    outs: List[Dict[str, Set[int]]] = [{} for _ in nodes]
    excs: List[Dict[str, Set[int]]] = [{} for _ in nodes]

    def apply(node: CFGNode, state: Dict[str, Set[int]], kill: bool
              ) -> Dict[str, Set[int]]:
        new = {k: set(v) for k, v in state.items()}
        for name in node.defs:
            if kill:
                new[name] = {node.idx}
            else:
                new.setdefault(name, set()).add(node.idx)
        return new

    work = [n.idx for n in nodes]
    while work:
        i = work.pop()
        node = nodes[i]
        merged: Dict[str, Set[int]] = {}
        for p, via_exc in preds[i]:
            src = excs[p] if via_exc else outs[p]
            for name, defs in src.items():
                merged.setdefault(name, set()).update(defs)
        new_out = apply(node, merged, kill=True)
        new_exc = apply(node, merged, kill=False)
        if merged != ins[i] or new_out != outs[i] or new_exc != excs[i]:
            ins[i], outs[i], excs[i] = merged, new_out, new_exc
            for s in node.succs + node.exc_succs:
                if s not in work:
                    work.append(s)
    return ins, outs


def def_use_chains(cfg: FunctionCFG) -> Dict[Tuple[int, str], Set[int]]:
    """(use node idx, name) -> node idxs whose definition may reach the
    use.  Uses evaluate before their own node's (re)bindings."""
    ins, _ = reaching_defs(cfg)
    out: Dict[Tuple[int, str], Set[int]] = {}
    for node in cfg.nodes:
        for name, _line in node.uses:
            out[(node.idx, name)] = set(ins[node.idx].get(name, set()))
    return out


# -- jit-boundary map -------------------------------------------------------

@dataclasses.dataclass(frozen=True)
class JitSpec:
    """Statically-known facts about one jit wrapper: donated/static
    argument positions and names.  Conditional expressions contribute
    the UNION of their branches (``donate_argnums=(0, 1) if donate
    else ()`` may donate 0 and 1 — a may-analysis must assume it
    does)."""

    donate_argnums: FrozenSet[int] = frozenset()
    donate_argnames: FrozenSet[str] = frozenset()
    static_argnums: FrozenSet[int] = frozenset()
    static_argnames: FrozenSet[str] = frozenset()
    inner: Optional[str] = None   # wrapped callable name, when a Name
    line: int = 0

    @property
    def donates(self) -> bool:
        return bool(self.donate_argnums or self.donate_argnames)

    def union(self, other: Optional["JitSpec"]) -> "JitSpec":
        if other is None:
            return self
        return JitSpec(
            donate_argnums=self.donate_argnums | other.donate_argnums,
            donate_argnames=self.donate_argnames | other.donate_argnames,
            static_argnums=self.static_argnums | other.static_argnums,
            static_argnames=self.static_argnames | other.static_argnames,
            inner=self.inner or other.inner,
            line=self.line or other.line)


def _const_ints(node) -> FrozenSet[int]:
    if node is None:
        return frozenset()
    return frozenset(n.value for n in ast.walk(node)
                     if isinstance(n, ast.Constant)
                     and isinstance(n.value, int)
                     and not isinstance(n.value, bool))


def _const_strs(node) -> FrozenSet[str]:
    if node is None:
        return frozenset()
    return frozenset(n.value for n in ast.walk(node)
                     if isinstance(n, ast.Constant)
                     and isinstance(n.value, str))


_JIT_NAMES = ("jax.jit", "jit", "jax.pjit",
              "jax.experimental.pjit.pjit", "pjit")


def jit_call_spec(ctx: "FileCtx", call: ast.Call) -> Optional[JitSpec]:
    """JitSpec of a ``jax.jit(f, ...)`` / ``pjit(...)`` /
    ``functools.partial(jax.jit, ...)`` call expression, else None."""
    if not isinstance(call, ast.Call):
        return None
    dotted = ctx.resolve(call.func) or ""
    kwargs = {k.arg: k.value for k in call.keywords if k.arg}
    inner = None
    if dotted.split(".")[-1] == "partial" and call.args:
        if (ctx.resolve(call.args[0]) or "") not in _JIT_NAMES:
            return None
        if len(call.args) > 1 and isinstance(call.args[1], ast.Name):
            inner = call.args[1].id
    elif dotted in _JIT_NAMES:
        if call.args and isinstance(call.args[0], ast.Name):
            inner = call.args[0].id
    else:
        return None
    return JitSpec(
        donate_argnums=_const_ints(kwargs.get("donate_argnums")),
        donate_argnames=_const_strs(kwargs.get("donate_argnames")),
        static_argnums=_const_ints(kwargs.get("static_argnums")),
        static_argnames=_const_strs(kwargs.get("static_argnames")),
        inner=inner, line=call.lineno)


def jit_decorator_spec(ctx: "FileCtx", fn) -> Optional[JitSpec]:
    """JitSpec when `fn` is jit-decorated (``@jax.jit``,
    ``@jax.jit(...)``, ``@partial(jax.jit, ...)``), else None."""
    for dec in fn.decorator_list:
        if isinstance(dec, ast.Call):
            spec = jit_call_spec(ctx, dec)
            if spec is not None:
                return dataclasses.replace(spec, inner=fn.name,
                                           line=fn.lineno)
        elif (ctx.resolve(dec) or "") in _JIT_NAMES:
            return JitSpec(inner=fn.name, line=fn.lineno)
    return None


def _body_stmts(fn) -> List[ast.stmt]:
    """Every statement of `fn`'s own body — nested function/class
    *definitions* are included as statements, but their bodies are not
    descended into (those are separate scopes)."""
    out: List[ast.stmt] = []
    stack = list(fn.body)
    while stack:
        s = stack.pop()
        out.append(s)
        if isinstance(s, (ast.FunctionDef, ast.AsyncFunctionDef,
                          ast.ClassDef)):
            continue
        stack.extend(c for c in ast.iter_child_nodes(s)
                     if isinstance(c, ast.stmt))
    return out


def nested_defs(fn) -> List[ast.FunctionDef]:
    """Function definitions nested directly in `fn`'s own scope."""
    return [s for s in _body_stmts(fn)
            if isinstance(s, (ast.FunctionDef, ast.AsyncFunctionDef))]


def scope_functions(tree) -> List[ast.FunctionDef]:
    """Every function definition NOT nested inside another function —
    the entry points for per-function analyses: module-level functions
    AND class methods (at any class-nesting depth).  Function-nested
    defs are reached by each analysis' own recursion, which threads
    the enclosing scope's environment down to them.

    Memoized on the tree (several rules ask per file, and a marker
    walk from every function is quadratic on deeply-methoded files —
    measurable against the perf-gated full-tree run): one descent
    that simply stops at function boundaries is both linear and the
    definition itself."""
    cached = getattr(tree, "_splint_scope_fns", None)
    if cached is not None:
        return cached

    out: List[ast.FunctionDef] = []

    def descend(node):
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef,
                                  ast.AsyncFunctionDef)):
                out.append(child)  # and do NOT descend: nested defs
                continue           # belong to the per-analysis walks
            descend(child)

    descend(tree)
    tree._splint_scope_fns = out
    return out


def free_reads(fn) -> Set[str]:
    """Names `fn`'s body reads that `fn` itself does not bind — the
    closure/global reads, attributed to call sites by the donation
    analysis (calling ``snapshot()`` reads whatever ``snapshot``
    closes over, at the moment of the call)."""
    cfg = FunctionCFG(fn)
    bound: Set[str] = set()
    for node in cfg.nodes:
        bound.update(node.defs)
    out: Set[str] = set()
    for node in cfg.nodes:
        out.update(name for name, _ in node.uses)
    # comprehension-style targets inside expressions are already
    # excluded by _expr_loads; nested defs are opaque, so one level of
    # their own free reads is folded in (snapshot -> deeper closures)
    for sub in nested_defs(fn):
        out |= free_reads(sub)
    return out - bound


def callable_jit_spec(ctx: "FileCtx", expr,
                      env: Dict[str, JitSpec],
                      factories: Dict[str, JitSpec]
                      ) -> Optional[JitSpec]:
    """The JitSpec of the *value* of `expr`, when that value is a
    jit-wrapped callable: a direct ``jax.jit(...)`` expression, a name
    bound to one, a call to a jit *factory* (a function returning a
    jit-wrapped callable), or a conditional union of those."""
    if isinstance(expr, ast.IfExp):
        a = callable_jit_spec(ctx, expr.body, env, factories)
        b = callable_jit_spec(ctx, expr.orelse, env, factories)
        if a is None:
            return b
        return a.union(b)
    if isinstance(expr, ast.Name):
        return env.get(expr.id)
    if isinstance(expr, ast.Call):
        direct = jit_call_spec(ctx, expr)
        if direct is not None:
            return direct
        func = expr.func
        if isinstance(func, ast.IfExp):   # (_a if c else _b)(...)
            a = (factories.get(func.body.id)
                 if isinstance(func.body, ast.Name) else None)
            b = (factories.get(func.orelse.id)
                 if isinstance(func.orelse, ast.Name) else None)
            if a is None:
                return b
            return a.union(b)
        if isinstance(func, ast.Name):
            return factories.get(func.id)
    return None


def returns_jit_spec(ctx: "FileCtx", fn,
                     env: Dict[str, JitSpec],
                     factories: Dict[str, JitSpec]
                     ) -> Optional[JitSpec]:
    """JitSpec of `fn`'s return value when `fn` is a jit factory —
    it returns a jit-wrapped callable (directly, through a local
    binding, or by delegating to another known factory)."""
    local = dict(env)
    for s in _body_stmts(fn):
        if (isinstance(s, ast.Assign) and len(s.targets) == 1
                and isinstance(s.targets[0], ast.Name)):
            spec = callable_jit_spec(ctx, s.value, local, factories)
            if spec is not None:
                local[s.targets[0].id] = spec
    best: Optional[JitSpec] = None
    for s in _body_stmts(fn):
        if isinstance(s, ast.Return) and s.value is not None:
            spec = callable_jit_spec(ctx, s.value, local, factories)
            if spec is not None:
                best = spec if best is None else best.union(spec)
    return best


class JitBoundary:
    """Module-level jit-boundary map of one file.

    - `wrapped`: names bound to jit-wrapped callables (decorated
      functions, ``name = jax.jit(...)`` assignments);
    - `factories`: module-level functions whose RETURN VALUE is a
      jit-wrapped callable, resolved to a fixpoint so a factory may
      delegate to another factory (``build_sweep`` -> ``_make_sweep``
      -> ``jax.jit(sweep, donate_argnums=...)``);
    - `traced`: FunctionDef nodes whose body is traced (decorated, or
      referenced by name as a jit call's first argument), with the
      spec — what SPL009/SPL010 scan.
    """

    def __init__(self, ctx: "FileCtx"):
        self.wrapped: Dict[str, JitSpec] = {}
        self.factories: Dict[str, JitSpec] = {}
        self.traced: List[Tuple[ast.FunctionDef, JitSpec]] = []
        module_fns = [s for s in ctx.tree.body
                      if isinstance(s, (ast.FunctionDef,
                                        ast.AsyncFunctionDef))]
        for s in ctx.tree.body:
            if isinstance(s, (ast.FunctionDef, ast.AsyncFunctionDef)):
                spec = jit_decorator_spec(ctx, s)
                if spec is not None:
                    self.wrapped[s.name] = spec
            elif (isinstance(s, ast.Assign) and len(s.targets) == 1
                    and isinstance(s.targets[0], ast.Name)):
                spec = jit_call_spec(ctx, s.value)
                if spec is not None:
                    self.wrapped[s.targets[0].id] = spec
        # factory fixpoint over module-level functions (delegation
        # chains are short; cap the iteration defensively)
        for _ in range(8):
            changed = False
            for fn in module_fns:
                spec = returns_jit_spec(ctx, fn, self.wrapped,
                                        self.factories)
                if spec is not None and spec != self.factories.get(fn.name):
                    self.factories[fn.name] = spec
                    changed = True
            if not changed:
                break
        # traced functions: decorated ones, plus defs referenced by
        # name from a jit call in the same (or an enclosing) scope
        def visit(scope_fns: Dict[str, ast.FunctionDef], body):
            local_defs = {s.name: s for s in body
                          if isinstance(s, (ast.FunctionDef,
                                            ast.AsyncFunctionDef))}
            fns = dict(scope_fns, **local_defs)
            for s in body:
                for call in ast.walk(s):
                    spec = (jit_call_spec(ctx, call)
                            if isinstance(call, ast.Call) else None)
                    if spec is not None and spec.inner in fns:
                        self.traced.append((fns[spec.inner], spec))
            for fn in local_defs.values():
                visit(fns, fn.body)

        for fn in walk_nodes(ctx.tree):
            if isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
                spec = jit_decorator_spec(ctx, fn)
                if spec is not None:
                    self.traced.append((fn, spec))
        visit({}, ctx.tree.body)


def jit_boundary(ctx: "FileCtx") -> JitBoundary:
    """The (cached) jit-boundary map of one analyzed file."""
    if getattr(ctx, "_jit_boundary", None) is None:
        ctx._jit_boundary = JitBoundary(ctx)
    return ctx._jit_boundary


# -- run loop ---------------------------------------------------------------

def run(config: Config, baseline: Optional[Dict[str, dict]] = None,
        rules=None) -> Report:
    """Analyze the configured paths and reconcile against `baseline`."""
    from tools.splint.rules import RULES

    rules = RULES if rules is None else rules
    project = Project(config)
    for path in collect_files(config):
        rel = _relpath(path, config.root)
        ctx = _load_file(path, rel, project.parse_errors)
        if ctx is not None:
            project.files.append(ctx)

    raw: List[Finding] = list(project.parse_errors)
    for rule in rules:
        for ctx in project.files:
            raw.extend(rule.check(ctx, project))
    for rule in rules:
        raw.extend(rule.finalize(project))

    findings: List[Finding] = []
    suppressed = 0
    for f in sorted(raw, key=lambda f: (f.path, f.line, f.rule)):
        ctx = next((c for c in project.files if c.relpath == f.path), None)
        hit = ctx.ignores.suppresses(f) if ctx else None
        if hit is not None:
            suppressed += 1
            reason, pragma_line = hit
            if not reason:
                findings.append(Finding(
                    "SPL000", f.path, pragma_line,
                    f"ignore pragma for {f.rule} has no reason — the "
                    f"escape hatch requires a justification"))
            continue
        findings.append(f)
    # pragma syntax problems surface even when nothing was suppressed
    for ctx in project.files:
        for line, msg in ctx.ignores.errors:
            findings.append(Finding("SPL000", ctx.relpath, line, msg))

    baseline = baseline or {}
    groups: Dict[str, List[Finding]] = {}
    for f in findings:
        groups.setdefault(f.key, []).append(f)
    new: List[Finding] = []
    shrunk: Dict[str, Tuple[int, int]] = {}
    for key, group in sorted(groups.items()):
        allowed = int(baseline.get(key, {}).get("count", 0))
        if len(group) > allowed:
            if allowed:
                for f in group:
                    f.message += (f" [group {key}: {len(group)} found > "
                                  f"{allowed} baselined]")
            new.extend(group)
        elif len(group) < allowed:
            shrunk[key] = (len(group), allowed)
    stale = sorted(k for k in baseline if k not in groups)
    return Report(findings=findings, new=new, suppressed=suppressed,
                  stale=stale, shrunk=shrunk)
