"""Pallas kernel differential tests (interpret mode on CPU).

≙ the reference's practice of running the real optimized kernels in
tests (tests/mttkrp_test.c) — interpret mode executes the exact kernel
semantics that Mosaic compiles on TPU.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from splatt_tpu.blocked import BlockedSparse
from splatt_tpu.config import BlockAlloc, Options
from splatt_tpu.ops.mttkrp import mttkrp, mttkrp_blocked
from splatt_tpu.ops.pallas_kernels import (onehot_reduce_full,
                                           onehot_reduce_sorted)
from tests import gen
from tests.test_mttkrp import make_factors, np_mttkrp

TOL = 1e-10


def _np_onehot_sorted(local, prod, S):
    nb, B = local.shape
    out = np.zeros((nb, S, prod.shape[-1]), dtype=np.float64)
    for b in range(nb):
        for j in range(B):
            s = local[b, j]
            if 0 <= s < S:
                out[b, s] += prod[b, j]
    return out


def test_onehot_reduce_sorted_kernel():
    rng = np.random.default_rng(0)
    nb, B, S, R = 5, 128, 16, 8
    local = rng.integers(-1, S + 3, size=(nb, B)).astype(np.int32)
    prod = rng.random((nb, B, R))
    got = onehot_reduce_sorted(jnp.asarray(local), jnp.asarray(prod), S,
                               interpret=True)
    np.testing.assert_allclose(np.asarray(got),
                               _np_onehot_sorted(local, prod, S), atol=TOL)


def test_onehot_reduce_full_kernel():
    rng = np.random.default_rng(1)
    nb, B, W, R = 9, 128, 24, 8  # nb not divisible by the chunk size
    local = rng.integers(0, W, size=(nb, B)).astype(np.int32)
    prod = rng.random((nb, B, R))
    got = onehot_reduce_full(jnp.asarray(local), jnp.asarray(prod), W,
                             interpret=True)
    want = _np_onehot_sorted(local, prod, W).sum(axis=0)
    np.testing.assert_allclose(np.asarray(got), want, atol=TOL)


@pytest.mark.parametrize("name", ["med", "med4"])
def test_pallas_mttkrp_matches_oracle(name):
    """Full blocked MTTKRP with the Pallas engine (interpret) on every
    mode/path where a one-hot reduction runs."""
    tt = gen.fixture_tensor(name)
    opts = Options(block_alloc=BlockAlloc.ALLMODE, nnz_block=128,
                   val_dtype=np.float64)
    bs = BlockedSparse.from_coo(tt, opts)
    factors = make_factors(tt.dims)
    for mode in range(tt.nmodes):
        want = np_mttkrp(tt, factors, mode)
        got = mttkrp_blocked(bs.layout_for(mode), factors, mode,
                             path="sorted_onehot", impl="pallas_interpret")
        np.testing.assert_allclose(np.asarray(got), want, atol=TOL,
                                   err_msg=f"sorted_onehot mode={mode}")
        other = bs.layout_for((mode + 1) % tt.nmodes)
        if other.mode != mode:
            got = mttkrp_blocked(other, factors, mode,
                                 path="privatized", impl="pallas_interpret")
            np.testing.assert_allclose(np.asarray(got), want, atol=TOL,
                                       err_msg=f"privatized mode={mode}")


def test_public_mttkrp_forced_pallas():
    tt = gen.fixture_tensor("med")
    opts = Options(val_dtype=np.float64, use_pallas=True, nnz_block=256)
    bs = BlockedSparse.from_coo(tt, opts)
    factors = make_factors(tt.dims)
    got = mttkrp(bs, factors, bs.layouts[0].mode)
    want = np_mttkrp(tt, factors, bs.layouts[0].mode)
    np.testing.assert_allclose(np.asarray(got), want, atol=TOL)


def test_fused_mttkrp_kernel_direct():
    """Direct fused-kernel calls (sorted partials + privatized totals)
    vs the numpy brute force."""
    from splatt_tpu.blocked import build_layout
    from splatt_tpu.ops.pallas_kernels import fused_mttkrp

    tt = gen.fixture_tensor("med")
    factors = make_factors(tt.dims)
    for mode in range(tt.nmodes):
        lay = build_layout(tt, mode, block=128, val_dtype=np.float64)
        want = np_mttkrp(tt, factors, mode)
        S = lay.seg_width
        parts = fused_mttkrp(lay, factors, mode, S, accumulate=False,
                             interpret=True)
        idx = (np.asarray(lay.row_start)[:, None] + np.arange(S)).reshape(-1)
        out = np.zeros((tt.dims[mode] + S + 1, factors[0].shape[1]))
        np.add.at(out, idx, np.asarray(parts).reshape(-1, factors[0].shape[1]))
        np.testing.assert_allclose(out[:tt.dims[mode]], want, atol=TOL,
                                   err_msg=f"fused sorted mode={mode}")
        W = -(-(tt.dims[mode] + 1) // 8) * 8
        tot = fused_mttkrp(lay, factors, mode, W, accumulate=True,
                           interpret=True)
        np.testing.assert_allclose(np.asarray(tot)[:tt.dims[mode]], want,
                                   atol=TOL,
                                   err_msg=f"fused privatized mode={mode}")


def test_fused_tg_kernel_direct():
    """Sublane-tiled fused kernel (grid over rank tiles × blocks) vs the
    numpy brute force — covering multi-chunk lane gathers (block larger
    than a padded mode dim), multiple rank tiles, and both output
    contracts."""
    from splatt_tpu.blocked import build_layout
    from splatt_tpu.ops.pallas_kernels import fused_mttkrp_tg

    for name, block, rank in (("med", 128, 8),      # single tile
                              ("med", 512, 20),     # ck>1, 3 rank tiles
                              ("med4", 256, 12)):   # 4-mode
        tt = gen.fixture_tensor(name)
        factors = make_factors(tt.dims, rank=rank)
        for mode in range(tt.nmodes):
            lay = build_layout(tt, mode, block=block, val_dtype=np.float64)
            want = np_mttkrp(tt, factors, mode)
            S = lay.seg_width
            parts = fused_mttkrp_tg(lay, factors, mode, S, accumulate=False,
                                    interpret=True)
            idx = (np.asarray(lay.row_start)[:, None]
                   + np.arange(S)).reshape(-1)
            out = np.zeros((tt.dims[mode] + S + 1, rank))
            np.add.at(out, idx, np.asarray(parts).reshape(-1, rank))
            np.testing.assert_allclose(
                out[:tt.dims[mode]], want, atol=TOL,
                err_msg=f"fused_tg sorted {name} block={block} mode={mode}")
            W = -(-(tt.dims[mode] + 1) // 8) * 8
            tot = fused_mttkrp_tg(lay, factors, mode, W, accumulate=True,
                                  interpret=True)
            np.testing.assert_allclose(
                np.asarray(tot)[:tt.dims[mode]], want, atol=TOL,
                err_msg=f"fused_tg priv {name} block={block} mode={mode}")


def test_fused_tg_dispatch_when_tables_too_big(monkeypatch):
    """When whole-table residency (fused_t) is gated out, dispatch picks
    the sublane-tiled kernel — whose VMEM plan is rank/dim independent —
    and the answer still matches."""
    import splatt_tpu.ops.pallas_kernels as pk
    from splatt_tpu.ops.mttkrp import engine_plan

    tt = gen.fixture_tensor("med")
    opts = Options(block_alloc=BlockAlloc.ALLMODE, nnz_block=128,
                   val_dtype=np.float64)
    bs = BlockedSparse.from_coo(tt, opts)
    factors = make_factors(tt.dims)
    monkeypatch.setattr(pk, "fused_t_vmem_ok", lambda *a, **k: False)
    mttkrp_blocked.clear_cache()
    for mode in range(tt.nmodes):
        lay = bs.layout_for(mode)
        assert engine_plan(lay, factors, mode, "sorted_onehot",
                           "pallas_interpret") == "fused_tg"
        want = np_mttkrp(tt, factors, mode)
        got = mttkrp_blocked(lay, factors, mode,
                             path="sorted_onehot", impl="pallas_interpret")
        np.testing.assert_allclose(np.asarray(got), want, atol=TOL,
                                   err_msg=f"fused_tg dispatch mode={mode}")


def test_fused_tg_bf16_accumulates_f32():
    from splatt_tpu.blocked import build_layout
    from splatt_tpu.ops.pallas_kernels import fused_mttkrp_tg

    tt = gen.fixture_tensor("med")
    factors = [jnp.asarray(np.asarray(f), dtype=jnp.bfloat16)
               for f in make_factors(tt.dims)]
    lay = build_layout(tt, 0, block=128, val_dtype=jnp.bfloat16)
    W = -(-(tt.dims[0] + 1) // 8) * 8
    tot = fused_mttkrp_tg(lay, factors, 0, W, accumulate=True,
                          interpret=True)
    assert tot.dtype == jnp.float32
    want = np_mttkrp(tt, [np.asarray(f, np.float64) for f in factors], 0)
    np.testing.assert_allclose(np.asarray(tot)[:tt.dims[0]], want, atol=0.6,
                               rtol=0.1)


def test_fused_vmem_gate():
    from splatt_tpu.ops.pallas_kernels import fused_vmem_ok

    small = [jnp.zeros((64, 16)) for _ in range(3)]
    assert fused_vmem_ok(small, 0, 64, 128)
    huge = [jax.ShapeDtypeStruct((4_000_000, 64), jnp.float32)
            for _ in range(3)]
    assert not fused_vmem_ok(huge, 0, 64, 4096)


def test_pallas_unfused_fallback_matches(monkeypatch):
    """When factors exceed the fused VMEM budget the Pallas engine falls
    back to the unfused (prod-precomputed) kernels — same answer."""
    import splatt_tpu.ops.pallas_kernels as pk

    tt = gen.fixture_tensor("med")
    opts = Options(block_alloc=BlockAlloc.ALLMODE, nnz_block=128,
                   val_dtype=np.float64)
    bs = BlockedSparse.from_coo(tt, opts)
    factors = make_factors(tt.dims)
    monkeypatch.setattr(pk, "fused_vmem_ok", lambda *a, **k: False)
    monkeypatch.setattr(pk, "fused_t_vmem_ok", lambda *a, **k: False)
    monkeypatch.setattr(pk, "fused_tg_vmem_ok", lambda *a, **k: False)
    # identical statics/avals were traced earlier in this file with the
    # fused branch; drop the cache so the monkeypatch is consulted
    mttkrp_blocked.clear_cache()
    from splatt_tpu.ops.mttkrp import engine_plan

    for mode in range(tt.nmodes):
        lay = bs.layout_for(mode)
        assert engine_plan(lay, factors, mode, "sorted_onehot",
                           "pallas_interpret") == "unfused_pallas"
        want = np_mttkrp(tt, factors, mode)
        got = mttkrp_blocked(lay, factors, mode,
                             path="sorted_onehot", impl="pallas_interpret")
        np.testing.assert_allclose(np.asarray(got), want, atol=TOL,
                                   err_msg=f"unfused fallback mode={mode}")


def test_fused_bf16_accumulates_f32():
    from splatt_tpu.blocked import build_layout
    from splatt_tpu.ops.pallas_kernels import fused_mttkrp

    tt = gen.fixture_tensor("med")
    factors = [jnp.asarray(np.asarray(f), dtype=jnp.bfloat16)
               for f in make_factors(tt.dims)]
    lay = build_layout(tt, 0, block=128, val_dtype=jnp.bfloat16)
    W = -(-(tt.dims[0] + 1) // 8) * 8
    tot = fused_mttkrp(lay, factors, 0, W, accumulate=True, interpret=True)
    assert tot.dtype == jnp.float32
    want = np_mttkrp(tt, [np.asarray(f, np.float64) for f in factors], 0)
    np.testing.assert_allclose(np.asarray(tot)[:tt.dims[0]], want, atol=0.6,
                               rtol=0.1)


def test_vmem_chunk_bounds():
    from splatt_tpu.ops.pallas_kernels import vmem_chunk

    assert vmem_chunk(64, 512, 128) >= 1          # typical config fits
    assert vmem_chunk(4096, 4096, 128) == 0       # pathological: fall back
    assert 1 <= vmem_chunk(8, 128, 8) <= 8


def test_pallas_bf16_accumulates_f32():
    """bf16 inputs through the Pallas kernels produce f32 outputs that
    match the f64 brute force at bf16 tolerance."""
    rng = np.random.default_rng(3)
    nb, B, S, R = 4, 128, 16, 8
    local = rng.integers(-1, S + 2, size=(nb, B)).astype(np.int32)
    prod16 = jnp.asarray(rng.random((nb, B, R)), dtype=jnp.bfloat16)
    got = onehot_reduce_sorted(jnp.asarray(local), prod16, S, interpret=True)
    assert got.dtype == jnp.float32
    want = _np_onehot_sorted(local, np.asarray(prod16, dtype=np.float64), S)
    np.testing.assert_allclose(np.asarray(got, dtype=np.float64), want,
                               atol=3e-2)
    got2 = onehot_reduce_full(jnp.asarray(local), prod16, S + 8,
                              interpret=True)
    assert got2.dtype == jnp.float32
    want2 = _np_onehot_sorted(local, np.asarray(prod16, dtype=np.float64),
                              S + 8).sum(axis=0)
    np.testing.assert_allclose(np.asarray(got2, dtype=np.float64), want2,
                               atol=3e-2)


def test_fused_tg_production_dims_interpret():
    """fused_tg index math at real NELL-2 production dims — block 4096,
    28928-lane padded gathers, rank 50 (the shapes whose Mosaic
    compiles crash for fused_t) — stays exact in interpret mode."""
    from splatt_tpu.blocked import build_layout
    from splatt_tpu.ops.mttkrp import mttkrp_stream
    from splatt_tpu.ops.pallas_kernels import fused_mttkrp_tg

    rng = np.random.default_rng(0)
    dims = (12092, 9184, 28818)
    nnz, rank = 4096, 50
    inds = np.stack([rng.integers(0, d, nnz) for d in dims]).astype(np.int64)
    from splatt_tpu.coo import SparseTensor

    tt = SparseTensor(inds=inds, vals=rng.standard_normal(nnz), dims=dims)
    fac = [jnp.asarray(rng.standard_normal((d, rank)).astype(np.float32))
           for d in dims]
    lay = build_layout(tt, 0, block=4096, val_dtype=np.float32)
    S = lay.seg_width
    parts = fused_mttkrp_tg(lay, fac, 0, S, accumulate=False,
                            interpret=True)
    idx = (np.asarray(lay.row_start)[:, None] + np.arange(S)).reshape(-1)
    out = np.zeros((dims[0] + S + 1, rank), np.float32)
    np.add.at(out, idx, np.asarray(parts).reshape(-1, rank))
    gold = np.asarray(mttkrp_stream(jnp.asarray(tt.inds),
                                    jnp.asarray(tt.vals), fac, 0, dims[0]))
    err = (np.abs(out[:dims[0]] - gold).max()
           / max(np.abs(gold).max(), 1e-9))
    assert err < 5e-5, err


def test_scan_target_knob_changes_chunking_not_results():
    """scan_target tunes the XLA engine's scan granularity (the
    hardware sweep knob; default from SPLATT_SCAN_TARGET_ELEMS) as a
    static jit argument — distinct values re-trace — without changing
    the computed MTTKRP."""
    from splatt_tpu.blocked import build_layout

    tt = gen.fixture_tensor("med")
    factors = make_factors(tt.dims)
    lay = build_layout(tt, 0, block=128, val_dtype=np.float64)
    want = np_mttkrp(tt, factors, 0)
    for target in (1 << 10, 1 << 16, 1 << 24):
        got = mttkrp_blocked(lay, factors, 0, path="sorted_onehot",
                             impl="xla", scan_target=target)
        np.testing.assert_allclose(np.asarray(got), want, atol=TOL,
                                   err_msg=str(target))
