from splatt_tpu.ops.mttkrp import mttkrp, mttkrp_stream, mttkrp_blocked
from splatt_tpu.ops.linalg import (
    gram,
    form_normal_lhs,
    solve_normals,
    normalize_columns,
)

__all__ = [
    "mttkrp",
    "mttkrp_stream",
    "mttkrp_blocked",
    "gram",
    "form_normal_lhs",
    "solve_normals",
    "normalize_columns",
]
