"""splint v5 (part 1): dtype-precision flow rules (SPL024, SPL028).

splatt-tpu's mixed-precision story is a contract, not a convention:
factors and nonzeros may be stored narrow (bf16/f16) but every
reduction over them — segment-sums, Grams, inner products, fit
numerators — must accumulate wide.  The sanctioned forms are exactly
three: route the dtype through the ``config`` acc-dtype helpers
(``acc_dtype`` / ``_acc_dtype`` / ``fit_dtype`` / ``host_acc_dtype``),
pin MXU output via ``preferred_element_type=...``, or pass an explicit
wide ``dtype=`` to the reduce itself.  An unpinned ``bf16 @ bf16`` is
silently 8-mantissa-bit accumulation; a pre-reduce ``narrow * wide``
stream op silently doubles hot-loop bytes.  Both are invisible to
tests that only run f32.

These rules run a small abstract interpreter over each audited
function: a dtype lattice {f64, f32, bf16, f16, int, bool, py-scalar,
unknown} is propagated through assignments (``astype``, ``dtype=``
kwargs, ``zeros/full``-style constructors, elementwise ops, promotion
at binops, comparisons → bool) so reduce operands can be judged at
their consumption site.  The lattice is deliberately conservative:
``unknown`` never passes a reduce — the fix is either a sanctioned
upcast (which the lattice CAN see) or a ``# splint: ignore[SPL024]``
with a reason.

Rules (hard zero-rules — never baselined):

SPL024 accumulation-dtype discipline
    In the ``numerics-modules`` scope, every reduction call —
    ``jnp.sum``/``.sum()``/``mean``/``prod``, ``segment_sum``,
    ``dot_general``/``matmul``/``dot``/``tensordot``/``einsum`` and
    the ``@`` operator — must satisfy one of: a
    ``preferred_element_type=`` pin (dot family), an explicit
    ``dtype=`` kwarg (sum family), an operand the lattice proves wide
    (f32/f64 — including the RESULT of an already-pinned dot, and of
    ``x.astype(acc_helper(...))``), or an operand proven integer/bool
    (index math and mask counting accumulate exactly).  Anything
    else — narrow or unresolvable — fires.  Each configured
    acc-dtype helper must also exist in the dtype-policy module
    (``config-module``), both directions of the registry.

SPL028 implicit-upcast-on-hot-path
    Hot stream functions (``hot-stream-functions``, with entry dtypes
    declared in ``hot-stream-param-dtypes`` — the storage contract the
    dispatch layer feeds them) must not mix narrow and wide operands
    in elementwise arithmetic before the sanctioned accumulate point:
    ``f32_M * bf16_U`` materializes a full-size f32 stream where the
    kernel was supposed to move bf16 bytes and upcast only inside the
    reduce.  The fix is a single pinned contraction
    (``einsum(..., preferred_element_type=acc)``) or reordering the
    upcast into the reduce operand.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Tuple

from tools.splint.core import (FileCtx, Finding, Project, walk_nodes)

WIDE = ("f64", "f32")
NARROW = ("bf16", "f16")
EXACT = ("int", "bool")

#: dtype attribute / string spellings → lattice token
_DTYPE_TOKENS = {
    "float64": "f64", "double": "f64",
    "float32": "f32", "float_": "f32", "single": "f32",
    "bfloat16": "bf16", "float16": "f16", "half": "f16",
    "int8": "int", "int16": "int", "int32": "int", "int64": "int",
    "uint8": "int", "uint16": "int", "uint32": "int", "uint64": "int",
    "bool_": "bool", "bool": "bool",
}

#: lattice join order for binop promotion (higher wins)
_ORDER = {"py": 0, "bool": 1, "int": 2, "f16": 3, "bf16": 3,
          "f32": 4, "f64": 5}

_SUM_FAMILY = ("sum", "mean", "prod", "nansum", "nanmean")
_DOT_FAMILY = ("dot_general", "matmul", "dot", "tensordot", "einsum")
_SEGMENT_FAMILY = ("segment_sum", "segment_max", "segment_min",
                   "segment_prod")
#: elementwise/shape ops through which the operand dtype passes
_PASSTHROUGH = (
    "sqrt", "abs", "exp", "log", "log1p", "negative", "square",
    "maximum", "minimum", "where", "clip", "reshape", "transpose",
    "swapaxes", "broadcast_to", "pad", "ravel", "squeeze",
    "expand_dims", "take", "take_along_axis", "concatenate", "stack",
    "outer", "multiply", "add", "subtract", "divide", "true_divide",
)
_ARITH_OPS = (ast.Mult, ast.Add, ast.Sub, ast.Div, ast.Pow)


def _dedupe(findings: List[Finding]) -> List[Finding]:
    seen, out = set(), []
    for f in findings:
        key = (f.rule, f.path, f.line, f.message)
        if key not in seen:
            seen.add(key)
            out.append(f)
    return out


def _functions(tree: ast.AST):
    for node in walk_nodes(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            yield node


def _last_seg(dotted: str) -> str:
    return dotted.rsplit(".", 1)[-1]


def _in_scope(relpath: str, entries: List[str]) -> bool:
    for e in entries:
        e = e.rstrip("/")
        if relpath == e or relpath.startswith(e + "/"):
            return True
    return False


def _join(a: Optional[str], b: Optional[str]) -> Optional[str]:
    """Binop promotion on the lattice.  ``py`` (python scalar literal)
    is neutral — jax weak types take the array side's dtype.  Unknown
    poisons (promotion with an unknown operand is unknown)."""
    if a == "py":
        return b
    if b == "py":
        return a
    if a is None or b is None:
        return None
    if a == b:
        return a
    # two distinct narrow floats never meet in this codebase; if they
    # do, the result is not provably anything useful
    if _ORDER[a] == _ORDER[b]:
        return None
    return a if _ORDER[a] > _ORDER[b] else b


class _Env:
    """Per-function abstract state: array lattice values and
    dtype-valued locals (``acc = _acc_dtype(x.dtype)``)."""

    def __init__(self):
        self.arrays: Dict[str, Optional[str]] = {}
        self.dtypes: Dict[str, Optional[str]] = {}


def _is_acc_helper(ctx: FileCtx, call: ast.Call,
                   helpers: List[str]) -> bool:
    dotted = ctx.resolve(call.func) or ""
    return bool(dotted) and _last_seg(dotted) in helpers


def _kwarg(call: ast.Call, name: str) -> Optional[ast.expr]:
    for kw in call.keywords:
        if kw.arg == name:
            return kw.value
    return None


def _dtype_token(ctx: FileCtx, expr: Optional[ast.expr], env: _Env,
                 helpers: List[str]) -> Optional[str]:
    """Evaluate an expression in DTYPE position (``astype(...)`` arg,
    ``dtype=`` kwarg, ``preferred_element_type=`` kwarg)."""
    if expr is None:
        return None
    if isinstance(expr, ast.Constant) and isinstance(expr.value, str):
        return _DTYPE_TOKENS.get(expr.value)
    if isinstance(expr, ast.Name):
        if expr.id in env.dtypes:
            return env.dtypes[expr.id]
        dotted = ctx.resolve(expr) or ""
        return _DTYPE_TOKENS.get(_last_seg(dotted)) if dotted else None
    if isinstance(expr, ast.Attribute):
        if expr.attr == "dtype":
            # x.dtype — the lattice value of x IS its dtype
            if isinstance(expr.value, ast.Name):
                return env.arrays.get(expr.value.id)
            return None
        dotted = ctx.resolve(expr) or ""
        return _DTYPE_TOKENS.get(_last_seg(dotted)) if dotted else None
    if isinstance(expr, ast.Call):
        if _is_acc_helper(ctx, expr, helpers):
            # the whole point of the helpers: result is never narrow.
            # f64 in → f64 out, everything else → f32; "f32" is the
            # conservative wide witness either way.
            arg = _dtype_token(ctx, expr.args[0], env, helpers) \
                if expr.args else None
            return arg if arg == "f64" else "f32"
        dotted = ctx.resolve(expr.func) or ""
        if _last_seg(dotted) == "dtype" and expr.args:
            # jnp.dtype(x) — identity on the lattice
            return _dtype_token(ctx, expr.args[0], env, helpers)
    return None


def _array_value(ctx: FileCtx, expr: ast.expr, env: _Env,
                 helpers: List[str]) -> Optional[str]:
    """Evaluate an expression in ARRAY position → lattice token."""
    if isinstance(expr, ast.Constant):
        if isinstance(expr.value, bool):
            return "bool"
        if isinstance(expr.value, (int, float)):
            return "py"
        return None
    if isinstance(expr, ast.Name):
        return env.arrays.get(expr.id)
    if isinstance(expr, (ast.Subscript, ast.Starred)):
        return _array_value(ctx, expr.value, env, helpers)
    if isinstance(expr, ast.UnaryOp):
        return _array_value(ctx, expr.operand, env, helpers)
    if isinstance(expr, ast.IfExp):
        return _join(_array_value(ctx, expr.body, env, helpers),
                     _array_value(ctx, expr.orelse, env, helpers))
    if isinstance(expr, ast.Compare):
        return "bool"
    if isinstance(expr, ast.BoolOp):
        return "bool"
    if isinstance(expr, ast.BinOp):
        return _join(_array_value(ctx, expr.left, env, helpers),
                     _array_value(ctx, expr.right, env, helpers))
    if isinstance(expr, (ast.Tuple, ast.List)):
        val: Optional[str] = "py"
        for e in expr.elts:
            val = _join(val, _array_value(ctx, e, env, helpers))
        return val
    if isinstance(expr, ast.Call):
        return _call_value(ctx, expr, env, helpers)
    return None


def _call_value(ctx: FileCtx, call: ast.Call, env: _Env,
                helpers: List[str]) -> Optional[str]:
    dotted = ctx.resolve(call.func) or ""
    last = _last_seg(dotted) if dotted else ""
    # x.astype(d) — method on an unresolvable receiver: resolve() gives
    # None for calls-on-calls, so handle the Attribute shape directly
    if isinstance(call.func, ast.Attribute) and call.func.attr == "astype":
        return _dtype_token(ctx, call.args[0] if call.args else None,
                            env, helpers)
    if not last:
        return None
    if last in ("asarray", "array", "zeros", "ones", "full", "empty",
                "zeros_like", "ones_like", "full_like", "empty_like"):
        tok = _dtype_token(ctx, _kwarg(call, "dtype"), env, helpers)
        if tok is not None:
            return tok
        if last.endswith("_like") and call.args:
            return _array_value(ctx, call.args[0], env, helpers)
        if last in ("asarray", "array") and call.args:
            return _array_value(ctx, call.args[0], env, helpers)
        return None
    if last in ("arange", "argsort", "argmax", "argmin", "searchsorted",
                "nonzero", "flatnonzero"):
        tok = _dtype_token(ctx, _kwarg(call, "dtype"), env, helpers)
        return tok if tok is not None else "int"
    if last in _DOT_FAMILY:
        pin = _kwarg(call, "preferred_element_type")
        if pin is not None:
            # the pin IS the sanctioned discipline; if splint cannot
            # resolve its value the author still routed it explicitly,
            # and the conservative downstream witness is "wide"
            tok = _dtype_token(ctx, pin, env, helpers)
            return tok if tok is not None else "f32"
        args = [a for a in call.args
                if not (isinstance(a, ast.Constant)
                        and isinstance(a.value, str))]
        val: Optional[str] = "py"
        for a in args:
            val = _join(val, _array_value(ctx, a, env, helpers))
        return val
    if last in _SUM_FAMILY or last in _SEGMENT_FAMILY:
        tok = _dtype_token(ctx, _kwarg(call, "dtype"), env, helpers)
        if tok is not None:
            return tok
        if isinstance(call.func, ast.Attribute) and not dotted.startswith(
                ("jax", "numpy", "jnp", "np")):
            return _array_value(ctx, call.func.value, env, helpers)
        if call.args:
            return _array_value(ctx, call.args[0], env, helpers)
        return None
    if last in _PASSTHROUGH:
        if last == "where" and len(call.args) == 3:
            return _join(_array_value(ctx, call.args[1], env, helpers),
                         _array_value(ctx, call.args[2], env, helpers))
        if last in ("concatenate", "stack") and call.args:
            return _array_value(ctx, call.args[0], env, helpers)
        if isinstance(call.func, ast.Attribute) and last in (
                "reshape", "transpose", "swapaxes", "ravel", "squeeze",
                "take", "clip"):
            recv = _array_value(ctx, call.func.value, env, helpers)
            if recv is not None:
                return recv
        if call.args:
            val: Optional[str] = "py"
            for a in call.args:
                val = _join(val, _array_value(ctx, a, env, helpers))
            return val
    return None


def _seed_params(fn: ast.AST, relpath: str, env: _Env,
                 param_dtypes: List[str]) -> None:
    """Apply ``hot-stream-param-dtypes`` declarations
    ("relpath::fn::param=token") — the storage contract the dispatch
    layer feeds this function."""
    prefix = f"{relpath}::{fn.name}::"
    for entry in param_dtypes:
        if not entry.startswith(prefix):
            continue
        param, _, tok = entry[len(prefix):].partition("=")
        if tok in _ORDER:
            env.arrays[param.strip()] = tok.strip()


def _build_env(ctx: FileCtx, fn: ast.AST, helpers: List[str],
               seed: Optional[_Env] = None) -> _Env:
    """Two-pass flow-insensitive assignment sweep.  Conflicting
    re-assignments degrade to unknown; the second pass lets values
    assigned late in a loop body reach uses earlier in it."""
    env = _Env()
    if seed is not None:
        env.arrays.update(seed.arrays)
        env.dtypes.update(seed.dtypes)
    seeded = set(env.arrays)
    for _ in range(2):
        stmts = [n for n in walk_nodes(fn)
                 if isinstance(n, (ast.Assign, ast.AnnAssign,
                                   ast.AugAssign))]
        for st in sorted(stmts, key=lambda n: n.lineno):
            value = st.value
            if value is None:
                continue
            targets = (st.targets if isinstance(st, ast.Assign)
                       else [st.target])
            dt = _dtype_token(ctx, value, env, helpers)
            av = _array_value(ctx, value, env, helpers)
            for t in targets:
                if not isinstance(t, ast.Name):
                    continue
                if t.id in seeded:
                    continue  # declared contract wins over local flow
                if isinstance(st, ast.AugAssign):
                    env.arrays[t.id] = _join(env.arrays.get(t.id), av)
                    continue
                # a name can hold a dtype OR an array, not both; dtype
                # interpretation wins only when the RHS is clearly a
                # dtype expression (helper call / dtype literal)
                if dt is not None and (
                        isinstance(value, ast.Call)
                        and _is_acc_helper(ctx, value, helpers)
                        or isinstance(value, (ast.Attribute, ast.Name))
                        and av is None):
                    env.dtypes[t.id] = dt
                prev = env.arrays.get(t.id)
                env.arrays[t.id] = av if prev is None else (
                    av if prev == av else None)
    return env


class _NumericsRule:
    id = "SPL0xx"
    title = ""
    hint = ""

    def finding(self, ctx_or_path, line: int, message: str) -> Finding:
        path = (ctx_or_path.relpath if isinstance(ctx_or_path, FileCtx)
                else ctx_or_path)
        return Finding(self.id, path, line, f"{self.title}: {message}",
                       hint=self.hint)

    def check(self, ctx: FileCtx, project: Project) -> List[Finding]:
        return []

    def finalize(self, project: Project) -> List[Finding]:
        return []


class AccumulationDiscipline(_NumericsRule):
    """SPL024: every reduce over possibly-narrow storage must carry the
    accumulation-dtype discipline."""

    id = "SPL024"
    title = "reduction without accumulation-dtype discipline"
    hint = ("route the reduce through config.acc_dtype (operand "
            "``.astype(acc_dtype(x.dtype))``, ``dtype=acc`` on the sum, "
            "or ``preferred_element_type=acc`` on the dot); if the "
            "operand is provably exact/wide for a reason splint cannot "
            "see, add `# splint: ignore[SPL024] <reason>`")

    def check(self, ctx: FileCtx, project: Project) -> List[Finding]:
        conf = project.config
        if not _in_scope(ctx.relpath, conf.numerics_modules):
            return []
        helpers = conf.acc_dtype_helpers
        out: List[Finding] = []
        for fn in _functions(ctx.tree):
            env = _Env()
            _seed_params(fn, ctx.relpath, env,
                         conf.hot_stream_param_dtypes)
            env = _build_env(ctx, fn, helpers, seed=env)
            for node in walk_nodes(fn):
                if isinstance(node, ast.BinOp) and isinstance(
                        node.op, ast.MatMult):
                    val = _join(
                        _array_value(ctx, node.left, env, helpers),
                        _array_value(ctx, node.right, env, helpers))
                    if val not in WIDE and val not in EXACT:
                        out.append(self.finding(
                            ctx, node.lineno,
                            "`@` has no preferred_element_type pin and "
                            f"its operands are {val or 'unresolvable'}; "
                            "narrow storage would accumulate narrow"))
                    continue
                if not isinstance(node, ast.Call):
                    continue
                out.extend(self._check_call(ctx, node, env, helpers))
        return _dedupe(out)

    def _check_call(self, ctx: FileCtx, call: ast.Call, env: _Env,
                    helpers: List[str]) -> List[Finding]:
        dotted = ctx.resolve(call.func) or ""
        if not dotted and isinstance(call.func, ast.Attribute):
            # method reduce on an unresolvable receiver: x.sum()
            last = call.func.attr
            if last not in _SUM_FAMILY:
                return []
            if _dtype_token(ctx, _kwarg(call, "dtype"), env,
                            helpers) is not None:
                return []
            recv = _array_value(ctx, call.func.value, env, helpers)
            if recv in WIDE or recv in EXACT:
                return []
            return [self.finding(
                ctx, call.lineno,
                f".{last}() over a {recv or 'unresolvable'} operand "
                "with no dtype= accumulation pin")]
        last = _last_seg(dotted) if dotted else ""
        if last in _DOT_FAMILY:
            if _kwarg(call, "preferred_element_type") is not None:
                return []
            args = [a for a in call.args
                    if not (isinstance(a, ast.Constant)
                            and isinstance(a.value, str))]
            val: Optional[str] = "py"
            for a in args:
                val = _join(val, _array_value(ctx, a, env, helpers))
            if val in WIDE or val in EXACT:
                return []
            return [self.finding(
                ctx, call.lineno,
                f"{last} has no preferred_element_type pin and its "
                f"operands are {val or 'unresolvable'}")]
        if last in _SUM_FAMILY or last in _SEGMENT_FAMILY:
            is_jnp_style = dotted.startswith(
                ("jax", "numpy", "jnp", "np"))
            if not is_jnp_style and not isinstance(
                    call.func, ast.Attribute):
                return []
            if _dtype_token(ctx, _kwarg(call, "dtype"), env,
                            helpers) is not None:
                return []
            if last in _SEGMENT_FAMILY or is_jnp_style or not isinstance(
                    call.func, ast.Attribute):
                operand = call.args[0] if call.args else None
            else:
                operand = call.func.value
            val = _array_value(ctx, operand, env, helpers) \
                if operand is not None else None
            if val in WIDE or val in EXACT:
                return []
            if last in _SEGMENT_FAMILY:
                return [self.finding(
                    ctx, call.lineno,
                    f"{last} accumulates in its operand dtype "
                    f"({val or 'unresolvable'}) — upcast the operand "
                    "via .astype(acc_dtype(...)) before the reduce")]
            return [self.finding(
                ctx, call.lineno,
                f"{last} over a {val or 'unresolvable'} operand with "
                "no dtype= accumulation pin")]
        return []

    def finalize(self, project: Project) -> List[Finding]:
        """Registry leg: every configured acc-dtype helper that looks
        project-local (not dunder/builtin) must exist in the
        dtype-policy module — a helper splint trusts but nobody
        defines is a hole in the discipline."""
        conf = project.config
        ctx = project.ctx_for(conf.config_module)
        if ctx is None:
            return []
        defined = {fn.name for fn in _functions(ctx.tree)}
        out: List[Finding] = []
        for h in conf.acc_dtype_helpers:
            base = h.lstrip("_")
            if h in defined or base in defined or f"_{base}" in defined:
                continue
            out.append(self.finding(
                conf.config_module, 1,
                f"configured acc-dtype helper {h!r} is not defined in "
                "the dtype-policy module (stale [tool.splint] entry?)"))
        return out


class ImplicitHotUpcast(_NumericsRule):
    """SPL028: narrow×wide elementwise arithmetic in a hot stream
    function materializes a wide stream before the reduce."""

    id = "SPL028"
    title = "implicit upcast on hot path"
    hint = ("fold the upcast into the sanctioned accumulate point — a "
            "single pinned contraction (einsum/dot_general with "
            "preferred_element_type) or .astype(acc) directly on the "
            "reduce operand — instead of materializing a wide "
            "elementwise intermediate")

    def check(self, ctx: FileCtx, project: Project) -> List[Finding]:
        conf = project.config
        wanted = {e.split("::", 2)[1] for e in conf.hot_stream_functions
                  if e.startswith(ctx.relpath + "::")}
        if not wanted:
            return []
        helpers = conf.acc_dtype_helpers
        out: List[Finding] = []
        for fn in _functions(ctx.tree):
            if fn.name not in wanted:
                continue
            env = _Env()
            _seed_params(fn, ctx.relpath, env,
                         conf.hot_stream_param_dtypes)
            env = _build_env(ctx, fn, helpers, seed=env)
            for node in walk_nodes(fn):
                if not (isinstance(node, ast.BinOp)
                        and isinstance(node.op, _ARITH_OPS)):
                    continue
                left = _array_value(ctx, node.left, env, helpers)
                right = _array_value(ctx, node.right, env, helpers)
                pair = {left, right}
                if pair & set(NARROW) and pair & set(WIDE):
                    out.append(self.finding(
                        ctx, node.lineno,
                        f"{fn.name}: elementwise op mixes "
                        f"{left or '?'} and {right or '?'} — the "
                        "result promotes wide BEFORE the accumulate "
                        "point, doubling hot-loop bytes"))
        return _dedupe(out)


NUMERICS_RULES = [AccumulationDiscipline(), ImplicitHotUpcast()]
