"""SPL007 good: every referenced SPLATT_* var is declared in ENV_VARS."""

from splatt_tpu.utils.env import read_env, read_env_float

_TTL_ENV = "SPLATT_PROBE_CACHE_TTL_S"

A = read_env("SPLATT_ENGINE_FALLBACK")
B = read_env_float(_TTL_ENV)
