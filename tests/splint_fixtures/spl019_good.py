"""SPL019 good: the full atomic-publish protocol in order inside the
sanctioned helper, and only pure renames (no self-written source)
outside it."""

import os


def _fsync_dir(path):
    # configured durable-write helper: the rename-durability barrier
    fd = os.open(os.path.dirname(os.path.abspath(path)) or ".",
                 os.O_RDONLY)
    try:
        os.fsync(fd)
    finally:
        os.close(fd)


def publish_bytes(path, data):
    # tmp write -> content fsync -> atomic rename -> parent-dir fsync:
    # every step present, in order, on the normal path only
    tmp = f"{path}.~{os.getpid()}.tmp"
    with open(tmp, "wb") as f:
        f.write(data)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)
    _fsync_dir(path)


def rotate(path):
    # renaming an EXISTING file this function never wrote is not a
    # publish — rotation/claim verbs stay clean
    os.replace(path, path + ".1")
