"""splatt-tpu: a TPU-native sparse tensor factorization framework.

A from-scratch JAX/XLA/Pallas re-design of the capabilities of SPLATT
("The Surprisingly ParalleL spArse Tensor Toolkit", reference C library):
Canonical Polyadic Decomposition (CPD) of large sparse tensors via
Alternating Least Squares, built around the MTTKRP kernel.

Where the reference uses CSF trees + OpenMP locks + MPI messages, this
framework uses a blocked/padded sparse format, MXU-friendly one-hot
segment reductions (Pallas), and `jax.sharding` meshes with XLA
collectives.

Public API surface (mirrors the reference's ``include/splatt.h``):

- :class:`SparseTensor`        (≙ ``sptensor_t``, COO)
- :class:`BlockedSparse`       (≙ ``splatt_csf``, the compiled device format)
- :class:`KruskalTensor`       (≙ ``splatt_kruskal``)
- :func:`load` / :func:`save`  (≙ ``splatt_load`` / tensor writers)
- :func:`mttkrp`               (≙ ``splatt_mttkrp``)
- :func:`cpd_als`              (≙ ``splatt_cpd_als``)
- :func:`default_opts`         (≙ ``splatt_default_opts``)
"""

from splatt_tpu.config import (
    MAX_NMODES,
    BlockAlloc,
    CommPattern,
    Decomposition,
    ModeOrder,
    Options,
    Verbosity,
    default_opts,
)
from splatt_tpu.coo import SparseTensor
from splatt_tpu.io import load, save
from splatt_tpu.blocked import BlockedSparse, ModeLayout
from splatt_tpu.kruskal import KruskalTensor
from splatt_tpu.ops.mttkrp import mttkrp, mttkrp_stream, mttkrp_blocked
from splatt_tpu.cpd import cpd_als
from splatt_tpu.version import __version__, version_major, version_minor

__all__ = [
    "MAX_NMODES",
    "BlockAlloc",
    "CommPattern",
    "Decomposition",
    "ModeOrder",
    "Options",
    "Verbosity",
    "default_opts",
    "SparseTensor",
    "BlockedSparse",
    "ModeLayout",
    "KruskalTensor",
    "load",
    "save",
    "mttkrp",
    "mttkrp_stream",
    "mttkrp_blocked",
    "cpd_als",
    "__version__",
    "version_major",
    "version_minor",
]
