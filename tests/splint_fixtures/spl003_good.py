"""SPL003 good: device-side work stays device-side; syncs live at the
un-traced sweep boundary."""

import jax
import jax.numpy as jnp


@jax.jit
def pure_device(x):
    return jnp.asarray(x) * 2.0  # jnp.asarray is device-side, not a sync


def driver(x):
    out = pure_device(x)
    jax.block_until_ready(out)  # outside any traced function: fine
    return float(out[0])
