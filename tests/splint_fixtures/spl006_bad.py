"""SPL006 bad: a fault site the SITES registry never declared."""

from splatt_tpu.utils import faults


def risky_write():
    faults.maybe_fail("spl006_fixture_undeclared_site")


def risky_dispatch(engine):
    # dynamic family with an undeclared prefix
    faults.maybe_fail(f"spl006_fixture_family.{engine}")
