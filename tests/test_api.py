"""API conventions (≙ tests/api_test.c:38-92: default opts, version)."""

import numpy as np

import splatt_tpu
from splatt_tpu.config import (BlockAlloc, CommPattern, Decomposition,
                               Verbosity, default_opts)
from splatt_tpu.utils.timers import TimerRegistry


def test_default_opts_match_reference_defaults():
    """≙ splatt_default_opts (src/opts.c:10-47)."""
    o = default_opts()
    assert o.tolerance == 1e-5
    assert o.max_iterations == 50
    assert o.regularization == 0.0
    assert o.block_alloc is BlockAlloc.TWOMODE
    assert o.priv_threshold == 0.02
    assert o.decomposition is Decomposition.MEDIUM
    # None = env default: ALL2ALL unless SPLATT_COMM overrides
    # (docs/ring.md)
    assert o.comm_pattern is None
    from splatt_tpu.config import resolve_comm_pattern

    assert resolve_comm_pattern(o) is CommPattern.ALL2ALL
    assert o.random_seed is None  # seed-from-time until resolved


def test_seed_pinned_once():
    o = default_opts()
    s1 = o.seed()
    s2 = o.seed()
    assert s1 == s2
    assert o.random_seed == s1


def test_version():
    assert splatt_tpu.version_major == 0
    assert splatt_tpu.__version__.count(".") == 2


def test_public_surface():
    for name in splatt_tpu.__all__:
        assert hasattr(splatt_tpu, name), name


def test_timer_registry():
    reg = TimerRegistry()
    with reg.time("mttkrp"):
        pass
    reg.start("cpd")
    reg.stop("cpd")
    assert reg["mttkrp"] >= 0.0
    report = reg.report(level=2)
    assert "mttkrp" in report or reg["mttkrp"] == 0.0
    reg.reset()
    assert reg["cpd"] == 0.0


def test_max_nmodes_guard():
    import pytest

    with pytest.raises(ValueError):
        splatt_tpu.SparseTensor(np.zeros((9, 1), dtype=np.int64),
                                np.ones(1), tuple([2] * 9))

def test_options_validate():
    import pytest

    from splatt_tpu.config import Options

    Options().validate()
    with pytest.raises(ValueError):
        Options(tolerance=-1.0).validate()
    with pytest.raises(ValueError):
        Options(max_iterations=-1).validate()
    with pytest.raises(ValueError):
        Options(nnz_block=0).validate()
