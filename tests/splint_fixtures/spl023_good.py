"""SPL023 good: durable appends routed through the sanctioned helper
(which owns the fsync), scratch writes left alone."""

import os
import tempfile

from splatt_tpu.utils.durable import append_line


def append_journal(root, line):
    # the sanctioned durable-append chokepoint fsyncs for us
    journal_path = os.path.join(root, "journal.jsonl")
    append_line(journal_path, (line + "\n").encode())


def write_scratch(payload):
    # not under any durable root: scratch files need no barrier
    fd, scratch = tempfile.mkstemp(suffix=".scratch")
    with os.fdopen(fd, "w") as f:
        f.write(payload)
    return scratch
