"""Ring-variant memory proof (VERDICT round-1 item 10).

The POINT2POINT/ring sweep's claim is O(dim/ndev) peak factor memory
vs the all2all variant's O(dim) gathered buffers (≙ the reference's
Isend/Irecv variant, src/mpi/mpi_cpd.c:323-546).  XLA's compiled
memory analysis measures exactly the live-buffer peak per device, so
the claim is asserted against the compiler, not a hand model.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from splatt_tpu.config import default_opts
from splatt_tpu.coo import SparseTensor
from splatt_tpu.cpd import init_factors
from splatt_tpu.parallel.mesh import make_mesh
from splatt_tpu.parallel.sharded import (make_sharded_sweep, shard_factors,
                                         shard_nnz)
from splatt_tpu.utils.env import ceil_to


def _lower_sweep(variant, tt, rank, mesh, axis="nnz"):
    ndev = mesh.shape[axis]
    dims_pad = tuple(ceil_to(d, ndev) for d in tt.dims)
    inds, vals = shard_nnz(tt, mesh, axis=axis, val_dtype=np.float32)
    factors = tuple(shard_factors(
        [jnp.asarray(f, jnp.float32)
         for f in init_factors(tt.dims, rank, 3)], tt.dims, mesh, axis=axis))
    from splatt_tpu.ops.linalg import gram

    gram_sharding = NamedSharding(mesh, P(None, None))
    grams = tuple(jax.device_put(gram(U), gram_sharding) for U in factors)
    sweep = make_sharded_sweep(mesh, tt.nmodes, 0.0, dims_pad, axis=axis,
                               variant=variant)
    flag = jnp.asarray(0.0, jnp.float32)
    return sweep.lower(inds, vals, factors, grams, flag, ()).compile()


def test_ring_peak_memory_fraction_of_all2all():
    """On a long-mode tensor the ring sweep's temp memory must be a
    small fraction of the all2all sweep's — the all_gather materializes
    (dim_pad, R) per input factor while the ring holds one (dim/ndev, R)
    block (measured peak factor-buffer ratio ≈ 1/ndev)."""
    rng = np.random.default_rng(0)
    dims = (16384, 64, 48)   # one long mode dominates the buffers
    nnz = 6000
    rank = 32
    inds = np.stack([rng.integers(0, d, nnz) for d in dims]).astype(np.int64)
    tt = SparseTensor(inds=inds, vals=rng.random(nnz), dims=dims)
    mesh = make_mesh(axis_names=("nnz",))
    ndev = mesh.shape["nnz"]
    if ndev < 4:
        pytest.skip("needs a multi-device mesh")

    a2a = _lower_sweep("all2all", tt, rank, mesh)
    ring = _lower_sweep("ring", tt, rank, mesh)
    m_a2a = a2a.memory_analysis()
    m_ring = ring.memory_analysis()
    assert m_a2a is not None and m_ring is not None

    # the gathered long-mode factor alone: dims_pad[0] * R * 4 bytes
    gathered = ceil_to(dims[0], ndev) * rank * 4
    assert m_a2a.temp_size_in_bytes >= gathered  # all2all materializes it
    # ring never holds a full gathered factor; give generous headroom
    # for unrelated temporaries while still proving the O(dim/ndev) claim
    assert m_ring.temp_size_in_bytes < m_a2a.temp_size_in_bytes / 2
    assert m_ring.temp_size_in_bytes < gathered // 2

    # per-step ring buffers are block-sized: (dim_pad/ndev) * R * 4 each;
    # a handful of them (gather block, reduce block, psum buffer) must
    # fit in the measured temp
    block_bytes = (ceil_to(dims[0], ndev) // ndev) * rank * 4
    assert m_ring.temp_size_in_bytes < 64 * block_bytes


def test_ring_and_all2all_same_math():
    rng = np.random.default_rng(1)
    dims = (256, 40, 56)
    nnz = 2000
    inds = np.stack([rng.integers(0, d, nnz) for d in dims]).astype(np.int64)
    tt = SparseTensor(inds=inds, vals=rng.random(nnz), dims=dims)
    from splatt_tpu.config import CommPattern
    from splatt_tpu.parallel.sharded import sharded_cpd_als

    opts = default_opts()
    opts.random_seed = 4
    opts.max_iterations = 3
    a = sharded_cpd_als(tt, rank=3, opts=opts)
    opts2 = default_opts()
    opts2.random_seed = 4
    opts2.max_iterations = 3
    opts2.comm_pattern = CommPattern.POINT2POINT
    b = sharded_cpd_als(tt, rank=3, opts=opts2)
    assert abs(float(a.fit) - float(b.fit)) < 1e-5
    for x, y in zip(a.factors, b.factors):
        np.testing.assert_allclose(np.asarray(x), np.asarray(y),
                                   rtol=2e-4, atol=2e-5)
