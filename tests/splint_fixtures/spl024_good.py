"""SPL024 good: every reduce carries the accumulation-dtype
discipline — pinned dots, acc-helper upcasts at the segment reduce,
explicit dtype= on sums, and exact integer counting."""

import jax
import jax.numpy as jnp

from splatt_tpu.config import acc_dtype


def good_pinned_gram(U):
    return jnp.matmul(U.T, U,
                      preferred_element_type=acc_dtype(U.dtype))


def good_upcast_segment_reduce(prod, inds, dim):
    return jax.ops.segment_sum(prod.astype(acc_dtype(prod.dtype)),
                               inds, num_segments=dim)


def good_sum_with_acc(had):
    acc = acc_dtype(had.dtype)
    return jnp.sum(had, dtype=acc)


def good_exact_count(mask):
    # integer/bool reductions accumulate exactly — no pin needed
    return mask.astype(jnp.int32).sum()
