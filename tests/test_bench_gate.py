"""bench.py regression gate (ROADMAP open item 1, docs/serve.md era).

The gate compares a fresh bench record against the newest prior
``BENCH_*.json`` ON THE SAME METRIC, flags >10% slowdowns as
``bench_regression`` run-report events (via the shared resilience
helper), carries them in the JSON artifact, and — under ``--gate`` —
exits nonzero so a perf PR ships with a verdict, not just a number.
"""

import json
import os
import subprocess
import sys

import pytest

import bench
from splatt_tpu import resilience

REC = {"metric": "M1", "value": 2.0, "unit": "sec/iter",
       "timing_stats": {"blocked": {"median": 2.0},
                        "stream": {"median": 10.0}}}
PRIOR = {"metric": "M1", "value": 1.5, "unit": "sec/iter",
         "timing_stats": {"blocked": {"median": 1.5},
                          "stream": {"median": 11.0}}}


def test_regressions_flag_headline_and_per_path():
    regs = bench._bench_regressions(REC, PRIOR)
    assert {r["path"] for r in regs} == {"headline", "blocked"}
    head = next(r for r in regs if r["path"] == "headline")
    assert head["sec"] == 2.0 and head["prior_sec"] == 1.5
    assert head["pct"] == pytest.approx(33.3)
    # stream got FASTER: not flagged


def test_within_threshold_is_clean():
    ok = dict(REC, value=1.64, timing_stats={})  # +9.3% < 10%
    assert bench._bench_regressions(ok, PRIOR) == []


def test_unlike_metrics_are_never_compared():
    other = dict(PRIOR, metric="a different workload")
    assert bench._bench_regressions(REC, other) == []


def test_prior_discovery_newest_usable_wins(tmp_path):
    def write(name, value, wrap=True):
        rec = {"metric": "M1", "value": value, "unit": "sec/iter"}
        payload = {"parsed": rec} if wrap else rec
        (tmp_path / name).write_text(json.dumps(payload))

    write("BENCH_r01.json", 1.0)
    write("BENCH_r02.json", 1.5)
    (tmp_path / "BENCH_r03.json").write_text("not json at all")
    name, rec = bench._prior_bench_record(str(tmp_path))
    assert name == "BENCH_r02.json" and rec["value"] == 1.5
    # a bare (unwrapped) record is also a valid prior
    write("BENCH_r04.json", 1.7, wrap=False)
    name, rec = bench._prior_bench_record(str(tmp_path))
    assert name == "BENCH_r04.json" and rec["value"] == 1.7


def test_prior_discovery_empty_dir(tmp_path):
    assert bench._prior_bench_record(str(tmp_path)) is None


def test_record_bench_regression_event():
    resilience.run_report().clear()
    ev = resilience.record_bench_regression("blocked", 2.0, 1.5, 33.3,
                                            "BENCH_r05.json")
    assert ev["kind"] == "bench_regression" and ev["pct"] == 33.3
    lines = resilience.run_report().summary()
    assert any("BENCH REGRESSION" in ln for ln in lines)
    resilience.run_report().clear()


def test_repo_priors_are_discoverable():
    """The real repo artifacts parse: the gate has a baseline today."""
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    found = bench._prior_bench_record(repo)
    assert found is not None
    name, rec = found
    assert name.startswith("BENCH_") and rec["value"] > 0


def test_gate_end_to_end_nonzero_exit(tmp_path):
    """--gate e2e: a tiny bench run against a fabricated prior with an
    impossibly fast value exits nonzero, records bench_regression in
    the JSON artifact, and still prints the headline number (the
    verdict never eats the measurement)."""
    nnz, rank = 60000, 4
    metric = (f"CPD-ALS sec/iteration, synthetic NELL-2-shaped "
              f"(3-mode, {nnz} nnz, rank {rank}, float32) on cpu; "
              f"baseline: reference 1-thread CPU same tensor")
    (tmp_path / "BENCH_r98.json").write_text(json.dumps(
        {"parsed": {"metric": metric, "value": 0.0001,
                    "unit": "sec/iter"}}))
    env = dict(os.environ)
    env.update(SPLATT_BENCH_NNZ=str(nnz), SPLATT_BENCH_RANK=str(rank),
               SPLATT_BENCH_ITERS="1", SPLATT_BENCH_PATHS="blocked",
               SPLATT_BENCH_PRIOR_DIR=str(tmp_path),
               SPLATT_TUNE_CACHE=str(tmp_path / "tc.json"),
               JAX_PLATFORMS="cpu")
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    p = subprocess.run([sys.executable, os.path.join(repo, "bench.py"),
                        "--gate"], env=env, capture_output=True,
                       text=True, timeout=600, cwd=repo)
    assert p.returncode == 1, (p.returncode, p.stderr[-800:])
    line = [ln for ln in p.stdout.splitlines() if ln.startswith("{")]
    assert line, p.stderr[-800:]
    rec = json.loads(line[-1])
    assert rec["value"] > 0                       # headline survived
    regs = rec["bench_regressions"]
    assert rec["bench_prior"] == "BENCH_r98.json"
    assert any(r["path"] == "headline" for r in regs)
    assert "REGRESSION" in p.stderr


def test_unknown_argv_rejected():
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    p = subprocess.run([sys.executable, os.path.join(repo, "bench.py"),
                        "--bogus"], capture_output=True, text=True,
                       timeout=120)
    assert p.returncode == 2 and "unknown arguments" in p.stderr


def test_prior_discovery_skips_unlike_metrics_to_older_prior(tmp_path):
    """A different workload benched in between must not disable the
    gate: the search keeps walking to the newest SAME-metric prior."""
    (tmp_path / "BENCH_r01.json").write_text(json.dumps(
        {"parsed": {"metric": "M1", "value": 1.5, "unit": "sec/iter"}}))
    (tmp_path / "BENCH_r02.json").write_text(json.dumps(
        {"parsed": {"metric": "OTHER", "value": 9.0,
                    "unit": "sec/iter"}}))
    name, rec = bench._prior_bench_record(str(tmp_path), metric="M1")
    assert name == "BENCH_r01.json" and rec["value"] == 1.5
    # and with no metric constraint the newest usable one still wins
    name, _ = bench._prior_bench_record(str(tmp_path))
    assert name == "BENCH_r02.json"
    # no same-metric prior at all -> no baseline
    assert bench._prior_bench_record(str(tmp_path),
                                     metric="UNSEEN") is None
