"""`splatt serve` — an isolated, crash-resumable multi-tenant
decomposition daemon (ROADMAP open item 4; docs/serve.md).

The million-user scenario is many concurrent jobs, not one big run.
This module turns the single-run reliability spine (failure taxonomy,
engine demotion, health sentinel + rollback, deadline watchdog) into a
SERVICE without letting one tenant's failures poison its neighbors:

Durable job queue
    Every accepted job is journaled to an append-only JSONL file
    (:class:`Journal`) before the submitter hears "accepted" — one
    fsynced line per state transition (``accepted`` → ``started`` →
    ``done``/``failed``, plus ``resumed``/``interrupted``/``rejected``).
    A crashed or preempted daemon replays the journal on start: every
    accepted-but-non-terminal job is re-enqueued (a ``job_resumed``
    event) and resumes from its last hardened checkpoint — the
    checksummed, ``.bak``-generationed checkpoints of cpd.py, one per
    job under ``<root>/ckpt/``.  A torn final line (SIGKILL mid-append)
    is skipped, never fatal.

Per-job isolation
    Each job runs under :func:`splatt_tpu.resilience.scope`: its engine
    demotions, health verdicts, retry budget, watchdog deadline and
    run-report events are attributed to the job and invisible to every
    neighbor — one tenant's NUMERICAL rollback or OOM demotion must not
    steer another tenant's dispatch (≙ the reference's per-run
    ``splatt_opts``/workspace separation).  A job spec may declare its
    own fault schedule (``"faults"``, SPLATT_FAULTS grammar), armed via
    :func:`splatt_tpu.utils.faults.scoped` inside that job only.  The
    probe/tune/compile caches stay SHARED and warm — the Nth request in
    a known shape regime pays zero compile — behind the locked cache
    protocol (ops/pallas_kernels.py).

Overload handling
    The pending queue is bounded (``SPLATT_SERVE_QUEUE_MAX``); a
    submission past the bound is load-shed with an explicit rejection
    (``queue_full`` event + a ``rejected`` result) instead of queueing
    unboundedly.  Per-job deadlines ride the PR 5 watchdog
    (``SPLATT_SERVE_JOB_DEADLINE_S`` / spec ``deadline_s``).  SIGTERM
    drains gracefully: running jobs checkpoint through the cpd ``stop``
    hook and are journaled ``interrupted`` (→ resumed next start),
    queued jobs simply stay journaled.

Job API (machine-readable)
    Filed requests: clients drop ``<id>.json`` job specs into
    ``<root>/requests/`` (:func:`file_request` writes them atomically);
    the daemon ingests, journals and deletes them.  Results appear as
    ``<root>/results/<id>.json`` carrying the same machine-readable
    schema as ``splatt cpd --json`` (fit, events, demotions) plus the
    job's status.  :func:`read_status` / :func:`read_result` are the
    client-side readers.  The :class:`Server` methods are the same API
    in-process.

Fleet mode (docs/fleet.md)
    ``Server(root, fleet=True)`` / ``splatt serve --fleet`` runs this
    daemon as one of N replicas over the SAME root: the journal
    becomes a flock-serialized shared log every replica tails, job
    ownership becomes a lease (``splatt_tpu/fleet.py`` — claimed at
    dispatch, renewed by a heartbeat thread, adopted by a live peer
    once a dead replica's lease expires), scheduling becomes
    cache-affinity routing (jobs prefer the replica whose warm
    probe/tune/compile caches match their shape regime; load is the
    tiebreaker, never the signal), and admission control grows
    per-tenant quotas (``SPLATT_FLEET_TENANT_QUOTA``) and priority
    classes (``priority: high|normal|low``) over the queue_full
    shedding.  `splatt chaos --fleet` is the soak proving the fleet
    invariant: SIGKILL-and-restart across replicas under multi-tenant
    load loses no accepted job, never runs a job on two replicas at
    once, and keeps the Nth-request-is-free property through
    adoption.

Batched + incremental serving (docs/batched.md)
    A replica whose queue holds >= ``SPLATT_SERVE_BATCH_MIN``
    batchable jobs sharing one regime key dispatches them as ONE
    vmapped :func:`splatt_tpu.cpd.cpd_als_batched` batch — K tenants
    share a single compile while per-job journal lineage, results,
    quotas and health verdicts stay per-member; any batch-path
    failure degrades CLASSIFIED to per-tensor dispatch.  An
    ``update`` job appends a delta COO to an existing checkpointed
    model and runs a few warm-started sweeps (delta-touched rows
    re-solved first, sentinel-gated, full refits as the repair path)
    — the journal/checkpoint store acting as a model store.

A job spec is a JSON object::

    {"id": "j1", "rank": 8, "iters": 25, "seed": 0,
     "synthetic": {"dims": [40, 32, 24], "nnz": 3000, "seed": 0},
     # or "tensor": "/path/to/tensor.tns",
     "tol": 1e-5, "checkpoint_every": 5, "tune": false,
     "autotune": null, "health_retries": null, "deadline_s": null,
     "faults": "", "tenant": "default", "priority": "normal"}

    # incremental model update (docs/batched.md):
    {"id": "up1", "kind": "update", "base": "j1",
     "delta": {"dims": [40, 32, 24], "nnz": 100, "seed": 42},
     # or "delta_tensor": "/path/to/delta.tns",
     "iters": 5}
"""

from __future__ import annotations

import json
import os
import re
import signal
import threading
import time
import uuid
from typing import Callable, Dict, List, Optional

# journal record kinds (the `rec` field of each JSONL line)
#: in-memory-only reservation state while the accept append fsyncs
#: (never journaled; a concurrent same-id submission dedups on it)
ACCEPTING = "accepting"
ACCEPTED = "accepted"
STARTED = "started"
RESUMED = "resumed"
ADOPTED = "adopted"    # fleet: a live replica took over a dead peer's job
INTERRUPTED = "interrupted"
DONE = "done"          # terminal: converged or degraded (see status)
FAILED = "failed"      # terminal: a classified error
REJECTED = "rejected"  # terminal: load-shed or invalid

#: records after which a job needs no further work
TERMINAL = (DONE, FAILED, REJECTED)

#: every record kind this version journals — the replay forward-compat
#: vocabulary.  A replayed record whose kind is NOT here (a newer
#: version's journal, or hand-edited debris) is skipped with a
#: classified ``journal_unknown_kind`` event instead of corrupting the
#: job table.  splint SPL022 checks this registry against every
#: ``_rec`` emission and every test, in both directions.
KNOWN_KINDS = (ACCEPTED, STARTED, RESUMED, ADOPTED, INTERRUPTED,
               DONE, FAILED, REJECTED)

#: admission priority classes, class -> rank (lower runs first); the
#: scheduler orders by (priority rank, arrival) so within a class the
#: queue stays FIFO (docs/fleet.md)
PRIORITIES = {"high": 0, "normal": 1, "low": 2}

_ID_RE = re.compile(r"^[A-Za-z0-9][A-Za-z0-9._-]{0,63}$")

#: how many scheduler passes a job warm on a PEER replica may be
#: deferred to that peer before this replica takes it anyway —
#: affinity is a routing preference, never a starvation mechanism
AFFINITY_DEFER_MAX = 3

#: hard cap on one coalesced batch (docs/batched.md): K slots stack
#: K× the bucket-padded tensor in device memory, so a flooded queue
#: must coalesce in bounded bites, not one unbounded vmap
BATCH_MAX = 32

#: job kinds a spec may declare (docs/batched.md): "cpd" decomposes a
#: workload from scratch (the default), "update" appends a delta COO
#: to an existing checkpointed model and runs a few warm-started
#: sweeps — the journal/checkpoint store acting as a model store —
#: and "predict" reads a committed model generation (docs/predict.md)
#: on a dedicated low-latency lane: leased/journaled like every job,
#: but never coalesced, never affinity-deferred; "ingest" streams a
#: raw record file into a COO tensor under the exactly-once chunk
#: journal (docs/ingest.md) and — when the spec names a `base` model —
#: emits one `update` job per watermark interval, the live-feed shape
JOB_KINDS = ("cpd", "update", "predict", "ingest")


def _job_id(spec: dict) -> str:
    """The job's id: the spec's, else a fresh one.  Ids name journal
    records, checkpoint files and result files, so they are restricted
    to a filesystem-safe alphabet."""
    jid = str(spec.get("id") or uuid.uuid4().hex[:12])
    if not _ID_RE.match(jid):
        raise ValueError(
            f"job id {jid!r} is not filesystem-safe (want "
            f"[A-Za-z0-9][A-Za-z0-9._-]*, max 64 chars)")
    return jid


class Journal:
    """Append-only JSONL job journal with durable, atomic appends.

    One write of a full line + fsync per record, serialized across
    threads AND processes (an advisory ``flock`` beside the in-process
    lock — a fleet of replicas shares one journal, docs/fleet.md).  A
    SIGKILL can tear a line anywhere a writer died: :meth:`replay`
    skips every unparseable record with a classified ``journal_torn``
    event (the record it carried is re-derived — an un-journaled
    terminal record just means the job re-runs, and resume makes that
    cheap), and :meth:`append` heals a torn TAIL (no trailing newline)
    before writing, so crash debris can never merge into — and
    swallow — the next record.  The flock + heal + fsync discipline
    itself lives in :func:`splatt_tpu.utils.durable.append_line`, the
    sanctioned durable-append helper (splint rule SPL016) shared with
    every other durable writer in the tree."""

    def __init__(self, path: str):
        self.path = str(path)
        self._lock = threading.Lock()

    def append(self, rec: dict) -> None:
        """Durably append one record (raises on IO failure — callers
        decide whether durability is load-bearing for this record)."""
        from splatt_tpu.utils import faults
        from splatt_tpu.utils.durable import append_line

        faults.maybe_fail("serve.journal_write")
        line = json.dumps(dict(rec, ts=time.time()), sort_keys=True)
        with self._lock:
            append_line(self.path, line.encode())

    def replay(self):
        """Parse every complete record → (records, torn_line_count).
        A torn/garbled line — final OR mid-file, which concurrent
        fleet appends can leave when a writer dies mid-write — is
        skipped with a classified ``journal_torn`` event, never fatal:
        replay must not die on its own crash debris, and one replica's
        debris must never poison a peer's replay."""
        recs, torn, _ = self._parse(self._read(0),
                                    partial_tail_is_torn=True)
        return recs, torn

    def replay_new(self, offset: int):
        """Incremental tail read for live fleet sync: parse complete
        records from byte `offset` on → (records, torn, new_offset).
        A final line with no newline yet is a peer's IN-PROGRESS
        append, not debris: it is left unconsumed (the returned offset
        stays before it) and re-read complete on the next call."""
        recs, torn, consumed = self._parse(self._read(offset),
                                           partial_tail_is_torn=False)
        return recs, torn, offset + consumed

    def _read(self, offset: int) -> bytes:
        try:
            with open(self.path, "rb") as f:
                if offset:
                    f.seek(offset)
                return f.read()
        except FileNotFoundError:
            return b""  # fresh serve root: nothing journaled yet

    def _parse(self, data: bytes, partial_tail_is_torn: bool):
        recs: List[dict] = []
        torn = 0
        consumed = 0
        for raw in data.split(b"\n"):
            complete = consumed + len(raw) < len(data)  # has its \n
            if not complete and not partial_tail_is_torn:
                break  # in-progress append: not ours to judge yet
            consumed += len(raw) + (1 if complete else 0)
            if not raw.strip():
                continue
            try:
                rec = json.loads(raw.decode(errors="replace"))
                if not isinstance(rec, dict):
                    raise ValueError("journal record is not an object")
            except ValueError as e:
                torn += 1
                self._report_torn(raw, e)
                continue
            recs.append(rec)
        return recs, torn, consumed

    def _report_torn(self, raw: bytes, exc: Exception) -> None:
        """One skipped record → a classified ``journal_torn`` event:
        tolerated crash debris is still OBSERVABLE crash debris."""
        from splatt_tpu import resilience

        resilience.run_report().add(
            "journal_torn", path=self.path,
            failure_class=resilience.classify_failure(exc).value,
            error=resilience.failure_message(exc)[:120],
            preview=raw[:60].decode(errors="replace"))


class Server:
    """The serve daemon: a bounded, journal-backed job queue and a
    small supervisor pool running each CPD under the guarded drivers
    with per-job resilience scoping (module docstring; docs/serve.md).
    """

    def __init__(self, root: str, workers: Optional[int] = None,
                 queue_max: Optional[int] = None,
                 poll_s: Optional[float] = None,
                 job_deadline_s: Optional[float] = None,
                 verbose: bool = False,
                 fleet: bool = False, replica: Optional[str] = None,
                 lease_s: Optional[float] = None,
                 heartbeat_s: Optional[float] = None,
                 tenant_quota: Optional[int] = None,
                 affinity: Optional[bool] = None,
                 batch_min: Optional[int] = None):
        from splatt_tpu.utils.env import read_env_float, read_env_int

        self.root = os.path.abspath(root)
        self.requests_dir = os.path.join(self.root, "requests")
        self.results_dir = os.path.join(self.root, "results")
        self.ckpt_dir = os.path.join(self.root, "ckpt")
        for d in (self.root, self.requests_dir, self.results_dir,
                  self.ckpt_dir):
            os.makedirs(d, exist_ok=True)
        self.journal = Journal(os.path.join(self.root, "journal.jsonl"))
        self.workers = int(workers if workers is not None
                           else read_env_int("SPLATT_SERVE_WORKERS"))
        self.queue_max = int(queue_max if queue_max is not None
                             else read_env_int("SPLATT_SERVE_QUEUE_MAX"))
        self.poll_s = float(poll_s if poll_s is not None
                            else read_env_float("SPLATT_SERVE_POLL_S"))
        self.job_deadline_s = float(
            job_deadline_s if job_deadline_s is not None
            else read_env_float("SPLATT_SERVE_JOB_DEADLINE_S"))
        # auto coalescing (docs/batched.md): when the queue holds >=
        # batch_min batchable jobs sharing one regime key, a worker
        # dispatches them as ONE vmapped batch; 0 disables
        self.batch_min = int(batch_min if batch_min is not None
                             else read_env_int("SPLATT_SERVE_BATCH_MIN"))
        # metrics cadence (docs/observability.md): with a path set, the
        # registry is snapshotted in Prometheus text format every
        # interval seconds and at daemon exit; interval <= 0 snapshots
        # at exit only
        from splatt_tpu.utils.env import read_env

        self.metrics_path = read_env("SPLATT_METRICS_PATH") or None
        self.metrics_interval_s = float(
            read_env_float("SPLATT_METRICS_INTERVAL_S"))
        self._metrics_last = 0.0
        self.verbose = verbose
        # admission control (docs/fleet.md): per-tenant cap on
        # non-terminal jobs (0 = unlimited), layered over queue_full
        self.tenant_quota = int(
            tenant_quota if tenant_quota is not None
            else read_env_int("SPLATT_FLEET_TENANT_QUOTA"))
        # the declared shared structures below mirror [tool.splint]
        # shared-state; under SPLATT_LOCKCHECK they become
        # owner-assertion proxies (utils/lockcheck.py — the dynamic
        # cross-check of splint rule SPL014), otherwise they pass
        # through untouched
        from splatt_tpu.utils import lockcheck

        self._lock = lockcheck.guard_lock(threading.Lock())
        #: id -> {"spec": dict|None, "state": str, "status": str|None,
        #:        "resumed": bool, "tenant": str, "priority": str,
        #:        "seq": int, "owner": str|None (fleet: last journaled
        #:        replica), "adopt_from": str|None, "deferred": int}
        self._jobs: Dict[str, dict] = lockcheck.guard(
            {}, self._lock, "serve.Server._jobs")
        #: pending job ids; _next() picks by (priority, arrival seq)
        self._queue: List[str] = lockcheck.guard(
            [], self._lock, "serve.Server._queue")
        #: pending predict ids — the dedicated low-latency lane
        #: (docs/predict.md): FIFO, bounded separately, dispatched
        #: before any fit/update, never coalesced or deferred
        self._pqueue: List[str] = lockcheck.guard(
            [], self._lock, "serve.Server._pqueue")
        self.predict_queue_max = int(
            read_env_int("SPLATT_PREDICT_QUEUE_MAX"))
        # in-replica hot factors keyed by (model, generation): an
        # update commit invalidates by generation ADVANCE, never
        # deletion, so a pinned in-flight predict finishes bit-exactly
        from splatt_tpu.predict import HotFactorCache

        self._hot_cache = HotFactorCache(
            int(read_env_int("SPLATT_PREDICT_CACHE_MAX")))
        self._seq = 0
        #: job ids currently claimed/running on THIS replica's workers
        self._running: set = lockcheck.guard(
            set(), self._lock, "serve.Server._running")
        self._draining = threading.Event()
        # fleet membership (docs/fleet.md): job ownership is a lease,
        # routing prefers warm caches, dead peers' jobs are adopted
        self.fleet = None
        self._journal_offset = 0
        self._hb_thread: Optional[threading.Thread] = None
        if fleet:
            from splatt_tpu.fleet import FleetMember

            self.fleet = FleetMember(self.root, replica=replica,
                                     lease_s=lease_s,
                                     heartbeat_s=heartbeat_s)
            from splatt_tpu.utils.env import read_env

            self.affinity = bool(affinity if affinity is not None
                                 else str(read_env(
                                     "SPLATT_FLEET_AFFINITY")).lower()
                                 not in ("0", "off", "false", "no"))
            # fleet observability wiring (docs/observability.md):
            # default the snapshot into the shared spool — the fleet
            # aggregator scans heartbeats for it.  (The process-wide
            # replica stamp on spans/points is the CLI daemon entry's
            # to set — cli.cmd_serve — so library/test constructions
            # never flip global trace state behind the caller's back.)
            if not self.metrics_path:
                mdir = os.path.join(self.root, "fleet", "metrics")
                os.makedirs(mdir, exist_ok=True)
                self.metrics_path = os.path.join(
                    mdir, f"{self.fleet.replica}.prom")
            self.fleet.metrics_path = self.metrics_path
        else:
            self.affinity = False
        # the SLO layer rides the metrics cadence (the "aggregator's
        # cadence" of docs/observability.md): fleet replicas evaluate
        # over the merged fleet samples, a single daemon over its own
        from splatt_tpu.fleetobs import SloEvaluator

        self._slo = SloEvaluator(
            replica=self.fleet.replica if self.fleet else None)
        self._replay()
        if self.fleet is not None:
            self.fleet.beat()
            self._start_heartbeat()

    # -- crash recovery -----------------------------------------------------

    def _new_job_locked(self, spec: Optional[dict] = None,
                        state: Optional[str] = None) -> dict:
        """One fresh job-table entry.  The ``_locked`` suffix is the
        caller-owns-the-lock convention (docs/static-analysis.md,
        SPL014): every caller holds the server lock."""
        j = {"spec": spec, "state": state, "status": None,
             "resumed": False, "tenant": "default", "priority": "normal",
             "seq": self._seq, "owner": None, "adopt_from": None,
             "adopted_from": None, "deferred": 0, "regime": None,
             "t_accepted": None, "gen_pinned": None}
        self._seq += 1
        if spec is not None:
            self._fill_admission(j, spec)
        return j

    @staticmethod
    def _fill_admission(j: dict, spec: dict) -> None:
        """Derive the admission/routing fields from a job spec: the
        tenant (quota unit), priority class, and shape-regime key (the
        cache-affinity signal, docs/fleet.md)."""
        from splatt_tpu.fleet import job_regime

        j["tenant"] = str(spec.get("tenant") or "default")
        p = str(spec.get("priority") or "normal")
        j["priority"] = p if p in PRIORITIES else "normal"
        j["regime"] = job_regime(spec)

    def _apply_rec_locked(self, rec: dict) -> Optional[str]:
        """Fold one journal record into the job table (last record per
        job wins — the flock-serialized journal is totally ordered
        even across fleet replicas).  Callers hold the server lock
        (the ``_locked`` convention, SPL014).  Returns the job id."""
        jid = rec.get("job")
        kind = rec.get("rec")
        if not jid or not kind:
            return None
        if kind not in KNOWN_KINDS:
            # forward-compat: a newer version's record kind (or hand-
            # edited debris) is skipped classified, never folded — an
            # unknown kind blindly applied would wedge the job in a
            # state no scheduler transition leaves (SPL022)
            from splatt_tpu import resilience

            resilience.run_report().add(
                "journal_unknown_kind", path=self.journal.path,
                job=str(jid), record_kind=str(kind)[:60],
                failure_class="permanent",
                error="journal record kind unknown to this version; "
                      "skipped (newer writer?)")
            return None
        j = self._jobs.setdefault(jid, self._new_job_locked())
        if kind == ACCEPTED:
            if rec.get("spec") is not None:
                j["spec"] = rec.get("spec")
                self._fill_admission(j, j["spec"])
            j["state"] = ACCEPTED
            # the journaled accept time feeds the queue-wait histogram
            # for replayed/peer-accepted jobs too (docs/observability.md)
            j["t_accepted"] = rec.get("ts")
            if rec.get("gen_pinned") is not None:
                # the generation a predict pinned at admission — the
                # journal-auditable staleness floor (docs/predict.md);
                # folded here so a peer/adopter serves the same pin
                j["gen_pinned"] = int(rec["gen_pinned"])
        else:
            j["state"] = kind
            if kind in (DONE, FAILED):
                j["status"] = rec.get("status")
        if rec.get("replica"):
            j["owner"] = rec["replica"]
        return jid

    def _rec(self, kind: str, jid: str, **kw) -> dict:
        """One journal record, stamped with this replica's id in
        fleet mode (the soak's single-owner lineage audit and the
        adoption scan both key on it)."""
        rec = {"rec": kind, "job": jid, **kw}
        if self.fleet is not None:
            rec["replica"] = self.fleet.replica
        return rec

    def _replay(self) -> None:
        """Rebuild queue state from the journal: the last record per
        job wins; every accepted-but-non-terminal job is re-enqueued
        (``job_resumed``) and will resume from its checkpoint.  In
        fleet mode a job whose lease is validly held by a live peer is
        only TRACKED — the peer owns it; the adoption scan takes over
        if that peer dies (docs/fleet.md)."""
        from splatt_tpu import resilience

        if self.fleet is not None:
            recs, torn, self._journal_offset = self.journal.replay_new(0)
        else:
            recs, torn = self.journal.replay()
        if torn:
            self._log(f"journal: skipped {torn} torn line(s) "
                      f"(crash debris)")
        # the job-table/queue mutations run under the server lock even
        # though __init__ is still single-threaded (SPL014: the
        # shared-state invariant is uniform, with no "but this call
        # path is special" carve-outs); the journal appends — fsyncs —
        # run after the lock is released, like every other append site
        resumed: List[tuple] = []
        with self._lock:
            for rec in recs:
                self._apply_rec_locked(rec)
            for jid, j in self._jobs.items():
                if j["state"] in TERMINAL or j["spec"] is None:
                    continue
                if self.fleet is not None:
                    me = self.fleet.replica
                    lease = self.fleet.lease_of(jid)
                    if lease is not None and not lease.expired() \
                            and lease.replica != me:
                        continue  # a live peer's; watched by _fleet_scan
                    if lease is not None and lease.expired() \
                            and lease.replica != me:
                        j["adopt_from"] = lease.replica
                    elif lease is None \
                            and j.get("owner") not in (None, me) \
                            and not self.fleet.replica_alive(j["owner"]):
                        # accepted by a dead peer, never claimed: taking
                        # it over is an adoption, audited as one
                        j["adopt_from"] = j["owner"]
                j["resumed"] = True
                # lane routing inlined (not _enqueue_locked): the
                # mutation stays visible to SPL014's lock-set proof
                # over _replay, which a *_locked helper would exempt
                if str(j["spec"].get("kind") or "cpd") == "predict":
                    self._pqueue.append(jid)
                else:
                    self._queue.append(jid)
                resumed.append((jid, j["state"]))
            depth = len(self._queue)
            pdepth = len(self._pqueue)
        for jid, was in resumed:
            resilience.run_report().add("job_resumed", job=jid,
                                        from_state=was)
            self._log(f"job {jid}: resumed from journal (was {was})")
            try:
                self.journal.append(self._rec(RESUMED, jid))
            except Exception as e:
                # lineage entry only — the ACCEPTED record already
                # guarantees a later replay re-finds this job
                self._warn_journal("resume", jid, e)
        if depth:
            self._queue_metric(depth)
        if pdepth:
            self._pqueue_metric(pdepth)

    # -- submission / job API ----------------------------------------------

    def submit(self, spec: dict) -> dict:
        """Accept (journal durably + enqueue) or reject one job.

        Durability-first: the submitter hears "accepted" only after the
        journal append succeeded — a submission the journal cannot
        record is REJECTED, because a crash would silently forget it.
        Admission control layers on top: an unknown ``priority`` class
        is invalid, a tenant at its non-terminal-job quota
        (``SPLATT_FLEET_TENANT_QUOTA``) is shed with a
        ``quota_rejected`` event, and a full pending queue load-sheds
        with an explicit ``queue_full`` rejection.  Re-submitting a
        known id is idempotent (a crashed client retrying, or a spool
        file re-ingested after a crash)."""
        from splatt_tpu import resilience
        from splatt_tpu.utils import faults

        faults.maybe_fail("serve.submit")
        jid = _job_id(spec)
        spec = dict(spec, id=jid)
        # decide under the lock, do the durable IO OUTSIDE it: fsyncs
        # must not stall the daemon's control plane (status/summary/
        # worker dequeue all share this lock)
        reason = None
        with self._lock:
            known = self._jobs.get(jid)
            if known is not None and known["state"] != REJECTED:
                # idempotent re-submission of a live/terminal job; a
                # REJECTED id may be resubmitted — load shedding is an
                # invitation to retry, not a permanent verdict
                return {"job": jid, "state": known["state"],
                        "duplicate": True}
            tenant = str(spec.get("tenant") or "default")
            prio = spec.get("priority")
            kind = str(spec.get("kind") or "cpd")
            if kind not in JOB_KINDS:
                reason = (f"invalid: unknown kind {kind!r} (want one "
                          f"of {sorted(JOB_KINDS)})")
            elif kind == "update" and not spec.get("base"):
                reason = "invalid: update job needs 'base': <job id>"
            elif kind == "update" and not (spec.get("delta")
                                           or spec.get("delta_tensor")):
                reason = ("invalid: update job needs 'delta': "
                          "{dims, nnz, seed} or 'delta_tensor': <path>")
            elif kind == "cpd" and not (spec.get("synthetic")
                                        or spec.get("tensor")):
                reason = ("invalid: no workload (give 'synthetic' or "
                          "'tensor')")
            elif kind == "predict" and not spec.get("model"):
                reason = ("invalid: predict job needs 'model': "
                          "<job id of a committed model>")
            elif kind == "predict" and spec.get("coords") is None \
                    and not isinstance(spec.get("top_k"), dict):
                reason = ("invalid: predict job needs 'coords': "
                          "[[i0, i1, ...], ...] and/or 'top_k': "
                          "{fixed, mode, k}")
            elif kind == "ingest" and not spec.get("source"):
                reason = ("invalid: ingest job needs 'source': "
                          "<record stream path>")
            elif kind == "ingest" and spec.get("base") \
                    and not spec.get("dims"):
                reason = ("invalid: ingest job chaining updates "
                          "against 'base' needs 'dims' (the base "
                          "model's mode sizes — deltas must not grow "
                          "past the checkpointed factors)")
            elif prio is not None and str(prio) not in PRIORITIES:
                reason = (f"invalid: unknown priority {prio!r} (want "
                          f"one of {sorted(PRIORITIES)})")
            elif spec.get("faults"):
                # validate the declared chaos schedule at the door: a
                # typo rejects THIS submission with the parse error
                # instead of failing the job at run time
                try:
                    faults.parse_schedule(str(spec["faults"]))
                except (ValueError, TypeError) as e:
                    reason = f"invalid: bad faults schedule ({e})"
            if reason is None and self.tenant_quota > 0:
                live = sum(1 for j in self._jobs.values()
                           if j.get("tenant") == tenant
                           and j["state"] not in TERMINAL)
                if live >= self.tenant_quota:
                    # per-tenant isolation at the door: one tenant
                    # flooding the spool cannot crowd out the rest
                    resilience.run_report().add(
                        "quota_rejected", job=jid, tenant=tenant,
                        quota=self.tenant_quota, live=live)
                    reason = f"quota:{tenant}"
            if reason is None and kind == "predict":
                # the predict lane's own bound (docs/predict.md): a
                # flood of reads load-sheds explicitly without
                # starving — or being starved by — the fit queue
                if self.predict_queue_max > 0 \
                        and len(self._pqueue) >= self.predict_queue_max:
                    resilience.run_report().add(
                        "queue_full", job=jid, lane="predict",
                        queue_max=self.predict_queue_max)
                    reason = "queue_full"
            elif reason is None and self.queue_max > 0 \
                    and len(self._queue) >= self.queue_max:
                resilience.run_report().add("queue_full", job=jid,
                                            queue_max=self.queue_max)
                reason = "queue_full"
            if reason is None:
                # reserve the id so a concurrent same-id submission
                # dedups while we journal lock-free below
                self._jobs[jid] = self._new_job_locked(spec, ACCEPTING)
        if reason is not None:
            return self._reject(jid, spec, reason)
        # pin the staleness floor at admission (docs/predict.md): the
        # newest COMMITTED generation right now — stamped into the
        # durable ACCEPTED record so the invariant "served gen >= the
        # newest generation committed before acceptance" is auditable
        # from the journal alone, on any replica.  File IO, so outside
        # the lock like every other submit-path read.
        gen_pinned = None
        if kind == "predict":
            from splatt_tpu.predict import current_generation

            gen_pinned = int(current_generation(
                self.ckpt_dir, str(spec.get("model"))))
        # durability-first: the submitter hears "accepted" only once
        # this append has fsynced
        try:
            acc = self._rec(ACCEPTED, jid, spec=spec)
            if gen_pinned is not None:
                acc["gen_pinned"] = gen_pinned
            self.journal.append(acc)
        except Exception as e:
            cls = resilience.classify_failure(e)
            return self._reject(
                jid, spec, f"journal_error ({cls.value}: "
                f"{resilience.failure_message(e)[:120]})")
        resilience.run_report().add("job_accepted", job=jid)
        with self._lock:
            self._jobs[jid]["state"] = ACCEPTED
            self._jobs[jid]["t_accepted"] = time.time()
            self._jobs[jid]["gen_pinned"] = gen_pinned
            # a fleet peer's journal sync may have surfaced the id
            # while our accept append fsynced — never queue it twice
            if jid not in self._queue and jid not in self._pqueue \
                    and jid not in self._running:
                self._enqueue_locked(jid)
            # gauge published under the lock: concurrent workers'
            # pop/publish pairs stay ordered, so the depth is
            # monotone-consistent with the queue
            self._queue_metric(len(self._queue))
            self._pqueue_metric(len(self._pqueue))
        self._log(f"job {jid}: accepted")
        return {"job": jid, "state": ACCEPTED}

    def _reject(self, jid: str, spec: dict, reason: str) -> dict:
        """Record one rejection (result file + best-effort journal
        line) — explicit load shedding, never a silent drop.  Takes
        the server lock only for the state update; the IO runs
        outside it."""
        from splatt_tpu import resilience

        with self._lock:
            j = self._new_job_locked(spec, REJECTED)
            j["status"] = "rejected"
            self._jobs[jid] = j
        try:
            # splint: ignore[SPL020] admission-time load shed: the job
            # never ran, so no lease exists to fence this terminal
            self.journal.append(self._rec(REJECTED, jid, reason=reason))
        except Exception as e:
            # the rejection itself needs no durability: an un-journaled
            # rejected job simply never existed after a restart
            self._warn_journal("reject", jid, e)
        self._write_result(jid, {"job": jid, "status": "rejected",
                                 "reason": reason})
        from splatt_tpu import trace

        trace.metric_inc("splatt_serve_jobs_total", status="rejected",
                         job=jid)
        self._log(f"job {jid}: rejected ({reason})")
        return {"job": jid, "state": REJECTED, "reason": reason}

    def status(self, jid: str) -> dict:
        """The job's current state (and terminal status, when known)."""
        with self._lock:
            j = self._jobs.get(jid)
            if j is None:
                return {"job": jid, "state": None}
            return {"job": jid, "state": j["state"],
                    "status": j["status"], "resumed": j["resumed"]}

    def result(self, jid: str) -> Optional[dict]:
        """The job's result record, or None while non-terminal."""
        return read_result(self.root, jid)

    def summary(self) -> dict:
        """Machine-readable daemon summary (the `splatt serve` exit
        report): per-job states, state counts, queue depth."""
        with self._lock:
            jobs = {jid: j["state"] for jid, j in self._jobs.items()}
            pending = len(self._queue)
            pending_predict = len(self._pqueue)
        counts: Dict[str, int] = {}
        for s in jobs.values():
            counts[s] = counts.get(s, 0) + 1
        out = {"jobs": jobs, "counts": counts, "pending": pending,
               "pending_predict": pending_predict,
               "draining": self._draining.is_set()}
        if self.fleet is not None:
            out["replica"] = self.fleet.replica
            out["held_leases"] = self.fleet.held()
        return out

    # -- filed-request spool -------------------------------------------------

    def scan_requests(self) -> int:
        """Ingest filed requests: every ``*.json`` under ``requests/``
        is parsed, submitted and unlinked — journal-first, so a crash
        between journaling and unlink re-ingests a known id, which the
        idempotent :meth:`submit` dedups.  A malformed or failing
        request is quarantined as ``<name>.bad`` (classified, logged)
        so the scanner cannot spin on it.

        Fleet mode makes the spool multi-consumer: a replica CLAIMS a
        request first (atomic rename to ``<name>.<replica>.claim`` —
        exactly one of N racing replicas wins; the losers skip
        silently), then parses and submits from the claimed file.  A
        replica that dies between claim and journal leaves the
        ``.claim`` file behind; :meth:`_reclaim_requests` renames a
        dead claimant's files back into the spool, so a claimed-but-
        never-journaled request is delayed, never lost."""
        from splatt_tpu import resilience

        n = 0
        if self.fleet is not None:
            self._reclaim_requests()
        for name in sorted(os.listdir(self.requests_dir)):
            if not name.endswith(".json"):
                continue
            path = os.path.join(self.requests_dir, name)
            read_path = path
            if self.fleet is not None:
                claim = f"{path}.{self.fleet.replica}.claim"
                try:
                    os.replace(path, claim)
                except OSError:
                    continue  # a peer claimed this request first
                read_path = claim
            try:
                with open(read_path) as f:
                    spec = json.load(f)
                if not isinstance(spec, dict):
                    raise ValueError("job spec must be a JSON object")
                spec.setdefault("id", name[:-5])
                self.submit(spec)
                n += 1
            except Exception as e:
                cls = resilience.classify_failure(e)
                self._log(f"request {name} failed to ingest "
                          f"({cls.value}: "
                          f"{resilience.failure_message(e)[:120]}); "
                          f"quarantined as {name}.bad", error=True)
                try:
                    os.replace(read_path, path + ".bad")
                except OSError:
                    pass
                continue
            try:
                os.unlink(read_path)
            except OSError:
                pass  # re-ingested next scan; submit dedups
        return n

    def _reclaim_requests(self) -> None:
        """Return a dead (or our own restarted) claimant's
        ``<name>.json.<replica>.claim`` spool files to the spool: the
        claim protects against double-ingest, never against ingest."""
        try:
            names = os.listdir(self.requests_dir)
        except OSError:
            return
        for name in names:
            if not name.endswith(".claim"):
                continue
            parts = name[:-len(".claim")].rsplit(".", 1)
            if len(parts) != 2 or not parts[0].endswith(".json"):
                continue
            base, rid = parts
            if rid != self.fleet.replica \
                    and self.fleet.replica_alive(rid):
                continue  # claimant lives; it is mid-ingest
            try:
                os.replace(os.path.join(self.requests_dir, name),
                           os.path.join(self.requests_dir, base))
            except OSError:
                pass

    # -- supervisor ----------------------------------------------------------

    def _order_locked(self) -> List[str]:
        """Queue in dispatch order: priority class first, arrival
        order within a class (callers hold the server lock)."""
        return sorted(
            self._queue,
            key=lambda jid: (PRIORITIES.get(
                self._jobs[jid].get("priority") or "normal", 1),
                self._jobs[jid].get("seq", 0)))

    def _next(self) -> Optional[str]:
        """Pick (and in fleet mode, lease-claim) the next job.

        Single replica: highest-priority, oldest job — done.  Fleet
        (docs/fleet.md): cache affinity is the scheduling signal, load
        the tiebreaker — a job whose shape regime is warm HERE is
        taken first (the Nth-request-is-free property survives
        scale-out); a job warm only on a live, not-busier PEER is
        deferred to that peer for up to AFFINITY_DEFER_MAX passes;
        everything else dispatches by priority/arrival.  The pick only
        becomes ours once the job's lease is acquired — a claim a peer
        won (or a claim fault) drops the job here and the fleet scan
        re-surfaces it."""
        # peer snapshot before taking the lock: heartbeat reads are
        # file IO and must not stall the control plane
        peers = (self.fleet.peers()
                 if self.fleet is not None and self.affinity else {})
        while True:
            routed = None  # (reason, jid, regime, peer) emitted below
            with self._lock:
                pick = None
                if self._pqueue:
                    # predict lane first (docs/predict.md): FIFO, no
                    # affinity pass, no deferral — the low-latency
                    # read path never waits behind a fit, and the
                    # lease claim below still applies like any job
                    pick = self._pqueue.pop(0)
                    self._running.add(pick)
                    self._pqueue_metric(len(self._pqueue))
                order = self._order_locked() if pick is None else []
                if pick is None and self.affinity \
                        and self.fleet is not None:
                    # affinity pass: ANY job warm on this replica
                    # beats queue position (within a scan the
                    # priority/arrival order still breaks warm ties)
                    for jid in order:
                        reg = self._jobs[jid].get("regime")
                        if reg and self.fleet.warm(reg):
                            pick = jid
                            routed = ("warm_local", jid, reg, None)
                            break
                for jid in order if pick is None else ():
                    j = self._jobs[jid]
                    reg = j.get("regime")
                    if not self.affinity or self.fleet is None:
                        pick = jid
                        break
                    peer = self.fleet.peer_warm(reg, peers)
                    if peer is not None:
                        if j["deferred"] < AFFINITY_DEFER_MAX \
                                and int(peers[peer].get("active", 0)) \
                                <= self.fleet.active_count() + 1:
                            j["deferred"] += 1
                            if j["deferred"] == 1:
                                routed = ("deferred", jid, reg, peer)
                            continue  # leave it for the warm peer
                        routed = ("load_tiebreak", jid, reg, peer)
                    pick = jid
                    break
                if pick is not None and pick in self._queue:
                    # predict-lane picks were popped above; only a
                    # priority-queue pick still needs dequeueing
                    self._queue.remove(pick)
                    self._running.add(pick)
                    self._queue_metric(len(self._queue))
            if routed is not None:
                self._route_event(*routed)
            if pick is None or self.fleet is None:
                return pick
            if self._claim(pick):
                if not self._terminal_after_claim(pick):
                    return pick
                # a peer finished this job between our queue scan and
                # our claim: the terminal append always happens UNDER
                # the lease, before release, so a journal read made
                # while WE hold the lease is authoritative — drop the
                # pick instead of re-running a finished job (found by
                # the interleaving checker, tools/splint/interleave.py)
                self.fleet.release(pick)
                self._log(f"job {pick}: finished by a peer before our "
                          f"claim; dropped")
            # a peer won the lease (or the claim faulted): not ours —
            # the fleet scan re-surfaces it if it goes unowned
            with self._lock:
                self._running.discard(pick)

    def _terminal_after_claim(self, jid: str) -> bool:
        """Post-claim journal re-check (fleet mode): tail the shared
        journal and report whether `jid` is now terminal.  Called
        while HOLDING the job's lease, which makes the read
        authoritative: a peer's terminal append happens under the
        lease before release, so if one exists it is visible here —
        and if none is visible, no zombie can add one later (its
        last-gate renew fails against our generation)."""
        with self._lock:
            recs, _torn, self._journal_offset = \
                self.journal.replay_new(self._journal_offset)
            for rec in recs:
                done = self._apply_rec_locked(rec)
                if done and self._jobs[done]["state"] in TERMINAL:
                    self._unqueue_locked(done)
            return self._jobs[jid]["state"] in TERMINAL

    # -- auto coalescing (docs/batched.md) -----------------------------------

    def _batchable(self, jid: str, j: dict) -> bool:
        """Whether one job table entry may ride a coalesced batch: a
        plain synthetic ``cpd`` job with no per-job machinery a batch
        cannot honor slot-wise — no declared fault schedule (scoped
        per job, the batch runs in one scope), no pre-tune, no
        explicit deadline, no per-job health budget.  A resumed job
        qualifies only when it left NO checkpoint (a crashed daemon's
        never-started small jobs re-batch on restart — the journal
        round-trip; a mid-run checkpoint wants the single-job resume
        path, batched runs do not checkpoint)."""
        spec = j.get("spec") or {}
        if j.get("resumed") and os.path.exists(
                os.path.join(self.ckpt_dir, f"{jid}.npz")):
            return False
        return (j.get("regime") is not None
                and str(spec.get("kind") or "cpd") == "cpd"
                and bool(spec.get("synthetic"))
                and not spec.get("tensor")
                and not spec.get("faults")
                and not spec.get("tune")
                and spec.get("deadline_s") is None
                and spec.get("health_retries") is None
                # fields the batch body cannot honor per-slot: engine
                # knobs would silently follow the leader's defaults,
                # and an EXPLICIT checkpoint cadence is a durability
                # request batched runs (which never checkpoint) must
                # not swallow
                and spec.get("use_pallas") is None
                and spec.get("engine_fallback") is None
                and spec.get("autotune") is None
                and "checkpoint_every" not in spec)

    def _batch_key(self, j: dict) -> tuple:
        """The coalescing key: jobs batch together only when ONE
        vmapped computation can honor every slot's contract — same
        shape regime (same bucket shape, same rank: the stacking
        precondition) and same iteration/tolerance budget."""
        spec = j["spec"]
        return (j["regime"], int(spec.get("iters", 25)),
                float(spec.get("tol", 1e-5)))

    def _next_batch(self) -> List[str]:
        """Pick the next unit of work: usually ``[jid]``, but when the
        queue holds >= ``batch_min`` batchable jobs sharing the picked
        job's coalescing key, up to BATCH_MAX of them dispatch as ONE
        batch (docs/batched.md).  In fleet mode every batch mate is
        individually lease-claimed exactly like a single pick — a mate
        a peer wins simply stays out of this batch."""
        jid = self._next()
        if jid is None:
            return []
        mates: List[str] = []
        with self._lock:
            j = self._jobs[jid]
            if self.batch_min >= 1 and self._batchable(jid, j):
                key = self._batch_key(j)
                mates = [q for q in self._order_locked()
                         if self._batchable(q, self._jobs[q])
                         and self._batch_key(self._jobs[q]) == key]
                if 1 + len(mates) < self.batch_min:
                    mates = []
                mates = mates[:BATCH_MAX - 1]
                for q in mates:
                    self._queue.remove(q)
                    self._running.add(q)
                if mates:
                    self._queue_metric(len(self._queue))
        batch = [jid]
        for q in mates:
            if self.fleet is None:
                batch.append(q)
                continue
            if self._claim(q):
                if not self._terminal_after_claim(q):
                    batch.append(q)
                    continue
                self.fleet.release(q)
                self._log(f"job {q}: finished by a peer before our "
                          f"batch claim; dropped")
            with self._lock:
                self._running.discard(q)
        return batch

    def _route_event(self, reason: str, jid: str, regime: str,
                     peer: Optional[str]) -> None:
        """One ``affinity_routed`` audit event (docs/fleet.md)."""
        from splatt_tpu import resilience

        info = dict(job=jid, regime=regime, reason=reason,
                    replica=self.fleet.replica)
        if peer is not None:
            info["to_replica"] = peer
        resilience.run_report().add("affinity_routed", **info)

    def _claim(self, jid: str) -> bool:
        """Acquire the job's lease (fleet mode): the normal path for
        an unclaimed job, the audited ``fleet.adopt`` takeover for an
        expired one.  A successful takeover journals an ``adopted``
        record and leaves ``job_adopted``/``lease_expired`` evidence;
        any failure degrades classified — never a dead worker."""
        from splatt_tpu import resilience, trace

        me = self.fleet.replica
        with self._lock:
            adopt_from = self._jobs[jid].get("adopt_from")
        try:
            lease = self.fleet.lease_of(jid)
            stale = (lease.replica if lease is not None
                     and lease.expired() else None)
            if stale is not None:
                ok = self.fleet.adopt(jid)
            else:
                ok = self.fleet.acquire(jid)
        except Exception as e:
            cls = resilience.classify_failure(e)
            self._log(f"job {jid}: lease claim degraded ({cls.value}: "
                      f"{resilience.failure_message(e)[:120]}); "
                      f"re-surfaced by the fleet scan", error=True)
            return False
        if not ok:
            return False
        victim = adopt_from or (stale if stale != me else None)
        if victim:
            # a dead peer's job changed hands: audit the takeover
            resilience.run_report().add(
                "job_adopted", job=jid, replica=me, from_replica=victim)
            trace.metric_inc("splatt_fleet_adoptions_total")
            if stale is not None and stale != me:
                resilience.run_report().add(
                    "lease_expired", job=jid, replica=stale,
                    role="adopter")
                trace.metric_inc("splatt_fleet_lease_expired_total",
                                 role="adopter")
            try:
                self.journal.append(self._rec(ADOPTED, jid,
                                              from_replica=victim))
            except Exception as e:
                # lineage entry only — the lease itself is the
                # ownership record
                self._warn_journal("adopt", jid, e)
            self._log(f"job {jid}: adopted from {victim}")
            with self._lock:
                self._jobs[jid]["adopt_from"] = None
                self._jobs[jid]["adopted_from"] = victim
                self._jobs[jid]["resumed"] = True
        return True

    @staticmethod
    def _queue_metric(depth: int) -> None:
        from splatt_tpu import trace

        trace.metric_set("splatt_serve_queue_depth", float(depth))

    @staticmethod
    def _pqueue_metric(depth: int) -> None:
        from splatt_tpu import trace

        trace.metric_set("splatt_predict_queue_depth", float(depth))

    def _enqueue_locked(self, jid: str) -> None:
        """Route one pending job to its lane (callers hold the server
        lock): predicts ride the dedicated low-latency queue
        (docs/predict.md), everything else the priority queue."""
        j = self._jobs[jid]
        if str((j.get("spec") or {}).get("kind") or "cpd") == "predict":
            self._pqueue.append(jid)
        else:
            self._queue.append(jid)

    def _unqueue_locked(self, jid: str) -> None:
        """Drop one id from whichever lane holds it (callers hold the
        server lock)."""
        if jid in self._queue:
            self._queue.remove(jid)
        if jid in self._pqueue:
            self._pqueue.remove(jid)

    def run_once(self) -> dict:
        """Ingest the spool (and in fleet mode, sync the shared
        journal + adopt dead peers' jobs), then run every queued job
        to a terminal state (or until a drain interrupts) on `workers`
        supervisor threads.  Returns :meth:`summary`.

        The outer pass loop exists for fleet affinity: a pass may end
        with only PEER-WARM jobs left deferred in the queue; each
        further pass bumps their deferral counters toward the
        AFFINITY_DEFER_MAX steal, so a batch (``--once``) run still
        terminates with every job dispatched somewhere."""
        from splatt_tpu import resilience

        self.scan_requests()
        if self.fleet is not None:
            self._fleet_scan()
        while not self._draining.is_set():
            with self._lock:
                idle = not self._queue and not self._pqueue
            if idle:
                # nothing queued (the serve_forever steady state): skip
                # worker-thread construction entirely — an idle daemon
                # must not churn threads twice a second
                break

            def loop():
                while not self._draining.is_set():
                    jids = self._next_batch()
                    if not jids:
                        return
                    try:
                        if len(jids) > 1:
                            self._run_batch(jids)
                        else:
                            self._run_job(jids[0])
                    except Exception as e:
                        # backstop: _run_job/_run_batch handle job
                        # failures themselves, so anything landing
                        # here is a supervisor bug — mark the job(s)
                        # failed (classified) rather than dying
                        # silently and stranding the rest of the
                        # queue behind a dead worker
                        cls = resilience.classify_failure(e)
                        msg = resilience.failure_message(e)[:200]
                        for jid in jids:
                            self._log(f"job {jid}: supervisor error "
                                      f"({cls.value}: {msg})",
                                      error=True)
                            self._backstop_fail(jid, cls, msg)
                    finally:
                        for jid in jids:
                            with self._lock:
                                self._running.discard(jid)
                            if self.fleet is not None:
                                try:
                                    # never leak a held lease past
                                    # the job (a heartbeat renewing a
                                    # finished job forever); a
                                    # failing release must not kill
                                    # the worker
                                    self.fleet.release(jid)
                                except Exception as e:
                                    from splatt_tpu import resilience \
                                        as _res

                                    self._log(
                                        f"job {jid}: lease release "
                                        f"degraded "
                                        f"({_res.classify_failure(e).value}"
                                        f": {e})", error=True)

            threads = [threading.Thread(target=loop, daemon=True,
                                        name=f"splatt-serve-w{i}")
                       for i in range(max(self.workers, 1))]
            for t in threads:
                t.start()
            for t in threads:
                t.join()
            with self._lock:
                again = bool(self._queue) or bool(self._pqueue)
            if not again or self.fleet is None:
                break
        return self.summary()

    def _renew_fence(self, jid: str) -> bool:
        """The live-lease commit fence as one dominating call: True
        when this replica may journal a terminal record for `jid`
        right now.  Single-replica mode has no lease plane — the fence
        is vacuously live.  In fleet mode a renew refusal (or an
        unverifiable lease: the conservative answer) means a peer owns
        the job — the caller must abandon uncommitted.  splint SPL020
        requires every terminal append to be DOMINATED by this call
        (or an inline ``fleet.renew``), which is only checkable when
        the fence is a single statement on every path."""
        if self.fleet is None:
            return True
        try:
            return bool(self.fleet.renew(jid))
        # splint: ignore[SPL002] an unverifiable lease is an
        # unowned lease: the conservative answer is abandon
        except Exception:
            return False

    def _backstop_fail(self, jid: str, cls, msg: str) -> None:
        """Commit a supervisor-error FAILED verdict — with the same
        fences the normal commit path has.  A job already terminal
        (the escape was post-commit cleanup) must NOT gain a second
        terminal record, and in fleet mode a terminal record may only
        be journaled under a live lease (a renew refusal means a peer
        owns the job now — abandon uncommitted, exactly like the
        zombie path in _run_job)."""
        with self._lock:
            already = self._jobs[jid]["state"] in TERMINAL
        if already:
            self._log(f"job {jid}: already terminal; the supervisor "
                      f"error was post-commit cleanup", error=True)
            return
        if not self._renew_fence(jid):
            with self._lock:
                self._jobs[jid]["state"] = ACCEPTED
            self._log(f"job {jid}: supervisor error without a "
                      f"live lease; abandoned uncommitted",
                      error=True)
            return
        self._write_result(jid, {"job": jid, "status": "failed",
                                 "failure_class": cls.value,
                                 "error": msg})
        try:
            self.journal.append(self._rec(FAILED, jid,
                                          status="failed"))
        except Exception as e2:
            self._warn_journal("finish", jid, e2)
        with self._lock:
            self._jobs[jid]["state"] = FAILED
            self._jobs[jid]["status"] = "failed"

    # -- fleet membership (docs/fleet.md) ------------------------------------

    def _start_heartbeat(self) -> None:
        """The replica's liveness thread: publish the membership lease
        and renew every held job lease each ``heartbeat_s`` — running
        jobs must stay owned through arbitrarily long sweeps, so the
        renewal cannot ride the workers' cooperative polls alone."""
        def beat_loop():
            while not self._draining.wait(self.fleet.heartbeat_s):
                self.fleet.beat()

        self._hb_thread = threading.Thread(
            target=beat_loop, daemon=True, name="splatt-fleet-hb")
        self._hb_thread.start()

    def _fleet_scan(self) -> None:
        """One fleet sync + adoption pass: fold the shared journal's
        new records into the job table, then (re-)surface every
        non-terminal job that is neither queued/running here nor
        validly leased elsewhere — a dead peer's jobs become local
        queue entries marked ``adopt_from`` (claimed, audited, via
        :meth:`_claim`).  Exactly one of N scanning replicas wins the
        subsequent lease claim."""
        me = self.fleet.replica
        with self._lock:
            recs, torn, self._journal_offset = \
                self.journal.replay_new(self._journal_offset)
            for rec in recs:
                jid = self._apply_rec_locked(rec)
                if jid and self._jobs[jid]["state"] in TERMINAL:
                    # a peer finished a job we still had queued
                    self._unqueue_locked(jid)
            candidates = [
                jid for jid, j in self._jobs.items()
                if j["state"] not in (*TERMINAL, ACCEPTING)
                and j["spec"] is not None
                and jid not in self._queue and jid not in self._pqueue
                and jid not in self._running]
        for jid in candidates:
            lease = self.fleet.lease_of(jid)
            if lease is not None and not lease.expired():
                continue  # validly owned (a live peer's, or mid-claim)
            with self._lock:
                j = self._jobs.get(jid)
                if (j is None or j["state"] in (*TERMINAL, ACCEPTING)
                        or jid in self._queue or jid in self._pqueue
                        or jid in self._running):
                    continue
                owner = (lease.replica if lease is not None
                         else j.get("owner"))
                steal = False
                if lease is None and owner not in (None, me) \
                        and self.fleet.replica_alive(owner):
                    # its accepting replica lives and will claim it —
                    # EXCEPT when our caches are warm for the job's
                    # regime: then this is the receiving half of the
                    # peer's affinity deferral (docs/fleet.md), and we
                    # surface the job here.  The flock'd lease claim
                    # resolves the resulting race to one owner.
                    if not (self.affinity and j.get("regime")
                            and self.fleet.warm(j["regime"])):
                        continue
                    steal = True
                j["adopt_from"] = owner if not steal \
                    and owner not in (None, me) else None
                # resumed=True is safe even for a never-started job:
                # _execute just finds no checkpoint and starts fresh
                j["resumed"] = not steal or j["state"] != ACCEPTED
                j["deferred"] = 0
                self._enqueue_locked(jid)
                self._queue_metric(len(self._queue))
                self._pqueue_metric(len(self._pqueue))
            if j["adopt_from"]:
                self._log(f"job {jid}: dead-peer candidate "
                          f"(owner {j['adopt_from']}); queued for "
                          f"adoption")

    def serve_forever(self) -> dict:
        """The daemon loop: process the queue, poll the spool, repeat —
        until a drain (SIGTERM via :meth:`install_signal_handlers`, or
        :meth:`drain`).  Returns the final :meth:`summary`."""
        while not self._draining.is_set():
            self.run_once()
            self._maybe_write_metrics()
            self._draining.wait(self.poll_s)
        self.write_metrics_now()
        return self.summary()

    # -- metrics snapshots (docs/observability.md) ---------------------------

    def _maybe_write_metrics(self) -> None:
        """One cadence tick: snapshot the registry to
        ``SPLATT_METRICS_PATH`` when the interval elapsed (interval
        <= 0 means exit-only snapshots)."""
        if not self.metrics_path or self.metrics_interval_s <= 0:
            return
        now = time.monotonic()
        if now - self._metrics_last >= self.metrics_interval_s:
            self.write_metrics_now()

    def write_metrics_now(self) -> Optional[dict]:
        """Force one Prometheus-text snapshot (atomic replace; a write
        failure degrades classified inside write_metrics — metrics must
        never kill the daemon they observe), then run the fleet
        aggregation + SLO tick on the same cadence.  No-op without
        ``SPLATT_METRICS_PATH`` (fleet mode defaults it into the
        spool)."""
        if not self.metrics_path:
            return None
        from splatt_tpu import trace

        self._metrics_last = time.monotonic()
        ev = trace.write_metrics(self.metrics_path)
        self._slo_tick()
        return ev

    def _slo_tick(self) -> bool:
        """One aggregator-cadence pass (docs/observability.md): merge
        the fleet's snapshots into ``fleet/metrics.prom``, evaluate
        the multi-window SLO burn rates over the MERGED samples (a
        peer's outage must burn this replica's alerts too), and
        persist the verdicts for `splatt status`.  Single-replica
        daemons evaluate over their own registry.  A tick that BURNS
        re-snapshots this replica (and re-merges before publishing),
        so a final-tick burn is durable in the per-replica snapshot
        AND the published fleet/metrics.prom — never lost to a
        post-mortem.  Any failure degrades classified — observing the
        fleet must never kill a member of it."""
        from splatt_tpu import fleetobs, resilience, trace

        try:
            if self.fleet is not None:
                agg = fleetobs.aggregate(self.root)
                # mirror the census into THIS member's registry (its
                # next snapshot carries its last fleet view); the
                # aggregator itself stays a side-effect-free reader
                for state in ("alive", "dead"):
                    trace.metric_set(
                        "splatt_fleet_replicas",
                        float(agg.samples.get(
                            ("splatt_fleet_replicas",
                             (("state", state),)), 0.0)),
                        state=state)
                res = self._slo.evaluate(agg.samples)
                self._slo.write_state(fleetobs.slo_state_path(
                    self.root, self.fleet.replica))
                if any(s.get("burning") for s in res["slos"].values()):
                    # the burn incremented splatt_slo_burn_total AFTER
                    # this tick's snapshot: re-snapshot and re-merge
                    # so the published exposition carries it even when
                    # this was the daemon's last tick
                    trace.write_metrics(self.metrics_path)
                    agg = fleetobs.aggregate(self.root)
                fleetobs.write_fleet_metrics(agg)
            else:
                res = self._slo.evaluate(trace.samples())
                if any(s.get("burning") for s in res["slos"].values()):
                    trace.write_metrics(self.metrics_path)
        except Exception as e:
            cls = resilience.classify_failure(e)
            self._log(f"slo/aggregation tick degraded ({cls.value}: "
                      f"{resilience.failure_message(e)[:120]})",
                      error=True)

    def drain(self) -> None:
        """Begin a graceful drain: stop pulling queued jobs, interrupt
        running jobs at their next fit check (they checkpoint through
        the cpd `stop` hook and are journaled ``interrupted``), leave
        everything else journaled for the next start."""
        self._draining.set()

    def shutdown(self) -> None:
        """Graceful-exit bookkeeping on top of :meth:`drain`: stop the
        fleet heartbeat thread and retire the membership lease, so
        peers route around this replica immediately instead of
        waiting out the lease window."""
        self.drain()
        if self._hb_thread is not None:
            self._hb_thread.join(timeout=max(self.fleet.heartbeat_s * 4,
                                             1.0))
        if self.fleet is not None:
            self.fleet.retire()

    def install_signal_handlers(self) -> None:
        """SIGTERM/SIGINT → graceful drain (main thread only)."""
        signal.signal(signal.SIGTERM, lambda s, f: self.drain())
        signal.signal(signal.SIGINT, lambda s, f: self.drain())

    # -- one supervised job --------------------------------------------------

    def _run_job(self, jid: str, journal_start: bool = True) -> None:
        from splatt_tpu import resilience

        with self._lock:
            j = self._jobs[jid]
            spec, resumed = j["spec"], j["resumed"]
            regime = j.get("regime")
            adopted_from = j.get("adopted_from")
            t_accepted = j.get("t_accepted")
            j["state"] = STARTED
        if journal_start:
            # False on the batch-degrade path: the batch already
            # journaled STARTED, marked liveness and observed the
            # queue wait for every member — the per-tensor re-run is
            # the same execution attempt, not a second start
            try:
                self.journal.append(self._rec(STARTED, jid))
            except Exception as e:
                # non-fatal: without this line a crash replays the job
                # from ACCEPTED — it re-runs, and checkpoint resume
                # makes the re-run cheap
                self._warn_journal("start", jid, e)
        self._log(f"job {jid}: started" + (" (resumed)" if resumed else ""))
        from splatt_tpu import trace

        # the flight recorder's deterministic liveness mark: a point
        # event on THIS replica's ring saying the job went live here
        # (rides the next ring flush) — what the fleet soak's
        # post-mortem reads off a SIGKILLed victim (docs/observability.md)
        if journal_start:
            resilience.run_report().add("job_started", job=jid,
                                        resumed=resumed)

        # queue-wait SLO observation (docs/observability.md): seconds
        # accepted-to-started — an adoption after a kill lands the
        # victim's whole outage here, which is what makes the burn-rate
        # spike the fleet soak asserts on
        if journal_start and t_accepted is not None:
            trace.metric_observe("splatt_serve_queue_wait_seconds",
                                 max(time.time() - float(t_accepted),
                                     0.0))
        # one span per supervised job (docs/observability.md): with
        # tracing on, a tenant's whole run — cpd.als and its guard
        # spans nested under it — carries the job id (and, in fleet
        # mode, the replica that ran it plus the adoption lineage —
        # the `splatt trace` fleet summary and the merged-trace flow
        # events key on replica/adopted_from/status)
        attrs = dict(job=jid, resumed=resumed)
        if self.fleet is not None:
            attrs["replica"] = self.fleet.replica
            if adopted_from:
                attrs["adopted_from"] = adopted_from
        with trace.span("serve.job", **attrs) as sp:
            record, stopped = self._execute(jid, spec, resumed)
            if self.fleet is not None and record is not None \
                    and not self.fleet.renew(jid):
                # commit fence: a terminal record may only be journaled
                # under a live lease.  A stalled heartbeat (paused
                # process, busy host) can let the lease expire mid-run
                # unnoticed by the cooperative poll — the renew refusal
                # here catches it at the last gate, so a zombie owner
                # can never double-commit a job a peer already adopted
                stopped["lease"] = True
                record = None
            if record is not None:
                self._write_result(jid, record)
                kind = FAILED if record["status"] == "failed" else DONE
                # generation evidence rides the terminal record
                # (docs/predict.md): a commit's advanced model_gen and
                # a predict's served/pinned gens make the staleness
                # invariant auditable from the journal alone
                fence = {k: record[k]
                         for k in ("model", "model_gen", "gen",
                                   "gen_pinned")
                         if record.get(k) is not None}
                try:
                    self.journal.append(self._rec(
                        kind, jid, status=record["status"], **fence))
                    # the span carries the terminal verdict only once
                    # it is durably journaled — the merged-trace
                    # lineage audit counts COMMITTED verdicts (exactly
                    # one per job), so a failed finish-append (replay
                    # re-runs the job) must not leave a span claiming
                    # a commit that never happened
                    sp.set(status=record["status"])
                except Exception as e:
                    self._warn_journal("finish", jid, e)
        if record is None and stopped.get("lease"):
            # ownership moved on (lease expired; possibly adopted):
            # abandon WITHOUT committing anything — no terminal
            # record, no result — the current owner carries the
            # job's lineage from here (docs/fleet.md)
            with self._lock:
                self._jobs[jid]["state"] = ACCEPTED
            self._log(f"job {jid}: lease lost mid-run; abandoned "
                      f"uncommitted (the adopter owns it now)",
                      error=True)
            return
        if record is None:
            # drain interrupt: NOT terminal — the job already
            # checkpointed via the stop hook; journal the interruption
            # so the restart lineage is explicit
            try:
                self.journal.append(self._rec(INTERRUPTED, jid))
            except Exception as e:
                self._warn_journal("interrupt", jid, e)
            with self._lock:
                self._jobs[jid]["state"] = INTERRUPTED
            if self.fleet is not None:
                # release immediately: any live replica may resume it
                self.fleet.release(jid)
            self._log(f"job {jid}: interrupted by drain (checkpointed; "
                      f"resumes next start)")
            return
        with self._lock:
            self._jobs[jid]["state"] = kind
            self._jobs[jid]["status"] = record["status"]
        if self.fleet is not None:
            self.fleet.release(jid)
            if kind == DONE:
                # advertise the now-warm regime: same-regime jobs
                # route here and hit the probe/tune/compile caches
                # warm.  A FAILED job proved nothing about the caches
                # — advertising it would concentrate same-regime work
                # on a replica that never warmed them.
                self.fleet.add_regime(regime)
        self._log(f"job {jid}: {record['status']}"
                  + (f" fit={record['fit']:.5f}"
                     if record.get("fit") is not None else ""))

    # -- one coalesced batch (docs/batched.md) -------------------------------

    def _run_batch(self, jids: List[str]) -> None:
        """Run >= 2 coalesced same-regime jobs as ONE vmapped batch.

        Per-job lineage is preserved end to end: every member gets its
        own STARTED journal record (stamped with the batch leader), its
        own queue-wait observation, its own result file, its own
        terminal journal record behind the fleet commit fence, and its
        own per-slot health evidence.  ANY batch-path failure degrades
        CLASSIFIED to per-tensor dispatch (``batch_degraded``) — the
        batch is an optimization, never a new way to lose a job."""
        from splatt_tpu import resilience, trace
        from splatt_tpu.utils import faults

        lead = jids[0]
        t0 = time.time()
        with self._lock:
            specs = {jid: self._jobs[jid]["spec"] for jid in jids}
            regime = self._jobs[lead].get("regime")
            t_acc = {jid: self._jobs[jid].get("t_accepted")
                     for jid in jids}
            resumed = {jid: bool(self._jobs[jid].get("resumed"))
                       for jid in jids}
            for jid in jids:
                self._jobs[jid]["state"] = STARTED
        for jid in jids:
            try:
                self.journal.append(self._rec(STARTED, jid, batch=lead))
            except Exception as e:
                self._warn_journal("start", jid, e)
            resilience.run_report().add("job_started", job=jid,
                                        resumed=resumed[jid])
            if t_acc[jid] is not None:
                trace.metric_observe(
                    "splatt_serve_queue_wait_seconds",
                    max(time.time() - float(t_acc[jid]), 0.0))
        resilience.run_report().add("batch_dispatched", jobs=list(jids),
                                    regime=regime, k=len(jids))
        trace.metric_inc("splatt_serve_batches_total",
                         outcome="dispatched")
        self._log(f"batch [{lead} +{len(jids) - 1}]: dispatched "
                  f"(regime {regime}, k={len(jids)})")
        try:
            faults.maybe_fail("serve.batch")
            records = self._execute_batch(jids, specs, t0, resumed)
        except Exception as e:
            cls = resilience.classify_failure(e)
            msg = resilience.failure_message(e)[:200]
            resilience.run_report().add(
                "batch_degraded", jobs=list(jids),
                failure_class=cls.value, error=msg)
            trace.metric_inc("splatt_serve_batches_total",
                             outcome="degraded")
            self._log(f"batch [{lead} +{len(jids) - 1}]: degraded to "
                      f"per-tensor dispatch ({cls.value}: {msg})",
                      error=True)
            for jid in jids:
                self._run_job(jid, journal_start=False)
            return
        if records is None:
            # drain interrupt mid-batch: members are journaled
            # interrupted (no batched checkpoints — small jobs restart
            # fresh on resume, which is the documented trade)
            for jid in jids:
                try:
                    self.journal.append(self._rec(INTERRUPTED, jid))
                except Exception as e:
                    self._warn_journal("interrupt", jid, e)
                with self._lock:
                    self._jobs[jid]["state"] = INTERRUPTED
                if self.fleet is not None:
                    self.fleet.release(jid)
            self._log(f"batch [{lead} +{len(jids) - 1}]: interrupted "
                      f"by drain; members resume next start")
            return
        for jid in jids:
            self._commit_batch_member(jid, records[jid], regime)

    def _commit_batch_member(self, jid: str, record: dict,
                             regime: Optional[str]) -> None:
        """One member's terminal commit — the same fences as
        :meth:`_run_job`'s tail: in fleet mode a terminal record is
        journaled only under a live lease (a renew refusal abandons
        THIS member uncommitted; its adopter owns it now), and a DONE
        member advertises the now-warm regime."""
        from splatt_tpu import resilience, trace

        if self.fleet is not None and not self.fleet.renew(jid):
            with self._lock:
                self._jobs[jid]["state"] = ACCEPTED
            self._log(f"job {jid}: lease lost mid-batch; abandoned "
                      f"uncommitted (the adopter owns it now)",
                      error=True)
            return
        # terminal metrics + the job's own registry cut, inside a
        # per-job scope so the samples carry THIS member's job label
        # (per-tenant isolation: a neighbor's counters never appear)
        with resilience.scope(jid):
            trace.metric_inc("splatt_serve_jobs_total",
                             status=record["status"])
            trace.metric_inc("splatt_serve_batch_jobs_total")
            trace.metric_observe("splatt_job_seconds",
                                 float(record["seconds"]))
            record["metrics"] = trace.metrics_snapshot(job=jid)
        if self.fleet is not None:
            record["replica"] = self.fleet.replica
            # a mate claimed through an expired-lease adoption carries
            # the same lineage stamp the single-job commit writes
            with self._lock:
                adopted_from = self._jobs[jid].get("adopted_from")
            if adopted_from:
                record["adopted_from"] = adopted_from
        self._write_result(jid, record)
        kind = FAILED if record["status"] == "failed" else DONE
        try:
            self.journal.append(self._rec(kind, jid,
                                          status=record["status"]))
        except Exception as e:
            self._warn_journal("finish", jid, e)
        with self._lock:
            self._jobs[jid]["state"] = kind
            self._jobs[jid]["status"] = record["status"]
        if self.fleet is not None:
            self.fleet.release(jid)
            if kind == DONE:
                self.fleet.add_regime(regime)
        self._log(f"job {jid}: {record['status']}"
                  + (f" fit={record['fit']:.5f}"
                     if record.get("fit") is not None else ""))

    def _execute_batch(self, jids: List[str], specs: Dict[str, dict],
                       t0: float, resumed: Dict[str, bool]
                       ) -> Optional[Dict[str, dict]]:
        """The batch body: stack every member's workload and run ONE
        :func:`splatt_tpu.cpd.cpd_als_batched` under a batch-scoped
        resilience scope.  Returns per-job result records (slot-cut
        health events included), or None when a drain interrupted the
        run.  Any exception escapes to :meth:`_run_batch`'s classified
        per-tensor degrade."""
        from splatt_tpu import resilience, trace
        from splatt_tpu.config import Options, Verbosity
        from splatt_tpu.cpd import cpd_als_batched

        lead = jids[0]
        spec0 = specs[lead]

        def _stop() -> bool:
            return self._draining.is_set()

        with resilience.scope(f"batch-{lead}") as sc:
            tensors = [_load_workload(specs[jid]) for jid in jids]
            seeds = [int(specs[jid].get("seed", 0)) for jid in jids]
            rank = int(spec0.get("rank", 8))
            opts = Options(
                random_seed=seeds[0],
                max_iterations=int(spec0.get("iters", 25)),
                tolerance=float(spec0.get("tol", 1e-5)),
                verbosity=(Verbosity.LOW if self.verbose
                           else Verbosity.NONE),
                autotune=spec0.get("autotune"))
            with trace.span("serve.batch", k=len(jids), leader=lead):
                res = cpd_als_batched(tensors, rank=rank, opts=opts,
                                      seeds=seeds, stop=_stop)
            if res.stopped or self._draining.is_set():
                return None
            events = [{k: v for k, v in e.items() if k != "ts"}
                      for e in sc.report.events()]
        records: Dict[str, dict] = {}
        for i, jid in enumerate(jids):
            status = res.statuses[i]
            slot_events = [e for e in events
                           if e.get("slot") in (None, i)]
            rec = {"job": jid, "status": status,
                   "fit": float(res.fits[i]),
                   "resumed": bool(resumed.get(jid)),
                   "seconds": round(time.time() - t0, 3),
                   "degraded": status != "converged",
                   "batched": {"k": res.k, "leader": lead, "slot": i,
                               "compiles": res.compiles,
                               "iterations": res.iterations,
                               "rollbacks": res.rollbacks[i]},
                   "events": slot_events, "demotions": []}
            if status == "degraded":
                rec["failure_class"] = "numerical"
            records[jid] = rec
        return records

    def _execute(self, jid: str, spec: dict, resumed: bool):
        """Run one job under its own resilience scope and fault
        schedule; returns ``(record, stopped)`` — the result record,
        or None when a drain interrupted the run (already
        checkpointed, not terminal) or the job's lease was lost
        (``stopped["lease"]``: abandon, committing nothing — the
        adopter owns the job now)."""
        from splatt_tpu import resilience
        from splatt_tpu.utils import faults

        t0 = time.time()
        stopped = {"drain": False, "deadline": False, "lease": False}

        def _stop() -> bool:
            if self.fleet is not None and self.fleet.lost(jid):
                # the heartbeat thread's renew was refused: ownership
                # is gone, stop before committing anything further
                stopped["lease"] = True
                return True
            if self._draining.is_set():
                stopped["drain"] = True
                return True
            return False

        # an explicit deadline_s (0 included — a documented opt-out for
        # a known-long job) beats the server default; only an UNSET
        # spec field falls back to it
        ds = spec.get("deadline_s")
        deadline_s = float(ds if ds is not None
                           else (self.job_deadline_s or 0.0))
        deadline_end = (time.monotonic() + deadline_s
                        if deadline_s > 0 else None)

        def _stop_or_deadline() -> bool:
            # the watchdog timer cannot preempt a worker thread (no
            # interrupt_main off the main thread), so the deadline is
            # ALSO enforced cooperatively through the same fit-check
            # poll the drain uses — a runaway job releases its worker
            # at the next check instead of holding the queue hostage
            if deadline_end is not None \
                    and time.monotonic() > deadline_end:
                stopped["deadline"] = True
                return True
            return _stop()

        with resilience.scope(jid,
                              health_retries=spec.get("health_retries"),
                              deadline_s=spec.get("deadline_s")) as sc:
            record: dict = {"job": jid}
            armed: Dict[str, object] = {}
            try:
                # the job's declared fault schedule parses INSIDE the
                # guarded region: a tenant's typo fails THAT job,
                # classified — never the supervisor thread
                with faults.scoped(spec.get("faults") or "") as armed:
                    with resilience.deadline("serve.job_run",
                                             deadline_s
                                             if deadline_s > 0 else 0):
                        faults.maybe_fail("serve.job_run")
                        update_info = None
                        predict_rec = None
                        ingest_rec = None
                        model_gen = None
                        job_kind = str(spec.get("kind") or "cpd")
                        if job_kind == "update":
                            out, update_info = self._run_update(
                                jid, spec, _stop_or_deadline)
                            tune_info = None
                        elif job_kind == "predict":
                            predict_rec = self._run_predict(jid, spec)
                            out, tune_info = None, None
                        elif job_kind == "ingest":
                            ingest_rec = self._run_ingest(
                                jid, spec, _stop_or_deadline)
                            out, tune_info = None, None
                        else:
                            out, tune_info, model_gen = self._run_cpd(
                                jid, spec, _stop_or_deadline)
                        if stopped["deadline"]:
                            # the cooperative stop beat the post-hoc
                            # timer raise: convert explicitly (with
                            # the watchdog's own event) so the verdict
                            # is TIMEOUT either way
                            resilience.run_report().add(
                                "deadline_blown", site="serve.job_run",
                                seconds=float(deadline_s))
                            raise resilience.DeadlineExceeded(
                                f"splatt deadline blown at "
                                f"serve.job_run after {deadline_s:g}s "
                                f"(cooperative job-deadline stop)")
                if stopped["lease"] or stopped["drain"]:
                    return None, stopped
                if predict_rec is not None:
                    # the predict verdict ("served"/"refused") is its
                    # own status class — never "converged", and a
                    # refusal is a degrade, not a failure
                    record.update(predict_rec)
                elif ingest_rec is not None:
                    # ingest's own verdict: "converged" on a finalized
                    # stream, "degraded" when the quarantine budget
                    # tripped — committed chunks survive either way
                    record.update(ingest_rec)
                else:
                    degraded = bool(
                        sc.report.events("health_degraded"))
                    if degraded:
                        # run_report() here IS the job scope's report
                        resilience.run_report().add(
                            "job_degraded", job=jid,
                            failure_class="numerical",
                            error="health-retry budget exhausted")
                    record.update(status="degraded" if degraded
                                  else "converged",
                                  fit=float(out.fit))
                if tune_info is not None:
                    record["tune"] = tune_info
                if update_info is not None:
                    record["update"] = update_info
                    if update_info.get("model_gen") is not None:
                        # surface the commit's generation at record
                        # top level: _run_job copies it into the
                        # terminal journal record, which is what the
                        # journal-only staleness audit keys on
                        record["model"] = update_info["base"]
                        record["model_gen"] = update_info["model_gen"]
                if model_gen is not None:
                    record["model"] = jid
                    record["model_gen"] = model_gen
            except Exception as e:
                cls = resilience.classify_failure(e)
                msg = resilience.failure_message(e)[:200]
                resilience.run_report().add(
                    "job_degraded", job=jid,
                    failure_class=cls.value, error=msg)
                record.update(status="failed",
                              failure_class=cls.value, error=msg)
            # fired counts survive both outcomes (a failed NaN job's
            # evidence matters most); {} when the schedule never parsed
            fired = {site: s.fired for site, s in armed.items()
                     if s.fired}
            record.update(
                resumed=resumed, seconds=round(time.time() - t0, 3),
                degraded=record["status"] not in ("converged",
                                                  "served"),
                events=[{k: v for k, v in e.items() if k != "ts"}
                        for e in sc.report.events()],
                demotions=[dict(engine=d.engine,
                                failure_class=d.failure_class.value,
                                shape_key=d.shape_key,
                                error=d.error[:120])
                           for d in resilience.demotions()])
            if fired:
                record["faults_fired"] = fired
            # terminal-job metrics, recorded INSIDE the scope so every
            # sample carries this tenant's job label, then the job's
            # own cut of the registry embedded in its result — a
            # neighbor's counters never appear (docs/observability.md)
            from splatt_tpu import trace

            trace.metric_inc("splatt_serve_jobs_total",
                             status=record["status"])
            trace.metric_observe("splatt_job_seconds",
                                 float(record["seconds"]))
            record["metrics"] = trace.metrics_snapshot(job=jid)
            if self.fleet is not None:
                record["replica"] = self.fleet.replica
                with self._lock:
                    adopted_from = self._jobs[jid].get("adopted_from")
                if adopted_from:
                    record["adopted_from"] = adopted_from
        return record, stopped

    def _run_cpd(self, jid: str, spec: dict, stop: Callable[[], bool]):
        """The job body: workload → (optional pre-tune) → blocked
        build → guarded cpd_als with a per-job checkpoint."""
        import dataclasses

        from splatt_tpu import tune as _tune
        from splatt_tpu.blocked import BlockedSparse
        from splatt_tpu.config import Options, Verbosity
        from splatt_tpu.cpd import cpd_als

        tt = _load_workload(spec)
        rank = int(spec.get("rank", 8))
        opts = Options(
            random_seed=int(spec.get("seed", 0)),
            max_iterations=int(spec.get("iters", 25)),
            tolerance=float(spec.get("tol", 1e-5)),
            verbosity=Verbosity.LOW if self.verbose else Verbosity.NONE,
            use_pallas=spec.get("use_pallas"),
            autotune=spec.get("autotune"),
            engine_fallback=spec.get("engine_fallback"))
        tune_info = None
        if spec.get("tune"):
            # pre-tune inside the job scope: the Nth same-regime job
            # hits the warm shared plan cache (measured == 0), which is
            # the serving payoff the result records as evidence
            res = _tune.tune(tt, rank=rank, opts=opts)
            tune_info = dict(
                measured=res.measured, cache_hits=res.cache_hits,
                skipped=res.skipped,
                plans={str(m): dataclasses.asdict(p)
                       for m, p in sorted(res.plans.items())})
        bs = BlockedSparse.compile(tt, opts, rank=rank)
        ckpt = os.path.join(self.ckpt_dir, f"{jid}.npz")
        out = cpd_als(bs, rank=rank, opts=opts, checkpoint_path=ckpt,
                      checkpoint_every=int(spec.get("checkpoint_every", 5)),
                      stop=stop)
        gen = None
        if not (stop is not None and stop()):
            # fit commit (docs/predict.md): publish the FINAL factors
            # as the model checkpoint and advance the generation stamp
            # — this is what makes a completed fit servable by the
            # predict lane.  A failed stamp advance (the
            # model.generation fault site) raises: the commit aborts
            # classified and readers keep the previous generation.
            from splatt_tpu.cpd import _save_checkpoint
            from splatt_tpu.predict import advance_generation

            _save_checkpoint(ckpt, out.factors, out.lam, 0,
                             float(out.fit))
            gen = advance_generation(self.ckpt_dir, jid, out.factors,
                                     out.lam)
        return out, tune_info, gen

    # -- one incremental model update (docs/batched.md) ----------------------

    def _run_update(self, jid: str, spec: dict,
                    stop: Callable[[], bool]):
        """The ``update`` job body: append the delta COO to the base
        job's model, run a few warm-started ALS sweeps (delta-touched
        rows re-solved first), and advance the model store — the
        journal/checkpoint machinery acting as a model store, the
        incremental half of ROADMAP open item 2.

        Repair path: a missing model, a periodic-refit boundary
        (``SPLATT_UPDATE_REFIT_EVERY``), a health-sentinel degrade, or
        ANY warm-path failure (the ``cpd.update`` fault site included)
        degrades CLASSIFIED to a from-scratch refit of the merged
        tensor (``refit_scheduled`` event) — an update can cost extra
        sweeps, never the model."""
        from splatt_tpu import resilience, trace
        from splatt_tpu.blocked import BlockedSparse
        from splatt_tpu.config import Options, Verbosity
        from splatt_tpu.cpd import (_save_checkpoint, cpd_als,
                                    load_checkpoint_resilient,
                                    refresh_touched_rows, touched_rows)
        from splatt_tpu.utils.env import read_env_int

        base = str(spec.get("base") or "")
        with self._lock:
            bj = self._jobs.get(base)
            base_spec = (dict(bj["spec"])
                         if bj is not None and bj.get("spec") else None)
            # ordinal of THIS update against the base model: prior DONE
            # updates + 1 — what the periodic-refit cadence counts
            nup = 1 + sum(
                1 for q, j2 in self._jobs.items()
                if q != jid and j2["state"] == DONE
                and str((j2.get("spec") or {}).get("kind")
                        or "cpd") == "update"
                and str((j2.get("spec") or {}).get("base")
                        or "") == base)
        if base_spec is None:
            raise ValueError(
                f"update base {base!r} is unknown to this spool (the "
                f"base job's accepted spec must be in the journal)")
        delta = _load_delta(spec)
        ckpt = os.path.join(self.ckpt_dir, f"{base}.npz")
        tpath = os.path.join(self.ckpt_dir, f"{base}.model.npz")
        tt, applied = _load_model_tensor(tpath)
        if tt is None:
            tt = _load_workload(base_spec)
            applied = []
        if jid in applied:
            # crash idempotency: a re-run of an update whose persist
            # landed but whose terminal journal record did not must
            # not merge its delta a second time
            merged = tt
        else:
            merged = _merge_delta(tt, delta)
        rank = int(base_spec.get("rank", 8))
        sweeps = int(spec.get("iters")
                     or read_env_int("SPLATT_UPDATE_SWEEPS"))
        refit_every = int(read_env_int("SPLATT_UPDATE_REFIT_EVERY"))
        info = {"base": base, "delta_nnz": int(delta.nnz),
                "update_n": int(nup), "sweeps": int(sweeps)}

        def make_opts(iters: int) -> Options:
            # reorder pinned to identity: the touched-row refresh runs
            # in ORIGINAL row space against the checkpointed factors —
            # a tuned relabeling would permute the model against the
            # delta's rows
            return Options(
                random_seed=int(spec.get("seed",
                                         base_spec.get("seed", 0))),
                max_iterations=int(iters),
                tolerance=float(spec.get("tol",
                                         base_spec.get("tol", 1e-5))),
                verbosity=(Verbosity.LOW if self.verbose
                           else Verbosity.NONE),
                autotune=spec.get("autotune"),
                reorder="identity")

        reason = None
        out = None
        model = None
        if not (os.path.exists(ckpt) or os.path.exists(ckpt + ".bak")):
            reason = "no_model"
        else:
            # expect_reorder pins the row-label space: a base model
            # checkpointed under a RELABELED order (SPLATT_REORDER or
            # a tuned recipe) must not be consumed as identity-space
            # factors — the mismatch degrades here to None, i.e. the
            # full-refit repair path (docs/layout-balance.md)
            model = load_checkpoint_resilient(
                ckpt, expect_reorder="identity")
            if model is None:
                reason = "no_model"
        if reason is None and refit_every > 0 and nup % refit_every == 0:
            reason = "periodic"
        if reason is None:
            try:
                factors = model[0]
                ck_dims = tuple(int(u.shape[0]) for u in factors)
                if ck_dims != tuple(merged.dims) \
                        or int(factors[0].shape[1]) != rank:
                    raise ValueError(
                        f"model checkpoint is for dims={ck_dims} "
                        f"rank={int(factors[0].shape[1])}, merged "
                        f"tensor wants dims={tuple(merged.dims)} "
                        f"rank={rank}")
                opts = make_opts(sweeps)
                bs = BlockedSparse.compile(merged, opts, rank=rank)
                with trace.span("cpd.update", job=jid, base=base,
                                delta_nnz=int(delta.nnz)):
                    warm = refresh_touched_rows(
                        bs, factors,
                        touched_rows(delta, merged.nmodes),
                        reg=opts.regularization)
                    out = cpd_als(bs, rank=rank, opts=opts, init=warm,
                                  stop=stop)
                if resilience.run_report().events("health_degraded"):
                    # the sentinel gates acceptance: a warm update that
                    # blew up numerically is repaired by a full refit,
                    # not committed
                    reason = "health"
                    out = None
            except Exception as e:
                cls = resilience.classify_failure(e)
                reason = f"failed:{cls.value}"
                self._log(f"job {jid}: warm update failed "
                          f"({cls.value}: "
                          f"{resilience.failure_message(e)[:120]}); "
                          f"repairing with a full refit", error=True)
        if reason is not None:
            resilience.run_report().add(
                "refit_scheduled", job=jid, base=base, reason=reason,
                update_n=int(nup))
            trace.metric_inc("splatt_serve_updates_total",
                             outcome="refit")
            info["refit"] = reason
            opts = make_opts(int(base_spec.get("iters", 25)))
            bs = BlockedSparse.compile(merged, opts, rank=rank)
            out = cpd_als(bs, rank=rank, opts=opts, stop=stop)
        else:
            resilience.run_report().add(
                "update_applied", job=jid, base=base, update_n=int(nup),
                sweeps=int(sweeps), delta_nnz=int(delta.nnz),
                fit=float(out.fit))
            trace.metric_inc("splatt_serve_updates_total",
                             outcome="applied")
        if not (stop is not None and stop()):
            # advance the model store only for COMPLETE runs: an
            # interrupted update re-runs whole, and its delta must not
            # be double-merged (`applied` stamps make the re-run
            # idempotent even across a crash between these writes and
            # the terminal journal record)
            _save_checkpoint(ckpt, out.factors, out.lam, 0,
                             float(out.fit))
            if jid not in applied:
                applied = list(applied) + [jid]
            _save_model_tensor(tpath, merged, applied)
            # the generation fence seals the commit LAST
            # (docs/predict.md): a failed stamp advance (the
            # model.generation fault site) raises — this update fails
            # classified, the stamp never moved, and readers verify
            # the OLD stamp against the .bak checkpoint, so the old
            # generation keeps serving.  A bit-identical re-commit
            # (crash idempotency above) returns the current ordinal
            # without advancing.
            from splatt_tpu.predict import advance_generation

            info["model_gen"] = int(advance_generation(
                self.ckpt_dir, base, out.factors, out.lam))
            if spec.get("ingest_committed_ts"):
                # an ingest-chained update: the source chunk's journal
                # commit to THIS model-store commit is the live-feed
                # freshness number (docs/ingest.md)
                lag = max(time.time()
                          - float(spec["ingest_committed_ts"]), 0.0)
                trace.metric_observe(
                    "splatt_ingest_update_lag_seconds", lag)
                info["ingest_lag_s"] = round(lag, 3)
        return out, info

    # -- one generation-fenced predict (docs/predict.md) ---------------------

    def _run_predict(self, jid: str, spec: dict) -> dict:
        """The ``predict`` job body: answer from an intact model
        generation or REFUSE — never garbage.

        The read path prefers the hot-factor cache at the generation
        PINNED at admission (an update commit advances the generation
        rather than deleting entries, so the pinned entry — when
        cached — replays bit-exactly); a cache miss or poisoned
        lookup (``predict.cache``) degrades classified to the direct
        fenced read (``predict.read`` inside), which serves the
        newest generation that verifies against a stamp.  No intact
        generation -> status "refused" with a classified
        ``predict_degraded`` event.  Latency is observed
        accepted-to-served into the predict p99 SLO histogram."""
        from splatt_tpu import predict as _predict
        from splatt_tpu import resilience, trace

        model = str(spec.get("model") or "")
        with self._lock:
            j = self._jobs.get(jid) or {}
            pinned = j.get("gen_pinned")
            t_accepted = j.get("t_accepted")
        rec: dict = {"model": model}
        if pinned is not None:
            rec["gen_pinned"] = int(pinned)
        with trace.span("serve.predict", job=jid, model=model) as sp:
            entry = None
            cache_outcome = "miss"
            if pinned:
                try:
                    entry = self._hot_cache.get(model, int(pinned))
                    if entry is not None:
                        cache_outcome = "hit"
                except Exception as e:
                    cls = resilience.classify_failure(e)
                    resilience.run_report().add(
                        "predict_degraded", job=jid, model=model,
                        reason="cache_poisoned",
                        failure_class=cls.value,
                        error=resilience.failure_message(e)[:120])
                    entry = None
            if entry is None:
                try:
                    entry = _predict.load_model_generation(
                        self.ckpt_dir, model)
                except Exception as e:
                    cls = resilience.classify_failure(e)
                    resilience.run_report().add(
                        "predict_degraded", job=jid, model=model,
                        reason="read_failed",
                        failure_class=cls.value,
                        error=resilience.failure_message(e)[:120])
                    entry = None
                if entry is not None:
                    self._hot_cache.put(model, entry["gen"], entry)
            if entry is None:
                resilience.run_report().add(
                    "predict_degraded", job=jid, model=model,
                    reason="no_intact_generation")
                trace.metric_inc("splatt_predict_requests_total",
                                 outcome="refused")
                sp.set(status="refused")
                rec.update(status="refused",
                           reason="no_intact_generation")
                return rec
            gen = int(entry["gen"])
            if spec.get("coords") is not None:
                vals = _predict.reconstruct_entries(
                    entry["factors"], entry["lam"], spec["coords"])
                rec["values"] = [float(v) for v in vals]
            tk = spec.get("top_k")
            if isinstance(tk, dict):
                fixed = {int(m): int(i) for m, i in
                         (tk.get("fixed") or {}).items()}
                idx, scores = _predict.top_k_slice(
                    entry["factors"], entry["lam"], fixed,
                    int(tk.get("mode", 0)), int(tk.get("k", 10)))
                rec["top_k"] = {"indices": [int(i) for i in idx],
                                "scores": [float(s) for s in scores]}
            rec.update(status="served", gen=gen, sha=entry["sha"],
                       cache=cache_outcome)
            resilience.run_report().add(
                "predict_served", job=jid, model=model, gen=gen,
                gen_pinned=(int(pinned) if pinned is not None
                            else None),
                cache=cache_outcome)
            trace.metric_inc("splatt_predict_requests_total",
                             outcome="served")
            if t_accepted is not None:
                trace.metric_observe(
                    "splatt_predict_latency_seconds",
                    max(time.time() - float(t_accepted), 0.0))
            sp.set(status="served", gen=gen, cache=cache_outcome)
        return rec

    # -- one streaming-ingest job (docs/ingest.md) ---------------------------

    def _run_ingest(self, jid: str, spec: dict, stop) -> dict:
        """The ``ingest`` job body: stream ``spec['source']`` into
        ``<root>/ingest/<jid>/`` under the exactly-once chunk journal
        (ingest.py), and — when the spec names a ``base`` model —
        emit one ``update`` job per watermark interval
        (``update_every`` / SPLATT_INGEST_UPDATE_EVERY committed
        chunks), each carrying its chunk's journal-commit timestamp so
        the model-store commit can observe end-to-end update lag
        (the ``splatt_ingest_update_lag_seconds`` histogram).

        Update emission is durable in its own right: each watermark
        interval lands in ``deltas/updates.jsonl`` in fence order —
        delta file published atomically under a range-keyed name
        (never overwriting a published delta), the intent journaled,
        THEN the job submitted under the deterministic id
        ``<jid>-up-<lo>-<hi>``.  A SIGKILLed or lease-stopped ingest
        job re-runs whole: ingest's watermark replay makes the chunk
        plane exactly-once, and the updates-journal replay re-derives
        the covered chunk range from disk — the re-run never re-spans
        chunks an earlier run already fed to an update, re-assembles
        a journaled-but-missing delta from the committed segments,
        and re-submits every journaled intent (the job store dedups
        known ids), so the live-feed update chain neither drops nor
        double-applies records across a crash."""
        from splatt_tpu import ingest as ingest_mod
        from splatt_tpu.io import _bin_header
        from splatt_tpu.utils.env import read_env_int

        source = str(spec["source"])
        dest = str(spec.get("dest")
                   or os.path.join(self.root, "ingest", jid))
        base = spec.get("base")
        update_every = int(spec.get("update_every")
                           or read_env_int("SPLATT_INGEST_UPDATE_EVERY"))
        dims = (tuple(int(d) for d in spec["dims"])
                if spec.get("dims") else None)
        updates: list = []
        ujournal = Journal(os.path.join(dest, "deltas",
                                        "updates.jsonl"))
        covered = {"hi": -1}

        def _submit_update(intent: dict) -> None:
            res = self.submit({
                "kind": "update", "base": str(base),
                "delta_tensor": str(intent["delta"]),
                "id": str(intent["id"]),
                "tenant": spec.get("tenant"),
                "ingest_committed_ts":
                    float(intent.get("ingest_committed_ts") or 0.0)})
            state = res.get("state") or ("queued" if res.get("job")
                                         else REJECTED)
            if res.get("job") and state != REJECTED:
                if res["job"] not in updates:
                    updates.append(res["job"])
            else:
                self._log(f"job {jid}: watermark update for chunks "
                          f"[{intent['lo']}, {intent['hi']}] not "
                          f"accepted ({res}); the delta file and its "
                          f"journaled intent remain for the next "
                          f"re-run to retry", error=True)

        if base:
            # re-run recovery, from durable state BEFORE any new
            # interval fires: the journaled intents say which chunks
            # earlier runs already fed to updates, a missing delta
            # (crash between intent append and publish never happens
            # — publish precedes the append — but debris-cleaned
            # dests do) re-assembles from the committed segments, and
            # every intent re-submits idempotently (dedup by id)
            intents, _torn = ujournal.replay()
            for it in intents:
                if it.get("rec") != "update_intent":
                    continue
                covered["hi"] = max(covered["hi"], int(it["hi"]))
                if not int(it.get("nnz") or 0):
                    continue
                if not os.path.exists(str(it["delta"])):
                    ingest_mod.assemble_delta(
                        dest, int(it["lo"]), int(it["hi"]),
                        tuple(it.get("dims") or dims),
                        str(it["delta"]))
                _submit_update(it)

        def on_watermark(st, rec):
            if not base:
                return
            n = int(rec["n"])
            if n - covered["hi"] < max(update_every, 1):
                return
            lo = covered["hi"] + 1
            dpath = os.path.join(dest, "deltas",
                                 f"up-{lo:08d}-{n:08d}.bin")
            os.makedirs(os.path.dirname(dpath), exist_ok=True)
            ddims = tuple(int(d) for d in (dims or st.final_dims()))
            if os.path.exists(dpath):
                # a crashed attempt already published this exact
                # range (publish is atomic, so the file is whole):
                # reuse it — a published delta is never overwritten
                nnz = int(_bin_header(dpath)[4])
            else:
                delta = ingest_mod.assemble_delta(dest, lo, n, ddims,
                                                  dpath)
                nnz = int(delta.nnz)
            intent = {"rec": "update_intent", "lo": lo, "hi": n,
                      "id": f"{jid}-up-{lo}-{n}", "delta": dpath,
                      "nnz": nnz, "dims": [int(d) for d in ddims],
                      "ingest_committed_ts":
                          float(rec.get("ts") or 0.0)}
            # the emission fence: the intent journals BEFORE the
            # submit, so a crash in between re-submits by id on the
            # next run instead of re-deriving an overlapping range.
            # Load-bearing — an append failure aborts the job rather
            # than risk a double-applied interval
            ujournal.append(intent)
            covered["hi"] = n
            if nnz:
                _submit_update(intent)

        summary = ingest_mod.ingest_stream(
            source, dest, fmt=str(spec.get("format") or "auto"),
            chunk_records=(int(spec["chunk_records"])
                           if spec.get("chunk_records") else None),
            dims=dims, stop=stop, on_watermark=on_watermark)
        return {
            "status": summary["status"],
            "ingest": {k: summary[k] for k in
                       ("dest", "format", "chunks", "watermark",
                        "records", "nnz", "quarantined", "resumed",
                        "stopped", "dims", "tensor",
                        "records_per_sec", "error")},
            "updates": updates,
        }

    # -- plumbing ------------------------------------------------------------

    def _write_result(self, jid: str, record: dict) -> None:
        """Atomic result publish (tmp + rename): a reader never sees a
        torn result file."""
        from splatt_tpu import resilience
        from splatt_tpu.utils.durable import publish_json

        path = os.path.join(self.results_dir, f"{jid}.json")
        try:
            publish_json(path, record, sort_keys=True)
        except Exception as e:
            cls = resilience.classify_failure(e)
            self._log(f"job {jid}: result write failed ({cls.value}: "
                      f"{resilience.failure_message(e)[:120]}) — the "
                      f"journal still carries the terminal state",
                      error=True)

    def _warn_journal(self, op: str, jid: str, exc) -> None:
        """Classified warn-and-continue for non-load-bearing journal
        appends (submission appends are load-bearing and reject
        instead — see submit)."""
        from splatt_tpu import resilience

        cls = resilience.classify_failure(exc)
        self._log(f"job {jid}: journal append ({op}) failed "
                  f"({cls.value}: "
                  f"{resilience.failure_message(exc)[:120]}); "
                  f"continuing — replay re-derives this record",
                  error=True)

    def _log(self, msg: str, error: bool = False) -> None:
        import sys

        if error or self.verbose:
            print(f"splatt-serve: {msg}",
                  file=sys.stderr if error else sys.stdout, flush=True)


def _load_workload(spec: dict):
    """The job's tensor: an on-disk file (``tensor``) or a seeded
    synthetic (``synthetic: {dims, nnz, seed}``)."""
    if spec.get("tensor"):
        from splatt_tpu.io import load

        return load(spec["tensor"])
    syn = spec.get("synthetic")
    if not isinstance(syn, dict) or not syn.get("dims"):
        raise ValueError("job spec needs 'tensor': <path> or "
                         "'synthetic': {dims, nnz, seed}")
    from splatt_tpu.chaos import synthetic_tensor

    return synthetic_tensor(tuple(int(d) for d in syn["dims"]),
                            int(syn.get("nnz", 1000)),
                            int(syn.get("seed", 0)))


# -- the model store's delta/tensor plumbing (docs/batched.md) ---------------

def _load_delta(spec: dict):
    """The update job's delta COO: an on-disk file (``delta_tensor``)
    or a seeded synthetic (``delta: {dims, nnz, seed}``)."""
    if spec.get("delta_tensor"):
        from splatt_tpu.io import load

        return load(spec["delta_tensor"])
    d = spec.get("delta")
    if not isinstance(d, dict) or not d.get("dims"):
        raise ValueError("update job needs 'delta': {dims, nnz, seed} "
                         "or 'delta_tensor': <path>")
    from splatt_tpu.chaos import synthetic_tensor

    return synthetic_tensor(tuple(int(x) for x in d["dims"]),
                            int(d.get("nnz", 100)),
                            int(d.get("seed", 0)))


def _merge_delta(tt, delta):
    """Append a delta COO to the model tensor (additive semantics:
    a delta hitting an existing coordinate ADDS to its value — the
    engines' segment sums make duplicates additive by construction).
    The delta may not grow any mode past the model's dims: the
    checkpointed factors have no rows for new indices."""
    import numpy as np

    from splatt_tpu.coo import SparseTensor

    if delta.nmodes != tt.nmodes:
        raise ValueError(f"delta has {delta.nmodes} modes, the model "
                         f"tensor has {tt.nmodes}")
    for m in range(tt.nmodes):
        if delta.dims[m] > tt.dims[m]:
            raise ValueError(
                f"delta grows mode {m} to {delta.dims[m]} past the "
                f"model's dim {tt.dims[m]} — the checkpointed factors "
                f"have no rows for new indices")
    return SparseTensor(
        inds=np.concatenate([np.asarray(tt.inds),
                             np.asarray(delta.inds)], axis=1),
        vals=np.concatenate([np.asarray(tt.vals),
                             np.asarray(delta.vals)]),
        dims=tt.dims)


def _save_model_tensor(path: str, tt, applied) -> None:
    """Persist the model's CURRENT merged COO beside its checkpoint
    (atomic publish through the sanctioned durable helper), with the
    ids of every applied update — the idempotency stamp a crashed
    update's re-run checks before re-merging its delta."""
    import io as _io

    import numpy as np

    from splatt_tpu.utils.durable import publish_bytes

    from splatt_tpu.cpd import _checkpoint_digest

    payload = {"inds": np.asarray(tt.inds), "vals": np.asarray(tt.vals),
               "dims": np.asarray(tt.dims),
               "applied": np.asarray(list(applied), dtype="U64")}
    buf = _io.BytesIO()
    np.savez(buf, checksum=np.asarray(_checkpoint_digest(payload)),
             **payload)
    publish_bytes(path, buf.getvalue())


def _load_model_tensor(path: str):
    """Load a persisted model tensor → (SparseTensor, applied ids), or
    ``(None, [])`` when absent or unreadable — a corrupt or torn model
    tensor (unparseable, missing its ``applied`` idempotency stamp, or
    failing its content checksum) emits a classified ``model_torn``
    event and degrades to rebuilding from the base workload (the refit
    repair path), never a failed update."""
    import numpy as np

    from splatt_tpu.coo import SparseTensor

    try:
        with np.load(path) as z:
            if "applied" not in z.files:
                raise ValueError(
                    "model tensor has no 'applied' idempotency stamp")
            if "checksum" in z.files:
                from splatt_tpu.cpd import _checkpoint_digest

                payload = {k: np.asarray(z[k])
                           for k in ("inds", "vals", "dims", "applied")}
                want = str(z["checksum"])
                got = _checkpoint_digest(payload)
                if got != want:
                    raise ValueError(
                        f"model tensor checksum mismatch: stored "
                        f"{want[:12]} != computed {got[:12]}")
            tt = SparseTensor(inds=np.asarray(z["inds"]),
                              vals=np.asarray(z["vals"]),
                              dims=tuple(int(d) for d in z["dims"]))
            applied = [str(s) for s in z["applied"]]
        return tt, applied
    except FileNotFoundError:
        return None, []
    except Exception as e:
        from splatt_tpu import resilience

        resilience.run_report().add(
            "model_torn", path=path, piece="model-tensor",
            failure_class=resilience.classify_failure(e).value,
            error=resilience.failure_message(e)[:200])
        return None, []


# -- client-side filed-request API -------------------------------------------

def file_request(root: str, spec: dict) -> str:
    """Client side of the filed-request API: atomically drop a job
    spec into ``<root>/requests/`` for a (possibly not-yet-running)
    daemon to ingest.  Returns the job id."""
    from splatt_tpu.utils.durable import publish_json

    jid = _job_id(spec)
    spec = dict(spec, id=jid)
    reqs = os.path.join(os.path.abspath(root), "requests")
    os.makedirs(reqs, exist_ok=True)
    publish_json(os.path.join(reqs, f"{jid}.json"), spec)
    return jid


def read_result(root: str, jid: str) -> Optional[dict]:
    """The published result record for `jid`, or None while the job is
    non-terminal (or unknown)."""
    path = os.path.join(os.path.abspath(root), "results", f"{jid}.json")
    try:
        with open(path) as f:
            return json.load(f)
    except FileNotFoundError:
        return None
    except ValueError:
        return None  # mid-replace torn read cannot happen (atomic
        #               rename); a hand-damaged file reads as absent


def read_status(root: str, jid: str) -> dict:
    """Journal-derived job state (client side, no daemon needed): the
    last journal record wins; the result record rides along when the
    job is terminal."""
    journal = Journal(os.path.join(os.path.abspath(root),
                                   "journal.jsonl"))
    recs, _ = journal.replay()
    state = None
    status = None
    for rec in recs:
        if rec.get("job") != jid:
            continue
        state = rec.get("rec")
        if state in (DONE, FAILED):
            status = rec.get("status")
        elif state == REJECTED:
            status = "rejected"
        else:
            status = None  # re-accepted after a rejection: not terminal
    out = {"job": jid, "state": state, "status": status}
    if state in TERMINAL:
        res = read_result(root, jid)
        if res is not None:
            out["result"] = res
    # a spool file not yet ingested still counts as "filed"
    if state is None and os.path.exists(
            os.path.join(os.path.abspath(root), "requests",
                         f"{jid}.json")):
        out["state"] = "filed"
    return out
