"""TPU tuning sweep: measure MTTKRP paths/engines/dtypes/block sizes on
real hardware and record the results for path-selection heuristics.

Run (on a machine with a TPU):  python tools/tpu_tune.py
Writes tune_results.json: one record per (config, path, engine, dtype).

This is the round-2 entry point for the perf work the blocked format
was designed around — the one-hot/scatter/privatized trade-offs and
the Pallas-vs-XLA engine choice are all heavily shape-dependent and
must be measured, not guessed (the CPU measurements that shaped
choose_path's off-TPU branch are in BASELINE_MEASURED.json).
"""

from __future__ import annotations

import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

from splatt_tpu.utils.env import apply_env_platform

apply_env_platform()

import jax
import jax.numpy as jnp

from bench import synthetic_nell2_like
from splatt_tpu.bench_algs import _time_call as timeit
from splatt_tpu.blocked import build_layout
from splatt_tpu.ops.mttkrp import mttkrp_blocked, mttkrp_stream


def main() -> None:
    nnz = int(sys.argv[1]) if len(sys.argv) > 1 else 20_000_000
    rank = int(sys.argv[2]) if len(sys.argv) > 2 else 50
    tt = synthetic_nell2_like(nnz)
    platform = jax.devices()[0].platform
    rng = np.random.default_rng(0)
    results = []

    for dtype in (jnp.float32, jnp.bfloat16):
        factors = [jnp.asarray(rng.random((d, rank)), dtype=dtype)
                   for d in tt.dims]
        inds = jnp.asarray(tt.inds)
        vals = jnp.asarray(tt.vals, dtype=dtype)
        t = timeit(lambda: mttkrp_stream(inds, vals, factors, 0,
                                         tt.dims[0]))
        results.append(dict(path="stream", engine="xla",
                            dtype=str(np.dtype(dtype)), block=None,
                            sec=round(t, 4)))
        print(results[-1], flush=True)
        # one-hot HBM traffic is nnz × seg_width and seg_width grows
        # with the block size, so the sweep leans small (512-4096);
        # 16384+ only ever paid for the VMEM-resident fused plans
        for block in (512, 1024, 2048, 4096):
            lay = build_layout(tt, 0, block=block, val_dtype=dtype)
            for path, engines in (("sorted_onehot", ("xla", "pallas")),
                                  ("sorted_scatter", ("xla",))):
                for engine in engines:
                    if engine == "pallas" and platform != "tpu":
                        continue
                    try:
                        t = timeit(lambda: mttkrp_blocked(
                            lay, factors, 0, path=path, impl=engine))
                        rec = dict(path=path, engine=engine,
                                   dtype=str(np.dtype(dtype)), block=block,
                                   seg_width=lay.seg_width,
                                   sec=round(t, 4))
                    except Exception as e:
                        rec = dict(path=path, engine=engine,
                                   dtype=str(np.dtype(dtype)), block=block,
                                   error=f"{type(e).__name__}: {e}"[:120])
                    results.append(rec)
                    print(rec, flush=True)
            del lay

    # scan-chunk sweep for the XLA engine (the proven-on-chip engine:
    # tune how much one-hot each lax.scan step materializes)
    lay = build_layout(tt, 0, block=4096, val_dtype=jnp.float32)
    factors = [jnp.asarray(np.random.default_rng(0).random((d, rank)),
                           jnp.float32) for d in tt.dims]
    for target in (1 << 21, 1 << 22, 1 << 23, 1 << 24, 1 << 25):
        try:
            t = timeit(lambda: mttkrp_blocked(lay, factors, 0,
                                              path="sorted_onehot",
                                              impl="xla",
                                              scan_target=target))
            rec = dict(path="sorted_onehot", engine="xla",
                       scan_target_elems=target, block=4096,
                       sec=round(t, 5))
        except Exception as e:
            rec = dict(path="sorted_onehot", engine="xla",
                       scan_target_elems=target, block=4096,
                       error=f"{type(e).__name__}: {e}"[:120])
        results.append(rec)
        print(rec, flush=True)

    out = dict(platform=platform, nnz=nnz, rank=rank, dims=tt.dims,
               results=results)
    with open("tune_results.json", "w") as f:
        json.dump(out, f, indent=1)
    print("wrote tune_results.json", flush=True)


if __name__ == "__main__":
    main()
