"""Async ICI ring sweep (docs/ring.md).

Contract under test:

- **bit parity**: the ASYNC_RING strategy's CPU/interpret fallback
  preserves today's ppermute semantics — async-ring ≡ ppermute-ring ≡
  all2all factors BIT-identically on the seeded synthetic CPD (the
  gather adds exactly one non-zero term per nonzero, and the reduce
  keeps psum ordering off-TPU);
- **fallback ladder**: a ``comm.ring_exchange`` failure degrades
  classified down the comm chain — async_ring -> ring -> all2all —
  with ``comm_fallback`` run-report events and the failed strategy
  demoted under its own ``:comm`` shape key; the terminal all2all is
  never demoted (an async-ring OOM must not indict it), and with
  engine fallback off the failure is loud;
- **overlap metric**: measure_ring_overlap reports the achieved
  exchange-hidden fraction next to the wire model's per-device bytes,
  and ring-variant runs emit it as a ``ring_overlap`` event (what
  `splatt cpd --json` and MULTICHIP artifacts carry);
- **wire model**: comm_volume_model stops assuming all2all — the ring
  legs carry per-hop bytes and the overlap-eligible fraction.
"""

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from splatt_tpu import resilience
from splatt_tpu.config import (CommPattern, Options, Verbosity,
                               resolve_comm_pattern)
from splatt_tpu.cpd import cpd_als, init_factors
from splatt_tpu.parallel.common import comm_volume_model, comm_volume_report
from splatt_tpu.parallel.mesh import make_mesh
from splatt_tpu.parallel.ring_kernels import (async_blockwise_reduce_rows,
                                              async_ring_gather_rows,
                                              async_ring_supported)
from splatt_tpu.parallel.sharded import (comm_chain, measure_ring_overlap,
                                         shard_factors, shard_nnz,
                                         sharded_cpd_als)
from splatt_tpu.utils import faults
from splatt_tpu.utils.env import ceil_to, shard_map
from tests import gen


@pytest.fixture(autouse=True)
def _clean():
    faults.reset()
    resilience.reset_demotions()
    resilience.run_report().clear()
    yield
    faults.reset()
    resilience.reset_demotions()
    resilience.run_report().clear()


def _opts(**kw):
    kw.setdefault("random_seed", 42)
    kw.setdefault("verbosity", Verbosity.NONE)
    kw.setdefault("val_dtype", np.float64)
    return Options(**kw)


def _arm(text):
    for site, spec in faults.parse_schedule(text).items():
        faults.arm(site, spec)


def _run(comm, mesh, tt, init, **kw):
    return sharded_cpd_als(tt, rank=5, mesh=mesh, init=init,
                           opts=_opts(max_iterations=5, comm_pattern=comm,
                                      **kw.pop("opts_kw", {})), **kw)


# -- parity -----------------------------------------------------------------


def test_async_ring_unit_parity():
    """The async gather/reduce primitives ≡ their ppermute versions on
    the fallback path (and trivially on a 1-wide axis)."""
    ndev = 8
    mesh = make_mesh(n_devices=ndev)
    rng = np.random.default_rng(0)
    dim_pad, R, nnz = 40, 6, 64
    U = jnp.asarray(rng.random((dim_pad, R)))
    idx = jnp.asarray(rng.integers(0, dim_pad, size=nnz).astype(np.int32))
    U_s = jax.device_put(U, NamedSharding(mesh, P("nnz", None)))

    @partial(shard_map, mesh=mesh, in_specs=(P("nnz", None), P(None)),
             out_specs=P(None), check_vma=False)
    def run(U_l, idx_rep):
        return async_ring_gather_rows(U_l, idx_rep, "nnz", ndev)

    got = run(U_s, idx)
    np.testing.assert_array_equal(np.asarray(got),
                                  np.asarray(U)[np.asarray(idx)])

    prod = rng.random((ndev * 32, R))
    ridx = rng.integers(0, dim_pad, size=ndev * 32).astype(np.int32)
    prod_s = jax.device_put(jnp.asarray(prod),
                            NamedSharding(mesh, P("nnz", None)))
    ridx_s = jax.device_put(jnp.asarray(ridx),
                            NamedSharding(mesh, P("nnz")))

    @partial(shard_map, mesh=mesh, in_specs=(P("nnz", None), P("nnz")),
             out_specs=P("nnz", None), check_vma=False)
    def red(prod_l, idx_l):
        return async_blockwise_reduce_rows(prod_l, idx_l, "nnz", ndev,
                                           dim_pad // ndev)

    want = np.zeros((dim_pad, R))
    np.add.at(want, ridx, prod)
    np.testing.assert_allclose(np.asarray(red(prod_s, ridx_s)), want,
                               atol=1e-12)


def test_async_ring_cpd_bit_parity_three_ways():
    """Acceptance: async-ring ≡ ppermute-ring ≡ all2all factors
    BIT-identically on the seeded synthetic CPD (CPU/interpret)."""
    tt = gen.fixture_tensor("med")
    mesh = make_mesh(n_devices=8)
    init = init_factors(tt.dims, 5, 42, dtype=jnp.float64)
    a = _run(CommPattern.ALL2ALL, mesh, tt, init, local_engine="stream")
    b = _run(CommPattern.POINT2POINT, mesh, tt, init)
    c = _run(CommPattern.ASYNC_RING, mesh, tt, init)
    assert float(a.fit) == float(b.fit) == float(c.fit)
    for fa, fb, fc in zip(a.factors, b.factors, c.factors):
        np.testing.assert_array_equal(np.asarray(fa), np.asarray(fb))
        np.testing.assert_array_equal(np.asarray(fb), np.asarray(fc))


def test_async_ring_single_device_degenerate():
    """ndev=1: the async strategy runs (fallback path, trivial ring)
    and matches the single-device driver."""
    tt = gen.fixture_tensor("med4")
    init = init_factors(tt.dims, 4, 42, dtype=jnp.float64)
    single = cpd_als(tt, rank=4, opts=_opts(max_iterations=5), init=init)
    ring = sharded_cpd_als(tt, rank=4, mesh=make_mesh(n_devices=1),
                           init=init,
                           opts=_opts(max_iterations=5,
                                      comm_pattern=CommPattern.ASYNC_RING))
    assert float(ring.fit) == pytest.approx(float(single.fit), abs=1e-8)


def test_async_ring_supported_is_false_on_cpu():
    """Tier-1 runs the ppermute-fallback dataflow — the RDMA kernels
    require a real TPU backend."""
    assert async_ring_supported() is False


# -- comm chain / env resolution --------------------------------------------


def test_comm_chain_shapes():
    assert comm_chain(CommPattern.ALL2ALL) == ("all2all",)
    assert comm_chain(CommPattern.POINT2POINT) == ("ring", "all2all")
    assert comm_chain(CommPattern.ASYNC_RING) == ("async_ring", "ring",
                                                  "all2all")


def test_resolve_comm_pattern_env(monkeypatch):
    assert resolve_comm_pattern(_opts()) is CommPattern.ALL2ALL
    monkeypatch.setenv("SPLATT_COMM", "async_ring")
    assert resolve_comm_pattern(_opts()) is CommPattern.ASYNC_RING
    # explicit option beats the env default
    assert resolve_comm_pattern(
        _opts(comm_pattern=CommPattern.POINT2POINT)) \
        is CommPattern.POINT2POINT
    monkeypatch.setenv("SPLATT_COMM", "bogus")
    with pytest.raises(ValueError):
        resolve_comm_pattern(_opts())


# -- fallback ladder --------------------------------------------------------


def test_comm_fallback_lands_on_sync_ring():
    """One injected async-ring failure: the sweep degrades classified
    to the ppermute ring (comm_fallback event, comm.async_ring
    demoted) and still converges bit-identically to a clean ring run."""
    tt = gen.fixture_tensor("med")
    mesh = make_mesh(n_devices=8)
    init = init_factors(tt.dims, 5, 42, dtype=jnp.float64)
    clean = _run(CommPattern.POINT2POINT, mesh, tt, init)
    resilience.run_report().clear()
    resilience.reset_demotions()
    with faults.inject("comm.ring_exchange", "runtime", times=1):
        out = _run(CommPattern.ASYNC_RING, mesh, tt, init)
    evs = resilience.run_report().events("comm_fallback")
    assert [(e["strategy"], e["fallback_to"]) for e in evs] \
        == [("async_ring", "ring")]
    assert [d.engine for d in resilience.demotions()] == ["comm.async_ring"]
    assert float(out.fit) == float(clean.fit)
    for fa, fb in zip(out.factors, clean.factors):
        np.testing.assert_array_equal(np.asarray(fa), np.asarray(fb))


def test_comm_fallback_oom_scoped_never_demotes_all2all():
    """Acceptance: an async-ring OOM demotes the ring engines PER
    SHAPE under the ':comm' key and lands on all2all — which is never
    demoted; a different shape keeps the async ring live."""
    tt = gen.fixture_tensor("med")
    mesh = make_mesh(n_devices=8)
    init = init_factors(tt.dims, 5, 42, dtype=jnp.float64)
    _arm("comm.ring_exchange:oom:2")
    out = _run(CommPattern.ASYNC_RING, mesh, tt, init)
    assert np.isfinite(float(out.fit))
    evs = resilience.run_report().events("comm_fallback")
    assert [(e["strategy"], e["fallback_to"]) for e in evs] \
        == [("async_ring", "ring"), ("ring", "all2all")]
    assert all(e["failure_class"] == "resource" for e in evs)
    dem = {d.engine: d for d in resilience.demotions()}
    assert set(dem) == {"comm.async_ring", "comm.ring"}
    for d in dem.values():
        assert d.shape_key is not None and d.shape_key.endswith(":comm")
    assert not resilience.is_demoted("comm.all2all", None)
    # per-shape scoping: another shape's key is untouched
    assert not resilience.is_demoted("comm.async_ring",
                                     "d8x8x8:w8:r5:float64:comm")
    # MTTKRP engine keys are a different namespace entirely
    assert not resilience.is_demoted("fused_t")


def test_demoted_comm_engine_skipped_next_run():
    """A second run at the demoted shape goes straight to the sync
    ring — no repeated probe failure, no new fallback event."""
    tt = gen.fixture_tensor("med")
    mesh = make_mesh(n_devices=8)
    init = init_factors(tt.dims, 5, 42, dtype=jnp.float64)
    _arm("comm.ring_exchange:runtime:1")
    _run(CommPattern.ASYNC_RING, mesh, tt, init)
    assert len(resilience.run_report().events("comm_fallback")) == 1
    faults.reset()
    out = _run(CommPattern.ASYNC_RING, mesh, tt, init)
    # still exactly ONE event: the demoted async engine was pruned,
    # not re-probed and re-failed
    assert len(resilience.run_report().events("comm_fallback")) == 1
    assert np.isfinite(float(out.fit))


def test_comm_fallback_disabled_fails_loudly():
    """engine_fallback off = the differential-test contract: the
    injected comm failure escapes instead of being rescued."""
    tt = gen.fixture_tensor("med")
    mesh = make_mesh(n_devices=8)
    init = init_factors(tt.dims, 5, 42, dtype=jnp.float64)
    _arm("comm.ring_exchange:runtime:1")
    with pytest.raises(Exception, match="injected"):
        _run(CommPattern.ASYNC_RING, mesh, tt, init,
             opts_kw=dict(engine_fallback=False))
    assert not resilience.run_report().events("comm_fallback")


def test_chaos_comm_drill_degrades_classified():
    """The `splatt chaos` comm drill: an armed ring-exchange fault
    under the ASYNC_RING strategy converges-or-degrades with
    comm_fallback evidence — never an unhandled exception."""
    from splatt_tpu import chaos

    res = chaos.run_chaos(schedule="comm.ring_exchange:oom:2", smoke=True)
    assert res.ok, res.violations
    assert res.fired.get("comm.ring_exchange") == 2
    kinds = {e["kind"] for e in res.events}
    assert "comm_fallback" in kinds


# -- overlap metric + wire model --------------------------------------------


def _sharded_operands(tt, mesh, rank=5):
    ndev = mesh.shape["nnz"]
    dims_pad = tuple(ceil_to(d, ndev) for d in tt.dims)
    inds, vals = shard_nnz(tt, mesh, val_dtype=np.float64)
    init = init_factors(tt.dims, rank, 42, dtype=jnp.float64)
    facs = tuple(shard_factors([jnp.asarray(f) for f in init], tt.dims,
                               mesh))
    from splatt_tpu.ops.linalg import gram

    grams = tuple(jax.device_put(
        gram(U), NamedSharding(mesh, P(None, None))) for U in facs)
    return dims_pad, inds, vals, facs, grams


def test_measure_ring_overlap_fields():
    tt = gen.fixture_tensor("med")
    mesh = make_mesh(n_devices=8)
    dims_pad, inds, vals, facs, grams = _sharded_operands(tt, mesh)
    ov = measure_ring_overlap(mesh, tt.nmodes, 0.0, dims_pad, "nnz",
                              "async_ring", inds, vals, facs, grams,
                              jnp.float64, reps=1)
    assert ov["variant"] == "async_ring"
    assert ov["engine"] == "ppermute_fallback"  # CPU: honest labelling
    assert 0.0 <= ov["overlap_frac"] <= 1.0
    assert ov["exchange_s"] > 0 and ov["step_s"] > 0
    assert ov["model_mb_per_device"] > 0
    assert ov["exposed_comm_s"] >= 0 and ov["hidden_comm_s"] >= 0
    assert 0.0 <= ov["overlap_eligible_frac"] < 1.0


def test_ring_overlap_event_emitted():
    """A ring-variant driver run with measurement on emits the
    ring_overlap event `splatt cpd --json` serializes."""
    tt = gen.fixture_tensor("med")
    mesh = make_mesh(n_devices=8)
    init = init_factors(tt.dims, 5, 42, dtype=jnp.float64)
    _run(CommPattern.ASYNC_RING, mesh, tt, init, measure_overlap=True)
    evs = resilience.run_report().events("ring_overlap")
    assert len(evs) == 1
    assert evs[0]["variant"] == "async_ring"
    assert "overlap_frac" in evs[0] and "model_mb_per_device" in evs[0]
    # and off by default at NONE verbosity
    resilience.run_report().clear()
    _run(CommPattern.ASYNC_RING, mesh, tt, init)
    assert not resilience.run_report().events("ring_overlap")


def test_comm_volume_model_ring_legs():
    """The wire model follows the selected strategy (ISSUE 8
    satellite): ring legs carry per-hop bytes and the async variant an
    overlap-eligible fraction; all2all keeps the collective model."""
    dims_pad = (64, 64, 64)
    a = comm_volume_model(dims_pad, 8, 8, ndev=8, variant="all2all")
    r = comm_volume_model(dims_pad, 8, 8, ndev=8, variant="ring")
    x = comm_volume_model(dims_pad, 8, 8, ndev=8, variant="async_ring")
    assert a["variant"] == "all2all" and a["overlap_eligible_frac"] == 0.0
    assert r["hops"] == 8 and r["per_hop_mb"] > 0
    assert x["hops"] == 7 and 0 < x["overlap_eligible_frac"] < 1
    # the async ring moves fewer gather bytes than the sync ring's
    # wasted final hop, and its reduce is point-to-point (half the
    # psum's 2x)
    assert x["gather_mb"] < r["gather_mb"]
    assert x["reduce_mb"] < r["reduce_mb"]
    # report lines name the strategy instead of assuming all2all
    line = comm_volume_report(dims_pad, 8, 8, ndev=8,
                              variant="async_ring")[0]
    assert "async ring" in line and "overlap-eligible" in line
    assert "all_gather" in comm_volume_report(dims_pad, 8, 8, ndev=8)[0]


def test_blocked_engine_rejected_for_async_ring():
    tt = gen.fixture_tensor("med")
    mesh = make_mesh(n_devices=8)
    with pytest.raises(ValueError, match="ring"):
        sharded_cpd_als(tt, rank=5, mesh=mesh,
                        opts=_opts(max_iterations=2,
                                   comm_pattern=CommPattern.ASYNC_RING),
                        local_engine="blocked")
