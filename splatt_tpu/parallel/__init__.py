from typing import Optional, Tuple

import numpy as np

from splatt_tpu.config import Decomposition, Options, default_opts
from splatt_tpu.coo import SparseTensor
from splatt_tpu.kruskal import KruskalTensor
from splatt_tpu.parallel.mesh import auto_grid, make_mesh
from splatt_tpu.parallel.sharded import sharded_cpd_als, sharded_mttkrp
from splatt_tpu.parallel.grid import GridDecomp, grid_cpd_als
from splatt_tpu.parallel.coarse import coarse_cpd_als


def distributed_cpd_als(tt: SparseTensor, rank: int,
                        opts: Optional[Options] = None,
                        init=None,
                        grid: Optional[Tuple[int, ...]] = None,
                        partition: Optional[np.ndarray] = None,
                        mesh=None,
                        row_distribute: Optional[str] = None,
                        checkpoint_path: Optional[str] = None,
                        checkpoint_every: int = 10,
                        resume: bool = True,
                        local_engine: Optional[str] = None,
                        out_dir: Optional[str] = None,
                        measure_overlap: Optional[bool] = None
                        ) -> KruskalTensor:
    """Distributed CPD-ALS, dispatching on ``opts.decomposition``
    (≙ SPLATT_OPTION_DECOMP, types_config.h:179-190):

    - MEDIUM (default): n-D grid, inputs local, outputs layer-psum'd
      (:func:`grid_cpd_als`)
    - COARSE: per-mode owner-computes copies, all_gather inputs, no
      output reduce (:func:`coarse_cpd_als`)
    - FINE: arbitrary nonzero placement (equal chunks, or a
      user-supplied per-nonzero `partition`), all_gather inputs +
      psum_scatter outputs (:func:`sharded_cpd_als`)

    `out_dir`: scratch directory for disk-backed decomposition arrays —
    with a memmapped tensor this makes the whole build out-of-core
    (streamed buckets + chunked counting-sort layouts), host RSS
    bounded at any scale.
    """
    opts = (opts or default_opts()).validate()
    ck = dict(checkpoint_path=checkpoint_path,
              checkpoint_every=checkpoint_every, resume=resume,
              out_dir=out_dir)
    # local_engine=None flows through unchanged: each driver's own
    # auto-detection picks "stream" for memmapped (beyond-RAM) tensors
    # and "blocked" otherwise — forcing "blocked" here would materialize
    # O(nnz) in-RAM sorted copies for exactly the inputs that can't.
    if opts.decomposition is Decomposition.MEDIUM and partition is None:
        if row_distribute is not None:
            raise ValueError("row_distribute applies to the FINE and "
                             "COARSE decompositions (the medium grid's "
                             "layer fences already localize inputs)")
        return grid_cpd_als(tt, rank, grid=grid, mesh=mesh, opts=opts,
                            init=init, local_engine=local_engine, **ck)
    if opts.decomposition is Decomposition.COARSE:
        if row_distribute not in (None, "balanced"):
            raise ValueError("COARSE supports row_distribute='balanced' "
                             "(nnz-weighted fences, docs/layout-"
                             "balance.md); 'greedy' is FINE-only")
        return coarse_cpd_als(tt, rank, mesh=mesh, opts=opts, init=init,
                              local_engine=local_engine,
                              row_distribute=row_distribute, **ck)
    return sharded_cpd_als(tt, rank, mesh=mesh, opts=opts, init=init,
                           partition=partition,
                           row_distribute=row_distribute,
                           local_engine=local_engine,
                           measure_overlap=measure_overlap, **ck)


__all__ = [
    "auto_grid",
    "make_mesh",
    "sharded_cpd_als",
    "sharded_mttkrp",
    "GridDecomp",
    "grid_cpd_als",
    "coarse_cpd_als",
    "distributed_cpd_als",
]
