"""SPL028 bad: a hot stream op mixing the declared-narrow factor
with the wide model matrix — the product materializes at f32 BEFORE
the accumulate point, doubling hot-loop bytes."""

import jax.numpy as jnp

from splatt_tpu.config import acc_dtype


def zz_stream(M, U, lam):
    acc = acc_dtype(M.dtype)
    # M is f32, U is bf16 (declared storage contract): M * U promotes
    # the whole stream to f32 before the reduce
    return jnp.sum(M * U, dtype=acc)
