"""Device mesh construction and grid auto-selection (≙ src/mpi/mpi_setup.c).

The reference builds an n-D cartesian MPI grid, auto-sizing it by
prime-factorizing the rank count onto the longest tensor modes
(p_get_best_mpi_dim, src/mpi/mpi_io.c:537-574).  On TPU the cartesian
grid is a `jax.sharding.Mesh`; layer communicators (per-mode
MPI_Comm_split, src/mpi/mpi_setup.c:201-243) are simply the mesh axis
names handed to collectives.
"""

from __future__ import annotations

from typing import List, Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh


def _prime_factors(n: int) -> List[int]:
    out: List[int] = []
    d = 2
    while d * d <= n:
        while n % d == 0:
            out.append(d)
            n //= d
        d += 1
    if n > 1:
        out.append(n)
    return sorted(out, reverse=True)


def auto_grid(n_devices: int, dims: Sequence[int]) -> Tuple[int, ...]:
    """Choose an n-D device grid for tensor `dims` (≙ p_get_best_mpi_dim).

    Greedy: hand each prime factor (largest first) to the mode with the
    most remaining length per grid slot.
    """
    grid = [1] * len(dims)
    for p in _prime_factors(n_devices):
        target = int(np.argmax([d / g for d, g in zip(dims, grid)]))
        grid[target] *= p
    return tuple(grid)


def make_mesh(n_devices: Optional[int] = None,
              axis_names: Sequence[str] = ("nnz",),
              grid: Optional[Sequence[int]] = None,
              devices: Optional[Sequence] = None) -> Mesh:
    """Build a Mesh over the available devices.

    Default is the 1-D ``('nnz',)`` mesh used by the medium-grain CPD:
    nonzeros and factor rows are both sharded over it (the reference's
    per-mode "layer" communicators collapse onto one axis when every
    mode is row-sharded the same way).
    """
    devs = list(devices if devices is not None else jax.devices())
    if n_devices is not None:
        devs = devs[:n_devices]
    n = len(devs)
    if grid is None:
        grid = (n,) if len(axis_names) == 1 else auto_grid(n, [1] * len(axis_names))
    mesh_devs = np.array(devs).reshape(tuple(grid))
    return Mesh(mesh_devs, tuple(axis_names))


def single_axis_of(mesh: Optional[Mesh], default_axis: str) -> Tuple[Optional[Mesh], str]:
    """Normalize a user mesh for the 1-D decompositions.

    Accepts a mesh with any single axis name (the caller's spec/axis
    arguments follow it); rejects multi-axis meshes with a clear error
    instead of a KeyError deep in a sharding.
    """
    if mesh is None:
        return None, default_axis
    if len(mesh.axis_names) != 1:
        raise ValueError(
            f"this decomposition needs a 1-D mesh; got axes "
            f"{mesh.axis_names} — build one with make_mesh(), or use the "
            f"MEDIUM grid decomposition for multi-axis meshes")
    return mesh, mesh.axis_names[0]
