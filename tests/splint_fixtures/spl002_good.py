"""SPL002 good: broad excepts that classify, re-raise, or are justified."""

from splatt_tpu import resilience


def classified(fn):
    try:
        return fn()
    except Exception as e:
        cls = resilience.classify_failure(e)
        resilience.run_report().add("probe_cache_io_error", op="load",
                                    failure_class=cls.value)
        return None


def reraises(fn):
    try:
        return fn()
    except Exception as e:
        raise RuntimeError("wrapped") from e


def narrow(fn):
    try:
        return fn()
    except (ValueError, OSError):
        return None


def justified(fn):
    try:
        return fn()
    # splint: ignore[SPL002] fixture: absence of the optional module is
    # the signal, not a failure
    except Exception:
        return None
