"""SPL025 good: dtype-aware sublane padding via config.tile_packing,
native-aligned literals, and grids over helper-padded extents."""

import jax
from jax.experimental import pallas as pl

from splatt_tpu.config import tile_packing
from splatt_tpu.utils.env import ceil_to


def _copy_kernel(x_ref, o_ref):
    o_ref[...] = x_ref[...]


def spl025_vmem_ok(block_elems):
    return 4 * block_elems * 2 <= 96 * 1024 * 1024


def good_dtype_aware_pad(x, R, width):
    R8 = ceil_to(R, tile_packing(x.dtype)[0])
    if not spl025_vmem_ok(R8 * width):
        raise ValueError("block too large for VMEM")
    return pl.pallas_call(
        _copy_kernel,
        grid=(1,),
        in_specs=[pl.BlockSpec((R8, width), lambda i: (0, 0))],
        out_specs=pl.BlockSpec((R8, width), lambda i: (0, 0)),
        out_shape=jax.ShapeDtypeStruct((R8, width), x.dtype),
    )(x)


def good_aligned_literals(x):
    if not spl025_vmem_ok(16 * 256):
        raise ValueError("block too large for VMEM")
    return pl.pallas_call(
        _copy_kernel,
        grid=(1,),
        in_specs=[pl.BlockSpec((16, 256), lambda i: (0, 0))],
        out_specs=pl.BlockSpec((16, 256), lambda i: (0, 0)),
        out_shape=jax.ShapeDtypeStruct((16, 256), x.dtype),
    )(x)


def good_padded_grid(x, nb):
    nb_pad = ceil_to(nb, 8)
    if not spl025_vmem_ok(8 * 128):
        raise ValueError("block too large for VMEM")
    return pl.pallas_call(
        _copy_kernel,
        grid=(nb_pad // 8,),
        in_specs=[pl.BlockSpec((8, 128), lambda i: (i, 0))],
        out_specs=pl.BlockSpec((8, 128), lambda i: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((8 * nb, 128), x.dtype),
    )(x)
