"""Comm-volume-aware factor-row distribution (≙ src/mpi/mpi_mat_distribute.c).

The reference's medium/fine decompositions assign factor rows to ranks
with a greedy comm-minimizing protocol: rows touched by exactly one
rank are auto-claimed, contested rows go to the rank that touches them
most, under capacity constraints, and the tensor is relabeled so each
rank's rows are contiguous (p_greedy_mat_distribution,
src/mpi/mpi_mat_distribute.c:436-548, perm applied :616-621).

On TPU the row-exchange collectives move statically-shaped blocks, so
ownership does not change the *wire volume* of all_gather/psum_scatter
— what it changes is **locality**: the fraction of a shard's factor-row
touches that land in its own fence.  That is exactly the quantity the
reference minimizes (its "ineed" lists), it is what a halo/ring
exchange pays for, and it is reported here the way
mpi_send_recv_stats reports comm volume (src/splatt_mpi.h:453-463).

Design: one host-side greedy pass per mode (vectorized numpy):

1. count touches T[row, shard] of each row by each nnz-shard;
2. visit rows by total touch count (hottest first, the rows whose
   placement matters most ≙ the claim-priority of the reference's
   work-queue protocol) and claim each for its heaviest-touching shard
   with fence capacity left;
3. label shard p's rows contiguously inside fence p (equal-width
   fences keep shapes static — the relabeling moves rows, not fences,
   like balanced_relabel does for nnz balance).
"""

from __future__ import annotations

from typing import List, Optional, Sequence, Tuple

import numpy as np

from splatt_tpu.utils.env import ceil_to


def touch_matrix(row_ids: np.ndarray, shard_of: np.ndarray, dim: int,
                 nshards: int) -> np.ndarray:
    """T[row, shard] = number of nonzeros of `shard` touching `row`."""
    flat = row_ids.astype(np.int64) * nshards + shard_of
    return np.bincount(flat, minlength=dim * nshards).reshape(dim, nshards)


def greedy_row_distribution(touch: np.ndarray, cap: int) -> np.ndarray:
    """Assign each row to a shard, minimizing non-local touches greedily.

    touch: (dim, nshards) touch counts; cap: fence capacity per shard
    (nshards*cap >= dim).  Returns (dim,) shard ids.  ≙ the claim logic
    of p_greedy_mat_distribution: single-toucher rows go home for free;
    contested rows go to their heaviest remaining toucher, hottest rows
    first.

    Vectorized as auction rounds: each unassigned row bids for its
    heaviest-touching shard that still has capacity; each shard accepts
    its hottest bidders up to capacity; losers re-bid next round.  A
    row is rejected only by a shard that fills in that round, so there
    are at most `nshards` rounds — million-row modes stay in numpy, not
    a per-row Python loop.
    """
    dim, nshards = touch.shape
    if nshards * cap < dim:
        raise ValueError(f"{nshards} fences x {cap} rows < {dim}")
    touch = touch.astype(np.int64)
    counts = np.zeros(nshards, dtype=np.int64)
    owner = np.full(dim, -1, dtype=np.int64)
    remaining = np.arange(dim)
    for _ in range(nshards):
        if remaining.size == 0:
            break
        avail = counts < cap
        tw = np.where(avail[None, :], touch[remaining], -1)
        bid = np.argmax(tw, axis=1)              # best available shard
        strength = tw[np.arange(remaining.size), bid]
        rejected = []
        for p in np.flatnonzero(avail):
            cand = np.flatnonzero(bid == p)
            room = cap - counts[p]
            if cand.size > room:
                # hottest bidders win (stable: ties keep row order)
                by_heat = cand[np.argsort(-strength[cand], kind="stable")]
                cand, spill = by_heat[:room], by_heat[room:]
                rejected.append(spill)
            owner[remaining[cand]] = p
            counts[p] += cand.size
        remaining = (remaining[np.sort(np.concatenate(rejected))]
                     if rejected else remaining[:0])
    return owner


def owner_to_relabel(owner: np.ndarray, nshards: int, cap: int) -> np.ndarray:
    """Contiguous labels inside each owner's fence: row r → label
    owner[r]*cap + slot (rows keep relative order within a fence,
    ≙ the contiguity relabeling of mpi_mat_distribute.c:616-621)."""
    dim = owner.shape[0]
    by_owner = np.lexsort((np.arange(dim), owner))
    counts = np.bincount(owner, minlength=nshards)
    starts = np.zeros(nshards, dtype=np.int64)
    np.cumsum(counts[:-1], out=starts[1:])
    slot = np.arange(dim) - starts[owner[by_owner]]
    relabel = np.empty(dim, dtype=np.int64)
    relabel[by_owner] = owner[by_owner] * cap + slot
    return relabel


def local_touch_fraction(row_ids: np.ndarray, shard_of: np.ndarray,
                         fence_cap: int) -> float:
    """Fraction of (nonzero, factor-row) touches that are shard-local —
    the complement of the reference's "ineed" volume."""
    if row_ids.size == 0:
        return 1.0
    return float(np.mean(row_ids // fence_cap == shard_of))


def comm_minimizing_relabels(
        inds: np.ndarray, dims: Sequence[int], nshards: int,
        shard_of: Optional[np.ndarray] = None
) -> Tuple[List[np.ndarray], List[dict]]:
    """Per-mode comm-minimizing row relabelings + before/after stats.

    `shard_of`: (nnz,) nnz→shard map (default: equal contiguous chunks,
    the sharded driver's layout).  Returns (relabels, stats) where
    relabels[m] maps old row id → new label in [0, nshards*cap_m), and
    stats[m] records the local-touch fraction before/after (the
    measurable ≙ of mpi_send_recv_stats volume reduction).
    """
    nmodes, nnz = inds.shape
    if shard_of is None:
        per = -(-nnz // nshards) if nnz else 1
        shard_of = np.minimum(np.arange(nnz) // per, nshards - 1)
    shard_of = np.asarray(shard_of, dtype=np.int64)
    relabels = []
    stats = []
    for m in range(nmodes):
        dim = int(dims[m])
        cap = ceil_to(max(dim, nshards), nshards) // nshards
        touch = touch_matrix(inds[m], shard_of, dim, nshards)
        owner = greedy_row_distribution(touch, cap)
        rl = owner_to_relabel(owner, nshards, cap)
        before = local_touch_fraction(inds[m], shard_of, cap)
        after = local_touch_fraction(rl[inds[m]], shard_of, cap)
        relabels.append(rl)
        stats.append(dict(mode=m, cap=cap, local_before=round(before, 4),
                          local_after=round(after, 4)))
    return relabels, stats
