"""SPL019 bad: torn-publish protocol violations — a sanctioned
publish helper missing the fsync steps, and an inline tmp-write →
rename publish outside the helpers."""

import json
import os


def publish_bytes(path, data):
    # configured atomic-publish helper, but the protocol is gutted: no
    # content fsync before the rename, no parent-dir fsync after it —
    # a crash can publish torn bytes, or lose the publish entirely
    tmp = f"{path}.tmp"
    with open(tmp, "wb") as f:
        f.write(data)
    os.replace(tmp, path)


def commit_inline(path, record):
    # inline re-implementation of the publish protocol: this function
    # writes the tmp file AND renames it into place itself, bypassing
    # the audited chokepoint
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        f.write(json.dumps(record))
    os.replace(tmp, path)
