"""Fault injection harness — the test hook points of the resilience layer.

Resilience code that only runs when the infrastructure misbehaves is
dead code until the day it matters; this module makes the misbehavior
reproducible.  Production call sites (probe compiles, engine dispatch,
checkpoint writes) call :func:`maybe_fail`/:func:`consume` with a site
name; tests (or an operator, via env var) arm faults against those
sites and the real error-handling paths execute.

Arming a fault
    - context manager (tests)::

        with faults.inject("probe_compile", "http500", times=2):
            ...   # the first two probe compiles raise an HTTP 500

    - env var (whole-process, e.g. under the CLI)::

        SPLATT_FAULTS="probe_compile:http500:2,engine.fused_t:runtime"

      Comma-separated ``site:kind[:times]`` specs; ``times`` defaults
      to 1, ``*`` means every call.

Sites used by the production code:
    - ``probe_compile``          — the capability-probe remote compile
    - ``engine.<name>``          — an MTTKRP dispatch engine at call
      time (e.g. ``engine.fused_t``, ``engine.xla_scan``)
    - ``checkpoint_write``       — raise during the checkpoint save
    - ``checkpoint_torn``        — consumed (not raised): the writer
      truncates the bytes it just wrote, simulating a torn write
    - ``tuner.measure``          — one autotuner candidate measurement
      (tune.py)

Fault kinds map to canned exceptions whose messages exercise specific
:func:`splatt_tpu.resilience.classify_failure` branches:

    ========== ==================================== ===============
    kind       message signature                    classifies as
    ========== ==================================== ===============
    http500    ``... HTTP code 500``                transient
    internal   ``INTERNAL: ...``                    transient
    unavailable ``UNAVAILABLE: ...``                transient
    timeout    ``TimeoutError``                     transient
    oom        ``RESOURCE_EXHAUSTED: ...``          resource
    mosaic     ``Mosaic ...``                       deterministic
    runtime    generic runtime failure              unknown
    ========== ==================================== ===============

The registry is process-local and the checks are O(1) dict lookups on
cold paths only (probes, dispatch resolution, checkpoint IO) — never
inside a kernel.
"""

from __future__ import annotations

import contextlib
import dataclasses
import threading
from typing import Dict, Optional

_FAULTS_ENV = "SPLATT_FAULTS"

#: times value meaning "every call"
ALWAYS = -1

#: The declared fault sites of the production code, site → doc.  A
#: trailing ``.*`` marks a dynamic family (the production call passes
#: an f-string with that prefix).  This registry is load-bearing, not
#: documentation-only: `splint` rule SPL006 checks that every site
#: string the production code passes to :func:`maybe_fail` /
#: :func:`consume` is declared here, that every declared site is still
#: called somewhere, and that every declared site is exercised by at
#: least one test — so a renamed hook cannot silently orphan the
#: resilience path it was built to exercise.  (Tests may arm ad-hoc
#: sites to test the harness itself; those need no declaration.)
SITES = {
    "probe_compile": "the capability-probe remote compile "
                     "(ops/pallas_kernels.py)",
    "engine.*": "an MTTKRP dispatch engine at call time, e.g. "
                "engine.fused_t / engine.xla_scan (ops/mttkrp.py)",
    "checkpoint_write": "raise during the checkpoint save (cpd.py)",
    "checkpoint_torn": "consumed (not raised): the writer truncates "
                       "the bytes it just wrote, simulating a torn "
                       "write (cpd.py)",
    "tuner.measure": "one autotuner candidate measurement — warm + "
                     "timed MTTKRP runs of a forced engine (tune.py); "
                     "a crashing measurement must degrade dispatch to "
                     "the heuristic chain, never fail the run",
}


def _canned(kind: str, site: str) -> Exception:
    if kind == "http500":
        return RuntimeError(
            f"XLA:TPU compile failed: HTTP code 500 from remote compile "
            f"service (injected fault at {site})")
    if kind == "internal":
        return RuntimeError(
            f"INTERNAL: injected transient service failure at {site}")
    if kind == "unavailable":
        return RuntimeError(
            f"UNAVAILABLE: injected relay failure at {site}")
    if kind == "timeout":
        return TimeoutError(f"injected deadline expiry at {site}")
    if kind == "oom":
        return RuntimeError(
            f"RESOURCE_EXHAUSTED: injected out-of-memory at {site} "
            f"(attempting to allocate 128.00G)")
    if kind == "mosaic":
        return RuntimeError(
            f"Mosaic failed to compile the injected kernel at {site}")
    if kind == "runtime":
        return RuntimeError(f"injected engine runtime failure at {site}")
    raise ValueError(f"unknown fault kind {kind!r}")


@dataclasses.dataclass
class FaultSpec:
    """One armed fault: what to raise and how many calls it covers."""

    kind: str
    times: int = 1          # remaining trigger count; ALWAYS = unbounded
    exc: Optional[Exception] = None   # overrides the canned exception
    fired: int = 0          # how often it actually triggered


_LOCK = threading.Lock()
_ACTIVE: Dict[str, FaultSpec] = {}
_env_loaded = False


def _load_env_locked() -> None:
    global _env_loaded
    if _env_loaded:
        return
    _env_loaded = True
    from splatt_tpu.utils.env import read_env

    raw = read_env(_FAULTS_ENV)
    for item in raw.split(","):
        item = item.strip()
        if not item:
            continue
        # every malformation is warn-and-ignore: a typo in a fault spec
        # must not kill the production run at some random hook site
        try:
            parts = item.split(":")
            if len(parts) not in (2, 3):
                raise ValueError("want site:kind[:times]")
            site, kind = parts[0].strip(), parts[1].strip()
            times = 1
            if len(parts) == 3:
                times = ALWAYS if parts[2].strip() == "*" \
                    else int(parts[2])
            _canned(kind, site)  # validate the kind at arm time
        except (ValueError, TypeError) as e:
            import sys

            print(f"splatt-tpu: bad {_FAULTS_ENV} entry {item!r} "
                  f"({e}); ignored", file=sys.stderr)
            continue
        _ACTIVE[site] = FaultSpec(kind=kind, times=times)


def _take(site: str) -> Optional[FaultSpec]:
    """Claim one firing of the fault armed at `site`, if any."""
    with _LOCK:
        _load_env_locked()
        spec = _ACTIVE.get(site)
        if spec is None or spec.times == 0:
            return None
        if spec.times != ALWAYS:
            spec.times -= 1
        spec.fired += 1
        return spec


def maybe_fail(site: str) -> None:
    """Production hook: raise the armed fault for `site`, if any.
    A no-op (one dict lookup) when nothing is armed."""
    spec = _take(site)
    if spec is not None:
        raise spec.exc if spec.exc is not None else _canned(spec.kind, site)


def consume(site: str) -> bool:
    """Production hook for non-raising faults (e.g. torn writes): True
    when a fault was armed at `site` (and claims one firing)."""
    return _take(site) is not None


def active(site: str) -> bool:
    """Whether a fault is currently armed at `site` (no claim)."""
    with _LOCK:
        _load_env_locked()
        spec = _ACTIVE.get(site)
        return spec is not None and spec.times != 0


@contextlib.contextmanager
def inject(site: str, kind: str = "runtime", times: int = 1,
           exc: Optional[Exception] = None):
    """Arm a fault at `site` for the duration of the block (tests).
    `times` bounds how many calls trigger (ALWAYS = every call); `exc`
    substitutes a custom exception for the canned one."""
    if exc is None:
        _canned(kind, site)  # validate early
    spec = FaultSpec(kind=kind, times=times, exc=exc)
    with _LOCK:
        _load_env_locked()
        prev = _ACTIVE.get(site)
        _ACTIVE[site] = spec
    try:
        yield spec
    finally:
        with _LOCK:
            if prev is None:
                _ACTIVE.pop(site, None)
            else:
                _ACTIVE[site] = prev


def reset() -> None:
    """Disarm everything and forget the env parse (tests)."""
    global _env_loaded
    with _LOCK:
        _ACTIVE.clear()
        _env_loaded = False
