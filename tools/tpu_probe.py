"""Probe the tunneled TPU chip: claim, run a tiny matmul, smoke-test the
Pallas kernels compiled for real hardware (not interpret mode), write
results to tools/tpu_probe_result.json.

Single-lease discipline: exactly one process, exits cleanly on success.
An internal alarm aborts a claim that never completes (writes a timeout
record first) so the process doesn't linger into the driver's own bench
run at round end.
"""
from __future__ import annotations

import json
import os
import signal
import sys
import time

RESULT = os.path.join(os.path.dirname(__file__), "tpu_probe_result.json")
CLAIM_TIMEOUT = int(os.environ.get("TPU_PROBE_TIMEOUT", "2700"))  # 45 min


def write(obj):
    obj["ts"] = time.time()
    with open(RESULT, "w") as f:
        json.dump(obj, f, indent=1)
    print(json.dumps(obj), flush=True)


def on_alarm(signum, frame):
    write({"ok": False, "stage": STAGE[0], "error": f"timeout after {CLAIM_TIMEOUT}s"})
    os._exit(3)


STAGE = ["claim"]
signal.signal(signal.SIGALRM, on_alarm)
signal.alarm(CLAIM_TIMEOUT)

t0 = time.time()
try:
    import jax
    import jax.numpy as jnp

    devs = jax.devices()
    claim_s = time.time() - t0
    d = devs[0]
    info = {
        "ok": True,
        "claim_s": round(claim_s, 1),
        "platform": d.platform,
        "device_kind": getattr(d, "device_kind", "?"),
        "n_devices": len(devs),
    }
    STAGE[0] = "matmul"
    x = jnp.ones((1024, 1024), jnp.bfloat16)
    y = (x @ x).block_until_ready()
    t1 = time.time()
    for _ in range(10):
        y = (x @ x).block_until_ready()
    info["matmul_1k_bf16_ms"] = round((time.time() - t1) / 10 * 1e3, 3)

    STAGE[0] = "memstats"
    try:
        ms = d.memory_stats() or {}
        info["hbm_limit_gb"] = round(ms.get("bytes_limit", 0) / 2**30, 2)
    except Exception as e:  # pragma: no cover
        info["memstats_error"] = str(e)

    # Pallas smoke: compile + run the round-1 one-hot reduce kernel for real.
    STAGE[0] = "pallas"
    try:
        sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
        import numpy as np
        from splatt_tpu.ops import pallas_kernels as pk

        rng = np.random.default_rng(0)
        nb, B, R, width = 32, 256, 32, 64
        local = jnp.asarray(rng.integers(0, width, (nb, B)).astype(np.int32))
        prod = jnp.asarray(rng.standard_normal((nb, B, R)).astype(np.float32))
        out = pk.onehot_reduce_full(local, prod, width, interpret=False)
        out.block_until_ready()
        ref = jax.ops.segment_sum(prod.reshape(-1, R), local.reshape(-1),
                                  num_segments=width)
        err = float(jnp.max(jnp.abs(out - ref)))
        info["pallas_onehot"] = {"ok": bool(err < 1e-2), "max_err": err}

        STAGE[0] = "pallas_sorted"
        # sorted variant too (the flagship path's engine)
        loc2 = jnp.asarray(np.sort(
            rng.integers(0, width, (nb, B)), axis=1).astype(np.int32))
        out2 = pk.onehot_reduce_sorted(loc2, prod, width, interpret=False)
        out2.block_until_ready()
        ref2 = jax.vmap(lambda l, p: jax.ops.segment_sum(
            p, l, num_segments=width))(loc2, prod)
        err2 = float(jnp.max(jnp.abs(out2 - ref2)))
        info["pallas_sorted"] = {"ok": bool(err2 < 1e-2), "max_err": err2}

        STAGE[0] = "pallas_fused"
        # fused kernel (gather+Hadamard+reduce in VMEM) — the round-2
        # flagship; exercises in-kernel jnp.take lowering on Mosaic
        import importlib

        from splatt_tpu.blocked import build_layout
        from splatt_tpu.coo import SparseTensor

        # `from splatt_tpu.ops import mttkrp` resolves to the *function*
        # re-exported by ops/__init__, not the module — load the module.
        mk = importlib.import_module("splatt_tpu.ops.mttkrp")

        dims = (96, 80, 112)
        nz = 4096
        tinds = np.stack([rng.integers(0, d, nz) for d in dims]).astype(np.int64)
        tvals = rng.standard_normal(nz)
        tt = SparseTensor(inds=tinds, vals=tvals, dims=dims)
        fac = [jnp.asarray(rng.standard_normal((d, 32)).astype(np.float32))
               for d in dims]
        lay = build_layout(tt, 0, block=512, val_dtype=np.float32)
        from splatt_tpu.ops.pallas_kernels import (fused_t_supported,
                                                   fused_tg_supported,
                                                   probe_regime)

        # Record whether the LIVE fused kernels (fused_t, then the
        # sublane-tiled fused_tg fallback) can lower on this jax/Mosaic,
        # or whether dispatch fell back to the unfused kernels — probed
        # at THIS config's regime/block so the recorded verdict is the
        # one the dispatch below actually consults.  (The dead row-major
        # fused kernel lost its probe slot: VERDICT r4 weak #5.)
        regime = probe_regime(dims[1:], lay.block)
        info["fused_t_supported"] = fused_t_supported(regime, lay.block)
        # lazy, like dispatch: the fallback kernel is only probed when
        # the flagship lost — each probe is a remote compile (~35 s) of
        # scarce claim-window time, and the kernel head-to-head stage
        # probes (and persists) fused_tg itself when it runs
        if not info["fused_t_supported"]:
            info["fused_tg_supported"] = fused_tg_supported(regime,
                                                            lay.block)
        got = mk.mttkrp_blocked(lay, fac, 0, path="sorted_onehot",
                                impl="pallas")
        got.block_until_ready()
        ref3 = mk.mttkrp_stream(jnp.asarray(tinds),
                                jnp.asarray(tvals, jnp.float32), fac, 0,
                                dims[0])
        err3 = float(jnp.max(jnp.abs(got - ref3)))
        info["pallas_fused"] = {"ok": bool(err3 < 1e-2), "max_err": err3}
    except Exception as e:
        info["pallas_" + {"pallas": "onehot", "pallas_sorted": "sorted",
                          "pallas_fused": "fused"}.get(STAGE[0], "onehot")] = {
            "ok": False, "error": f"{type(e).__name__}: {e}"}

    signal.alarm(0)
    write(info)
except Exception as e:
    signal.alarm(0)
    write({"ok": False, "stage": STAGE[0], "error": f"{type(e).__name__}: {e}",
           "elapsed_s": round(time.time() - t0, 1)})
    sys.exit(2)
