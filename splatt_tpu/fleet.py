"""Fleet membership for `splatt serve` — leases, heartbeats, adoption
(docs/fleet.md; ROADMAP open item 5).

One daemon process cannot serve the million-user workload, and N
*independent* daemons would each cold-start their own probe/tune/
compile caches and strand their jobs when they die.  This module is
the membership layer that turns N `splatt serve` replicas over one
shared spool into a FLEET:

Replica heartbeats
    Every replica maintains a lease file
    ``<root>/fleet/replicas/<replica>.json`` carrying ``{replica, pid,
    ts, expires, regimes, active}`` and renews it every
    ``heartbeat_s`` seconds.  A replica whose heartbeat expiry is in
    the past is DEAD as far as the fleet is concerned — there is no
    other failure detector.  The ``regimes`` list advertises the shape
    regimes whose probe/tune/compile caches this replica has already
    warmed (the affinity-routing signal, serve.py), ``active`` its
    current job-lease count (the load tiebreaker).

Job leases — ownership is a lease, not an assumption
    A replica may only RUN a job while it holds the job's lease file
    ``<root>/fleet/leases/<job>.json``.  The protocol is flock +
    atomic rename: every lease mutation happens under an exclusive
    ``flock`` on the job's ``.lock`` sidecar (two racing replicas
    serialize), reads the current lease inside the lock, decides, and
    publishes the new lease by tmp-write + ``os.replace`` (a reader
    outside the lock never sees a torn lease).  The rules:

    - :meth:`acquire` claims an absent lease, or renews one this
      replica already holds.  A lease validly held by a peer — and an
      EXPIRED lease, which only :meth:`adopt` may take — refuses.
    - :meth:`renew` extends a held lease, and REFUSES once the lease
      expired or changed hands (even if nobody re-took it yet:
      ownership must be continuous, a gap means a peer may have run
      the job meanwhile).  The owner learns it lost the job and stops
      at its next cooperative poll.
    - :meth:`adopt` takes over an expired lease (bumping the ``gen``
      counter so the previous owner's stale renew can never match) —
      the crash-failover path: a dead replica's non-terminal jobs are
      adopted by a live peer and resume from their hardened
      checkpoints.

    Expiry is the fence: a replica whose lease expired gets its renew
    refused at the next poll and abandons the job without committing
    anything further, while the adopter resumes from the last
    checkpoint.  (Between expiry and that poll the old owner may still
    be *computing* — but it can no longer journal a terminal record or
    keep the result, so the job's durable lineage stays single-owner.)

Fault sites (docs/resilience.md): ``fleet.lease_acquire`` (one atomic
lease acquisition), ``fleet.heartbeat`` (one membership heartbeat +
held-lease renewal sweep), ``fleet.adopt`` (one dead-peer takeover) —
each degrades classified, never killing the replica.
"""

from __future__ import annotations

import contextlib
import dataclasses
import json
import os
import threading
import time
import uuid
from typing import Callable, Dict, List, Optional

try:
    import fcntl
except ImportError:  # non-POSIX: leases degrade to rename-only
    fcntl = None

#: heartbeat cadence when SPLATT_FLEET_HEARTBEAT_S is unset/<=0:
#: renew this many times per lease window
_BEATS_PER_LEASE = 3.0


@dataclasses.dataclass
class Lease:
    """One published job lease: who owns the job until when.  ``gen``
    increments at every takeover, so a stale owner's renew (matching
    on replica AND gen) can never revive a lease that changed hands
    and came back."""

    job: str
    replica: str
    ts: float
    expires: float
    gen: int = 1

    def expired(self, now: Optional[float] = None) -> bool:
        return (now if now is not None else time.time()) >= self.expires

    def to_json(self) -> dict:
        return dataclasses.asdict(self)


class FleetMember:
    """This replica's view of the fleet: its own heartbeat, the leases
    it holds, and the liveness/warmth of its peers (serve.py drives
    one of these per fleet-mode daemon)."""

    def __init__(self, root: str, replica: Optional[str] = None,
                 lease_s: Optional[float] = None,
                 heartbeat_s: Optional[float] = None,
                 clock: Optional[Callable[[], float]] = None):
        from splatt_tpu.utils.env import read_env, read_env_float

        self.root = os.path.abspath(root)
        self.dir = os.path.join(self.root, "fleet")
        self.replicas_dir = os.path.join(self.dir, "replicas")
        self.leases_dir = os.path.join(self.dir, "leases")
        for d in (self.dir, self.replicas_dir, self.leases_dir):
            os.makedirs(d, exist_ok=True)
        rid = replica or read_env("SPLATT_FLEET_REPLICA") \
            or f"r-{os.getpid()}-{uuid.uuid4().hex[:6]}"
        self.replica = _safe_name(str(rid))
        self.lease_s = float(lease_s if lease_s is not None
                             else read_env_float("SPLATT_FLEET_LEASE_S"))
        hb = float(heartbeat_s if heartbeat_s is not None
                   else read_env_float("SPLATT_FLEET_HEARTBEAT_S"))
        self.heartbeat_s = hb if hb > 0 \
            else max(self.lease_s / _BEATS_PER_LEASE, 0.05)
        #: the protocol's time source.  Production uses the wall clock;
        #: the bounded-exhaustive interleaving checker
        #: (tools/splint/interleave.py, docs/fleet.md) injects a
        #: virtual clock so lease expiry becomes a schedule step it can
        #: enumerate instead of a race it must win.
        self._clock = clock if clock is not None else time.time
        # declared shared structures ([tool.splint] shared-state);
        # owner-assertion proxies under SPLATT_LOCKCHECK, pass-through
        # otherwise (utils/lockcheck.py, the SPL014 dynamic cross-check)
        from splatt_tpu.utils import lockcheck

        self._lock = lockcheck.guard_lock(threading.Lock())
        self._held: Dict[str, Lease] = lockcheck.guard(
            {}, self._lock, "fleet.FleetMember._held")
        self._lost: set = lockcheck.guard(
            set(), self._lock, "fleet.FleetMember._lost")
        self._regimes: set = lockcheck.guard(
            set(), self._lock, "fleet.FleetMember._regimes")
        #: this replica's Prometheus snapshot path, advertised in the
        #: heartbeat so the fleet aggregator (fleetobs.py) finds every
        #: replica's metrics without configuration; write-once at
        #: serve startup, before the heartbeat thread exists
        self.metrics_path: Optional[str] = None

    # -- flock + atomic-rename primitives ------------------------------------

    @contextlib.contextmanager
    def _locked(self, jid: str):
        """Exclusive advisory lock on the job's ``.lock`` sidecar —
        the mutual-exclusion half of the lease protocol (two replicas
        racing an acquire/renew/adopt serialize here; the atomic
        rename below makes the published lease torn-proof for
        lock-free readers)."""
        path = os.path.join(self.leases_dir, f"{_safe_name(jid)}.lock")
        f = open(path, "a")
        try:
            if fcntl is not None:
                fcntl.flock(f.fileno(), fcntl.LOCK_EX)
            yield
        finally:
            if fcntl is not None:
                fcntl.flock(f.fileno(), fcntl.LOCK_UN)
            f.close()

    def _lease_path(self, jid: str) -> str:
        return os.path.join(self.leases_dir, f"{_safe_name(jid)}.json")

    def _write_lease(self, lease: Lease) -> None:
        from splatt_tpu.utils.durable import publish_json

        publish_json(self._lease_path(lease.job), lease.to_json())

    def lease_of(self, jid: str) -> Optional[Lease]:
        """The published lease for `jid`, or None (lock-free read —
        the atomic rename guarantees an untorn file; a malformed one
        reads as absent, i.e. claimable)."""
        try:
            with open(self._lease_path(jid)) as f:
                rec = json.load(f)
            return Lease(job=str(rec["job"]), replica=str(rec["replica"]),
                         ts=float(rec["ts"]), expires=float(rec["expires"]),
                         gen=int(rec.get("gen", 1)))
        except (OSError, ValueError, KeyError, TypeError):
            return None

    # -- the lease state machine ---------------------------------------------

    def acquire(self, jid: str) -> bool:
        """Claim the job's lease: absent → this replica's; already
        ours and unexpired → renewed; held by a peer, or EXPIRED
        (stale leases are :meth:`adopt`'s, which audits the takeover)
        → refused.  Exactly one of N racing replicas wins."""
        from splatt_tpu.utils import faults

        faults.maybe_fail("fleet.lease_acquire")
        now = self._clock()
        with self._locked(jid):
            cur = self.lease_of(jid)
            if cur is not None:
                if cur.replica != self.replica or cur.expired(now):
                    return False
                lease = Lease(job=jid, replica=self.replica, ts=now,
                              expires=now + self.lease_s, gen=cur.gen)
            else:
                lease = Lease(job=jid, replica=self.replica, ts=now,
                              expires=now + self.lease_s, gen=1)
            self._write_lease(lease)
        with self._lock:
            self._held[jid] = lease
            self._lost.discard(jid)
        return True

    def renew(self, jid: str) -> bool:
        """Extend a held lease.  Refuses — and drops the job into the
        :meth:`lost` set — when the published lease is gone, expired,
        or no longer this replica's generation: ownership must be
        continuous, so an expired lease is never revived even if no
        peer re-took it yet."""
        with self._lock:
            held = self._held.get(jid)
        if held is None:
            return False
        now = self._clock()
        with self._locked(jid):
            cur = self.lease_of(jid)
            if (cur is None or cur.replica != self.replica
                    or cur.gen != held.gen or cur.expired(now)):
                self._mark_lost(jid)
                return False
            lease = Lease(job=jid, replica=self.replica, ts=now,
                          expires=now + self.lease_s, gen=cur.gen)
            self._write_lease(lease)
        with self._lock:
            self._held[jid] = lease
        return True

    def adopt(self, jid: str) -> bool:
        """Take over an EXPIRED lease (or claim an absent one) — the
        failover path for a dead peer's jobs.  Bumps ``gen`` so the
        previous owner's stale renew can never match.  Refuses while
        the lease is validly held."""
        from splatt_tpu.utils import faults

        faults.maybe_fail("fleet.adopt")
        now = self._clock()
        with self._locked(jid):
            cur = self.lease_of(jid)
            if cur is not None and not cur.expired(now) \
                    and cur.replica != self.replica:
                return False
            gen = (cur.gen + 1) if cur is not None else 1
            lease = Lease(job=jid, replica=self.replica, ts=now,
                          expires=now + self.lease_s, gen=gen)
            self._write_lease(lease)
        with self._lock:
            self._held[jid] = lease
            self._lost.discard(jid)
        return True

    def release(self, jid: str) -> None:
        """Drop a held lease (the job reached a terminal state).  A
        lease we no longer own is left alone — the current owner's."""
        with self._lock:
            held = self._held.pop(jid, None)
            self._lost.discard(jid)
        if held is None:
            return
        with self._locked(jid):
            cur = self.lease_of(jid)
            if cur is not None and cur.replica == self.replica \
                    and cur.gen == held.gen:
                try:
                    os.unlink(self._lease_path(jid))
                    # the .lock sidecar too, or leases/ grows one
                    # file per job forever.  Job ids are never reused
                    # after a terminal release, so a racer blocked on
                    # the old inode just re-reads an absent lease.
                    os.unlink(os.path.join(
                        self.leases_dir, f"{_safe_name(jid)}.lock"))
                except OSError:
                    pass

    def lost(self, jid: str) -> bool:
        """Whether this replica's lease on `jid` was lost (renew
        refused) — the running job's cooperative stop-poll checks this
        and abandons without committing anything further."""
        with self._lock:
            return jid in self._lost

    def held(self) -> List[str]:
        with self._lock:
            return sorted(self._held)

    def _mark_lost(self, jid: str) -> None:
        with self._lock:
            if jid in self._held:
                del self._held[jid]
                self._lost.add(jid)
        from splatt_tpu import resilience, trace

        resilience.run_report().add(
            "lease_expired", job=jid, replica=self.replica, role="owner")
        trace.metric_inc("splatt_fleet_lease_expired_total", role="owner")

    # -- membership heartbeat ------------------------------------------------

    def beat(self) -> List[str]:
        """One heartbeat tick: publish this replica's membership lease
        (liveness + warm regimes + load) and renew every held job
        lease.  Returns the jobs whose renewal was refused this tick.
        Any failure degrades classified — a missed beat makes this
        replica look dead sooner (peers adopt after ``lease_s``),
        which is the documented failure mode, not a crash."""
        from splatt_tpu import resilience
        from splatt_tpu.utils import faults

        lost: List[str] = []
        try:
            faults.maybe_fail("fleet.heartbeat")
            now = self._clock()
            with self._lock:
                regimes = sorted(self._regimes)
                active = len(self._held)
                held = list(self._held)
            rec = {"replica": self.replica, "pid": os.getpid(),
                   "ts": now, "expires": now + self.lease_s,
                   "regimes": regimes, "active": active}
            if self.metrics_path:
                rec["metrics"] = self.metrics_path
            from splatt_tpu.utils.durable import publish_json

            publish_json(os.path.join(self.replicas_dir,
                                      f"{self.replica}.json"), rec)
            for jid in held:
                if not self.renew(jid):
                    lost.append(jid)
        except Exception as e:
            cls = resilience.classify_failure(e)
            import sys

            print(f"splatt-fleet[{self.replica}]: heartbeat degraded "
                  f"({cls.value}: "
                  f"{resilience.failure_message(e)[:120]}); peers may "
                  f"adopt after {self.lease_s:g}s", file=sys.stderr)
        return lost

    def peers(self) -> Dict[str, dict]:
        """Live peers (unexpired heartbeats, this replica excluded):
        replica -> its heartbeat record.  Dead/malformed heartbeat
        files read as absent."""
        out: Dict[str, dict] = {}
        now = self._clock()
        try:
            names = os.listdir(self.replicas_dir)
        except OSError:
            return out
        for name in names:
            if not name.endswith(".json"):
                continue
            try:
                with open(os.path.join(self.replicas_dir, name)) as f:
                    rec = json.load(f)
                rid = str(rec["replica"])
                if rid == self.replica:
                    continue
                if float(rec.get("expires", 0)) > now:
                    out[rid] = rec
            except (OSError, ValueError, KeyError, TypeError):
                continue
        return out

    def replica_alive(self, rid: str) -> bool:
        """Whether `rid`'s membership lease is current (itself = yes)."""
        if rid == self.replica:
            return True
        return rid in self.peers()

    def retire(self) -> None:
        """Remove this replica's heartbeat (graceful exit): peers stop
        routing around it immediately instead of waiting out the lease."""
        try:
            os.unlink(os.path.join(self.replicas_dir,
                                   f"{self.replica}.json"))
        except OSError:
            pass

    # -- warm-regime advertisement (affinity routing, serve.py) --------------

    def add_regime(self, key: Optional[str]) -> None:
        """Advertise a shape regime as warm on this replica (published
        at the next beat)."""
        if key:
            with self._lock:
                self._regimes.add(str(key))

    def warm(self, key: Optional[str]) -> bool:
        """Whether this replica's caches are warm for `key`."""
        if not key:
            return False
        with self._lock:
            return key in self._regimes

    def peer_warm(self, key: Optional[str],
                  peers: Optional[Dict[str, dict]] = None
                  ) -> Optional[str]:
        """The least-loaded live peer advertising `key` warm, or None
        (`peers` reuses a snapshot from :meth:`peers`)."""
        if not key:
            return None
        if peers is None:
            peers = self.peers()
        best = None
        for rid, rec in sorted(peers.items()):
            if key in (rec.get("regimes") or []):
                load = int(rec.get("active", 0))
                if best is None or load < best[0]:
                    best = (load, rid)
        return best[1] if best else None

    def active_count(self) -> int:
        with self._lock:
            return len(self._held)


def _safe_name(name: str) -> str:
    """Replica/job ids become file names; serve._job_id already
    restricts job ids, this guards replica ids from the same escapes."""
    import re

    if not re.match(r"^[A-Za-z0-9][A-Za-z0-9._-]{0,63}$", name):
        raise ValueError(
            f"fleet name {name!r} is not filesystem-safe (want "
            f"[A-Za-z0-9][A-Za-z0-9._-]*, max 64 chars)")
    return name


def job_regime(spec: dict) -> Optional[str]:
    """The shape-regime key of a job spec — the affinity-routing
    signal (docs/fleet.md).  Matches the tune/probe cache granularity
    (power-of-two dim/nnz buckets + rank), so 'same regime' means
    'hits the same warm plans'.  File-tensor jobs return None (the
    shape is unknown without loading; they route by load only), and
    so do predicts — the low-latency read lane must never wait on
    affinity deferral or coalescing (docs/predict.md)."""
    if str(spec.get("kind") or "cpd") == "predict":
        return None
    syn = spec.get("synthetic")
    if not isinstance(syn, dict) or not syn.get("dims"):
        return None
    from splatt_tpu.tune import shape_regime

    try:
        dims = [int(d) for d in syn["dims"]]
        nnz = int(syn.get("nnz", 1000))
        rank = int(spec.get("rank", 8))
    except (TypeError, ValueError):
        return None
    return f"{shape_regime(dims, nnz)}:r{rank}"
