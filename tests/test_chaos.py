"""Chaos-schedule soak harness: the tier-1 invariant drill.

`splatt chaos` runs a real seeded CPD under injected NaNs + blown
deadlines + transient failures and asserts converged-or-gracefully-
degraded with zero unhandled exceptions and a complete run report
(docs/guarded-als.md).  The --smoke entry here is the acceptance
criterion exercised on every PR.
"""

import json

import pytest

from splatt_tpu import chaos, resilience, tune
from splatt_tpu.utils import faults


@pytest.fixture(autouse=True)
def _clean_state():
    resilience.reset_demotions()
    resilience.run_report().clear()
    resilience.set_deadline(None)
    faults.reset()
    tune.set_cache_path(None)
    yield
    resilience.reset_demotions()
    resilience.run_report().clear()
    resilience.set_deadline(None)
    faults.reset()
    tune.set_cache_path(None)


def test_chaos_smoke_invariant_holds(capsys):
    """Acceptance: the seeded NaN+timeout+transient smoke soak finishes
    with exit code 0, zero unhandled exceptions, matching health_*/
    deadline/transient events in the run report, and finite factors."""
    from splatt_tpu.cli import main

    rc = main(["chaos", "--smoke"])
    out = capsys.readouterr().out
    assert rc == 0, out
    assert "chaos verdict: CONVERGED" in out or \
           "chaos verdict: DEGRADED" in out
    assert "INVARIANT VIOLATED" not in out
    # each default-schedule leg left its evidence in the printed report
    assert "rolled back to the last-good snapshot" in out
    assert "deadline watchdog blew at tuner.measure" in out
    assert "transient failure(s) retried" in out


def test_chaos_smoke_json(capsys):
    from splatt_tpu.cli import main

    rc = main(["chaos", "--smoke", "--json"])
    out = capsys.readouterr().out
    assert rc == 0
    rec = json.loads([l for l in out.splitlines()
                      if l.startswith("{")][-1])
    assert rec["verdict"] in ("converged", "degraded")
    assert rec["violations"] == []
    assert rec["finite"] is True
    kinds = {e["kind"] for e in rec["events"]}
    assert {"health_nonfinite", "health_rollback",
            "deadline_blown", "transient_retry"} <= kinds
    # every emitted kind is declared (the report is complete)
    assert kinds <= set(resilience.RUN_REPORT_EVENTS)
    # all three armed legs actually fired
    assert all(n > 0 for n in rec["fired"].values())


def test_chaos_custom_schedule_budget_exhaustion():
    """An always-on NaN schedule exhausts the rollback budget: the run
    must DEGRADE (explicit verdict), not violate the invariant."""
    res = chaos.run_chaos(schedule="cpd.sweep:nan:*", smoke=True)
    assert res.ok, res.violations
    assert res.verdict == "degraded"
    assert any(e["kind"] == "health_degraded" for e in res.events)


def test_chaos_probabilistic_schedule_is_seeded():
    """A p-schedule run is replayable: same seed, same firing counts,
    same verdict."""
    a = chaos.run_chaos(
        schedule="cpd.sweep:nan:p=0.4:seed=11:*", smoke=True)
    b = chaos.run_chaos(
        schedule="cpd.sweep:nan:p=0.4:seed=11:*", smoke=True)
    assert a.ok and b.ok, (a.violations, b.violations)
    assert a.fired == b.fired
    assert a.verdict == b.verdict


def test_chaos_detects_silent_degradation():
    """The invariant checker itself works: a fired fault with no
    matching run-report evidence is flagged.  (Simulated by checking a
    result object directly — the production paths always report.)"""
    res = chaos.run_chaos(schedule="cpd.sweep:nan:iter=2", smoke=True)
    assert res.ok
    # now forge a 'fired but no events' result through the checker's
    # own data: wipe the events and re-derive violations via a rerun
    # with the sentinel disabled is covered in test_guarded; here just
    # assert the evidence map knows every fault kind
    for kind in faults.RAISING_KINDS + faults.POISON_KINDS \
            + faults.DELAY_KINDS:
        assert kind in chaos._EVIDENCE, kind


def test_chaos_bad_schedule_fails_loudly():
    with pytest.raises(ValueError):
        chaos.run_chaos(schedule="site:notakind", smoke=True)


def test_chaos_leaves_no_armed_state():
    chaos.run_chaos(smoke=True)
    assert not faults.active("cpd.sweep")
    assert resilience.deadline_seconds() is None
    # the throwaway plan cache did not leak into the process override
    from splatt_tpu.tune import _cache_path_override

    assert _cache_path_override is None


def test_cpd_json_includes_health_events(tmp_path, tensors_dir,
                                         capsys, monkeypatch):
    """Satellite: `splatt cpd --json` carries health/rollback events
    and demotions in machine-readable form."""
    from splatt_tpu.cli import main

    monkeypatch.chdir(tmp_path)
    with faults.inject("cpd.sweep", "nan", iter_at=2):
        rc = main(["cpd", str(tensors_dir / "med.tns"), "-r", "3",
                   "-i", "4", "--seed", "1", "--nowrite", "--json"])
    out = capsys.readouterr().out
    assert rc == 0
    rec = json.loads([l for l in out.splitlines()
                      if l.startswith("{")][-1])
    kinds = {e["kind"] for e in rec["events"]}
    assert {"health_nonfinite", "health_rollback"} <= kinds
    assert rec["degraded"] is False
    assert "demotions" in rec
    # the human summary prints the same facts (distributed and
    # single-device share this path)
    assert "rolled back to the last-good snapshot" in out


def test_cpd_json_distributed(tmp_path, tensors_dir, capsys,
                              monkeypatch):
    from splatt_tpu.cli import main

    monkeypatch.chdir(tmp_path)
    with faults.inject("cpd.sweep", "nan", iter_at=2):
        rc = main(["cpd", str(tensors_dir / "med.tns"), "-r", "3",
                   "-i", "4", "--seed", "1", "--nowrite",
                   "--decomp", "fine", "--json"])
    out = capsys.readouterr().out
    assert rc == 0
    rec = json.loads([l for l in out.splitlines()
                      if l.startswith("{")][-1])
    kinds = {e["kind"] for e in rec["events"]}
    assert "health_rollback" in kinds
    assert "rolled back to the last-good snapshot" in out


def test_bench_path_error_recording(monkeypatch):
    """Satellite: a failing bench path records {"error": <classified>}
    and the benchmark continues — via the shared resilience helper the
    bench driver calls."""
    ev = resilience.record_path_error(
        "tuned", RuntimeError("Mosaic failed to lower"))
    assert ev["failure_class"] == "deterministic"
    evs = resilience.run_report().events("bench_path_error")
    assert len(evs) == 1 and evs[0]["path"] == "tuned"


def test_bench_continues_past_failing_path(tmp_path):
    """Satellite (end-to-end): with a fault killing every blocked-path
    engine, bench.py still reports the stream path's timing and carries
    the failed paths classified under "path_errors"."""
    import os
    import subprocess
    import sys

    env = dict(os.environ)
    env.update(SPLATT_BENCH_NNZ="60000", SPLATT_BENCH_RANK="4",
               SPLATT_BENCH_ITERS="1",
               SPLATT_BENCH_PATHS="blocked,stream",
               # force the jit engine family so the engine.* fault
               # site is actually on the blocked path (the native
               # host engine has no engine sites)
               SPLATT_BENCH_ENGINE="xla",
               SPLATT_FAULTS="engine.xla:mosaic:*",
               SPLATT_TUNE_CACHE=str(tmp_path / "tc.json"),
               JAX_PLATFORMS="cpu")
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    p = subprocess.run([sys.executable, os.path.join(repo, "bench.py")],
                       env=env, capture_output=True, text=True,
                       timeout=600, cwd=repo)
    line = [l for l in p.stdout.splitlines() if l.startswith("{")]
    assert line, p.stderr[-800:]
    rec = json.loads(line[-1])
    assert "stream" in rec["timing_stats"]          # survived
    assert "blocked" in rec["path_errors"]          # recorded, not fatal
    assert rec["path_errors"]["blocked"]["error"].startswith(
        "deterministic:")
    assert "continuing with the remaining paths" in p.stderr


# -- serve kill-and-restart soak (docs/serve.md) -----------------------------

def test_serve_chaos_smoke_kill_and_restart():
    """Acceptance (tier-1): SIGKILL a real serve daemon mid-queue,
    restart it, and the soak invariant holds — no accepted job is
    lost (every one reaches a terminal state with its journal and
    checkpoint lineage intact), the restart resumes the in-flight
    jobs, and the NaN-poisoned tenant's rollback stays contained: the
    clean neighbors' results carry no health events and no demotions.
    """
    res = chaos.run_serve_chaos(smoke=True)
    assert res.ok, res.violations
    assert res.verdict == "survived"
    assert res.killed_mid_queue  # the kill genuinely landed mid-queue
    assert res.resumed           # the restart re-enqueued jobs
    assert set(res.jobs) == {"chaos-0-nan", "chaos-clean0",
                             "chaos-clean1"}
    assert all(s in ("converged", "degraded")
               for s in res.jobs.values())
    rec = res.to_json()
    assert rec["verdict"] == "survived" and not rec["violations"]
    # the kill's crash windows were classified and recorded — the
    # static-vs-dynamic coverage comparison (docs/static-analysis.md)
    # reads this event against the crash-point checker's enumeration
    assert isinstance(res.crash_windows, list)
    evs = resilience.run_report().events("crash_windows_exercised")
    assert evs and evs[0]["soak"] == "serve"
    assert evs[0]["windows"] == ",".join(res.crash_windows)


def test_crash_window_classifier(tmp_path):
    """The post-mortem window classifier reads a fabricated post-kill
    spool back into the crash-point checker's window vocabulary: torn
    journal tails, publish tmp debris on every plane, and the two
    result/journal divergence directions."""
    import os

    root = str(tmp_path)
    os.makedirs(os.path.join(root, "results"))
    os.makedirs(os.path.join(root, "leases"))
    with open(os.path.join(root, "journal.jsonl"), "w") as f:
        f.write('{"rec": "accepted", "job": "j1", "ts": 1}\n')
        f.write('{"rec": "done", "job": "j1", "ts": 2}\n')
        f.write('{"rec": "accepted", "job": "j3", "ts": 3}\n')
        f.write('{"rec": "acce')  # torn mid-append
    # j3: result published but no terminal record (died before DONE);
    # j1: DONE journaled but its result is gone
    with open(os.path.join(root, "results", "j3.json"), "w") as f:
        json.dump({"job": "j3", "status": "converged"}, f)
    for debris in ("m1.gen.json.~7.tmp", "m1.gen.json.bak.~7.tmp",
                   "m1.npz.~7.tmp"):
        open(os.path.join(root, debris), "w").close()
    open(os.path.join(root, "leases", "j1.json.~7.tmp"), "w").close()
    got = chaos._crash_windows_exercised(root)
    assert got == sorted({
        "journal.append", "journal.append.torn", "journal.append[done]",
        "result.publish", "stamp.publish", "stamp.bak.publish",
        "ckpt.publish", "lease.publish",
    })
    # every id the classifier can emit is in the checker's vocabulary
    from tools.splint.crashpoint import _windows

    assert set(got) <= _windows()


def test_serve_chaos_cli_flag_parses():
    from splatt_tpu.cli import build_parser

    args = build_parser().parse_args(["chaos", "--serve", "--smoke"])
    assert args.serve and args.smoke


# -- fleet kill-and-FAILOVER soak (docs/fleet.md) -----------------------------

def test_fleet_chaos_smoke_kill_and_failover():
    """Acceptance (tier-1): SIGKILL one of 2 fleet replicas mid-job
    under seeded multi-tenant load, restart a replacement, and the
    fleet invariant holds — zero accepted jobs lost (the pinned job is
    ADOPTED by the survivor and finishes from its checkpoint), the
    journal's single-owner lineage is clean for every job, the adopted
    same-regime job hits the warm shared caches (cache_hits > 0, zero
    re-measurements), tenant isolation holds, and the adopter's
    metrics snapshot + span trace account for the takeover.

    ISSUE 14: the kill must additionally be visible END-TO-END in the
    fleet observability plane — the merged aggregate counts the lease
    expiry, the adoption and an slo_burn spike that recovers; the
    victim's flight-recorder ring replays its timeline up to the kill
    (the pinned job's job_started mark included); and `splatt status`
    agrees with the journal throughout (assertions inside
    run_fleet_chaos; the evidence rides `observability`)."""
    res = chaos.run_fleet_chaos(smoke=True)
    assert res.ok, res.violations
    assert res.verdict == "survived"
    assert res.victim is not None        # the kill genuinely landed
    assert "fleet-1-pin" in res.adopted  # and forced an adoption
    # ISSUE 15: the batched + update tenant mix rides the same soak —
    # three same-regime jobs filed as a burst (coalescing candidates)
    # plus an update chain against the base model (docs/batched.md)
    assert set(res.jobs) == {"fleet-0-warm", "fleet-1-pin",
                             "fleet-2-nan", "fleet-3-clean",
                             "fleet-4-base", "fleet-5-up",
                             "fleet-b0", "fleet-b1", "fleet-b2",
                             "fleet-p0", "fleet-p1", "fleet-p2",
                             "fleet-p3"}
    assert all(s in ("converged", "degraded")
               for j, s in res.jobs.items()
               if not j.startswith("fleet-p"))
    # ISSUE 16: the predict stream rides the same soak — every predict
    # reaches an honest terminal answer (served, or a classified
    # refusal), at least one is served across the kill, and the
    # shredded-model predict REFUSES rather than serving garbage
    assert all(s in ("served", "refused")
               for j, s in res.jobs.items() if j.startswith("fleet-p"))
    assert res.observability["predicts_served"] >= 1
    assert res.jobs["fleet-p3"] == "refused"
    # batched coverage is recorded (spool-claim races can split the
    # burst across replicas, so smoke records rather than requires;
    # the 3-replica slow leg and tests/test_serve_batched.py pin it)
    assert "batched_jobs" in res.observability
    aff = res.affinity["fleet-1-pin"]
    assert aff["cache_hits"] and not aff["measured"]
    assert aff["adopted_from"] == res.victim
    ob = res.observability
    assert ob["adoptions"] >= 1 and ob["lease_expired"] >= 1
    assert ob["slo_burns"] >= 1          # the burn spike was counted
    assert ob["replicas_dead"] >= 1      # the census saw the victim
    assert ob["flight_events"] >= 1      # the black box is readable
    rec = res.to_json()
    assert rec["verdict"] == "survived" and not rec["violations"]


@pytest.mark.slow
def test_fleet_chaos_three_replicas():
    """The same kill-and-failover invariant at 3 replicas (slow tier;
    the ISSUE 14 acceptance runs the soak at 2 AND 3): more scanners
    racing the same adoption, same single-owner lineage, same
    end-to-end observability evidence.  ISSUE 15 makes this the
    batched soak leg: the same-regime burst must actually coalesce
    (>= 2 jobs committed through a batch) and the update chain must
    leave auditable model-store lineage."""
    res = chaos.run_fleet_chaos(smoke=True, replicas=3)
    assert res.ok, res.violations
    assert res.verdict == "survived"
    assert "fleet-1-pin" in res.adopted
    assert res.observability["adoptions"] >= 1
    assert res.observability["slo_burns"] >= 1
    assert res.observability["flight_events"] >= 1
    assert res.observability["batched_jobs"] >= 2
    assert res.jobs.get("fleet-5-up") in ("converged", "degraded")
    # ISSUE 16: the predict stream holds at 3 replicas too
    assert res.observability["predicts_served"] >= 1
    assert res.jobs.get("fleet-p3") == "refused"


def test_fleet_chaos_cli_flag_parses():
    from splatt_tpu.cli import build_parser

    args = build_parser().parse_args(
        ["chaos", "--fleet", "--smoke", "--replicas", "3"])
    assert args.fleet and args.smoke and args.replicas == 3


def test_bench_gate_cli_flag_parses():
    from splatt_tpu.cli import build_parser

    args = build_parser().parse_args(["chaos", "--smoke", "--bench-gate"])
    assert args.smoke and args.bench_gate


def test_chaos_smoke_bench_gate(tmp_path, monkeypatch):
    """The bench regression gate rides the chaos --smoke tier
    (docs/format.md): a smoke-sized `bench.py --gate` subprocess runs
    to completion, reports per-path achieved bytes + format summaries,
    and exits 0 when no same-metric prior regresses.  A format change
    that re-inflated bytes >10% would fail this test loudly."""
    from splatt_tpu import chaos

    # a throwaway prior dir + plan cache: the smoke bench must neither
    # compare against unlike full-scale priors nor dirty the real cache
    monkeypatch.setenv("SPLATT_BENCH_PRIOR_DIR", str(tmp_path))
    monkeypatch.setenv("SPLATT_TUNE_CACHE",
                       str(tmp_path / "tune_cache.json"))
    gate = chaos.run_bench_gate(smoke=True)
    assert gate["ok"], gate["stderr_tail"]
    rec = gate["record"]
    assert rec["unit"] == "sec/iter" and rec["value"] > 0
    # the format satellite: achieved bytes + encoding summary per path
    assert "compact" in rec["model_gb_per_path"]
    assert "bf16" in rec["format"]["compact"]
    assert rec["model_gb_per_path"]["compact"] < \
        rec["model_gb_per_path"]["blocked"]
    # second run against a matching prior: the gate actually compares.
    # Times in the prior are inflated 10x (smoke-scale wall clocks are
    # noisy; the gate's time leg must not make this test flaky) — the
    # BYTES leg stays exact, so a format re-inflation would still fail.
    prior = dict(rec, value=rec["value"] * 10,
                 timing_stats={k: {s: v[s] * 10 for s in v}
                               for k, v in rec["timing_stats"].items()})
    (tmp_path / "BENCH_r98.json").write_text(json.dumps(prior))
    gate2 = chaos.run_bench_gate(smoke=True)
    assert gate2["ok"], gate2["stderr_tail"]
    assert gate2["record"].get("bench_regressions") is None
