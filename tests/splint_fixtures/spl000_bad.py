"""SPL000 bad: an ignore pragma with no reason (the escape hatch
requires a justification)."""

import jax.numpy as jnp

A = jnp.zeros(4, jnp.float32)  # splint: ignore[SPL005]
