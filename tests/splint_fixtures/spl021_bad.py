"""SPL021 bad: generation-stamp advance and factor persist travelling
separately — a stamp with no dominating persist, and a commit persist
with a normal-flow path to exit that skips the advance."""


def advance_generation(ckpt_dir, model, factors, lam):
    return 1  # stand-in for splatt_tpu.predict.advance_generation


def _save_checkpoint(path, factors, lam, it, fit):
    pass  # stand-in for splatt_tpu.cpd._save_checkpoint


def _save_model_tensor(path, tt, applied):
    pass  # stand-in for splatt_tpu.serve._save_model_tensor


def commit_stamp_only(ckpt_dir, model, factors, lam):
    # advances the stamp without persisting the factors it fences:
    # readers verify the sha against stale content and REFUSE — a
    # committed generation becomes unservable
    return advance_generation(ckpt_dir, model, factors, lam)


def commit_tensor_only(path, ckpt_dir, model, tt, factors, lam,
                       applied, dry_run):
    _save_checkpoint(path, factors, lam, 0, 0.0)
    _save_model_tensor(path + ".model", tt, applied)
    if dry_run:
        # normal-flow exit that skips the advance: the tensor just
        # published has no stamp and never will
        return None
    return advance_generation(ckpt_dir, model, factors, lam)
