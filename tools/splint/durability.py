"""splint v4: crash-consistency protocol rules (SPL019–SPL023).

The serve/fleet/predict planes rest on hand-maintained durability
protocols — tmp-write + fsync + ``os.replace`` publishes, flock-
serialized journal appends, lease-fenced terminal commits, generation
stamps that advance atomically with factor checksums.  Until now the
only enforcement was two SIGKILL chaos soaks, which SAMPLE a handful
of crash windows per run.  These rules make the protocols structural:
they run over the splint v2 CFG/def-use engine and fail the build on
any ordering the protocol forbids, whether or not a soak ever lands a
kill inside that window.

Rules (all hard zero-rules — never baselined):

SPL019 torn-publish
    The sanctioned atomic-publish helpers (``[tool.splint]``
    ``atomic-publish-helpers``) must contain the full protocol in
    order — content fsync BEFORE the ``os.replace``, a parent-
    directory fsync AFTER it, and no publish step on an exception
    path.  Outside the helpers, any ``os.replace``/``os.rename``/
    ``shutil.move`` whose source this same function wrote is an inline
    publish bypassing the chokepoint (a torn-publish window splint
    cannot audit).  Pure renames of pre-existing files are fine.

SPL020 unfenced terminal commit
    A terminal journal append (``done``/``failed``/``rejected``)
    reachable without a DOMINATING live-lease renew is the PR 11
    zombie window made static: a deposed replica can journal a
    terminal record for a job a peer already adopted.  Every journal
    append site must live in a function registered in
    ``journal-append-functions``; terminal appends must additionally
    be in ``lease-fenced-functions`` and be dominated (over normal AND
    exception edges) by a call from ``lease-fence-calls``.

SPL021 stamp-factor atomicity
    A generation-stamp advance (``stamp-advance-calls``) not dominated
    by a factor persist (``factor-persist-calls``) can stamp content
    that was never written; a commit persist (``commit-persist-calls``)
    with a normal-edge path to exit that skips the advance publishes
    factors no stamp will ever fence.  Exception edges are exempt from
    the second leg: a raise IS the crash the replay/refit repair paths
    cover — the stamp correctly never moves.

SPL022 replay totality
    Every journal record kind emitted anywhere (``_rec(...)`` first
    argument, resolved through constants and local assignments) must
    be declared in serve's ``KNOWN_KINDS`` registry; every declared
    kind must be emitted somewhere and exercised by at least one test
    (the SPL006 shape, for the journal plane).  A kind splint cannot
    resolve statically is itself a finding — replay totality that
    cannot be audited is not totality.

SPL023 fsync-barrier
    A write-mode ``open`` whose path lands under a durable root
    (``durable-roots`` fragments: journal, ckpt, stamp, lease, result,
    metrics …) inside a function with no fsync and no sanctioned
    helper call publishes bytes a post-crash reader may never see —
    or worse, see torn.  Lock-sidecar files are exempt (their content
    is meaningless; only their existence matters).

The module deliberately imports ONLY from ``tools.splint.core`` so it
can be loaded standalone (and by ``rules.py``) without import cycles.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set, Tuple

from tools.splint.core import (FileCtx, Finding, FunctionCFG, Project,
                               walk_nodes)

__all__ = [
    "TornPublish", "UnfencedTerminalCommit", "StampFactorAtomicity",
    "ReplayTotality", "FsyncBarrier", "DURABILITY_RULES",
]


# -- shared machinery --------------------------------------------------------

class _DurabilityRule:
    """Duck-typed splint rule (same interface as ``rules.Rule``)."""

    id = "SPL?"
    title = ""
    hint = ""

    def check(self, ctx: FileCtx, project: Project) -> List[Finding]:
        return []

    def finalize(self, project: Project) -> List[Finding]:
        return []

    def finding(self, ctx_or_path, line: int, message: str) -> Finding:
        path = (ctx_or_path.relpath if isinstance(ctx_or_path, FileCtx)
                else ctx_or_path)
        return Finding(self.id, path, line, message, hint=self.hint)


def _dedupe(findings: List[Finding]) -> List[Finding]:
    seen = set()
    out = []
    for f in findings:
        k = (f.rule, f.path, f.line, f.message)
        if k not in seen:
            seen.add(k)
            out.append(f)
    return out


def _functions(tree: ast.AST):
    for node in walk_nodes(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            yield node


def _last_seg(dotted: str) -> str:
    return dotted.rsplit(".", 1)[-1] if dotted else ""


def _fn_calls(ctx: FileCtx, fn: ast.AST) -> List[Tuple[ast.Call, str]]:
    """Every call in `fn` (nested defs included — conservative) with
    its alias-resolved dotted name."""
    out = []
    for n in ast.walk(fn):
        if isinstance(n, ast.Call):
            out.append((n, ctx.resolve(n.func) or ""))
    return out


def _node_exprs(node) -> List[ast.AST]:
    """The expressions a CFG node actually EVALUATES.  Branch-owning
    nodes (``test``/``for``/``with``/``except``) hold the whole
    compound statement in ``.stmt``; only the controlling expression
    belongs to the node — the bodies are separate nodes."""
    s = node.stmt
    if s is None:
        return []
    if node.kind == "test":
        return [s.test]
    if node.kind == "for":
        return [s.iter]
    if node.kind == "with":
        return [i.context_expr for i in s.items]
    if node.kind == "except":
        return [s.type] if getattr(s, "type", None) is not None else []
    if isinstance(s, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)):
        return []  # nested scopes are opaque to this function's CFG
    return [s]


def _node_calls(ctx: FileCtx, node) -> List[Tuple[ast.Call, str]]:
    out = []
    for e in _node_exprs(node):
        for n in ast.walk(e):
            if isinstance(n, ast.Call):
                out.append((n, ctx.resolve(n.func) or ""))
    return out


def _dominators(cfg: FunctionCFG) -> List[Set[int]]:
    """Iterative dominator sets: ``dom(n) = {n} ∪ ⋂ dom(pred)`` over
    BOTH normal and exception edges.  A fence only counts if it sits
    on EVERY path to the commit, including the path through a handler
    — which is exactly what "dominates over all edges" means."""
    n = len(cfg.nodes)
    preds = cfg.preds()
    every = set(range(n))
    entry = cfg.nodes[0].idx
    dom = [set(every) for _ in range(n)]
    dom[entry] = {entry}
    changed = True
    while changed:
        changed = False
        for i in range(n):
            if i == entry:
                continue
            ps = [p for p, _exc in preds.get(i, [])]
            if not ps:
                continue
            new = set.intersection(*(dom[p] for p in ps))
            new.add(i)
            if new != dom[i]:
                dom[i] = new
                changed = True
    return dom


def _node_of(cfg: FunctionCFG, call: ast.Call) -> Optional[int]:
    """The CFG node whose evaluated expressions contain `call` (by
    object identity), or None (call inside a nested def)."""
    for node in cfg.nodes:
        for e in _node_exprs(node):
            for n in ast.walk(e):
                if n is call:
                    return node.idx
    return None


def _local_assigns(fn: ast.AST) -> Dict[str, List[ast.AST]]:
    """name → assigned value expressions, this function's body only."""
    out: Dict[str, List[ast.AST]] = {}
    for s in ast.walk(fn):
        if isinstance(s, ast.Assign):
            for t in s.targets:
                if isinstance(t, ast.Name):
                    out.setdefault(t.id, []).append(s.value)
        elif isinstance(s, ast.AnnAssign) and s.value is not None \
                and isinstance(s.target, ast.Name):
            out.setdefault(s.target.id, []).append(s.value)
    return out


def _path_tokens(expr: ast.AST, assigns: Dict[str, List[ast.AST]],
                 depth: int = 1) -> Set[str]:
    """The identifier/attribute/string-literal tokens a path expression
    is built from, chasing function-local Name assignments one level
    (``fp = os.path.join(ckdir, name)`` → tokens of BOTH args)."""
    toks: Set[str] = set()
    for n in ast.walk(expr):
        if isinstance(n, ast.Name):
            toks.add(n.id)
            if depth > 0:
                for v in assigns.get(n.id, []):
                    toks |= _path_tokens(v, assigns, depth - 1)
        elif isinstance(n, ast.Attribute):
            toks.add(n.attr)
        elif isinstance(n, ast.Constant) and isinstance(n.value, str):
            toks.add(n.value)
        elif isinstance(n, ast.arg):
            toks.add(n.arg)
    return toks


def _open_mode(call: ast.Call) -> Optional[str]:
    """The literal mode of an ``open()`` call, or None (default 'r' or
    non-literal)."""
    if len(call.args) > 1 and isinstance(call.args[1], ast.Constant) \
            and isinstance(call.args[1].value, str):
        return call.args[1].value
    for kw in call.keywords:
        if kw.arg == "mode" and isinstance(kw.value, ast.Constant) \
                and isinstance(kw.value.value, str):
            return kw.value.value
    return None


def _is_write_mode(mode: Optional[str]) -> bool:
    return bool(mode) and any(c in mode for c in "wax+")


# -- journal record-kind resolution (SPL020/SPL022) --------------------------

def _kind_values(ctx: FileCtx, fn: ast.AST, expr: ast.AST,
                 depth: int = 2) -> Set[str]:
    """Every string a kind-valued expression can evaluate to: literals,
    module/function-level string constants, and (one chase) function-
    local assignments (handles ``kind = FAILED if ... else DONE``)."""
    kinds: Set[str] = set()
    assigns = _local_assigns(fn)

    def visit(e: ast.AST, d: int, seen: Set[str]) -> None:
        if isinstance(e, ast.Constant):
            if isinstance(e.value, str):
                kinds.add(e.value)
            return
        if isinstance(e, ast.Name):
            v = ctx.str_consts.get(e.id)
            if v is not None:
                kinds.add(v)
                return
            if d > 0 and e.id not in seen:
                seen = seen | {e.id}
                for val in assigns.get(e.id, []):
                    visit(val, d - 1, seen)
            return
        if isinstance(e, ast.IfExp):
            visit(e.body, d, seen)
            visit(e.orelse, d, seen)
            return
        # compound fallback: any Name/const inside may carry the kind
        for n in ast.walk(e):
            if isinstance(n, ast.Constant) and isinstance(n.value, str):
                kinds.add(n.value)
            elif isinstance(n, ast.Name):
                v = ctx.str_consts.get(n.id)
                if v is not None:
                    kinds.add(v)

    visit(expr, depth, set())
    return kinds


def _record_kinds(ctx: FileCtx, fn: ast.AST, expr: ast.AST,
                  depth: int = 2) -> Set[str]:
    """The journal record kinds a record-valued expression can carry:
    ``self._rec(KIND, ...)`` first args, dict literals / ``dict()``
    calls with a ``rec`` key, chased through local assignments
    (handles ``acc = self._rec(ACCEPTED, ...)`` … ``append(acc)``)."""
    kinds: Set[str] = set()
    assigns = _local_assigns(fn)

    def visit(e: ast.AST, d: int, seen: Set[str]) -> None:
        found = False
        for n in ast.walk(e):
            if isinstance(n, ast.Call):
                name = _last_seg(ctx.resolve(n.func) or "")
                if name == "_rec" and n.args:
                    kinds.update(_kind_values(ctx, fn, n.args[0]))
                    found = True
                elif name == "dict":
                    for kw in n.keywords:
                        if kw.arg == "rec":
                            kinds.update(_kind_values(ctx, fn, kw.value))
                            found = True
            elif isinstance(n, ast.Dict):
                for k, v in zip(n.keys, n.values):
                    if isinstance(k, ast.Constant) and k.value == "rec" \
                            and v is not None:
                        kinds.update(_kind_values(ctx, fn, v))
                        found = True
        if found or d <= 0:
            return
        for n in ast.walk(e):
            if isinstance(n, ast.Name) and n.id not in seen:
                for val in assigns.get(n.id, []):
                    visit(val, d - 1, seen | {n.id})

    visit(expr, depth, set())
    return kinds


def _is_journal_append(ctx: FileCtx, call: ast.Call) -> bool:
    """``<something>.journal.append(...)`` or ``journal.append(...)``
    — the Journal chokepoint, matched structurally."""
    f = call.func
    if not (isinstance(f, ast.Attribute) and f.attr == "append"):
        return False
    v = f.value
    if isinstance(v, ast.Attribute) and v.attr == "journal":
        return True
    if isinstance(v, ast.Name) and v.id == "journal":
        return True
    return False


def _terminal_kinds(project: Project) -> Set[str]:
    """Serve's ``TERMINAL`` tuple, names resolved through the module's
    string constants.  Empty when the serve module is absent (fixture
    mini-projects without a serve plane)."""
    ctx = project.ctx_for(project.config.serve_module)
    if ctx is None:
        return set()
    for node in walk_nodes(ctx.tree):
        if (isinstance(node, ast.Assign) and len(node.targets) == 1
                and isinstance(node.targets[0], ast.Name)
                and node.targets[0].id == "TERMINAL"
                and isinstance(node.value, (ast.Tuple, ast.List))):
            out = set()
            for e in node.value.elts:
                if isinstance(e, ast.Constant) and isinstance(e.value, str):
                    out.add(e.value)
                elif isinstance(e, ast.Name):
                    v = ctx.str_consts.get(e.id)
                    if v is not None:
                        out.add(v)
            return out
    return set()


def _declared_kinds(ctx: FileCtx) -> Dict[str, int]:
    """Serve's ``KNOWN_KINDS`` registry → {kind: lineno}, names
    resolved through string constants.  Empty when undeclared."""
    for node in walk_nodes(ctx.tree):
        if (isinstance(node, ast.Assign) and len(node.targets) == 1
                and isinstance(node.targets[0], ast.Name)
                and node.targets[0].id == "KNOWN_KINDS"
                and isinstance(node.value, (ast.Tuple, ast.List, ast.Set))):
            out: Dict[str, int] = {}
            for e in node.value.elts:
                if isinstance(e, ast.Constant) and isinstance(e.value, str):
                    out[e.value] = e.lineno
                elif isinstance(e, ast.Name):
                    v = ctx.str_consts.get(e.id)
                    if v is not None:
                        out[v] = e.lineno
            return out
    return {}


# -- SPL019 ------------------------------------------------------------------

class TornPublish(_DurabilityRule):
    """Durable publish outside — or violating — the sanctioned
    tmp-write + fsync + ``os.replace`` + dir-fsync protocol.

    Inside an ``atomic-publish-helpers`` function the steps must all
    be present and ORDERED: content fsync strictly before the rename
    (else a crash publishes unsynced bytes), a parent-directory fsync
    after it (else the rename itself is volatile — the published file
    can vanish on power loss), and no rename on an exception path
    (exception handlers must clean up, never publish).  Outside the
    helpers, renaming a file this same function wrote is an inline
    re-implementation of the protocol that splint cannot audit — route
    it through the helper."""

    id = "SPL019"
    title = "torn-publish: durable publish outside/violating the " \
            "sanctioned atomic-publish protocol"
    hint = ("publish through splatt_tpu.utils.durable.publish_file/"
            "publish_bytes (tmp write → fsync → os.replace → parent-dir "
            "fsync); never rename self-written files inline")

    _RENAMES = {"os.replace", "os.rename", "shutil.move"}
    _WRITER_CALLS = {"savez", "savez_compressed", "save"}

    def check(self, ctx: FileCtx, project: Project) -> List[Finding]:
        cfg = project.config
        atomic = set(cfg.atomic_publish_helpers)
        helpers = set(cfg.durable_write_helpers) | atomic
        out: List[Finding] = []
        for fn in _functions(ctx.tree):
            if fn.name in atomic:
                out.extend(self._protocol(ctx, fn))
            elif fn.name not in helpers:
                out.extend(self._inline(ctx, fn))
        return _dedupe(out)

    def _protocol(self, ctx: FileCtx, fn: ast.AST) -> List[Finding]:
        calls = _fn_calls(ctx, fn)
        renames = [c for c, d in calls if d in self._RENAMES]
        fsyncs = [c for c, d in calls if d == "os.fsync"]
        dirsyncs = [c for c, d in calls if _last_seg(d) == "_fsync_dir"]
        out: List[Finding] = []
        if not renames:
            out.append(self.finding(
                ctx, fn.lineno,
                f"sanctioned publish helper '{fn.name}' contains no "
                f"atomic rename (os.replace) — its publishes are torn-"
                f"writable in place"))
            return out
        first_r = min(c.lineno for c in renames)
        last_r = max(c.lineno for c in renames)
        if not any(c.lineno < first_r for c in fsyncs):
            out.append(self.finding(
                ctx, first_r,
                f"'{fn.name}': no content fsync before the publish "
                f"rename — a crash can publish unsynced (torn) bytes"))
        if not any(c.lineno > last_r for c in dirsyncs) \
                and not any(c.lineno > last_r for c in fsyncs):
            out.append(self.finding(
                ctx, last_r,
                f"'{fn.name}': no parent-directory fsync after the "
                f"rename — the publish itself is volatile and can be "
                f"lost on power failure"))
        # exception-path leg: a rename inside a handler/finally runs
        # when the protocol already failed — steps reorder under crash
        exc_calls: Set[int] = set()
        for n in ast.walk(fn):
            if isinstance(n, ast.Try):
                for region in list(n.handlers) + list(n.finalbody):
                    for sub in ast.walk(region):
                        if isinstance(sub, ast.Call):
                            exc_calls.add(id(sub))
        for c in renames:
            if id(c) in exc_calls:
                out.append(self.finding(
                    ctx, c.lineno,
                    f"'{fn.name}': publish rename on an exception path "
                    f"— the protocol steps reorder or partially apply "
                    f"under failure"))
        return out

    def _inline(self, ctx: FileCtx, fn: ast.AST) -> List[Finding]:
        written: Set[str] = set()
        for n in ast.walk(fn):
            if isinstance(n, ast.Call):
                dotted = ctx.resolve(n.func) or ""
                last = _last_seg(dotted)
                if dotted == "open" and n.args \
                        and _is_write_mode(_open_mode(n)):
                    written |= {t for t in _path_tokens(n.args[0], {}, 0)
                                if t.isidentifier()}
                elif last in self._WRITER_CALLS and n.args:
                    written |= {t for t in _path_tokens(n.args[0], {}, 0)
                                if t.isidentifier()}
                elif last == "dump" and len(n.args) > 1:
                    written |= {t for t in _path_tokens(n.args[1], {}, 0)
                                if t.isidentifier()}
                elif isinstance(n.func, ast.Attribute) \
                        and n.func.attr in ("write_text", "write_bytes") \
                        and isinstance(n.func.value, ast.Name):
                    written.add(n.func.value.id)
        if not written:
            return []
        out: List[Finding] = []
        for n in ast.walk(fn):
            if isinstance(n, ast.Call) \
                    and (ctx.resolve(n.func) or "") in self._RENAMES \
                    and n.args:
                src_names = {t for t in _path_tokens(n.args[0], {}, 0)
                             if t.isidentifier()}
                if src_names & written:
                    out.append(self.finding(
                        ctx, n.lineno,
                        f"'{fn.name}' writes a file and renames it into "
                        f"place inline — an unaudited publish protocol; "
                        f"route it through the sanctioned durable helper"))
        return out


# -- SPL020 ------------------------------------------------------------------

class UnfencedTerminalCommit(_DurabilityRule):
    """Terminal journal append without a dominating live-lease fence.

    A replica that lost its lease (GC pause, preemption stall) but
    still runs can journal ``done``/``failed`` for a job a peer
    already adopted — the zombie double-commit PR 11's dynamic fence
    closes at runtime.  This rule makes the fence STRUCTURAL: every
    journal append site must be registered; terminal appends must sit
    in a lease-fenced function and be dominated, over normal and
    exception edges alike, by a fence call (``renew``/
    ``_renew_fence``) that proves the lease was live on this very
    path."""

    id = "SPL020"
    title = "terminal journal append not dominated by a live-lease fence"
    hint = ("guard the append with `if not self._renew_fence(jid): "
            "return` (or an equivalent dominating fleet.renew) and "
            "register the function in [tool.splint] "
            "journal-append-functions / lease-fenced-functions")

    def check(self, ctx: FileCtx, project: Project) -> List[Finding]:
        cfg = project.config
        registered = set(cfg.journal_append_functions)
        fenced = set(cfg.lease_fenced_functions)
        fence_calls = set(cfg.lease_fence_calls)
        if not registered and not fenced:
            return []
        terminal = _terminal_kinds(project)
        out: List[Finding] = []
        for fn in _functions(ctx.tree):
            sites = [c for c, _d in _fn_calls(ctx, fn)
                     if _is_journal_append(ctx, c)]
            if not sites:
                continue
            key = f"{ctx.relpath}::{fn.name}"
            if key not in registered:
                for c in sites:
                    out.append(self.finding(
                        ctx, c.lineno,
                        f"journal append in unregistered function "
                        f"'{fn.name}' — declare it in [tool.splint] "
                        f"journal-append-functions so the fence audit "
                        f"covers it"))
                continue
            if not terminal:
                continue
            term_sites = [c for c in sites if c.args
                          and _record_kinds(ctx, fn, c.args[0]) & terminal]
            if not term_sites:
                continue
            if key not in fenced:
                for c in term_sites:
                    out.append(self.finding(
                        ctx, c.lineno,
                        f"terminal journal append in '{fn.name}', which "
                        f"is not a lease-fenced function — a deposed "
                        f"replica can double-commit here"))
                continue
            g = FunctionCFG(fn)
            dom = _dominators(g)
            fence_nodes = {
                node.idx for node in g.nodes
                if any(_last_seg(d) in fence_calls
                       for _c, d in _node_calls(ctx, node))}
            for c in term_sites:
                idx = _node_of(g, c)
                dominated = idx is not None and any(
                    d in fence_nodes for d in dom[idx] if d != idx)
                if not dominated:
                    out.append(self.finding(
                        ctx, c.lineno,
                        f"terminal journal append in '{fn.name}' is not "
                        f"dominated by a live-lease fence "
                        f"({'/'.join(sorted(fence_calls))}) — some path "
                        f"reaches the commit without proving the lease "
                        f"is still held"))
        return _dedupe(out)


# -- SPL021 ------------------------------------------------------------------

class StampFactorAtomicity(_DurabilityRule):
    """Generation-stamp advance and factor persist must travel
    together on every path.

    Leg A: an ``advance_generation`` call not dominated by a factor
    persist can stamp (and thereby fence-approve) content that was
    never written — readers verify the sha against STALE factors and
    refuse, losing availability for a committed generation.  Leg B: a
    commit persist (``commit-persist-calls``) from which exit is
    reachable via normal edges without passing an advance publishes
    factors no stamp will ever cover — permanently unservable.
    Exception edges are exempt from leg B: a raise is the crash the
    replay/refit paths already repair (the stamp correctly stays
    put)."""

    id = "SPL021"
    title = "generation-stamp advance and factor persist not atomic " \
            "on every path"
    hint = ("persist the factors/model tensor first, then advance the "
            "generation stamp, on the SAME straight-line path; never "
            "return between them")

    def check(self, ctx: FileCtx, project: Project) -> List[Finding]:
        cfg = project.config
        adv = set(cfg.stamp_advance_calls)
        persist = set(cfg.factor_persist_calls)
        commit = set(cfg.commit_persist_calls)
        if not adv or not persist:
            return []
        out: List[Finding] = []
        for fn in _functions(ctx.tree):
            if fn.name in adv | persist | commit:
                continue  # the helpers' own definitions
            calls = _fn_calls(ctx, fn)
            has_adv = any(_last_seg(d) in adv for _c, d in calls)
            has_commit = any(_last_seg(d) in commit for _c, d in calls)
            if not (has_adv or has_commit):
                continue
            g = FunctionCFG(fn)
            dom = _dominators(g)
            adv_nodes, persist_nodes, commit_nodes = set(), set(), set()
            for node in g.nodes:
                for _c, d in _node_calls(ctx, node):
                    seg = _last_seg(d)
                    if seg in adv:
                        adv_nodes.add(node.idx)
                    if seg in persist:
                        persist_nodes.add(node.idx)
                    if seg in commit:
                        commit_nodes.add(node.idx)
            for i in sorted(adv_nodes):
                if not any(d in persist_nodes for d in dom[i] if d != i):
                    out.append(self.finding(
                        ctx, g.nodes[i].line,
                        f"'{fn.name}': generation-stamp advance without "
                        f"a dominating factor persist — some path stamps "
                        f"content that was never written"))
            for i in sorted(commit_nodes):
                if self._exit_reachable_avoiding(g, i, adv_nodes):
                    out.append(self.finding(
                        ctx, g.nodes[i].line,
                        f"'{fn.name}': commit persist with a normal-"
                        f"flow path to exit that skips the generation-"
                        f"stamp advance — the published factors are "
                        f"unservable (no stamp will fence them)"))
        return _dedupe(out)

    @staticmethod
    def _exit_reachable_avoiding(g: FunctionCFG, start: int,
                                 avoid: Set[int]) -> bool:
        exit_idx = g.nodes[1].idx
        seen = set()
        stack = [s for s in g.nodes[start].succs]
        while stack:
            i = stack.pop()
            if i in seen or i in avoid:
                continue
            if i == exit_idx:
                return True
            seen.add(i)
            stack.extend(g.nodes[i].succs)
        return False


# -- SPL022 ------------------------------------------------------------------

class ReplayTotality(_DurabilityRule):
    """Journal record kinds: emitted ↔ declared ↔ tested, both ways.

    Serve declares its record vocabulary in ``KNOWN_KINDS`` (replay's
    unknown-kind forward-compat gate keys off it).  Every ``_rec``
    emission anywhere must resolve to declared kinds; a kind splint
    cannot resolve statically is a finding in its own right.  In the
    other direction, a declared kind nobody emits is dead vocabulary,
    a registry nobody reads is decorative, and a kind no test
    mentions has an untested replay path — the SPL006 shape applied
    to the journal plane."""

    id = "SPL022"
    title = "journal record kind not declared/emitted/tested " \
            "(replay totality)"
    hint = ("declare every journal record kind in serve.KNOWN_KINDS, "
            "emit kinds only through _rec with statically resolvable "
            "names, and exercise each kind in at least one test")

    def check(self, ctx: FileCtx, project: Project) -> List[Finding]:
        serve_ctx = project.ctx_for(project.config.serve_module)
        if serve_ctx is None:
            return []
        declared = _declared_kinds(serve_ctx)
        if not declared:
            return []
        out: List[Finding] = []
        for fn in _functions(ctx.tree):
            if fn.name == "_rec":
                continue  # the constructor itself takes the kind param
            for call, dotted in _fn_calls(ctx, fn):
                if _last_seg(dotted) != "_rec" or not call.args:
                    continue
                kinds = _kind_values(ctx, fn, call.args[0])
                if not kinds:
                    out.append(self.finding(
                        ctx, call.lineno,
                        f"journal record kind in '{fn.name}' is not "
                        f"statically resolvable — replay totality "
                        f"cannot be audited for this emission"))
                    continue
                for k in sorted(kinds):
                    if k not in declared:
                        out.append(self.finding(
                            ctx, call.lineno,
                            f"journal record kind '{k}' emitted in "
                            f"'{fn.name}' is not declared in "
                            f"serve.KNOWN_KINDS — replay will skip it "
                            f"as unknown"))
        return _dedupe(out)

    def finalize(self, project: Project) -> List[Finding]:
        cfg = project.config
        serve_ctx = project.ctx_for(cfg.serve_module)
        if serve_ctx is None or serve_ctx not in project.files:
            return []
        declared = _declared_kinds(serve_ctx)
        if not declared:
            return []
        out: List[Finding] = []
        # registry consulted at all?
        consulted = any(
            isinstance(n, ast.Name) and n.id == "KNOWN_KINDS"
            and isinstance(n.ctx, ast.Load)
            for n in walk_nodes(serve_ctx.tree))
        if not consulted:
            out.append(self.finding(
                serve_ctx, min(declared.values()),
                "KNOWN_KINDS is declared but never consulted — the "
                "replay unknown-kind gate does not exist"))
        # emitted set across the whole project
        emitted: Set[str] = set()
        for ctx in project.files:
            for fn in _functions(ctx.tree):
                if fn.name == "_rec":
                    continue
                for call, dotted in _fn_calls(ctx, fn):
                    if _last_seg(dotted) == "_rec" and call.args:
                        emitted |= _kind_values(ctx, fn, call.args[0])
        for k, line in sorted(declared.items()):
            if k not in emitted:
                out.append(self.finding(
                    serve_ctx, line,
                    f"journal record kind '{k}' is declared in "
                    f"KNOWN_KINDS but never emitted anywhere"))
        # tested leg: each kind quoted (or its constant NAME used) in
        # at least one test file
        tests = project.test_ctxs()
        if tests:
            const_names: Dict[str, List[str]] = {}
            for name, val in serve_ctx.str_consts.items():
                const_names.setdefault(val, []).append(name)
            for k, line in sorted(declared.items()):
                needles = [f'"{k}"', f"'{k}'"]
                needles += const_names.get(k, [])
                if not any(any(nd in t.source for nd in needles)
                           for t in tests):
                    out.append(self.finding(
                        serve_ctx, line,
                        f"journal record kind '{k}' is exercised by no "
                        f"test — its replay path is unverified"))
        return _dedupe(out)


# -- SPL023 ------------------------------------------------------------------

class FsyncBarrier(_DurabilityRule):
    """Durable write with no fsync barrier before a cross-process read.

    A write-mode ``open`` on a path under a durable root, in a
    function that neither fsyncs nor delegates to a sanctioned durable
    helper, leaves bytes the page cache may never flush: the writer
    reports success, the process dies, and the post-crash reader —
    replay, a fleet peer, the fenced predict path — sees nothing, or
    a torn prefix.  Lock sidecars are exempt (only their existence
    matters, and flock state dies with the process anyway)."""

    id = "SPL023"
    title = "durable write without an fsync barrier on the write side"
    hint = ("route the write through splatt_tpu.utils.durable "
            "(publish_* / append_line), or fsync before any cross-"
            "process reader can depend on the bytes")

    def check(self, ctx: FileCtx, project: Project) -> List[Finding]:
        cfg = project.config
        helpers = set(cfg.durable_write_helpers) \
            | set(cfg.atomic_publish_helpers)
        roots = [r.lower() for r in cfg.durable_roots]
        if not roots:
            return []
        out: List[Finding] = []
        for fn in _functions(ctx.tree):
            if fn.name in helpers:
                continue
            calls = _fn_calls(ctx, fn)
            if any(d == "os.fsync" or _last_seg(d) == "_fsync_dir"
                   for _c, d in calls):
                continue  # the function carries its own barrier
            assigns = _local_assigns(fn)
            for call, dotted in calls:
                if dotted != "open" or not call.args:
                    continue
                if not _is_write_mode(_open_mode(call)):
                    continue
                toks = {t.lower()
                        for t in _path_tokens(call.args[0], assigns, 1)}
                if any("lock" in t for t in toks):
                    continue
                hit = sorted({r for r in roots
                              for t in toks if r in t})
                if hit:
                    out.append(self.finding(
                        ctx, call.lineno,
                        f"'{fn.name}' writes a durable path "
                        f"({'/'.join(hit)}) with no fsync barrier — a "
                        f"crash can lose or tear bytes a cross-process "
                        f"reader depends on"))
        return _dedupe(out)


DURABILITY_RULES = [TornPublish(), UnfencedTerminalCommit(),
                    StampFactorAtomicity(), ReplayTotality(),
                    FsyncBarrier()]
