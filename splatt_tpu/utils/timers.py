"""Named wall-clock timer registry (≙ src/timer.{h,c}).

The reference keeps a global array of named timers with verbosity levels
gating which are reported (timers[TIMER_NTIMERS], src/timer.h:36-85;
report_times, src/timer.c:67-90).  Same idea here: a process-global
registry, `timers.start/stop(name)` brackets, and a leveled report.

Since the trace layer landed (splatt_tpu/trace.py,
docs/observability.md) every timer bracket is ALSO a ``timer.<name>``
span: with tracing enabled the exact same brackets this module
aggregates appear on the exported Chrome trace, so the leveled text
report is a view over the trace rather than a second, driftable
accounting.  With tracing disabled the span handles are shared no-ops
and only the wall-clock totals below exist.

Report honesty: a started-but-never-stopped timer used to report its
stale accumulated total with no hint that the bracket was still open
(the pre-trace double-report drift).  :meth:`Timer.current` now folds
the running interval in, and :meth:`TimerRegistry.report` marks such
timers ``(running)`` — the printed number is the time actually spent,
not the total as of the last stop().

JAX note: device work is asynchronous — wrap regions whose cost you want
attributed with ``block=True`` (calls ``block_until_ready`` on a token) or
time whole steps; fine-grained on-device attribution belongs to the JAX
profiler, not wall clocks.
"""

from __future__ import annotations

import time
from typing import Dict

# Report levels (≙ timer_lvl in src/timer.h): 0 none, 1 summary, 2 detail.
_DEFAULT_LEVELS = {
    "total": 1,
    "io": 1,
    "blocked_build": 1,   # ≙ TIMER_CSF
    "sort": 2,            # ≙ TIMER_SORT
    "cpd": 1,             # ≙ TIMER_CPD
    "mttkrp": 2,          # ≙ TIMER_MTTKRP
    "solve": 2,           # ≙ TIMER_INV
    "normalize": 2,       # ≙ TIMER_MATNORM
    "gram": 2,            # ≙ TIMER_ATA
    "fit": 2,             # ≙ TIMER_FIT
    "reorder": 2,         # ≙ TIMER_PART
    "bench": 1,
}


class Timer:
    __slots__ = ("name", "seconds", "_t0", "running", "level", "_span")

    def __init__(self, name: str, level: int = 2) -> None:
        self.name = name
        self.seconds = 0.0
        self._t0 = 0.0
        self.running = False
        self.level = level
        self._span = None

    def start(self) -> None:
        if not self.running:
            self.running = True
            # the bracket is also a timer.<name> span (a shared no-op
            # when tracing is off) — one accounting, two views
            from splatt_tpu import trace

            self._span = trace.begin(f"timer.{self.name}")
            self._t0 = time.perf_counter()

    def stop(self) -> None:
        if self.running:
            self.seconds += time.perf_counter() - self._t0
            self.running = False
            if self._span is not None:
                from splatt_tpu import trace

                trace.end(self._span)
                self._span = None

    def current(self) -> float:
        """Accumulated seconds INCLUDING the still-running interval —
        the honest total a report must print (the old `.seconds` read
        went stale the moment a bracket was left open)."""
        if self.running:
            return self.seconds + (time.perf_counter() - self._t0)
        return self.seconds

    def reset(self) -> None:
        self.seconds = 0.0
        self.running = False
        if self._span is not None:
            # close (not drop) a still-open bracket's span: a leaked
            # open handle would stay in the recorder forever and
            # mis-parent every later span in this context
            from splatt_tpu import trace

            trace.end(self._span)
            self._span = None


class TimerRegistry:
    def __init__(self) -> None:
        self._timers: Dict[str, Timer] = {}
        for name, lvl in _DEFAULT_LEVELS.items():
            self._timers[name] = Timer(name, lvl)

    def get(self, name: str, level: int = 2) -> Timer:
        if name not in self._timers:
            self._timers[name] = Timer(name, level)
        return self._timers[name]

    def start(self, name: str) -> None:
        self.get(name).start()

    def stop(self, name: str) -> None:
        self.get(name).stop()

    def reset(self) -> None:
        for t in self._timers.values():
            t.reset()

    def __getitem__(self, name: str) -> float:
        return self.get(name).current()

    class _Bracket:
        def __init__(self, timer: Timer) -> None:
            self.timer = timer

        def __enter__(self):
            self.timer.start()
            return self.timer

        def __exit__(self, *exc):
            self.timer.stop()
            return False

    def time(self, name: str) -> "TimerRegistry._Bracket":
        return self._Bracket(self.get(name))

    def report(self, level: int = 1) -> str:
        """≙ report_times (src/timer.c:67-90).  Running (never-stopped)
        timers report their live total, marked ``(running)``."""
        lines = ["", "Timing information ---------------------------------"]
        for t in self._timers.values():
            secs = t.current()
            if secs > 0 and t.level <= level:
                mark = "  (running)" if t.running else ""
                lines.append(f"  {t.name + ':':<16s} {secs:0.3f}s{mark}")
        return "\n".join(lines)


timers = TimerRegistry()
