"""splint core: file model, ignore pragmas, baseline, and the run loop.

The analyzer is deliberately pure — stdlib ``ast`` + ``tokenize``, no
imports of the analyzed package (importing ``splatt_tpu`` would pull
jax into every lint run and couple the checker to a working runtime).
Everything a rule needs — module alias maps, dotted-name resolution,
declared registries — is derived statically from source.
"""

from __future__ import annotations

import ast
import dataclasses
import io
import json
import re
import tokenize
from pathlib import Path
from typing import Dict, List, Optional, Tuple

from tools.splint.config import Config


@dataclasses.dataclass
class Finding:
    """One rule violation at a source location."""

    rule: str
    path: str        # repo-relative, posix separators
    line: int
    message: str
    hint: str = ""

    @property
    def key(self) -> str:
        """Baseline grouping key.  Deliberately line-free: baselines
        keyed on line numbers churn on every unrelated edit; keying on
        (rule, file) with a count makes the baseline a burn-down
        ledger instead of a merge-conflict generator."""
        return f"{self.rule}:{self.path}"

    def as_dict(self, baselined: bool) -> dict:
        return dict(rule=self.rule, path=self.path, line=self.line,
                    message=self.message, hint=self.hint,
                    baselined=baselined)


@dataclasses.dataclass
class Report:
    """Outcome of one analyzer run over a project."""

    findings: List[Finding]            # all unsuppressed findings
    new: List[Finding]                 # findings over baseline budget
    suppressed: int                    # inline-pragma suppressions
    stale: List[str]                   # baseline keys with 0 findings
    shrunk: Dict[str, Tuple[int, int]]  # key -> (found, allowed), found<allowed

    @property
    def ok(self) -> bool:
        return not self.new


# -- ignore pragmas ---------------------------------------------------------

_PRAGMA_RE = re.compile(
    r"#\s*splint:\s*ignore\[\s*([A-Z0-9,\s]+?)\s*\]\s*(.*)$")
_PRAGMA_HINT_RE = re.compile(r"#\s*splint\s*:")


class Ignores:
    """Per-file map of ``# splint: ignore[RULES] reason`` pragmas.

    An inline pragma applies to its own line; a full-line comment
    pragma applies to the next non-blank, non-comment line (so a
    multi-line justification comment still covers the code below it).
    """

    def __init__(self, source: str):
        #: target line -> (set of rule ids, reason, pragma line)
        self.targets: Dict[int, Tuple[set, str, int]] = {}
        #: pragma parse problems -> SPL000 findings
        self.errors: List[Tuple[int, str]] = []
        lines = source.splitlines()
        try:
            tokens = list(tokenize.generate_tokens(
                io.StringIO(source).readline))
        except (tokenize.TokenError, IndentationError, SyntaxError):
            return  # the file-level parse error is reported elsewhere
        for tok in tokens:
            if tok.type != tokenize.COMMENT:
                continue
            m = _PRAGMA_RE.search(tok.string)
            if not m:
                if _PRAGMA_HINT_RE.search(tok.string) and \
                        "ignore" in tok.string:
                    self.errors.append(
                        (tok.start[0],
                         "malformed splint pragma (want "
                         "'# splint: ignore[RULE] reason')"))
                continue
            rules = {r.strip() for r in m.group(1).split(",") if r.strip()}
            reason = m.group(2).strip()
            row, col = tok.start
            full_line = lines[row - 1][:col].strip() == ""
            target = row
            if full_line:
                # skip over blank/comment lines (incl. the pragma's own
                # continuation comments) to the code line below
                t = row
                while t < len(lines):
                    nxt = lines[t].strip()
                    if nxt and not nxt.startswith("#"):
                        target = t + 1
                        break
                    t += 1
            prev = self.targets.get(target)
            if prev:
                rules = rules | prev[0]
                reason = reason or prev[1]
            self.targets[target] = (rules, reason, row)

    def suppresses(self, finding: Finding) -> Optional[Tuple[str, int]]:
        """(reason, pragma_line) when `finding` is pragma-suppressed."""
        entry = self.targets.get(finding.line)
        if entry and finding.rule in entry[0]:
            return entry[1], entry[2]
        return None


# -- file / project model ---------------------------------------------------

class FileCtx:
    """One analyzed source file: path, AST, alias map, pragmas."""

    def __init__(self, path: Path, relpath: str, source: str,
                 tree: ast.AST):
        self.path = path
        self.relpath = relpath
        self.source = source
        self.tree = tree
        self.ignores = Ignores(source)
        self._aliases: Optional[Dict[str, str]] = None
        self._consts: Optional[Dict[str, str]] = None

    @property
    def aliases(self) -> Dict[str, str]:
        """name -> dotted module/object it is bound to, from imports
        (``import numpy as np`` -> {'np': 'numpy'}; ``from jax import
        numpy as jnp`` -> {'jnp': 'jax.numpy'})."""
        if self._aliases is None:
            amap: Dict[str, str] = {}
            for node in ast.walk(self.tree):
                if isinstance(node, ast.Import):
                    for a in node.names:
                        amap[a.asname or a.name.split(".")[0]] = (
                            a.name if a.asname else a.name.split(".")[0])
                elif isinstance(node, ast.ImportFrom) and node.module:
                    for a in node.names:
                        amap[a.asname or a.name] = \
                            f"{node.module}.{a.name}"
            self._aliases = amap
        return self._aliases

    @property
    def str_consts(self) -> Dict[str, str]:
        """Simple module/function-level ``NAME = "literal"`` bindings —
        lets rules resolve ``read_env(_CACHE_ENV)`` to its value."""
        if self._consts is None:
            consts: Dict[str, str] = {}
            for node in ast.walk(self.tree):
                if (isinstance(node, ast.Assign)
                        and len(node.targets) == 1
                        and isinstance(node.targets[0], ast.Name)
                        and isinstance(node.value, ast.Constant)
                        and isinstance(node.value.value, str)):
                    consts[node.targets[0].id] = node.value.value
            self._consts = consts
        return self._consts

    def resolve(self, node: ast.AST) -> Optional[str]:
        """Dotted name of an expression through the alias map:
        ``np.asarray`` -> 'numpy.asarray', ``os.environ.get`` ->
        'os.environ.get'.  None for non-name expressions."""
        parts: List[str] = []
        while isinstance(node, ast.Attribute):
            parts.append(node.attr)
            node = node.value
        if not isinstance(node, ast.Name):
            return None
        base = self.aliases.get(node.id, node.id)
        return ".".join([base] + list(reversed(parts)))


class Project:
    """Cross-file state shared by the rules during one run."""

    def __init__(self, config: Config):
        self.config = config
        self.files: List[FileCtx] = []
        self.parse_errors: List[Finding] = []
        self._extra: Dict[str, Optional[FileCtx]] = {}

    def ctx_for(self, rel: str) -> Optional[FileCtx]:
        """FileCtx for a project module that may live outside the
        analyzed paths (env/faults modules, test files)."""
        for ctx in self.files:
            if ctx.relpath == rel:
                return ctx
        if rel not in self._extra:
            path = self.config.resolve(rel)
            # a registry module a mini-project simply doesn't have is
            # "nothing declared", not a parse error
            self._extra[rel] = (_load_file(path, rel, self.parse_errors)
                                if path.is_file() else None)
        return self._extra[rel]

    def test_ctxs(self) -> List[FileCtx]:
        tests_root = self.config.resolve(self.config.tests_path)
        out = []
        if tests_root.is_dir():
            for p in sorted(tests_root.rglob("*.py")):
                rel = _relpath(p, self.config.root)
                # splint's own rule fixtures arm deliberately-bogus
                # sites; they must not count as "exercised by a test"
                if "splint_fixtures" in rel:
                    continue
                ctx = self.ctx_for(rel)
                if ctx is not None:
                    out.append(ctx)
        return out


def _relpath(p: Path, root: Path) -> str:
    try:
        return p.resolve().relative_to(root.resolve()).as_posix()
    except ValueError:
        return p.as_posix()


def _load_file(path: Path, rel: str,
               errors: List[Finding]) -> Optional[FileCtx]:
    try:
        source = path.read_text()
        tree = ast.parse(source, filename=str(path))
    except (OSError, SyntaxError, ValueError) as e:
        errors.append(Finding(
            "SPL000", rel, getattr(e, "lineno", None) or 1,
            f"cannot analyze file: {type(e).__name__}: {e}"))
        return None
    return FileCtx(path, rel, source, tree)


def collect_files(config: Config) -> List[Path]:
    out: List[Path] = []
    for entry in config.paths:
        p = config.resolve(entry)
        if p.is_file():
            out.append(p)
        elif p.is_dir():
            out.extend(sorted(p.rglob("*.py")))
    return [p for p in out
            if not any(x in _relpath(p, config.root)
                       for x in config.exclude)]


# -- baseline ---------------------------------------------------------------

def load_baseline(path: Path) -> Dict[str, dict]:
    """Baseline entries: ``{"RULE:path": {"count": N, "reason": ...}}``."""
    if not path.exists():
        return {}
    data = json.loads(path.read_text())
    entries = data.get("entries", {})
    for key, entry in entries.items():
        if "count" not in entry:
            raise ValueError(f"splint baseline entry {key!r} has no count")
    return entries


def update_baseline(path: Path, report: Report) -> Dict[str, dict]:
    """Rewrite the baseline from the current findings, preserving the
    reasons of surviving entries.  Newly grandfathered groups get an
    UNJUSTIFIED placeholder — tests refuse a baseline containing one,
    so every grandfathered entry carries a human-written reason."""
    old = load_baseline(path) if path.exists() else {}
    groups: Dict[str, int] = {}
    for f in report.findings:
        groups[f.key] = groups.get(f.key, 0) + 1
    entries = {}
    for key in sorted(groups):
        reason = old.get(key, {}).get(
            "reason", "UNJUSTIFIED: justify this grandfathered group "
                      "or fix the findings")
        entries[key] = {"count": groups[key], "reason": reason}
    path.parent.mkdir(parents=True, exist_ok=True)
    path.write_text(json.dumps(
        {"comment": "splint grandfathered findings — burn this down; "
                    "regenerate with python -m tools.splint "
                    "--update-baseline (reasons are preserved)",
         "version": 1, "entries": entries}, indent=1, sort_keys=True)
        + "\n")
    return entries


# -- run loop ---------------------------------------------------------------

def run(config: Config, baseline: Optional[Dict[str, dict]] = None,
        rules=None) -> Report:
    """Analyze the configured paths and reconcile against `baseline`."""
    from tools.splint.rules import RULES

    rules = RULES if rules is None else rules
    project = Project(config)
    for path in collect_files(config):
        rel = _relpath(path, config.root)
        ctx = _load_file(path, rel, project.parse_errors)
        if ctx is not None:
            project.files.append(ctx)

    raw: List[Finding] = list(project.parse_errors)
    for rule in rules:
        for ctx in project.files:
            raw.extend(rule.check(ctx, project))
    for rule in rules:
        raw.extend(rule.finalize(project))

    findings: List[Finding] = []
    suppressed = 0
    for f in sorted(raw, key=lambda f: (f.path, f.line, f.rule)):
        ctx = next((c for c in project.files if c.relpath == f.path), None)
        hit = ctx.ignores.suppresses(f) if ctx else None
        if hit is not None:
            suppressed += 1
            reason, pragma_line = hit
            if not reason:
                findings.append(Finding(
                    "SPL000", f.path, pragma_line,
                    f"ignore pragma for {f.rule} has no reason — the "
                    f"escape hatch requires a justification"))
            continue
        findings.append(f)
    # pragma syntax problems surface even when nothing was suppressed
    for ctx in project.files:
        for line, msg in ctx.ignores.errors:
            findings.append(Finding("SPL000", ctx.relpath, line, msg))

    baseline = baseline or {}
    groups: Dict[str, List[Finding]] = {}
    for f in findings:
        groups.setdefault(f.key, []).append(f)
    new: List[Finding] = []
    shrunk: Dict[str, Tuple[int, int]] = {}
    for key, group in sorted(groups.items()):
        allowed = int(baseline.get(key, {}).get("count", 0))
        if len(group) > allowed:
            if allowed:
                for f in group:
                    f.message += (f" [group {key}: {len(group)} found > "
                                  f"{allowed} baselined]")
            new.extend(group)
        elif len(group) < allowed:
            shrunk[key] = (len(group), allowed)
    stale = sorted(k for k in baseline if k not in groups)
    return Report(findings=findings, new=new, suppressed=suppressed,
                  stale=stale, shrunk=shrunk)
