"""Test configuration.

Must run before jax is imported anywhere: force the CPU platform with 8
virtual devices so multi-chip sharding tests run on a single host
(≙ the reference testing MPI paths with `mpirun -np 4 / -np 7` on one
machine, scripts/mpi_test.sh), and enable x64 so differential tests can
use the reference's double-precision tolerances (tests/mttkrp_test.c:25-30).
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (_flags + " --xla_force_host_platform_device_count=8").strip()

# NOTE on SPLATT_COMPILE_CACHE (utils/env.py): do NOT enable the
# persistent executable cache suite-wide here.  On this jaxlib, a
# DESERIALIZED multi-device (8-virtual-device sharded) CPU executable
# corrupts the heap on execution — malloc() abort inside pxla — so the
# main pytest process, which runs the sharded paths constantly, must
# never read cache entries.  Single-device executables round-trip
# fine; the fleet chaos soak scopes the knob to its replica daemons
# (single-device jobs only), which is also the production shape.
import jax

# The env var alone is not enough where a site plugin (e.g. the axon TPU
# relay) selects platforms via jax.config at interpreter startup — the
# config programmatically set wins over JAX_PLATFORMS.  Setting it here,
# before any backend initializes, forces pure-CPU tests and keeps the
# single real TPU chip free (and avoids serializing test processes on
# its lease).
jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_enable_x64", True)

import numpy as np
import pytest

from tests import gen


def pytest_configure(config):
    # the tier-1 gate runs `-m 'not slow'` (ROADMAP.md); register the
    # marker so slow-tier tests don't warn as unknown
    config.addinivalue_line(
        "markers", "slow: excluded from the tier-1 `-m 'not slow'` "
                   "gate (full sweeps, 3-replica interleavings)")


@pytest.fixture(scope="session")
def tensors_dir(tmp_path_factory):
    """Generate the fixture tensor files once per session."""
    d = tmp_path_factory.mktemp("tensors")
    gen.write_fixtures(d)
    return d


@pytest.fixture(params=["small", "med", "small4", "med4", "med5"])
def any_tensor(request):
    """All fixture tensors as in-memory COO (≙ tests/tensors/*.tns sweep)."""
    return gen.fixture_tensor(request.param)
