"""splint — the project-native static-analysis pass.

An AST-based analyzer (stdlib only, no new dependencies) that enforces
the code-shape invariants this codebase's resilience and dispatch
layers depend on — properties no behavioral test can catch, because
the hazard is what the code *would* do on the day the infrastructure
misbehaves (PR 1 existed because one broad ``except Exception``
persisted a transient HTTP 500 as a permanent engine demotion).

Rules (see docs/static-analysis.md for the full catalog):

- SPL000 — splint usage errors (malformed/reasonless ignore pragmas,
  unparseable files)
- SPL001 — raw ``os.environ`` access outside ``utils/env.py``
- SPL002 — ``except Exception`` that swallows the failure class
- SPL003 — host-device sync inside jitted functions / hot paths
- SPL004 — recompilation hazards (Python branches on non-static jit args)
- SPL005 — dtype literals outside ``config.py``
- SPL006 — fault-site drift against ``utils/faults.py:SITES``
- SPL007 — undocumented ``SPLATT_*`` environment variables
- SPL008–SPL013 — the dataflow/registry family (use-after-donate,
  tracer leaks, recompile triggers, cache-lock discipline, run-report
  event and span-name drift)
- SPL014–SPL018 — the concurrency family (tools/splint/locks.py):
  shared-state writes without the owning lock, lock-order cycles,
  durability-protocol drift, blocking calls under a control-plane
  lock, contextvar set/reset leaks — paired with the dynamic side,
  ``tools/splint/interleave.py``, a bounded-exhaustive interleaving
  checker for the fleet lease protocol
- SPL019–SPL023 — the durability family (tools/splint/durability.py):
  atomic-publish protocol order, lease-fenced terminal appends,
  stamp/persist pairing, journal-kind vocabulary, fsync barriers
  under durable roots — paired with ``tools/splint/crashpoint.py``,
  the exhaustive crash-point replay checker
- SPL024–SPL028 — the numerics/tiling family
  (tools/splint/numerics.py, tools/splint/tiling.py):
  accumulation-dtype discipline via an abstract dtype-lattice
  interpreter, Pallas tile alignment per dtype packing, static VMEM
  envelopes with a kernel→gate registry, plan-cache schema
  completeness, narrow×wide hot-stream products — paired with
  ``tools/splint/dtypecheck.py``, the eval_shape dtype oracle
- SPL029 — metric-name drift against ``trace.py:METRICS``

Escape hatch: ``# splint: ignore[SPL002] <reason>`` on the flagged
line (inline) or as a full-line comment directly above it; the reason
is mandatory.  Grandfathered findings live in a checked-in baseline
(``tools/splint/baseline.json``) so new violations fail while old ones
burn down.

Run: ``python -m tools.splint [--json]``; configured via
``[tool.splint]`` in pyproject.toml; wired into tier-1 by
``tests/test_splint.py``.
"""

from tools.splint.config import Config, load_config
from tools.splint.core import (Finding, Report, load_baseline, run,
                               update_baseline)
from tools.splint.rules import RULES

__all__ = ["Config", "Finding", "Report", "RULES", "load_baseline",
           "load_config", "run", "update_baseline"]
