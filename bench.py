"""Benchmark driver: CPD-ALS sec/iteration (≙ BASELINE.json primary metric).

Runs rank-50 CPD-ALS on a NELL-2-shaped synthetic sparse tensor
(3-mode, power-law slice skew; NELL-2 itself — FROSTT, 77M nnz — is not
downloadable in this environment).  Prints ONE JSON line:

    {"metric": ..., "value": N, "unit": "sec/iter", "vs_baseline": N}

``vs_baseline`` is reference_sec_per_iter / ours (higher is better) when
a measured reference number exists in BASELINE_MEASURED.json; else 1.0.

Env knobs: SPLATT_BENCH_NNZ (default 20_000_000), SPLATT_BENCH_RANK (50),
SPLATT_BENCH_ITERS (3 timed iterations), SPLATT_BENCH_DTYPE
(float32 default; bfloat16 stores factors in bf16 with f32 accumulation),
SPLATT_BENCH_ENGINE (auto|pallas|xla — one-hot reduction engine; auto
lets dispatch probe Mosaic capability on TPU), SPLATT_BENCH_ALLOC
(allmode default — every mode gets its sorted layout; twomode/onemode
for the reference's memory-lean policies), SPLATT_BENCH_JIT
(auto|fused|phased — whole-sweep jit vs. per-phase jits; auto picks
phased on TPU where the fused program wedges the remote compiler),
SPLATT_BENCH_SHAPE (nell2 default | enron4 — the 4-mode Enron-shaped
workload of BASELINE.md row 2), SPLATT_BENCH_SCENARIO (uniform default
| zipf:<a> | powerlaw | amazon-like — named nnz-distribution scenarios,
docs/layout-balance.md; non-uniform scenarios tag the metric string and
carry per-scenario imbalance stats; batched — the K-tenant fleet A/B,
docs/batched.md; predict — the prediction plane's hot-cache vs
direct-fenced-read request-latency A/B with p50/p99 + cache hit rate,
docs/predict.md, sized by SPLATT_BENCH_PREDICT_B entries/request and
SPLATT_BENCH_PREDICT_N requests/leg), SPLATT_BENCH_PATHS
("blocked,balanced,compact,tuned,stream" default — which
representations to measure; "balanced" is the load-balanced row:
nnz-packed fibers with long-fiber splitting (docs/layout-balance.md);
"compact" is the format-v2 row: local narrow indices +
segment encoding + bf16 storage (docs/format.md), timed with matching
bf16 factors; "tuned" runs the splatt-tune autotuner (warm plan cache
= zero measurement) and times the winning plan — now including format,
packing and reorder candidates — reported with the chosen
engine/nnz_block/scan_target/format under "tuned_plan"; "blocked"
alone skips the slow stream oracle on long-rank configs / scarce chip
time), SPLATT_BENCH_GUARD_AB (1 = time cpd_als with the health
sentinel on/off x donation on/off and record the legs under
"guard_ab" — ROADMAP open item 1's explicit guard-cost measurement),
SPLATT_BENCH_TRACE_AB (1 = time cpd_als with span recording
enabled-but-unexported vs off — plus a leg with the flight-recorder
ring armed — and record the legs under "trace_ab": the <2%
tracing-overhead budget of docs/observability.md, measured, for both
the tracing and the black-box steady states).

Bytes are reported per path from the ENCODED layouts
(bench_algs.mttkrp_bytes_encoded) PLUS each path's operand-prep decode
traffic (bench_algs.mttkrp_decode_bytes, per the engine its plan
names): ``model_gb_per_path`` carries the achieved bytes/iteration,
``decode_overhead`` the achieved/encoded ratio (~1.0 when the plan
consumes the compact streams natively — the fused_v2 kernel or the
per-chunk scan decode — vs ~2x under operand-prep decode,
docs/format.md), ``format`` the achieved encoding summary, and the
regression gate compares the bytes too — a format OR engine change
that silently re-inflates traffic >10% fails ``--gate`` exactly like a
time regression.

Regression gate (ROADMAP open item 1): the fresh result is compared
against the newest prior ``BENCH_*.json`` (same metric only — unlike
workloads are never compared); any headline or per-path slowdown
beyond 10% is recorded as a ``bench_regression`` run-report event and
rides along in the JSON under ``"bench_regressions"``.  Run with
``--gate`` to turn regressions into a nonzero exit, so a perf PR ships
with a verdict, not just a number.  SPLATT_BENCH_PRIOR_DIR overrides
where priors are searched (tests).
"""

from __future__ import annotations

import json
import os
import sys
import time

import numpy as np

from splatt_tpu.utils.env import apply_compile_cache, apply_env_platform

apply_env_platform()
apply_compile_cache()


def synthetic_tensor(dims, nnz: int, seed: int = 0):
    """Power-law synthetic tensor: zipf-skewed indices per mode."""
    from splatt_tpu.coo import SparseTensor

    rng = np.random.default_rng(seed)
    inds = np.empty((len(dims), nnz), dtype=np.int64)
    for m, d in enumerate(dims):
        # zipf-ish skew, cycled through the mode so every slice is nonempty
        raw = rng.zipf(1.3, size=nnz).astype(np.int64)
        inds[m] = (raw * 2654435761 + rng.integers(0, d, size=nnz)) % d
    vals = rng.random(nnz)
    return SparseTensor(inds, vals, dims)


# workload shapes: NELL-2-like 3-mode (flagship) and Enron-like 4-mode
# (exercises the n-mode generic paths, ≙ BASELINE.md rows 2-3)
SHAPES = {
    "nell2": (12092, 9184, 28818),
    "enron4": (6066, 5699, 244268, 1176),
}

# scenario shape presets (docs/layout-balance.md): power-law MODE SIZES
# (three orders of magnitude between dims) and an Amazon-reviews-like
# (user x item x word) shape at 1/100 scale
SCENARIO_SHAPES = {
    "powerlaw": (131072, 4096, 128),
    "amazon-like": (48212, 17742, 18051),
    # one near-dense mode (docs/dense.md): at the default densemode
    # nnz the mode-0 unfolding fills past the dense threshold while
    # the tensor stays sparse by COO standards — the workload class
    # the dense tile layout + MXU engine exist for
    "densemode": (24, 256, 512),
}

#: per-mode zipf exponents of the amazon-like scenario: reviews/user
#: and reviews/item are heavy power-laws, word frequency is zipfian
#: but flatter at this truncation
_AMAZON_EXPONENTS = (1.5, 1.5, 1.2)


def synthetic_nell2_like(nnz: int, seed: int = 0):
    """Power-law 3-mode tensor with NELL-2-ish dims (12k × 9k × 29k)."""
    return synthetic_tensor(SHAPES["nell2"], nnz, seed)


def synthetic_zipf(dims, nnz: int, a=1.5, seed: int = 0,
                   exponents=None):
    """GENUINELY zipf-skewed synthetic tensor: slice popularity per
    mode follows zipf(a) (the hottest slice holds a macroscopic share
    of all nonzeros), with hot slices scattered across the index space
    by a fixed permutation.  Unlike :func:`synthetic_tensor` — whose
    per-nnz uniform offset destroys the zipf head, leaving an
    effectively uniform tensor — this is the power-law input the
    balanced layouts exist for (docs/layout-balance.md)."""
    from splatt_tpu.coo import SparseTensor

    rng = np.random.default_rng(seed)
    inds = np.empty((len(dims), nnz), dtype=np.int64)
    for m, d in enumerate(dims):
        am = float(exponents[m]) if exponents is not None else float(a)
        raw = (rng.zipf(am, size=nnz) - 1) % d
        inds[m] = rng.permutation(d)[raw]
    vals = rng.random(nnz)
    return SparseTensor(inds, vals, dims)


def scenario_tensor(scenario: str, shape: str, nnz: int, seed: int):
    """Build the bench tensor for a named scenario → (tt, desc, label).

    `desc` feeds the metric string; `label` is None for the default
    uniform scenario (metric string byte-identical to prior BENCH
    artifacts) and the scenario tag otherwise — the regression gate
    compares same-metric priors only, so scenarios never gate against
    unlike workloads."""
    names = {"nell2": "NELL-2-shaped", "enron4": "Enron-shaped"}
    if scenario in ("", "uniform", None):
        return (synthetic_tensor(SHAPES[shape], nnz, seed),
                names[shape], None)
    if scenario == "zipf" or scenario.startswith("zipf:"):
        # exact spellings only: a typo like "zipf1.8" must hit the
        # unknown-scenario error below, not silently bench exponent 1.5
        a = float(scenario.split(":", 1)[1]) if ":" in scenario else 1.5
        if not 1.0 < a <= 4.0:
            raise ValueError(f"zipf exponent must be in (1, 4], got {a}")
        label = f"zipf{a:g}"
        return (synthetic_zipf(SHAPES[shape], nnz, a=a, seed=seed),
                f"{names[shape]} {label}-skewed", label)
    if scenario == "powerlaw":
        return (synthetic_zipf(SCENARIO_SHAPES["powerlaw"], nnz, a=1.3,
                               seed=seed),
                "power-law-mode-size", "powerlaw")
    if scenario == "amazon-like":
        return (synthetic_zipf(SCENARIO_SHAPES["amazon-like"], nnz,
                               seed=seed, exponents=_AMAZON_EXPONENTS),
                "Amazon-like review-tensor", "amazon-like")
    if scenario == "densemode":
        return (synthetic_tensor(SCENARIO_SHAPES["densemode"], nnz, seed),
                "dense-mode", "densemode")
    raise ValueError(
        f"unknown SPLATT_BENCH_SCENARIO {scenario!r}; want uniform, "
        f"zipf:<a>, powerlaw, amazon-like, densemode, batched, "
        f"predict or ingest")


def _timing_cv(times) -> float:
    """Coefficient of variation of a timing sample (population stddev
    over mean; 0.0 on degenerate input) — the ONE dispersion
    definition every artifact path records and the CV-aware gate
    reads (ISSUE 14: four hand-rolled copies disagreeing someday is
    exactly how a noise rule rots)."""
    if not times:
        return 0.0
    mean = sum(times) / len(times)
    if mean <= 0:
        return 0.0
    var = sum((t - mean) ** 2 for t in times) / len(times)
    return (var ** 0.5) / mean


def _ref_sec_per_iter(measured: dict, shape: str, nnz: int, rank: int):
    """Reference sec/it for this exact workload from
    BASELINE_MEASURED.json, or None when it was never measured (then
    vs_baseline stays 1.0 rather than comparing unlike workloads)."""
    det = measured.get("details", {})
    if shape == "nell2":
        if rank == 200 and nnz == 20_000_000:
            return det.get("nell2_20m_rank200",
                           {}).get("reference_sec_per_iter")
        if rank == 50:
            return measured.get("cpd_sec_per_iter", {}).get(str(nnz))
    if shape == "enron4" and nnz == 5_000_000 and rank == 25:
        return det.get("enron4mode_5m_rank25",
                       {}).get("reference_sec_per_iter")
    return None


def _scaling_child(n: int) -> None:
    """One scaling-sweep measurement at `n` virtual CPU devices (the
    parent set XLA_FLAGS/JAX_PLATFORMS before this interpreter
    started).  Prints one ``SCALING {json}`` line.

    sec/iter is the median of the per-iteration wall clocks the
    distributed driver prints (each iteration is host-synced by the fit
    fetch at fit_check_every=1), skipping the first two iterations —
    they carry compile time.
    """
    import contextlib
    import io
    import re

    import jax

    jax.config.update("jax_platforms", "cpu")
    from splatt_tpu.config import Options, Verbosity
    from splatt_tpu.parallel.sharded import sharded_cpd_als

    nnz = int(os.environ.get("SPLATT_BENCH_NNZ", 2_000_000))
    rank = int(os.environ.get("SPLATT_BENCH_RANK", 16))
    iters = int(os.environ.get("SPLATT_BENCH_ITERS", 3))
    shape = os.environ.get("SPLATT_BENCH_SHAPE", "nell2")
    tt = synthetic_tensor(SHAPES.get(shape, SHAPES["nell2"]), nnz,
                          seed=1 if shape == "enron4" else 0)

    opts = Options(random_seed=7, verbosity=Verbosity.LOW,
                   val_dtype=np.float32, max_iterations=2 + iters,
                   tolerance=0.0, fit_check_every=1)
    buf = io.StringIO()
    with contextlib.redirect_stdout(buf):
        sharded_cpd_als(tt, rank, opts=opts)
    times = [float(s) for s in
             re.findall(r"its =\s*\d+ \(([0-9.]+)s\)", buf.getvalue())]
    steady = sorted(times[2:]) or sorted(times)
    sec = steady[len(steady) // 2] if steady else None
    # dispersion rides every timing artifact (ISSUE 14 satellite): a
    # scaling point without its CV cannot be judged against the
    # 2x-CV noise rule later
    cv = round(_timing_cv(steady), 4) if steady else None
    print("SCALING " + json.dumps(
        dict(n_devices=n,
             sec_per_iter=round(sec, 5) if sec is not None else None,
             cv=cv, nnz=nnz, rank=rank)), flush=True)


def _run_scaling(devices) -> None:
    """Worker-count scaling sweep over virtual CPU devices (≙ the
    thread-scaling loop of the reference's bench verb,
    src/bench.c:84-117,95-101 — the TPU analog scales devices, since
    XLA owns the chip's cores).  One subprocess per device count (the
    virtual device count is fixed at interpreter start), reporting
    sec/iter and parallel efficiency vs the smallest count."""
    import subprocess

    results = {}
    for n in devices:
        env = dict(os.environ)
        env["SPLATT_SCALING_CHILD"] = str(n)
        env["JAX_PLATFORMS"] = "cpu"
        flags = [f for f in env.get("XLA_FLAGS", "").split()
                 if "device_count" not in f]
        env["XLA_FLAGS"] = " ".join(
            flags + [f"--xla_force_host_platform_device_count={n}"])
        try:
            p = subprocess.run([sys.executable, os.path.abspath(__file__)],
                               env=env, capture_output=True, text=True,
                               timeout=1800)
            line = [l for l in p.stdout.splitlines()
                    if l.startswith("SCALING ")]
            results[n] = (json.loads(line[0][8:]) if line
                          else dict(error=p.stderr[-200:]))
        except subprocess.SubprocessError as e:
            results[n] = dict(error=str(e)[:200])
        print(f"bench: scaling n={n}: {results[n]}", file=sys.stderr,
              flush=True)
    n0 = devices[0]
    base = results.get(n0, {}).get("sec_per_iter")
    rows = []
    for n in devices:
        sec = results.get(n, {}).get("sec_per_iter")
        # a 0.0 measurement (iteration under the print resolution) is a
        # valid result, just unusable as a ratio denominator
        eff = (round(base * n0 / (n * sec), 3)
               if base and sec else (1.0 if n == n0 and base is not None
                                     else None))
        rows.append(dict(n_devices=n, sec_per_iter=sec, efficiency=eff))
    ok = [r for r in rows if r["sec_per_iter"] is not None]
    best = min(ok, key=lambda r: r["sec_per_iter"]) if ok else {}
    rec = dict(
        metric=f"CPD-ALS device-scaling sweep (fine decomposition, "
               f"virtual CPU devices {list(devices)})",
        value=best.get("sec_per_iter", 0.0),
        unit="sec/iter",
        vs_baseline=1.0,
        scaling=rows)
    if not ok:
        # a 0.0 "measurement" must not masquerade as a fast run
        rec["error"] = "all device counts failed; see stderr"
    print(json.dumps(rec, allow_nan=False), flush=True)
    if not ok:
        raise SystemExit(1)


def _guard_ab_legs(tt, rank: int, iters: int, bench_dtype, use_pallas,
                   alloc) -> dict:
    """Guard-cost A/B (ROADMAP open item 1): time the full cpd_als
    driver — the layer the guards actually live in; the raw-sweep
    timings above never execute them — with the health sentinel
    on/off x donation on/off, over the same blocked layouts.
    sec/iter per leg is the median of the per-iteration wall clocks
    cpd_als prints (first two skipped: compile), recorded under
    ``guard_ab`` in the bench JSON so the gate — and ROADMAP's r05
    investigation — can see guard cost explicitly instead of inferring
    it from cross-PR noise."""
    import contextlib
    import io
    import re

    from splatt_tpu import resilience
    from splatt_tpu.blocked import BlockedSparse
    from splatt_tpu.config import Options, Verbosity
    from splatt_tpu.cpd import cpd_als

    X = BlockedSparse.from_coo(
        tt, Options(random_seed=7, verbosity=Verbosity.NONE,
                    val_dtype=bench_dtype, use_pallas=use_pallas,
                    block_alloc=alloc, autotune=False))
    legs = {}
    for retries in (3, 0):
        for donate in (True, False):
            label = (f"guard_{'on' if retries else 'off'}:"
                     f"donate_{'on' if donate else 'off'}")
            opts = Options(random_seed=7, verbosity=Verbosity.LOW,
                           val_dtype=bench_dtype, use_pallas=use_pallas,
                           block_alloc=alloc, autotune=False,
                           donate_sweep=donate,
                           max_iterations=iters + 2, tolerance=0.0,
                           fit_check_every=1)
            buf = io.StringIO()
            # a scope per leg: the health budget override rides the
            # scope (serve's mechanism), and leg demotions/events stay
            # isolated from the main bench run
            with resilience.scope(f"bench-{label}",
                                  health_retries=retries):
                with contextlib.redirect_stdout(buf):
                    cpd_als(X, rank, opts=opts)
            times = sorted(float(s) for s in re.findall(
                r"its =\s*\d+ \(([0-9.]+)s\)", buf.getvalue())[2:])
            legs[label] = (round(times[len(times) // 2], 4)
                           if times else None)
            if times:
                # dispersion rides every timing artifact (ISSUE 14
                # satellite): guard legs were the one path still
                # publishing bare medians
                legs[f"{label}_cv"] = round(_timing_cv(times), 4)
    on = legs.get("guard_on:donate_on")
    off = legs.get("guard_off:donate_on")
    # `on` may legitimately round to 0.0 at smoke scale — only a missing
    # leg (None) or a zero denominator drops the headline ratio
    if on is not None and off:
        legs["guard_overhead_pct"] = round((on / off - 1.0) * 100, 1)
    return legs


#: overhead budget of enabled-but-unexported tracing on the blocked
#: path (docs/observability.md): the trace A/B leg records the measured
#: percentage; beyond this the observability layer is taxing the hot
#: loop it exists to observe
TRACE_OVERHEAD_BUDGET_PCT = 2.0


def _trace_ab_legs(tt, rank: int, iters: int, bench_dtype, use_pallas,
                   alloc) -> dict:
    """Trace-overhead A/B (docs/observability.md): time the full
    cpd_als driver over the same blocked layouts with span recording
    ON (enabled but never exported — the steady-state cost of leaving
    SPLATT_TRACE=1 on in production), ON + the flight-recorder ring
    armed (the fleet-replica steady state: every finished span/point
    appended to the bounded black box), and OFF.  sec/iter per leg is
    the median of the per-iteration wall clocks cpd_als prints (first
    two skipped: compile); ``trace_overhead_pct`` /
    ``flight_overhead_pct`` are the headlines the <2%% budget is
    judged against."""
    import contextlib
    import io
    import re
    import tempfile

    from splatt_tpu import trace
    from splatt_tpu.blocked import BlockedSparse
    from splatt_tpu.config import Options, Verbosity
    from splatt_tpu.cpd import cpd_als

    X = BlockedSparse.from_coo(
        tt, Options(random_seed=7, verbosity=Verbosity.NONE,
                    val_dtype=bench_dtype, use_pallas=use_pallas,
                    block_alloc=alloc, autotune=False))
    legs = {}
    # ALTERNATE the legs over two rounds and pool each label's
    # per-iteration samples: the effect under test (a few µs of span
    # bookkeeping per iteration) is far below this host's run-to-run
    # drift, and interleaving cancels slow drift that a
    # one-leg-then-the-other order would book entirely to one side
    samples = {"trace_off": [], "trace_on": [], "trace_flight": []}
    with tempfile.TemporaryDirectory(prefix="splatt-flight-ab-") as td:
        for _ in range(2):
            for label, tr in (("trace_off", False), ("trace_on", True),
                              ("trace_flight", True)):
                opts = Options(random_seed=7, verbosity=Verbosity.LOW,
                               val_dtype=bench_dtype,
                               use_pallas=use_pallas,
                               block_alloc=alloc, autotune=False,
                               trace=tr, max_iterations=iters + 2,
                               tolerance=0.0, fit_check_every=1)
                if label == "trace_flight":
                    trace.set_flight(f"{td}/flight.jsonl")
                before = len(trace.spans())
                buf = io.StringIO()
                try:
                    with contextlib.redirect_stdout(buf):
                        cpd_als(X, rank, opts=opts)
                finally:
                    if label == "trace_flight":
                        trace.set_flight(None)
                if label == "trace_on":
                    # enabled-but-unexported: report the leg's span
                    # count as a delta, and LEAVE the recorder alone —
                    # a caller exporting the whole process's trace
                    # (SPLATT_TRACE=1) keeps its earlier spans; ~100
                    # extra records are noise
                    legs["trace_spans"] = len(trace.spans()) - before
                samples[label] += [float(s) for s in re.findall(
                    r"its =\s*\d+ \(([0-9.]+)s\)", buf.getvalue())[2:]]
    for label, ts in samples.items():
        ts.sort()
        legs[label] = (round(ts[len(ts) // 2], 4) if ts else None)
        if ts:
            legs[f"{label}_cv"] = round(_timing_cv(ts), 4)
    on, off = legs.get("trace_on"), legs.get("trace_off")
    if on is not None and off:
        legs["trace_overhead_pct"] = round((on / off - 1.0) * 100, 1)
        legs["budget_pct"] = TRACE_OVERHEAD_BUDGET_PCT
    flight = legs.get("trace_flight")
    if flight is not None and off:
        legs["flight_overhead_pct"] = round((flight / off - 1.0) * 100,
                                            1)
    return legs


#: slowdown threshold of the regression gate: >10% beyond the newest
#: prior on the same metric flags a bench_regression
REGRESSION_THRESHOLD = 0.10

#: coefficient-of-variation ceiling for a trustworthy timing
#: comparison: a would-be regression whose CV (either side) exceeds
#: this is recorded as a ``bench_noisy`` warning instead of failing
#: the --gate (measured run-to-run spread on the shared CPU host is
#: ±7%; 0.15 leaves headroom without swallowing real 10% slips)
NOISE_CV = 0.15

#: the ROADMAP variance note, made the gate's default: a single-run
#: timing delta smaller than this multiple of the measured CV (either
#: side) is noise, whatever the absolute CV — r07/r08 CVs ran
#: 0.10-0.55 on this shared host, where a "12% regression" against a
#: 10%-CV distribution is one draw, not a verdict
CV_NOISE_MULT = 2.0


def _prior_bench_record(search_dir: str, metric: str = None):
    """(filename, parsed-record) of the newest prior ``BENCH_*.json``
    holding a usable bench record — the newest SAME-METRIC one when
    `metric` is given, so a different workload benched in between
    cannot silently disable the gate against an older comparable
    prior.  Newest = highest name in sort order (the drivers write
    BENCH_r01, BENCH_r02, ...); files without a usable record (or CPU
    side-artifacts without "parsed") are skipped rather than trusted."""
    import glob

    candidates = sorted(glob.glob(os.path.join(search_dir,
                                               "BENCH_*.json")),
                        reverse=True)
    for path in candidates:
        try:
            with open(path) as f:
                data = json.load(f)
        except (OSError, json.JSONDecodeError):
            continue
        rec = data.get("parsed") if isinstance(data, dict) else None
        if rec is None and isinstance(data, dict) and "value" in data:
            rec = data  # a bare bench record is also a valid prior
        if not (isinstance(rec, dict) and rec.get("value")
                and rec.get("unit") == "sec/iter"):
            continue
        if metric is not None and rec.get("metric") != metric:
            continue  # unlike workload: keep searching older priors
        return os.path.basename(path), rec
    return None


def _bench_regressions(rec: dict, prior: dict,
                       threshold: float = REGRESSION_THRESHOLD,
                       noise_cv: float = None) -> list:
    """Slowdowns beyond `threshold` between a fresh bench record and a
    prior one ON THE SAME METRIC: the headline value, plus every path
    both runs timed (per-path medians localize a regression to the
    representation that slipped, even when a different path holds the
    headline).  Pure function — the gate's unit under test.

    Variance hygiene (ISSUE 8 satellite, made CV-aware by default in
    ISSUE 14): a TIMING slowdown is marked ``noisy=True`` — the gate
    turns it into a loud ``bench_noisy`` warning instead of a hard
    failure — when either side's recorded coefficient of variation
    exceeds `noise_cv`, OR when the delta itself is smaller than
    ``CV_NOISE_MULT`` × that CV (the ROADMAP note: single-run deltas
    under ~2× the CV are one draw from the timing distribution, not a
    verdict).  Bytes/balance legs are deterministic and never noisy;
    priors without a recorded cv gate normally (noise cannot be
    claimed, only measured).
    """
    if noise_cv is None:
        noise_cv = NOISE_CV
    out = []
    if rec.get("metric") != prior.get("metric"):
        return out  # unlike workloads: no comparison, no verdict
    mine = rec.get("timing_stats") or {}
    theirs = prior.get("timing_stats") or {}

    def cv_of(stats: dict, path: str):
        try:
            v = (stats.get(path) or {}).get("cv")
            return float(v) if v is not None else None
        except (TypeError, ValueError):
            return None

    best = rec.get("best_path")
    pairs = [("headline", rec.get("value"), prior.get("value"),
              cv_of(mine, best) if best else None,
              cv_of(theirs, prior.get("best_path"))
              if prior.get("best_path") else None)]
    for path in sorted(set(mine) & set(theirs)):
        pairs.append((path, (mine[path] or {}).get("median"),
                      (theirs[path] or {}).get("median"),
                      cv_of(mine, path), cv_of(theirs, path)))
    # achieved bytes/iteration per path (the encoded-format model,
    # docs/format.md): a format that silently re-inflates traffic is a
    # regression even when the clock has not caught it yet
    mine_gb = rec.get("model_gb_per_path") or {}
    theirs_gb = prior.get("model_gb_per_path") or {}
    for path in sorted(set(mine_gb) & set(theirs_gb)):
        pairs.append((f"bytes:{path}", mine_gb[path], theirs_gb[path],
                      None, None))
    # modeled flops per path (docs/dense.md): work amplification the
    # bytes model cannot see — a dispatch change that silently
    # re-inflates padded MACs is a regression; deterministic, never
    # noisy (the bytes-legs contract)
    mine_f = rec.get("model_gflops_per_path") or {}
    theirs_f = prior.get("model_gflops_per_path") or {}
    for path in sorted(set(mine_f) & set(theirs_f)):
        pairs.append((f"flops:{path}", mine_f[path], theirs_f[path],
                      None, None))
    # achieved balance per path (docs/layout-balance.md): the one-hot
    # work amplification of the built layouts — a packing/reorder
    # change that silently re-inflates padded work is a regression
    # like a bytes inflation, deterministic and never noisy
    mine_b = (rec.get("imbalance") or {}).get("per_path") or {}
    theirs_b = (prior.get("imbalance") or {}).get("per_path") or {}
    for path in sorted(set(mine_b) & set(theirs_b)):
        pairs.append((f"balance:{path}",
                      (mine_b[path] or {}).get("work_amp"),
                      (theirs_b[path] or {}).get("work_amp"),
                      None, None))
    for path, sec, prior_sec, cv_a, cv_b in pairs:
        if not sec or not prior_sec:
            continue
        if sec > prior_sec * (1.0 + threshold):
            entry = dict(path=path, sec=round(float(sec), 4),
                         prior_sec=round(float(prior_sec), 4),
                         pct=round((sec / prior_sec - 1.0) * 100, 1))
            cv = max((c for c in (cv_a, cv_b) if c is not None),
                     default=None)
            if cv is not None and (cv > noise_cv
                                   or (sec / prior_sec - 1.0)
                                   < CV_NOISE_MULT * cv):
                entry["noisy"] = True
                entry["cv"] = round(cv, 4)
            out.append(entry)
    return out


def _apply_regression_gate(rec: dict) -> list:
    """Compare `rec` against the newest prior and record every
    regression (run-report event + stderr line + the record itself
    under ``bench_regressions``).  Returns the regression list."""
    from splatt_tpu import resilience

    search_dir = (os.environ.get("SPLATT_BENCH_PRIOR_DIR")
                  or os.path.dirname(os.path.abspath(__file__)))
    prior = _prior_bench_record(search_dir, metric=rec.get("metric"))
    if prior is None:
        print("bench: no prior BENCH_*.json with this metric found; "
              "regression gate has no baseline", file=sys.stderr,
              flush=True)
        return []
    fname, prec = prior
    found = _bench_regressions(rec, prec)
    regs = [r for r in found if not r.get("noisy")]
    noisy = [r for r in found if r.get("noisy")]
    for r in regs:
        resilience.record_bench_regression(prior_file=fname, **r)
        print(f"bench: REGRESSION on {r['path']}: {r['sec']}s vs "
              f"{r['prior_sec']}s in {fname} (+{r['pct']}%)",
              file=sys.stderr, flush=True)
    for r in noisy:
        # a slowdown measured through a noisy distribution is a
        # WARNING, not a verdict (bench_noisy event; the gate ignores
        # it) — ROADMAP open item 1's "regressions are verdicts".
        # Name the ACTUAL suppression rule: the absolute CV ceiling,
        # or the under-2x-CV delta rule (whichever fired)
        if r["cv"] > NOISE_CV:
            threshold, why = NOISE_CV, f"CV {r['cv']} > {NOISE_CV}"
        else:
            threshold = round(CV_NOISE_MULT * r["cv"], 4)
            why = (f"delta {r['pct']}% < {CV_NOISE_MULT:g}x CV "
                   f"{r['cv']} (= {threshold * 100:g}%)")
        resilience.record_bench_noisy(
            path=r["path"], cv=r["cv"], threshold=threshold,
            sec=r["sec"], prior_sec=r["prior_sec"], prior_file=fname)
        print(f"bench: NOISY comparison on {r['path']}: {r['sec']}s vs "
              f"{r['prior_sec']}s in {fname} (+{r['pct']}%) but {why} "
              f"— warning, not gated",
              file=sys.stderr, flush=True)
    if regs or noisy:
        rec["bench_prior"] = fname
    if regs:
        rec["bench_regressions"] = regs
    if noisy:
        rec["bench_noisy"] = noisy
    if not regs:
        print(f"bench: no gated >{int(REGRESSION_THRESHOLD * 100)}% "
              f"regression vs {fname}", file=sys.stderr, flush=True)
    return regs


def _run_batched_bench(gate: bool) -> None:
    """SPLATT_BENCH_SCENARIO=batched (docs/batched.md): the fleet
    shape — K small SAME-REGIME tensors (dims/nnz varied within one
    bucket, the realistic many-tenant mix) decomposed by (a) a
    sequential cpd_als loop, one dispatch + compile per tensor, and
    (b) ONE vmapped cpd_als_batched.  Reports amortized per-tensor
    s/iter for both arms (median over reps, with CVs), compile-count
    evidence, and a CV-aware in-run verdict: under --gate a batched
    arm SLOWER than sequential beyond 2x the worse CV fails the run;
    a delta inside the noise floor is a bench_noisy warning, never a
    verdict (the r07/r08 lesson)."""
    import jax
    import jax.numpy as jnp

    from splatt_tpu import resilience
    from splatt_tpu.blocked import BlockedSparse
    from splatt_tpu.config import Options, Verbosity
    from splatt_tpu.cpd import cpd_als, cpd_als_batched

    K = int(os.environ.get("SPLATT_BENCH_BATCH_K") or 32)
    nnz = int(os.environ.get("SPLATT_BENCH_NNZ") or 4000)
    rank = int(os.environ.get("SPLATT_BENCH_RANK") or 8)
    iters = int(os.environ.get("SPLATT_BENCH_ITERS") or 6)
    reps = 3
    rng = np.random.default_rng(7)
    base_dims = (48, 40, 36)
    tensors = []
    def in_bucket(v: int, frac: int) -> int:
        # jitter downward but stay inside v's power-of-two regime
        # bucket (bit_length must not drop)
        lo = max(v - v // frac, (1 << (int(v).bit_length() - 1)) + 1)
        return int(rng.integers(lo, v + 1))

    for i in range(K):
        # varied within the regime bucket: same per-mode bit_length,
        # same nnz bucket — what real tenant mixes look like, and what
        # makes the sequential loop pay K compiles where the batch
        # pays one (each distinct shape is its own XLA program)
        dims = tuple(in_bucket(d, 5) for d in base_dims)
        tensors.append(synthetic_tensor(dims, in_bucket(nnz, 4), seed=i))
    seeds = list(range(100, 100 + K))
    opts = lambda seed: Options(  # noqa: E731 - tiny per-slot factory
        random_seed=seed, max_iterations=iters, tolerance=0.0,
        verbosity=Verbosity.NONE, autotune=False)

    def seq_leg():
        t0 = time.perf_counter()
        fits = []
        for i, tt in enumerate(tensors):
            bs = BlockedSparse.compile(tt, opts(seeds[i]), rank=rank)
            out = cpd_als(bs, rank=rank, opts=opts(seeds[i]))
            fits.append(float(out.fit))
        return time.perf_counter() - t0, fits

    compiles = []

    def batched_leg():
        t0 = time.perf_counter()
        res = cpd_als_batched(tensors, rank=rank, opts=opts(seeds[0]),
                              seeds=seeds)
        compiles.append(res.compiles)
        return time.perf_counter() - t0, res.fits

    # one discarded warmup pass: first-touch library/tracing overhead
    # (imports, layout machinery) lands outside the timed reps.  The
    # per-run compile costs the A/B is ABOUT still recur inside every
    # timed rep — each cpd_als call rebuilds its jitted sweep (K
    # programs sequentially, one vmapped program batched).
    print("bench: batched warmup pass", file=sys.stderr, flush=True)
    seq_leg()
    batched_leg()
    compiles.clear()
    # alternating legs so drift on a shared host hits both arms alike
    seq_times, bat_times = [], []
    fits_seq = fits_bat = None
    for r in range(reps):
        s, fits_seq = seq_leg()
        b, fits_bat = batched_leg()
        seq_times.append(s)
        bat_times.append(b)
        print(f"bench: batched rep {r + 1}/{reps}: sequential "
              f"{s:.2f}s, batched {b:.2f}s", file=sys.stderr,
              flush=True)
    denom = K * iters
    seq_amort = float(np.median(seq_times)) / denom
    bat_amort = float(np.median(bat_times)) / denom
    cv_seq = _timing_cv(seq_times)
    cv_bat = _timing_cv(bat_times)
    max_fit_dev = float(max(abs(a - b)
                            for a, b in zip(fits_seq, fits_bat)))
    platform = jax.devices()[0].platform
    rec = {
        "metric": f"batched fleet CPD amortized sec/tensor-iter, "
                  f"k={K} same-regime synthetic ({nnz} nnz bucket, "
                  f"rank {rank}, f32) on {platform}; baseline: "
                  f"sequential cpd_als loop, same tensors",
        "value": round(bat_amort, 5),
        "unit": "sec/tensor-iter",
        "batched": {
            "k": K, "iters": iters, "reps": reps,
            "seq_s_per_tensor_iter": round(seq_amort, 5),
            "batched_s_per_tensor_iter": round(bat_amort, 5),
            "speedup": round(seq_amort / max(bat_amort, 1e-12), 2),
            "cv_seq": round(cv_seq, 4), "cv_batched": round(cv_bat, 4),
            "batched_compiles_per_run": max(compiles),
            "seq_sweep_builds_per_run": K,
            "max_fit_dev": round(max_fit_dev, 6),
        },
    }
    # CV-aware in-run verdict (the same noise rule the prior-artifact
    # gate applies): a delta smaller than 2x the worse CV is noise
    noise = 2.0 * max(cv_seq, cv_bat)
    delta = (bat_amort - seq_amort) / max(seq_amort, 1e-12)
    if delta > 0 and delta <= noise:
        resilience.record_bench_noisy(
            "batched", cv=max(cv_seq, cv_bat), threshold=noise,
            sec=bat_amort, prior_sec=seq_amort,
            prior_file="(in-run sequential baseline)")
        rec["batched"]["verdict"] = "noisy"
    elif delta > 0:
        resilience.record_bench_regression(
            "batched", sec=bat_amort, prior_sec=seq_amort,
            pct=100 * delta, prior_file="(in-run sequential baseline)")
        rec["batched"]["verdict"] = "fail"
    else:
        rec["batched"]["verdict"] = ("pass" if -delta > noise
                                     else "pass-within-noise")
    regressions = []
    try:
        regressions = _apply_regression_gate(rec)
    except Exception as e:
        print(f"bench: regression gate skipped "
              f"({resilience.classify_failure(e).value}: {e})",
              file=sys.stderr, flush=True)
    print(json.dumps(rec))
    if gate and (rec["batched"]["verdict"] == "fail" or regressions):
        raise SystemExit(1)


def _run_predict_bench(gate: bool) -> None:
    """SPLATT_BENCH_SCENARIO=predict (docs/predict.md): the prediction
    plane's request latency — N requests, each a B-entry batched
    reconstruct plus one top-k slice scan against a committed model
    generation, served (a) through the hot-factor cache (the steady
    state) and (b) through the direct fenced read EVERY request (the
    cache-miss/degrade arm: stamp read + checkpoint load + sha verify
    per request).  Reports p50/p99 per stage and per arm, the achieved
    cache hit rate, and a CV-aware in-run verdict: under --gate a hot
    arm slower than the direct read beyond 2x the worse CV fails the
    run — a cache that does not beat re-reading the store from disk is
    pure overhead."""
    import tempfile

    from splatt_tpu import predict, resilience
    from splatt_tpu.cpd import _save_checkpoint

    rank = int(os.environ.get("SPLATT_BENCH_RANK") or 16)
    B = int(os.environ.get("SPLATT_BENCH_PREDICT_B") or 256)
    N = int(os.environ.get("SPLATT_BENCH_PREDICT_N") or 120)
    topk = 10
    reps = 3
    dims = (2048, 1024, 512)
    rng = np.random.default_rng(11)
    factors = [np.asarray(rng.standard_normal((d, rank)),
                          dtype=np.float32) for d in dims]
    lam = np.asarray(rng.uniform(0.5, 2.0, rank), dtype=np.float32)
    root = tempfile.mkdtemp(prefix="splatt-bench-predict-")
    ckdir = os.path.join(root, "ckpt")
    os.makedirs(ckdir, exist_ok=True)
    _save_checkpoint(os.path.join(ckdir, "m.npz"), factors, lam,
                     0, 0.9)
    gen = predict.advance_generation(ckdir, "m", factors, lam)
    coords = np.stack([rng.integers(0, d, size=N * B) for d in dims],
                      axis=1)

    cache = predict.HotFactorCache(8)
    hit_miss = [0, 0]

    def hot_entry():
        entry = cache.get("m", gen)
        if entry is None:
            hit_miss[1] += 1
            entry = predict.load_model_generation(ckdir, "m")
            cache.put("m", gen, entry)
        else:
            hit_miss[0] += 1
        return entry

    def leg(lookup):
        # per-request stage latencies: (lookup, reconstruct, top-k)
        lat = {"lookup": [], "reconstruct": [], "topk": [],
               "request": []}
        for i in range(N):
            req = coords[i * B:(i + 1) * B]
            t0 = time.perf_counter()
            entry = lookup()
            t1 = time.perf_counter()
            predict.reconstruct_entries(entry["factors"],
                                        entry["lam"], req)
            t2 = time.perf_counter()
            predict.top_k_slice(entry["factors"], entry["lam"],
                                {1: int(req[0][1]), 2: int(req[0][2])},
                                0, topk)
            t3 = time.perf_counter()
            lat["lookup"].append(t1 - t0)
            lat["reconstruct"].append(t2 - t1)
            lat["topk"].append(t3 - t2)
            lat["request"].append(t3 - t0)
        return lat

    def direct_entry():
        return predict.load_model_generation(ckdir, "m")

    print("bench: predict warmup pass", file=sys.stderr, flush=True)
    leg(hot_entry)
    leg(direct_entry)
    hit_miss[0] = hit_miss[1] = 0
    # alternating legs so drift on a shared host hits both arms alike
    hot_legs, direct_legs = [], []
    for r in range(reps):
        hot_legs.append(leg(hot_entry))
        direct_legs.append(leg(direct_entry))
        print(f"bench: predict rep {r + 1}/{reps}: hot p99 "
              f"{1e3 * np.percentile(hot_legs[-1]['request'], 99):.3f}"
              f"ms, direct p99 "
              f"{1e3 * np.percentile(direct_legs[-1]['request'], 99):.3f}"
              f"ms", file=sys.stderr, flush=True)

    def pcts(legs, key):
        allv = np.concatenate([lg[key] for lg in legs])
        return (round(float(np.percentile(allv, 50)) * 1e3, 4),
                round(float(np.percentile(allv, 99)) * 1e3, 4))

    hot_p50, hot_p99 = pcts(hot_legs, "request")
    dir_p50, dir_p99 = pcts(direct_legs, "request")
    rec_p50, rec_p99 = pcts(hot_legs, "reconstruct")
    top_p50, top_p99 = pcts(hot_legs, "topk")
    # the CV legs for the noise rule: per-rep median request latency
    cv_hot = _timing_cv([float(np.median(lg["request"]))
                         for lg in hot_legs])
    cv_dir = _timing_cv([float(np.median(lg["request"]))
                         for lg in direct_legs])
    hit_rate = hit_miss[0] / max(hit_miss[0] + hit_miss[1], 1)
    rec = {
        "metric": f"predict request p99 latency (hot-cache arm), "
                  f"B={B} entries/request + top-{topk}, rank {rank} "
                  f"model dims {dims}, f32, host-side numpy",
        "value": hot_p99,
        "unit": "ms/request p99",
        "predict": {
            "requests_per_leg": N, "entries_per_request": B,
            "reps": reps, "cache_hit_rate": round(hit_rate, 4),
            "hot_p50_ms": hot_p50, "hot_p99_ms": hot_p99,
            "direct_p50_ms": dir_p50, "direct_p99_ms": dir_p99,
            "reconstruct_p50_ms": rec_p50,
            "reconstruct_p99_ms": rec_p99,
            "topk_p50_ms": top_p50, "topk_p99_ms": top_p99,
            "cv_hot": round(cv_hot, 4), "cv_direct": round(cv_dir, 4),
        },
    }
    # CV-aware in-run verdict (the same noise rule as the prior gate):
    # the hot arm must not lose to re-reading the store per request
    hot_med = float(np.median([np.median(lg["request"])
                               for lg in hot_legs]))
    dir_med = float(np.median([np.median(lg["request"])
                               for lg in direct_legs]))
    noise = 2.0 * max(cv_hot, cv_dir)
    delta = (hot_med - dir_med) / max(dir_med, 1e-12)
    if delta > 0 and delta <= noise:
        resilience.record_bench_noisy(
            "predict", cv=max(cv_hot, cv_dir), threshold=noise,
            sec=hot_med, prior_sec=dir_med,
            prior_file="(in-run direct-read baseline)")
        rec["predict"]["verdict"] = "noisy"
    elif delta > 0:
        resilience.record_bench_regression(
            "predict", sec=hot_med, prior_sec=dir_med,
            pct=100 * delta, prior_file="(in-run direct-read baseline)")
        rec["predict"]["verdict"] = "fail"
    else:
        rec["predict"]["verdict"] = ("pass" if -delta > noise
                                     else "pass-within-noise")
    regressions = []
    try:
        regressions = _apply_regression_gate(rec)
    except Exception as e:
        print(f"bench: regression gate skipped "
              f"({resilience.classify_failure(e).value}: {e})",
              file=sys.stderr, flush=True)
    print(json.dumps(rec))
    if gate and (rec["predict"]["verdict"] == "fail" or regressions):
        raise SystemExit(1)


def _run_ingest_bench(gate: bool) -> None:
    """SPLATT_BENCH_SCENARIO=ingest (docs/ingest.md): the streaming
    ingest plane end-to-end.  (a) Throughput: a synthetic mixed feed
    (vocab-keyed mode 0, ~1% malformed rows so the quarantine path's
    cost is in the number) ingested fresh per rep through the full
    exactly-once pipeline — parse + vocab delta + segment publish +
    fsync'd journal append per chunk — reported as records/sec with
    the headline as wall ms per 1k records (lower-better, so the
    regression gate's slowdown rule reads it directly).  (b)
    Freshness: a serve ``ingest`` job chaining ``update`` jobs off a
    committed base model; the commit->update-observe lag
    (splatt_ingest_update_lag_seconds) p95 is the live-feed freshness
    number.  The rep CV rides ``timing_stats`` so the 2x-CV noise
    rule applies to the throughput comparison."""
    import shutil
    import tempfile

    from splatt_tpu import ingest, resilience, serve

    records = int(os.environ.get("SPLATT_BENCH_INGEST_RECORDS")
                  or 60_000)
    chunk = int(os.environ.get("SPLATT_BENCH_INGEST_CHUNK") or 5_000)
    reps = 3
    root = tempfile.mkdtemp(prefix="splatt-bench-ingest-")
    src = os.path.join(root, "stream.tns")
    rng = np.random.default_rng(5)
    us = rng.integers(0, 4096, size=records)
    ii = rng.integers(0, 512, size=records)
    kk = rng.integers(0, 64, size=records)
    vv = rng.random(records) + 0.1
    bad = 0
    with open(src, "w") as f:
        for n in range(records):
            if n % 101 == 13:
                f.write("malformed row\n")
                bad += 1
            else:
                f.write(f"u{us[n]} {ii[n]} {kk[n]} {vv[n]:.6f}\n")
    print(f"bench: ingest stream {records} records ({bad} malformed), "
          f"chunk {chunk}", file=sys.stderr, flush=True)

    def leg(tag):
        dest = os.path.join(root, f"dest-{tag}")
        t0 = time.perf_counter()
        summary = ingest.ingest_stream(src, dest, fmt="tns",
                                       chunk_records=chunk)
        sec = time.perf_counter() - t0
        assert summary["status"] == "converged", summary
        assert summary["quarantined"] == bad, summary
        shutil.rmtree(dest, ignore_errors=True)
        return sec

    print("bench: ingest warmup pass", file=sys.stderr, flush=True)
    leg("warmup")
    secs = []
    for r in range(reps):
        secs.append(leg(f"r{r}"))
        print(f"bench: ingest rep {r + 1}/{reps}: "
              f"{records / secs[-1]:,.0f} records/s",
              file=sys.stderr, flush=True)
    med = float(np.median(secs))
    cv = _timing_cv(secs)
    rps = records / med
    ms_per_krec = 1e3 * med / (records / 1000.0)

    # freshness leg: serve ingest job chaining updates off a base
    # model — each update result carries the commit->observe lag the
    # splatt_ingest_update_lag_seconds histogram records
    srv = serve.Server(os.path.join(root, "serve"), workers=1)
    dims = [48, 32, 16]
    base = {"id": "base", "rank": 4, "iters": 6, "seed": 7,
            "checkpoint_every": 2,
            "synthetic": {"dims": dims, "nnz": 2000, "seed": 3}}
    lags = []
    if srv.submit(base)["state"] == serve.ACCEPTED:
        srv.run_once()
        usrc = os.path.join(root, "updates.tns")
        un = 4000
        with open(usrc, "w") as f:
            for n in range(un):
                f.write(f"{rng.integers(0, dims[0])} "
                        f"{rng.integers(0, dims[1])} "
                        f"{rng.integers(0, dims[2])} "
                        f"{rng.random() + 0.1:.5f}\n")
        spec = {"id": "ing", "kind": "ingest", "source": usrc,
                "base": "base", "dims": dims,
                "chunk_records": un // 8, "update_every": 2}
        if srv.submit(spec)["state"] == serve.ACCEPTED:
            srv.run_once()
            res = serve.read_result(srv.root, "ing") or {}
            for uid in res.get("updates", []):
                ur = serve.read_result(srv.root, uid) or {}
                lag = (ur.get("update") or {}).get("ingest_lag_s")
                if ur.get("status") == "converged" and lag is not None:
                    lags.append(float(lag))
    lag_p95 = (round(float(np.percentile(lags, 95)), 4)
               if lags else None)
    print(f"bench: ingest {rps:,.0f} records/s (cv {cv:.4f}); "
          f"update lag p95 "
          f"{'n/a' if lag_p95 is None else f'{lag_p95}s'} over "
          f"{len(lags)} update(s)", file=sys.stderr, flush=True)

    rec = {
        "metric": f"streaming ingest wall ms per 1k records, mixed "
                  f"vocab+numeric 4-col feed with ~1% quarantined, "
                  f"{records} records chunk {chunk}, host-side numpy "
                  f"+ fsync'd exactly-once commits",
        "value": round(ms_per_krec, 4),
        "unit": "ms/krec",
        "timing_stats": {"ingest_stream": {"median": round(med, 4),
                                           "cv": round(cv, 4)}},
        "ingest": {
            "records": records, "malformed": bad,
            "chunk_records": chunk, "reps": reps,
            "records_per_sec": round(rps, 1),
            "sec_per_rep": [round(s, 4) for s in secs],
            "cv": round(cv, 4),
            "update_lag_p95_s": lag_p95,
            "updates_chained": len(lags),
        },
    }
    regressions = []
    try:
        regressions = _apply_regression_gate(rec)
    except Exception as e:
        print(f"bench: regression gate skipped "
              f"({resilience.classify_failure(e).value}: {e})",
              file=sys.stderr, flush=True)
    print(json.dumps(rec))
    if gate and regressions:
        raise SystemExit(1)


def _device_precheck(timeout_sec: int = 180) -> None:
    """Probe device availability in a subprocess so a wedged accelerator
    lease cannot hang the benchmark; fall back to CPU on failure.

    The probe mirrors JAX_PLATFORMS into jax.config (site plugins may
    override the env var), so a CPU-intent run never touches the chip
    and a healthy chip claims well within the timeout.
    """
    import subprocess
    import sys

    probe = ("import os\n"
             "p = os.environ.get('JAX_PLATFORMS')\n"
             "import jax\n"
             "if p:\n"
             "    jax.config.update('jax_platforms', p)\n"
             "jax.devices()\n")
    ok = False
    try:
        p = subprocess.Popen([sys.executable, "-c", probe],
                             stdout=subprocess.DEVNULL,
                             stderr=subprocess.DEVNULL)
        try:
            ok = p.wait(timeout=timeout_sec) == 0
        except subprocess.TimeoutExpired:
            # a hung claim means the relay is down; SIGTERM (never
            # SIGKILL — a killed mid-claim client wedges the lease) and
            # give it a grace period.  If it ignores SIGTERM, orphan it:
            # the doomed claim expires on its own and only the CPU
            # fallback follows anyway.
            p.terminate()
            try:
                # short grace only — a pending claim in the child can't
                # block the parent's CPU fallback, so don't delay it
                p.wait(timeout=2)
            except subprocess.TimeoutExpired:
                print("bench: probe ignoring SIGTERM; orphaning it "
                      "(claim will expire server-side)",
                      file=sys.stderr, flush=True)
    except OSError:
        pass
    if not ok:
        print("bench: accelerator unavailable, falling back to CPU",
              file=sys.stderr, flush=True)
        import jax

        try:
            jax.config.update("jax_platforms", "cpu")
        except Exception:
            pass


def main(gate: bool = False) -> None:
    child = os.environ.get("SPLATT_SCALING_CHILD")
    if child:
        _scaling_child(int(child))
        return
    devices = os.environ.get("SPLATT_BENCH_DEVICES")
    if devices:
        try:
            devs = [int(x) for x in devices.split(",") if x.strip()]
            assert devs and all(d >= 1 for d in devs)
        except (ValueError, AssertionError):
            print(f"bench: bad SPLATT_BENCH_DEVICES {devices!r}; "
                  f"expected e.g. 1,2,4,8", file=sys.stderr, flush=True)
            raise SystemExit(2)
        _run_scaling(devs)
        return
    if os.environ.get("SPLATT_BENCH_SCENARIO", "").strip() == "batched":
        # the batched fleet scenario is its own A/B harness (K small
        # tensors, in-run sequential baseline) — not a path sweep over
        # one big tensor
        _device_precheck()
        _run_batched_bench(gate)
        return
    if os.environ.get("SPLATT_BENCH_SCENARIO", "").strip() == "predict":
        # the prediction plane's request-latency A/B is host-side
        # numpy over a committed model store — no device needed
        _run_predict_bench(gate)
        return
    if os.environ.get("SPLATT_BENCH_SCENARIO", "").strip() == "ingest":
        # the streaming-ingest plane is host-side numpy + fsync'd
        # commits — no device needed
        _run_ingest_bench(gate)
        return
    _device_precheck()
    import jax
    import jax.numpy as jnp

    from splatt_tpu.blocked import BlockedSparse
    from splatt_tpu.config import BlockAlloc, Options, Verbosity
    from splatt_tpu.cpd import _make_phased_sweep, _make_sweep, init_factors
    from splatt_tpu.ops.linalg import gram

    nnz = int(os.environ.get("SPLATT_BENCH_NNZ", 20_000_000))
    rank = int(os.environ.get("SPLATT_BENCH_RANK", 50))
    iters = int(os.environ.get("SPLATT_BENCH_ITERS", 3))
    try:
        bench_dtype = jnp.dtype(os.environ.get("SPLATT_BENCH_DTYPE",
                                               "float32"))
        if not jnp.issubdtype(bench_dtype, jnp.floating):
            raise TypeError(f"non-floating dtype {bench_dtype}")
    except TypeError as e:
        print(f"bench: bad SPLATT_BENCH_DTYPE ({e}); using float32",
              file=sys.stderr, flush=True)
        bench_dtype = jnp.dtype("float32")

    shape = os.environ.get("SPLATT_BENCH_SHAPE", "nell2")
    if shape not in SHAPES:
        print(f"bench: bad SPLATT_BENCH_SHAPE {shape!r}; using nell2",
              file=sys.stderr, flush=True)
        shape = "nell2"
    scenario = os.environ.get("SPLATT_BENCH_SCENARIO", "uniform")
    _T0 = time.perf_counter()
    # seeds match the tensors the reference was measured on
    # (BASELINE_MEASURED.json description: nell2 seed 0, enron4 seed 1)
    try:
        tt, scen_desc, scen_label = scenario_tensor(
            scenario, shape, nnz, seed=1 if shape == "enron4" else 0)
    except ValueError as e:
        print(f"bench: {e}; using the uniform scenario",
              file=sys.stderr, flush=True)
        tt, scen_desc, scen_label = scenario_tensor(
            "uniform", shape, nnz, seed=1 if shape == "enron4" else 0)

    factors = init_factors(tt.dims, rank, 7, dtype=bench_dtype)

    def sync(f2):
        # The timed sweeps chain (each consumes the previous factors),
        # so fencing the last one fences them all.
        from splatt_tpu.utils.env import host_fence

        host_fence(f2)

    def note(msg):
        print(f"bench: {msg} [t+{time.perf_counter() - _T0:.0f}s]",
              file=sys.stderr, flush=True)

    jit_mode = os.environ.get("SPLATT_BENCH_JIT", "auto").lower()
    if jit_mode not in ("auto", "fused", "phased"):
        print(f"bench: bad SPLATT_BENCH_JIT {jit_mode!r}; using auto",
              file=sys.stderr, flush=True)
        jit_mode = "auto"

    def run(X):
        # auto: phased per-phase jits on TPU (a whole-sweep program at
        # NELL scale wedges the tunneled remote-compile service) and
        # whenever the native host MTTKRP engine runs (host calls can't
        # live inside a whole-sweep trace); the fully fused sweep
        # elsewhere.
        from splatt_tpu.ops.mttkrp import choose_impl, describe_plan

        native = (isinstance(X, BlockedSparse)
                  and choose_impl(X.opts) == "native")
        phased = (jit_mode == "phased"
                  or (jit_mode == "auto"
                      and (jax.default_backend() == "tpu" or native)))
        if isinstance(X, BlockedSparse):
            # name the dispatch plan in the log: the TPU number is only
            # interpretable knowing which engine (fused_t/fused_tg/
            # xla_scan/native) actually ran.  Inside a FUSED whole-sweep
            # trace the host-only native engine cannot run (tracer
            # inputs) — say so rather than mislabel the measurement.
            plan = describe_plan(X, factors)
            if not phased and "native" in plan:
                plan += " [fused whole-sweep jit: native falls back to xla]"
            note(plan)
        sweep = (_make_phased_sweep if phased
                 else _make_sweep)(X, tt.nmodes, 0.0, donate=True)
        # donated sweeps consume their inputs: give each path a private
        # copy so the shared factor set survives for the next path —
        # cast to the layout's STORAGE dtype (the compact path stores
        # bf16 and runs bf16 factors with f32 accumulation, exactly as
        # a cpd_als over that BlockedSparse would; docs/format.md)
        dt = (X.layouts[0].vals.dtype if isinstance(X, BlockedSparse)
              else bench_dtype)
        f2 = [jnp.array(u, dtype=dt) for u in factors]
        g2 = [gram(u) for u in f2]
        # warmup / compile
        note("compiling + first sweep")
        f2, g2, *_ = sweep(f2, g2, True)
        sync(f2)
        note("warm sweep")
        f2, g2, *_ = sweep(f2, g2, False)
        sync(f2)
        note(f"timing {iters} sweeps")
        # per-sweep timing, MEDIAN headline: robust to OS noise spikes
        # on a shared host (measured ±7% run-to-run on identical code);
        # the per-sweep sync is one host fence (~ms) against
        # 0.5-6 s/sweep.  ≙ the reference printing each iteration's
        # time (src/cpd.c:357-367).  mean/min/max ride along in the
        # JSON: BASELINE reference rows are per-iteration MEANS over
        # 2-iteration runs, and under a skewed timing distribution the
        # median sits below the mean — emitting both keeps the
        # mean-vs-mean comparison reconstructable from the artifact.
        times = []
        for _ in range(iters):
            t0 = time.perf_counter()
            f2, g2, *_ = sweep(f2, g2, False)
            sync(f2)
            times.append(time.perf_counter() - t0)
        times.sort()
        # coefficient of variation rides along (ISSUE 8 satellite;
        # _timing_cv is the single dispersion definition): the --gate
        # comparison downgrades a >10% "regression" to a bench_noisy
        # WARNING when either side's CV exceeds NOISE_CV or the delta
        # sits under CV_NOISE_MULT x CV — a regression verdict must be
        # a verdict, not OS noise
        return {"median": times[len(times) // 2],
                "mean": sum(times) / len(times),
                "min": times[0], "max": times[-1],
                "cv": _timing_cv(times)}

    # Measure both tensor representations and report the best: the
    # blocked/one-hot layout (Pallas on TPU, XLA engine elsewhere) and
    # the stream formulation. Degrade gracefully if one fails to
    # compile (e.g. a Mosaic lowering issue on new hardware).
    def release():
        # free the previous path's device buffers and jit executables so
        # measurements don't pollute each other (the sweeps close over
        # multi-GB layout arrays)
        import gc

        gc.collect()
        jax.clear_caches()

    results = {}
    default_paths = "blocked,balanced,compact,tuned,stream"
    if scen_label == "densemode":
        # the densemode scenario exists to A/B the hybrid dense-tile
        # dispatch against the sparse rows (docs/dense.md)
        default_paths = "blocked,compact,dense,tuned,stream"
    raw_paths = [p.strip() for p in
                 os.environ.get("SPLATT_BENCH_PATHS",
                                default_paths).split(",") if p.strip()]
    paths = [p for p in raw_paths
             if p in ("blocked", "balanced", "compact", "stream",
                      "tuned", "dense")]
    if paths != raw_paths:
        # keep the valid subset rather than silently re-enabling the
        # slow paths the caller asked to skip — inside a hard-timeout
        # chip window that would kill the run before any JSON prints
        print(f"bench: ignoring unknown SPLATT_BENCH_PATHS entries in "
              f"{raw_paths!r}; running "
              f"{paths or default_paths.split(',')}",
              file=sys.stderr, flush=True)
    if not paths:
        paths = default_paths.split(",")
    engine = os.environ.get("SPLATT_BENCH_ENGINE", "auto").lower()
    if engine not in ("auto", "pallas", "xla"):
        print(f"bench: bad SPLATT_BENCH_ENGINE {engine!r}; using auto",
              file=sys.stderr, flush=True)
        engine = "auto"
    use_pallas = {"auto": None, "pallas": True, "xla": False}[engine]
    try:
        alloc = BlockAlloc(os.environ.get("SPLATT_BENCH_ALLOC", "allmode"))
    except ValueError:
        print("bench: bad SPLATT_BENCH_ALLOC; using allmode",
              file=sys.stderr, flush=True)
        alloc = BlockAlloc.ALLMODE
    # the "blocked" row is the STATIC-default reference the tuned row
    # is judged against, so it must not consult the plan cache
    opts = Options(random_seed=7, verbosity=Verbosity.NONE,
                   val_dtype=bench_dtype, use_pallas=use_pallas,
                   block_alloc=alloc, autotune=False)
    # a path that fails mid-run is CLASSIFIED and recorded (the
    # bench_path_error run-report event + the path_errors JSON field)
    # and the remaining paths continue — one path's Mosaic rejection or
    # OOM must not cost the whole benchmark's chip window
    path_errors = {}
    # per-path ACHIEVED bytes/iteration + format summary, from the
    # encoded layouts (docs/format.md) — the fixed i32/f32 model would
    # claim the compact format moves bytes it no longer does.  The
    # achieved bytes INCLUDE each path's operand-prep decode traffic
    # (bench_algs.mttkrp_decode_bytes, per the engine the path's plan
    # names), so the bytes:<path> gate legs cover an engine change
    # that silently reintroduces prep decode; decode_overhead is the
    # achieved/encoded ratio — ~1.0 when the plan consumes the streams
    # natively (fused_v2/xla_scan), ~2x under operand-prep decode
    path_gb = {}
    path_decode = {}
    path_fmt = {}
    # per-path modeled FLOPs (bench_algs.mttkrp_flops): the compute
    # half of the roofline — beside the bytes-only model, it is what
    # separates the dense MXU path (high intensity) from the
    # bandwidth-bound sparse rows (docs/dense.md).  flops:<path> gate
    # legs, like bytes:<path>.
    path_flops = {}
    # per-path achieved balance (docs/layout-balance.md): max/mean nnz
    # and row span per block (worst layout) + the summed one-hot work
    # amplification — the quantities the balanced packing improves,
    # and a deterministic --gate leg (balance:<path>) like bytes
    path_imb = {}
    pallas_ran = (use_pallas is True
                  or (use_pallas is None
                      and jax.default_backend() == "tpu"))

    def note_format(label, X, pallas=None):
        from splatt_tpu.bench_algs import (mttkrp_bytes_encoded,
                                           mttkrp_decode_bytes,
                                           mttkrp_flops)
        from splatt_tpu.ops.mttkrp import plan_mttkrp

        # `pallas` overrides the run-wide engine family for paths that
        # force their own (the blocked_xla fallback): the traffic model
        # must match what the path's engines actually stream
        if pallas is None:
            pallas = pallas_ran
        alg = "blocked_pallas" if pallas else "blocked"
        itemsize = jnp.dtype(X.layouts[0].vals.dtype).itemsize
        enc_gb = sum(mttkrp_bytes_encoded(alg, X, rank, m, itemsize)
                     for m in range(X.nmodes)) / 1e9
        # decode traffic follows the engine each mode's plan will run
        # (docs/format.md): plan probe factors are shape-only
        plan_facs = [jnp.zeros((d, rank), X.layouts[0].vals.dtype)
                     for d in X.dims]
        dec_gb = sum(mttkrp_decode_bytes(
                         X, rank, m, plan_mttkrp(X, plan_facs, m).engine)
                     for m in range(X.nmodes)) / 1e9
        gb = enc_gb + dec_gb
        # 4 decimals (0.1 MB): the gate COMPARES these values, and a
        # 2-decimal round would blind the >10% bytes leg at smoke scale
        path_gb[label] = round(gb, 4)
        path_decode[label] = (round(gb / enc_gb, 3) if enc_gb > 0
                              else 1.0)
        path_fmt[label] = X.format_summary()
        path_flops[label] = round(
            sum(mttkrp_flops(alg, X, rank, m)
                for m in range(X.nmodes)) / 1e9, 4)
        # dense tile layouts have no nnz stream to balance — a
        # fully-dense hybrid has no imbalance row (and no balance leg)
        per_mode = X.imbalance()
        if per_mode:
            path_imb[label] = dict(
                block_nnz_max_mean=max(d["block_nnz_max_mean"]
                                       for d in per_mode.values()),
                span_max_mean=max(d["span_max_mean"]
                                  for d in per_mode.values()),
                work_amp=round(sum(d["work_amp"]
                                   for d in per_mode.values()), 2),
                packing=sorted({d["packing"] for d in per_mode.values()}))
        bal = (f"; balance: block nnz max/mean "
               f"{path_imb[label]['block_nnz_max_mean']}, one-hot work "
               f"x{path_imb[label]['work_amp']}/nnz"
               if label in path_imb else "")
        note(f"format[{label}]: {path_fmt[label]} -> "
             f"{path_gb[label]} GB/iter (achieved bytes; decode "
             f"overhead x{path_decode[label]}), "
             f"{path_flops[label]} GFLOP/iter{bal}")

    def record_failure(label, e):
        from splatt_tpu import resilience

        ev = resilience.record_path_error(label, e)
        path_errors[label] = {"error": f"{ev['failure_class']}: "
                                       f"{ev['error']}"}
        print(f"bench: {label} path failed ({ev['failure_class']}: "
              f"{type(e).__name__}: {e}); continuing with the "
              f"remaining paths", file=sys.stderr, flush=True)

    blocked_failed = False
    if "blocked" in paths:
        try:
            note("building blocked layouts")
            X = BlockedSparse.from_coo(tt, opts)
            note_format("blocked", X)
            results["blocked"] = run(X)
        except Exception as e:
            record_failure("blocked", e)
            blocked_failed = True
        release()  # outside any handler: no traceback pinning buffers
    if blocked_failed:
        try:
            note("retrying blocked with the XLA engine")
            opts_x = Options(random_seed=7, verbosity=Verbosity.NONE,
                             val_dtype=bench_dtype, use_pallas=False,
                             block_alloc=alloc)
            X = BlockedSparse.from_coo(tt, opts_x)
            note_format("blocked_xla", X, pallas=False)
            results["blocked_xla"] = run(X)
        except Exception as e2:
            record_failure("blocked_xla", e2)
        release()
    if "balanced" in paths:
        # the load-balanced row (docs/layout-balance.md): same sweep,
        # layouts cut by nnz-balanced fiber packing with long-fiber
        # splitting — on skewed scenarios the bounded per-block row
        # span shrinks seg_width (and with it the one-hot work) that
        # the fixed slicing lets one straggler block inflate
        try:
            note("building balanced (nnz-packed fibers) layouts")
            opts_b = Options(random_seed=7, verbosity=Verbosity.NONE,
                             val_dtype=bench_dtype, use_pallas=use_pallas,
                             block_alloc=alloc, autotune=False,
                             fiber_packing="balanced")
            X = BlockedSparse.from_coo(tt, opts_b)
            note_format("balanced", X)
            results["balanced"] = run(X)
        except Exception as e:
            record_failure("balanced", e)
        release()
    if "compact" in paths:
        # the format-v2 row (docs/format.md): same sweep, layouts
        # encoded with local narrow indices + segment ids + bf16 value
        # storage — the bytes/iteration halving the roofline analysis
        # says the bandwidth-bound kernel converts into speed
        try:
            note("building compact (v2 idx + bf16 storage) layouts")
            opts_c = Options(random_seed=7, verbosity=Verbosity.NONE,
                             val_dtype=bench_dtype, use_pallas=use_pallas,
                             block_alloc=alloc, autotune=False,
                             idx_width="auto", val_storage="bf16")
            X = BlockedSparse.from_coo(tt, opts_c)
            note_format("compact", X)
            results["compact"] = run(X)
        except Exception as e:
            record_failure("compact", e)
        release()
    if "dense" in paths:
        # the hybrid dense-mode row (docs/dense.md): same sweep, modes
        # whose padded density crosses the threshold ride the dense
        # tile layout + MXU matmul engines, the rest keep the sparse
        # blocked path — zero index bytes on the dense modes is the
        # whole bet, and the bytes:dense gate leg holds it
        try:
            note("building hybrid dense-mode layouts")
            opts_d = Options(random_seed=7, verbosity=Verbosity.NONE,
                             val_dtype=bench_dtype, use_pallas=use_pallas,
                             block_alloc=alloc, autotune=False,
                             dense="auto")
            X = BlockedSparse.from_coo(tt, opts_d)
            note_format("dense", X)
            results["dense"] = run(X)
        except Exception as e:
            record_failure("dense", e)
        release()
    tuned_plan_info = None
    if "tuned" in paths:
        # the autotuned row: measure candidate plans (or hit the warm
        # plan cache), build the layouts at the tuned blocks, and time
        # the same sweep — so the BENCH trajectory can attribute wins
        # to tuning rather than to unrelated code movement
        try:
            import dataclasses as _dc

            from splatt_tpu import tune as _tune

            # on the densemode scenario the dense tile candidates join
            # the tuner's matrix (docs/dense.md) — the hybrid verdict
            # is measured, not assumed
            topts = Options(random_seed=7, verbosity=Verbosity.NONE,
                            val_dtype=bench_dtype, use_pallas=use_pallas,
                            block_alloc=alloc, autotune=True,
                            dense=("auto" if scen_label == "densemode"
                                   else None))
            note(f"autotuning (plan cache: {_tune.cache_path()})")
            tres = _tune.tune(tt, rank=rank, opts=topts)
            if tres.measured == 0 and tres.plans:
                note("tune: warm plan cache hit for every mode — "
                     "skipped all measurement")
            else:
                note(f"tune: {tres.measured} candidate measurements, "
                     f"{tres.cache_hits} cache hits")
            tuned_plan_info = {str(m): _dc.asdict(p)
                               for m, p in sorted(tres.plans.items())}
            note(f"tuned plans: {tuned_plan_info}")
            note("building tuned blocked layouts")
            X = BlockedSparse.compile(tt, topts, rank=rank)
            note_format("tuned", X)
            results["tuned"] = run(X)
        except Exception as e:
            record_failure("tuned", e)
        release()
    if "stream" in paths:
        try:
            note("stream path")
            results["stream"] = run(tt)
        except Exception as e:
            record_failure("stream", e)
    if not results:
        raise RuntimeError(
            f"all benchmark paths failed: {path_errors}")
    best = min(results, key=lambda k: results[k]["median"])
    sec_per_iter = results[best]["median"]
    timings = {k: round(v["median"], 4) for k, v in results.items()}
    print(f"bench: paths {timings} -> best {best}", file=sys.stderr,
          flush=True)

    vs = 1.0
    try:
        with open(os.path.join(os.path.dirname(__file__),
                               "BASELINE_MEASURED.json")) as f:
            measured = json.load(f)
        ref = _ref_sec_per_iter(measured, shape, nnz, rank)
        if ref:
            vs = ref / sec_per_iter
    except (OSError, json.JSONDecodeError):
        pass

    platform = jax.devices()[0].platform
    rec = {
        "metric": f"CPD-ALS sec/iteration, synthetic {scen_desc} "
                  f"({tt.nmodes}-mode, {nnz} nnz, rank {rank}, "
                  f"{jnp.dtype(factors[0].dtype).name}) on {platform}; "
                  f"baseline: reference 1-thread CPU same tensor",
        "value": round(sec_per_iter, 4),
        "unit": "sec/iter",
        "vs_baseline": round(vs, 3),
        # per-path spread: the headline `value` is the best path's
        # median; mean/min/max keep mean-vs-mean BASELINE comparisons
        # reconstructable from this artifact alone
        "best_path": best,
        "timing_stats": {k: {s: round(v[s], 4)
                             for s in ("median", "mean", "min", "max",
                                       "cv") if s in v}
                         for k, v in results.items()},
    }
    if scen_label is not None:
        rec["scenario"] = scen_label
    # per-scenario imbalance stats (docs/layout-balance.md): slice skew
    # of the input, nnz per equal row fence at 8 shards (what a
    # distributed run would see), and each path's achieved block
    # balance — deterministic numbers the --gate compares via the
    # balance:<path> legs.  per_path is recorded OUTSIDE the try: it
    # arms the balance gate legs, and an unrelated skew-stat failure
    # must not silently disarm a regression gate (the bytes-legs
    # precedent)
    rec["imbalance"] = {"per_path": dict(path_imb)} if path_imb else {}
    try:
        from splatt_tpu.stats import skew_stats
        from splatt_tpu.utils.env import max_mean_ratio

        st = skew_stats(tt)
        shard8 = {}
        for m in range(tt.nmodes):
            hist = tt.mode_histogram(m)
            cap = -(-tt.dims[m] // 8)
            fences = np.add.reduceat(
                np.concatenate([hist, np.zeros(cap * 8 - tt.dims[m],
                                               dtype=hist.dtype)]),
                np.arange(0, cap * 8, cap))
            shard8[str(m)] = max_mean_ratio(fences)
        rec["imbalance"].update(
            slices={m: d["max_mean"] for m, d in st["modes"].items()},
            slice_p99_median={m: d["p99_median"]
                              for m, d in st["modes"].items()},
            shard8_max_mean=shard8)
    except Exception as e:
        print(f"bench: imbalance stats skipped ({type(e).__name__}: {e})",
              file=sys.stderr, flush=True)
    if not rec["imbalance"]:
        del rec["imbalance"]
    if path_errors:
        # failed paths ride along classified: `{"error": <class>: msg}`
        # per path, so the artifact records WHY a row is missing
        # instead of silently narrowing the comparison
        rec["path_errors"] = path_errors
    if tuned_plan_info is not None:
        # the tuner's chosen plan rides along with the "tuned" timing so
        # the BENCH trajectory can attribute wins to tuning
        rec["tuned_plan"] = tuned_plan_info
    if os.environ.get("SPLATT_BENCH_GUARD_AB", "").strip() == "1":
        # guard-cost A/B legs (ROADMAP open item 1; docs/guarded-als.md)
        try:
            note("guard A/B: timing cpd_als with health sentinel "
                 "on/off x donation on/off")
            rec["guard_ab"] = _guard_ab_legs(tt, rank, iters, bench_dtype,
                                             use_pallas, alloc)
            note(f"guard A/B: {rec['guard_ab']}")
        except Exception as e:
            print(f"bench: guard A/B skipped ({type(e).__name__}: {e})",
                  file=sys.stderr, flush=True)
        release()
    if os.environ.get("SPLATT_BENCH_TRACE_AB", "").strip() == "1":
        # trace-overhead A/B legs (docs/observability.md): the <2%
        # enabled-but-unexported budget, measured, in the artifact
        try:
            note("trace A/B: timing cpd_als with span recording "
                 "on (unexported) vs off")
            rec["trace_ab"] = _trace_ab_legs(tt, rank, iters, bench_dtype,
                                             use_pallas, alloc)
            note(f"trace A/B: {rec['trace_ab']}")
        except Exception as e:
            print(f"bench: trace A/B skipped ({type(e).__name__}: {e})",
                  file=sys.stderr, flush=True)
        release()
    try:
        # first-order roofline: one iteration = nmodes MTTKRPs' HBM
        # traffic against the measured sec/iter — shows headroom next
        # to the seconds.  Blocked paths report ACHIEVED bytes from
        # their encoded layouts (computed per path above); the stream
        # path keeps the logical COO model.
        from splatt_tpu.bench_algs import hbm_peak_gbs, mttkrp_bytes

        if best in path_gb:
            gb = float(path_gb[best])
        else:
            itemsize = jnp.dtype(bench_dtype).itemsize
            gb = sum(mttkrp_bytes("stream", tt, rank, m, itemsize)
                     for m in range(tt.nmodes)) / 1e9
        rec["model_gb_per_iter"] = round(gb, 2)
        rec["eff_gbs"] = round(gb / sec_per_iter, 1)
        if path_gb:
            # per-path achieved bytes + eff_gbs + format summary: what
            # the --gate comparison and the BENCH trajectory read.
            # decode_overhead is achieved/encoded bytes per path — the
            # in-kernel-decode contract (achieved ≈ encoded, ≤ ~1.15x)
            # made a recorded number (docs/format.md)
            rec["model_gb_per_path"] = dict(path_gb)
            rec["decode_overhead"] = dict(path_decode)
            rec["eff_gbs_per_path"] = {
                k: round(path_gb[k] / results[k]["median"], 1)
                for k in path_gb if k in results}
            rec["format"] = dict(path_fmt)
        if path_flops:
            # the compute half of the roofline (docs/dense.md): modeled
            # GFLOP/iteration per path and the intensity-vs-ridge
            # verdict — the flops:<path> gate legs read the former, a
            # reader takes the bound classification from the latter
            from splatt_tpu.bench_algs import roofline_verdict

            rec["model_gflops_per_path"] = dict(path_flops)
            rec["roofline_verdict"] = {
                k: roofline_verdict(path_gb[k] * 1e9,
                                    path_flops[k] * 1e9)
                for k in path_flops if k in path_gb}
        peak = hbm_peak_gbs()
        if peak:
            rec["hbm_peak_pct"] = round(100 * gb / sec_per_iter / peak, 1)
    except Exception as e:  # the headline number must never be lost
        print(f"bench: roofline model skipped ({type(e).__name__}: {e})",
              file=sys.stderr, flush=True)
    # regression gate (ROADMAP open item 1): compare against the newest
    # prior BENCH_*.json on the same metric; >10% slowdowns are
    # recorded (bench_regression event + the JSON artifact) and, under
    # --gate, fail the run AFTER the headline JSON prints — the number
    # is never lost to the verdict
    regressions = []
    try:
        regressions = _apply_regression_gate(rec)
    except Exception as e:
        from splatt_tpu import resilience

        print(f"bench: regression gate skipped "
              f"({resilience.classify_failure(e).value}: {e})",
              file=sys.stderr, flush=True)
    print(json.dumps(rec))
    if gate and regressions:
        raise SystemExit(1)


if __name__ == "__main__":
    _unknown = [a for a in sys.argv[1:] if a != "--gate"]
    if _unknown:
        print(f"bench: unknown arguments {_unknown}; only --gate is "
              f"accepted (knobs are SPLATT_BENCH_* env vars)",
              file=sys.stderr, flush=True)
        raise SystemExit(2)
    main(gate="--gate" in sys.argv[1:])
